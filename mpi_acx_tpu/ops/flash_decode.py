"""Length-aware GQA flash-decode: a Pallas TPU decode-attention kernel.

:func:`mpi_acx_tpu.models.decoding.grouped_decode_attend` — the single
decode-attention definition every family, the serving loop, the
speculative window passes, and the TP generation loops share — is a
dense einsum that reads the ENTIRE ``[B, max_len, Hkv, D]`` cache every
token, even when a slot sits at position 40 of 4096 (measured ~17% of
the KV-bandwidth roofline on the longctx bench). This kernel replaces
that read with an online softmax over K/V blocks (the same
``_online_softmax_step`` as ops/attention.py — THE shared block-update
definition) that is

* **length-aware** — each slot's ``pos`` lands in SMEM and bounds the
  fori_loop at ``ceil((pos + W) / block_k)`` blocks, with per-row
  causal masking only on the straddle block. The K/V cache stays in HBM
  (``memory_space=ANY``) and each program DMAs exactly the live blocks
  into VMEM scratch, so HBM traffic is O(live length), not O(max_len).
* **GQA-native** — q ``[B, W, Hkv, n_rep, D]`` rides the grid as
  ``[B, Hkv, W*n_rep, D]`` (row ``i`` is window slot ``i // n_rep``),
  attending the UN-repeated KV group directly.
* **int8-fused** — when the cache is an ``(int8 codes, f32 scales)``
  tuple (ops/kvquant.py), the codes blocks are dequantized IN REGISTER
  in VMEM via the per-position scales: ``kb = codes_f32 * scales``.
  Algebraically identical to the dense path's scale-on-scores factoring
  (``sum_d q_d*(K_kd*s_k) == (sum_d q_d*K_kd)*s_k``), but int8 is the
  only HBM-resident form and the only form that crosses the DMA — the
  bytes halving the factoring was built for finally reaches the wire.
* **window-capable** — W > 1 for the speculative-decode window passes,
  and ``pos`` scalar or ``[B]`` for continuous-batching serving.

Dispatch mirrors ``select_attention``: :func:`select_decode_attend` is
the ONE flash/dense decode switch (``decode_flash`` config field on all
three families). Off-TPU the pallas_call runs in interpret mode, so the
tier-1 CPU tests exercise this exact code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_acx_tpu.ops.attention import (_NEG_INF, _online_softmax_step,
                                       _out_struct)

# jax renamed TPUCompilerParams -> CompilerParams; accept either.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _fit_block_k(max_len, want):
    """Largest divisor of max_len <= want, preferring 128-multiples
    (Mosaic-native tiling); any divisor as a last resort (interpret
    mode, where arbitrary cache lengths are legal)."""
    b = min(want, max_len)
    while b > 128 and max_len % b:
        b -= 128
    while max_len % b:
        b -= 1
    return b


_fallback_warned: set = set()


def _warn_dense_fallback(max_len):
    if max_len not in _fallback_warned:
        _fallback_warned.add(max_len)
        import warnings

        warnings.warn(
            f"flash_decode: max_len={max_len} is not a multiple of 128; "
            "Mosaic cannot tile the cache — using the dense decode "
            "reference for this cache", RuntimeWarning, stacklevel=3)


def _decode_kernel(pos_ref, q_ref, *refs, block_k, n_rep, n_k, quant,
                   scale):
    """One (batch slot, KV group) program: online softmax over the LIVE
    K/V blocks of this slot's cache row.

    ``pos_ref`` is this slot's position in SMEM — it sets the trip
    counts, so a slot at position 40 of a 4096 cache issues one block's
    DMA, not 16. Blocks [0, n_full) are visible to every window row and
    run unmasked; blocks [n_full, n_live) straddle some row's horizon
    and mask with the ABSOLUTE row positions ``pos + i // n_rep``
    (row i of the [W*n_rep, D] q tile is window slot i // n_rep — not
    affine in i, hence the ``rows=`` form of _online_softmax_step).
    K/V HBM refs are manually DMA'd block-by-block into VMEM scratch;
    with ``quant`` the scales ride two extra [block_k, 1] f32 copies
    and dequantization happens in register, after the wire."""
    if quant:
        (k_ref, v_ref, ks_ref, vs_ref, o_ref,
         k_scr, v_scr, ks_scr, vs_scr, sem) = refs
    else:
        k_ref, v_ref, o_ref, k_scr, v_scr, sem = refs
    b = pl.program_id(0)
    g = pl.program_id(1)
    pos = pos_ref[0, 0]
    Wn, D = q_ref.shape[2], q_ref.shape[3]
    W = Wn // n_rep

    # Pre-scale q once (the _flash_kernel idiom); on the quant path q
    # stays f32 to dot against the dequantized f32 blocks exactly.
    qv = q_ref[0, 0].astype(jnp.float32) * scale         # [Wn, D]
    if quant:
        q, prec = qv, jax.lax.Precision.HIGHEST
    else:
        q = qv.astype(q_ref.dtype)
        prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
                else jax.lax.Precision.DEFAULT)

    # Absolute row positions for the straddle-block mask.
    rows = pos + jax.lax.broadcasted_iota(jnp.int32, (Wn, 1), 0) // n_rep

    def load(j):
        cps = [pltpu.make_async_copy(
                   k_ref.at[b, pl.ds(j * block_k, block_k), g],
                   k_scr, sem.at[0]),
               pltpu.make_async_copy(
                   v_ref.at[b, pl.ds(j * block_k, block_k), g],
                   v_scr, sem.at[1])]
        if quant:
            cps += [pltpu.make_async_copy(
                        ks_ref.at[b, pl.ds(j * block_k, block_k), g],
                        ks_scr, sem.at[2]),
                    pltpu.make_async_copy(
                        vs_ref.at[b, pl.ds(j * block_k, block_k), g],
                        vs_scr, sem.at[3])]
        for c in cps:
            c.start()
        for c in cps:
            c.wait()
        if quant:
            return (k_scr[...].astype(jnp.float32) * ks_scr[...],
                    v_scr[...].astype(jnp.float32) * vs_scr[...])
        return k_scr[...], v_scr[...]

    def step(j, carry, masked):
        m, l, acc = carry
        kb, vb = load(j)
        return _online_softmax_step(q, kb, vb, m, l, acc, 0, j * block_k,
                                    masked, prec, rows=rows)

    m0 = jnp.full((Wn, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Wn, 1), jnp.float32)
    acc0 = jnp.zeros((Wn, D), jnp.float32)

    # Block-skip bounds: block j holds cache cols [j*bk, (j+1)*bk); the
    # last visible col is pos + W - 1, so n_live = ceil((pos+W)/bk)
    # blocks carry any live key. A block is FULLY visible to every row
    # when its last col <= pos (row 0's horizon): n_full blocks.
    n_live = jnp.minimum((pos + W + block_k - 1) // block_k, n_k)
    n_full = jnp.minimum((pos + 1) // block_k, n_live)
    carry = jax.lax.fori_loop(
        0, n_full, lambda j, c: step(j, c, masked=False), (m0, l0, acc0))
    m, l, acc = jax.lax.fori_loop(
        n_full, n_live, lambda j, c: step(j, c, masked=True), carry)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def flash_decode_attend(q, kc, vc, pos, max_len, n_rep, block_k: int = 256):
    """Length-aware Pallas decode attention; drop-in for
    :func:`mpi_acx_tpu.models.decoding.dense_decode_attend` — same
    signature, same output [B, W, Hq*D], same (codes, scales) tuple
    convention for int8 caches. See the module docstring."""
    ks = vs = None
    if isinstance(kc, tuple):
        kc, ks = kc
    if isinstance(vc, tuple):
        vc, vs = vc
    quant = ks is not None
    if jax.default_backend() == "tpu" and max_len % 128:
        _warn_dense_fallback(max_len)
        from mpi_acx_tpu.models.decoding import dense_decode_attend
        kin = kc if ks is None else (kc, ks)
        vin = vc if vs is None else (vc, vs)
        return dense_decode_attend(q, kin, vin, pos, max_len, n_rep)

    B, W, Hq, D = q.shape
    Hkv = kc.shape[2]
    assert Hq == Hkv * n_rep, (Hq, Hkv, n_rep)
    Wn = W * n_rep
    block_k = _fit_block_k(max_len, block_k)

    pos = jnp.asarray(pos, jnp.int32)
    if pos.ndim == 0:
        pos = jnp.full((B,), pos, jnp.int32)
    pos2 = pos.reshape(B, 1)

    # [B, W, Hkv, n_rep, D] -> [B, Hkv, W*n_rep, D]: row i = w*n_rep + r
    # so the kernel recovers the window slot as i // n_rep.
    qg = q.reshape(B, W, Hkv, n_rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, Hkv, Wn, D)

    kernel = functools.partial(
        _decode_kernel, block_k=block_k, n_rep=n_rep,
        n_k=max_len // block_k, quant=quant, scale=1.0 / D ** 0.5)
    in_specs = [
        pl.BlockSpec((1, 1), lambda b, g: (b, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, Wn, D), lambda b, g: (b, g, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.ANY),     # K cache stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),     # V cache stays in HBM
    ]
    operands = [pos2, qg, kc, vc]
    scratch = [pltpu.VMEM((block_k, D), kc.dtype),
               pltpu.VMEM((block_k, D), vc.dtype)]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [ks, vs]
        scratch += [pltpu.VMEM((block_k, 1), jnp.float32)] * 2
    scratch.append(pltpu.SemaphoreType.DMA((4,)))

    out = pl.pallas_call(
        kernel,
        grid=(B, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Wn, D), lambda b, g: (b, g, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((B, Hkv, Wn, D), q.dtype, q, kc, vc),
        scratch_shapes=scratch,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=jax.default_backend() != "tpu",
    )(*operands)
    return out.reshape(B, Hkv, W, n_rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, W, Hq * D)


def _paged_decode_kernel(pos_ref, table_ref, q_ref, *refs, page_tokens,
                         n_rep, n_k, quant, scale):
    """The paged sibling of :func:`_decode_kernel`: one (batch slot, KV
    group) program whose K/V blocks are POOL PAGES resolved through the
    slot's block table instead of contiguous rows of a private cache.
    ``table_ref`` rides SMEM next to ``pos`` — the per-slot ``pos``
    plumbing generalized to a ``[B, max_pages]`` row — and block j's
    DMA source is ``k_ref.at[table[j]]`` in the
    ``[P, page_tokens, Hkv, D]`` pool. Everything else (q pre-scale,
    GQA rows, n_full/n_live trip counts, the _online_softmax_step
    order) is byte-for-byte the fixed kernel's math, which is the
    bit-equality proof: at ``block_k == page_tokens`` the two kernels
    run identical FLOPs over identical block values."""
    if quant:
        (k_ref, v_ref, ks_ref, vs_ref, o_ref,
         k_scr, v_scr, ks_scr, vs_scr, sem) = refs
    else:
        k_ref, v_ref, o_ref, k_scr, v_scr, sem = refs
    b = pl.program_id(0)
    g = pl.program_id(1)
    pos = pos_ref[0, 0]
    Wn, D = q_ref.shape[2], q_ref.shape[3]
    W = Wn // n_rep

    qv = q_ref[0, 0].astype(jnp.float32) * scale         # [Wn, D]
    if quant:
        q, prec = qv, jax.lax.Precision.HIGHEST
    else:
        q = qv.astype(q_ref.dtype)
        prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
                else jax.lax.Precision.DEFAULT)

    rows = pos + jax.lax.broadcasted_iota(jnp.int32, (Wn, 1), 0) // n_rep

    def load(j):
        page = table_ref[0, j]
        cps = [pltpu.make_async_copy(k_ref.at[page, :, g], k_scr,
                                     sem.at[0]),
               pltpu.make_async_copy(v_ref.at[page, :, g], v_scr,
                                     sem.at[1])]
        if quant:
            cps += [pltpu.make_async_copy(ks_ref.at[page, :, g], ks_scr,
                                          sem.at[2]),
                    pltpu.make_async_copy(vs_ref.at[page, :, g], vs_scr,
                                          sem.at[3])]
        for c in cps:
            c.start()
        for c in cps:
            c.wait()
        if quant:
            return (k_scr[...].astype(jnp.float32) * ks_scr[...],
                    v_scr[...].astype(jnp.float32) * vs_scr[...])
        return k_scr[...], v_scr[...]

    def step(j, carry, masked):
        m, l, acc = carry
        kb, vb = load(j)
        return _online_softmax_step(q, kb, vb, m, l, acc, 0,
                                    j * page_tokens, masked, prec,
                                    rows=rows)

    m0 = jnp.full((Wn, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Wn, 1), jnp.float32)
    acc0 = jnp.zeros((Wn, D), jnp.float32)

    n_live = jnp.minimum((pos + W + page_tokens - 1) // page_tokens, n_k)
    n_full = jnp.minimum((pos + 1) // page_tokens, n_live)
    carry = jax.lax.fori_loop(
        0, n_full, lambda j, c: step(j, c, masked=False), (m0, l0, acc0))
    m, l, acc = jax.lax.fori_loop(
        n_full, n_live, lambda j, c: step(j, c, masked=True), carry)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def paged_gather_attend(q, kp, vp, table, pos, page_tokens, n_rep):
    """Dense reference for paged attention: gather each slot's pages
    into the contiguous ``[B, max_len, Hkv, D]`` layout the fixed-slot
    path attends and call :func:`dense_decode_attend` — identical
    shapes, identical XLA reduction, so a paged slot whose pages hold
    the fixed cache's rows produces BIT-EQUAL output (gathered garbage
    past the horizon contributes exactly 0.0 through the masked
    softmax, same as the fixed cache's own dead tail)."""
    from mpi_acx_tpu.models.decoding import dense_decode_attend

    B, max_pages = table.shape
    max_len = max_pages * page_tokens

    def gather(pool):
        t = jnp.take(pool, table, axis=0)     # [B, max_pages, pt, H, *]
        return t.reshape((B, max_len) + pool.shape[2:])

    kin = ((gather(kp[0]), gather(kp[1])) if isinstance(kp, tuple)
           else gather(kp))
    vin = ((gather(vp[0]), gather(vp[1])) if isinstance(vp, tuple)
           else gather(vp))
    return dense_decode_attend(q, kin, vin, pos, max_len, n_rep)


def paged_flash_decode_attend(q, kp, vp, table, pos, page_tokens, n_rep):
    """Pallas paged decode attention: K/V pools ``[P, page_tokens,
    Hkv, D]`` (plus (codes, scales) tuples for int8 pools) addressed
    through a ``[B, max_pages]`` block table. Block size IS the page
    size; a page that Mosaic cannot tile (page_tokens % 128 on TPU)
    falls back to :func:`paged_gather_attend` with a one-time
    warning."""
    ks = vs = None
    if isinstance(kp, tuple):
        kp, ks = kp
    if isinstance(vp, tuple):
        vp, vs = vp
    quant = ks is not None
    if jax.default_backend() == "tpu" and page_tokens % 128:
        _warn_dense_fallback(page_tokens)
        kin = kp if ks is None else (kp, ks)
        vin = vp if vs is None else (vp, vs)
        return paged_gather_attend(q, kin, vin, table, pos, page_tokens,
                                   n_rep)

    B, W, Hq, D = q.shape
    Hkv = kp.shape[2]
    assert Hq == Hkv * n_rep, (Hq, Hkv, n_rep)
    Wn = W * n_rep
    max_pages = table.shape[1]

    pos = jnp.asarray(pos, jnp.int32)
    if pos.ndim == 0:
        pos = jnp.full((B,), pos, jnp.int32)
    pos2 = pos.reshape(B, 1)
    table = jnp.asarray(table, jnp.int32)

    qg = q.reshape(B, W, Hkv, n_rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, Hkv, Wn, D)

    kernel = functools.partial(
        _paged_decode_kernel, page_tokens=page_tokens, n_rep=n_rep,
        n_k=max_pages, quant=quant, scale=1.0 / D ** 0.5)
    in_specs = [
        pl.BlockSpec((1, 1), lambda b, g: (b, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, max_pages), lambda b, g: (b, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, Wn, D), lambda b, g: (b, g, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.ANY),     # K pool stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),     # V pool stays in HBM
    ]
    operands = [pos2, table, qg, kp, vp]
    scratch = [pltpu.VMEM((page_tokens, D), kp.dtype),
               pltpu.VMEM((page_tokens, D), vp.dtype)]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        operands += [ks, vs]
        scratch += [pltpu.VMEM((page_tokens, 1), jnp.float32)] * 2
    scratch.append(pltpu.SemaphoreType.DMA((4,)))

    out = pl.pallas_call(
        kernel,
        grid=(B, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Wn, D), lambda b, g: (b, g, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((B, Hkv, Wn, D), q.dtype, q, kp, vp),
        scratch_shapes=scratch,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=jax.default_backend() != "tpu",
    )(*operands)
    return out.reshape(B, Hkv, W, n_rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, W, Hq * D)


def auto_paged_decode_attend(q, kp, vp, table, pos, page_tokens, n_rep):
    """Paged auto policy: the Pallas paged kernel on TPU when Mosaic
    can tile the page (page_tokens % 128 == 0); the gather-dense
    reference elsewhere — on CPU a dense einsum beats an interpreted
    kernel, and gather-dense is also the bit-equality anchor."""
    if jax.default_backend() == "tpu" and page_tokens % 128 == 0:
        return paged_flash_decode_attend(q, kp, vp, table, pos,
                                         page_tokens, n_rep)
    return paged_gather_attend(q, kp, vp, table, pos, page_tokens, n_rep)


def select_paged_decode_attend(decode_flash):
    """The paged arm of the ``select_attention`` idiom, keyed on the
    same ``decode_flash`` config field: ``None`` -> auto, ``True`` ->
    paged Pallas kernel, ``False`` -> gather-dense reference. All
    returned callables take
    ``(q, kp, vp, table, pos, page_tokens, n_rep)``."""
    if decode_flash is None:
        return auto_paged_decode_attend
    return (paged_flash_decode_attend if decode_flash
            else paged_gather_attend)


def auto_decode_attend(q, kc, vc, pos, max_len, n_rep):
    """THE decode flash/dense auto policy (mirrors ``auto_attention``):
    the Pallas kernel on TPU when the cache is long enough for
    block-skip to pay (max_len >= 1024) and Mosaic can tile it
    (max_len % 128 == 0); the dense reference elsewhere — including
    every CPU path, where a dense einsum beats an interpreted kernel."""
    if (jax.default_backend() == "tpu" and max_len >= 1024
            and max_len % 128 == 0):
        return flash_decode_attend(q, kc, vc, pos, max_len, n_rep)
    from mpi_acx_tpu.models.decoding import dense_decode_attend

    return dense_decode_attend(q, kc, vc, pos, max_len, n_rep)


def select_decode_attend(decode_flash):
    """THE single flash/dense dispatch for the ``decode_flash`` config
    field (the ``select_attention`` idiom — every decode path routes
    here so the policy can't drift): ``None`` -> per-shape auto policy,
    ``True`` -> Pallas decode kernel (interpret mode off-TPU), ``False``
    -> dense reference. All returned callables take
    ``(q, kc, vc, pos, max_len, n_rep)``."""
    from mpi_acx_tpu.models.decoding import dense_decode_attend

    if decode_flash is None:
        return auto_decode_attend
    return flash_decode_attend if decode_flash else dense_decode_attend
