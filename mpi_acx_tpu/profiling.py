"""Device-side profiling and step timing for the TPU compute layer.

The native runtime has its own op-lifecycle Chrome trace (ACX_TRACE,
src/core/trace.cc — the host plane's observability); this module is the
device half: XLA/TPU profiler capture and honest wall-clock step
statistics. The reference's only observability is printf-with--DDEBUG
(SURVEY.md §5.1/§5.5) — both halves here exceed it.

Timing rule learned the hard way on the tunneled chip (BASELINE.md):
host-side per-call timing of sub-ms device work measures dispatch RTT,
not the device. ``StepTimer`` forces a ``block_until_ready`` sync per
step so each sample is a true device round-trip; for sub-ms kernels use
a device-side rep loop (bench.py's methodology) instead.
"""

from __future__ import annotations

import contextlib
import json
import math
import time
from typing import Any, Dict, List, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA profiler trace into ``logdir`` (TensorBoard's
    profile plugin / xprof format). Wrap the region of interest:

        with profiling.trace("/tmp/prof"):
            jax.block_until_ready(step(params, batch))
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named sub-region inside a trace (shows as a span in the viewer):

        with profiling.annotate("attention"):
            o = flash_attention(q, k, v)
    """
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock statistics over training/serving steps.

    Each timed region ends with ``jax.block_until_ready`` on the value
    handed to ``stop`` (or the region's result), so a sample covers the
    full device execution, not just dispatch. Percentiles use the sorted
    sample list (no interpolation — honest for small n).

        timer = StepTimer()
        for batch in data:
            with timer.step() as t:
                loss, params = train_step(params, batch)
                t.sync(loss)
        print(timer.summary())
    """

    class _Region:
        def __init__(self):
            self._value = None
            self._synced = False

        def sync(self, value: Any):
            """Register the value whose readiness ends the step."""
            self._value = value
            self._synced = True

    def __init__(self):
        self.samples: List[float] = []

    @contextlib.contextmanager
    def step(self):
        region = StepTimer._Region()
        t0 = time.perf_counter()
        yield region
        if not region._synced:
            # Without a sync point the sample would measure async DISPATCH
            # only — the exact pitfall this class exists to prevent
            # (module docstring). Fail loudly rather than record it.
            raise RuntimeError(
                "StepTimer.step() region ended without sync(value); the "
                "sample would time dispatch, not the device step")
        jax.block_until_ready(region._value)
        self.samples.append(time.perf_counter() - t0)

    def _pct(self, p: float) -> float:
        s = sorted(self.samples)
        if not s:
            return 0.0
        # Nearest-rank percentile: the ceil(p*n)-th smallest sample.
        return s[max(0, math.ceil(p * len(s)) - 1)]

    def reset(self) -> None:
        """Drop all recorded samples (e.g. after a warmup phase, so the
        compile-step outlier doesn't poison the percentiles)."""
        self.samples = []

    def summary(self) -> Dict[str, float]:
        if not self.samples:
            return {"steps": 0}
        n = len(self.samples)
        return {
            "steps": n,
            "mean_s": sum(self.samples) / n,
            "min_s": min(self.samples),
            "p50_s": self._pct(0.50),
            "p90_s": self._pct(0.90),
            "p99_s": self._pct(0.99),
            "max_s": max(self.samples),
        }

    def dump(self, path: str, extra: Optional[Dict[str, Any]] = None):
        """Write summary + raw samples as JSON."""
        out = dict(self.summary(), samples=self.samples, **(extra or {}))
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        return out
