"""MPIX triggers embedded inside a jitted XLA program.

The reference arms CUDA stream memOps / tiny kernels so that *the device
reaching a point in its queue* fires an MPIX operation
(reference src/sendrecv.cu:152-208); SURVEY.md §7.1 maps that trigger
mechanism onto PJRT host callbacks. This module is that mapping:
``jax.experimental.io_callback(ordered=True)`` nodes compiled INTO the
program fire exactly when execution reaches them, in program order, and
run the native enqueue/wait on the host while the rest of the program
continues — a single jitted computation can compute, trigger a native
transfer mid-program, and consume the reply.

Ordering: all triggers placed in one program are ordered among themselves
(ordered=True serializes the callback nodes), which is STRONGER than the
reference's non-overtaking caveat (its enqueued ops post in arbitrary
order once triggered, reference README.md:173-176).

Lifetime rule (same as the C API): a send's buffer must stay alive until
the operation completes. ``send_in_program`` copies the device value into
a host buffer held in the runtime-wide pending set; call
``drain_sends(rt)`` (host side, after the program) or let a later
``recv_in_program`` from the same peer imply completion, exactly like
MPIX_Wait on the C side.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import numpy as np
from jax.experimental import io_callback


def _pending_of(rt) -> List[Tuple[object, np.ndarray]]:
    """The runtime's pending in-program sends: (request, host buffer)
    pairs. Stored ON the Runtime object (lazily) so the registry's
    lifetime is exactly the runtime's — a module dict keyed by ``id(rt)``
    could alias a finalized-then-reallocated Runtime and silently hold
    buffers alive (round-3 verdict weak #8)."""
    lst = getattr(rt, "_inprogram_sends", None)
    if lst is None:
        lst = []
        rt._inprogram_sends = lst
    return lst


def send_in_program(rt, x: jax.Array, dest: int, tag: int = 0) -> jax.Array:
    """Place a send trigger at this point of a jitted program.

    When the executing program reaches this node, the current value of
    ``x`` is handed to the native runtime as an enqueued send to ``dest``
    (MPIX_Isend_enqueue through mpi_acx_tpu.runtime). Returns ``x``
    unchanged so callers keep a data dependence on the triggered value.
    """
    def cb(val):
        buf = np.ascontiguousarray(val)
        req = rt.isend_enqueue(buf, dest, tag)
        _pending_of(rt).append((req, buf))

    io_callback(cb, None, x, ordered=True)
    return x


def recv_in_program(rt, shape, dtype, source: int, tag: int = 0) -> jax.Array:
    """Place a receive at this point of a jitted program: when execution
    arrives, enqueue a native receive from ``source`` and wait for it; the
    received buffer becomes this node's value, consumed by the rest of
    the program. (MPIX_Irecv_enqueue + MPIX_Wait; the wait runs
    caller-driven proxy progress, so it completes even with the proxy
    thread parked.)"""
    def cb():
        buf = np.zeros(shape, dtype)
        req = rt.irecv_enqueue(buf, source, tag)
        rt.wait(req)
        return buf

    return io_callback(cb, jax.ShapeDtypeStruct(shape, dtype), ordered=True)


def drain_sends(rt) -> int:
    """Host side: wait out every send this runtime triggered from inside
    programs (the MPIX_Wait half of the enqueue/wait pair). Returns how
    many were completed."""
    pending = _pending_of(rt)
    done = 0
    while pending:
        req, _buf = pending.pop()
        rt.wait(req)
        done += 1
    return done
