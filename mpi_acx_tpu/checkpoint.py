"""Checkpoint/resume for training state.

The reference has no checkpointing (SURVEY.md §5.4: "none"); this is part
of the framework surface a training stack needs. Orbax-backed: async-safe
atomic step directories, sharded-array aware (each host writes only its
shards of a global array — the multihost story composes with
parallel/multihost.py), retention policy, and exact-resume semantics
(restored state is bit-identical, so a resumed run reproduces the
original trajectory step for step).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


class Checkpointer:
    """Step-indexed checkpoint directory with retention.

    >>> ckpt = Checkpointer("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(step, {"params": params, "opt": opt_state})
    >>> state = ckpt.restore(like={"params": params0, "opt": opt0})

    ``like`` supplies the pytree structure, dtypes, and shardings for
    restore — restored arrays land exactly where ``like``'s live, so for
    a distributed run pass state already placed on the mesh (the
    initialized-and-sharded state a fresh worker builds anyway). With no
    ``step``, restores the latest.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: Any, wait: bool = True) -> None:
        """Write ``state`` (any pytree of arrays/scalars) for ``step``.
        wait=False lets orbax finish the write in the background
        (call wait_until_finished() or close() before exiting)."""
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, step: Optional[int] = None,
                like: Optional[Any] = None) -> Any:
        """Read a step (default: latest). ``like`` gives the target
        structure/shardings; without it, leaves come back as jax.Arrays
        on the default device with the saved dtypes (fine for inspection;
        distributed restores should always pass ``like``)."""
        import orbax.checkpoint as ocp
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._dir}")
        if like is not None:
            target = jax.tree.map(_abstractify, like)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target))
        return self._mgr.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def warm_start(directory: str, like: Any,
               step: Optional[int] = None):
    """Join-warm restore for a replacement rank (docs/DESIGN.md §12).

    A rank joining a serving fleet mid-job must come up with the SAME
    weights the fleet is serving, not re-initialized ones — restore the
    latest step (or ``step``) into ``like``'s structure/shardings and
    return ``(state, step)``. Returns ``(None, None)`` when the directory
    holds no checkpoint yet (a fleet that never saved: the joiner keeps
    its freshly built state, which is what the others are running too).

    >>> state, step = warm_start(ckpt_dir, like=init_state)
    >>> if state is None: state = init_state
    """
    ckpt = Checkpointer(directory)
    try:
        if step is None:
            step = ckpt.latest_step()
        if step is None:
            return None, None
        return ckpt.restore(step, like=like), step
    finally:
        ckpt.close()


def _abstractify(x):
    """Target entry for StandardRestore: keep jax.Arrays as abstract
    shape/dtype/sharding descriptors, leave scalars and numpy as-is."""
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x
