"""Distributed training step: dp x pp x tp/sp in one shard_map program.

The flagship composition of the framework's primitives (the counterpart of
the reference's driver configs, BASELINE.json configs[3,4]):

* **pp** — pipeline stages over the 'pp' mesh axis; microbatch activations
  travel stage->stage by collective permute
  (mpi_acx_tpu.parallel.pipeline).
* **tp + sp** — inside each stage, attention runs sequence-parallel over
  the 'tp' axis with ring attention (K/V rotating on ICI), and the MLP
  runs tensor-parallel with the FFN dim sharded over 'tp' and one psum.
* **dp** — the microbatch dim is sharded over 'dp'; gradients are averaged
  with one pmean.

Everything is a single jitted SPMD program: XLA sees the mesh, the
collectives, and the scan — no host in the loop.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.parallel.pipeline import (pipeline_forward,
                                           pipeline_forward_interleaved)
from mpi_acx_tpu.parallel.ring_attention import ring_attention_batched


def _gpt2_attn_sp(cfg, lp: Dict[str, Any], h: jax.Array,
                  tp_axis: str) -> jax.Array:
    """The GPT-2-layout attention half under sequence parallelism: each
    tp rank projects q/k/v for ITS sequence block, ring attention rotates
    K/V blocks on ICI, and the outputs are re-assembled with one
    all_gather. Shared by the dense and MoE families (same ln1/wqkv/wo
    leaf names)."""
    tpn = lax.axis_size(tp_axis)
    ti = lax.axis_index(tp_axis)
    mb, S, d = h.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    blk = S // tpn

    hn = tfm.layernorm(h, lp["ln1_g"], lp["ln1_b"])
    loc = lax.dynamic_slice_in_dim(hn, ti * blk, blk, axis=1)  # [mb,blk,d]
    qkv = loc @ lp["wqkv"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(mb, blk, H, Dh)
    k = k.reshape(mb, blk, H, Dh)
    v = v.reshape(mb, blk, H, Dh)
    o = ring_attention_batched(q, k, v, tp_axis, causal=True,
                               use_flash=cfg.use_flash).reshape(mb, blk, d)
    o = o @ lp["wo"].astype(h.dtype)
    # Re-assemble the full sequence on every tp rank.
    attn = lax.all_gather(o, tp_axis, axis=1, tiled=True)     # [mb, S, d]
    return h + attn


def _block_sp_tp(cfg: tfm.TransformerConfig, lp: Dict[str, Any],
                 h: jax.Array, tp_axis: str) -> jax.Array:
    """Transformer block, sequence-parallel attention + tensor-parallel MLP.

    h: [mb, S, d] replicated over tp. lp's w1/b1/w2 are the LOCAL tp slices
    (shard_map hands us [d, ff/tp] etc.); wqkv/wo are replicated.
    """
    h = _gpt2_attn_sp(cfg, lp, h, tp_axis)

    # --- MLP: shard the FFN dim over tp; one psum to reduce ---
    hn = tfm.layernorm(h, lp["ln2_g"], lp["ln2_b"])
    y = jax.nn.gelu(hn @ lp["w1"].astype(h.dtype) +
                    lp["b1"].astype(h.dtype))                 # [mb,S,ff/tp]
    part = y @ lp["w2"].astype(h.dtype)
    return h + lax.psum(part, tp_axis) + lp["b2"].astype(h.dtype)


def _moe_block_sp_tp(cfg, lp: Dict[str, Any], h: jax.Array,
                     tp_axis: str):
    """MoE-transformer block under the flagship composition: the GPT-2
    attention half (sequence-parallel ring attention), then the routed
    expert FFN with EXPERTS sharded over the tp axis (EP folded onto the
    tp mesh axis).

    Tokens are REPLICATED over tp here, so the replicated-EP path
    applies: each rank routes all tokens but runs only its LOCAL expert
    block, and one psum assembles the output — 1/tp the expert FLOPs
    per rank and a single collective per layer
    (moe.moe_layer_replicated_ep; routing is bit-equal to the
    single-device dispatch).

    Returns ``(h, (load_balance, router_z))`` — the router auxiliaries
    ride the pipeline scan's aux accumulator (pipeline_forward
    ``with_aux``) into the flagship loss, so pp x tp MoE training
    carries the same regularization as the dp(+ep) trainer
    (models/moe_transformer.py). The aux pair is replicated over tp
    (full gates on every rank); the loss gates its contribution to
    ti == 0 to keep cotangent paths exclusive."""
    from mpi_acx_tpu.models.moe_transformer import _moe_ffn

    h = _gpt2_attn_sp(cfg, lp, h, tp_axis)
    return _moe_ffn(cfg, lp, h, ep_axis=tp_axis, replicated=True,
                    with_aux=True)


def _llama_block_sp_tp(cfg, lp: Dict[str, Any], h: jax.Array,
                       tp_axis: str) -> jax.Array:
    """Llama block (RMSNorm + RoPE + GQA + SwiGLU), sequence-parallel
    attention + tensor-parallel MLP — the Llama-family counterpart of
    :func:`_block_sp_tp` (BASELINE.json configs[4]).

    h: [mb, S, d] replicated over tp. lp's w_gate/w_up/w_down are the
    LOCAL tp slices of the SwiGLU FFN; attention weights are replicated.
    RoPE uses each rank's GLOBAL positions (ti*blk + arange), so the
    sharded rotation matches the single-device computation exactly.
    """
    from mpi_acx_tpu.models import llama as lm

    tpn = lax.axis_size(tp_axis)
    ti = lax.axis_index(tp_axis)
    mb, S, d = h.shape
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    blk = S // tpn

    # --- attention: shard the SEQUENCE over tp; ring-attend K/V blocks ---
    hn = lm.rmsnorm(h, lp["attn_norm"])
    loc = lax.dynamic_slice_in_dim(hn, ti * blk, blk, axis=1)  # [mb,blk,d]
    q = (loc @ lp["wq"].astype(h.dtype)).reshape(mb, blk, Hq, Dh)
    k = (loc @ lp["wk"].astype(h.dtype)).reshape(mb, blk, Hkv, Dh)
    v = (loc @ lp["wv"].astype(h.dtype)).reshape(mb, blk, Hkv, Dh)
    positions = ti * blk + jnp.arange(blk)
    q = lm.rope(q, positions, cfg.rope_theta)
    k = lm.rope(k, positions, cfg.rope_theta)
    # K/V stay at Hkv heads: the ring rotates the un-expanded GQA heads
    # (Hq/Hkv x less ICI traffic) and broadcasts per block.
    o = ring_attention_batched(q, k, v, tp_axis, causal=True,
                               use_flash=cfg.use_flash,
                               kv_repeat=Hq // Hkv)
    o = o.reshape(mb, blk, Hq * Dh) @ lp["wo"].astype(h.dtype)
    attn = lax.all_gather(o, tp_axis, axis=1, tiled=True)     # [mb, S, d]
    h = h + attn

    # --- SwiGLU MLP: shard the FFN dim over tp; one psum to reduce ---
    hn = lm.rmsnorm(h, lp["mlp_norm"])
    gate = jax.nn.silu(hn @ lp["w_gate"].astype(h.dtype))     # [mb,S,ff/tp]
    up = hn @ lp["w_up"].astype(h.dtype)
    part = (gate * up) @ lp["w_down"].astype(h.dtype)
    return h + lax.psum(part, tp_axis)


def param_specs(stage: bool = True) -> Dict[str, Any]:
    """PartitionSpecs for the stage-sliced GPT-2 parameter pytree
    (tfm.stage_slice output): layers carry a leading 'pp' stage axis; the
    FFN dims of w1/b1/w2 shard over 'tp'; everything else replicates."""
    pp = "pp" if stage else None
    return {
        "embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
        "layers": {
            "ln1_g": P(pp), "ln1_b": P(pp),
            "wqkv": P(pp), "wo": P(pp),
            "ln2_g": P(pp), "ln2_b": P(pp),
            "w1": P(pp, None, None, "tp"), "b1": P(pp, None, "tp"),
            "w2": P(pp, None, "tp", None), "b2": P(pp),
        },
    }


def llama_param_specs(stage: bool = True) -> Dict[str, Any]:
    """PartitionSpecs for the stage-sliced Llama parameter pytree: the
    SwiGLU FFN dims shard over 'tp'; attention/norms replicate per stage."""
    pp = "pp" if stage else None
    return {
        "embed": P(), "final_norm": P(), "unembed": P(),
        "layers": {
            "attn_norm": P(pp), "wq": P(pp), "wk": P(pp), "wv": P(pp),
            "wo": P(pp), "mlp_norm": P(pp),
            "w_gate": P(pp, None, None, "tp"),
            "w_up": P(pp, None, None, "tp"),
            "w_down": P(pp, None, "tp", None),
        },
    }


def moe_param_specs(stage: bool = True) -> Dict[str, Any]:
    """PartitionSpecs for the stage-sliced MoE-transformer pytree: the
    EXPERT dim of w1/w2 shards over 'tp' (EP on the tp mesh axis);
    attention, norms, and the gate replicate per stage."""
    pp = "pp" if stage else None
    return {
        "embed": P(), "pos": P(), "lnf_g": P(), "lnf_b": P(),
        "layers": {
            "ln1_g": P(pp), "ln1_b": P(pp),
            "wqkv": P(pp), "wo": P(pp),
            "ln2_g": P(pp), "ln2_b": P(pp),
            "gate": P(pp),
            "w1": P(pp, None, "tp"), "w2": P(pp, None, "tp"),
        },
    }


class _Family:
    """Model-family adapter: everything make_loss_and_grads needs to run a
    family through the dp x pp x tp/sp composition."""

    def __init__(self, block, embed, final, head, specs, tp_sharded,
                 has_aux=False):
        self.block = block           # (cfg, lp, h, tp_axis) -> h | (h, aux)
        self.embed = embed           # (params, cfg, tokens) -> x [...,S,d]
        self.final = final           # (params, ys) -> ys
        self.head = head             # (params) -> [vocab, d] logits matrix
        self.specs = specs           # () -> PartitionSpec tree
        self.tp_sharded = tp_sharded  # layer-leaf name -> bool
        self.has_aux = has_aux       # block returns (h, (balance, z))


def _family(cfg) -> _Family:
    from mpi_acx_tpu.models.llama import LlamaConfig, rmsnorm
    from mpi_acx_tpu.models.moe_transformer import MoeTransformerConfig

    if isinstance(cfg, LlamaConfig):
        return _Family(
            block=_llama_block_sp_tp,
            embed=lambda p, c, t: p["embed"][t].astype(c.dtype),
            final=lambda p, ys: rmsnorm(ys, p["final_norm"]),
            head=lambda p: p["unembed"],
            specs=llama_param_specs,
            tp_sharded=lambda k: k in ("w_gate", "w_up", "w_down"),
        )
    if isinstance(cfg, MoeTransformerConfig):
        return _Family(
            block=_moe_block_sp_tp,
            embed=lambda p, c, t: (p["embed"][t] +
                                   p["pos"][:t.shape[-1]]).astype(c.dtype),
            final=lambda p, ys: tfm.layernorm(ys, p["lnf_g"], p["lnf_b"]),
            head=lambda p: p["embed"],
            specs=moe_param_specs,
            tp_sharded=lambda k: k in ("w1", "w2"),
            has_aux=True,
        )
    return _Family(
        block=_block_sp_tp,
        embed=lambda p, c, t: (p["embed"][t] +
                               p["pos"][:t.shape[-1]]).astype(c.dtype),
        final=lambda p, ys: tfm.layernorm(ys, p["lnf_g"], p["lnf_b"]),
        head=lambda p: p["embed"],
        specs=param_specs,
        tp_sharded=lambda k: k in ("w1", "b1", "w2"),
    )


def make_loss_and_grads(cfg, mesh: Mesh, n_micro: int, n_virtual: int = 1,
                        remat: bool = False,
                        dp_quant_bits: int | None = None,
                        aux_weight: float = 1e-2, z_weight: float = 1e-3,
                        schedule: str = "gpipe",
                        xent_chunk: int | None = None):
    """Builds a jitted (params, tokens, targets) -> (loss, grads) over a
    ('dp','pp','tp') mesh — the shard_map core every optimizer shares.
    Returned grads carry the same shardings as params, so any elementwise
    optimizer applied outside stays correctly sharded by propagation.

    cfg selects the model family (tfm.TransformerConfig or
    llama.LlamaConfig — both run the same composition through their
    _Family adapter). params must be tfm.stage_slice(init_params(...),
    pp_size) — or tfm.stage_slice_interleaved(..., pp_size, n_virtual)
    when ``n_virtual > 1`` selects the interleaved pipeline schedule
    (bubble / n_virtual; needs n_micro % pp == 0). tokens/targets:
    [n_micro, micro_batch, S] int32, batch over 'dp'.

    ``remat=True`` wraps each layer body in ``jax.checkpoint``: the
    backward pass recomputes block activations (including the ring
    attention and its collectives) instead of keeping them live through
    the whole pipeline scan — activation memory drops from O(layers) to
    O(1) blocks per stage for ~1/3 more FLOPs, the standard trade when
    HBM, not the MXU, is the binding constraint. Gradients are the same
    function, so the exact-match tests hold with remat on
    (tests/test_train.py).

    ``dp_quant_bits=8`` replaces the exact dp-gradient pmean with the
    int8-quantized ring all-reduce (parallel/quantized.py, after EQuARX)
    — ~4x less traffic on the dp axis, the one that rides DCN in
    multi-slice layouts, at ~<1% gradient error. None (default) keeps
    gradient sync exact.

    For the MoE family the loss is CE + ``aux_weight`` * load-balance +
    ``z_weight`` * router-z, with the router auxiliaries threaded
    through the pipeline scan (pipeline_forward ``with_aux``) and
    normalized per (layer, microbatch) router call — at pp=tp=1,
    n_micro=1 the scalar exact-matches the dp+ep trainer's
    moe_transformer.loss_fn (tests/test_train_moe_flagship.py). The
    weights are ignored by the dense families.

    ``schedule="1f1b"`` swaps the autodiff-through-the-scan backward for
    the memory-bounded 1F1B schedule (pipeline._pipeline_1f1b_engine):
    one slot scan whose body runs the stage forward and an explicit
    ``jax.vjp`` backward from an interval-colored input buffer, so peak
    activation residency is O(pp) (O(n_virtual * pp) interleaved)
    instead of O(n_micro) scan residuals. Same loss and gradients as
    the GPipe path (tests/test_train_1f1b.py asserts exact parity at
    dp2 x pp2 x tp2 for all three families). Because every rank must
    execute the stage collectives in lockstep, the slot body computes
    both the forward and the backward unconditionally and masks the
    accumulations (~2x the op count of the cond-based pipeline-level
    schedule; the win is memory, not FLOPs). ``n_virtual > 1`` composes
    1F1B with the interleaved schedule — memory win AND the bubble/v
    win together (Megatron interleaved 1F1B; needs n_micro % pp == 0).
    """
    n_stages = mesh.shape["pp"]
    fam = _family(cfg)
    from mpi_acx_tpu.models.moe_transformer import MoeTransformerConfig
    if isinstance(cfg, MoeTransformerConfig):
        assert cfg.n_experts % mesh.shape["tp"] == 0, (
            f"n_experts ({cfg.n_experts}) must divide by the 'tp' mesh "
            f"axis ({mesh.shape['tp']}) — experts shard over tp")
    assert schedule in ("gpipe", "1f1b"), schedule

    def ll_sum(head_mat, ys_blk, tg_blk):
        """Summed target log-likelihood of a rank's exclusive slice.
        ``xent_chunk`` selects the memory-bounded chunked-vocab path
        (ops/xent.py — the [tokens, vocab] logits tensor never
        materializes; identical values/grads up to fp summation order),
        None the naive log_softmax."""
        if xent_chunk is None:
            logits = ys_blk.astype(jnp.float32) @ head_mat.T
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, tg_blk[..., None], -1)[..., 0]
            return jnp.sum(ll)
        from mpi_acx_tpu.ops.xent import chunked_xent_ll
        d = ys_blk.shape[-1]
        return jnp.sum(chunked_xent_ll(
            ys_blk.reshape(-1, d), head_mat, tg_blk.reshape(-1),
            xent_chunk))

    def reduce_grad(g, tp_sharded: bool, pp_sharded: bool):
        """Gradient reduction rule shared by both schedules: pmean over
        dp (mean loss over the global batch), psum over every axis the
        leaf is REPLICATED on, nothing over sharded axes."""
        if dp_quant_bits is not None:
            from mpi_acx_tpu.parallel.quantized import quantized_pmean
            g = quantized_pmean(g, "dp", dp_quant_bits)
        else:
            g = lax.pmean(g, "dp")
        if not tp_sharded:
            g = lax.psum(g, "tp")
        if not pp_sharded:
            g = lax.psum(g, "pp")
        return g

    def make_stage_fn():
        layer_fn = lambda lp, h: fam.block(cfg, lp, h, "tp")  # noqa: E731
        if remat:
            layer_fn = jax.checkpoint(layer_fn)
        if fam.has_aux:
            def stage_fn(stage_layers, h):
                def body(carry, lp):
                    h, lb, rz = carry
                    h, (b_lb, b_rz) = layer_fn(lp, h)
                    return (h, lb + b_lb, rz + b_rz), None
                zero = jnp.zeros((), jnp.float32)
                (h, lb, rz), _ = lax.scan(body, (h, zero, zero),
                                          stage_layers)
                return h, (lb, rz)
        else:
            def stage_fn(stage_layers, h):
                def body(h, lp):
                    return layer_fn(lp, h), None
                h, _ = lax.scan(body, h, stage_layers)
                return h
        return stage_fn

    def per_shard(params, tokens, targets):
        def loss_fn(params):
            # Embed on every rank (dp-local microbatches). The pipeline
            # consumes xs only on stage 0, so the embedding-gather cotangent
            # path is exclusive to stage 0 by construction.
            S = tokens.shape[-1]
            x = fam.embed(params, cfg, tokens)         # [M, mbl, S, d]
            stage_fn = make_stage_fn()

            aux = None
            if n_virtual > 1:
                ys = pipeline_forward_interleaved(
                    stage_fn, params["layers"], x, "pp", n_virtual,
                    with_aux=fam.has_aux)
            else:
                ys = pipeline_forward(stage_fn, params["layers"], x, "pp",
                                      with_aux=fam.has_aux)
            if fam.has_aux:
                ys, aux = ys
            ys = fam.final(params, ys)

            # EXCLUSIVE loss paths: every rank scores only its own slice —
            # its tp sequence block, and only on the last pipeline stage —
            # and the scalar is assembled by psum. This keeps every
            # parameter's cotangent path unique, so gradient reduction is a
            # plain psum over the axes a leaf is replicated on (redundant
            # loss computation would scale cotangents by the redundancy).
            tpn = lax.axis_size("tp")
            ti = lax.axis_index("tp")
            si = lax.axis_index("pp")
            blk = S // tpn
            ys_blk = lax.dynamic_slice_in_dim(ys, ti * blk, blk, axis=2)
            tg_blk = lax.dynamic_slice_in_dim(targets, ti * blk, blk, axis=2)
            contrib = jnp.where(si == n_stages - 1,
                                ll_sum(fam.head(params), ys_blk, tg_blk),
                                0.0)
            if fam.has_aux:
                # Aux is replicated over tp (full gates everywhere) and
                # device-varying over pp (each stage owns its layers):
                # gate to ti == 0 for an exclusive cotangent path, then
                # the same psum that assembles the CE sums every stage's
                # contribution exactly once. Normalize per router call —
                # one call per (layer, microbatch) — to match the dp+ep
                # trainer's mean-over-layers convention.
                lb_c = jnp.where(ti == 0, aux[0], 0.0)
                rz_c = jnp.where(ti == 0, aux[1], 0.0)
                total, lb_t, rz_t = lax.psum((contrib, lb_c, rz_c),
                                             ("pp", "tp"))
                calls = cfg.n_layers * tokens.shape[0]
                aux_term = (aux_weight * lb_t + z_weight * rz_t) / calls
            else:
                total = lax.psum(contrib, ("pp", "tp"))
                aux_term = 0.0
            n_tok = tokens.shape[0] * tokens.shape[1] * S
            return -total / n_tok + aux_term

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # With check_vma=False the transpose of psum is psum (replication is
        # untracked), so the loss-assembly psum over ('pp','tp') all-reduces
        # the per-rank unit seeds: every cotangent — and thus every gradient
        # leaf — is uniformly scaled by pp*tp. Undo it explicitly.
        group = lax.axis_size("pp") * lax.axis_size("tp")
        grads = jax.tree.map(lambda g: g / group, grads)
        loss = lax.pmean(loss, "dp")

        # Gradient reduction rule: see reduce_grad ('tp' psum for
        # attention/norm leaves, 'pp'+'tp' for the embedding family; no
        # reduction over axes the leaf is sharded on).
        out = dict(grads)
        for k in grads:
            if k != "layers":
                out[k] = reduce_grad(grads[k], False, False)
        out["layers"] = {
            k: reduce_grad(grads["layers"][k], fam.tp_sharded(k), True)
            for k in grads["layers"]
        }
        return loss, out

    def per_shard_1f1b(params, tokens, targets):
        """The 1F1B counterpart of per_shard: a thin adapter over
        pipeline._pipeline_1f1b_engine (the slot scan, timetable, and
        ring buffers live THERE, once — round-4 verdict item #5). This
        wires in the flagship specifics: ``lockstep=True`` because the
        stage body contains tp collectives (every rank computes every
        slot and masks accumulations), the tail (final-norm + head)
        loss vjp, the embedding vjp at global stage 0, and the MoE
        router-aux seeds gated to ti == 0 (exclusive-path rule).
        ``n_virtual > 1`` runs the interleaved 1F1B schedule."""
        from mpi_acx_tpu.parallel.pipeline import _pipeline_1f1b_engine
        M, mbl, S = tokens.shape
        tpn = lax.axis_size("tp")
        ti = lax.axis_index("tp")
        blk = S // tpn
        n_tok = M * mbl * S
        calls = cfg.n_layers * M

        slayers = jax.tree.map(lambda p: p[0], params["layers"])
        if n_virtual == 1:
            slayers = jax.tree.map(lambda p: p[None], slayers)  # chunk axis
        tail = {k: v for k, v in params.items() if k != "layers"}
        zero_tail = jax.tree.map(jnp.zeros_like, tail)
        stage_fn = make_stage_fn()

        # fam.embed/final/head only read the tail leaves; hand them a
        # params dict without the layer stack (its layout differs
        # between the chunked and flat cases and is never touched).
        def with_tail(tailp):
            return dict(tailp, layers=None)

        x_all = fam.embed(params, cfg, tokens)     # [M, mbl, S, d]

        def tail_ll(tailp, y, tgt_m):
            # This rank's EXCLUSIVE loss share for one microbatch: the
            # local tp sequence slice, collective-free (assembly is one
            # psum of the accumulated scalars after the scan).
            full = with_tail(tailp)
            ys = fam.final(full, y)
            ys_blk = lax.dynamic_slice_in_dim(ys, ti * blk, blk, axis=1)
            tg_blk = lax.dynamic_slice_in_dim(tgt_m, ti * blk, blk,
                                              axis=1)
            return ll_sum(fam.head(full), ys_blk, tg_blk)

        def loss_side(y_, m):
            tgt_m = lax.dynamic_index_in_dim(targets, m, 0,
                                             keepdims=False)
            llsum, tail_vjp = jax.vjp(
                lambda tp_, yy: tail_ll(tp_, yy, tgt_m), tail, y_)
            d_tail, dy = tail_vjp(
                jnp.asarray(-1.0 / n_tok, llsum.dtype))
            return llsum, d_tail, dy.astype(y_.dtype)

        def embed_side(dx_, m):
            tok_m = lax.dynamic_index_in_dim(tokens, m, 0,
                                             keepdims=False)
            _, embed_vjp = jax.vjp(
                lambda tp_: fam.embed(with_tail(tp_), cfg, tok_m), tail)
            (d,) = embed_vjp(dx_.astype(x_all.dtype))
            return d

        if fam.has_aux:
            gate = (ti == 0).astype(jnp.float32)
            aux_seed = (aux_weight / calls * gate,
                        z_weight / calls * gate)
            aux_gate = ti == 0
        else:
            aux_seed = aux_gate = None

        lacc, aux_acc, gl, gt = _pipeline_1f1b_engine(
            stage_fn, slayers, x_all, "pp", n_virtual,
            loss_side=loss_side, zero_head=zero_tail,
            embed_side=embed_side, aux_seed=aux_seed,
            aux_gate=aux_gate, lockstep=True)

        if fam.has_aux:
            total_ll, lb_t, rz_t = lax.psum(
                (lacc, aux_acc[0], aux_acc[1]), ("pp", "tp"))
        else:
            total_ll = lax.psum(lacc, ("pp", "tp"))
        loss = -total_ll / n_tok
        if fam.has_aux:
            loss = loss + (aux_weight * lb_t + z_weight * rz_t) / calls
        loss = lax.pmean(loss, "dp")

        if n_virtual == 1:
            gl = jax.tree.map(lambda g: g[0], gl)  # drop chunk axis
        # These are TRUE local grads (manual vjp with exclusive seeds —
        # no autodiff loss-assembly psum to undo); reduce directly.
        out = {k: reduce_grad(gt[k], False, False) for k in gt}
        out["layers"] = {
            k: reduce_grad(gl[k][None], fam.tp_sharded(k), True)
            for k in gl
        }
        return loss, out

    specs = fam.specs()
    if n_virtual > 1:
        # Layer leaves gain a chunk axis after 'pp': P(pp, *r) -> P(pp,None,*r).
        specs = dict(specs)
        specs["layers"] = {
            k: P(*((s[0], None) + tuple(s[1:])))
            for k, s in specs["layers"].items()
        }
    data_spec = P(None, "dp")
    body = per_shard_1f1b if schedule == "1f1b" else per_shard
    fn = shard_map(body, mesh=mesh,
                   in_specs=(specs, data_spec, data_spec),
                   out_specs=(P(), specs),
                   check_vma=False)
    return jax.jit(fn), n_stages


def make_train_step(cfg: tfm.TransformerConfig, mesh: Mesh,
                    n_micro: int, lr: float = 1e-2, n_virtual: int = 1,
                    remat: bool = False, dp_quant_bits: int | None = None,
                    aux_weight: float = 1e-2, z_weight: float = 1e-3,
                    schedule: str = "gpipe",
                    xent_chunk: int | None = None):
    """Jitted (params, tokens, targets) -> (loss, new_params) SGD step
    (stateless optimizer; for stateful ones use make_train_step_optax)."""
    grad_fn, n_stages = make_loss_and_grads(cfg, mesh, n_micro,
                                            n_virtual=n_virtual,
                                            remat=remat,
                                            dp_quant_bits=dp_quant_bits,
                                            aux_weight=aux_weight,
                                            z_weight=z_weight,
                                            schedule=schedule,
                                            xent_chunk=xent_chunk)

    @jax.jit
    def step(params, tokens, targets):
        loss, grads = grad_fn(params, tokens, targets)
        new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return loss, new

    return step, n_stages


def make_train_step_optax(cfg: tfm.TransformerConfig, mesh: Mesh,
                          n_micro: int, optimizer, n_virtual: int = 1,
                          remat: bool = False,
                          dp_quant_bits: int | None = None,
                          aux_weight: float = 1e-2, z_weight: float = 1e-3,
                          schedule: str = "gpipe",
                          xent_chunk: int | None = None):
    """Distributed train step with any optax GradientTransformation.

    Returns (step, n_stages): step(params, opt_state, tokens, targets) ->
    (loss, new_params, new_opt_state). Initialize opt_state with
    ``optimizer.init(params)`` — its leaves mirror the parameter tree, so
    XLA's sharding propagation keeps optimizer moments sharded exactly
    like their parameters (pp-staged, tp-split FFN slices included), and
    the whole state checkpoints through mpi_acx_tpu.checkpoint.
    """
    import optax

    grad_fn, n_stages = make_loss_and_grads(cfg, mesh, n_micro,
                                            n_virtual=n_virtual,
                                            remat=remat,
                                            dp_quant_bits=dp_quant_bits,
                                            aux_weight=aux_weight,
                                            z_weight=z_weight,
                                            schedule=schedule,
                                            xent_chunk=xent_chunk)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = grad_fn(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return loss, params, opt_state

    return step, n_stages
