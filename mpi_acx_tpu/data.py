"""Input pipeline: memory-mapped token datasets, batching, and
background prefetch onto the device mesh.

Decode-side and train-side throughput die when the host sits between
batches — the device finishes a step and waits while Python assembles
the next array. This module keeps the device fed:

* :class:`TokenDataset` — a zero-copy ``np.memmap`` view over a binary
  token file (the OS page cache IS the native IO path here: mmap + madvise
  beats any hand-rolled C++ reader for sequential token streams, so
  unlike the runtime's data plane there is genuinely no native code to
  write);
* :func:`batches` — deterministic, seedable [B, S+1] window sampling
  (context + shifted target in one array, the standard LM layout);
* :func:`prefetch` — a bounded background thread that stages the next
  batches on device (``jax.device_put``, optionally with a
  ``NamedSharding`` so dp-sharded train steps consume them with zero
  relayout) while the current step runs.

The reference has no data layer at all (its test "data" is closed-form
ring values — SURVEY.md §4); this is framework-side completeness, built
the JAX way.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import jax
import numpy as np


class TokenDataset:
    """Zero-copy view over a flat binary token file.

    ``dtype`` must match the file's on-disk layout (uint16 covers vocabs
    to 65k — GPT-2's 50257 fits — at half the IO of uint32).
    """

    def __init__(self, path: str, dtype=np.uint16):
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        if len(self.tokens) == 0:
            raise ValueError(f"empty token file: {path}")

    @classmethod
    def from_array(cls, arr) -> "TokenDataset":
        """In-memory variant (tests, synthetic data): same interface
        without a file."""
        self = cls.__new__(cls)
        self.tokens = np.asarray(arr)
        return self

    def __len__(self) -> int:
        return len(self.tokens)


def batches(ds: TokenDataset, batch: int, seq: int, *,
            seed: Optional[int] = 0,
            n_batches: Optional[int] = None) -> Iterator[np.ndarray]:
    """Yields int32 [batch, seq+1] windows (tokens[:, :-1] is the input,
    tokens[:, 1:] the target — slice once on device).

    ``seed=None`` walks the file sequentially without overlap (epoch
    order, truncated tail); an integer seed samples window starts
    uniformly (the usual LM training regime), reproducibly.
    """
    n = len(ds)
    w = seq + 1
    if n < w:
        raise ValueError(f"dataset ({n} tokens) shorter than window {w}")
    if seed is None:
        starts_all = np.arange(0, n - w + 1, w)
        total = len(starts_all) // batch
        if n_batches is not None:
            total = min(total, n_batches)
        for b in range(total):
            s = starts_all[b * batch:(b + 1) * batch]
            yield np.stack([np.asarray(ds.tokens[i:i + w]) for i in s]
                           ).astype(np.int32)
        return
    rng = np.random.default_rng(seed)
    b = 0
    while n_batches is None or b < n_batches:
        s = rng.integers(0, n - w + 1, size=batch)
        yield np.stack([np.asarray(ds.tokens[i:i + w]) for i in s]
                       ).astype(np.int32)
        b += 1


def prefetch(it: Iterator, size: int = 2, sharding=None) -> Iterator:
    """Stage ``size`` upcoming batches on device while the consumer runs.

    A daemon thread pulls from ``it``, ``jax.device_put``s each batch
    (with ``sharding`` when given — e.g. ``NamedSharding(mesh,
    P("dp"))`` so a dp-sharded train step consumes it relayout-free),
    and parks it in a bounded queue; the device-side transfer overlaps
    the consumer's current step. Exceptions in the source iterator are
    re-raised at the consumption point.
    """
    q: "queue.Queue" = queue.Queue(maxsize=size)
    END, ERR = object(), object()
    stop = threading.Event()

    def put(item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if stop.is_set():
                    return
                if sharding is not None:
                    item = jax.device_put(item, sharding)
                else:
                    item = jax.device_put(item)
                if not put(item):
                    return
            put(END)
        except BaseException as e:  # noqa: BLE001 — surfaced to consumer
            put((ERR, e))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if (isinstance(item, tuple) and len(item) == 2
                    and item[0] is ERR):
                raise item[1]
            yield item
    finally:
        # Consumer finished or abandoned the generator (break/exception/
        # GeneratorExit): release the worker and drop staged batches so
        # device buffers are not pinned for the process lifetime.
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
