"""tpu-acx benchmark — prints ONE JSON line for the driver.

Primary metric: enqueued Isend/Irecv ping-pong p50 latency (µs) through the
full native stack (host execution queue -> flag table -> proxy -> socket
wire), 2 processes under acxrun — BASELINE.md metric #2. Also reports
partitioned-exchange bandwidth (host plane) and, when a TPU chip is
present, flagship-model forward throughput on the MXU.

The reference (NVIDIA/mpi-acx) publishes no numbers (SURVEY.md §6);
BASELINE.md records our own round-2 measurements as the baseline, so
vs_baseline tracks regression/improvement across rounds.
"""

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Round-2 baseline measurements (this machine, recorded in BASELINE.md).
BASELINE_P50_US = 26.6
BASELINE_PART_BW_GBPS = 1.12


def native_bench():
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True)
    r = subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
         "300", os.path.join(REPO, "build", "bench_pingpong")],
        capture_output=True, text=True, timeout=400)
    m = re.search(r"pingpong_p50_us=([\d.]+).*part_bw_gbps=([\d.]+)",
                  r.stdout)
    if not m:
        raise RuntimeError(f"bench_pingpong failed: {r.stdout} {r.stderr}")
    return float(m.group(1)), float(m.group(2))


def tpu_bench():
    """Flagship GPT-2 125M forward throughput (tokens/s) on the local
    accelerator; None if JAX has no usable device.

    The repetition loop runs ON DEVICE (lax.scan of REPS forwards with an
    iteration-dependent input so XLA can't hoist the body) and the result
    is fetched as a scalar. Host-side loops measure the host<->device
    round-trip (tens of ms through the axon tunnel), not the TPU — this
    methodology reports device throughput, which is what a deployment
    without the tunnel gets."""
    try:
        import jax
        import jax.numpy as jnp
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graft_entry", os.path.join(REPO, "__graft_entry__.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fn, (params, tokens) = mod.entry()
        reps = 50
        vocab = int(tokens.max()) + 1

        @jax.jit
        def loop(params, tokens):
            def body(carry, i):
                acc, t = carry
                ti = (t + i) % vocab
                return (acc + fn(params, ti).sum(), t), None
            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), tokens),
                jnp.arange(reps))
            return acc

        float(loop(params, tokens))                    # compile + warm
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            float(loop(params, tokens))                # device_get = sync
            best = min(best, (time.perf_counter() - t0) / reps)
        toks = tokens.size / best
        return round(toks, 1), str(jax.devices()[0].platform)
    except Exception as e:  # no TPU / compile issue: report without it
        print(f"bench: tpu path skipped: {e}", file=sys.stderr)
        return None, None


def main():
    p50, bw = native_bench()
    toks, platform = tpu_bench()
    out = {
        "metric": "enqueued_pingpong_p50_latency",
        "value": p50,
        "unit": "us",
        # Latency: lower is better -> ratio >= 1 means at/above baseline.
        "vs_baseline": round(BASELINE_P50_US / p50, 3),
        "partitioned_bw_gbps": bw,
        "partitioned_bw_vs_baseline": round(bw / BASELINE_PART_BW_GBPS, 3),
    }
    if toks is not None:
        out["gpt2_fwd_tokens_per_s"] = toks
        out["device"] = platform
    print(json.dumps(out))


if __name__ == "__main__":
    main()
