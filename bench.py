"""tpu-acx benchmark — prints ONE JSON line for the driver.

Primary metric: enqueued Isend/Irecv ping-pong p50 latency (µs) through the
full native stack (host execution queue -> flag table -> proxy -> socket
wire), 2 processes under acxrun — BASELINE.md metric #2. Also reports
partitioned-exchange bandwidth (host plane) and flagship-model forward
throughput + MFU on the TPU chip.

The TPU measurement runs in a SUBPROCESS with retries: the chip arrives
via the axon tunnel and its PJRT init can fail or hang transiently
(round 2 lost all TPU evidence to exactly that). A hung child is killed
by timeout and retried; after the last attempt the failure is reported
LOUDLY as a "tpu_error" field in the JSON line instead of being dropped.

`python bench.py --full` additionally re-measures the secondary
BASELINE.md rows (flash-attention speedup @ S=4096, KV-cache decode
tok/s, AdamW train-step tok/s) and regression-checks all starred/TPU
rows against BASELINE.md with a 10% tolerance, writing BENCH_FULL.json
and exiting nonzero on any regression.

The reference (NVIDIA/mpi-acx) publishes no numbers (SURVEY.md §6);
BASELINE.md records our own measurements as the baseline, so
vs_baseline tracks regression/improvement across rounds.
"""

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Round-2 baseline measurements (this machine, recorded in BASELINE.md).
BASELINE_P50_US = 26.6
BASELINE_PART_BW_GBPS = 1.12
BASELINE_GPT2_FWD_TOKS = 221_900.0
BASELINE_GPT2_FWD_B16S512_TOKS = 377_600.0  # saturating shape (r3)
# Device-side-loop methodology (round 3); round-2's 5.3x was host-side
# per-call timing, which through the axon tunnel reports dispatch latency
# rather than kernel time (see BASELINE.md).
BASELINE_FLASH_SPEEDUP_4096 = 2.4
BASELINE_DECODE_TOKS = 2_700.0
BASELINE_TRAIN_TOKS = 78_000.0  # device-side scan-loop measurement (r3)
# Deterministic (CPU-compiled HLO) — measured 3.88x; gate below it.
BASELINE_QUANT_TRAFFIC_REDUCTION = 3.5

# v5e bf16 peak: 197 TFLOP/s per chip (public spec).
V5E_BF16_PEAK_FLOPS = 197e12
GPT2_SMALL_PARAMS = 124e6


def native_bench():
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True)
    r = subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
         "300", os.path.join(REPO, "build", "bench_pingpong")],
        capture_output=True, text=True, timeout=400)
    m = re.search(r"pingpong_p50_us=([\d.]+).*part_bw_gbps=([\d.]+)",
                  r.stdout)
    if not m:
        raise RuntimeError(f"bench_pingpong failed: {r.stdout} {r.stderr}")
    return float(m.group(1)), float(m.group(2))


def _run_tpu_child(mode: str, attempts: int = 3, timeout: int = 420,
                   child_flag: str = "tpu-child", env: dict | None = None):
    """Run `bench.py --<child_flag>-<mode>` in a fresh process, retrying
    on failure/hang. Returns (parsed dict | None, last_error | None)."""
    if attempts < 1:
        return None, "skipped (previous TPU child exhausted its retries)"
    last = None
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 f"--{child_flag}-{mode}"],
                env=env, capture_output=True, text=True, timeout=timeout)
            for line in r.stdout.splitlines():
                if line.startswith("{"):
                    return json.loads(line), None
            last = (f"rc={r.returncode} no JSON in output; "
                    f"stderr tail: {r.stderr[-300:]}")
        except subprocess.TimeoutExpired:
            last = f"timeout after {timeout}s (attempt {i + 1})"
        except Exception as e:  # noqa: BLE001 — report, don't crash bench
            last = f"{type(e).__name__}: {e}"
        if i + 1 < attempts:
            time.sleep(10 * (i + 1))   # tunnel hiccups are transient
    return None, last


def tpu_child_fwd():
    """Child process: flagship GPT-2 125M forward throughput (tokens/s).

    The repetition loop runs ON DEVICE (lax.scan of REPS forwards with an
    iteration-dependent input so XLA can't hoist the body) and the result
    is fetched as a scalar. Host-side loops measure the host<->device
    round-trip (tens of ms through the axon tunnel), not the TPU — this
    methodology reports device throughput, which is what a deployment
    without the tunnel gets."""
    import jax
    import jax.numpy as jnp
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(REPO, "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, (params, tokens) = mod.entry()
    reps = 50
    vocab = int(tokens.max()) + 1

    def measure(tokens, reps_n):
        @jax.jit
        def loop_n(params, tokens):
            def body(carry, i):
                acc, t = carry
                ti = (t + i) % vocab
                return (acc + fn(params, ti).sum(), t), None
            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), tokens),
                jnp.arange(reps_n))
            return acc

        float(loop_n(params, tokens))              # compile + warm
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            float(loop_n(params, tokens))          # device_get = sync
            best = min(best, (time.perf_counter() - t0) / reps_n)
        return tokens.size / best

    toks = measure(tokens, reps)
    # Forward-pass MFU: ~2 FLOPs per parameter per token on the matmuls.
    mfu = toks * 2 * GPT2_SMALL_PARAMS / V5E_BF16_PEAK_FLOPS
    # Saturating shape (B=16, S=512): the entry() row (B=2, S=256) is a
    # latency shape; this one shows the chip's throughput ceiling.
    big = jax.random.randint(jax.random.key(2), (16, 512), 0, vocab)
    toks_big = measure(big, 10)
    print(json.dumps({
        "gpt2_fwd_tokens_per_s": round(toks, 1),
        "gpt2_fwd_mfu": round(mfu, 4),
        "gpt2_fwd_b16s512_tokens_per_s": round(toks_big, 1),
        "gpt2_fwd_b16s512_mfu": round(
            toks_big * 2 * GPT2_SMALL_PARAMS / V5E_BF16_PEAK_FLOPS, 4),
        "device": str(jax.devices()[0].platform),
    }))


def tpu_child_full():
    """Child process: secondary BASELINE.md rows — flash-attention speedup
    vs dense at S=4096 (GPT-2 heads) and KV-cache greedy decode tok/s."""
    import jax
    import jax.numpy as jnp
    from mpi_acx_tpu.ops.attention import attention_reference, flash_attention
    from mpi_acx_tpu.models import transformer as tfm

    def timeit(f, *a, reps=1):
        """Best-of-3 wall time of one f(*a) call (fully synced)."""
        jax.block_until_ready(f(*a))               # compile + warm
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = f(*a)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / reps)
        return best

    def timeit_device(fn, q, k, v, reps=20):
        """Device-side rep loop (lax.scan with an iteration-dependent
        input so XLA can't hoist the body): host-side per-call timing
        through the axon tunnel reports dispatch latency, not kernel
        time — sub-ms kernels need the loop ON the device."""
        @jax.jit
        def loop(q, k, v):
            def body(acc, i):
                qq = q + (i % 2).astype(q.dtype) * 1e-3
                return acc + fn(qq, k, v).astype(jnp.float32).sum(), None
            acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                  jnp.arange(reps))
            return acc
        float(loop(q, k, v))                       # compile + warm
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            float(loop(q, k, v))                   # scalar fetch = sync
            best = min(best, (time.perf_counter() - t0) / reps)
        return best

    # Flash vs dense, GPT-2 head geometry, S=4096, device-side loops.
    B, S, H, D = 1, 4096, 12, 64
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in ks)
    t_dense = timeit_device(attention_reference, q, k, v)
    t_flash = timeit_device(flash_attention, q, k, v)
    speedup = t_dense / t_flash

    # KV-cache greedy decode, B=8, bf16 weights.
    cfg = tfm.gpt2_small()
    params_f32 = tfm.init_params(jax.random.key(0), cfg)
    params = tfm.cast_params(params_f32, jnp.bfloat16)
    B, S_p, n_new = 8, 32, 64
    prompt = jax.random.randint(jax.random.key(1), (B, S_p), 0, cfg.vocab)
    gen = jax.jit(lambda p, t: tfm.generate(p, cfg, t, n_new, max_len=256))
    decode_toks = B * n_new / timeit(gen, params, prompt)
    # Single-chip AdamW training step, B=8 S=512 (README's training row).
    # The rep loop is a lax.scan of real optimizer steps ON DEVICE (host
    # per-call timing would fold the tunnel dispatch RTT into a ~75 ms
    # step); params/opt-state are the scan carry, so every iteration is a
    # genuine dependent update XLA can't elide.
    import optax
    opt = optax.adamw(1e-4)
    ostate = opt.init(params_f32)
    tok = jax.random.randint(jax.random.key(2), (8, 512), 0, cfg.vocab)
    tgt = jnp.roll(tok, -1, axis=-1)
    treps = 5

    @jax.jit
    def train_loop(p, s, tok, tgt):
        def body(carry, _):
            p, s = carry
            loss, g = jax.value_and_grad(tfm.loss_fn)(p, cfg, tok, tgt)
            upd, s = opt.update(g, s, p)
            return (optax.apply_updates(p, upd), s), loss
        (_, _), losses = jax.lax.scan(body, (p, s), None, length=treps)
        return losses[-1]

    train_toks = tok.size / (
        timeit(train_loop, params_f32, ostate, tok, tgt) / treps)

    # A/B: the same step with chunked-vocab CE (ops/xent.py) — the
    # [4096, 50257] logits tensor (~0.8 GB f32) never materializes;
    # measures whether the saved HBM traffic beats the scan overhead.
    @jax.jit
    def train_loop_chunked(p, s, tok, tgt):
        def body(carry, _):
            p, s = carry
            loss, g = jax.value_and_grad(
                lambda p: tfm.loss_fn(p, cfg, tok, tgt,
                                      xent_chunk=8192))(p)
            upd, s = opt.update(g, s, p)
            return (optax.apply_updates(p, upd), s), loss
        (_, _), losses = jax.lax.scan(body, (p, s), None, length=treps)
        return losses[-1]

    train_toks_chunked = tok.size / (
        timeit(train_loop_chunked, params_f32, ostate, tok, tgt) / treps)

    print(json.dumps({
        "flash_speedup_s4096": round(speedup, 2),
        "flash_ms": round(t_flash * 1e3, 3),
        "dense_ms": round(t_dense * 1e3, 3),
        "decode_tokens_per_s": round(decode_toks, 1),
        "train_step_tokens_per_s": round(train_toks, 1),
        "train_step_xentchunk_tokens_per_s": round(train_toks_chunked, 1),
        "device": str(jax.devices()[0].platform),
    }))


def tpu_child_spec():
    """Child process: on-chip speculative-decoding wall-clock. Trains the
    GPT-2 125M target and a 2-layer draft on a repetition task (so the
    draft's proposals usually match), then times plain greedy decode vs
    the speculative loop at the same (B=1, n_new) workload. Informational
    row — never regression-gated (acceptance depends on the task)."""
    import jax
    import jax.numpy as jnp
    import optax
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.models.speculative import speculative_generate

    import dataclasses
    n_new, k = 128, 4
    cfg = tfm.gpt2_small()
    dcfg = dataclasses.replace(cfg, n_layers=2)
    tok = jax.random.randint(jax.random.key(1), (8, 64), 0, cfg.vocab)

    def train(c, key, steps=40):
        p = tfm.init_params(key, c)
        opt = optax.adam(3e-3)
        st = opt.init(p)

        @jax.jit
        def step(p, st):
            loss, g = jax.value_and_grad(tfm.loss_fn)(p, c, tok, tok)
            up, st = opt.update(g, st)
            return optax.apply_updates(p, up), st, loss
        for _ in range(steps):
            p, st, _ = step(p, st)
        return tfm.cast_params(p, jnp.bfloat16)

    params = train(cfg, jax.random.key(0))
    dparams = train(dcfg, jax.random.key(5))
    prompt = tok[:1, :32]

    gen = jax.jit(lambda p, t: tfm.generate(
        p, cfg, t, n_new, max_len=32 + n_new + k))
    jax.block_until_ready(gen(params, prompt))
    t0 = time.perf_counter()
    jax.block_until_ready(gen(params, prompt))
    t_plain = time.perf_counter() - t0

    out, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    jax.block_until_ready(out)
    t_spec = time.perf_counter() - t0
    rounds = int(stats["rounds"])

    # Batched speculation (B=8): the vmap-lifted loop — per-row rounds,
    # wall-clock bounded by the slowest row.
    B = 8
    prompts = jnp.tile(tok[:1, :32], (B, 1)).at[:, -1].set(
        jnp.arange(B) % cfg.vocab)
    outb, statsb = speculative_generate(dparams, dcfg, params, cfg,
                                        prompts, n_new, k=k)
    jax.block_until_ready(outb)
    t0 = time.perf_counter()
    outb, statsb = speculative_generate(dparams, dcfg, params, cfg,
                                        prompts, n_new, k=k)
    jax.block_until_ready(outb)
    t_spec_b = time.perf_counter() - t0
    rounds_b = [int(r) for r in statsb["rounds"]]

    print(json.dumps({
        "spec_speedup": round(t_plain / t_spec, 2),
        "spec_plain_ms": round(t_plain * 1e3, 1),
        "spec_ms": round(t_spec * 1e3, 1),
        "spec_rounds": rounds,
        "spec_target_pass_reduction": round(n_new / rounds, 2),
        "spec_accepted": int(stats["drafted_accepted"]),
        "spec_batched_ms": round(t_spec_b * 1e3, 1),
        "spec_batched_tokens_per_s": round(B * n_new / t_spec_b, 1),
        "spec_batched_rounds_max": max(rounds_b),
        "spec_batched_target_pass_reduction": round(
            n_new / max(rounds_b), 2),
        "device": str(jax.devices()[0].platform),
    }))


def cpu_child_quant():
    """Child process (forced CPU, 8 virtual devices): wire-byte ratio of
    the int8-quantized ring all-reduce vs an f32 ring with the identical
    schedule, counted from collective-permute payload types in the
    compiled HLO. Deterministic — no chip, no weather — so the driver's
    artifact carries a perf-design metric even when the TPU tunnel is
    down."""
    import re as _re
    import jax
    # This child is CPU by definition: pin unconditionally so a direct
    # `bench.py --cpu-child-quant` invocation cannot block in the pinned
    # accelerator plugin's init loop (the round-2 dryrun failure mode).
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from mpi_acx_tpu.parallel import mesh_from_devices
    from mpi_acx_tpu.parallel.quantized import ring_psum

    n, SZ = 8, 131072
    mesh = mesh_from_devices({"x": n}, jax.devices()[:n])

    def wire_bytes(fn):
        f = shard_map(fn, mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                      check_vma=False)
        txt = jax.jit(f).lower(
            jnp.zeros((n, SZ), jnp.float32)).compile().as_text()
        per = {"u8": 1, "s8": 1, "pred": 1, "bf16": 2, "f16": 2,
               "f32": 4, "s32": 4}
        total = 0
        for mm in _re.finditer(
                r"(u8|s8|pred|f32|s32|bf16|f16)\[([\d,]*)\]\S* "
                r"collective-permute", txt):
            cnt = 1
            for d in mm.group(2).split(","):
                if d:
                    cnt *= int(d)
            total += cnt * per[mm.group(1)]
        return total

    # Numerator and denominator share ONE ring skeleton
    # (quantized.ring_psum), so the comparison cannot silently drift.
    bq = wire_bytes(lambda v: ring_psum(v[0], "x", quantize=True)[None])
    be = wire_bytes(lambda v: ring_psum(v[0], "x", quantize=False)[None])
    print(json.dumps({
        "quant_allreduce_wire_bytes": bq,
        "exact_ring_wire_bytes": be,
        "quant_allreduce_traffic_reduction": round(be / max(bq, 1), 2),
    }))


def _run_cpu_child(mode: str, timeout: int = 300):
    """_run_tpu_child with a forced 8-virtual-device CPU backend (the
    pinned axon platform must never initialize here)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return _run_tpu_child(mode, attempts=1, timeout=timeout,
                          child_flag="cpu-child", env=env)


def main(full: bool = False):
    p50, bw = native_bench()
    out = {
        "metric": "enqueued_pingpong_p50_latency",
        "value": p50,
        "unit": "us",
        # Latency: lower is better -> ratio >= 1 means at/above baseline.
        "vs_baseline": round(BASELINE_P50_US / p50, 3),
        "partitioned_bw_gbps": bw,
        "partitioned_bw_vs_baseline": round(bw / BASELINE_PART_BW_GBPS, 3),
    }
    # Provisional line FIRST: if a driver timeout kills us mid-TPU-retry,
    # the native metrics still reach the artifact (the driver parses the
    # last JSON line, so a completed run supersedes this one).
    provisional = dict(out)
    provisional["tpu_error"] = "provisional line: TPU measurement pending"
    print(json.dumps(provisional), flush=True)

    fwd, err = _run_tpu_child("fwd")
    if fwd is not None:
        out.update(fwd)
        out["gpt2_fwd_vs_baseline"] = round(
            fwd["gpt2_fwd_tokens_per_s"] / BASELINE_GPT2_FWD_TOKS, 3)
    else:
        out["tpu_error"] = err     # LOUD: never silently drop the metric

    # Deterministic, chip-independent design metric (CPU-compiled HLO).
    qb, qerr = _run_cpu_child("quant")
    if qb is not None:
        out.update(qb)
    else:
        out["quant_bytes_error"] = qerr

    checks = []
    if full:
        # Don't burn another 3x600s if the tunnel just proved dead.
        sec, err2 = _run_tpu_child(
            "full", attempts=3 if fwd is not None else 1, timeout=600)
        if sec is not None:
            out.update(sec)
        else:
            out["tpu_full_error"] = err2
        # Speculative decode wall-clock: informational, isolated in its
        # own child so a failure cannot cost the gated rows above.
        spec, err3 = _run_tpu_child(
            "spec", attempts=2 if fwd is not None else 1, timeout=600)
        if spec is not None:
            out.update(spec)
        else:
            out["tpu_spec_error"] = err3
        # Regression gate: every starred/TPU BASELINE.md row, 10%.
        # An UNMEASURED row is recorded as skipped — loudly, with the
        # outage reason — NOT as a regression: a red gate must mean the
        # code got slower, never that the tunnel was down (round-3
        # verdict weak #2). The skip requires a recorded child failure
        # for THAT row's source: a metric that vanishes while its child
        # succeeded (key drift), or a chip-INDEPENDENT child failing,
        # still fails the gate.
        def gate(name, value, baseline, higher_is_better=True,
                 unmeasured_reason=None):
            if value is None:
                if unmeasured_reason is not None:
                    checks.append({
                        "metric": name, "ok": None, "skipped": True,
                        "reason": f"not measured ({unmeasured_reason})"})
                else:
                    checks.append({
                        "metric": name, "ok": False,
                        "reason": "metric missing from a successful "
                                  "child (key drift?)"})
                return
            if higher_is_better:
                ok = value >= baseline * 0.9
            else:                      # latency: at most 10% above baseline
                ok = value <= baseline * 1.1
            checks.append({"metric": name, "value": value,
                           "baseline": baseline,
                           "ratio": round(value / baseline, 3), "ok": ok})

        fwd_why = None if fwd is not None else f"TPU outage: {err}"
        sec_why = None if sec is not None else f"TPU outage: {err2}"
        gate("pingpong_p50_us", p50, BASELINE_P50_US, higher_is_better=False)
        gate("partitioned_bw_gbps", bw, BASELINE_PART_BW_GBPS)
        gate("gpt2_fwd_tokens_per_s",
             (fwd or {}).get("gpt2_fwd_tokens_per_s"), BASELINE_GPT2_FWD_TOKS,
             unmeasured_reason=fwd_why)
        gate("gpt2_fwd_b16s512_tokens_per_s",
             (fwd or {}).get("gpt2_fwd_b16s512_tokens_per_s"),
             BASELINE_GPT2_FWD_B16S512_TOKS, unmeasured_reason=fwd_why)
        gate("flash_speedup_s4096",
             (sec or {}).get("flash_speedup_s4096"),
             BASELINE_FLASH_SPEEDUP_4096, unmeasured_reason=sec_why)
        gate("decode_tokens_per_s",
             (sec or {}).get("decode_tokens_per_s"), BASELINE_DECODE_TOKS,
             unmeasured_reason=sec_why)
        gate("train_step_tokens_per_s",
             (sec or {}).get("train_step_tokens_per_s"),
             BASELINE_TRAIN_TOKS, unmeasured_reason=sec_why)
        # Chip-independent row: a failure here is NEVER an outage skip.
        gate("quant_allreduce_traffic_reduction",
             (qb or {}).get("quant_allreduce_traffic_reduction"),
             BASELINE_QUANT_TRAFFIC_REDUCTION)
        out["regressions"] = [c["metric"] for c in checks
                              if c["ok"] is False]
        out["unmeasured"] = [c["metric"] for c in checks
                             if c.get("skipped")]
        with open(os.path.join(REPO, "BENCH_FULL.json"), "w") as f:
            json.dump({"checks": checks, "result": out}, f, indent=1)

    print(json.dumps(out))
    if full and any(c["ok"] is False for c in checks):
        sys.exit(1)


if __name__ == "__main__":
    if "--cpu-child-quant" in sys.argv:
        cpu_child_quant()
    elif "--tpu-child-fwd" in sys.argv:
        tpu_child_fwd()
    elif "--tpu-child-full" in sys.argv:
        tpu_child_full()
    elif "--tpu-child-spec" in sys.argv:
        tpu_child_spec()
    else:
        main(full="--full" in sys.argv)
