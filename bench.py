"""tpu-acx benchmark — prints ONE JSON line for the driver.

Primary metric: enqueued Isend/Irecv ping-pong p50 latency (µs) through the
full native stack (host execution queue -> flag table -> proxy -> socket
wire), 2 processes under acxrun — BASELINE.md metric #2. Also reports
partitioned-exchange bandwidth (host plane) and flagship-model forward
throughput + MFU on the TPU chip.

The TPU measurement runs in SUBPROCESSES with retries: the chip arrives
via the axon tunnel and its PJRT init can fail or hang transiently
(round 2 lost all TPU evidence to exactly that). A hung child is killed
by timeout and retried; after the last attempt the failure is reported
LOUDLY as a "tpu_error" field in the JSON line instead of being dropped.

Capture is INCREMENTAL (rounds 2-4 lost entire windows to all-or-nothing
600 s children): a cheap probe child gates the expensive ones, each
metric group runs in its OWN child with its own timeout, every child's
rows are banked to BENCH_BANK.json the moment they land, and in --full
mode BENCH_FULL.json is rewritten after EVERY child — a driver kill or
tunnel drop mid-run keeps everything measured up to that point.

`python bench.py --full` additionally re-measures the secondary
BASELINE.md rows (flash-attention speedup @ S=4096, KV-cache decode
tok/s, AdamW train-step tok/s) and regression-checks all starred/TPU
rows against BASELINE.md with a 10% tolerance, writing BENCH_FULL.json
and exiting nonzero on any regression.

The reference (NVIDIA/mpi-acx) publishes no numbers (SURVEY.md §6);
BASELINE.md records our own measurements as the baseline, so
vs_baseline tracks regression/improvement across rounds.
"""

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Round-2 baseline measurements (this machine, recorded in BASELINE.md).
BASELINE_P50_US = 26.6
BASELINE_PART_BW_GBPS = 1.12
BASELINE_GPT2_FWD_TOKS = 221_900.0
BASELINE_GPT2_FWD_B16S512_TOKS = 377_600.0  # saturating shape (r3)
# Device-side-loop methodology (round 3); round-2's 5.3x was host-side
# per-call timing, which through the axon tunnel reports dispatch latency
# rather than kernel time (see BASELINE.md).
BASELINE_FLASH_SPEEDUP_4096 = 2.4
BASELINE_DECODE_TOKS = 2_700.0
BASELINE_TRAIN_TOKS = 78_000.0  # device-side scan-loop measurement (r3)
# Deterministic (CPU-compiled HLO) — measured 3.88x; gate below it.
BASELINE_QUANT_TRAFFIC_REDUCTION = 3.5

# v5e bf16 peak: 197 TFLOP/s per chip (public spec).
V5E_BF16_PEAK_FLOPS = 197e12
GPT2_SMALL_PARAMS = 124e6


def native_bench(msg_bytes: int | None = None):
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True)
    cmd = [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
           "300", os.path.join(REPO, "build", "bench_pingpong")]
    if msg_bytes is not None:
        cmd.append(str(msg_bytes))
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=400)
    m = re.search(r"pingpong_p50_us=([\d.]+) pingpong_p99_us=([\d.]+) "
                  r"part_bw_gbps=([\d.]+)", r.stdout)
    if not m:
        raise RuntimeError(f"bench_pingpong failed: {r.stdout} {r.stderr}")
    return float(m.group(1)), float(m.group(2)), float(m.group(3))


def native_stripe_sweep(lane_counts=(1, 2, 4)):
    """Striped-wire bandwidth rows (DESIGN.md §15). ACX_STRIPES is fixed
    at transport construction, so each lane count is its own acxrun on
    the socket plane; ACX_RV_THRESHOLD=0 forces the eager path so large
    messages actually stripe instead of taking rendezvous."""
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True)
    rows = []
    for s in lane_counts:
        env = dict(os.environ, ACX_BENCH_STRIPE_SWEEP="1",
                   ACX_RV_THRESHOLD="0", ACX_STRIPES=str(s))
        cmd = [os.path.join(REPO, "build", "acxrun"), "-np", "2",
               "-timeout", "300", "-transport", "socket",
               os.path.join(REPO, "build", "bench_pingpong"), "8"]
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=400, env=env)
        got = re.findall(r"BENCH_STRIPE stripes=(\d+) msg_bytes=(\d+) "
                         r"bw_gbps=([\d.]+)", r.stdout)
        if not got:
            raise RuntimeError(
                f"stripe sweep stripes={s} produced no rows: "
                f"{r.stdout[-300:]} {r.stderr[-300:]}")
        for st, mb, g in got:
            rows.append({"stripes": int(st), "msg_bytes": int(mb),
                         "bw_gbps": float(g)})
    return rows


def _record_wire_rows(rows, part_bw):
    """Fold the striped-wire rows into the newest MULTICHIP_r*.json so
    the multichip artifact carries the wire-plane numbers alongside the
    mesh result. The artifact belongs to the driver: merge, never fail."""
    import glob
    files = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    if not files:
        return
    try:
        with open(files[-1]) as f:
            d = json.load(f)
        d["wire"] = {"partitioned_bw_gbps": part_bw, "stripe_sweep": rows}
        with open(files[-1], "w") as f:
            json.dump(d, f)
            f.write("\n")
    except Exception:  # noqa: BLE001
        pass


def disagg_fleet_rows(n_reqs: int = 6, timeout: int = 300):
    """TTFT A/B of the role-split disagg fleet (models/disagg.py): the
    same 3-rank (1 prefill + 2 decode) workload with per-layer Pready
    overlap ON vs OFF (ship only after the full prompt pass). Decode
    ranks print DISAGG_ROW lines with their observed TTFT p50 and the
    exposed-ship p50 (FIN-carried: publish time left after the head) —
    per-layer Pready hides the ship under compute, so its exposed time
    is ~0 while the baseline pays the full serialized pack+publish on
    the TTFT path. ACX_DISAGG_BIG makes each handoff ~1 MiB so that
    exposure is milliseconds, not clock noise."""
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True)
    rows = {}
    for key, overlap in (("overlap", "1"), ("noverlap", "0")):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["ACX_ROLE"] = "prefill,decode,decode"
        env["ACX_DISAGG_OVERLAP"] = overlap
        env["ACX_DISAGG_REQS"] = str(n_reqs)
        env["ACX_DISAGG_BIG"] = "1"
        cmd = [os.path.join(REPO, "build", "acxrun"), "-np", "3",
               "-timeout", str(timeout), "-transport", "socket",
               sys.executable, os.path.join(REPO, "tests",
                                            "disagg_worker.py")]
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout + 60, env=env)
        decoded = [json.loads(ln.split("DISAGG_ROW ", 1)[1])
                   for ln in r.stdout.splitlines()
                   if ln.startswith("DISAGG_ROW ")]
        decoded = [d for d in decoded if d.get("role") == "decode"]
        if r.returncode != 0 or not decoded:
            raise RuntimeError(
                f"disagg fleet ({key}) rc={r.returncode}: "
                f"{r.stdout[-300:]} {r.stderr[-300:]}")
        ttfts = sorted(d["ttft_p50_s"] for d in decoded)
        exposes = sorted(d["expose_p50_s"] for d in decoded)
        rows[f"disagg_fleet_ttft_{key}_p50_s"] = round(
            ttfts[len(ttfts) // 2], 4)
        rows[f"disagg_fleet_ship_exposed_{key}_p50_ms"] = round(
            exposes[len(exposes) // 2] * 1e3, 3)
    rows["disagg_fleet_overlap_ttft_speedup"] = round(
        rows["disagg_fleet_ttft_noverlap_p50_s"]
        / max(rows["disagg_fleet_ttft_overlap_p50_s"], 1e-9), 3)
    rows["disagg_fleet_ship_hidden_ms"] = round(
        rows["disagg_fleet_ship_exposed_noverlap_p50_ms"]
        - rows["disagg_fleet_ship_exposed_overlap_p50_ms"], 3)
    return rows


def _record_disagg_rows(rows):
    """Fold the disagg rows into the newest MULTICHIP_r*.json (same
    merge-never-fail contract as _record_wire_rows)."""
    import glob
    files = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    if not files:
        return
    try:
        with open(files[-1]) as f:
            d = json.load(f)
        d["disagg"] = rows
        with open(files[-1], "w") as f:
            json.dump(d, f)
            f.write("\n")
    except Exception:  # noqa: BLE001
        pass


def journey_phase_rows(n_reqs: int = 6, timeout: int = 300):
    """Per-phase serving-time budget from the request-journey plane
    (docs/DESIGN.md §20): run the 3-rank journaled fleet
    (tests/request_worker.py — mono warmup first, so the phases measure
    serving rather than XLA compiles), reconstruct the journeys offline
    with tools/acx_request.py, and bank the fleet queue/prefill/ship/
    decode p50/p99 so future PRs can regress against phase budgets, not
    just the aggregate TTFT the disagg rows already carry."""
    import glob
    import tempfile
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True)
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["ACX_ROLE"] = "prefill,decode,decode"
        env["ACX_DISAGG_REQS"] = str(n_reqs)
        env["ACX_REQLOG"] = os.path.join(td, "run")
        env["ACX_TRACE"] = os.path.join(td, "run")
        env["ACX_TRACE_CAP"] = "2000000"
        cmd = [os.path.join(REPO, "build", "acxrun"), "-np", "3",
               "-timeout", str(timeout), "-transport", "socket",
               sys.executable, os.path.join(REPO, "tests",
                                            "request_worker.py")]
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout + 60, env=env)
        if r.returncode != 0:
            raise RuntimeError(
                f"journey fleet rc={r.returncode}: "
                f"{r.stdout[-300:]} {r.stderr[-300:]}")
        inputs = (sorted(glob.glob(os.path.join(
                      td, "run.rank*.reqlog.jsonl")))
                  + sorted(glob.glob(os.path.join(
                      td, "run.rank*.trace.json"))))
        rep_path = os.path.join(td, "journey.json")
        rq = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "acx_request.py"),
             "--json", rep_path] + inputs,
            capture_output=True, text=True, timeout=120)
        if rq.returncode != 0:
            raise RuntimeError(
                f"acx_request rc={rq.returncode}: {rq.stderr[-300:]}")
        with open(rep_path) as f:
            rep = json.load(f)
    rows = {}
    for ph in ("queue", "prefill", "ship", "decode"):
        st = rep["phase_breakdown"][ph]
        rows[f"journey_{ph}_p50_s"] = round(st["p50_s"] or 0.0, 4)
        rows[f"journey_{ph}_p99_s"] = round(st["p99_s"] or 0.0, 4)
    rows["journey_reconstructed_rate"] = rep["reconstructed_rate"]
    rows["journey_dominant_phase"] = rep["dominant_phase"]
    return rows


def _record_journey_rows(rows):
    """Fold the journey phase-budget rows into the newest BENCH_r*.json
    (same merge-never-fail contract as _record_paged_rows)."""
    import glob
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not files:
        return
    try:
        with open(files[-1]) as f:
            d = json.load(f)
        d["journey"] = rows
        with open(files[-1], "w") as f:
            json.dump(d, f)
            f.write("\n")
    except Exception:  # noqa: BLE001
        pass


def _code_rev():
    """Fingerprint of the MEASURED code: tree hashes of the source
    paths plus any uncommitted diff to them. Deliberately excludes the
    bench artifacts, so the banker's own artifact commits don't shift
    it — but ANY code change (committed or not) does, which is what
    lets _bank_reuse refuse rows measured on code that no longer
    exists (r05 review: the decode group's 0.73x int8-KV row predated
    the scale-on-scores fix and would otherwise have been reused as
    evidence for it)."""
    paths = ["mpi_acx_tpu", "src", "include", "bench.py"]
    try:
        import hashlib
        h = subprocess.run(
            ["git", "-C", REPO, "rev-parse"] +
            [f"HEAD:{p}" for p in paths],
            capture_output=True, text=True, timeout=30).stdout
        d = subprocess.run(
            ["git", "-C", REPO, "diff", "HEAD", "--"] + paths,
            capture_output=True, text=True, timeout=30).stdout
        # Untracked sources are invisible to both rev-parse and diff —
        # a brand-new module measured before its first commit would
        # otherwise share a fingerprint with the tree that lacks it.
        u = subprocess.run(
            ["git", "-C", REPO, "ls-files", "--others",
             "--exclude-standard", "--"] + paths,
            capture_output=True, text=True, timeout=30).stdout
        parts = [h.encode(), d.encode()]
        for name in sorted(u.split()):
            try:
                with open(os.path.join(REPO, name), "rb") as f:
                    parts.append(name.encode() + b"\0" + f.read())
            except OSError:  # racing delete: name alone still shifts it
                parts.append(name.encode() + b"\0?")
        return hashlib.sha1(b"".join(parts)).hexdigest()[:12]
    except Exception:  # noqa: BLE001 — no git: disable reuse, not bench
        return "unknown"


def _bench_cfg():
    """The bench model geometry: GPT-2 125M, or a seconds-scale toy
    under ACX_BENCH_TINY=1 — the smoke mode that lets every TPU child
    run end-to-end on CPU BEFORE a healthy-tunnel window risks
    crashing on untested code (tiny numbers are meaningless and must
    never be banked: _bank refuses when the env is set)."""
    from mpi_acx_tpu.models import transformer as tfm
    if os.environ.get("ACX_BENCH_TINY") == "1":
        return tfm.tiny_config(vocab=128, d_model=32, n_heads=2,
                               n_layers=2, d_ff=64, max_seq=4096)
    return tfm.gpt2_small()


# A `*_speedup` row is a RATIO of two measured rows; it is only evidence
# when baseline and variant came from the same code. This maps each
# speedup row to its (baseline, variant) component rows so the artifact
# writer can refuse ratios whose parts were measured at different revs
# (or predate rev stamping — both sides silently defaulting to
# "unrecorded" used to count as a match).
_SPEEDUP_COMPONENTS = {
    "flash_speedup_s4096": ("dense_ms", "flash_ms"),
    "decode_int8w_speedup": ("decode_tokens_per_s",
                             "decode_int8w_tokens_per_s"),
    "decode_flash_speedup": ("decode_tokens_per_s",
                             "decode_flash_tokens_per_s"),
    "decode_longctx_int8kv_speedup": ("decode_longctx_tokens_per_s",
                                      "decode_longctx_int8kv_tokens_per_s"),
    "decode_longctx_flash_speedup": (
        "decode_longctx_dense_tokens_per_s",
        "decode_longctx_flash_tokens_per_s"),
    "decode_longctx_int8kv_flash_speedup": (
        "decode_longctx_int8kv_dense_tokens_per_s",
        "decode_longctx_int8kv_flash_tokens_per_s"),
    "spec_speedup": ("spec_plain_ms", "spec_ms"),
    "serve_speedup": ("serve_static_tokens_per_s",
                      "serve_cont_tokens_per_s"),
}


def _load_bank() -> dict:
    """BENCH_BANK.json as a dict; {} when absent or corrupt. The one
    read path for the bank (banking, reuse, outage fallback)."""
    try:
        with open(os.path.join(REPO, "BENCH_BANK.json")) as f:
            bank = json.load(f)
        return bank if isinstance(bank, dict) else {}
    except Exception:  # noqa: BLE001 — first run or corrupt file
        return {}


def _bank(rows: dict, group: str | None = None):
    """Merge measured rows into BENCH_BANK.json IMMEDIATELY (checked-in,
    append-only evidence: a 3-minute healthy tunnel window must survive a
    later crash/outage — round-4 verdict item #1)."""
    if os.environ.get("ACX_BENCH_TINY") == "1":
        return      # smoke geometry: numbers are meaningless
    path = os.path.join(REPO, "BENCH_BANK.json")
    bank = _load_bank()
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    rev = _code_rev()
    for k, v in rows.items():
        if k != "device":
            bank[k] = {"value": v, "ts": ts, "rev": rev,
                       "device": rows.get("device", "?")}
            if group is not None:
                bank[k]["group"] = group
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bank, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _bank_reuse(group: str):
    """Return {metric: value} for GROUP from BENCH_BANK.json if every
    row is TPU-measured within ACX_BANK_REUSE_H hours, else None.

    Off by default (driver runs measure fresh); the banker loop sets
    the env so a RETRY pass skips straight to the groups the last
    window didn't reach instead of re-burning healthy-tunnel minutes
    on already-banked ones (r05: window died between decode and
    train)."""
    if os.environ.get("ACX_BENCH_TINY") == "1":
        return None   # the smoke exists to RUN the children, not skip
    hours = float(os.environ.get("ACX_BANK_REUSE_H", "0") or 0)
    if hours <= 0:
        return None
    bank = _load_bank()
    rows = {k: v for k, v in bank.items()
            if isinstance(v, dict) and v.get("group") == group}
    if not rows:
        return None
    import calendar
    cutoff = time.time() - hours * 3600
    rev = _code_rev()
    for v in rows.values():
        if v.get("device") != "tpu":
            return None
        # Only rows measured on EXACTLY this code may stand in for a
        # fresh measurement ("unknown" never matches itself safely).
        if rev == "unknown" or v.get("rev") != rev:
            return None
        try:
            # Bank timestamps are UTC ("...Z"); timegm parses as UTC.
            t = calendar.timegm(time.strptime(v.get("ts", ""),
                                              "%Y-%m-%dT%H:%M:%SZ"))
        except ValueError:
            return None     # malformed row: fall through to measuring
        if t < cutoff:
            return None
    return {k: v["value"] for k, v in rows.items()}


def _run_tpu_child(mode: str, attempts: int = 3, timeout: int = 420,
                   child_flag: str = "tpu-child", env: dict | None = None):
    """Run `bench.py --<child_flag>-<mode>` in a fresh process, retrying
    on failure/hang. Returns (parsed dict | None, last_error | None)."""
    if attempts < 1:
        return None, "skipped (previous TPU child exhausted its retries)"
    last = None
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 f"--{child_flag}-{mode}"],
                env=env, capture_output=True, text=True, timeout=timeout)
            for line in r.stdout.splitlines():
                if line.startswith("{"):
                    return json.loads(line), None
            last = (f"rc={r.returncode} no JSON in output; "
                    f"stderr tail: {r.stderr[-300:]}")
        except subprocess.TimeoutExpired:
            last = f"timeout after {timeout}s (attempt {i + 1})"
        except Exception as e:  # noqa: BLE001 — report, don't crash bench
            last = f"{type(e).__name__}: {e}"
        if i + 1 < attempts:
            time.sleep(10 * (i + 1))   # tunnel hiccups are transient
    return None, last


def tpu_child_fwd():
    """Child process: flagship GPT-2 125M forward throughput (tokens/s).

    The repetition loop runs ON DEVICE (lax.scan of REPS forwards with an
    iteration-dependent input so XLA can't hoist the body) and the result
    is fetched as a scalar. Host-side loops measure the host<->device
    round-trip (tens of ms through the axon tunnel), not the TPU — this
    methodology reports device throughput, which is what a deployment
    without the tunnel gets."""
    import jax
    import jax.numpy as jnp
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(REPO, "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, (params, tokens) = mod.entry()
    # The flagship entry has no tiny variant; the CPU smoke just cuts
    # the rep count so the 125M forwards finish in seconds.
    reps = 3 if os.environ.get("ACX_BENCH_TINY") == "1" else 50
    vocab = int(tokens.max()) + 1

    def measure(tokens, reps_n):
        @jax.jit
        def loop_n(params, tokens):
            def body(carry, i):
                acc, t = carry
                ti = (t + i) % vocab
                return (acc + fn(params, ti).sum(), t), None
            (acc, _), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), tokens),
                jnp.arange(reps_n))
            return acc

        float(loop_n(params, tokens))              # compile + warm
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            float(loop_n(params, tokens))          # device_get = sync
            best = min(best, (time.perf_counter() - t0) / reps_n)
        return tokens.size / best

    toks = measure(tokens, reps)
    # Forward-pass MFU: ~2 FLOPs per parameter per token on the matmuls.
    mfu = toks * 2 * GPT2_SMALL_PARAMS / V5E_BF16_PEAK_FLOPS
    # Saturating shape (B=16, S=512): the entry() row (B=2, S=256) is a
    # latency shape; this one shows the chip's throughput ceiling.
    big = jax.random.randint(jax.random.key(2), (16, 512), 0, vocab)
    toks_big = measure(big, 10)
    print(json.dumps({
        "gpt2_fwd_tokens_per_s": round(toks, 1),
        "gpt2_fwd_mfu": round(mfu, 4),
        "gpt2_fwd_b16s512_tokens_per_s": round(toks_big, 1),
        "gpt2_fwd_b16s512_mfu": round(
            toks_big * 2 * GPT2_SMALL_PARAMS / V5E_BF16_PEAK_FLOPS, 4),
        "device": str(jax.devices()[0].platform),
    }))


def tpu_child_probe():
    """Child process: cheap tunnel-health probe. Gates the expensive
    children — when the tunnel is down this fails in ONE short timeout
    instead of burning 3x420 s per metric group (rounds 2-4 lost whole
    windows to exactly that)."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128), jnp.bfloat16)
    y = float(jax.jit(lambda a: (a @ a).sum())(x))   # real compile+run
    print(json.dumps({"tpu_probe_ok": y > 0,
                      "device": str(jax.devices()[0].platform)}))


def _timeit(f, *a, reps=1):
    """Best-of-3 wall time of one f(*a) call (fully synced)."""
    import jax
    jax.block_until_ready(f(*a))               # compile + warm
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(*a)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def tpu_child_flash():
    """Child process: flash-attention speedup vs dense at S=4096 (GPT-2
    head geometry), device-side rep loops."""
    import jax
    import jax.numpy as jnp
    from mpi_acx_tpu.ops.attention import attention_reference, flash_attention

    def timeit_device(fn, q, k, v, reps=20):
        """Device-side rep loop (lax.scan with an iteration-dependent
        input so XLA can't hoist the body): host-side per-call timing
        through the axon tunnel reports dispatch latency, not kernel
        time — sub-ms kernels need the loop ON the device."""
        @jax.jit
        def loop(q, k, v):
            def body(acc, i):
                qq = q + (i % 2).astype(q.dtype) * 1e-3
                return acc + fn(qq, k, v).astype(jnp.float32).sum(), None
            acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                  jnp.arange(reps))
            return acc
        float(loop(q, k, v))                       # compile + warm
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            float(loop(q, k, v))                   # scalar fetch = sync
            best = min(best, (time.perf_counter() - t0) / reps)
        return best

    B, S, H, D = 1, 4096, 12, 64
    if os.environ.get("ACX_BENCH_TINY") == "1":
        S, H = 512, 2                  # CPU smoke shape (_bench_cfg)
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in ks)
    t_dense = timeit_device(attention_reference, q, k, v)
    t_flash = timeit_device(flash_attention, q, k, v)
    print(json.dumps({
        "flash_speedup_s4096": round(t_dense / t_flash, 2),
        "flash_ms": round(t_flash * 1e3, 3),
        "dense_ms": round(t_dense * 1e3, 3),
        "device": str(jax.devices()[0].platform),
    }))


def tpu_child_decode():
    """Child process: KV-cache greedy decode tok/s (B=8, bf16 125M) plus
    the HBM roofline bounding it. Decode is bandwidth-bound (see
    parallel/tp_inference.py:3-8): every step re-streams the full weight
    set (amortized over the batch) plus each row's padded KV cache, so
    the per-step floor is bytes_moved / HBM_BW and roofline tok/s =
    B / floor (round-4 verdict item #7)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from mpi_acx_tpu.models import transformer as tfm

    cfg = _bench_cfg()
    params = tfm.cast_params(tfm.init_params(jax.random.key(0), cfg),
                             jnp.bfloat16)
    B, S_p, n_new, max_len = 8, 32, 64, 256
    lc_max, lc_new = 2048, 32
    if os.environ.get("ACX_BENCH_TINY") == "1":
        # The flash A/B doubles the longctx compiles and the forced-flash
        # rows run the kernel INTERPRETED on CPU — shrink the smoke so
        # make decode-check stays seconds-scale.
        n_new, lc_max, lc_new = 8, 512, 4
    prompt = jax.random.randint(jax.random.key(1), (B, S_p), 0, cfg.vocab)
    gen = jax.jit(lambda p, t: tfm.generate(p, cfg, t, n_new,
                                            max_len=max_len))
    decode_toks = B * n_new / _timeit(gen, params, prompt)

    # Dense-vs-flash A/B at the short operating point. The auto policy
    # picks dense at max_len=256 (below the block-skip crossover), so
    # decode_tokens_per_s above IS the dense baseline; this row forces
    # the ops/flash_decode.py kernel on the identical workload.
    fcfg = dataclasses.replace(cfg, decode_flash=True)
    fgen = jax.jit(lambda p, t: tfm.generate(p, fcfg, t, n_new,
                                             max_len=max_len))
    decode_toks_f = B * n_new / _timeit(fgen, params, prompt)

    # Roofline: v5e HBM ~819 GB/s (public spec). Static shapes mean the
    # kernels stream the PADDED (max_len) cache each step.
    HBM_BW = 819e9
    from mpi_acx_tpu.ops.wquant import (GPT2_WEIGHTS,
                                        quantize_weights_int8,
                                        weight_bytes)
    wbytes = weight_bytes(params)
    kvbytes = 2 * cfg.n_layers * max_len * cfg.d_model * 2 * B
    roofline = B * HBM_BW / (wbytes + kvbytes)

    # The roofline optimization attempt (round-4 verdict item #7):
    # int8 weight-only quantization halves the dominant per-step
    # stream (weights ~40x the KV bytes at this shape), so its
    # roofline is ~2x — the row records how much of that the kernel
    # actually realizes on chip.
    qparams = quantize_weights_int8(params, GPT2_WEIGHTS)
    decode_toks_q = B * n_new / _timeit(gen, qparams, prompt)
    qbytes = weight_bytes(qparams)
    roofline_q = B * HBM_BW / (qbytes + kvbytes)

    # Long-context operating point (max_len=2048): the KV stream is
    # now ~2.4x the int8 weight stream — the regime ops/kvquant.py
    # targets. A/B bf16 vs int8 cache AND dense vs flash on the same
    # workload: the dcfg/fcfg pair forces the decode backend either way
    # (cfg's None would auto-pick flash here, max_len >= 1024).
    dcfg = dataclasses.replace(cfg, decode_flash=False)
    lprompt = jax.random.randint(jax.random.key(3), (B, 32), 0,
                                 cfg.vocab)

    def ltoks(c, int8):
        lgen = jax.jit(lambda p, t: tfm.generate(p, c, t, lc_new,
                                                 max_len=lc_max,
                                                 kv_int8=int8))
        return B * lc_new / _timeit(lgen, qparams, lprompt)

    lc_toks, lc_toks8 = ltoks(cfg, False), ltoks(cfg, True)
    lc_dense, lc_dense8 = ltoks(dcfg, False), ltoks(dcfg, True)
    lc_flash, lc_flash8 = ltoks(fcfg, False), ltoks(fcfg, True)
    lc_kv = 2 * cfg.n_layers * lc_max * cfg.d_model * 2 * B
    lc_kv8 = lc_kv // 2 + lc_kv // (2 * cfg.head_dim) * 4  # codes+scales
    # Length-aware roofline: the flash kernel reads O(live length), not
    # O(max_len) — over this run the mean live length is S_p + lc_new/2
    # cache rows, so the bandwidth floor shrinks by live/max. The dense
    # rooflines above keep charging the full padded cache.
    live_frac = (32 + lc_new / 2) / lc_max
    lc_kv_live = lc_kv * live_frac
    lc_kv8_live = lc_kv8 * live_frac
    print(json.dumps({
        "decode_tokens_per_s": round(decode_toks, 1),
        "decode_flash_tokens_per_s": round(decode_toks_f, 1),
        "decode_flash_speedup": round(decode_toks_f / decode_toks, 2),
        "decode_roofline_tokens_per_s": round(roofline, 1),
        "decode_roofline_frac": round(decode_toks / roofline, 3),
        "decode_weight_mb": round(wbytes / 1e6, 1),
        "decode_kv_mb": round(kvbytes / 1e6, 1),
        "decode_int8w_tokens_per_s": round(decode_toks_q, 1),
        "decode_int8w_speedup": round(decode_toks_q / decode_toks, 2),
        "decode_int8w_roofline_frac": round(decode_toks_q / roofline_q,
                                            3),
        "decode_int8w_weight_mb": round(qbytes / 1e6, 1),
        "decode_longctx_tokens_per_s": round(lc_toks, 1),
        "decode_longctx_int8kv_tokens_per_s": round(lc_toks8, 1),
        "decode_longctx_int8kv_speedup": round(lc_toks8 / lc_toks, 2),
        "decode_longctx_dense_tokens_per_s": round(lc_dense, 1),
        "decode_longctx_flash_tokens_per_s": round(lc_flash, 1),
        "decode_longctx_flash_speedup": round(lc_flash / lc_dense, 2),
        "decode_longctx_int8kv_dense_tokens_per_s": round(lc_dense8, 1),
        "decode_longctx_int8kv_flash_tokens_per_s": round(lc_flash8, 1),
        "decode_longctx_int8kv_flash_speedup": round(
            lc_flash8 / lc_dense8, 2),
        "decode_longctx_kv_mb": round(lc_kv / 1e6, 1),
        "decode_longctx_int8kv_mb": round(lc_kv8 / 1e6, 1),
        "decode_longctx_roofline_tokens_per_s": round(
            B * HBM_BW / (qbytes + lc_kv), 1),
        "decode_longctx_int8kv_roofline_tokens_per_s": round(
            B * HBM_BW / (qbytes + lc_kv8), 1),
        "decode_longctx_live_roofline_tokens_per_s": round(
            B * HBM_BW / (qbytes + lc_kv_live), 1),
        "decode_longctx_int8kv_live_roofline_tokens_per_s": round(
            B * HBM_BW / (qbytes + lc_kv8_live), 1),
        "decode_longctx_live_roofline_frac": round(
            lc_flash / (B * HBM_BW / (qbytes + lc_kv_live)), 3),
        "device": str(jax.devices()[0].platform),
    }))


def _train_setup():
    """Shared geometry for the two train children (split r05: the
    combined child's 4 full train-step compiles blew past a 480 s
    tunnel timeout — train compiles 2, trainseg 3 with its own 900 s
    budget; trainseg re-times step_full on purpose so the fwd/bwd/opt
    segments come from the SAME run — the chip's ±40% day swing makes
    cross-child deltas meaningless)."""
    import jax
    import jax.numpy as jnp
    import optax
    from mpi_acx_tpu.models import transformer as tfm

    cfg = _bench_cfg()
    params_f32 = tfm.init_params(jax.random.key(0), cfg)
    opt = optax.adamw(1e-4)
    ostate = opt.init(params_f32)
    tok = jax.random.randint(jax.random.key(2), (8, 512), 0, cfg.vocab)
    tgt = jnp.roll(tok, -1, axis=-1)
    treps = 5

    def scan_loop(body):
        @jax.jit
        def loop(p, s, tok, tgt):
            (_, _), losses = jax.lax.scan(
                lambda c, _: body(c, tok, tgt), (p, s), None,
                length=treps)
            return losses[-1]
        return loop

    def step_full(carry, tok, tgt, chunk=None):
        p, s = carry
        loss, g = jax.value_and_grad(
            lambda p: tfm.loss_fn(p, cfg, tok, tgt, xent_chunk=chunk))(p)
        upd, s = opt.update(g, s, p)
        return (optax.apply_updates(p, upd), s), loss

    # Segment isolates: fwd-only and fwd+bwd steps whose carries stay
    # loss-dependent so the scan iterations remain sequential.
    def step_fwd(carry, tok, tgt):
        p, s = carry
        loss = tfm.loss_fn(p, cfg, tok, tgt)
        p = jax.tree.map(lambda x: x + (0 * loss).astype(x.dtype), p)
        return (p, s), loss

    def step_grad(carry, tok, tgt):
        p, s = carry
        loss, g = jax.value_and_grad(tfm.loss_fn)(p, cfg, tok, tgt)
        p = jax.tree.map(lambda a, b: a - 0.0 * b, p, g)
        return (p, s), loss

    from types import SimpleNamespace
    return SimpleNamespace(
        jax=jax, tok=tok, tgt=tgt, treps=treps, params=params_f32,
        ostate=ostate, scan_loop=scan_loop, step_full=step_full,
        step_fwd=step_fwd, step_grad=step_grad)


def tpu_child_train():
    """Child process: single-chip AdamW train step (B=8, S=512), plain vs
    chunked-vocab CE, plus train MFU at 6*N FLOPs per token (round-4
    verdict item #6). Rep loops are lax.scan ON DEVICE with
    params/opt-state as the carry so every iteration is a dependent
    update XLA can't elide; host per-call timing would fold the ~75 ms
    tunnel dispatch RTT in."""
    b = _train_setup()
    t_full = _timeit(b.scan_loop(b.step_full), b.params, b.ostate,
                     b.tok, b.tgt) / b.treps
    t_chunk = _timeit(b.scan_loop(
        lambda c, x, y: b.step_full(c, x, y, chunk=8192)),
        b.params, b.ostate, b.tok, b.tgt) / b.treps

    toks = b.tok.size / t_full
    # Train MFU: ~6 FLOPs per param per token (fwd 2 + bwd 4).
    mfu = toks * 6 * GPT2_SMALL_PARAMS / V5E_BF16_PEAK_FLOPS
    print(json.dumps({
        "train_step_tokens_per_s": round(toks, 1),
        "train_step_xentchunk_tokens_per_s": round(b.tok.size / t_chunk, 1),
        "train_step_mfu": round(mfu, 4),
        "train_seg_total_ms": round(t_full * 1e3, 2),
        "device": str(b.jax.devices()[0].platform),
    }))


def tpu_child_trainseg():
    """Child process: the fwd-only / fwd+bwd segment isolates that
    attribute the train step's time across fwd / bwd / optimizer
    (verdict item #6). Split from tpu_child_train so neither child
    exceeds ~2 tunnel compiles per run."""
    b = _train_setup()
    t_full = _timeit(b.scan_loop(b.step_full), b.params, b.ostate,
                     b.tok, b.tgt) / b.treps
    t_fwd = _timeit(b.scan_loop(b.step_fwd), b.params, b.ostate,
                    b.tok, b.tgt) / b.treps
    t_grad = _timeit(b.scan_loop(b.step_grad), b.params, b.ostate,
                     b.tok, b.tgt) / b.treps
    print(json.dumps({
        "train_seg_fwd_ms": round(t_fwd * 1e3, 2),
        "train_seg_bwd_ms": round((t_grad - t_fwd) * 1e3, 2),
        "train_seg_opt_ms": round((t_full - t_grad) * 1e3, 2),
        # Distinct key from the train child's train_seg_total_ms: the
        # two children bank under different groups and a shared key
        # would flip-flop its group tag (breaking _bank_reuse).
        "trainseg_total_ms": round(t_full * 1e3, 2),
        "device": str(b.jax.devices()[0].platform),
    }))


def _spec_setup():
    """Shared geometry for the two speculative children (split r05: one
    child was 5 tunnel compiles — two 40-step trainings, plain decode,
    and the speculative while_loop at B=1 AND B=8 — far past its 600 s
    timeout). Trained params are cached in build/ (gitignored scratch)
    so the second child skips the training compiles when it runs in the
    same window; a cold cache just retrains."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from mpi_acx_tpu.models import transformer as tfm

    n_new, k = 128, 4
    cfg = _bench_cfg()
    dcfg = dataclasses.replace(cfg, n_layers=2)
    tok = jax.random.randint(jax.random.key(1), (8, 64), 0, cfg.vocab)
    cache = os.path.join(REPO, "build", "spec_params.npy")

    def train(c, key, steps=40):
        p = tfm.init_params(key, c)
        opt = optax.adam(3e-3)
        st = opt.init(p)

        @jax.jit
        def step(p, st):
            loss, g = jax.value_and_grad(tfm.loss_fn)(p, c, tok, tok)
            up, st = opt.update(g, st)
            return optax.apply_updates(p, up), st, loss
        for _ in range(steps):
            p, st, _ = step(p, st)
        return tfm.cast_params(p, jnp.bfloat16)

    params = dparams = None
    rev = _code_rev()
    try:
        blob = np.load(cache, allow_pickle=True).item()
        # The cache is only a stand-in for training on the CURRENT
        # code — a rev/geometry mismatch is a cold cache, not an error
        # (same staleness rule as _bank_reuse).
        if blob.get("rev") == rev != "unknown" and blob.get("cfg") == cfg:
            to_dev = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
            params = to_dev(blob["params"])
            dparams = to_dev(blob["dparams"])
    except Exception:  # noqa: BLE001 — cold cache: train fresh
        pass
    if params is None:
        params = train(cfg, jax.random.key(0))
        dparams = train(dcfg, jax.random.key(5))
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        to_host = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
        # tmp + os.replace: the child runs under a hard timeout kill
        # and a truncated cache would cost the next child its warm
        # start (np.save appends .npy, hence the suffixed tmp name).
        tmp = cache + ".tmp.npy"
        np.save(tmp, {"params": to_host(params),
                      "dparams": to_host(dparams),
                      "rev": rev, "cfg": cfg},
                allow_pickle=True)
        os.replace(tmp, cache)
    from types import SimpleNamespace
    return SimpleNamespace(jax=jax, jnp=jnp, tfm=tfm, cfg=cfg, dcfg=dcfg,
                           tok=tok, n_new=n_new, k=k, params=params,
                           dparams=dparams)


def tpu_child_spec():
    """Child process: on-chip speculative-decoding wall-clock at B=1.
    Trains the GPT-2 125M target and a 2-layer draft on a repetition
    task (so the draft's proposals usually match), then times plain
    greedy decode vs the speculative loop at the same (B=1, n_new)
    workload. Informational row — never regression-gated (acceptance
    depends on the task)."""
    from mpi_acx_tpu.models.speculative import speculative_generate
    s = _spec_setup()
    jax, n_new, k = s.jax, s.n_new, s.k
    prompt = s.tok[:1, :32]

    gen = jax.jit(lambda p, t: s.tfm.generate(
        p, s.cfg, t, n_new, max_len=32 + n_new + k))
    jax.block_until_ready(gen(s.params, prompt))
    t0 = time.perf_counter()
    jax.block_until_ready(gen(s.params, prompt))
    t_plain = time.perf_counter() - t0

    out, stats = speculative_generate(s.dparams, s.dcfg, s.params, s.cfg,
                                      prompt, n_new, k=k)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out, stats = speculative_generate(s.dparams, s.dcfg, s.params, s.cfg,
                                      prompt, n_new, k=k)
    jax.block_until_ready(out)
    t_spec = time.perf_counter() - t0
    rounds = int(stats["rounds"])

    print(json.dumps({
        "spec_speedup": round(t_plain / t_spec, 2),
        "spec_plain_ms": round(t_plain * 1e3, 1),
        "spec_ms": round(t_spec * 1e3, 1),
        "spec_rounds": rounds,
        "spec_target_pass_reduction": round(n_new / rounds, 2),
        "spec_accepted": int(stats["drafted_accepted"]),
        "device": str(jax.devices()[0].platform),
    }))


def tpu_child_specb():
    """Child process: batched (B=8) speculation — the vmap-lifted loop,
    per-row rounds, wall-clock bounded by the slowest row. Separate
    from tpu_child_spec because the B=8 while_loop is its own heavy
    compile; reuses the cached trained params when warm."""
    from mpi_acx_tpu.models.speculative import speculative_generate
    s = _spec_setup()
    jax, jnp, n_new, k = s.jax, s.jnp, s.n_new, s.k
    B = 8
    prompts = jnp.tile(s.tok[:1, :32], (B, 1)).at[:, -1].set(
        jnp.arange(B) % s.cfg.vocab)
    outb, statsb = speculative_generate(s.dparams, s.dcfg, s.params,
                                        s.cfg, prompts, n_new, k=k)
    jax.block_until_ready(outb)
    t0 = time.perf_counter()
    outb, statsb = speculative_generate(s.dparams, s.dcfg, s.params,
                                        s.cfg, prompts, n_new, k=k)
    jax.block_until_ready(outb)
    t_spec_b = time.perf_counter() - t0
    rounds_b = [int(r) for r in statsb["rounds"]]

    print(json.dumps({
        "spec_batched_ms": round(t_spec_b * 1e3, 1),
        "spec_batched_tokens_per_s": round(B * n_new / t_spec_b, 1),
        "spec_batched_rounds_max": max(rounds_b),
        "spec_batched_target_pass_reduction": round(
            n_new / max(rounds_b), 2),
        "device": str(jax.devices()[0].platform),
    }))


def tpu_child_serve():
    """Child process: continuous batching (models/serving.py) vs static
    batches on a mixed-output-length workload — the scheduling win the
    serving tier exists for. 16 requests (prompt 32, n_new cycling
    16/96/32/128) through 8 slots with chunk=32, against the same
    requests run as two static B=8 generate() batches that each must
    decode to their LONGEST member. Throughput counts only REQUESTED
    tokens, so the static row pays for its padding honestly.
    Informational — never regression-gated (the ratio depends on the
    length mix)."""
    import jax
    import jax.numpy as jnp
    from mpi_acx_tpu.models import serving
    from mpi_acx_tpu.models import transformer as tfm

    cfg = _bench_cfg()
    params = tfm.cast_params(tfm.init_params(jax.random.key(0), cfg),
                             jnp.bfloat16)
    S, chunk, n_slots = 32, 32, 8
    lens = [16, 96, 32, 128] * 4                       # 16 requests
    max_len = S + max(lens) + chunk
    keys = jax.random.split(jax.random.key(1), len(lens))
    prompts = [jax.random.randint(k, (S,), 0, cfg.vocab) for k in keys]

    # Warm both compile caches outside the timed region — the serve
    # warmup must run through the SAME server_fns the timed call uses
    # (a bare serve_greedy call builds fresh jit closures every time).
    fns = serving.make_server_fns(params, cfg, tfm, chunk=chunk)
    serving.serve_greedy(params, cfg, prompts[:2], [chunk, chunk],
                         n_slots=n_slots, max_len=max_len, family=tfm,
                         chunk=chunk, server_fns=fns)
    gen = jax.jit(lambda p, t: tfm.generate(p, cfg, t, max(lens),
                                            max_len=max_len))
    batch = jnp.stack(prompts[:n_slots])
    jax.block_until_ready(gen(params, batch))

    t0 = time.perf_counter()
    serving.serve_greedy(params, cfg, prompts, lens, n_slots=n_slots,
                         max_len=max_len, family=tfm, chunk=chunk,
                         server_fns=fns)
    t_cont = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(0, len(prompts), n_slots):
        jax.block_until_ready(
            gen(params, jnp.stack(prompts[i:i + n_slots])))
    t_static = time.perf_counter() - t0

    requested = sum(lens)
    print(json.dumps({
        "serve_cont_tokens_per_s": round(requested / t_cont, 1),
        "serve_static_tokens_per_s": round(requested / t_static, 1),
        "serve_speedup": round(t_static / t_cont, 2),
        "serve_requests": len(lens),
        "device": str(jax.devices()[0].platform),
    }))


def cpu_child_quant():
    """Child process (forced CPU, 8 virtual devices): wire-byte ratio of
    the int8-quantized ring all-reduce vs an f32 ring with the identical
    schedule, counted from collective-permute payload types in the
    compiled HLO. Deterministic — no chip, no weather — so the driver's
    artifact carries a perf-design metric even when the TPU tunnel is
    down."""
    import re as _re
    import jax
    # This child is CPU by definition: pin unconditionally so a direct
    # `bench.py --cpu-child-quant` invocation cannot block in the pinned
    # accelerator plugin's init loop (the round-2 dryrun failure mode).
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from mpi_acx_tpu.parallel import mesh_from_devices
    from mpi_acx_tpu.parallel.quantized import ring_psum

    n, SZ = 8, 131072
    mesh = mesh_from_devices({"x": n}, jax.devices()[:n])

    def wire_bytes(fn):
        f = shard_map(fn, mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                      check_vma=False)
        txt = jax.jit(f).lower(
            jnp.zeros((n, SZ), jnp.float32)).compile().as_text()
        per = {"u8": 1, "s8": 1, "pred": 1, "bf16": 2, "f16": 2,
               "f32": 4, "s32": 4}
        total = 0
        for mm in _re.finditer(
                r"(u8|s8|pred|f32|s32|bf16|f16)\[([\d,]*)\]\S* "
                r"collective-permute", txt):
            cnt = 1
            for d in mm.group(2).split(","):
                if d:
                    cnt *= int(d)
            total += cnt * per[mm.group(1)]
        return total

    # Numerator and denominator share ONE ring skeleton
    # (quantized.ring_psum), so the comparison cannot silently drift.
    bq = wire_bytes(lambda v: ring_psum(v[0], "x", quantize=True)[None])
    be = wire_bytes(lambda v: ring_psum(v[0], "x", quantize=False)[None])
    print(json.dumps({
        "quant_allreduce_wire_bytes": bq,
        "exact_ring_wire_bytes": be,
        "quant_allreduce_traffic_reduction": round(be / max(bq, 1), 2),
    }))


def cpu_child_disagg():
    """Child process (forced CPU): loopback disagg serve (models/
    disagg.py) — the full wire handoff path in one process. Reports the
    TTFT handoff split (prefill vs ship vs pickup p50) for per-layer
    overlap and for the ship-after-full-prefill baseline, plus handoff
    wire throughput for the two prefill-side cache variants (int8
    quantize-at-compute vs bf16 quantize-at-wire — same wire bytes, the
    EQuARX rule, different pack cost). Deterministic in shape; no chip."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.models.disagg import serve_disagg_greedy
    from mpi_acx_tpu.models.serving import make_server_fns

    cfg = tfm.tiny_config()
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, cfg.vocab, size=n).astype(np.int32)
               for n in (5, 11, 17, 8)]
    n_new = [4, 3, 5, 4]
    fns = make_server_fns(params, cfg, tfm, chunk=1, kv_int8=True)

    def one(**kw):
        b = serve_disagg_greedy(params, cfg, prompts, n_new, n_slots=2,
                                max_len=64, server_fns=fns, **kw)
        m = b.metrics
        wire = sum(h.wire_bytes for h in m.handoffs)
        wall = sum(h.ship_s + h.pickup_s for h in m.handoffs) or 1e-9
        return m, wire / wall / 1e9

    m_ov, gbps_bf16 = one()                      # warm compile caches
    m_ov, gbps_bf16 = one()
    m_no, _ = one(overlap=False)
    m_i8, gbps_int8 = one(prefill_kv_int8=True)
    print(json.dumps({
        "disagg_requests": m_ov.requests,
        "disagg_handoff_prefill_p50_ms": round(
            m_ov.handoff_prefill_p50_s * 1e3, 3),
        "disagg_handoff_ship_p50_ms": round(
            m_ov.handoff_ship_p50_s * 1e3, 3),
        "disagg_handoff_pickup_p50_ms": round(
            m_ov.handoff_pickup_p50_s * 1e3, 3),
        "disagg_noverlap_ship_p50_ms": round(
            m_no.handoff_ship_p50_s * 1e3, 3),
        "disagg_handoff_gbps_bf16": round(gbps_bf16, 4),
        "disagg_handoff_gbps_int8": round(gbps_int8, 4),
        "device": str(jax.devices()[0].platform),
    }))


def cpu_child_paged():
    """Child process (forced CPU): the serving_sweep rows for the paged
    KV plane (models/kvpage.py, DESIGN.md §19). Three claims, each a
    row family:

    1. HBM KV bytes scale with LIVE tokens, not n_slots*max_len — the
       same workload served at max_len 64 and 128 holds its paged
       high-water bytes while the fixed-slot reservation doubles.
    2. A prefix-cache hit skips the shared prefix's prefill: hit-path
       TTFT (seat -> first token, timed through on_token on a 1-slot
       strictly-sequential server) beats the cold path's.
    3. Max concurrent requests under a FIXED HBM budget: pages buy
       admission for every request whose live need fits, not only
       budget/max_len slots — verified by actually serving that
       concurrency with zero preemptions.

    Shape-deterministic; wall-clock rows are informational (CPU)."""
    import time as _t

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from mpi_acx_tpu.models import kvpage, serving
    from mpi_acx_tpu.models import transformer as tfm

    cfg = tfm.tiny_config()
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(5)
    pt, n_slots, chunk = 8, 2, 1

    # -- claim 1: bytes per live token vs max_len ------------------------
    prompts = [rng.integers(0, cfg.vocab, size=n).astype(np.int32)
               for n in (5, 11, 17, 8)]
    n_new = [4, 3, 5, 4]
    rows = {}
    for ml in (64, 128):
        out = serving.serve_paged_greedy(
            params, cfg, prompts, n_new, n_slots=n_slots, max_len=ml,
            family=tfm, chunk=chunk, page_tokens=pt,
            return_paged_state=True)
        pkv = out.paged_state
        # Bytes of ONE page across every pool array ([L, P, pt, H, Dh]:
        # per-page = L * pt * H * Dh * itemsize, summed over k/v).
        page_bytes = sum(
            pkv.pool[k].shape[0] * pt
            * int(np.prod(pkv.pool[k].shape[3:]))
            * pkv.pool[k].dtype.itemsize for k in pkv.pool)
        # The fixed-slot server's bf16 k+v reservation at this max_len.
        fixed = (cfg.n_layers * 2 * n_slots * ml * cfg.n_heads
                 * cfg.head_dim * 2)
        rows[f"paged_kv_hwm_bytes_maxlen{ml}"] = \
            out.metrics.pages_hwm * page_bytes
        rows[f"fixed_kv_bytes_maxlen{ml}"] = fixed
    live = sum(len(p) + n for p, n in zip(prompts, n_new))
    rows["paged_kv_bytes_per_live_token"] = round(
        rows["paged_kv_hwm_bytes_maxlen64"] / live, 1)
    rows["fixed_kv_bytes_per_live_token_maxlen64"] = round(
        rows["fixed_kv_bytes_maxlen64"] / live, 1)
    # The scaling claim itself: fixed doubles with max_len, paged holds.
    rows["paged_hbm_maxlen_growth"] = round(
        rows["paged_kv_hwm_bytes_maxlen128"]
        / max(rows["paged_kv_hwm_bytes_maxlen64"], 1), 2)
    rows["fixed_hbm_maxlen_growth"] = round(
        rows["fixed_kv_bytes_maxlen128"]
        / rows["fixed_kv_bytes_maxlen64"], 2)

    # -- claim 2: prefix-hit vs cold TTFT (1 slot = sequential seats) ----
    system = rng.integers(0, cfg.vocab, 24).astype(np.int32)  # 3 pages
    shared = [np.concatenate([system,
                              rng.integers(0, cfg.vocab, 4 + i)
                              .astype(np.int32)]) for i in range(4)]

    def ttfts(prefix_cache):
        stamps = {}
        t0 = _t.perf_counter()

        def on_token(rid, tok):
            stamps.setdefault(rid, []).append(_t.perf_counter())

        out = serving.serve_paged_greedy(
            params, cfg, shared, 4, n_slots=1, max_len=40, family=tfm,
            page_tokens=pt, prefix_cache=prefix_cache, on_token=on_token)
        # Seat time for rid i on the 1-slot server is rid i-1's last
        # token (or serve start); TTFT = first token - seat.
        tt = []
        for rid in range(len(shared)):
            seat = t0 if rid == 0 else stamps[rid - 1][-1]
            tt.append(stamps[rid][0] - seat)
        return out, tt

    ttfts(False)                                  # warm compile caches
    ttfts(True)
    out_cold, tt_cold = ttfts(False)
    out_hit, tt_hit = ttfts(True)
    assert out_hit.metrics.prefix_hits >= 3, out_hit.metrics
    # p50 over the requests that CAN hit (rid >= 1).
    rows["paged_prefix_cold_ttft_p50_ms"] = round(
        sorted(tt_cold[1:])[len(tt_cold[1:]) // 2] * 1e3, 3)
    rows["paged_prefix_hit_ttft_p50_ms"] = round(
        sorted(tt_hit[1:])[len(tt_hit[1:]) // 2] * 1e3, 3)
    rows["paged_prefix_pages_reused"] = out_hit.metrics.prefix_pages_reused

    # -- claim 3: max concurrency at a fixed HBM budget ------------------
    # Budget: the fixed-slot server's 4-slot, max_len=64 reservation =
    # 32 pages of 8. Fixed admits 4 concurrent requests, period; paged
    # admits every request whose LIVE need fits the pool.
    budget_pages = 4 * (64 // pt)
    S, n = 8, 8
    need = kvpage.pages_needed(S + n + chunk, pt)
    max_conc = budget_pages // need
    many = [rng.integers(0, cfg.vocab, S).astype(np.int32)
            for _ in range(max_conc)]
    out = serving.serve_paged_greedy(
        params, cfg, many, n, n_slots=max_conc, max_len=64, family=tfm,
        chunk=chunk, page_tokens=pt, n_pages=budget_pages,
        return_paged_state=True)
    assert out.metrics.preemptions == 0, out.metrics
    assert all(not isinstance(o, serving.RequestRejected) for o in out)
    rows.update({
        "fixed_max_concurrent_at_budget": 4,
        "paged_max_concurrent_at_budget": max_conc,
        "paged_concurrency_gain": round(max_conc / 4, 2),
        "paged_budget_pages_hwm": out.metrics.pages_hwm,
        "device": str(jax.devices()[0].platform),
    })
    print(json.dumps(rows))


def _record_paged_rows(rows):
    """Fold the paged serving-sweep rows into the newest BENCH_r*.json
    (same merge-never-fail contract as _record_disagg_rows)."""
    import glob
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not files:
        return
    try:
        with open(files[-1]) as f:
            d = json.load(f)
        d["paged"] = rows
        with open(files[-1], "w") as f:
            json.dump(d, f)
            f.write("\n")
    except Exception:  # noqa: BLE001
        pass


def _run_cpu_child(mode: str, timeout: int = 300):
    """_run_tpu_child with a forced 8-virtual-device CPU backend (the
    pinned axon platform must never initialize here)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return _run_tpu_child(mode, attempts=1, timeout=timeout,
                          child_flag="cpu-child", env=env)


def main(full: bool = False):
    p50, p99, bw = native_bench()
    out = {
        "metric": "enqueued_pingpong_p50_latency",
        "value": p50,
        "unit": "us",
        # Latency: lower is better -> ratio >= 1 means at/above baseline.
        "vs_baseline": round(BASELINE_P50_US / p50, 3),
        "pingpong_p99_us": p99,
        "partitioned_bw_gbps": bw,
        "partitioned_bw_vs_baseline": round(bw / BASELINE_PART_BW_GBPS, 3),
    }
    # Provisional line FIRST: if a driver timeout kills us mid-TPU-retry,
    # the native metrics still reach the artifact (the driver parses the
    # last JSON line, so a completed run supersedes this one).
    provisional = dict(out)
    provisional["tpu_error"] = "provisional line: TPU measurement pending"
    print(json.dumps(provisional), flush=True)

    # Striped-wire lane sweep (socket plane). The stripes=1 no-regression
    # gate is the partitioned_bw_gbps check above: striping is off by
    # default, so native_bench IS the unstriped measurement.
    try:
        srows = native_stripe_sweep()
        out["stripe_sweep"] = srows
        _record_wire_rows(srows, bw)
    except Exception as e:  # noqa: BLE001 — report, don't crash
        out["stripe_sweep_error"] = str(e)

    # Disagg serving rows: loopback TTFT handoff split + wire GB/s for
    # the two prefill-side cache variants (CPU child), then the 3-rank
    # role-split fleet's overlap-vs-ship-after-prefill TTFT A/B — the
    # per-layer-Pready win only visible with the roles on separate
    # processes. Folded into the MULTICHIP artifact like the wire rows.
    db, derr = _run_cpu_child("disagg")
    if db is not None:
        out.update(db)
    else:
        out["disagg_error"] = derr
    try:
        drows = disagg_fleet_rows()
        out.update(drows)
        _record_disagg_rows({**(db or {}), **drows})
    except Exception as e:  # noqa: BLE001 — report, don't crash
        out["disagg_fleet_error"] = str(e)

    # Request-journey phase budget (DESIGN.md §20): where a request's
    # wall time goes — queue/prefill/ship/decode p50/p99 from the
    # journaled 3-rank fleet — so a regression in ONE leg is visible
    # even when the aggregate TTFT still passes.
    try:
        jrows = journey_phase_rows()
        out.update(jrows)
        _record_journey_rows(jrows)
    except Exception as e:  # noqa: BLE001 — report, don't crash
        out["journey_error"] = str(e)

    # Paged-KV serving sweep (CPU child): HBM-per-live-token scaling,
    # prefix-hit TTFT split, fixed-budget concurrency (DESIGN.md §19).
    pb, perr2 = _run_cpu_child("paged")
    if pb is not None:
        out.update(pb)
        _record_paged_rows(pb)
    else:
        out["paged_error"] = perr2

    # Deterministic, chip-independent design metric (CPU-compiled HLO).
    qb, qerr = _run_cpu_child("quant")
    if qb is not None:
        out.update(qb)
    else:
        out["quant_bytes_error"] = qerr

    # --- TPU capture: probe-first, per-row children, bank-as-you-go ---
    # A dead tunnel costs ONE ~150 s probe timeout (x2 attempts), not
    # 3x420 s per group; each group's rows land in BENCH_BANK.json (and,
    # in --full mode, a rewritten BENCH_FULL.json) the moment its child
    # exits, so a mid-run kill preserves everything measured so far.
    probe, perr = _run_tpu_child("probe", attempts=2, timeout=150)
    errs = {}
    results = {}
    tunnel_dead = probe is None

    def run_group(name, timeout, attempts=2):
        nonlocal tunnel_dead
        banked = _bank_reuse(name)
        if banked is not None:
            results[name] = banked
            out.update(banked)
            out[f"{name}_from_bank"] = True   # per-group provenance
            return banked
        if tunnel_dead:
            errs[name] = (f"probe failed: {perr}" if probe is None
                          else "tunnel died mid-run (re-probe failed)")
            return None
        r, e = _run_tpu_child(name, attempts=attempts, timeout=timeout)
        if r is not None:
            results[name] = r
            out.update(r)
            _bank(r, group=name)
        else:
            errs[name] = e
            # A group that exhausted its retries usually means the
            # tunnel dropped mid-run. Re-probe CHEAPLY; if dead, later
            # groups fail fast instead of burning attempts x timeout
            # each (~1.5 h of guaranteed timeouts otherwise).
            rp, _ = _run_tpu_child("probe", attempts=1, timeout=150)
            tunnel_dead = rp is None
        return r

    fwd = run_group("fwd", timeout=420, attempts=3)
    if fwd is not None and "gpt2_fwd_tokens_per_s" in fwd:
        out["gpt2_fwd_vs_baseline"] = round(
            fwd["gpt2_fwd_tokens_per_s"] / BASELINE_GPT2_FWD_TOKS, 3)
    if probe is None:
        out["tpu_error"] = f"probe failed: {perr}"  # LOUD, never dropped
    elif fwd is None:
        out["tpu_error"] = errs["fwd"]
    def attach_banked_rows():
        """Outage fallback: attach the committed BENCH_BANK.json rows,
        clearly labeled with when and on what code they were measured.
        Rounds 2-4 each ended with a tpu_error-only artifact while
        chip-measured evidence existed in the repo — the artifact
        should carry it rather than pretend none exists. Called on ANY
        recorded outage (probe-dead OR mid---full tunnel death).

        `*_speedup` rows are ratios and only attach when the speedup
        AND both its component rows (_SPEEDUP_COMPONENTS) carry the
        SAME recorded rev — a baseline and variant measured on
        different code (or before rev stamping, when both sides
        defaulted to "unrecorded") is refused and listed loudly under
        banked_speedups_dropped instead."""
        bank = {k: v for k, v in _load_bank().items()
                if isinstance(v, dict) and v.get("device") == "tpu"}

        def rev_of(key):
            r = bank.get(key, {}).get("rev")
            return r if r not in (None, "unrecorded", "unknown") else None

        rows, dropped = {}, {}
        for k, v in bank.items():
            if "_speedup" in k:
                parts = _SPEEDUP_COMPONENTS.get(k)
                if parts is None:
                    dropped[k] = "no component mapping for this ratio"
                    continue
                revs = {rev_of(k)} | {rev_of(p) for p in parts}
                if None in revs:
                    dropped[k] = "ratio or component rev unrecorded"
                    continue
                if len(revs) != 1:
                    dropped[k] = ("baseline and variant measured at "
                                  "different revs")
                    continue
            rows[k] = {"value": v.get("value"), "ts": v.get("ts"),
                       "rev": v.get("rev", "unrecorded")}
        if rows:
            out["banked_tpu_rows"] = rows
        if dropped:
            out["banked_speedups_dropped"] = dropped

    if "tpu_error" in out:
        attach_banked_rows()

    checks = []

    def write_full(partial: bool):
        """(Re)compute the gate over whatever has landed and write
        BENCH_FULL.json NOW — called after every child in --full mode.
        An UNMEASURED row is recorded as skipped — loudly, with the
        outage reason — NOT as a regression: a red gate must mean the
        code got slower, never that the tunnel was down. The skip
        requires a recorded child failure for THAT row's source; a
        metric missing from a successful child (key drift), or a
        chip-INDEPENDENT child failing, still fails the gate."""
        if "tpu_error" in out or errs:
            # Keep the artifact self-contained on ANY outage shape:
            # the banked evidence must be in BENCH_FULL.json itself,
            # not only the stdout line (review r05).
            attach_banked_rows()
        checks.clear()

        def gate(name, value, baseline, higher_is_better=True,
                 unmeasured_reason=None):
            if value is None:
                if unmeasured_reason is not None:
                    checks.append({
                        "metric": name, "ok": None, "skipped": True,
                        "reason": f"not measured ({unmeasured_reason})"})
                else:
                    checks.append({
                        "metric": name, "ok": False,
                        "reason": "metric missing from a successful "
                                  "child (key drift?)"})
                return
            if higher_is_better:
                ok = value >= baseline * 0.9
            else:                  # latency: at most 10% above baseline
                ok = value <= baseline * 1.1
            checks.append({"metric": name, "value": value,
                           "baseline": baseline,
                           "ratio": round(value / baseline, 3), "ok": ok})

        def why(name):
            if name in errs:
                return f"TPU outage: {errs[name]}"
            if name not in results:
                return "child not yet run (partial write)" if partial \
                    else f"child not run: {errs.get(name, 'unknown')}"
            return None

        g = lambda n: results.get(n, {})  # noqa: E731
        gate("pingpong_p50_us", p50, BASELINE_P50_US,
             higher_is_better=False)
        gate("partitioned_bw_gbps", bw, BASELINE_PART_BW_GBPS)
        gate("gpt2_fwd_tokens_per_s",
             g("fwd").get("gpt2_fwd_tokens_per_s"),
             BASELINE_GPT2_FWD_TOKS, unmeasured_reason=why("fwd"))
        gate("gpt2_fwd_b16s512_tokens_per_s",
             g("fwd").get("gpt2_fwd_b16s512_tokens_per_s"),
             BASELINE_GPT2_FWD_B16S512_TOKS, unmeasured_reason=why("fwd"))
        gate("flash_speedup_s4096",
             g("flash").get("flash_speedup_s4096"),
             BASELINE_FLASH_SPEEDUP_4096, unmeasured_reason=why("flash"))
        gate("decode_tokens_per_s",
             g("decode").get("decode_tokens_per_s"), BASELINE_DECODE_TOKS,
             unmeasured_reason=why("decode"))
        gate("train_step_tokens_per_s",
             g("train").get("train_step_tokens_per_s"),
             BASELINE_TRAIN_TOKS, unmeasured_reason=why("train"))
        # Chip-independent row: a failure here is NEVER an outage skip.
        gate("quant_allreduce_traffic_reduction",
             (qb or {}).get("quant_allreduce_traffic_reduction"),
             BASELINE_QUANT_TRAFFIC_REDUCTION)
        out["regressions"] = [c["metric"] for c in checks
                              if c["ok"] is False]
        out["unmeasured"] = [c["metric"] for c in checks
                             if c.get("skipped")]
        doc = {"checks": checks, "result": out}
        if partial:
            doc["partial"] = True
        # Tiny smoke numbers must never overwrite the checked-in
        # artifact (same rule as _bank): they land in /tmp instead.
        dest = ("/tmp/BENCH_FULL.smoke.json"
                if os.environ.get("ACX_BENCH_TINY") == "1"
                else os.path.join(REPO, "BENCH_FULL.json"))
        tmp = dest + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, dest)

    if full:
        write_full(partial=True)
        # TPU groups FIRST and back-to-back: healthy-tunnel minutes are
        # the scarce resource — no host-only work may sit between them.
        # decode got 600 s when the flash A/B tripled its compile count
        # (short flash + forced dense/flash x bf16/int8 longctx).
        for name, timeout in (("flash", 420), ("decode", 600),
                              ("train", 600), ("trainseg", 900)):
            run_group(name, timeout=timeout)
            if name in errs:
                out[f"tpu_{name}_error"] = errs[name]
            write_full(partial=True)
        # Speculative decode wall-clock: informational, isolated in its
        # own children so a failure cannot cost the gated rows above
        # (spec = B=1 + the trainings; specb = the batched while_loop,
        # reusing spec's cached trained params when warm).
        for name in ("spec", "specb", "serve"):
            run_group(name, timeout=900)
            if name in errs:     # same convention as the gated groups
                out[f"tpu_{name}_error"] = errs[name]
            write_full(partial=True)
        # Host-plane message-size sweep (p50/p99 per size) — native, no
        # chip needed (round-4 verdict item #8); runs after the chip
        # work on purpose.
        sweep = []
        for msg in (1, 1024, 65536, 1048576):
            try:
                sp50, sp99, _ = native_bench(msg_bytes=msg)
                sweep.append({"msg_bytes": msg, "p50_us": sp50,
                              "p99_us": sp99})
            except Exception as e:  # noqa: BLE001 — report, don't crash
                sweep.append({"msg_bytes": msg, "error": str(e)})
        out["pingpong_sweep"] = sweep
        write_full(partial=False)

    print(json.dumps(out))
    if (full and any(c["ok"] is False for c in checks)
            and os.environ.get("ACX_BENCH_TINY") != "1"):
        # Tiny smoke: toy numbers red-flag every gate by construction;
        # the smoke's pass/fail signal is "did every child run".
        sys.exit(1)


def dryrun_decode():
    """`make decode-check` hook: run the decode child in-process on the
    tiny CPU geometry and assert the dense-vs-flash A/B rows actually
    land — the flash rows exercise the ops/flash_decode.py kernel in
    interpret mode, so this catches kernel breakage AND row-name drift
    before a healthy-tunnel window burns minutes on it."""
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        tpu_child_decode()
    rows = json.loads(buf.getvalue().strip().splitlines()[-1])
    need = ["decode_flash_tokens_per_s", "decode_flash_speedup",
            "decode_longctx_dense_tokens_per_s",
            "decode_longctx_flash_tokens_per_s",
            "decode_longctx_flash_speedup",
            "decode_longctx_int8kv_dense_tokens_per_s",
            "decode_longctx_int8kv_flash_tokens_per_s",
            "decode_longctx_int8kv_flash_speedup",
            "decode_longctx_live_roofline_tokens_per_s"]
    missing = [k for k in need if k not in rows]
    assert not missing, f"decode dryrun: rows missing {missing}"
    assert all(rows[k] > 0 for k in need), rows
    print(json.dumps({"dryrun_decode_ok": True,
                      "rows": {k: rows[k] for k in need}}))


def dryrun_disagg():
    """`make disagg-check` hook: run the disagg loopback child
    in-process on the tiny CPU geometry and assert the TTFT-split and
    wire-throughput rows actually land — catches wire-path breakage and
    row-name drift before a bench window burns minutes on it. The fleet
    A/B runs in the same make target as its own acxrun legs, so this
    dryrun stays single-process."""
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cpu_child_disagg()
    rows = json.loads(buf.getvalue().strip().splitlines()[-1])
    need = ["disagg_handoff_prefill_p50_ms", "disagg_handoff_ship_p50_ms",
            "disagg_handoff_pickup_p50_ms", "disagg_noverlap_ship_p50_ms",
            "disagg_handoff_gbps_bf16", "disagg_handoff_gbps_int8"]
    missing = [k for k in need if k not in rows]
    assert not missing, f"disagg dryrun: rows missing {missing}"
    assert all(rows[k] > 0 for k in need), rows
    print(json.dumps({"dryrun_disagg_ok": True,
                      "rows": {k: rows[k] for k in need}}))


def dryrun_paged():
    """`make paged-check` hook: run the paged serving child in-process
    on the tiny CPU geometry and assert the three §19 row families
    actually land — the HBM-scaling rows, the prefix-hit TTFT split,
    and the fixed-budget concurrency rows — catching scheduler
    breakage and row-name drift before a bench window burns minutes on
    it. The 3-rank paged fleet runs in the same make target as its own
    acxrun legs, so this dryrun stays single-process."""
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cpu_child_paged()
    rows = json.loads(buf.getvalue().strip().splitlines()[-1])
    need = ["paged_kv_hwm_bytes_maxlen64", "paged_kv_hwm_bytes_maxlen128",
            "fixed_kv_bytes_maxlen64", "fixed_kv_bytes_maxlen128",
            "paged_kv_bytes_per_live_token", "paged_hbm_maxlen_growth",
            "fixed_hbm_maxlen_growth", "paged_prefix_cold_ttft_p50_ms",
            "paged_prefix_hit_ttft_p50_ms", "paged_prefix_pages_reused",
            "paged_max_concurrent_at_budget", "paged_concurrency_gain"]
    missing = [k for k in need if k not in rows]
    assert missing == [], f"paged dryrun: rows missing {missing}"
    # The acceptance shape: the fixed reservation doubles with max_len,
    # the paged high-water does not move (live tokens are unchanged);
    # pages buy strictly more concurrency than slots at equal HBM.
    assert rows["fixed_hbm_maxlen_growth"] == 2.0, rows
    assert rows["paged_hbm_maxlen_growth"] == 1.0, rows
    assert rows["paged_concurrency_gain"] > 1, rows
    assert rows["paged_prefix_pages_reused"] >= 9, rows  # 3 hits * 3 pages
    _record_paged_rows(rows)
    print(json.dumps({"dryrun_paged_ok": True,
                      "rows": {k: rows[k] for k in need}}))


if __name__ == "__main__":
    if ("--dryrun-decode" in sys.argv or "--dryrun-disagg" in sys.argv
            or "--dryrun-paged" in sys.argv):
        # The dryrun is a correctness smoke, never a measurement: force
        # the tiny CPU geometry no matter how it was invoked.
        os.environ["ACX_BENCH_TINY"] = "1"
    if os.environ.get("ACX_BENCH_TINY") == "1":
        # Smoke mode runs on CPU by definition; the env var alone is
        # not enough (the axon sitecustomize overrides jax_platforms
        # via jax.config, and a dead tunnel then HANGS the child —
        # the r05 lesson), so pin through the config, which wins.
        import jax
        jax.config.update("jax_platforms", "cpu")
    if "--cpu-child-quant" in sys.argv:
        cpu_child_quant()
    elif "--cpu-child-disagg" in sys.argv:
        cpu_child_disagg()
    elif "--cpu-child-paged" in sys.argv:
        cpu_child_paged()
    elif "--dryrun-disagg" in sys.argv:
        dryrun_disagg()
    elif "--dryrun-paged" in sys.argv:
        dryrun_paged()
    elif "--tpu-child-probe" in sys.argv:
        tpu_child_probe()
    elif "--tpu-child-fwd" in sys.argv:
        tpu_child_fwd()
    elif "--tpu-child-flash" in sys.argv:
        tpu_child_flash()
    elif "--dryrun-decode" in sys.argv:
        dryrun_decode()
    elif "--tpu-child-decode" in sys.argv:
        tpu_child_decode()
    elif "--tpu-child-trainseg" in sys.argv:
        tpu_child_trainseg()
    elif "--tpu-child-train" in sys.argv:
        tpu_child_train()
    elif "--tpu-child-serve" in sys.argv:
        tpu_child_serve()
    elif "--tpu-child-specb" in sys.argv:
        tpu_child_specb()
    elif "--tpu-child-spec" in sys.argv:
        tpu_child_spec()
    else:
        main(full="--full" in sys.argv)
