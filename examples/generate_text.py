"""End-to-end inference example: KV-cache decode with greedy or sampled
continuation, on either model family.

  python examples/generate_text.py --family llama --temperature 0.8 \
      --top-k 40 --top-p 0.95
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "llama"], default="gpt2")
    ap.add_argument("--n-new", type=int, default=24)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy argmax")
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--top-p", type=float, default=None)
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")  # wins over a pinned plugin
    import jax.numpy as jnp

    from mpi_acx_tpu.models import llama as lm
    from mpi_acx_tpu.models import transformer as tfm

    if args.family == "llama":
        cfg = lm.tiny_llama(n_layers=2)
        params = lm.init_params(jax.random.key(0), cfg)
        gen, gen_s = lm.generate, lm.generate_sample
    else:
        cfg = tfm.tiny_config(n_layers=2)
        params = tfm.init_params(jax.random.key(0), cfg)
        gen, gen_s = tfm.generate, tfm.generate_sample

    prompt = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    if args.temperature == 0.0 and args.top_k is None and args.top_p is None:
        out = gen(params, cfg, prompt, n_new=args.n_new)
    else:
        out = gen_s(params, cfg, prompt, n_new=args.n_new,
                    key=jax.random.key(42), temperature=args.temperature,
                    top_k=args.top_k, top_p=args.top_p)
    print(f"{args.family} prompt: ", prompt[0].tolist())
    print(f"{args.family} output: ", out[0, prompt.shape[1]:].tolist())
    print("example OK")


if __name__ == "__main__":
    main()
