"""End-to-end inference example: KV-cache decode with greedy or sampled
continuation, on either model family — optionally speculative (a small
draft proposes, the target verifies k tokens per window pass) and
batched (rows advance independently).

  python examples/generate_text.py --family llama --temperature 0.8 \
      --top-k 40 --top-p 0.95
  python examples/generate_text.py --speculative --batch 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "llama"], default="gpt2")
    ap.add_argument("--n-new", type=int, default=24)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy argmax")
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--speculative", action="store_true",
                    help="draft-proposes / target-verifies decoding "
                         "(greedy: output equals plain greedy decode)")
    ap.add_argument("--batch", type=int, default=1,
                    help="rows decode together; each row's output and "
                         "round count equal its own solo run")
    ap.add_argument("--int8-weights", action="store_true",
                    help="int8 weight-only quantization (ops/wquant.py):"
                         " halves the weight stream decode re-reads "
                         "every token")
    ap.add_argument("--int8-kv", action="store_true",
                    help="int8 KV cache (ops/kvquant.py): halves the "
                         "cache stream, the binding term at long "
                         "context")
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")  # wins over a pinned plugin
    import jax.numpy as jnp

    from mpi_acx_tpu.models import llama as lm
    from mpi_acx_tpu.models import transformer as tfm

    if args.family == "llama":
        cfg = lm.tiny_llama(n_layers=2)
        params = lm.init_params(jax.random.key(0), cfg)
        gen, gen_s = lm.generate, lm.generate_sample
    else:
        cfg = tfm.tiny_config(n_layers=2)
        params = tfm.init_params(jax.random.key(0), cfg)
        gen, gen_s = tfm.generate, tfm.generate_sample
    if args.speculative and args.int8_kv:
        ap.error("--int8-kv does not apply to the speculative path "
                 "(its verify windows manage their own cache); "
                 "--int8-weights composes with --speculative fine")
    if args.int8_weights:
        from mpi_acx_tpu.ops.wquant import (GPT2_WEIGHTS, LLAMA_WEIGHTS,
                                            quantize_weights_int8)
        wnames = (LLAMA_WEIGHTS if args.family == "llama"
                  else GPT2_WEIGHTS)
        params = quantize_weights_int8(params, wnames)

    base = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    prompt = jnp.tile(base, (args.batch, 1)).at[:, -1].add(
        jnp.arange(args.batch))
    if args.speculative:
        import dataclasses
        from mpi_acx_tpu.models.speculative import (speculative_generate,
                                                    speculative_sample)
        dcfg = dataclasses.replace(cfg, n_layers=1)
        if args.family == "llama":
            dparams = lm.init_params(jax.random.key(7), dcfg)
        else:
            dparams = tfm.init_params(jax.random.key(7), dcfg)
        if args.temperature == 0.0:
            out, stats = speculative_generate(dparams, dcfg, params, cfg,
                                              prompt, args.n_new, k=4)
        else:
            out, stats = speculative_sample(
                dparams, dcfg, params, cfg, prompt, args.n_new,
                jax.random.key(42), k=4, temperature=args.temperature)
        import numpy as np
        print("rounds per row:", np.asarray(stats["rounds"]).tolist())
    elif args.temperature == 0.0 and args.top_k is None and args.top_p is None:
        out = gen(params, cfg, prompt, n_new=args.n_new,
                  kv_int8=args.int8_kv)
    else:
        out = gen_s(params, cfg, prompt, n_new=args.n_new,
                    key=jax.random.key(42), temperature=args.temperature,
                    top_k=args.top_k, top_p=args.top_p,
                    kv_int8=args.int8_kv)
    for b in range(args.batch):
        print(f"{args.family} row {b}: ",
              out[b, prompt.shape[1]:].tolist())
    print("example OK")


if __name__ == "__main__":
    main()
