"""End-to-end distributed training example: dp x pp x tp on any backend.

Runs a GPT-2-family (or Llama-family with --family llama) model through
the framework's single-program SPMD train step — pipeline stages over
'pp', tensor/sequence parallelism (ring attention) over 'tp', data
parallelism over 'dp' — with AdamW, checkpointing, and a resume.

Works anywhere:
  # 8 virtual CPU devices (laptop / CI):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_distributed.py
  # a real TPU slice: run as-is (one process per host with
  #   mpi_acx_tpu.parallel.multihost.initialize() for multi-host).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "llama", "moe"],
                    default="gpt2")
    ap.add_argument("--schedule", choices=["gpipe", "1f1b"],
                    default="gpipe",
                    help="pipeline schedule: GPipe (autodiff backward) "
                         "or 1F1B (O(pp) activation residency)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--ckpt", default="")
    ap.add_argument("--virtual", type=int, default=1,
                    help="virtual chunks per device (interleaved pipeline "
                         "schedule; 1 = GPipe)")
    ap.add_argument("--data", default="",
                    help="binary uint16 token file to train on (streamed "
                         "through mpi_acx_tpu.data with device prefetch); "
                         "default: synthetic ramp task")
    args = ap.parse_args()
    # --schedule 1f1b composes with --virtual > 1: the interleaved 1F1B
    # schedule (O(v*pp) activation residency AND bubble/v).

    import jax
    # Hosts with a pinned accelerator plugin (e.g. the axon tunnel) register
    # it at interpreter start; an explicit JAX_PLATFORMS=cpu request must
    # win, and jax.config does (the env alone does not).
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from mpi_acx_tpu.models import llama as lm
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.train import make_train_step_optax

    need = args.dp * args.pp * args.tp
    if len(jax.devices()) < need:
        raise SystemExit(
            f"need {need} devices (dp*pp*tp), have {len(jax.devices())} — "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "JAX_PLATFORMS=cpu for a virtual mesh")
    mesh = mesh_from_devices({"dp": args.dp, "pp": args.pp, "tp": args.tp})

    n_layers = 2 * args.pp * args.virtual
    if args.family == "llama":
        cfg = lm.tiny_llama(vocab=256, d_model=64, n_heads=4, n_kv_heads=2,
                            n_layers=n_layers, d_ff=128, max_seq=64)
        params = lm.init_params(jax.random.key(0), cfg)
    elif args.family == "moe":
        from mpi_acx_tpu.models import moe_transformer as mtf
        cfg = mtf.tiny_moe_config(vocab=256, d_model=64, n_heads=4,
                                  n_layers=n_layers, d_ff=128,
                                  n_experts=2 * args.tp,
                                  capacity_factor=4.0, max_seq=64)
        params = mtf.init_params(jax.random.key(0), cfg)
    else:
        cfg = tfm.tiny_config(vocab=256, d_model=64, n_heads=4,
                              n_layers=n_layers, d_ff=128, max_seq=64)
        params = tfm.init_params(jax.random.key(0), cfg)

    opt = optax.adamw(3e-3)
    # Interleaved schedule needs n_micro % pp == 0.
    M = args.pp if args.virtual > 1 else 2
    step, n_stages = make_train_step_optax(cfg, mesh, n_micro=M,
                                           optimizer=opt,
                                           n_virtual=args.virtual,
                                           schedule=args.schedule)
    if args.virtual > 1:
        p = tfm.stage_slice_interleaved(params, n_stages, args.virtual)
    else:
        p = tfm.stage_slice(params, n_stages)
    s = opt.init(p)

    mb, S = 2 * args.dp, 32
    if args.data:
        # Stream real tokens: memmap file -> [M, mb, S+1] windows staged
        # on device by a background prefetch thread, already dp-sharded
        # (and globally addressable, which the multi-host deployment the
        # header describes requires).
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        from mpi_acx_tpu.data import TokenDataset, batches, prefetch
        ds = TokenDataset(args.data)
        sh = NamedSharding(mesh, P(None, "dp"))

        def windows():
            for w in batches(ds, M * mb, S, seed=0, n_batches=args.steps):
                yield (w.astype(np.int32) % cfg.vocab).reshape(
                    M, mb, S + 1)

        def stream():
            for w in prefetch(windows(), sharding=sh):
                yield w[:, :, :-1], w[:, :, 1:]
        data_iter = stream()
    else:
        # Synthetic copy task: predict the next token of a ramp sequence.
        base = jnp.arange(S)[None, None, :] + jnp.arange(mb)[None, :, None]
        tokens = (base + jnp.arange(M)[:, None, None]) % cfg.vocab
        targets = jnp.roll(tokens, -1, axis=-1)
        data_iter = iter(lambda: (tokens, targets), None)  # repeat forever

    ck = None
    if args.ckpt:
        from mpi_acx_tpu.checkpoint import Checkpointer
        ck = Checkpointer(args.ckpt)

    for i in range(args.steps):
        tokens, targets = next(data_iter)
        loss, p, s = step(p, s, tokens, targets)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}", flush=True)
        if ck is not None and i and i % 10 == 0:
            ck.save(i, {"params": p, "opt": s})

    if ck is not None:
        ck.save(args.steps, {"params": p, "opt": s})
        restored = ck.restore(like={"params": p, "opt": s})
        l2, _, _ = step(restored["params"], restored["opt"], tokens, targets)
        print(f"resumed-from-checkpoint loss {float(l2):.4f}")
        ck.close()

    print("example OK")


if __name__ == "__main__":
    main()
