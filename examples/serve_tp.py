"""Tensor-parallel serving example: Megatron-split generation over a
device mesh, for the GPT-2, Llama, or MoE family.

Runs on real TPU chips or a virtual CPU mesh:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/serve_tp.py --family llama --tp 4 --temperature 0.8

The weights and KV cache are sharded over the 'tp' axis (Llama shards by
KV-head group, keeping GQA's small cache per rank); the entire prefill +
decode loop is one shard_map program with two psums per layer. Output is
token-identical to the single-device generate path.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "llama", "moe"],
                default="gpt2")
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--n-new", type=int, default=24)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy argmax")
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--speculative", action="store_true",
                    help="draft-proposes / target-verifies decoding, "
                         "draft and target both TP-split")
    ap.add_argument("--ep-dispatch", default="auto",
                    choices=["auto", "sharded", "replicated"],
                    help="MoE family only: how tokens reach their "
                         "experts (auto = sharded when the call's "
                         "token count divides tp, else replicated)")
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")  # wins over a pinned plugin

    from mpi_acx_tpu.models import llama as lm
    from mpi_acx_tpu.models import moe_transformer as mtf
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.parallel import (make_tp_generate,
                                      make_tp_generate_llama,
                                      make_tp_generate_moe,
                                      mesh_from_devices)

    n_dev = len(jax.devices())
    if args.tp > n_dev:
        raise SystemExit(f"--tp {args.tp} > available devices ({n_dev}); "
                         "on CPU set XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N")
    mesh = mesh_from_devices({"tp": args.tp}, jax.devices()[:args.tp])

    if args.family == "llama":
        # KV heads must split over tp: scale the toy config with it.
        cfg = lm.tiny_llama(n_layers=2, n_heads=2 * args.tp,
                            n_kv_heads=args.tp)
        params = lm.init_params(jax.random.key(0), cfg)
        gen = make_tp_generate_llama(cfg, mesh, args.n_new,
                                     temperature=args.temperature,
                                     top_k=args.top_k, top_p=args.top_p)
        single = lambda p, t: lm.generate(  # noqa: E731
            p, cfg, t, args.n_new, max_len=t.shape[1] + args.n_new)
    elif args.family == "moe":
        # Experts split over tp: scale the expert count with it.
        cfg = mtf.tiny_moe_config(n_layers=2, n_heads=2 * args.tp,
                                  n_experts=2 * args.tp, top_k=2,
                                  capacity_factor=2 * args.tp)
        params = mtf.init_params(jax.random.key(0), cfg)
        gen = make_tp_generate_moe(cfg, mesh, args.n_new,
                                   temperature=args.temperature,
                                   top_k=args.top_k, top_p=args.top_p,
                                   ep_dispatch=args.ep_dispatch)
        single = lambda p, t: mtf.generate(  # noqa: E731
            p, cfg, t, args.n_new, max_len=t.shape[1] + args.n_new)
    else:
        cfg = tfm.tiny_config(n_layers=2)
        params = tfm.init_params(jax.random.key(0), cfg)
        gen = make_tp_generate(cfg, mesh, args.n_new,
                               temperature=args.temperature,
                               top_k=args.top_k, top_p=args.top_p)
        single = lambda p, t: tfm.generate(  # noqa: E731
            p, cfg, t, args.n_new, max_len=t.shape[1] + args.n_new)

    if args.speculative:
        import dataclasses
        from mpi_acx_tpu.parallel import make_tp_speculative_generate
        dcfg = dataclasses.replace(cfg, n_layers=1)
        dinit = {"llama": lm.init_params, "moe": mtf.init_params,
                 "gpt2": tfm.init_params}[args.family]
        dparams = dinit(jax.random.key(7), dcfg)
        sgen = make_tp_speculative_generate(
            dcfg, cfg, mesh, args.n_new, k=4,
            temperature=args.temperature,
            ep_dispatch=args.ep_dispatch)
        prompt = jax.random.randint(jax.random.key(1), (1, 8), 0,
                                    cfg.vocab)
        out, stats = sgen(dparams, params, prompt, jax.random.key(2))
        print(f"family={args.family} tp={args.tp} speculative "
              f"rounds={int(stats['rounds'])} "
              f"accepted={int(stats['drafted_accepted'])}")
        print("output :", out[:, prompt.shape[1]:].tolist())
        return

    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    out = gen(params, prompt, jax.random.key(2))
    print(f"family={args.family} tp={args.tp} devices={n_dev}")
    print("prompt :", prompt.tolist())
    print("output :", out[:, prompt.shape[1]:].tolist())

    if args.temperature == 0.0:
        import numpy as np
        ref = single(params, prompt)
        match = bool((np.asarray(out) == np.asarray(ref)).all())
        print("matches single-device greedy:", match)
        if not match:
            raise SystemExit(1)


if __name__ == "__main__":
    main()
