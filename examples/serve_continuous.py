"""Continuous-batching serving demo (models/serving.py).

Requests with different prompt and output lengths stream through a
fixed pool of cache slots; finished requests are swapped out and queued
prompts swapped in mid-stream, so the device never drains to wait for
the longest request in a batch. Every output is bit-equal to the same
request's solo generate() run (per-slot positions).

Run (CPU):
  JAX_PLATFORMS=cpu python examples/serve_continuous.py \
      --requests 8 --slots 3 --chunk 4

The reference has no serving stack (SURVEY.md §0) — this demonstrates
framework-goal surface above it.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "llama", "moe"],
                    default="gpt2")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--chunk", type=int, default=4,
                    help="decode steps per host dispatch")
    ap.add_argument("--int8-kv", action="store_true",
                    help="serve from int8 slot caches (ops/kvquant.py)")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel serving over N mesh ranks "
                         "(0 = single device; on CPU set "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=N); the toy config's head counts scale "
                         "with N, and f32 is forced so TP outputs "
                         "match the single-device verify exactly")
    ap.add_argument("--verify", action="store_true",
                    help="check every output against its solo run")
    args = ap.parse_args()
    if args.tp and args.int8_kv and args.family == "moe":
        ap.error("--tp --int8-kv: gpt2/llama only for now")

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")  # wins over a pinned plugin

    from mpi_acx_tpu.models import serving
    # Under --tp the toy geometry scales with the mesh so the TP
    # split's divisibility always holds (serve_tp.py's 2*tp pattern).
    heads = 2 * args.tp if args.tp else 4
    if args.family == "gpt2":
        from mpi_acx_tpu.models import transformer as mod
        cfg = mod.tiny_config(vocab=96, d_model=16 * heads,
                              n_heads=heads, n_layers=3,
                              d_ff=32 * heads, max_seq=128)
    elif args.family == "moe":
        from mpi_acx_tpu.models import moe_transformer as mod
        cfg = mod.tiny_moe_config(vocab=96, d_model=16 * heads,
                                  n_heads=heads, n_layers=3,
                                  d_ff=32 * heads, max_seq=128,
                                  n_experts=2 * args.tp if args.tp
                                  else 4)
    else:
        from mpi_acx_tpu.models import llama as mod
        cfg = mod.tiny_llama(vocab=96, d_model=16 * heads,
                             n_heads=heads,
                             n_kv_heads=args.tp if args.tp else 2,
                             n_layers=3, d_ff=32 * heads, max_seq=128)
    server_fns = None
    if args.tp:
        import dataclasses
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = mod.init_params(jax.random.key(0), cfg)
    if args.tp:
        from mpi_acx_tpu.parallel.mesh import mesh_from_devices
        from mpi_acx_tpu.parallel.tp_inference import make_tp_server_fns
        mesh = mesh_from_devices({"tp": args.tp},
                                 jax.devices()[:args.tp])
        server_fns = make_tp_server_fns(params, cfg, mesh,
                                        chunk=args.chunk,
                                        family=args.family,
                                        kv_int8=args.int8_kv)

    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab, rng.integers(3, 14),
                            dtype=np.int32)
               for _ in range(args.requests)]
    n_new = [int(rng.integers(2, 12)) for _ in range(args.requests)]
    max_len = 14 + max(n_new) + args.chunk + 1

    t0 = time.perf_counter()
    outs = serving.serve_greedy(params, cfg, prompts, n_new,
                                n_slots=args.slots, max_len=max_len,
                                family=mod, chunk=args.chunk,
                                kv_int8=args.int8_kv,
                                server_fns=server_fns)
    dt = time.perf_counter() - t0
    total = sum(n_new)
    print(f"{args.requests} requests (lens "
          f"{[len(p) for p in prompts]} -> +{n_new}) through "
          f"{args.slots} slots, chunk={args.chunk}: "
          f"{total} tokens in {dt:.2f}s")
    for i, o in enumerate(outs[:3]):
        print(f"req {i}: {o.tolist()}")

    if args.verify:
        for p, g, n in zip(prompts, outs, n_new):
            want = mod.generate(params, cfg, jnp.asarray(p)[None], n,
                                max_len=max_len, kv_int8=args.int8_kv)
            np.testing.assert_array_equal(np.asarray(g),
                                          np.asarray(want)[0])
        print("all outputs equal their solo runs")
    print("example OK")


if __name__ == "__main__":
    main()
