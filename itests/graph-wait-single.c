/* tpu-acx integration test: graph-mode SINGLE MPIX_Wait_enqueue +
 * non-overtaking ordering stress.
 *
 * Closes the coverage hole SURVEY.md §4 flags in the reference: its
 * graph-construction test only ever exercises MPIX_Waitall_enqueue
 * (reference test/src/ring-all-graph-construction.c:79), leaving the
 * single-wait graph path untested — which is exactly where the
 * reference's latent bug lives (wait kernel armed with PENDING instead
 * of COMPLETED, reference src/sendrecv.cu:411). Part 1 composes a ring
 * exchange from single-op graphs with ONE MPIX_Wait_enqueue PER REQUEST
 * (send and recv each get their own wait node), chains them with
 * dependency edges, destroys the component graphs, and relaunches the
 * executable `size` times — a wait that observed the wrong state would
 * either hang (waiting for a value the flag never revisits) or let the
 * relaunch read a stale buffer, and the circulated value check catches
 * both.
 *
 * Part 2 is a non-overtaking stress the reference explicitly punts on
 * (reference README.md:173-176): two in-flight same-peer/same-tag pairs
 * per round, enqueue order alternating, for many rounds. Our transport
 * matches FIFO per (src, tag, ctx) (src/net/socket_transport.cc:332),
 * so the first-posted receive MUST complete with the first-sent payload.
 */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;

    /* ---- Part 1: single Wait_enqueue nodes in a composed graph ---- */
    int send_val = rank + 1, recv_val = -1;
    MPIX_Request req[2];
    cudaGraph_t g_send, g_recv, g_wait_recv, g_wait_send, graph;
    cudaGraphNode_t n_send, n_recv, n_wrecv, n_wsend;

    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 11, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_GRAPH, &g_send);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 11, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_GRAPH, &g_recv);
    /* The hole itself: one wait PER REQUEST, not a Waitall batch. */
    MPIX_Wait_enqueue(&req[1], MPI_STATUS_IGNORE, MPIX_QUEUE_XLA_GRAPH,
                      &g_wait_recv);
    MPIX_Wait_enqueue(&req[0], MPI_STATUS_IGNORE, MPIX_QUEUE_XLA_GRAPH,
                      &g_wait_send);

    if (cudaGraphCreate(&graph, 0) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);
    cudaGraphAddChildGraphNode(&n_send, graph, NULL, 0, g_send);
    cudaGraphAddChildGraphNode(&n_recv, graph, &n_send, 1, g_recv);
    cudaGraphAddChildGraphNode(&n_wrecv, graph, &n_recv, 1, g_wait_recv);
    cudaGraphAddChildGraphNode(&n_wsend, graph, &n_wrecv, 1, g_wait_send);

    cudaGraphExec_t exec;
    if (cudaGraphInstantiate(&exec, graph, NULL, NULL, 0) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);
    /* Components die first: the exec's refcounted cleanup owns the ops. */
    cudaGraphDestroy(g_send);
    cudaGraphDestroy(g_recv);
    cudaGraphDestroy(g_wait_recv);
    cudaGraphDestroy(g_wait_send);

    for (int i = 0; i < size; i++) {
        cudaGraphLaunch(exec, 0);
        cudaMemcpyAsync(&send_val, &recv_val, sizeof(int),
                        cudaMemcpyHostToHost, 0);
    }
    cudaStreamSynchronize(0);
    cudaGraphExecDestroy(exec);
    cudaGraphDestroy(graph);

    if (recv_val != rank + 1) {
        printf("[%d] graph single-wait: got %d after circulation, want %d\n",
               rank, recv_val, rank + 1);
        errs++;
    }

    /* ---- Part 2: non-overtaking, two in-flight same-peer/same-tag ---- */
    cudaStream_t stream;
    cudaStreamCreate(&stream);
    for (int round = 0; round < 200; round++) {
        int s[2] = {1000 * rank + 2 * round, 1000 * rank + 2 * round + 1};
        int r[2] = {-1, -1};
        MPIX_Request q[4];
        /* Alternate enqueue order so neither side's posting order is a
         * fixed pattern the matching could accidentally depend on. */
        if (round % 2 == 0) {
            MPIX_Isend_enqueue(&s[0], 1, MPI_INT, right, 7, MPI_COMM_WORLD,
                               &q[0], MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Isend_enqueue(&s[1], 1, MPI_INT, right, 7, MPI_COMM_WORLD,
                               &q[1], MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Irecv_enqueue(&r[0], 1, MPI_INT, left, 7, MPI_COMM_WORLD,
                               &q[2], MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Irecv_enqueue(&r[1], 1, MPI_INT, left, 7, MPI_COMM_WORLD,
                               &q[3], MPIX_QUEUE_XLA_STREAM, &stream);
        } else {
            MPIX_Irecv_enqueue(&r[0], 1, MPI_INT, left, 7, MPI_COMM_WORLD,
                               &q[2], MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Irecv_enqueue(&r[1], 1, MPI_INT, left, 7, MPI_COMM_WORLD,
                               &q[3], MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Isend_enqueue(&s[0], 1, MPI_INT, right, 7, MPI_COMM_WORLD,
                               &q[0], MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Isend_enqueue(&s[1], 1, MPI_INT, right, 7, MPI_COMM_WORLD,
                               &q[1], MPIX_QUEUE_XLA_STREAM, &stream);
        }
        cudaStreamSynchronize(stream);          /* triggers fired */
        MPI_Status st[4];
        MPIX_Waitall(4, q, st);
        int want0 = 1000 * left + 2 * round;
        if (r[0] != want0 || r[1] != want0 + 1) {
            if (errs < 5)
                printf("[%d] r%d OVERTAKE: got (%d,%d) want (%d,%d)\n",
                       rank, round, r[0], r[1], want0, want0 + 1);
            errs++;
        }
    }
    cudaStreamDestroy(stream);

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("graph-wait-single: OK\n");
    return errs != 0;
}
