/* tpu-acx integration test: edge cases beyond the reference's coverage.
 *
 * 1. MPIX_Wait on an inactive (never-started / already-waited) persistent
 *    partitioned request returns immediately (MPI persistent semantics).
 * 2. Ops enqueued BEFORE stream capture whose waits are recorded DURING
 *    capture: the captured wait must observe-only, and relaunching the
 *    graph must not consume the slot twice (r2 code-review regression).
 */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;

    /* 1: wait-on-inactive returns at once (would deadlock if broken). */
    int pbuf[4];
    MPIX_Request preq;
    MPI_Status pst;
    MPIX_Psend_init(pbuf, 4, 1, MPI_INT, right, 8, MPI_COMM_WORLD,
                    MPI_INFO_NULL, &preq);
    if (MPIX_Wait(&preq, &pst) != MPI_SUCCESS) errs++;   /* never started */
    MPIX_Request_free(&preq);

    /* 2: pre-capture enqueue + captured waitall, relaunched twice. */
    int send_val = rank + 1, recv_val = -1;
    MPIX_Request req[2];
    cudaStream_t stream;
    cudaStreamCreate(&stream);

    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 9, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 9, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_STREAM, &stream);

    cudaStreamBeginCapture(stream, cudaStreamCaptureModeGlobal);
    MPIX_Waitall_enqueue(2, req, MPI_STATUSES_IGNORE, MPIX_QUEUE_XLA_STREAM,
                         &stream);
    cudaGraph_t graph;
    cudaStreamEndCapture(stream, &graph);
    cudaGraphExec_t exec;
    cudaGraphInstantiate(&exec, graph, NULL, NULL, 0);

    /* First launch completes the pre-capture ops... */
    cudaGraphLaunch(exec, stream);
    cudaStreamSynchronize(stream);
    if (recv_val != left + 1) {
        printf("[%d] capture-wait: got %d want %d\n", rank, recv_val,
               left + 1);
        errs++;
    }
    /* ...second launch re-runs the observe-only waits: must return
     * instantly (slot still COMPLETED), not hang or consume a fresh slot. */
    cudaGraphLaunch(exec, stream);
    cudaStreamSynchronize(stream);

    cudaGraphExecDestroy(exec);
    cudaGraphDestroy(graph);
    cudaStreamDestroy(stream);

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("edge-cases: OK\n");
    return errs != 0;
}
