/* tpu-acx integration test: edge cases beyond the reference's coverage.
 *
 * 1. MPIX_Wait on an inactive (never-started / already-waited) persistent
 *    partitioned request returns immediately (MPI persistent semantics).
 * 2. Ops enqueued BEFORE stream capture whose waits are recorded DURING
 *    capture: the captured wait must observe-only, and relaunching the
 *    graph must not consume the slot twice (r2 code-review regression).
 * 3. Truncated receive: buffer shorter than the matched message delivers
 *    the prefix with status.MPI_ERROR = MPI_ERR_TRUNCATE and the real
 *    received count (MPI semantics the reference inherits from MPI).
 * 4. Error returns: MPIX_Prequest_create on a basic (non-partitioned)
 *    request and on NULL must fail cleanly, not crash.
 */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;

    /* 1: wait-on-inactive returns at once (would deadlock if broken). */
    int pbuf[4];
    MPIX_Request preq;
    MPI_Status pst;
    MPIX_Psend_init(pbuf, 4, 1, MPI_INT, right, 8, MPI_COMM_WORLD,
                    MPI_INFO_NULL, &preq);
    if (MPIX_Wait(&preq, &pst) != MPI_SUCCESS) errs++;   /* never started */
    MPIX_Request_free(&preq);

    /* 2: pre-capture enqueue + captured waitall, relaunched twice. */
    int send_val = rank + 1, recv_val = -1;
    MPIX_Request req[2];
    cudaStream_t stream;
    cudaStreamCreate(&stream);

    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 9, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 9, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_STREAM, &stream);

    cudaStreamBeginCapture(stream, cudaStreamCaptureModeGlobal);
    MPIX_Waitall_enqueue(2, req, MPI_STATUSES_IGNORE, MPIX_QUEUE_XLA_STREAM,
                         &stream);
    cudaGraph_t graph;
    cudaStreamEndCapture(stream, &graph);
    cudaGraphExec_t exec;
    cudaGraphInstantiate(&exec, graph, NULL, NULL, 0);

    /* First launch completes the pre-capture ops... */
    cudaGraphLaunch(exec, stream);
    cudaStreamSynchronize(stream);
    if (recv_val != left + 1) {
        printf("[%d] capture-wait: got %d want %d\n", rank, recv_val,
               left + 1);
        errs++;
    }
    /* ...second launch re-runs the observe-only waits: must return
     * instantly (slot still COMPLETED), not hang or consume a fresh slot. */
    cudaGraphLaunch(exec, stream);
    cudaStreamSynchronize(stream);

    cudaGraphExecDestroy(exec);
    cudaGraphDestroy(graph);
    cudaStreamDestroy(stream);

    /* 3: truncated receive reports MPI_ERR_TRUNCATE + the short count. */
    cudaStream_t ts;
    cudaStreamCreate(&ts);
    {
        int big[8], small[2] = {-1, -1};
        MPIX_Request treq[2];
        MPI_Status tst;
        int i;
        for (i = 0; i < 8; i++) big[i] = rank * 100 + i;
        MPIX_Isend_enqueue(big, 8, MPI_INT, right, 21, MPI_COMM_WORLD,
                           &treq[0], MPIX_QUEUE_XLA_STREAM, &ts);
        MPIX_Irecv_enqueue(small, 2, MPI_INT, left, 21, MPI_COMM_WORLD,
                           &treq[1], MPIX_QUEUE_XLA_STREAM, &ts);
        cudaStreamSynchronize(ts);
        if (MPIX_Wait(&treq[1], &tst) != MPI_SUCCESS) errs++;
        if (tst.MPI_ERROR != MPI_ERR_TRUNCATE) {
            printf("[%d] truncation: MPI_ERROR=%d want %d\n", rank,
                   tst.MPI_ERROR, MPI_ERR_TRUNCATE);
            errs++;
        }
        if (tst.acx_bytes != 2 * sizeof(int)) {
            printf("[%d] truncation: bytes=%zu want %zu\n", rank,
                   tst.acx_bytes, 2 * sizeof(int));
            errs++;
        }
        if (small[0] != left * 100 + 0 || small[1] != left * 100 + 1) {
            printf("[%d] truncation: prefix %d,%d\n", rank, small[0],
                   small[1]);
            errs++;
        }
        if (MPIX_Wait(&treq[0], &tst) != MPI_SUCCESS) errs++;
        if (tst.MPI_ERROR != MPI_SUCCESS) errs++;   /* sender unaffected */
    }

    /* 3b: typed Allreduce (float SUM, double MIN, int64 MAX) — the MPI
     * substrate role beyond the INT-only control path. */
    {
        float f = (float)rank + 0.5f;
        double d = 10.0 - rank;
        long long ll = rank * 7;
        int p;
        float fs = 0.0f;
        double dm = 1e9;
        long long lm = -1;
        MPI_Allreduce(MPI_IN_PLACE, &f, 1, MPI_FLOAT, MPI_SUM,
                      MPI_COMM_WORLD);
        MPI_Allreduce(MPI_IN_PLACE, &d, 1, MPI_DOUBLE, MPI_MIN,
                      MPI_COMM_WORLD);
        MPI_Allreduce(MPI_IN_PLACE, &ll, 1, MPI_INT64_T, MPI_MAX,
                      MPI_COMM_WORLD);
        for (p = 0; p < size; p++) {
            fs += (float)p + 0.5f;
            if (10.0 - p < dm) dm = 10.0 - p;
            if (p * 7LL > lm) lm = p * 7LL;
        }
        if (f != fs || d != dm || ll != lm) {
            printf("[%d] typed allreduce: %f/%f %f/%f %lld/%lld\n", rank,
                   f, fs, d, dm, ll, lm);
            errs++;
        }
    }

    /* 4: Prequest_create misuse fails cleanly. */
    {
        int v = 0;
        MPIX_Request basic;
        MPIX_Prequest pq = MPIX_PREQUEST_NULL;
        MPIX_Isend_enqueue(&v, 1, MPI_INT, right, 22, MPI_COMM_WORLD, &basic,
                           MPIX_QUEUE_XLA_STREAM, &ts);
        if (MPIX_Prequest_create(basic, &pq) == MPI_SUCCESS) errs++;
        if (pq != MPIX_PREQUEST_NULL) errs++;
        if (MPIX_Prequest_create(NULL, &pq) == MPI_SUCCESS) errs++;
        cudaStreamSynchronize(ts);
        {   /* drain the matching recv so finalize sees no leaked slots */
            int w = -1;
            MPIX_Request r2;
            MPI_Status st2;
            MPIX_Irecv_enqueue(&w, 1, MPI_INT, left, 22, MPI_COMM_WORLD, &r2,
                               MPIX_QUEUE_XLA_STREAM, &ts);
            cudaStreamSynchronize(ts);
            if (MPIX_Wait(&r2, &st2) != MPI_SUCCESS) errs++;
            if (MPIX_Wait(&basic, &st2) != MPI_SUCCESS) errs++;
        }
    }
    cudaStreamDestroy(ts);

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("edge-cases: OK\n");
    return errs != 0;
}
