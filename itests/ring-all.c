/* tpu-acx integration test: batched on-queue wait (MPIX_Waitall_enqueue).
 * Coverage parity with reference test/src/ring-all.c:72-90. */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;
    int send_val = rank + 100, recv_val = -1;
    MPIX_Request req[2];
    MPI_Status statuses[2];
    cudaStream_t stream = 0;

    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 3, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 3, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Waitall_enqueue(2, req, statuses, MPIX_QUEUE_XLA_STREAM, &stream);

    if (cudaStreamSynchronize(stream) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);

    if (recv_val != left + 100) {
        printf("[%d] got %d, want %d\n", rank, recv_val, left + 100);
        errs++;
    }
    if (statuses[1].MPI_SOURCE != left || statuses[1].MPI_TAG != 3) {
        printf("[%d] bad recv status (%d,%d)\n", rank, statuses[1].MPI_SOURCE,
               statuses[1].MPI_TAG);
        errs++;
    }

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("ring-all: OK\n");
    return errs != 0;
}
