/* tpu-acx integration test: dead-peer detection bounds a wedged recv.
 *
 * Ranks != 0 exit right after init WITHOUT finalizing — simulated crashed
 * peers. Rank 0 then posts a recv from rank 1 that can never be satisfied
 * and must get a PEER_DEAD (or, failsafe, TIMEOUT) status in bounded time
 * instead of hanging forever — the reference's behavior in this scenario
 * is an indefinite wedge (its only failure story is MPI_ERRORS_ARE_FATAL).
 * Detection is EOF on the socket plane and heartbeat loss on the shm plane
 * (rings have no EOF), so this test is meaningful in every `make check`
 * transport config. Run under `acxrun -np N`.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <unistd.h>

#include <mpi.h>
#include <mpi-acx.h>

#ifdef __cplusplus
extern "C"
#endif
void acx_resilience_stats(uint64_t *out);

int main(int argc, char **argv) {
    /* Heartbeat knobs must be armed before the transport is created. */
    setenv("ACX_HEARTBEAT_MS", "25", 1);
    setenv("ACX_PEER_TIMEOUT_MS", "150", 1);
    setenv("ACX_PEER_GRACE_MS", "500", 1);

    int provided, rank, size, errs = 0;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (size < 2) {
        printf("dead-peer: needs >= 2 ranks\n");
        MPI_Abort(MPI_COMM_WORLD, 1);
    }

    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    if (rank != 0) _exit(0); /* crash: no finalize, no goodbye */

    /* Failsafe: even if detection somehow missed, the per-op deadline
     * bounds the wait well under acxrun's job timeout. */
    MPIX_Set_deadline(5000);

    int v = -1;
    MPIX_Request req;
    MPI_Status st;
    cudaStream_t stream = 0;
    MPIX_Irecv_enqueue(&v, 1, MPI_INT, 1, 0, MPI_COMM_WORLD, &req,
                       MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Wait(&req, &st);

    if (st.MPI_ERROR != MPIX_ERR_PEER_DEAD &&
        st.MPI_ERROR != MPIX_ERR_TIMEOUT) {
        printf("[0] expected PEER_DEAD/TIMEOUT status, got %d\n",
               st.MPI_ERROR);
        errs++;
    }

    /* The failure must be visible in the resilience counters, not just in
     * the one status (acceptance: counters in proxy statistics). */
    uint64_t rs[8];
    acx_resilience_stats(rs);
    if (rs[7] < 1 && rs[1] < 1) {
        printf("[0] no peer-dead (%llu) or timeout (%llu) counted\n",
               (unsigned long long)rs[7], (unsigned long long)rs[1]);
        errs++;
    }

    MPIX_Set_deadline(0);
    MPIX_Finalize();
    MPI_Finalize(); /* barrier against dead peers must not hang */
    if (errs == 0) printf("dead-peer: OK\n");
    return errs != 0;
}
