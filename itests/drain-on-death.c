/* tpu-acx integration test: survivors drain and exit cleanly after a rank
 * dies mid-flight.
 *
 * The victim (highest rank) exits without a word while the survivors have
 * a recv posted against it — and no failure detector is armed to save
 * them: heartbeats are off (on the shm plane the victim is simply never
 * declared dead) and the reconnect ladder is pinned long (on the socket
 * plane the EOF parks the op in RECOVERING for ~10s of dial attempts).
 * MPIX_Drain is therefore the ONLY mechanism that can unblock the waiter:
 * it cancels the in-flight op with a typed error (TIMEOUT while the peer
 * still looks healthy, PEER_DEAD while its link is recovering), the
 * drained-slot counter ticks, healthy traffic among survivors is
 * untouched, and every survivor exits 0 — the reference wedges forever in
 * this scenario. Survivors _exit after MPIX_Finalize instead of running
 * MPI_Finalize's barrier: the victim is deliberately never declared dead,
 * so a barrier against it would block on the ladder, not on the drain
 * under test. Run under `acxrun -np N` (N >= 3 keeps a live neighbor pair
 * to prove the survivors still talk). */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <unistd.h>

#include <mpi.h>
#include <mpi-acx.h>

#ifdef __cplusplus
extern "C" {
#endif
void acx_recovery_stats(uint64_t *out);
int acx_drain(double timeout_ms);
#ifdef __cplusplus
}
#endif

int main(int argc, char **argv) {
    /* Pin the reconnect ladder well past the test window so a socket-plane
     * EOF keeps the op parked in RECOVERING until the drain cancels it
     * (500+1000+2000+2000+... ms of backoff before the peer could be
     * declared dead). Must be set before the transport exists. */
    setenv("ACX_RECONNECT_MAX", "8", 1);
    setenv("ACX_RECONNECT_BACKOFF_MS", "500", 1);

    int provided, rank, size, errs = 0;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (size < 2) {
        printf("drain-on-death: needs >= 2 ranks\n");
        MPI_Abort(MPI_COMM_WORLD, 1);
    }

    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int victim = size - 1;
    if (rank == victim) {
        usleep(100 * 1000); /* let survivors post against us first */
        _exit(0);           /* die mid-flight: no finalize, no goodbye */
    }

    cudaStream_t stream = 0;
    MPI_Status st;

    /* A recv from the victim that can never complete. */
    int dead_v = -1;
    MPIX_Request dead_req;
    MPIX_Irecv_enqueue(&dead_v, 1, MPI_INT, victim, 7, MPI_COMM_WORLD,
                       &dead_req, MPIX_QUEUE_XLA_STREAM, &stream);

    /* A live neighbor exchange among survivors: draining the dead op must
     * not break healthy traffic. */
    int nsurv = size - 1, sv = rank * 13 + 1, rv = -1;
    MPIX_Request live_req[2];
    if (nsurv >= 2) {
        const int right = (rank + 1) % nsurv;
        const int left = (rank + nsurv - 1) % nsurv;
        MPIX_Isend_enqueue(&sv, 1, MPI_INT, right, 9, MPI_COMM_WORLD,
                           &live_req[0], MPIX_QUEUE_XLA_STREAM, &stream);
        MPIX_Irecv_enqueue(&rv, 1, MPI_INT, left, 9, MPI_COMM_WORLD,
                           &live_req[1], MPIX_QUEUE_XLA_STREAM, &stream);
        MPIX_Wait(&live_req[0], MPI_STATUS_IGNORE);
        MPIX_Wait(&live_req[1], &st);
        if (st.MPI_ERROR != MPI_SUCCESS || rv != left * 13 + 1) {
            printf("[%d] live exchange broken (err %d, got %d)\n", rank,
                   st.MPI_ERROR, rv);
            errs++;
        }
    }

    /* Give the victim time to actually die, then drain. The dead recv
     * must be cancelled (>= 1); a clean 0 would mean it "completed". */
    usleep(200 * 1000);
    const int drained = MPIX_Drain(400);
    if (drained < 1) {
        printf("[%d] MPIX_Drain cancelled %d ops, want >= 1\n", rank,
               drained);
        errs++;
    }

    /* The cancelled request's waiter unblocks immediately with the typed
     * error the drain stamped. */
    MPIX_Wait(&dead_req, &st);
    if (st.MPI_ERROR != MPIX_ERR_PEER_DEAD &&
        st.MPI_ERROR != MPIX_ERR_TIMEOUT) {
        printf("[%d] drained recv status %d, want PEER_DEAD/TIMEOUT\n",
               rank, st.MPI_ERROR);
        errs++;
    }

    uint64_t rs[7];
    acx_recovery_stats(rs);
    if (rs[4] < 1) {
        printf("[%d] drained_slots %llu, want >= 1\n", rank,
               (unsigned long long)rs[4]);
        errs++;
    }

    MPIX_Finalize(); /* local teardown only — no barrier with the dead */
    if (rank == 0 && errs == 0) printf("drain-on-death: OK\n");
    fflush(stdout);
    fflush(stderr);
    _exit(errs != 0); /* skip MPI_Finalize's barrier: see header comment */
}
