/* tpu-acx integration test: chaos soak under a seeded multi-fault schedule
 * (DESIGN.md §16 — the chaos-conductor capstone).
 *
 * Serving-shaped traffic — a byte-verified neighbor ring leg plus a
 * partitioned (Psend/Precv) leg per round, fenced all-to-all — runs for
 * ACX_CC_ROUNDS rounds while a fault schedule (ACX_FAULT / ACX_CHAOS,
 * armed by the harness) drops, delays, corrupts and stalls underneath it.
 * Recoverable wire faults must be absorbed invisibly by the CRC/NAK/replay
 * machinery: every payload integer is checked against a closed-form
 * formula, so a single duplicated or lost delivery fails the run.
 *
 * The `kill` action is the one fault the transport cannot hide: the victim
 * rank dies by SIGKILL mid-round (no dump, no goodbye — SIGKILL is
 * uncatchable) and `acxrun -chaos` respawns it with ACX_JOIN=1. This
 * workload supplies the application half of that story, the heal protocol:
 *   - any op error sends a survivor into heal: dump flight state once
 *     (evidence for tools/acx_doctor.py), MPIX_Drain parked ops, identify
 *     the victim by probing for the joiner's hello (only a respawned
 *     incarnation ever sends tag 900 — a DEAD slot in the fleet view
 *     cannot be trusted here, the victim may have already rejoined by the
 *     time a survivor unwedges from an abandoned round), and report the
 *     round it died in to the coordinator (the lowest-ranked survivor);
 *   - the coordinator takes the MINIMUM failing round across survivors
 *     (ranks can be one round apart when the kill lands inside a fence)
 *     and, once the joiner's hello lands, tells the joiner and every
 *     survivor where to resume;
 *   - the respawned incarnation (ACX_JOIN=1 in env) joins the fleet,
 *     hellos every survivor, receives the resume round, and the FULL
 *     fleet re-runs from there. Payloads are closed-form in (rank, round,
 *     i), so replayed rounds reproduce byte-identical traffic and
 *     duplicate deliveries of a redone round are detected, not absorbed.
 *
 * Run under `acxrun -np N -transport socket -chaos`. Fault-free it is a
 * plain soak and passes on any plane; the heal path needs the socket
 * plane's rendezvous listeners (ACX_JOB_ID) to readmit the joiner.
 *
 * Knobs: ACX_CC_ROUNDS (default 10), ACX_CC_INTS (ring payload ints,
 * default 1024), ACX_CC_JOIN_WAIT_MS (heal wait for the joiner's hello,
 * default 30000).
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <mpi.h>
#include <mpi-acx.h>

#define MAX_RANKS 16
#define MAX_INTS 65536
#define PARTS 8
#define PART_INTS 32

static int g_rank, g_size, g_rounds, g_ints;
static uint64_t g_join_wait_ms;
static int g_dumped; /* MPIX_Dump_state once per process */

static int expect(int rank, int round, int i) {
    return rank * 1000003 + round * 8191 + i * 7 + 1;
}

static uint64_t now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000u + (uint64_t)(ts.tv_nsec / 1000000);
}

static int env_int(const char *name, int dflt) {
    const char *s = getenv(name);
    return s != NULL && atoi(s) > 0 ? atoi(s) : dflt;
}

/* ---- traffic legs -------------------------------------------------- */

/* Neighbor ring, ACX_CC_INTS ints, byte-verified. Returns 0 ok, -1 on an
 * op error (heal), >0 on a verify miss (hard failure: the transport
 * delivered wrong bytes — nothing to heal). */
static int ring_leg(int round) {
    static int sbuf[MAX_INTS], rbuf[MAX_INTS];
    const int right = (g_rank + 1) % g_size;
    const int left = (g_rank + g_size - 1) % g_size;
    for (int i = 0; i < g_ints; i++) {
        sbuf[i] = expect(g_rank, round, i);
        rbuf[i] = -1;
    }
    cudaStream_t stream = 0;
    MPIX_Request req[2];
    MPI_Status st[2];
    MPIX_Isend_enqueue(sbuf, g_ints, MPI_INT, right, 100 + round,
                       MPI_COMM_WORLD, &req[0], MPIX_QUEUE_XLA_STREAM,
                       &stream);
    MPIX_Irecv_enqueue(rbuf, g_ints, MPI_INT, left, 100 + round,
                       MPI_COMM_WORLD, &req[1], MPIX_QUEUE_XLA_STREAM,
                       &stream);
    MPIX_Wait(&req[0], &st[0]);
    MPIX_Wait(&req[1], &st[1]);
    if (st[0].MPI_ERROR != MPI_SUCCESS || st[1].MPI_ERROR != MPI_SUCCESS)
        return -1;
    for (int i = 0; i < g_ints; i++) {
        if (rbuf[i] != expect(left, round, i)) {
            printf("[%d] round %d: ring rbuf[%d] = %d, want %d\n", g_rank,
                   round, i, rbuf[i], expect(left, round, i));
            return 1;
        }
    }
    return 0;
}

/* Partitioned leg: PARTS x PART_INTS ints to the right neighbor, Pready
 * out of order, arrival polled with a bound (a dead peer never flips the
 * arrived flag — the bounded poll falls through to Waitall, which reports
 * the teardown error and routes us into heal). Same return contract as
 * ring_leg. */
static int partitioned_leg(int round) {
    static int sbuf[PARTS * PART_INTS], rbuf[PARTS * PART_INTS];
    const int right = (g_rank + 1) % g_size;
    const int left = (g_rank + g_size - 1) % g_size;
    for (int i = 0; i < PARTS * PART_INTS; i++) {
        sbuf[i] = expect(g_rank, round, 500000 + i);
        rbuf[i] = -1;
    }
    MPIX_Request req[2];
    MPI_Status st[2];
    MPIX_Prequest psend, precv;
    if (MPIX_Psend_init(sbuf, PARTS, PART_INTS, MPI_INT, right, 500 + round,
                        MPI_COMM_WORLD, MPI_INFO_NULL, &req[0]) ||
        MPIX_Precv_init(rbuf, PARTS, PART_INTS, MPI_INT, left, 500 + round,
                        MPI_COMM_WORLD, MPI_INFO_NULL, &req[1]))
        return 1;
    MPIX_Prequest_create(req[0], &psend);
    MPIX_Prequest_create(req[1], &precv);
    MPIX_Startall(2, req);
    for (int p = PARTS - 1; p >= 0; p--) MPIX_Pready(p, psend);
    const uint64_t poll_deadline = now_ms() + 8000;
    for (int p = 0; p < PARTS; p++) {
        int flag = 0;
        while (!flag && now_ms() < poll_deadline) {
            MPIX_Parrived(precv, p, &flag);
            if (!flag) usleep(200);
        }
        if (!flag) break; /* peer likely dead: let Waitall name the error */
    }
    MPIX_Waitall(2, req, st);
    MPIX_Prequest_free(&psend);
    MPIX_Prequest_free(&precv);
    MPIX_Request_free(&req[0]);
    MPIX_Request_free(&req[1]);
    if (st[0].MPI_ERROR != MPI_SUCCESS || st[1].MPI_ERROR != MPI_SUCCESS)
        return -1;
    for (int i = 0; i < PARTS * PART_INTS; i++) {
        if (rbuf[i] != expect(left, round, 500000 + i)) {
            printf("[%d] round %d: part rbuf[%d] = %d, want %d\n", g_rank,
                   round, i, rbuf[i], expect(left, round, 500000 + i));
            return 1;
        }
    }
    return 0;
}

/* All-to-all token fence closing each round: bounds cross-rank round skew
 * to one and guarantees every survivor of a mid-round kill observes the
 * death within that round (the victim's missing token fails the fence
 * even on ranks that are not the victim's ring neighbors). Returns 0 ok,
 * -1 on op error, >0 on token mismatch. */
static int fence_leg(int round) {
    cudaStream_t stream = 0;
    static int token;
    token = round;
    MPIX_Request req[2 * MAX_RANKS];
    int rbuf[MAX_RANKS];
    int n = 0;
    for (int r = 0; r < g_size; r++) {
        if (r == g_rank) continue;
        MPIX_Isend_enqueue(&token, 1, MPI_INT, r, 700 + round,
                           MPI_COMM_WORLD, &req[n++], MPIX_QUEUE_XLA_STREAM,
                           &stream);
        rbuf[r] = -1;
        MPIX_Irecv_enqueue(&rbuf[r], 1, MPI_INT, r, 700 + round,
                           MPI_COMM_WORLD, &req[n++], MPIX_QUEUE_XLA_STREAM,
                           &stream);
    }
    int bad = 0;
    for (int i = 0; i < n; i++) {
        MPI_Status st;
        MPIX_Wait(&req[i], &st);
        if (st.MPI_ERROR != MPI_SUCCESS) bad = 1;
    }
    if (bad) return -1;
    for (int r = 0; r < g_size; r++) {
        if (r != g_rank && rbuf[r] != round) {
            printf("[%d] round %d: fence token from %d = %d\n", g_rank,
                   round, r, rbuf[r]);
            return 1;
        }
    }
    return 0;
}

/* ---- heal protocol -------------------------------------------------- */

/* Retrying one-int send: the heal window overlaps the victim's LEFT/DEAD
 * latch, so a post can complete immediately with PEER_DEAD until the
 * joiner is adopted. Bounded by `deadline` (absolute ms). */
static int send_retry(int *val, int peer, int tag, uint64_t deadline) {
    cudaStream_t stream = 0;
    for (;;) {
        MPIX_Request req;
        MPI_Status st;
        MPIX_Isend_enqueue(val, 1, MPI_INT, peer, tag, MPI_COMM_WORLD, &req,
                           MPIX_QUEUE_XLA_STREAM, &stream);
        MPIX_Wait(&req, &st);
        if (st.MPI_ERROR == MPI_SUCCESS) return 0;
        if (now_ms() >= deadline) return -1;
        usleep(5000);
    }
}

static int recv_retry(int *val, int peer, int tag, uint64_t deadline) {
    cudaStream_t stream = 0;
    for (;;) {
        const uint64_t left_ms =
            deadline > now_ms() ? deadline - now_ms() : 1;
        MPIX_Set_deadline((double)left_ms);
        MPIX_Request req;
        MPI_Status st;
        MPIX_Irecv_enqueue(val, 1, MPI_INT, peer, tag, MPI_COMM_WORLD, &req,
                           MPIX_QUEUE_XLA_STREAM, &stream);
        MPIX_Wait(&req, &st);
        MPIX_Set_deadline(8000); /* restore the failsafe */
        if (st.MPI_ERROR == MPI_SUCCESS) return 0;
        if (now_ms() >= deadline) return -1;
        usleep(5000);
    }
}

/* One short-deadline recv, no retry: the victim-discovery probe. A probe
 * against a live peer times out in `ms`; one against the joiner's slot
 * consumes the buffered hello and succeeds. */
static int probe_recv(int *val, int peer, int tag, uint64_t ms) {
    cudaStream_t stream = 0;
    MPIX_Set_deadline((double)ms);
    MPIX_Request req;
    MPI_Status st;
    MPIX_Irecv_enqueue(val, 1, MPI_INT, peer, tag, MPI_COMM_WORLD, &req,
                       MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Wait(&req, &st);
    MPIX_Set_deadline(8000);
    return st.MPI_ERROR == MPI_SUCCESS ? 0 : -1;
}

/* Survivor heal: returns the resume round (>= 0) or -1 on failure. */
static int heal(int failed_round) {
    if (!g_dumped) {
        g_dumped = 1;
        MPIX_Dump_state(); /* evidence for acx_doctor before the wait */
    }
    MPIX_Drain(500); /* cancel parked ops so the retry lanes are clean */
    /* Victim discovery doubles as the adoption wait: the respawned
     * incarnation hellos every survivor (tag 900) right after its JOIN
     * lands, and nothing else ever sends that tag — so probing each peer
     * in turn both names the victim and proves our transport adopted the
     * joiner (its frame can only arrive over the link installed when the
     * JOIN dial was accepted). The fleet view is NOT consulted: a
     * survivor that unwedges late can enter heal after the DEAD->ACTIVE
     * rejoin transition already erased the verdict. */
    const uint64_t deadline = now_ms() + g_join_wait_ms;
    int victim = -1;
    while (victim < 0) {
        for (int r = 0; r < g_size && victim < 0; r++) {
            if (r == g_rank) continue;
            int token = -1;
            if (probe_recv(&token, r, 900, 400) == 0) victim = r;
        }
        if (victim < 0 && now_ms() >= deadline) {
            printf("[%d] heal: no joiner hello within %llums\n", g_rank,
                   (unsigned long long)g_join_wait_ms);
            fflush(stdout);
            MPIX_Dump_state();
            return -1;
        }
    }
    int coord = -1;
    for (int r = 0; r < g_size; r++)
        if (r != victim) { coord = r; break; }
    printf("[%d] heal: victim=%d coord=%d failed_round=%d\n", g_rank,
           victim, coord, failed_round);
    fflush(stdout);
    int resume = failed_round;
    if (g_rank == coord) {
        /* Min failing round across survivors: a rank that passed the
         * fence the victim's tokens squeaked through can be one round
         * ahead of its peers. */
        for (int r = 0; r < g_size; r++) {
            if (r == victim || r == coord) continue;
            int fr = -1;
            if (recv_retry(&fr, r, 930, deadline) != 0) return -1;
            if (fr >= 0 && fr < resume) resume = fr;
        }
    } else {
        if (send_retry(&failed_round, coord, 930, deadline) != 0) return -1;
    }
    if (g_rank == coord) {
        if (send_retry(&resume, victim, 901, deadline) != 0) return -1;
        for (int r = 0; r < g_size; r++) {
            if (r == victim || r == coord) continue;
            if (send_retry(&resume, r, 902, deadline) != 0) return -1;
        }
    } else {
        if (recv_retry(&resume, coord, 902, deadline) != 0) return -1;
    }
    printf("[%d] heal: resuming at round %d (epoch %llu)\n", g_rank, resume,
           (unsigned long long)MPIX_Fleet_epoch());
    fflush(stdout);
    return resume;
}

/* Joiner-side heal entry: hello every survivor, learn where to resume. */
static int join_resume(void) {
    const uint64_t deadline = now_ms() + g_join_wait_ms;
    int coord = -1;
    for (int r = 0; r < g_size; r++)
        if (r != g_rank) { coord = r; break; }
    static int token;
    token = g_rank;
    for (int r = 0; r < g_size; r++) {
        if (r == g_rank) continue;
        if (send_retry(&token, r, 900, deadline) != 0) return -1;
    }
    int resume = -1;
    if (recv_retry(&resume, coord, 901, deadline) != 0) return -1;
    printf("[%d] join: resuming at round %d (epoch %llu)\n", g_rank, resume,
           (unsigned long long)MPIX_Fleet_epoch());
    fflush(stdout);
    return resume;
}

int main(int argc, char **argv) {
    /* Snappy failure detection: the kill leg budgets ~2s for the death
     * latch, not the 30s defaults. overwrite=0 so a harness can repin. */
    setenv("ACX_HEARTBEAT_MS", "25", 0);
    setenv("ACX_PEER_TIMEOUT_MS", "2000", 0);
    setenv("ACX_PEER_GRACE_MS", "2000", 0);

    const int joiner = getenv("ACX_JOIN") != NULL &&
                       atoi(getenv("ACX_JOIN")) != 0;
    g_rounds = env_int("ACX_CC_ROUNDS", 10);
    g_ints = env_int("ACX_CC_INTS", 1024);
    if (g_ints > MAX_INTS) g_ints = MAX_INTS;
    g_join_wait_ms = (uint64_t)env_int("ACX_CC_JOIN_WAIT_MS", 30000);

    int provided;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &g_rank);
    MPI_Comm_size(MPI_COMM_WORLD, &g_size);
    if (g_size < 2 || g_size > MAX_RANKS) {
        printf("chaos-conductor: needs 2..%d ranks\n", MAX_RANKS);
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);
    /* Leg failsafe: generous against recoverable faults (a reconnect
     * ladder runs ~2s) but short enough that a survivor whose live peer
     * abandoned the round unwedges while the joiner is still waiting. */
    MPIX_Set_deadline(8000);

    const uint64_t epoch0 = MPIX_Fleet_epoch();
    int round = 0;
    if (joiner) {
        round = join_resume();
        if (round < 0) {
            fflush(stdout);
            _exit(7);
        }
    }

    int errs = 0;
    while (round < g_rounds) {
        int rc = ring_leg(round);
        if (rc == 0) rc = partitioned_leg(round);
        if (rc == 0) rc = fence_leg(round);
        if (rc > 0) { /* wrong bytes delivered: nothing to heal */
            errs = 1;
            break;
        }
        if (rc < 0) {
            const int resume = heal(round);
            if (resume < 0) {
                fflush(stdout);
                _exit(7);
            }
            round = resume;
            continue;
        }
        round++;
    }

    /* Completion barrier, best-effort: a clean rank must NOT exit while a
     * peer still needs its last round's frames. Exit closes the links, and
     * a straggler whose final fence recv loses the race sees EOF -> phantom
     * death -> a full joiner wait for a joiner that never comes. Tokens to
     * dead/absent peers are abandoned at the deadline (that side already
     * chose its own exit). */
    if (errs == 0) {
        const uint64_t dl = now_ms() + 5000;
        static int done_tok;
        done_tok = g_rounds;
        for (int r = 0; r < g_size; r++)
            if (r != g_rank) send_retry(&done_tok, r, 799, dl);
        for (int r = 0; r < g_size; r++) {
            int v = 0;
            if (r != g_rank) recv_retry(&v, r, 799, dl);
        }
    }

    /* A healed run must show the membership churn: one death + one join
     * is two epoch bumps minimum over the incarnation's starting point. */
    if (errs == 0 && g_dumped && MPIX_Fleet_epoch() < epoch0 + 2) {
        printf("[%d] epoch %llu did not climb past %llu after heal\n",
               g_rank, (unsigned long long)MPIX_Fleet_epoch(),
               (unsigned long long)epoch0);
        errs = 1;
    }

    MPIX_Finalize(); /* local teardown; no barrier — the fleet is a mix of
                        original and respawned incarnations */
    if (g_rank == 0 && errs == 0) printf("chaos-conductor: OK\n");
    fflush(stdout);
    fflush(stderr);
    _exit(errs != 0);
}
