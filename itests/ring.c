/* tpu-acx integration test: stream-enqueued ring exchange.
 *
 * Coverage parity with reference test/src/ring.c:74-142 — enqueued
 * Isend/Irecv with (a) on-queue waits + queue sync and (b) host waits, full
 * MPI_Status field validation both times — written for the tpu-acx host
 * execution queue. Run under `acxrun -np N`.
 */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

static int check_status(int rank, const MPI_Status *st, int want_src,
                        int want_tag) {
    int errs = 0;
    if (st->MPI_SOURCE != want_src) {
        printf("[%d] bad status source %d, want %d\n", rank, st->MPI_SOURCE,
               want_src);
        errs++;
    }
    if (st->MPI_TAG != want_tag) {
        printf("[%d] bad status tag %d, want %d\n", rank, st->MPI_TAG,
               want_tag);
        errs++;
    }
    if (st->MPI_ERROR != MPI_SUCCESS) {
        printf("[%d] bad status error %d\n", rank, st->MPI_ERROR);
        errs++;
    }
    return errs;
}

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);

    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;
    int send_val = rank * 7 + 1;
    int recv_val;
    MPIX_Request req[2];
    MPI_Status status;
    cudaStream_t stream = 0; /* default queue */

    /* Phase 1: waits on the queue, then sync. */
    recv_val = -1;
    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 0, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 0, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Wait_enqueue(&req[0], MPI_STATUS_IGNORE, MPIX_QUEUE_XLA_STREAM,
                      &stream);
    MPIX_Wait_enqueue(&req[1], &status, MPIX_QUEUE_XLA_STREAM, &stream);
    if (cudaStreamSynchronize(stream) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);

    if (recv_val != left * 7 + 1) {
        printf("[%d] phase1: got %d, want %d\n", rank, recv_val, left * 7 + 1);
        errs++;
    }
    errs += check_status(rank, &status, left, 0);

    /* Phase 2: enqueue triggers, wait on the host. */
    recv_val = -1;
    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 1, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 1, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Wait(&req[0], MPI_STATUS_IGNORE);
    MPIX_Wait(&req[1], &status);

    if (recv_val != left * 7 + 1) {
        printf("[%d] phase2: got %d, want %d\n", rank, recv_val, left * 7 + 1);
        errs++;
    }
    errs += check_status(rank, &status, left, 1);

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("ring: OK\n");
    return errs != 0;
}
