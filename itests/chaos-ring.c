/* tpu-acx integration test: ring exchange under wire-level chaos.
 *
 * Every rank sends a patterned int array (256 ints by default;
 * ACX_CHAOS_INTS overrides — `make stripe-check` uses 16384 = 64 KiB so
 * messages cross the striping floor and fan out across subflows) right
 * and receives from the left for ACX_CHAOS_ROUNDS rounds, verifying
 * every payload byte-exactly.
 * Run fault-free it is a plain stress ring; run with a wire-level
 * ACX_FAULT spec (drop_frame / corrupt_frame / stall_link_ms /
 * close_link_once, armed via `acxrun -fault ... -transport socket`) it
 * asserts the survivable-link machinery of DESIGN.md §9: CRC rejects and
 * sequence gaps get NAKed and re-pulled from the replay buffer, a closed
 * link reconnects with a bumped epoch and replays unacked frames — and
 * every delivered payload is still byte-identical. Run under `acxrun`.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include <mpi.h>
#include <mpi-acx.h>

#define N 256

static int expect(int rank, int round, int i) {
    return rank * 1000003 + round * 8191 + i * 7 + 1;
}

int main(int argc, char **argv) {
    /* Heartbeats must be armed before the transport exists: the tail-loss
     * NAK (a dropped FINAL frame with no traffic behind it) heals off the
     * heartbeat's tx high-water mark. */
    setenv("ACX_HEARTBEAT_MS", "25", 1);
    setenv("ACX_PEER_TIMEOUT_MS", "2000", 1);
    setenv("ACX_PEER_GRACE_MS", "2000", 1);

    int provided, rank, size, errs = 0;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);

    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    /* Failsafe well under acxrun's job timeout: if recovery ever wedges,
     * ops fail with TIMEOUT and the test reports instead of hanging. */
    MPIX_Set_deadline(20000);

    int rounds = 30;
    const char *r_s = getenv("ACX_CHAOS_ROUNDS");
    if (r_s != NULL && atoi(r_s) > 0) rounds = atoi(r_s);
    int n = N;
    const char *n_s = getenv("ACX_CHAOS_INTS");
    if (n_s != NULL && atoi(n_s) > 0) n = atoi(n_s);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;
    int *sbuf = (int *)malloc((size_t)n * sizeof(int));
    int *rbuf = (int *)malloc((size_t)n * sizeof(int));
    if (sbuf == NULL || rbuf == NULL) MPI_Abort(MPI_COMM_WORLD, 3);
    cudaStream_t stream = 0;

    for (int round = 0; round < rounds; round++) {
        int i;
        for (i = 0; i < n; i++) {
            sbuf[i] = expect(rank, round, i);
            rbuf[i] = -1;
        }
        MPIX_Request req[2];
        MPI_Status st;
        MPIX_Isend_enqueue(sbuf, n, MPI_INT, right, round, MPI_COMM_WORLD,
                           &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
        MPIX_Irecv_enqueue(rbuf, n, MPI_INT, left, round, MPI_COMM_WORLD,
                           &req[1], MPIX_QUEUE_XLA_STREAM, &stream);
        MPIX_Wait(&req[0], MPI_STATUS_IGNORE);
        MPIX_Wait(&req[1], &st);
        if (st.MPI_ERROR != MPI_SUCCESS) {
            printf("[%d] round %d: recv status error %d\n", rank, round,
                   st.MPI_ERROR);
            errs++;
            break;
        }
        /* Zero payload corruption, ever: a CRC-rejected or replayed frame
         * must deliver byte-identical data on the re-pull. */
        for (i = 0; i < n; i++) {
            if (rbuf[i] != expect(left, round, i)) {
                printf("[%d] round %d: rbuf[%d] = %d, want %d\n", rank,
                       round, i, rbuf[i], expect(left, round, i));
                errs++;
                break;
            }
        }
        if (errs) break;
    }

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Set_deadline(0);
    free(sbuf);
    free(rbuf);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("chaos-ring: OK\n");
    return errs != 0;
}
