/* Multi-threaded enqueue stress: T app threads per rank concurrently
 * allocate slots, enqueue isend/irecv pairs, and host-wait, while the
 * proxy progresses.  The reference's slot allocator is explicitly
 * single-thread-only (its triggered.cpp FIXME); ours claims lock-free
 * thread safety — this program, run under `make check` (all transport
 * matrix rows) and `make tsan`, is the proof.
 *
 * Each (rank, thread, round) uses payload = rank*1e6 + thread*1e3 + round
 * on tag = thread*ROUNDS + round, so any cross-thread matching confusion
 * is caught by value.
 */
#include <mpi.h>
#include <mpi-acx.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>

#define THREADS 4
#define ROUNDS 32

static int g_rank, g_peer;
static int g_errs[THREADS];

static void* worker(void* arg) {
  int tid = (int)(long)arg;
  cudaStream_t s0 = 0;
  for (int r = 0; r < ROUNDS; r++) {
    int tag = tid * ROUNDS + r;
    int sendv = g_rank * 1000000 + tid * 1000 + r;
    int recvv = -1;
    MPIX_Request req[2];
    if (MPIX_Isend_enqueue(&sendv, 1, MPI_INT, g_peer, tag, MPI_COMM_WORLD,
                           &req[0], MPIX_QUEUE_XLA_STREAM, &s0) ||
        MPIX_Irecv_enqueue(&recvv, 1, MPI_INT, g_peer, tag, MPI_COMM_WORLD,
                           &req[1], MPIX_QUEUE_XLA_STREAM, &s0)) {
      /* Fail loudly: a silent return would leave the peer's matching
       * thread blocked in MPIX_Wait until the launcher timeout masks
       * the real error. */
      fprintf(stderr, "rank %d tid %d round %d: enqueue failed\n", g_rank,
              tid, r);
      MPI_Abort(MPI_COMM_WORLD, 3);
    }
    MPI_Status st;
    MPIX_Wait(&req[1], &st);
    MPIX_Wait(&req[0], MPI_STATUS_IGNORE);
    int want = g_peer * 1000000 + tid * 1000 + r;
    if (recvv != want) {
      fprintf(stderr, "rank %d tid %d round %d: got %d want %d\n", g_rank,
              tid, r, recvv, want);
      g_errs[tid]++;
    }
    if (st.MPI_TAG != tag || st.MPI_SOURCE != g_peer) {
      fprintf(stderr, "rank %d tid %d: bad status tag=%d src=%d\n", g_rank,
              tid, st.MPI_TAG, st.MPI_SOURCE);
      g_errs[tid]++;
    }
  }
  return NULL;
}

int main(int argc, char** argv) {
  int provided, size;
  MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
  MPI_Comm_rank(MPI_COMM_WORLD, &g_rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (size % 2 != 0) {
    if (g_rank == 0)
      fprintf(stderr, "concurrent-stress needs an even -np\n");
    MPI_Abort(MPI_COMM_WORLD, 2);
  }
  g_peer = g_rank ^ 1;   /* xor pairing: (0,1), (2,3), ... */
  if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

  pthread_t th[THREADS];
  for (long t = 0; t < THREADS; t++)
    pthread_create(&th[t], NULL, worker, (void*)t);
  int errs = 0;
  for (int t = 0; t < THREADS; t++) {
    pthread_join(th[t], NULL);
    errs += g_errs[t];
  }

  int total = errs;
  MPI_Allreduce(&errs, &total, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
  if (g_rank == 0)
    printf(total == 0 ? "concurrent-stress: OK\n"
                      : "concurrent-stress: FAIL\n");
  MPIX_Finalize();
  MPI_Finalize();
  return total != 0;
}
