/* tpu-acx integration test: flag-table exhaustion end to end.
 *
 * SURVEY.md §4 lists "no slot-exhaustion test" among the reference's
 * coverage gaps (its allocator FIXME at triggered.cpp:40-44 was never
 * exercised at the API boundary). Here the table is shrunk to 8 slots
 * (ACX_NFLAGS, set before MPIX_Init reads it), filled with pending
 * receives, and the 9th enqueue must fail CLEANLY: nonzero return,
 * request handed back as MPIX_REQUEST_NULL, no crash, no corruption of
 * the 8 live ops. After the live ops complete, their slots must be
 * reclaimed — a fresh enqueue succeeds and delivers.
 *
 * Ranks 2+ (the np=4 matrix row) idle through the barriers.
 */
#include <stdio.h>
#include <stdlib.h>
#include <unistd.h>
#include <mpi.h>
#include <mpi-acx.h>

#define NSLOTS 8

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0, i;

    /* Must precede MPIX_Init, which sizes the table from the env. */
    setenv("ACX_NFLAGS", "8", 1);

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    if (rank == 0) {
        int buf[NSLOTS + 1];
        MPIX_Request req[NSLOTS + 1];
        MPI_Status st;
        cudaStream_t stream;
        cudaStreamCreate(&stream);

        /* Fill every slot with a pending receive from rank 1. */
        for (i = 0; i < NSLOTS; i++) {
            buf[i] = -1;
            if (MPIX_Irecv_enqueue(&buf[i], 1, MPI_INT, 1, 30 + i,
                                   MPI_COMM_WORLD, &req[i],
                                   MPIX_QUEUE_XLA_STREAM,
                                   &stream) != MPI_SUCCESS) {
                printf("[0] enqueue %d failed with table not full\n", i);
                errs++;
            }
        }

        /* Table full: the next enqueue must fail loudly-but-cleanly. */
        buf[NSLOTS] = -1;
        req[NSLOTS] = (MPIX_Request)&errs;   /* poison: must be reset */
        if (MPIX_Irecv_enqueue(&buf[NSLOTS], 1, MPI_INT, 1, 30 + NSLOTS,
                               MPI_COMM_WORLD, &req[NSLOTS],
                               MPIX_QUEUE_XLA_STREAM,
                               &stream) == MPI_SUCCESS) {
            printf("[0] enqueue past ACX_NFLAGS unexpectedly succeeded\n");
            errs++;
        }
        if (req[NSLOTS] != MPIX_REQUEST_NULL) {
            printf("[0] failed enqueue left a non-NULL request\n");
            errs++;
        }

        MPI_Barrier(MPI_COMM_WORLD);        /* rank 1 sends the 8 */

        for (i = 0; i < NSLOTS; i++) {
            if (MPIX_Wait(&req[i], &st) != MPI_SUCCESS) errs++;
            if (buf[i] != 100 + i) {
                printf("[0] recv %d: got %d want %d\n", i, buf[i], 100 + i);
                errs++;
            }
        }

        /* Slots reclaimed: a fresh enqueue must succeed. Reclamation
         * may ride the proxy sweep, so allow it a few milliseconds. */
        {
            int tries = 0, rc;
            do {
                rc = MPIX_Irecv_enqueue(&buf[NSLOTS], 1, MPI_INT, 1,
                                        30 + NSLOTS, MPI_COMM_WORLD,
                                        &req[NSLOTS],
                                        MPIX_QUEUE_XLA_STREAM, &stream);
                if (rc != MPI_SUCCESS) usleep(1000);
            } while (rc != MPI_SUCCESS && ++tries < 2000);
            if (rc != MPI_SUCCESS) {
                printf("[0] enqueue after reclamation never succeeded\n");
                errs++;
            }
        }
        MPI_Barrier(MPI_COMM_WORLD);        /* rank 1 sends the last */
        if (MPIX_Wait(&req[NSLOTS], &st) != MPI_SUCCESS) errs++;
        if (buf[NSLOTS] != 100 + NSLOTS) {
            printf("[0] post-reclaim recv: got %d want %d\n", buf[NSLOTS],
                   100 + NSLOTS);
            errs++;
        }
        cudaStreamDestroy(stream);
    } else if (rank == 1) {
        MPI_Barrier(MPI_COMM_WORLD);
        for (i = 0; i < NSLOTS; i++) {
            int v = 100 + i;
            MPI_Send(&v, 1, MPI_INT, 0, 30 + i, MPI_COMM_WORLD);
        }
        MPI_Barrier(MPI_COMM_WORLD);
        {
            int v = 100 + NSLOTS;
            MPI_Send(&v, 1, MPI_INT, 0, 30 + NSLOTS, MPI_COMM_WORLD);
        }
    } else {
        MPI_Barrier(MPI_COMM_WORLD);
        MPI_Barrier(MPI_COMM_WORLD);
    }

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("slot-exhaustion: OK\n");
    return errs != 0;
}
