/* tpu-acx integration test: rolling restart of the whole fleet under load
 * (DESIGN.md §12 — the elastic-fleet capstone).
 *
 * Every rank is replaced one at a time, rank 0 last. In each round the
 * victim drains and leaves gracefully (MPIX_Fleet_leave), then forks a
 * replacement that execs this same binary with ACX_JOIN=1 and no inherited
 * fds — the replacement bootstraps every link through the peers'
 * ACX_JOB_ID rendezvous listeners with a JOIN handshake while the
 * original process stays behind as a supervisor, waiting to chain the
 * replacement's verdict up to acxrun. Meanwhile the survivors keep
 * traffic flowing among themselves (continuous service through the
 * outage), wait for their own adoption of the new incarnation, and then
 * the FULL ring — replacement included — exchanges byte-verified
 * payloads. Asserted every round, on every rank: zero payload loss or
 * corruption, the local fleet epoch strictly increasing, and a
 * fully-ACTIVE membership view after the join settles.
 *
 * Wedged-join leg (ACX_RR_WEDGE=1): the first replacement execs with a
 * poisoned ACX_JOB_ID so its JOIN can never rendezvous. Survivors time
 * out waiting for the slot to come back, dump flight state
 * (MPIX_Dump_state) and exit 7; the replacement exits 13 without ever
 * writing a dump — which is exactly the missing-dump-as-evidence case
 * tools/acx_doctor.py must attribute (tests/test_fleet.py drives this).
 *
 * Needs the socket plane and an ACX_JOB_ID (the rendezvous namespace);
 * on any other configuration it reports OK and exits 0 so the
 * all-planes `make check` matrix can run it unconditionally.
 * Run under `acxrun -np N -transport socket`.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <mpi.h>
#include <mpi-acx.h>

#ifdef __cplusplus
extern "C" {
#endif
void acx_fleet_stats(uint64_t *out);
#ifdef __cplusplus
}
#endif

extern char **environ;

#define N_PAYLOAD 256
#define MAX_RANKS 16

static int expect(int rank, int round, int i) {
    return rank * 1000003 + round * 8191 + i * 7 + 1;
}

static uint64_t now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000u + (uint64_t)(ts.tv_nsec / 1000000);
}

/* Build the replacement's environment: strip the inherited wiring
 * (ACX_FDS / ACX_SHM_FD — the fds themselves are CLOEXEC and will not
 * survive the exec), arm the JOIN path, and tell the new incarnation
 * which round it is joining into. Built in the parent BEFORE fork so the
 * post-fork child only execs (no allocation in the forked child of a
 * multithreaded process). */
static char **make_join_env(int round, int wedge) {
    int n = 0;
    while (environ[n] != NULL) n++;
    char **env = (char **)malloc((size_t)(n + 4) * sizeof(char *));
    int m = 0;
    for (int i = 0; i < n; i++) {
        const char *e = environ[i];
        if (strncmp(e, "ACX_FDS=", 8) == 0) continue;
        if (strncmp(e, "ACX_SHM_FD=", 11) == 0) continue;
        if (strncmp(e, "ACX_JOIN=", 9) == 0) continue;
        if (strncmp(e, "ACX_RR_RESUME=", 14) == 0) continue;
        if (wedge && strncmp(e, "ACX_JOB_ID=", 11) == 0) continue;
        env[m++] = (char *)e;
    }
    static char join_kv[] = "ACX_JOIN=1";
    static char resume_kv[32];
    static char wedge_kv[64];
    snprintf(resume_kv, sizeof resume_kv, "ACX_RR_RESUME=%d", round);
    env[m++] = join_kv;
    env[m++] = resume_kv;
    if (wedge) {
        /* A job id nobody listens on: the JOIN can never rendezvous. */
        snprintf(wedge_kv, sizeof wedge_kv, "ACX_JOB_ID=wedged-%d",
                 (int)getpid());
        env[m++] = wedge_kv;
    }
    env[m] = NULL;
    return env;
}

/* Full-fleet ring exchange for `round`, byte-verified. Returns 0 on
 * success. */
static int full_ring(int rank, int size, int round) {
    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;
    int sbuf[N_PAYLOAD], rbuf[N_PAYLOAD];
    for (int i = 0; i < N_PAYLOAD; i++) {
        sbuf[i] = expect(rank, round, i);
        rbuf[i] = -1;
    }
    cudaStream_t stream = 0;
    MPIX_Request req[2];
    MPI_Status st;
    MPIX_Isend_enqueue(sbuf, N_PAYLOAD, MPI_INT, right, 100 + round,
                       MPI_COMM_WORLD, &req[0], MPIX_QUEUE_XLA_STREAM,
                       &stream);
    MPIX_Irecv_enqueue(rbuf, N_PAYLOAD, MPI_INT, left, 100 + round,
                       MPI_COMM_WORLD, &req[1], MPIX_QUEUE_XLA_STREAM,
                       &stream);
    MPIX_Wait(&req[0], MPI_STATUS_IGNORE);
    MPIX_Wait(&req[1], &st);
    if (st.MPI_ERROR != MPI_SUCCESS) {
        printf("[%d] round %d: verify recv error %d\n", rank, round,
               st.MPI_ERROR);
        return 1;
    }
    for (int i = 0; i < N_PAYLOAD; i++) {
        if (rbuf[i] != expect(left, round, i)) {
            printf("[%d] round %d: rbuf[%d] = %d, want %d\n", rank, round,
                   i, rbuf[i], expect(left, round, i));
            return 1;
        }
    }
    return 0;
}

/* Ring among the survivors of `victim` — the injected load that must keep
 * completing while the slot is empty. Fixed iteration count so every
 * survivor posts exactly the same ops. */
static int survivor_ring(int rank, int size, int victim, int round) {
    int alive[MAX_RANKS], nsurv = 0, idx = -1;
    for (int r = 0; r < size; r++) {
        if (r == victim) continue;
        if (r == rank) idx = nsurv;
        alive[nsurv++] = r;
    }
    if (nsurv < 2) return 0;
    const int right = alive[(idx + 1) % nsurv];
    const int left = alive[(idx + nsurv - 1) % nsurv];
    cudaStream_t stream = 0;
    for (int it = 0; it < 3; it++) {
        int sv = rank * 31 + round * 7 + it, rv = -1;
        MPIX_Request req[2];
        MPI_Status st;
        MPIX_Isend_enqueue(&sv, 1, MPI_INT, right, 200 + round * 8 + it,
                           MPI_COMM_WORLD, &req[0], MPIX_QUEUE_XLA_STREAM,
                           &stream);
        MPIX_Irecv_enqueue(&rv, 1, MPI_INT, left, 200 + round * 8 + it,
                           MPI_COMM_WORLD, &req[1], MPIX_QUEUE_XLA_STREAM,
                           &stream);
        MPIX_Wait(&req[0], MPI_STATUS_IGNORE);
        MPIX_Wait(&req[1], &st);
        if (st.MPI_ERROR != MPI_SUCCESS || rv != left * 31 + round * 7 + it) {
            printf("[%d] round %d: survivor ring broken (err %d, got %d)\n",
                   rank, round, st.MPI_ERROR, rv);
            return 1;
        }
    }
    return 0;
}

/* All-to-all token exchange: returns 0 when every peer's token arrived.
 * Used (twice) to fence the final membership assertion: after one round
 * everyone has reached the fence; after the check a second round keeps
 * every process alive until every CHECK has run, so nobody's teardown EOF
 * flips a slot to LEFT under a peer still asserting all-ACTIVE. */
static int token_fence(int rank, int size, int tag) {
    cudaStream_t stream = 0;
    static int token;
    token = tag;
    MPIX_Request req[2 * MAX_RANKS];
    int rbuf[MAX_RANKS];
    int n = 0;
    for (int r = 0; r < size; r++) {
        if (r == rank) continue;
        MPIX_Isend_enqueue(&token, 1, MPI_INT, r, tag, MPI_COMM_WORLD,
                           &req[n++], MPIX_QUEUE_XLA_STREAM, &stream);
        rbuf[r] = -1;
        MPIX_Irecv_enqueue(&rbuf[r], 1, MPI_INT, r, tag, MPI_COMM_WORLD,
                           &req[n++], MPIX_QUEUE_XLA_STREAM, &stream);
    }
    for (int i = 0; i < n; i++) {
        MPI_Status st;
        MPIX_Wait(&req[i], &st);
        if (st.MPI_ERROR != MPI_SUCCESS) {
            printf("[%d] fence %d: op error %d\n", rank, tag, st.MPI_ERROR);
            return 1;
        }
    }
    for (int r = 0; r < size; r++) {
        if (r != rank && rbuf[r] != tag) {
            printf("[%d] fence %d: token from %d = %d\n", rank, tag, r,
                   rbuf[r]);
            return 1;
        }
    }
    return 0;
}

/* The replacement announces itself to every survivor right after its JOIN
 * completes. A DELIVERED hello is the race-free adoption signal: frames
 * from the new incarnation can only arrive over the link our transport
 * installed when it accepted the JOIN dial, so receiving one proves our
 * slot points at the replacement — membership polling alone cannot (a
 * fanned-out VIEW can mark the slot ACTIVE before the joiner dials us). */
static void send_join_hellos(int rank, int size, int round) {
    cudaStream_t stream = 0;
    static int token;
    token = round;
    for (int r = 0; r < size; r++) {
        if (r == rank) continue;
        MPIX_Request req;
        MPIX_Isend_enqueue(&token, 1, MPI_INT, r, 900 + round,
                           MPI_COMM_WORLD, &req, MPIX_QUEUE_XLA_STREAM,
                           &stream);
        MPIX_Wait(&req, MPI_STATUS_IGNORE);
    }
}

/* Survivor side: wait (bounded) for the replacement's hello. While the
 * victim's graceful LEFT is still latched, posts against the slot complete
 * immediately with MPIX_ERR_PEER_DEAD — retry until the JOIN lands. On a
 * wedged join nothing ever arrives: dump flight state for the hang doctor
 * and fail. */
static void await_join_hello(int rank, int round, int victim,
                             uint64_t wait_ms) {
    cudaStream_t stream = 0;
    const uint64_t deadline = now_ms() + wait_ms;
    for (;;) {
        const uint64_t left_ms = deadline > now_ms() ? deadline - now_ms() : 1;
        MPIX_Set_deadline((double)left_ms);
        int token = -1;
        MPIX_Request req;
        MPI_Status st;
        MPIX_Irecv_enqueue(&token, 1, MPI_INT, victim, 900 + round,
                           MPI_COMM_WORLD, &req, MPIX_QUEUE_XLA_STREAM,
                           &stream);
        MPIX_Wait(&req, &st);
        if (st.MPI_ERROR == MPI_SUCCESS) {
            if (token != round) {
                printf("[%d] round %d: join hello token %d, want %d\n",
                       rank, round, token, round);
                fflush(stdout);
                _exit(9);
            }
            MPIX_Set_deadline(30000); /* restore the failsafe */
            return;
        }
        if (now_ms() >= deadline) {
            printf("[%d] round %d: replacement for rank %d never joined "
                   "(%llums, last err %d); dumping flight state\n",
                   rank, round, victim, (unsigned long long)wait_ms,
                   st.MPI_ERROR);
            fflush(stdout);
            MPIX_Dump_state();
            _exit(7);
        }
        usleep(5000); /* slot still LEFT-latched; retry until adoption */
    }
}

int main(int argc, char **argv) {
    (void)argc;
    /* Socket plane + a rendezvous namespace or there is nothing to test;
     * report OK so the all-planes `make check` matrix can include us. */
    const char *want = getenv("ACX_TRANSPORT");
    const int socket_plane =
        (want != NULL && strcmp(want, "socket") == 0) ||
        getenv("ACX_SHM_FD") == NULL;
    if (!socket_plane || getenv("ACX_JOB_ID") == NULL) {
        const char *r_s = getenv("ACX_RANK");
        if (r_s == NULL || atoi(r_s) == 0)
            printf("rolling-restart: OK (skipped: needs socket plane + "
                   "ACX_JOB_ID)\n");
        return 0;
    }

    int provided, rank, size, errs = 0;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (size < 2 || size > MAX_RANKS) {
        printf("rolling-restart: needs 2..%d ranks\n", MAX_RANKS);
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);
    MPIX_Set_deadline(30000); /* failsafe under acxrun's job timeout */

    const int wedge = getenv("ACX_RR_WEDGE") != NULL &&
                      atoi(getenv("ACX_RR_WEDGE")) != 0;
    const char *jw_s = getenv("ACX_RR_JOIN_WAIT_MS");
    const uint64_t join_wait_ms =
        jw_s != NULL && atoi(jw_s) > 0 ? (uint64_t)atoi(jw_s)
                                       : (wedge ? 6000u : 20000u);
    const char *resume_s = getenv("ACX_RR_RESUME");
    const int resume = resume_s != NULL ? atoi(resume_s) : -1;

    /* Round r replaces victim (r + 1) % size — rank 0 goes last. A
     * replacement (resume >= 0) joined DURING round `resume`: it skips
     * the leave/outage phases of that round, announces itself, and goes
     * straight to the full-ring verify. */
    for (int round = resume >= 0 ? resume : 0; round < size; round++) {
        const int victim = (round + 1) % size;
        const int joined_this_round = (resume == round);

        if (joined_this_round) {
            send_join_hellos(rank, size, round);
        } else if (rank == victim) {
            /* Graceful exit: drain, announce LEFT, surrender the
             * listener — then hand the slot to a fresh incarnation and
             * stay behind only to chain its verdict to acxrun (which
             * waits on its direct children, not grandchildren). */
            const int cancelled = MPIX_Fleet_leave(2000);
            if (cancelled != 0) {
                printf("[%d] round %d: leave cancelled %d ops, want 0\n",
                       rank, round, cancelled);
                fflush(stdout);
                _exit(3);
            }
            char **env = make_join_env(round, wedge && round == 0);
            fflush(stdout);
            fflush(stderr);
            pid_t pid = fork();
            if (pid < 0) _exit(4);
            if (pid == 0) {
                execve(argv[0], argv, env);
                _exit(127);
            }
            int st = 0;
            while (waitpid(pid, &st, 0) < 0) {
            }
            _exit(WIFEXITED(st) ? WEXITSTATUS(st) : 128 + WTERMSIG(st));
        } else {
            /* Injected load: service among survivors keeps completing
             * while the victim's slot is down. */
            if (survivor_ring(rank, size, victim, round)) {
                fflush(stdout);
                _exit(5);
            }
            /* Then wait for our own adoption of the replacement. */
            await_join_hello(rank, round, victim, join_wait_ms);
        }

        /* Full fleet back: verify service with every rank, replacement
         * included, with a round-unique byte-checked payload. */
        if (full_ring(rank, size, round)) {
            fflush(stdout);
            _exit(8);
        }

        /* Fleet epoch floor: every completed round contributes exactly
         * two bumps to every live rank's view (the victim's LEFT — via
         * VIEW frame, quiet EOF latch, or the supersede step of JOIN
         * adoption — and the replacement's join), and a replacement
         * adopts at least its first acceptor's post-join epoch. So after
         * round r every rank must be at >= 1 + 2*(r+1). */
        const uint64_t e = MPIX_Fleet_epoch();
        const uint64_t floor_e = 1 + 2u * (uint64_t)(round + 1);
        if (e < floor_e) {
            printf("[%d] round %d: fleet epoch %llu below floor %llu\n",
                   rank, round, (unsigned long long)e,
                   (unsigned long long)floor_e);
            errs++;
            break;
        }

        /* After the LAST join settles the local view is fully ACTIVE.
         * (Intermediate rounds can't assert this: the next victim's leave
         * races with this read. And even the final read must be fenced on
         * both sides — a peer's teardown EOF flips its slot to LEFT.) */
        if (round == size - 1) {
            errs += token_fence(rank, size, 980);
            uint64_t fs[5];
            acx_fleet_stats(fs);
            if (errs == 0 && fs[4] != (uint64_t)size) {
                printf("[%d] round %d: %llu ACTIVE slots, want %d\n", rank,
                       round, (unsigned long long)fs[4], size);
                errs++;
            }
            errs += token_fence(rank, size, 981);
        }
    }

    MPIX_Finalize(); /* local teardown; no barrier — peers are chains of
                        supervisors and replacements, not one rank set */
    if (rank == 0 && errs == 0) printf("rolling-restart: OK\n");
    fflush(stdout);
    fflush(stderr);
    _exit(errs != 0);
}
