/* tpu-acx integration test: kernel-style partitioned communication.
 *
 * Coverage parity with reference test/src/ring-partitioned.cu:91-127 —
 * persistent Psend/Precv channels restarted across 10 iterations with 10
 * partitions, partitions marked ready from queue-ordered "kernels" through
 * the MPIX_Prequest device-mirror handle (out of order!), and arrival
 * polled by a *separate* queue work item (the reference's separate
 * mark_ready / wait_until_arrived kernels — its README:152-159 deadlock
 * rule). On TPU the kernels are Pallas flag ops from the Python layer; here
 * they are host-queue functions via cudaLaunchHostFunc. */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

#define PARTS 10
#define ITERS 10

static MPIX_Prequest g_preq_send, g_preq_recv;

/* "mark_ready kernel": flag every partition ready, highest index first. */
static void mark_ready(void *unused) {
    (void)unused;
    for (int p = PARTS - 1; p >= 0; p--) MPIX_Pready(p, g_preq_send);
}

/* "wait_until_arrived kernel": poll each partition until it lands. */
static void wait_until_arrived(void *unused) {
    (void)unused;
    for (int p = 0; p < PARTS; p++) {
        int flag = 0;
        while (!flag) MPIX_Parrived(g_preq_recv, p, &flag);
    }
}

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;

    int send_buf[PARTS], recv_buf[PARTS];
    MPIX_Request req[2];
    MPI_Status status[2];

    MPIX_Psend_init(send_buf, PARTS, 1, MPI_INT, right, 0, MPI_COMM_WORLD,
                    MPI_INFO_NULL, &req[0]);
    MPIX_Precv_init(recv_buf, PARTS, 1, MPI_INT, left, 0, MPI_COMM_WORLD,
                    MPI_INFO_NULL, &req[1]);
    MPIX_Prequest_create(req[0], &g_preq_send);
    MPIX_Prequest_create(req[1], &g_preq_recv);

    for (int iter = 0; iter < ITERS; iter++) {
        for (int p = 0; p < PARTS; p++) {
            send_buf[p] = rank * 1000 + p * 10 + iter;
            recv_buf[p] = -1;
        }

        MPIX_Startall(2, req);

        cudaLaunchHostFunc(0, mark_ready, NULL);
        cudaLaunchHostFunc(0, wait_until_arrived, NULL);
        if (cudaStreamSynchronize(0) != cudaSuccess)
            MPI_Abort(MPI_COMM_WORLD, 2);

        MPIX_Waitall(2, req, status);

        for (int p = 0; p < PARTS; p++) {
            const int want = left * 1000 + p * 10 + iter;
            if (recv_buf[p] != want) {
                if (errs < 3)
                    printf("[%d] iter %d part %d: got %d, want %d\n", rank,
                           iter, p, recv_buf[p], want);
                errs++;
            }
        }
    }

    MPIX_Prequest_free(&g_preq_send);
    MPIX_Prequest_free(&g_preq_recv);
    MPIX_Request_free(&req[0]);
    MPIX_Request_free(&req[1]);

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("ring-partitioned: OK\n");
    return errs != 0;
}
