/* tpu-acx integration test: sequenced ping-pong for the causal-tracing
 * plane (docs/DESIGN.md §14).
 *
 * Rank 0 sends a patterned payload to rank 1, rank 1 verifies and sends
 * it back, for ACX_PING_ROUNDS rounds — a strictly serialized causal
 * chain, so the cross-rank critical path of the run IS the ping-pong
 * itself. Every k rounds both ranks cross an MPI_Barrier: the shim's
 * barrier_exit instants are the anchors tools/acx_trace_merge.py (and
 * tools/acx_critpath.py through it) align the per-rank clocks on.
 *
 * Run under `acxrun -np 2 -transport socket` with ACX_TRACE set; `make
 * causality-check` then asserts that every data frame's span id shows up
 * on both ranks, that one-way transit is non-negative after skew
 * correction, and — with `-fault stall_link_ms:rank=0:nth=5:ms=40` —
 * that acx_critpath.py names the stalled 0->1 link as the dominant edge.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include <mpi.h>
#include <mpi-acx.h>

#define N 256
#define BARRIER_EVERY 8

static int expect(int round, int i) {
    return round * 131071 + i * 13 + 5;
}

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (size != 2) {
        /* The causal chain this test builds is a strict 2-rank relay;
         * under the generic np-4 sweep there is nothing to assert. */
        if (rank == 0) printf("causality-ping: OK (skipped: needs exactly 2 ranks)\n");
        MPI_Finalize();
        return 0;
    }

    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    /* Failsafe well under acxrun's job timeout: a wedged link fails ops
     * with TIMEOUT and the test reports instead of hanging. */
    MPIX_Set_deadline(20000);

    int rounds = 40;
    const char *r_s = getenv("ACX_PING_ROUNDS");
    if (r_s != NULL && atoi(r_s) > 0) rounds = atoi(r_s);
    /* Payload size knob: `make stripe-check` pings 64 KiB payloads so the
     * causal chain rides the striped (envelope + chunks) path. */
    int n = N;
    const char *n_s = getenv("ACX_PING_INTS");
    if (n_s != NULL && atoi(n_s) > 0) n = atoi(n_s);

    const int peer = 1 - rank;
    int *buf = (int *)malloc((size_t)n * sizeof(int));
    if (buf == NULL) MPI_Abort(MPI_COMM_WORLD, 3);
    cudaStream_t stream = 0;

    for (int round = 0; round < rounds && errs == 0; round++) {
        MPIX_Request req;
        MPI_Status st;
        int i;
        if (rank == 0) {
            for (i = 0; i < n; i++) buf[i] = expect(round, i);
            MPIX_Isend_enqueue(buf, n, MPI_INT, peer, round, MPI_COMM_WORLD,
                               &req, MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Wait(&req, MPI_STATUS_IGNORE);
            for (i = 0; i < n; i++) buf[i] = -1;
            MPIX_Irecv_enqueue(buf, n, MPI_INT, peer, round, MPI_COMM_WORLD,
                               &req, MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Wait(&req, &st);
        } else {
            for (i = 0; i < n; i++) buf[i] = -1;
            MPIX_Irecv_enqueue(buf, n, MPI_INT, peer, round, MPI_COMM_WORLD,
                               &req, MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Wait(&req, &st);
            MPIX_Isend_enqueue(buf, n, MPI_INT, peer, round, MPI_COMM_WORLD,
                               &req, MPIX_QUEUE_XLA_STREAM, &stream);
            MPIX_Wait(&req, MPI_STATUS_IGNORE);
        }
        if (st.MPI_ERROR != MPI_SUCCESS) {
            printf("[%d] round %d: status error %d\n", rank, round,
                   st.MPI_ERROR);
            errs++;
            break;
        }
        /* The echoed payload must round-trip byte-exactly. */
        for (i = 0; i < n; i++) {
            if (buf[i] != expect(round, i)) {
                printf("[%d] round %d: buf[%d] = %d, want %d\n", rank,
                       round, i, buf[i], expect(round, i));
                errs++;
                break;
            }
        }
        /* Periodic barrier = clock anchor for the offline skew fit. */
        if ((round + 1) % BARRIER_EVERY == 0)
            MPI_Barrier(MPI_COMM_WORLD);
    }

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    /* One final anchor AFTER all traffic: compute_skew aligns on the
     * LAST common barrier_exit, so this pins the whole spanned window. */
    MPI_Barrier(MPI_COMM_WORLD);
    MPIX_Set_deadline(0);
    free(buf);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("causality-ping: OK\n");
    return errs != 0;
}
