/* tpu-acx integration test: seeded randomized exercise of the MPIX
 * surface ("fuzz"). Both ranks derive the SAME schedule from a shared
 * seed (ACX_FUZZ_SEED env, default 12345), so every send has a matching
 * receive; payloads are deterministic functions of (seed, round, slot,
 * element) and verified byte-for-byte on arrival.
 *
 * Each round randomizes: message sizes (1 .. ~16K ints), tags, the number
 * of in-flight op pairs, the ENQUEUE ORDER of sends vs receives, and the
 * completion style (host MPIX_Wait vs stream MPIX_Waitall_enqueue).
 * Every 4th round runs a partitioned exchange with a random partition
 * count and a random Pready ORDER (out-of-order readiness is the
 * reference's flagship semantics). The reference has no randomized
 * tests at all (SURVEY.md §4 lists the gaps as TODOs to inherit-fix).
 */
#include <stdio.h>
#include <stdlib.h>
#include <mpi.h>
#include <mpi-acx.h>

#define MAX_PAIRS 8
#define MAX_ELEMS 16384
#define DEFAULT_ROUNDS 24   /* ACX_FUZZ_ROUNDS overrides (deep soaks) */

static unsigned long long st;
static unsigned rnd(void) {            /* xorshift64*, same on all ranks */
    st ^= st >> 12; st ^= st << 25; st ^= st >> 27;
    return (unsigned)((st * 2685821657736338717ULL) >> 33);
}

/* src is the SENDING rank: mixing it in makes cross-rank misrouting
 * (right round/slot, wrong source) visible to the verifier, which
 * checks against its left neighbor's rank. */
static int payload(unsigned seed, int round, int slot, int i, int src) {
    return (int)(seed ^ (round * 2654435761u) ^ (slot * 40503u) ^ i
                 ^ (src * 0x85EBCA6Bu));
}

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const char *se = getenv("ACX_FUZZ_SEED");
    unsigned seed = se ? (unsigned)strtoul(se, NULL, 10) : 12345u;
    st = seed * 0x9E3779B97F4A7C15ULL + 1;
    /* Negative control: with ACX_FUZZ_CANARY=1, rank 0 deliberately
     * corrupts one received element in round 0 and the run SUCCEEDS
     * only if the verifier catches it — proving the harness can see
     * corruption, not just confirm clean runs. */
    const char *ce = getenv("ACX_FUZZ_CANARY");
    int canary = ce && atoi(ce);
    const char *re = getenv("ACX_FUZZ_ROUNDS");
    int rounds = re ? atoi(re) : DEFAULT_ROUNDS;
    if (rounds < 1) rounds = DEFAULT_ROUNDS;
    if (rank == 0) printf("fuzz: seed=%u rounds=%d canary=%d\n",
                          seed, rounds, canary);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;
    static int sbuf[MAX_PAIRS][MAX_ELEMS], rbuf[MAX_PAIRS][MAX_ELEMS];

    cudaStream_t stream;
    cudaStreamCreate(&stream);

    for (int round = 0; round < rounds; round++) {
        if (round % 4 == 3) {
            /* -- partitioned round: random partitions, random Pready order */
            int nparts = 1 + (int)(rnd() % 8);
            int per = 1 + (int)(rnd() % 256);
            int n = nparts * per;
            MPIX_Request sreq, rreq;
            MPIX_Psend_init(sbuf[0], nparts, per, MPI_INT, right, round,
                            MPI_COMM_WORLD, MPI_INFO_NULL, &sreq);
            MPIX_Precv_init(rbuf[0], nparts, per, MPI_INT, left, round,
                            MPI_COMM_WORLD, MPI_INFO_NULL, &rreq);
            int reps = 1 + (int)(rnd() % 3);     /* persistent restart */
            for (int it = 0; it < reps; it++) {
                /* Rep-dependent payload + cleared rbuf: every RESTART
                 * must deliver fresh bytes, not coast on rep 0's. */
                for (int i = 0; i < n; i++) {
                    sbuf[0][i] = payload(seed, round, 0, i, rank)
                                 ^ (it * 40961);
                    rbuf[0][i] = -1;
                }
                MPIX_Request both[2] = {sreq, rreq};
                MPIX_Startall(2, both);
                /* Fisher-Yates over partition indices = random order. */
                int order[8];
                for (int p = 0; p < nparts; p++) order[p] = p;
                for (int p = nparts - 1; p > 0; p--) {
                    int j = (int)(rnd() % (unsigned)(p + 1));
                    int t = order[p]; order[p] = order[j]; order[j] = t;
                }
                for (int p = 0; p < nparts; p++)
                    MPIX_Pready(order[p], sreq);
                MPI_Status stt[2];
                MPIX_Waitall(2, both, stt);
                for (int i = 0; i < n; i++) {
                    if (rbuf[0][i] !=
                        (payload(seed, round, 0, i, left) ^ (it * 40961))) {
                        errs++;
                        if (errs < 5)
                            printf("[%d] r%d rep %d part elem %d: got %d\n",
                                   rank, round, it, i, rbuf[0][i]);
                        break;
                    }
                }
            }
            MPIX_Request_free(&sreq);
            MPIX_Request_free(&rreq);
            continue;
        }

        /* -- enqueued round: random pair count/sizes/order/wait style -- */
        int pairs = 1 + (int)(rnd() % MAX_PAIRS);
        int elems[MAX_PAIRS], tags[MAX_PAIRS];
        for (int p = 0; p < pairs; p++) {
            elems[p] = 1 + (int)(rnd() % MAX_ELEMS);
            tags[p] = 100 + (int)(rnd() % 64) + 64 * p; /* unique per slot */
            for (int i = 0; i < elems[p]; i++)
                sbuf[p][i] = payload(seed, round, p, i, rank);
            for (int i = 0; i < elems[p]; i++) rbuf[p][i] = -1;
        }
        MPIX_Request reqs[2 * MAX_PAIRS];
        int recv_first = (int)(rnd() % 2);
        int wait_on_stream = (int)(rnd() % 2);
        for (int pass = 0; pass < 2; pass++) {
            int do_recv = (pass == 0) == (recv_first == 1);
            for (int p = 0; p < pairs; p++) {
                if (do_recv)
                    MPIX_Irecv_enqueue(rbuf[p], elems[p], MPI_INT, left,
                                       tags[p], MPI_COMM_WORLD,
                                       &reqs[2 * p + 1],
                                       MPIX_QUEUE_XLA_STREAM, &stream);
                else
                    MPIX_Isend_enqueue(sbuf[p], elems[p], MPI_INT, right,
                                       tags[p], MPI_COMM_WORLD,
                                       &reqs[2 * p],
                                       MPIX_QUEUE_XLA_STREAM, &stream);
            }
        }
        if (wait_on_stream) {
            MPIX_Waitall_enqueue(2 * pairs, reqs, MPI_STATUSES_IGNORE,
                                 MPIX_QUEUE_XLA_STREAM, &stream);
            cudaStreamSynchronize(stream);
        } else {
            cudaStreamSynchronize(stream);     /* triggers fired */
            MPIX_Waitall(2 * pairs, reqs, MPI_STATUSES_IGNORE);
        }
        if (canary && round == 0 && rank == 0)
            rbuf[0][0] ^= 0x5A5A5A5A;
        for (int p = 0; p < pairs; p++) {
            for (int i = 0; i < elems[p]; i++) {
                if (rbuf[p][i] != payload(seed, round, p, i, left)) {
                    errs++;
                    if (errs < 5)
                        printf("[%d] r%d pair %d elem %d: got %d want %d\n",
                               rank, round, p, i, rbuf[p][i],
                               payload(seed, round, p, i, left));
                    break;
                }
            }
        }
    }

    cudaStreamDestroy(stream);
    MPIX_Finalize();
    int total = 0;
    MPI_Allreduce(&errs, &total, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPI_Finalize();
    int failed = canary ? (total == 0) : (total != 0);
    if (rank == 0)
        printf("fuzz: %s%s\n", failed ? "FAILED" : "OK",
               canary ? " (canary)" : "");
    return failed ? 1 : 0;
}
