/* tpu-acx integration test: capture → instantiate → relaunch (re-fire).
 *
 * Coverage parity with reference test/src/ring-all-graph.c:74-101: capture
 * an enqueued exchange into a graph, relaunch it world_size times with a
 * send<-recv copy between launches, and expect each rank's value to travel
 * the whole ring back to it. Exercises per-launch re-firing of graph-owned
 * ops and cleanup tied to graph/exec lifetime. */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;
    int send_val = rank + 1, recv_val = -1;
    MPIX_Request req[2];
    cudaStream_t stream;
    cudaGraph_t graph;

    if (cudaStreamCreate(&stream) != cudaSuccess) MPI_Abort(MPI_COMM_WORLD, 2);
    if (cudaStreamBeginCapture(stream, cudaStreamCaptureModeGlobal) !=
        cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);

    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 5, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 5, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Waitall_enqueue(2, req, MPI_STATUSES_IGNORE, MPIX_QUEUE_XLA_STREAM,
                         &stream);

    if (cudaStreamEndCapture(stream, &graph) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);

    cudaGraphExec_t exec;
    if (cudaGraphInstantiate(&exec, graph, NULL, NULL, 0) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);

    /* Circulate: after `size` launches my own value is back. */
    for (int i = 0; i < size; i++) {
        cudaGraphLaunch(exec, stream);
        cudaMemcpyAsync(&send_val, &recv_val, sizeof(int),
                        cudaMemcpyHostToHost, stream);
    }
    cudaStreamSynchronize(stream);

    cudaGraphExecDestroy(exec);
    cudaGraphDestroy(graph);
    cudaStreamDestroy(stream);

    if (recv_val != rank + 1) {
        printf("[%d] got %d after full circulation, want %d\n", rank,
               recv_val, rank + 1);
        errs++;
    }

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("ring-all-graph: OK\n");
    return errs != 0;
}
