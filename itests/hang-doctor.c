/* tpu-acx integration test: stall watchdog + flight dumps + hang doctor.
 *
 * Builds a real cross-rank hang and asserts the observability plane turns
 * it into evidence: rank 0 opens a 2-partition Psend channel to rank 1 but
 * publishes only partition 0, so rank 1's partition-1 arrival poll can
 * never complete; rank 0 additionally posts a recv (tag 9) that rank 1
 * only answers at the very end, so BOTH ranks hold a hopeless in-flight op.
 * With ACX_STALL_WARN_MS/ACX_HANG_DUMP_MS tightened, each rank's stall
 * watchdog must trip and write <ACX_FLIGHT>.rank<r>.flight.json while the
 * job is wedged. Once both dump files exist the test un-wedges itself
 * (Pready of partition 1, then the tag-9 reply), verifies the payload, and
 * exits clean — the hang was real but bounded.
 *
 * `make doctor-check` re-runs this binary with ACX_FLIGHT pointed into
 * build/ and feeds the two dumps to tools/acx_doctor.py, which must name
 * the anomaly (never_published_partition) and the culprit (rank 0). In the
 * generic `make check` legs the test manages its own /tmp prefix and
 * removes the dumps on success. Ranks >= 2 (np=4 leg) idle through
 * finalize. Run under `acxrun -np N`.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <mpi.h>
#include <mpi-acx.h>

#ifdef __cplusplus
extern "C" {
#endif
void acx_flight_stats(uint64_t *out);
#ifdef __cplusplus
}
#endif

#define PARTS 2
#define PART_INTS 4
#define DONE_TAG 9

/* Block until `path` exists non-empty, up to max_ms. */
static int wait_for_file(const char *path, int max_ms) {
    for (int waited = 0; waited < max_ms; waited += 20) {
        struct stat st;
        if (stat(path, &st) == 0 && st.st_size > 0) return 1;
        usleep(20 * 1000);
    }
    return 0;
}

int main(int argc, char **argv) {
    /* Tight watchdog so the deliberate hang converts to dumps quickly;
     * must be set before the runtime latches the thresholds. */
    setenv("ACX_STALL_WARN_MS", "150", 1);
    setenv("ACX_HANG_DUMP_MS", "400", 1);
    /* Dump prefix: keep the caller's (make doctor-check inspects the
     * files); otherwise use a job-scoped /tmp prefix we clean up. */
    int own_prefix = getenv("ACX_FLIGHT") == NULL;
    if (own_prefix) {
        const char *job = getenv("ACX_JOB_ID");
        char prefix[256];
        snprintf(prefix, sizeof prefix, "/tmp/hang-doctor-%s",
                 job != NULL ? job : "solo");
        setenv("ACX_FLIGHT", prefix, 1);
    }

    int provided, rank, size, errs = 0;
    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (size < 2) {
        printf("hang-doctor: needs >= 2 ranks\n");
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    char dump0[512], dump1[512];
    snprintf(dump0, sizeof dump0, "%s.rank0.flight.json",
             getenv("ACX_FLIGHT"));
    snprintf(dump1, sizeof dump1, "%s.rank1.flight.json",
             getenv("ACX_FLIGHT"));

    if (rank == 0) {
        int send_buf[PARTS * PART_INTS];
        for (int i = 0; i < PARTS * PART_INTS; i++) send_buf[i] = 100 + i;
        MPIX_Request sreq, rreq;
        MPI_Status st;
        cudaStream_t stream = 0;
        MPIX_Psend_init(send_buf, PARTS, PART_INTS, MPI_INT, 1, 0,
                        MPI_COMM_WORLD, MPI_INFO_NULL, &sreq);
        MPIX_Start(&sreq);
        MPIX_Pready(0, sreq);            /* partition 1 deliberately withheld */
        int done = -1;
        MPIX_Irecv_enqueue(&done, 1, MPI_INT, 1, DONE_TAG, MPI_COMM_WORLD,
                           &rreq, MPIX_QUEUE_XLA_STREAM, &stream);

        /* Wedged: our tag-9 recv has no sender yet, rank 1 polls a
         * partition we never published. Both watchdogs must now trip. */
        if (!wait_for_file(dump0, 15000) || !wait_for_file(dump1, 15000)) {
            printf("[0] watchdog dumps never appeared (%s, %s)\n",
                   dump0, dump1);
            errs++;
        }
        uint64_t fs[5];
        acx_flight_stats(fs);
        if (fs[3] < 1) {   /* hang_dumps */
            printf("[0] watchdog tripped no hang dump (hang_dumps=%llu)\n",
                   (unsigned long long)fs[3]);
            errs++;
        }
        if (fs[2] < 1) {   /* stall_warns fire earlier, at 150ms */
            printf("[0] no stall warning recorded (stall_warns=%llu)\n",
                   (unsigned long long)fs[2]);
            errs++;
        }

        /* Un-wedge: publish the withheld partition, then collect the
         * tag-9 reply rank 1 sends after its side completes. */
        MPIX_Pready(1, sreq);
        MPIX_Wait(&sreq, &st);
        MPIX_Wait(&rreq, &st);
        if (done != 4242) {
            printf("[0] bad done token %d\n", done);
            errs++;
        }
        MPIX_Request_free(&sreq);
    } else if (rank == 1) {
        int recv_buf[PARTS * PART_INTS];
        memset(recv_buf, -1, sizeof recv_buf);
        MPIX_Request rreq;
        MPI_Status st;
        MPIX_Precv_init(recv_buf, PARTS, PART_INTS, MPI_INT, 0, 0,
                        MPI_COMM_WORLD, MPI_INFO_NULL, &rreq);
        MPIX_Start(&rreq);

        /* Partition 0 arrives (it was published); partition 1 is the
         * hang this test exists to diagnose. */
        int flag = 0;
        while (!flag) {
            if (MPIX_Parrived(rreq, 0, &flag)) MPI_Abort(MPI_COMM_WORLD, 3);
            if (!flag) usleep(1000);
        }
        if (!wait_for_file(dump0, 15000) || !wait_for_file(dump1, 15000)) {
            printf("[1] watchdog dumps never appeared (%s, %s)\n",
                   dump0, dump1);
            errs++;
        }

        /* Rank 0 publishes partition 1 once it has seen both dumps. */
        MPIX_Wait(&rreq, &st);
        for (int i = 0; i < PARTS * PART_INTS; i++) {
            if (recv_buf[i] != 100 + i) {
                if (errs < 3)
                    printf("[1] part data [%d]: got %d, want %d\n", i,
                           recv_buf[i], 100 + i);
                errs++;
            }
        }
        int done = 4242;
        MPI_Send(&done, 1, MPI_INT, 0, DONE_TAG, MPI_COMM_WORLD);
        MPIX_Request_free(&rreq);
    }
    /* Ranks >= 2 just ride along to finalize (np=4 leg). */

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (own_prefix && rank <= 1 && errs == 0)
        unlink(rank == 0 ? dump0 : dump1);
    if (rank == 0 && errs == 0) printf("hang-doctor: OK\n");
    return errs != 0;
}
