/* tpu-acx integration test: explicit graph construction + composition.
 *
 * Coverage parity with reference test/src/ring-all-graph-construction.c:
 * 74-107 — MPIX_QUEUE_XLA_GRAPH hands back single-op graphs which the app
 * composes with child-graph nodes and dependency edges, instantiates once,
 * and relaunches; the component graphs are destroyed while the exec lives
 * (refcounted cleanup must keep slots alive). */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;
    int send_val = rank + 1, recv_val = -1;
    MPIX_Request req[2];
    cudaGraph_t send_graph, recv_graph, wait_graph, graph;
    cudaGraphNode_t send_node, recv_node, wait_node;

    MPIX_Isend_enqueue(&send_val, 1, MPI_INT, right, 6, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_GRAPH, &send_graph);
    MPIX_Irecv_enqueue(&recv_val, 1, MPI_INT, left, 6, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_GRAPH, &recv_graph);
    MPIX_Waitall_enqueue(2, req, MPI_STATUSES_IGNORE, MPIX_QUEUE_XLA_GRAPH,
                         &wait_graph);

    if (cudaGraphCreate(&graph, 0) != cudaSuccess) MPI_Abort(MPI_COMM_WORLD, 2);
    cudaGraphAddChildGraphNode(&send_node, graph, NULL, 0, send_graph);
    cudaGraphAddChildGraphNode(&recv_node, graph, &send_node, 1, recv_graph);
    cudaGraphAddChildGraphNode(&wait_node, graph, &recv_node, 1, wait_graph);

    cudaGraphExec_t exec;
    if (cudaGraphInstantiate(&exec, graph, NULL, NULL, 0) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);

    for (int i = 0; i < size; i++) {
        cudaGraphLaunch(exec, 0);
        cudaMemcpyAsync(&send_val, &recv_val, sizeof(int),
                        cudaMemcpyHostToHost, 0);
    }
    cudaStreamSynchronize(0);

    cudaGraphExecDestroy(exec);
    cudaGraphDestroy(graph);
    cudaGraphDestroy(send_graph);
    cudaGraphDestroy(recv_graph);
    cudaGraphDestroy(wait_graph);

    if (recv_val != rank + 1) {
        printf("[%d] got %d after full circulation, want %d\n", rank,
               recv_val, rank + 1);
        errs++;
    }

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("ring-all-graph-construction: OK\n");
    return errs != 0;
}
