/* tpu-acx integration test: exchange through "device" allocations with
 * host-side MPIX_Waitall. Coverage parity with reference
 * test/src/ring-all-device.c (cudaMalloc buffers + host Waitall to avoid
 * blocking the queue; rationale in its comments at :93-101). On the tpu-acx
 * host plane, device allocations are host memory staged by the shim
 * (include/compat/cuda_runtime.h); on-TPU arrays belong to the JAX layer. */
#include <stdio.h>
#include <mpi.h>
#include <mpi-acx.h>

#define N 256

int main(int argc, char **argv) {
    int provided, rank, size, errs = 0;

    MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
    if (provided < MPI_THREAD_MULTIPLE) MPI_Abort(MPI_COMM_WORLD, 1);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

    const int right = (rank + 1) % size;
    const int left = (rank + size - 1) % size;

    int host_send[N], host_recv[N];
    int *dev_send = NULL, *dev_recv = NULL;
    if (cudaMalloc((void **)&dev_send, sizeof host_send) != cudaSuccess ||
        cudaMalloc((void **)&dev_recv, sizeof host_recv) != cudaSuccess)
        MPI_Abort(MPI_COMM_WORLD, 2);

    for (int i = 0; i < N; i++) {
        host_send[i] = rank * N + i;
        host_recv[i] = -1;
    }
    cudaMemcpy(dev_send, host_send, sizeof host_send, cudaMemcpyHostToDevice);
    cudaMemcpy(dev_recv, host_recv, sizeof host_recv, cudaMemcpyHostToDevice);

    MPIX_Request req[2];
    cudaStream_t stream = 0;

    MPIX_Isend_enqueue(dev_send, N, MPI_INT, right, 4, MPI_COMM_WORLD,
                       &req[0], MPIX_QUEUE_XLA_STREAM, &stream);
    MPIX_Irecv_enqueue(dev_recv, N, MPI_INT, left, 4, MPI_COMM_WORLD,
                       &req[1], MPIX_QUEUE_XLA_STREAM, &stream);

    /* Host-side waits: do not block the execution queue (the deadlock class
     * reference ring-all-device.c documents). */
    MPIX_Waitall(2, req, MPI_STATUSES_IGNORE);

    cudaMemcpy(host_recv, dev_recv, sizeof host_recv, cudaMemcpyDeviceToHost);
    for (int i = 0; i < N; i++) {
        if (host_recv[i] != left * N + i) {
            if (errs < 3)
                printf("[%d] elem %d: got %d, want %d\n", rank, i,
                       host_recv[i], left * N + i);
            errs++;
        }
    }

    cudaFree(dev_send);
    cudaFree(dev_recv);

    MPI_Allreduce(MPI_IN_PLACE, &errs, 1, MPI_INT, MPI_MAX, MPI_COMM_WORLD);
    MPIX_Finalize();
    MPI_Finalize();
    if (rank == 0 && errs == 0) printf("ring-all-device: OK\n");
    return errs != 0;
}
