// Name-table / enum agreement for the metrics registry.
//
// The compile-time half lives in src/core/metrics.cc: kCounterName and
// kHistName are unsized arrays whose lengths static_assert against
// kNumCounters / kNumHists, so adding an enum entry without a name (or a
// name without an entry) fails the build. This test covers what the
// static_assert cannot: every name is a real, distinct, non-placeholder
// string (the tools key JSON objects by these names — a duplicate would
// silently merge two counters), the gauge set is exactly the documented
// one, and the snapshot JSON actually carries every name.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <set>
#include <string>
#include <vector>

#include "acx/metrics.h"

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

using namespace acx::metrics;

namespace {

void test_counter_names() {
  std::set<std::string> seen;
  for (int i = 0; i < kNumCounters; i++) {
    const char* n = CounterName(static_cast<Counter>(i));
    CHECK(n != nullptr);
    CHECK(n[0] != '\0');
    CHECK(std::strcmp(n, "?") != 0);
    // Names become JSON keys; keep them simple identifiers.
    for (const char* p = n; *p; p++)
      CHECK((*p >= 'a' && *p <= 'z') || (*p >= '0' && *p <= '9') ||
            *p == '_');
    CHECK(seen.insert(n).second);  // distinct
  }
  CHECK(static_cast<int>(seen.size()) == kNumCounters);
  // Out-of-range lookups must not read past the table.
  CHECK(std::strcmp(CounterName(static_cast<Counter>(-1)), "?") == 0);
  CHECK(std::strcmp(CounterName(kNumCounters), "?") == 0);
}

void test_hist_names() {
  std::set<std::string> seen;
  for (int i = 0; i < kNumHists; i++) {
    const char* n = HistName(static_cast<Hist>(i));
    CHECK(n != nullptr);
    CHECK(n[0] != '\0');
    CHECK(std::strcmp(n, "?") != 0);
    CHECK(seen.insert(n).second);
  }
  CHECK(static_cast<int>(seen.size()) == kNumHists);
  CHECK(std::strcmp(HistName(static_cast<Hist>(-1)), "?") == 0);
  CHECK(std::strcmp(HistName(kNumHists), "?") == 0);
}

void test_gauge_set() {
  // Exactly the four documented gauges (metrics.h counters-vs-gauges
  // note); everything else is a cumulative counter the fleet tools may
  // sum.
  for (int i = 0; i < kNumCounters; i++) {
    Counter c = static_cast<Counter>(i);
    bool want = (c == kFleetEpoch || c == kSlotHighWater ||
                 c == kPagesFree || c == kPagesShared);
    CHECK(IsGauge(c) == want);
  }
}

void test_snapshot_carries_every_name() {
  // Populate a little so the snapshot is non-trivial.
  Add(kTriggers, 3);
  Set(kFleetEpoch, 7);
  MaxGauge(kSlotHighWater, 5);
  Observe(kProxySweepNs, 1024);

  int need = SnapshotJson(nullptr, 0);
  CHECK(need > 0);
  std::vector<char> buf(need + 1);
  int got = SnapshotJson(buf.data(), need + 1);
  CHECK(got == need);
  std::string js(buf.data());
  for (int i = 0; i < kNumCounters; i++) {
    std::string key = std::string("\"") +
                      CounterName(static_cast<Counter>(i)) + "\":";
    CHECK(js.find(key) != std::string::npos);
  }
  for (int i = 0; i < kNumHists; i++) {
    std::string key = std::string("\"") +
                      HistName(static_cast<Hist>(i)) + "\":";
    CHECK(js.find(key) != std::string::npos);
  }
  CHECK(js.find("\"gauges\":[") != std::string::npos);
  CHECK(js.find("\"fleet_epoch\"") != std::string::npos);
  CHECK(js.find("\"slot_hwm\"") != std::string::npos);
  CHECK(js.find("\"pages_free\"") != std::string::npos);
  CHECK(js.find("\"pages_shared\"") != std::string::npos);
  CHECK(js.find("\"proxy_util_pct\":") != std::string::npos);

  // Point reads agree with what was recorded above.
  CHECK(Value(kTriggers) >= 3);
  CHECK(Value(kFleetEpoch) == 7);
  CHECK(Value(kSlotHighWater) >= 5);
  uint64_t count = 0, sum = 0, buckets[kNumBuckets] = {0};
  HistRead(kProxySweepNs, &count, &sum, buckets);
  CHECK(count >= 1);
  CHECK(sum >= 1024);
  uint64_t bsum = 0;
  for (int i = 0; i < kNumBuckets; i++) bsum += buckets[i];
  CHECK(bsum == count);
}

}  // namespace

int main() {
  test_counter_names();
  test_hist_names();
  test_gauge_set();
  test_snapshot_carries_every_name();
  std::printf("test_metrics_names: all checks passed\n");
  return 0;
}
