// Unit tests for StreamTransport (both wire planes) + partitioned channels
// + proxy integration.
//
// Two transports live in one process, connected by a real wire — an AF_UNIX
// socketpair or a shared-memory ring segment — with rank 1 driven from a
// second thread: the same shape the reference only ever tests via two
// mpiexec ranks (reference test/src/ring.c), but unit-testable. Covers:
// basic sendrecv, FIFO (src,tag,ctx) matching with out-of-order tags, large
// (multi-MB, > wire buffer) payloads, truncating receives, self-send,
// barrier, allreduce, partitioned rounds with out-of-order Pready, the full
// proxy-driven enqueued lifecycle over a real wire, and SPSC-ring
// wrap-around at the byte level.

#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <memory>
#include <thread>
#include <vector>

#include "acx/fault.h"
#include "acx/net.h"
#include "acx/proxy.h"
#include "acx/state.h"
#include "src/net/link.h"

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

namespace {

enum class Wire { kSocket, kShm };
const char* WireName(Wire w) { return w == Wire::kSocket ? "socket" : "shm"; }

struct Pair {
  std::unique_ptr<acx::Transport> t0, t1;
  void* shm = nullptr;
  size_t shm_len = 0;
  // Deliberately small shm rings (4 KiB) so multi-MB tests exercise ring
  // wrap-around and flow control hard.
  explicit Pair(Wire w = Wire::kSocket, size_t ring_bytes = 4096) {
    if (w == Wire::kSocket) {
      int a[2];
      CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, a) == 0);
      // fds vector: index = peer rank; own slot unused.
      t0.reset(acx::CreateSocketTransport(0, 2, {-1, a[0]}));
      t1.reset(acx::CreateSocketTransport(1, 2, {a[1], -1}));
    } else {
      shm_len = acx::ShmSegmentBytes(2, ring_bytes);
      shm = mmap(nullptr, shm_len, PROT_READ | PROT_WRITE,
                 MAP_SHARED | MAP_ANONYMOUS, -1, 0);
      CHECK(shm != MAP_FAILED);
      t0.reset(acx::CreateShmTransport(0, 2, shm, ring_bytes));
      t1.reset(acx::CreateShmTransport(1, 2, shm, ring_bytes));
    }
  }
  ~Pair() {
    t0.reset();
    t1.reset();
    if (shm != nullptr) munmap(shm, shm_len);
  }
};

void WaitDone(acx::Ticket* t, acx::Status* st) {
  while (!t->Test(st)) std::this_thread::yield();
}

void test_basic_sendrecv(Wire w) {
  Pair p(w);
  int sv = 42, rv = -1;
  std::unique_ptr<acx::Ticket> s(p.t0->Isend(&sv, sizeof sv, 1, 7, 0));
  std::unique_ptr<acx::Ticket> r(p.t1->Irecv(&rv, sizeof rv, 0, 7, 0));
  acx::Status st;
  WaitDone(r.get(), &st);
  WaitDone(s.get(), nullptr);
  CHECK(rv == 42);
  CHECK(st.source == 0 && st.tag == 7 && st.error == 0 &&
        st.bytes == sizeof sv);
  std::printf("  transport basic sendrecv (%s): ok\n", WireName(w));
}

void test_matching_out_of_order_tags(Wire w) {
  Pair p(w);
  int a = 1, b = 2, ra = 0, rb = 0;
  // Send tag 5 then tag 6; recv tag 6 first. Matching is by tag, FIFO
  // within a tag.
  std::unique_ptr<acx::Ticket> s1(p.t0->Isend(&a, sizeof a, 1, 5, 0));
  std::unique_ptr<acx::Ticket> s2(p.t0->Isend(&b, sizeof b, 1, 6, 0));
  acx::Status st;
  std::unique_ptr<acx::Ticket> r2(p.t1->Irecv(&rb, sizeof rb, 0, 6, 0));
  WaitDone(r2.get(), &st);
  CHECK(rb == 2 && st.tag == 6);
  std::unique_ptr<acx::Ticket> r1(p.t1->Irecv(&ra, sizeof ra, 0, 5, 0));
  WaitDone(r1.get(), &st);
  CHECK(ra == 1 && st.tag == 5);
  WaitDone(s1.get(), nullptr);
  WaitDone(s2.get(), nullptr);
  std::printf("  transport tag matching (%s): ok\n", WireName(w));
}

void test_large_message(Wire w) {
  Pair p(w);
  const size_t n = 8u << 20;  // 8 MiB, far beyond AF_UNIX buffering
  std::vector<char> src(n), dst(n, 0);
  for (size_t i = 0; i < n; i++) src[i] = static_cast<char>(i * 31 + 7);
  // Both sides must make progress concurrently: run rank 1 in a thread.
  std::thread peer([&] {
    std::unique_ptr<acx::Ticket> r(p.t1->Irecv(dst.data(), n, 0, 1, 0));
    acx::Status st;
    WaitDone(r.get(), &st);
    CHECK(st.bytes == n);
  });
  std::unique_ptr<acx::Ticket> s(p.t0->Isend(src.data(), n, 1, 1, 0));
  WaitDone(s.get(), nullptr);
  peer.join();
  CHECK(memcmp(src.data(), dst.data(), n) == 0);
  std::printf("  transport 8MiB message (%s): ok\n", WireName(w));
}

void test_self_send() {
  std::unique_ptr<acx::Transport> t(acx::CreateSelfTransport());
  int sv = 9, rv = 0;
  std::unique_ptr<acx::Ticket> s(t->Isend(&sv, sizeof sv, 0, 3, 0));
  std::unique_ptr<acx::Ticket> r(t->Irecv(&rv, sizeof rv, 0, 3, 0));
  acx::Status st;
  WaitDone(r.get(), &st);
  WaitDone(s.get(), nullptr);
  CHECK(rv == 9 && st.source == 0);
  std::printf("  self transport loopback: ok\n");
}

void test_barrier_allreduce(Wire w) {
  Pair p(w);
  std::thread peer([&] {
    p.t1->Barrier(0);
    int32_t v[2] = {5, -3};
    p.t1->AllreduceInt(v, 2, 0, 0);  // MAX
    CHECK(v[0] == 7 && v[1] == -3);
  });
  p.t0->Barrier(0);
  int32_t v[2] = {7, -9};
  p.t0->AllreduceInt(v, 2, 0, 0);
  CHECK(v[0] == 7 && v[1] == -3);
  peer.join();
  std::printf("  barrier + allreduce(max) (%s): ok\n", WireName(w));
}

void test_partitioned_round_trip(Wire w) {
  Pair p(w);
  constexpr int kParts = 10;
  constexpr int kIters = 3;
  int send[kParts], recv[kParts];
  std::unique_ptr<acx::PartitionedChan> tx(
      p.t0->PsendInit(send, kParts, sizeof(int), 1, 2, 0));
  std::unique_ptr<acx::PartitionedChan> rx(
      p.t1->PrecvInit(recv, kParts, sizeof(int), 0, 2, 0));
  for (int it = 0; it < kIters; it++) {
    for (int i = 0; i < kParts; i++) {
      send[i] = it * 100 + i;
      recv[i] = -1;
    }
    tx->StartRound();
    rx->StartRound();
    // Mark partitions ready out of order — per-partition messages make
    // this legal by construction.
    for (int i = kParts - 1; i >= 0; i--) tx->Pready(i);
    acx::Status st;
    rx->FinishRound(&st);
    tx->FinishRound(nullptr);
    CHECK(st.bytes == sizeof(int) * kParts);
    for (int i = 0; i < kParts; i++) CHECK(recv[i] == it * 100 + i);
  }
  std::printf("  partitioned %d-part x%d rounds (out-of-order Pready, %s): ok\n",
              kParts, kIters, WireName(w));
}

// The full L1+L2+L0 stack over a real wire: two proxies, two flag tables,
// enqueued isend/irecv lifecycle driven purely by flag transitions — the
// unit-level equivalent of the reference's ring.c flow (sendrecv.cu:129-327
// + init.cpp:55-154).
void test_proxy_over_wire(Wire w) {
  Pair p(w);
  acx::FlagTable ft0(64), ft1(64);
  acx::Proxy px0(&ft0, p.t0.get()), px1(&ft1, p.t1.get());
  px0.Start();
  px1.Start();

  int sv = 1234, rv = -1;
  // Rank 0: enqueue a send op and trigger it (as the stream would).
  int si = ft0.Allocate();
  CHECK(si >= 0);
  acx::Op& so = ft0.op(si);
  so.kind = acx::OpKind::kIsend;
  so.sbuf = &sv;
  so.bytes = sizeof sv;
  so.peer = 1;
  so.tag = 9;
  ft0.Store(si, acx::kPending);
  px0.Kick();

  // Rank 1: enqueue the matching recv.
  int ri = ft1.Allocate();
  CHECK(ri >= 0);
  acx::Op& ro = ft1.op(ri);
  ro.kind = acx::OpKind::kIrecv;
  ro.rbuf = &rv;
  ro.bytes = sizeof rv;
  ro.peer = 0;
  ro.tag = 9;
  ft1.Store(ri, acx::kPending);
  px1.Kick();

  // Host-wait on both (spin until COMPLETED), then CLEANUP.
  while (ft1.Load(ri) != acx::kCompleted) std::this_thread::yield();
  CHECK(rv == 1234);
  CHECK(ro.status.source == 0 && ro.status.tag == 9);
  while (ft0.Load(si) != acx::kCompleted) std::this_thread::yield();
  ft0.Store(si, acx::kCleanup);
  ft1.Store(ri, acx::kCleanup);
  px0.Kick();
  px1.Kick();
  while (ft0.active.load() != 0 || ft1.active.load() != 0)
    std::this_thread::yield();
  px0.Stop();
  px1.Stop();
  std::printf("  proxy-driven enqueued sendrecv over wire (%s): ok\n", WireName(w));
}

// Byte-level SPSC ring: partial writes when full, partial reads when
// draining, and correctness across many wrap-arounds with co-prime chunk
// sizes.
void test_shm_ring_wraparound() {
  constexpr size_t kCap = 64;
  alignas(64) char slot[sizeof(acx::ShmRingHdr) + kCap] = {};
  auto* hdr = new (slot) acx::ShmRingHdr();
  char* data = slot + sizeof(acx::ShmRingHdr);

  // Full/partial-write behavior.
  std::vector<char> big(100, 'x');
  CHECK(acx::ShmRingWrite(hdr, data, kCap, big.data(), big.size()) == kCap);
  CHECK(acx::ShmRingWrite(hdr, data, kCap, big.data(), 1) == 0);  // full
  std::vector<char> sink(100);
  CHECK(acx::ShmRingRead(hdr, data, kCap, sink.data(), 100) == kCap);
  CHECK(acx::ShmRingRead(hdr, data, kCap, sink.data(), 1) == 0);  // empty

  // Streaming correctness across wrap-arounds: writer pushes 7-byte chunks,
  // reader pulls 5-byte chunks, 10k bytes total.
  const size_t total = 10000;
  size_t wrote = 0, read = 0;
  std::vector<char> out(total);
  while (read < total) {
    if (wrote < total) {
      char chunk[7];
      size_t n = total - wrote < 7 ? total - wrote : 7;
      for (size_t i = 0; i < n; i++)
        chunk[i] = static_cast<char>((wrote + i) * 13 + 5);
      wrote += acx::ShmRingWrite(hdr, data, kCap, chunk, n);
    }
    read += acx::ShmRingRead(hdr, data, kCap, out.data() + read,
                             total - read < 5 ? total - read : 5);
  }
  for (size_t i = 0; i < total; i++)
    CHECK(out[i] == static_cast<char>(i * 13 + 5));
  std::printf("  shm ring wrap-around: ok\n");
}

// A recv buffer smaller than the incoming message truncates (both the
// direct-delivery path — recv posted first — and the unexpected path).
void test_truncated_recv(Wire w) {
  Pair p(w);
  char msg[64];
  for (size_t i = 0; i < sizeof msg; i++) msg[i] = static_cast<char>(i + 1);
  acx::Status st;
  {
    // Direct path: recv posted before the message arrives.
    char small[16] = {0};
    std::unique_ptr<acx::Ticket> r(p.t1->Irecv(small, sizeof small, 0, 4, 0));
    std::unique_ptr<acx::Ticket> s(p.t0->Isend(msg, sizeof msg, 1, 4, 0));
    WaitDone(r.get(), &st);
    WaitDone(s.get(), nullptr);
    CHECK(st.bytes == sizeof small);
    CHECK(memcmp(small, msg, sizeof small) == 0);
  }
  {
    // Unexpected path: message arrives (and buffers) before the recv.
    std::unique_ptr<acx::Ticket> s(p.t0->Isend(msg, sizeof msg, 1, 5, 0));
    WaitDone(s.get(), nullptr);
    // Drive t1's progress with an unrelated probe so the tag-5 message is
    // drained into the unexpected queue before its recv exists.
    int dummy;
    std::unique_ptr<acx::Ticket> probe(
        p.t1->Irecv(&dummy, sizeof dummy, 0, 99, 0));
    probe->Test(nullptr);
    char small[16] = {0};
    std::unique_ptr<acx::Ticket> r(p.t1->Irecv(small, sizeof small, 0, 5, 0));
    WaitDone(r.get(), &st);
    CHECK(st.bytes == sizeof small);
    CHECK(memcmp(small, msg, sizeof small) == 0);
    // Satisfy the probe before `dummy` leaves scope — a posted RecvReq
    // holds the buffer pointer for as long as it stays unmatched.
    int one = 1;
    std::unique_ptr<acx::Ticket> ps(p.t0->Isend(&one, sizeof one, 1, 99, 0));
    WaitDone(probe.get(), nullptr);
    WaitDone(ps.get(), nullptr);
  }
  std::printf("  truncated recv, direct + unexpected (%s): ok\n", WireName(w));
}

// Drain while a link is mid-recovery (DESIGN.md §9): an op parked on a
// RECOVERING link must cancel in bounded time with the typed peer error,
// and repeated drains must not re-count it — the cancelled op's flag left
// the in-flight states, so a second CancelInflight finds nothing.
void test_drain_while_recovering() {
  // Arm recovery: socket plane + job id (binds this rank's rendezvous
  // listener) + a long-pinned ladder so the link stays RECOVERING for the
  // whole test — the redial target (rank 1's listener) never exists.
  char job[64];
  std::snprintf(job, sizeof job, "acx-ctest-drainrec-%d", getpid());
  setenv("ACX_JOB_ID", job, 1);
  setenv("ACX_RECONNECT_MAX", "8", 1);
  setenv("ACX_RECONNECT_BACKOFF_MS", "500", 1);
  {
    int a[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, a) == 0);
    std::unique_ptr<acx::Transport> t0(
        acx::CreateSocketTransport(0, 2, {-1, a[0]}));
    acx::FlagTable ft(64);
    acx::Proxy px(&ft, t0.get());
    px.Start();

    int rv = -1;
    int ri = ft.Allocate();
    CHECK(ri >= 0);
    acx::Op& ro = ft.op(ri);
    ro.kind = acx::OpKind::kIrecv;
    ro.rbuf = &rv;
    ro.bytes = sizeof rv;
    ro.peer = 1;
    ro.tag = 4;
    ft.Store(ri, acx::kPending);
    px.Kick();
    const uint64_t deadline = acx::NowNs() + 10ull * 1000 * 1000 * 1000;
    while (ft.Load(ri) == acx::kPending) {
      CHECK(acx::NowNs() < deadline);
      std::this_thread::yield();
    }
    // Cut the wire from the far end. With a recv in flight and the ladder
    // armed, the transport enters RECOVERING instead of the dead-latch.
    close(a[1]);
    while (t0->peer_health(1) != acx::PeerHealth::kRecovering) {
      CHECK(acx::NowNs() < deadline);
      CHECK(t0->peer_health(1) != acx::PeerHealth::kDead);
      std::this_thread::yield();
    }
    // First drain cancels the parked op — exactly one, typed as a peer
    // failure because the peer is unhealthy at cancel time.
    CHECK(px.CancelInflight() == 1);
    CHECK(ft.Load(ri) == acx::kCompleted);
    CHECK(ro.status.error == acx::kErrPeerDead);
    // Second drain of the (still recovering) link finds nothing left in
    // flight: drained counts must not double.
    CHECK(px.CancelInflight() == 0);
    ft.Store(ri, acx::kCleanup);
    px.Kick();
    while (ft.active.load() != 0) std::this_thread::yield();
    px.Stop();
  }
  unsetenv("ACX_JOB_ID");
  unsetenv("ACX_RECONNECT_MAX");
  unsetenv("ACX_RECONNECT_BACKOFF_MS");
  std::printf("  drain while link RECOVERING: ok\n");
}

}  // namespace

int main() {
  test_shm_ring_wraparound();
  test_self_send();
  for (Wire w : {Wire::kSocket, Wire::kShm}) {
    test_basic_sendrecv(w);
    test_matching_out_of_order_tags(w);
    test_large_message(w);
    test_truncated_recv(w);
    test_barrier_allreduce(w);
    test_partitioned_round_trip(w);
    test_proxy_over_wire(w);
  }
  test_drain_while_recovering();
  std::printf("test_transport: ALL OK\n");
  return 0;
}
