// Unit tests for SocketTransport + partitioned channels + proxy integration.
//
// Two transports live in one process, connected by a real socketpair, with
// rank 1 driven from a second thread — the same shape the reference only
// ever tests via two mpiexec ranks (reference test/src/ring.c), but
// unit-testable. Covers: basic sendrecv, FIFO (src,tag,ctx) matching with
// out-of-order tags, large (multi-MB, > socket buffer) payloads, self-send,
// barrier, allreduce, partitioned rounds with out-of-order Pready, and the
// full proxy-driven enqueued lifecycle over a real wire.

#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <memory>
#include <thread>
#include <vector>

#include "acx/net.h"
#include "acx/proxy.h"
#include "acx/state.h"

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

namespace {

struct Pair {
  std::unique_ptr<acx::Transport> t0, t1;
  Pair() {
    int a[2], b[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, a) == 0);
    // fds vector: index = peer rank; own slot unused.
    t0.reset(acx::CreateSocketTransport(0, 2, {-1, a[0]}));
    t1.reset(acx::CreateSocketTransport(1, 2, {a[1], -1}));
    (void)b;
  }
};

void WaitDone(acx::Ticket* t, acx::Status* st) {
  while (!t->Test(st)) std::this_thread::yield();
}

void test_basic_sendrecv() {
  Pair p;
  int sv = 42, rv = -1;
  std::unique_ptr<acx::Ticket> s(p.t0->Isend(&sv, sizeof sv, 1, 7, 0));
  std::unique_ptr<acx::Ticket> r(p.t1->Irecv(&rv, sizeof rv, 0, 7, 0));
  acx::Status st;
  WaitDone(r.get(), &st);
  WaitDone(s.get(), nullptr);
  CHECK(rv == 42);
  CHECK(st.source == 0 && st.tag == 7 && st.error == 0 &&
        st.bytes == sizeof sv);
  std::printf("  transport basic sendrecv: ok\n");
}

void test_matching_out_of_order_tags() {
  Pair p;
  int a = 1, b = 2, ra = 0, rb = 0;
  // Send tag 5 then tag 6; recv tag 6 first. Matching is by tag, FIFO
  // within a tag.
  std::unique_ptr<acx::Ticket> s1(p.t0->Isend(&a, sizeof a, 1, 5, 0));
  std::unique_ptr<acx::Ticket> s2(p.t0->Isend(&b, sizeof b, 1, 6, 0));
  acx::Status st;
  std::unique_ptr<acx::Ticket> r2(p.t1->Irecv(&rb, sizeof rb, 0, 6, 0));
  WaitDone(r2.get(), &st);
  CHECK(rb == 2 && st.tag == 6);
  std::unique_ptr<acx::Ticket> r1(p.t1->Irecv(&ra, sizeof ra, 0, 5, 0));
  WaitDone(r1.get(), &st);
  CHECK(ra == 1 && st.tag == 5);
  WaitDone(s1.get(), nullptr);
  WaitDone(s2.get(), nullptr);
  std::printf("  transport tag matching: ok\n");
}

void test_large_message() {
  Pair p;
  const size_t n = 8u << 20;  // 8 MiB, far beyond AF_UNIX buffering
  std::vector<char> src(n), dst(n, 0);
  for (size_t i = 0; i < n; i++) src[i] = static_cast<char>(i * 31 + 7);
  // Both sides must make progress concurrently: run rank 1 in a thread.
  std::thread peer([&] {
    std::unique_ptr<acx::Ticket> r(p.t1->Irecv(dst.data(), n, 0, 1, 0));
    acx::Status st;
    WaitDone(r.get(), &st);
    CHECK(st.bytes == n);
  });
  std::unique_ptr<acx::Ticket> s(p.t0->Isend(src.data(), n, 1, 1, 0));
  WaitDone(s.get(), nullptr);
  peer.join();
  CHECK(memcmp(src.data(), dst.data(), n) == 0);
  std::printf("  transport 8MiB message: ok\n");
}

void test_self_send() {
  std::unique_ptr<acx::Transport> t(acx::CreateSelfTransport());
  int sv = 9, rv = 0;
  std::unique_ptr<acx::Ticket> s(t->Isend(&sv, sizeof sv, 0, 3, 0));
  std::unique_ptr<acx::Ticket> r(t->Irecv(&rv, sizeof rv, 0, 3, 0));
  acx::Status st;
  WaitDone(r.get(), &st);
  WaitDone(s.get(), nullptr);
  CHECK(rv == 9 && st.source == 0);
  std::printf("  self transport loopback: ok\n");
}

void test_barrier_allreduce() {
  Pair p;
  std::thread peer([&] {
    p.t1->Barrier(0);
    int32_t v[2] = {5, -3};
    p.t1->AllreduceInt(v, 2, 0, 0);  // MAX
    CHECK(v[0] == 7 && v[1] == -3);
  });
  p.t0->Barrier(0);
  int32_t v[2] = {7, -9};
  p.t0->AllreduceInt(v, 2, 0, 0);
  CHECK(v[0] == 7 && v[1] == -3);
  peer.join();
  std::printf("  barrier + allreduce(max): ok\n");
}

void test_partitioned_round_trip() {
  Pair p;
  constexpr int kParts = 10;
  constexpr int kIters = 3;
  int send[kParts], recv[kParts];
  std::unique_ptr<acx::PartitionedChan> tx(
      p.t0->PsendInit(send, kParts, sizeof(int), 1, 2, 0));
  std::unique_ptr<acx::PartitionedChan> rx(
      p.t1->PrecvInit(recv, kParts, sizeof(int), 0, 2, 0));
  for (int it = 0; it < kIters; it++) {
    for (int i = 0; i < kParts; i++) {
      send[i] = it * 100 + i;
      recv[i] = -1;
    }
    tx->StartRound();
    rx->StartRound();
    // Mark partitions ready out of order — per-partition messages make
    // this legal by construction.
    for (int i = kParts - 1; i >= 0; i--) tx->Pready(i);
    acx::Status st;
    rx->FinishRound(&st);
    tx->FinishRound(nullptr);
    CHECK(st.bytes == sizeof(int) * kParts);
    for (int i = 0; i < kParts; i++) CHECK(recv[i] == it * 100 + i);
  }
  std::printf("  partitioned %d-part x%d rounds (out-of-order Pready): ok\n",
              kParts, kIters);
}

// The full L1+L2+L0 stack over a real wire: two proxies, two flag tables,
// enqueued isend/irecv lifecycle driven purely by flag transitions — the
// unit-level equivalent of the reference's ring.c flow (sendrecv.cu:129-327
// + init.cpp:55-154).
void test_proxy_over_wire() {
  Pair p;
  acx::FlagTable ft0(64), ft1(64);
  acx::Proxy px0(&ft0, p.t0.get()), px1(&ft1, p.t1.get());
  px0.Start();
  px1.Start();

  int sv = 1234, rv = -1;
  // Rank 0: enqueue a send op and trigger it (as the stream would).
  int si = ft0.Allocate();
  CHECK(si >= 0);
  acx::Op& so = ft0.op(si);
  so.kind = acx::OpKind::kIsend;
  so.sbuf = &sv;
  so.bytes = sizeof sv;
  so.peer = 1;
  so.tag = 9;
  ft0.Store(si, acx::kPending);
  px0.Kick();

  // Rank 1: enqueue the matching recv.
  int ri = ft1.Allocate();
  CHECK(ri >= 0);
  acx::Op& ro = ft1.op(ri);
  ro.kind = acx::OpKind::kIrecv;
  ro.rbuf = &rv;
  ro.bytes = sizeof rv;
  ro.peer = 0;
  ro.tag = 9;
  ft1.Store(ri, acx::kPending);
  px1.Kick();

  // Host-wait on both (spin until COMPLETED), then CLEANUP.
  while (ft1.Load(ri) != acx::kCompleted) std::this_thread::yield();
  CHECK(rv == 1234);
  CHECK(ro.status.source == 0 && ro.status.tag == 9);
  while (ft0.Load(si) != acx::kCompleted) std::this_thread::yield();
  ft0.Store(si, acx::kCleanup);
  ft1.Store(ri, acx::kCleanup);
  px0.Kick();
  px1.Kick();
  while (ft0.active.load() != 0 || ft1.active.load() != 0)
    std::this_thread::yield();
  px0.Stop();
  px1.Stop();
  std::printf("  proxy-driven enqueued sendrecv over wire: ok\n");
}

}  // namespace

int main() {
  test_basic_sendrecv();
  test_matching_out_of_order_tags();
  test_large_message();
  test_self_send();
  test_barrier_allreduce();
  test_partitioned_round_trip();
  test_proxy_over_wire();
  std::printf("test_transport: ALL OK\n");
  return 0;
}
