// Unit tests for the resilience plane: fault-spec parsing, deterministic
// injection windows, the proxy's drop->retry->success path, per-op
// deadlines, retry exhaustion, and dead-peer detection on both wire planes
// (EOF on sockets, heartbeat loss on shm rings — which have no EOF).
//
// Everything runs in-process with real transports (the test_transport.cc
// two-ranks-in-one-process shape), so the acceptance path "injected
// transient drop is retried with backoff and the op completes" is checked
// at the C layer before the Python tests drive it end to end.

#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <thread>

#include "acx/fault.h"
#include "acx/net.h"
#include "acx/proxy.h"
#include "acx/state.h"
#include "src/net/link.h"

extern "C" {
int MPIX_Set_deadline(double timeout_ms);
int MPIX_Get_deadline(double* timeout_ms);
int MPIX_Op_status(void* request, int* state, int* error, int* attempts);
}

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

using namespace acx;

namespace {

uint64_t ElapsedMs(uint64_t t0) { return (NowNs() - t0) / 1000000; }

void RestorePolicy() {
  Policy().timeout_ns.store(0);
  Policy().backoff_us.store(200);
  Policy().max_retries.store(8);
  fault::Configure(fault::Config{});  // disarm
}

void test_parse_spec() {
  fault::Config c;
  CHECK(fault::ParseSpec("drop", &c));
  CHECK(c.action == fault::Action::kDrop);
  CHECK(c.rank == -1 && c.kind == 0 && c.peer == -1);
  CHECK(c.nth == 1 && c.count == 1);

  CHECK(fault::ParseSpec("drop:rank=1:kind=send:nth=3:count=2", &c));
  CHECK(c.action == fault::Action::kDrop);
  CHECK(c.rank == 1 && c.kind == 1 && c.nth == 3 && c.count == 2);

  CHECK(fault::ParseSpec("delay:us=2500:kind=recv:peer=2", &c));
  CHECK(c.action == fault::Action::kDelay);
  CHECK(c.delay_us == 2500 && c.kind == 2 && c.peer == 2);

  CHECK(fault::ParseSpec("fail:err=21:kind=any", &c));
  CHECK(c.action == fault::Action::kFail);
  CHECK(c.err == 21 && c.kind == 0);

  CHECK(fault::ParseSpec("none", &c));
  CHECK(c.action == fault::Action::kNone);

  // Wire-level actions (docs/DESIGN.md §9 chaos machinery).
  CHECK(fault::ParseSpec("drop_frame:rank=0:nth=3:count=2", &c));
  CHECK(c.action == fault::Action::kDropFrame);
  CHECK(c.rank == 0 && c.nth == 3 && c.count == 2);

  CHECK(fault::ParseSpec("corrupt_frame:peer=1:nth=4", &c));
  CHECK(c.action == fault::Action::kCorruptFrame);
  CHECK(c.peer == 1 && c.nth == 4 && c.count == 1);

  CHECK(fault::ParseSpec("stall_link_ms:ms=40:nth=5", &c));
  CHECK(c.action == fault::Action::kStallLink);
  CHECK(c.stall_ms == 40 && c.nth == 5);

  CHECK(fault::ParseSpec("close_link_once:rank=1:nth=6", &c));
  CHECK(c.action == fault::Action::kCloseLink);
  CHECK(c.rank == 1 && c.nth == 6);

  // Malformed specs must be rejected, not half-parsed.
  CHECK(!fault::ParseSpec("", &c));
  CHECK(!fault::ParseSpec(nullptr, &c));
  CHECK(!fault::ParseSpec("explode", &c));
  CHECK(!fault::ParseSpec("drop:bogus=1", &c));
  CHECK(!fault::ParseSpec("drop:rank", &c));
  CHECK(!fault::ParseSpec("drop:kind=sideways", &c));
  CHECK(!fault::ParseSpec("drop:nth=0", &c));
  CHECK(!fault::ParseSpec("drop:count=0", &c));
  CHECK(!fault::ParseSpec("stall_link_ms:ms=0", &c));
  std::printf("parse_spec: OK\n");
}

void test_on_frame_window() {
  // Frame and issue consults are disjoint: an armed wire action never
  // fires at OnIssue, and OnFrame filters by rank/peer before consuming
  // its window.
  fault::Config c;
  CHECK(fault::ParseSpec("drop_frame:rank=0:peer=1:nth=2:count=1", &c));
  fault::Configure(c);
  uint64_t us = 0;
  int err = 0;
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnFrame(1, 1, 0, &us) == fault::Action::kNone);  // wrong rank
  CHECK(fault::OnFrame(0, 0, 0, &us) == fault::Action::kNone);  // wrong peer
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // match 1
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kDropFrame);  // match 2
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // window spent

  CHECK(fault::ParseSpec("stall_link_ms:ms=7:nth=1", &c));
  fault::Configure(c);
  us = 0;
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kStallLink);
  CHECK(us == 7000);  // ms -> us for the transport's stall gate

  // subflow= filters before the window counter: only lane-2 frames count,
  // so frames on other lanes neither fire nor burn the nth= budget.
  CHECK(fault::ParseSpec("drop_frame:subflow=2:nth=2:count=1", &c));
  fault::Configure(c);
  CHECK(c.subflow == 2);
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // lane 0
  CHECK(fault::OnFrame(0, 1, 1, &us) == fault::Action::kNone);  // lane 1
  CHECK(fault::OnFrame(0, 1, 2, &us) == fault::Action::kNone);  // match 1
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // lane 0
  CHECK(fault::OnFrame(0, 1, 2, &us) == fault::Action::kDropFrame);  // match 2
  CHECK(fault::OnFrame(0, 1, 2, &us) == fault::Action::kNone);  // spent
  RestorePolicy();
  std::printf("on_frame_window: OK\n");
}

void test_on_issue_window() {
  fault::Config c;
  CHECK(fault::ParseSpec("fail:rank=0:kind=send:nth=2:count=2", &c));
  fault::Configure(c);
  uint64_t us = 0;
  int err = 0;
  // Filtered out: wrong rank / wrong kind never consume the window.
  CHECK(fault::OnIssue(1, true, 0, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnIssue(0, false, 0, &us, &err) == fault::Action::kNone);
  // Matching attempts 1..4: window [2, 4) hits.
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kFail);
  CHECK(err == kErrInjected);  // err=0 in spec -> default code
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kFail);
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kNone);
  CHECK(fault::stats().fails >= 2);
  RestorePolicy();
  std::printf("on_issue_window: OK\n");
}

// Post one enqueued op through a real FlagTable+Proxy and wait for COMPLETED.
int RunOpThroughProxy(Transport* t, uint32_t max_retries, uint64_t backoff_us,
                      uint64_t timeout_ms, Proxy::Stats* out_stats,
                      OpKind kind = OpKind::kIsend) {
  Policy().max_retries.store(max_retries);
  Policy().backoff_us.store(backoff_us);
  Policy().timeout_ns.store(timeout_ms * 1000000);
  FlagTable table(8);
  Proxy proxy(&table, t);
  proxy.Start();
  static int payload = 777;
  const int idx = table.Allocate();
  CHECK(idx >= 0);
  Op& op = table.op(idx);
  op.kind = kind;
  op.sbuf = &payload;
  op.rbuf = &payload;
  op.bytes = sizeof payload;
  op.peer = 0;  // self
  op.tag = 5;
  op.ctx = 0;
  table.Store(idx, kPending);
  proxy.Kick();
  const uint64_t t0 = NowNs();
  while (table.Load(idx) != kCompleted) {
    CHECK(ElapsedMs(t0) < 10000);  // the whole point: bounded time
    std::this_thread::yield();
  }
  const int err = op.status.error;
  if (out_stats != nullptr) *out_stats = proxy.stats();
  proxy.Stop();
  return err;
}

void test_drop_retry_success() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  // Drop the first send issue attempt; the retry (2nd attempt) goes clean.
  CHECK(fault::ParseSpec("drop:kind=send:nth=1", &c));
  const uint64_t drops_before = fault::stats().drops;
  fault::Configure(c);
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 0, &s);
  CHECK(err == 0);  // op completed successfully after the retry
  CHECK(s.retries >= 1);
  CHECK(s.timeouts == 0);
  CHECK(fault::stats().drops == drops_before + 1);
  RestorePolicy();
  std::printf("drop_retry_success: OK\n");
}

void test_injected_fail() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  CHECK(fault::ParseSpec("fail:kind=send:nth=1", &c));
  fault::Configure(c);
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 0, &s);
  CHECK(err == kErrInjected);
  RestorePolicy();
  std::printf("injected_fail: OK\n");
}

void test_injected_delay() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  CHECK(fault::ParseSpec("delay:kind=send:nth=1:us=30000", &c));
  fault::Configure(c);
  const uint64_t t0 = NowNs();
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 0, &s);
  CHECK(err == 0);
  CHECK(ElapsedMs(t0) >= 25);  // the 30ms gate actually held the op
  RestorePolicy();
  std::printf("injected_delay: OK\n");
}

void test_retries_exhausted() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  // Every attempt dropped; with max_retries=2 the op must fail kErrTimeout
  // after 3 attempts instead of retrying forever.
  CHECK(fault::ParseSpec("drop:kind=send:count=1000000", &c));
  fault::Configure(c);
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 2, 1, 0, &s);
  CHECK(err == kErrTimeout);
  CHECK(s.timeouts >= 1);
  RestorePolicy();
  std::printf("retries_exhausted: OK\n");
}

void test_deadline_timeout() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  // A recv nothing ever matches: must complete with kErrTimeout within the
  // 50ms deadline, not hang.
  const uint64_t t0 = NowNs();
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 50, &s, OpKind::kIrecv);
  CHECK(err == kErrTimeout);
  CHECK(s.timeouts >= 1);
  CHECK(ElapsedMs(t0) >= 45);
  RestorePolicy();
  std::printf("deadline_timeout: OK\n");
}

void test_eof_dead_peer() {
  int a[2];
  CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, a) == 0);
  std::unique_ptr<Transport> t0(CreateSocketTransport(0, 2, {-1, a[0]}));
  std::unique_ptr<Transport> t1(CreateSocketTransport(1, 2, {a[1], -1}));
  t1.reset();  // rank 1 dies: its end of the socketpair closes
  int v = 0;
  std::unique_ptr<Ticket> r(t0->Irecv(&v, sizeof v, 1, 7, 0));
  Status st;
  const uint64_t start = NowNs();
  while (!r->Test(&st)) {
    CHECK(ElapsedMs(start) < 5000);
    std::this_thread::yield();
  }
  CHECK(st.error == kErrPeerDead);
  // Once latched, new ops against the dead peer error immediately.
  std::unique_ptr<Ticket> s(t0->Isend(&v, sizeof v, 1, 7, 0));
  CHECK(s->Test(&st));
  CHECK(st.error == kErrPeerDead);
  CHECK(t0->net_stats().peers_dead == 1);
  CHECK(t0->net_stats().failed_ops >= 1);
  std::printf("eof_dead_peer: OK\n");
}

void test_heartbeat_dead_peer() {
  // Shm rings have no EOF: death is only observable via heartbeat silence.
  setenv("ACX_HEARTBEAT_MS", "20", 1);
  setenv("ACX_PEER_TIMEOUT_MS", "200", 1);
  setenv("ACX_PEER_GRACE_MS", "100", 1);
  const size_t ring_bytes = 4096;
  const size_t len = ShmSegmentBytes(2, ring_bytes);
  void* shm = mmap(nullptr, len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_ANONYMOUS, -1, 0);
  CHECK(shm != MAP_FAILED);
  {
    std::unique_ptr<Transport> t0(CreateShmTransport(0, 2, shm, ring_bytes));
    // Rank 1's transport exists but is NEVER progressed — a wedged peer.
    std::unique_ptr<Transport> t1(CreateShmTransport(1, 2, shm, ring_bytes));
    int v = 0;
    std::unique_ptr<Ticket> r(t0->Irecv(&v, sizeof v, 1, 7, 0));
    Status st;
    const uint64_t start = NowNs();
    while (!r->Test(&st)) {
      CHECK(ElapsedMs(start) < 5000);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
    CHECK(st.error == kErrPeerDead);
    CHECK(ElapsedMs(start) >= 100);  // grace window held
    const NetStats ns = t0->net_stats();
    CHECK(ns.hb_sent >= 1);
    CHECK(ns.peers_dead == 1);
  }
  munmap(shm, len);
  unsetenv("ACX_HEARTBEAT_MS");
  unsetenv("ACX_PEER_TIMEOUT_MS");
  unsetenv("ACX_PEER_GRACE_MS");
  std::printf("heartbeat_dead_peer: OK\n");
}

void test_deadline_api() {
  double ms = -1;
  CHECK(MPIX_Set_deadline(1234.5) == 0);
  CHECK(MPIX_Get_deadline(&ms) == 0);
  CHECK(ms > 1234.4 && ms < 1234.6);
  CHECK(MPIX_Set_deadline(-1) != 0);  // rejected, value unchanged
  CHECK(MPIX_Get_deadline(&ms) == 0);
  CHECK(ms > 1234.4 && ms < 1234.6);
  CHECK(MPIX_Get_deadline(nullptr) != 0);
  CHECK(MPIX_Set_deadline(0) == 0);  // disarm
  // Bad handles are rejected, not dereferenced.
  int st = 0, err = 0, att = 0;
  CHECK(MPIX_Op_status(nullptr, &st, &err, &att) != 0);
  RestorePolicy();
  std::printf("deadline_api: OK\n");
}

}  // namespace

int main() {
  test_parse_spec();
  test_on_issue_window();
  test_on_frame_window();
  test_drop_retry_success();
  test_injected_fail();
  test_injected_delay();
  test_retries_exhausted();
  test_deadline_timeout();
  test_eof_dead_peer();
  test_heartbeat_dead_peer();
  test_deadline_api();
  std::printf("test_fault: ALL OK\n");
  return 0;
}
