// Unit tests for the resilience plane: fault-spec parsing, deterministic
// injection windows, the proxy's drop->retry->success path, per-op
// deadlines, retry exhaustion, and dead-peer detection on both wire planes
// (EOF on sockets, heartbeat loss on shm rings — which have no EOF).
//
// Everything runs in-process with real transports (the test_transport.cc
// two-ranks-in-one-process shape), so the acceptance path "injected
// transient drop is retried with backoff and the op completes" is checked
// at the C layer before the Python tests drive it end to end.

#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

#include <chrono>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <thread>

#include "acx/fault.h"
#include "acx/net.h"
#include "acx/proxy.h"
#include "acx/state.h"
#include "src/net/link.h"

extern "C" {
int MPIX_Set_deadline(double timeout_ms);
int MPIX_Get_deadline(double* timeout_ms);
int MPIX_Op_status(void* request, int* state, int* error, int* attempts);
}

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

using namespace acx;

namespace {

uint64_t ElapsedMs(uint64_t t0) { return (NowNs() - t0) / 1000000; }

void RestorePolicy() {
  Policy().timeout_ns.store(0);
  Policy().backoff_us.store(200);
  Policy().max_retries.store(8);
  fault::Configure(fault::Config{});  // disarm
}

void test_parse_spec() {
  fault::Config c;
  CHECK(fault::ParseSpec("drop", &c));
  CHECK(c.action == fault::Action::kDrop);
  CHECK(c.rank == -1 && c.kind == 0 && c.peer == -1);
  CHECK(c.nth == 1 && c.count == 1);

  CHECK(fault::ParseSpec("drop:rank=1:kind=send:nth=3:count=2", &c));
  CHECK(c.action == fault::Action::kDrop);
  CHECK(c.rank == 1 && c.kind == 1 && c.nth == 3 && c.count == 2);

  CHECK(fault::ParseSpec("delay:us=2500:kind=recv:peer=2", &c));
  CHECK(c.action == fault::Action::kDelay);
  CHECK(c.delay_us == 2500 && c.kind == 2 && c.peer == 2);

  CHECK(fault::ParseSpec("fail:err=21:kind=any", &c));
  CHECK(c.action == fault::Action::kFail);
  CHECK(c.err == 21 && c.kind == 0);

  CHECK(fault::ParseSpec("none", &c));
  CHECK(c.action == fault::Action::kNone);

  // Wire-level actions (docs/DESIGN.md §9 chaos machinery).
  CHECK(fault::ParseSpec("drop_frame:rank=0:nth=3:count=2", &c));
  CHECK(c.action == fault::Action::kDropFrame);
  CHECK(c.rank == 0 && c.nth == 3 && c.count == 2);

  CHECK(fault::ParseSpec("corrupt_frame:peer=1:nth=4", &c));
  CHECK(c.action == fault::Action::kCorruptFrame);
  CHECK(c.peer == 1 && c.nth == 4 && c.count == 1);

  CHECK(fault::ParseSpec("stall_link_ms:ms=40:nth=5", &c));
  CHECK(c.action == fault::Action::kStallLink);
  CHECK(c.stall_ms == 40 && c.nth == 5);

  CHECK(fault::ParseSpec("close_link_once:rank=1:nth=6", &c));
  CHECK(c.action == fault::Action::kCloseLink);
  CHECK(c.rank == 1 && c.nth == 6);

  // Partitioned-push domain selector (op=part): issue actions only.
  CHECK(fault::ParseSpec("drop:op=part:rank=1:nth=3", &c));
  CHECK(c.action == fault::Action::kDrop && c.op == 1);
  CHECK(c.rank == 1 && c.nth == 3);
  CHECK(fault::ParseSpec("delay:op=part:us=2500", &c));
  CHECK(c.action == fault::Action::kDelay && c.op == 1 && c.delay_us == 2500);
  CHECK(fault::ParseSpec("drop:op=plain", &c));
  CHECK(c.op == 0);
  // Round-trips through the canonical formatter.
  CHECK(fault::ParseSpec("drop:op=part:nth=2:count=3", &c));
  {
    char buf[128];
    CHECK(fault::FormatSpec(c, buf, sizeof buf) > 0);
    CHECK(strstr(buf, ":op=part") != nullptr);
    fault::Config c2;
    CHECK(fault::ParseSpec(buf, &c2));
    CHECK(c2.op == 1 && c2.nth == 2 && c2.count == 3);
  }

  // Malformed specs must be rejected, not half-parsed.
  CHECK(!fault::ParseSpec("", &c));
  CHECK(!fault::ParseSpec(nullptr, &c));
  CHECK(!fault::ParseSpec("explode", &c));
  CHECK(!fault::ParseSpec("drop:bogus=1", &c));
  CHECK(!fault::ParseSpec("drop:rank", &c));
  CHECK(!fault::ParseSpec("drop:kind=sideways", &c));
  CHECK(!fault::ParseSpec("drop:nth=0", &c));
  CHECK(!fault::ParseSpec("drop:count=0", &c));
  CHECK(!fault::ParseSpec("stall_link_ms:ms=0", &c));
  CHECK(!fault::ParseSpec("drop:op=bogus", &c));
  // op=part names an OnPartIssue domain; frame actions never consult it.
  CHECK(!fault::ParseSpec("drop_frame:op=part", &c));
  CHECK(!fault::ParseSpec("stall_link_ms:op=part", &c));
  std::printf("parse_spec: OK\n");
}

void test_on_frame_window() {
  // Frame and issue consults are disjoint: an armed wire action never
  // fires at OnIssue, and OnFrame filters by rank/peer before consuming
  // its window.
  fault::Config c;
  CHECK(fault::ParseSpec("drop_frame:rank=0:peer=1:nth=2:count=1", &c));
  fault::Configure(c);
  uint64_t us = 0;
  int err = 0;
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnFrame(1, 1, 0, &us) == fault::Action::kNone);  // wrong rank
  CHECK(fault::OnFrame(0, 0, 0, &us) == fault::Action::kNone);  // wrong peer
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // match 1
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kDropFrame);  // match 2
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // window spent

  CHECK(fault::ParseSpec("stall_link_ms:ms=7:nth=1", &c));
  fault::Configure(c);
  us = 0;
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kStallLink);
  CHECK(us == 7000);  // ms -> us for the transport's stall gate

  // subflow= filters before the window counter: only lane-2 frames count,
  // so frames on other lanes neither fire nor burn the nth= budget.
  CHECK(fault::ParseSpec("drop_frame:subflow=2:nth=2:count=1", &c));
  fault::Configure(c);
  CHECK(c.subflow == 2);
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // lane 0
  CHECK(fault::OnFrame(0, 1, 1, &us) == fault::Action::kNone);  // lane 1
  CHECK(fault::OnFrame(0, 1, 2, &us) == fault::Action::kNone);  // match 1
  CHECK(fault::OnFrame(0, 1, 0, &us) == fault::Action::kNone);  // lane 0
  CHECK(fault::OnFrame(0, 1, 2, &us) == fault::Action::kDropFrame);  // match 2
  CHECK(fault::OnFrame(0, 1, 2, &us) == fault::Action::kNone);  // spent
  RestorePolicy();
  std::printf("on_frame_window: OK\n");
}

void test_on_issue_window() {
  fault::Config c;
  CHECK(fault::ParseSpec("fail:rank=0:kind=send:nth=2:count=2", &c));
  fault::Configure(c);
  uint64_t us = 0;
  int err = 0;
  // Filtered out: wrong rank / wrong kind never consume the window.
  CHECK(fault::OnIssue(1, true, 0, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnIssue(0, false, 0, &us, &err) == fault::Action::kNone);
  // Matching attempts 1..4: window [2, 4) hits.
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kFail);
  CHECK(err == kErrInjected);  // err=0 in spec -> default code
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kFail);
  CHECK(fault::OnIssue(0, true, 0, &us, &err) == fault::Action::kNone);
  CHECK(fault::stats().fails >= 2);
  RestorePolicy();
  std::printf("on_issue_window: OK\n");
}

// Post one enqueued op through a real FlagTable+Proxy and wait for COMPLETED.
int RunOpThroughProxy(Transport* t, uint32_t max_retries, uint64_t backoff_us,
                      uint64_t timeout_ms, Proxy::Stats* out_stats,
                      OpKind kind = OpKind::kIsend) {
  Policy().max_retries.store(max_retries);
  Policy().backoff_us.store(backoff_us);
  Policy().timeout_ns.store(timeout_ms * 1000000);
  FlagTable table(8);
  Proxy proxy(&table, t);
  proxy.Start();
  static int payload = 777;
  const int idx = table.Allocate();
  CHECK(idx >= 0);
  Op& op = table.op(idx);
  op.kind = kind;
  op.sbuf = &payload;
  op.rbuf = &payload;
  op.bytes = sizeof payload;
  op.peer = 0;  // self
  op.tag = 5;
  op.ctx = 0;
  table.Store(idx, kPending);
  proxy.Kick();
  const uint64_t t0 = NowNs();
  while (table.Load(idx) != kCompleted) {
    CHECK(ElapsedMs(t0) < 10000);  // the whole point: bounded time
    std::this_thread::yield();
  }
  const int err = op.status.error;
  if (out_stats != nullptr) *out_stats = proxy.stats();
  proxy.Stop();
  return err;
}

void test_drop_retry_success() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  // Drop the first send issue attempt; the retry (2nd attempt) goes clean.
  CHECK(fault::ParseSpec("drop:kind=send:nth=1", &c));
  const uint64_t drops_before = fault::stats().drops;
  fault::Configure(c);
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 0, &s);
  CHECK(err == 0);  // op completed successfully after the retry
  CHECK(s.retries >= 1);
  CHECK(s.timeouts == 0);
  CHECK(fault::stats().drops == drops_before + 1);
  RestorePolicy();
  std::printf("drop_retry_success: OK\n");
}

void test_injected_fail() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  CHECK(fault::ParseSpec("fail:kind=send:nth=1", &c));
  fault::Configure(c);
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 0, &s);
  CHECK(err == kErrInjected);
  RestorePolicy();
  std::printf("injected_fail: OK\n");
}

void test_injected_delay() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  CHECK(fault::ParseSpec("delay:kind=send:nth=1:us=30000", &c));
  fault::Configure(c);
  const uint64_t t0 = NowNs();
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 0, &s);
  CHECK(err == 0);
  CHECK(ElapsedMs(t0) >= 25);  // the 30ms gate actually held the op
  RestorePolicy();
  std::printf("injected_delay: OK\n");
}

void test_retries_exhausted() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  fault::Config c;
  // Every attempt dropped; with max_retries=2 the op must fail kErrTimeout
  // after 3 attempts instead of retrying forever.
  CHECK(fault::ParseSpec("drop:kind=send:count=1000000", &c));
  fault::Configure(c);
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 2, 1, 0, &s);
  CHECK(err == kErrTimeout);
  CHECK(s.timeouts >= 1);
  RestorePolicy();
  std::printf("retries_exhausted: OK\n");
}

void test_deadline_timeout() {
  std::unique_ptr<Transport> t(CreateSelfTransport());
  // A recv nothing ever matches: must complete with kErrTimeout within the
  // 50ms deadline, not hang.
  const uint64_t t0 = NowNs();
  Proxy::Stats s{};
  const int err = RunOpThroughProxy(t.get(), 8, 100, 50, &s, OpKind::kIrecv);
  CHECK(err == kErrTimeout);
  CHECK(s.timeouts >= 1);
  CHECK(ElapsedMs(t0) >= 45);
  RestorePolicy();
  std::printf("deadline_timeout: OK\n");
}

void test_eof_dead_peer() {
  int a[2];
  CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, a) == 0);
  std::unique_ptr<Transport> t0(CreateSocketTransport(0, 2, {-1, a[0]}));
  std::unique_ptr<Transport> t1(CreateSocketTransport(1, 2, {a[1], -1}));
  t1.reset();  // rank 1 dies: its end of the socketpair closes
  int v = 0;
  std::unique_ptr<Ticket> r(t0->Irecv(&v, sizeof v, 1, 7, 0));
  Status st;
  const uint64_t start = NowNs();
  while (!r->Test(&st)) {
    CHECK(ElapsedMs(start) < 5000);
    std::this_thread::yield();
  }
  CHECK(st.error == kErrPeerDead);
  // Once latched, new ops against the dead peer error immediately.
  std::unique_ptr<Ticket> s(t0->Isend(&v, sizeof v, 1, 7, 0));
  CHECK(s->Test(&st));
  CHECK(st.error == kErrPeerDead);
  CHECK(t0->net_stats().peers_dead == 1);
  CHECK(t0->net_stats().failed_ops >= 1);
  std::printf("eof_dead_peer: OK\n");
}

void test_heartbeat_dead_peer() {
  // Shm rings have no EOF: death is only observable via heartbeat silence.
  setenv("ACX_HEARTBEAT_MS", "20", 1);
  setenv("ACX_PEER_TIMEOUT_MS", "200", 1);
  setenv("ACX_PEER_GRACE_MS", "100", 1);
  const size_t ring_bytes = 4096;
  const size_t len = ShmSegmentBytes(2, ring_bytes);
  void* shm = mmap(nullptr, len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_ANONYMOUS, -1, 0);
  CHECK(shm != MAP_FAILED);
  {
    std::unique_ptr<Transport> t0(CreateShmTransport(0, 2, shm, ring_bytes));
    // Rank 1's transport exists but is NEVER progressed — a wedged peer.
    std::unique_ptr<Transport> t1(CreateShmTransport(1, 2, shm, ring_bytes));
    int v = 0;
    std::unique_ptr<Ticket> r(t0->Irecv(&v, sizeof v, 1, 7, 0));
    Status st;
    const uint64_t start = NowNs();
    while (!r->Test(&st)) {
      CHECK(ElapsedMs(start) < 5000);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
    CHECK(st.error == kErrPeerDead);
    CHECK(ElapsedMs(start) >= 100);  // grace window held
    const NetStats ns = t0->net_stats();
    CHECK(ns.hb_sent >= 1);
    CHECK(ns.peers_dead == 1);
  }
  munmap(shm, len);
  unsetenv("ACX_HEARTBEAT_MS");
  unsetenv("ACX_PEER_TIMEOUT_MS");
  unsetenv("ACX_PEER_GRACE_MS");
  std::printf("heartbeat_dead_peer: OK\n");
}

void test_parse_schedule() {
  fault::Config cs[fault::kMaxSpecs];
  int n = 0;
  CHECK(fault::ParseSchedule("drop:rank=1;kill:rank=2:nth=5;delay:us=100",
                             cs, fault::kMaxSpecs, &n));
  CHECK(n == 3);
  CHECK(cs[0].action == fault::Action::kDrop && cs[0].rank == 1);
  CHECK(cs[1].action == fault::Action::kKill && cs[1].nth == 5);
  CHECK(cs[2].action == fault::Action::kDelay && cs[2].delay_us == 100);

  // Single spec is a 1-schedule; a trailing/empty segment is malformed.
  CHECK(fault::ParseSchedule("drop", cs, fault::kMaxSpecs, &n) && n == 1);
  CHECK(!fault::ParseSchedule("drop;;drop", cs, fault::kMaxSpecs, &n));
  CHECK(!fault::ParseSchedule("drop;", cs, fault::kMaxSpecs, &n));
  CHECK(!fault::ParseSchedule("", cs, fault::kMaxSpecs, &n));
  CHECK(!fault::ParseSchedule("drop;explode", cs, fault::kMaxSpecs, &n));
  // Over-cap schedules are refused outright, not truncated.
  char big[512];
  big[0] = '\0';
  for (int i = 0; i < fault::kMaxSpecs + 1; i++)
    strcat(big, i == 0 ? "drop" : ";drop");
  CHECK(!fault::ParseSchedule(big, cs, fault::kMaxSpecs, &n));
  std::printf("parse_schedule: OK\n");
}

void test_schedule_independent_windows() {
  // Two specs on the SAME attempt stream keep independent matched
  // counters: both advance every attempt, the first in-window spec fires.
  fault::Config cs[2];
  int n = 0;
  CHECK(fault::ParseSchedule("drop:kind=send:nth=2;fail:kind=send:nth=4",
                             cs, 2, &n) && n == 2);
  fault::ConfigureSchedule(cs, n);
  uint64_t us = 0;
  int err = 0;
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kDrop);
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kFail);
  CHECK(fault::ScheduleSize() == 2);
  // Per-spec ledger: both specs matched all 4 attempts, each fired once.
  CHECK(fault::SpecMatched(0) == 4 && fault::SpecFired(0) == 1);
  CHECK(fault::SpecMatched(1) == 4 && fault::SpecFired(1) == 1);
  CHECK(fault::SpecMatched(7) == 0 && fault::SpecFired(7) == 0);
  RestorePolicy();
  std::printf("schedule_independent_windows: OK\n");
}

void test_part_domain() {
  // op=part specs live in a SEPARATE match domain: OnIssue attempts never
  // match (or count against) them, and OnPartIssue attempts never match
  // plain specs — each domain keeps its own nth= coordinate.
  fault::Config cs[2];
  int n = 0;
  CHECK(fault::ParseSchedule("drop:op=part:kind=send:nth=2;drop:kind=send:nth=1",
                             cs, 2, &n) && n == 2);
  fault::ConfigureSchedule(cs, n);
  uint64_t us = 0;
  int err = 0;
  // Plain attempts: only the plain spec (schedule pos 1) matches; the part
  // spec's window is untouched.
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kDrop);
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  CHECK(fault::SpecMatched(0) == 0);  // part spec saw no plain attempts
  // Part attempts: the part spec fires at ITS nth=2, the plain spec's
  // counter does not advance.
  CHECK(fault::OnPartIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  CHECK(fault::OnPartIssue(0, true, 1, &us, &err) == fault::Action::kDrop);
  CHECK(fault::OnPartIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  CHECK(fault::SpecMatched(0) == 3 && fault::SpecFired(0) == 1);
  CHECK(fault::SpecMatched(1) == 2 && fault::SpecFired(1) == 1);

  // Delay fills delay_us from the part spec, same as OnIssue.
  fault::Config c;
  CHECK(fault::ParseSpec("delay:op=part:us=7000:nth=1", &c));
  fault::Configure(c);
  us = 0;
  CHECK(fault::OnPartIssue(0, true, 1, &us, &err) == fault::Action::kDelay);
  CHECK(us == 7000);
  CHECK(fault::OnIssue(0, true, 1, &us, &err) == fault::Action::kNone);
  RestorePolicy();
  std::printf("part_domain: OK\n");
}

void test_expand_chaos_part() {
  // mix=part draws only recoverable op=part actions (drop/delay), and the
  // three match domains (issue / wire / part) de-shadow independently.
  for (uint64_t seed = 1; seed <= 20; seed++) {
    char spec[64], out[2048];
    snprintf(spec, sizeof spec, "seed=%llu:faults=6:mix=part",
             (unsigned long long)seed);
    CHECK(fault::ExpandChaos(spec, 3, out, sizeof out));
    fault::Config cs[fault::kMaxSpecs];
    int n = 0;
    CHECK(fault::ParseSchedule(out, cs, fault::kMaxSpecs, &n));
    CHECK(n == 6);
    for (int i = 0; i < n; i++) {
      CHECK(cs[i].op == 1);
      CHECK(cs[i].action == fault::Action::kDrop ||
            cs[i].action == fault::Action::kDelay);
      // Same-rank part windows are disjoint (first in-window spec wins —
      // an overlapped later spec could never fire).
      for (int j = 0; j < i; j++) {
        if (cs[i].rank != cs[j].rank) continue;
        const bool overlap = cs[i].nth < cs[j].nth + cs[j].count &&
                             cs[j].nth < cs[i].nth + cs[i].count;
        CHECK(!overlap);
      }
    }
  }
  // Deterministic, like every other mix.
  char a[2048], b[2048];
  CHECK(fault::ExpandChaos("seed=9:faults=5:mix=issue,part", 2, a, sizeof a));
  CHECK(fault::ExpandChaos("seed=9:faults=5:mix=issue,part", 2, b, sizeof b));
  CHECK(strcmp(a, b) == 0);
  // A combined mix keeps per-domain windows disjoint but may overlap
  // ACROSS domains (each has its own attempt stream).
  for (uint64_t seed = 1; seed <= 20; seed++) {
    char spec[64], out[2048];
    snprintf(spec, sizeof spec, "seed=%llu:faults=8:mix=issue,part,kill",
             (unsigned long long)seed);
    CHECK(fault::ExpandChaos(spec, 3, out, sizeof out));
    fault::Config cs[fault::kMaxSpecs];
    int n = 0;
    CHECK(fault::ParseSchedule(out, cs, fault::kMaxSpecs, &n));
    CHECK(n == 8);
    int kills = 0;
    for (int i = 0; i < n; i++) {
      if (cs[i].action == fault::Action::kKill) kills++;
      for (int j = 0; j < i; j++) {
        if (cs[i].rank != cs[j].rank || cs[i].op != cs[j].op) continue;
        const bool overlap = cs[i].nth < cs[j].nth + cs[j].count &&
                             cs[j].nth < cs[i].nth + cs[i].count;
        CHECK(!overlap);
      }
    }
    CHECK(kills <= 1);
  }
  RestorePolicy();
  std::printf("expand_chaos_part: OK\n");
}

void test_expand_chaos() {
  char a[1024], b[1024];
  // Deterministic: same (seed, np) -> byte-identical schedule, forever.
  CHECK(fault::ExpandChaos("seed=42:faults=4:mix=issue,wire,kill", 3, a,
                           sizeof a));
  CHECK(fault::ExpandChaos("seed=42:faults=4:mix=issue,wire,kill", 3, b,
                           sizeof b));
  CHECK(strcmp(a, b) == 0);
  // Different seed or np -> different schedule.
  CHECK(fault::ExpandChaos("seed=43:faults=4:mix=issue,wire,kill", 3, b,
                           sizeof b));
  CHECK(strcmp(a, b) != 0);

  // Every expansion parses back, has the asked-for spec count, at most one
  // kill, and no two same-rank specs of the same match domain (issue-level
  // vs wire-level) with overlapping [nth, nth+count) windows — an
  // overlapped later spec could never fire (first in-window spec wins).
  for (uint64_t seed = 1; seed <= 40; seed++) {
    char spec[64], out[2048];
    snprintf(spec, sizeof spec, "seed=%llu:faults=6:mix=issue,wire,kill",
             (unsigned long long)seed);
    CHECK(fault::ExpandChaos(spec, 3, out, sizeof out));
    fault::Config cs[fault::kMaxSpecs];
    int n = 0;
    CHECK(fault::ParseSchedule(out, cs, fault::kMaxSpecs, &n));
    CHECK(n == 6);
    int kills = 0;
    for (int i = 0; i < n; i++) {
      if (cs[i].action == fault::Action::kKill) kills++;
      const bool wi = cs[i].action >= fault::Action::kDropFrame &&
                      cs[i].action <= fault::Action::kCloseLink;
      for (int j = 0; j < i; j++) {
        const bool wj = cs[j].action >= fault::Action::kDropFrame &&
                        cs[j].action <= fault::Action::kCloseLink;
        if (cs[i].rank != cs[j].rank || wi != wj) continue;
        const bool overlap = cs[i].nth < cs[j].nth + cs[j].count &&
                             cs[j].nth < cs[i].nth + cs[i].count;
        CHECK(!overlap);
      }
    }
    CHECK(kills <= 1);
  }

  // Malformed seed specs are refused, not guessed at.
  CHECK(!fault::ExpandChaos("faults=3", 3, a, sizeof a));        // no seed
  CHECK(!fault::ExpandChaos("seed=1:mix=zebra", 3, a, sizeof a));
  CHECK(!fault::ExpandChaos("seed=1:faults=0", 3, a, sizeof a));
  CHECK(!fault::ExpandChaos("seed=1:faults=17", 3, a, sizeof a));
  CHECK(!fault::ExpandChaos("seed=1", 0, a, sizeof a));          // np < 1
  CHECK(!fault::ExpandChaos("seed=1", 3, a, 4));                 // cap
  std::printf("expand_chaos: OK\n");
}

void test_kill_action() {
  // kill raises SIGKILL at the matching issue attempt — verify in a forked
  // child so the test binary survives to report it.
  const pid_t pid = fork();
  CHECK(pid >= 0);
  if (pid == 0) {
    fault::Config c;
    if (!fault::ParseSpec("kill:kind=send:nth=2", &c)) _exit(90);
    fault::Configure(c);
    uint64_t us = 0;
    int err = 0;
    if (fault::OnIssue(0, true, 1, &us, &err) != fault::Action::kNone)
      _exit(91);           // attempt 1: window not yet reached
    fault::OnIssue(0, true, 1, &us, &err);  // attempt 2: does not return
    _exit(92);
  }
  int st = 0;
  CHECK(waitpid(pid, &st, 0) == pid);
  CHECK(WIFSIGNALED(st) && WTERMSIG(st) == SIGKILL);
  RestorePolicy();
  std::printf("kill_action: OK\n");
}

// Self-exec probes: the env-seeded schedule and policy parse exactly once
// per process (function-local statics), so a FRESH process is the only
// place their failure modes are observable.
int SelfExecProbe(const char* self, const char* mode, const char* env_kv,
                  std::string* err_out) {
  int ep[2];
  CHECK(pipe(ep) == 0);
  const pid_t pid = fork();
  CHECK(pid >= 0);
  if (pid == 0) {
    close(ep[0]);
    dup2(ep[1], 2);
    close(ep[1]);
    char kv[256];
    snprintf(kv, sizeof kv, "%s", env_kv);
    putenv(kv);
    execl(self, self, mode, (char*)nullptr);
    _exit(127);
  }
  close(ep[1]);
  if (err_out != nullptr) {
    char buf[4096];
    ssize_t n;
    while ((n = read(ep[0], buf, sizeof buf)) > 0) err_out->append(buf, n);
  }
  close(ep[0]);
  int st = 0;
  CHECK(waitpid(pid, &st, 0) == pid);
  return st;
}

void test_bad_env_aborts(const char* self) {
  // S1: a malformed ACX_FAULT/ACX_CHAOS must abort LOUDLY at first use —
  // running fault-free when the operator asked for faults would silently
  // invalidate the whole experiment.
  std::string err;
  int st = SelfExecProbe(self, "--fault-probe", "ACX_FAULT=explode", &err);
  CHECK(WIFSIGNALED(st) && WTERMSIG(st) == SIGABRT);
  CHECK(err.find("ACX_FAULT") != std::string::npos);
  CHECK(err.find("fatal") != std::string::npos);

  err.clear();
  st = SelfExecProbe(self, "--fault-probe", "ACX_CHAOS=seed=banana", &err);
  CHECK(WIFSIGNALED(st) && WTERMSIG(st) == SIGABRT);
  CHECK(err.find("ACX_CHAOS") != std::string::npos);

  // A well-formed schedule in the same probe mode parses and arms.
  err.clear();
  st = SelfExecProbe(self, "--fault-probe",
                     "ACX_FAULT=drop:rank=7;kill:rank=9", &err);
  CHECK(WIFEXITED(st) && WEXITSTATUS(st) == 0);
  std::printf("bad_env_aborts: OK\n");
}

void test_policy_env_refused(const char* self) {
  // S2: malformed policy knobs are refused LOUDLY (stderr names the
  // variable) and the default is kept — never half-applied.
  std::string err;
  int st = SelfExecProbe(self, "--policy-probe",
                         "ACX_OP_TIMEOUT_MS=squid", &err);
  CHECK(WIFEXITED(st) && WEXITSTATUS(st) == 0);
  CHECK(err.find("ACX_OP_TIMEOUT_MS") != std::string::npos);

  err.clear();
  st = SelfExecProbe(self, "--policy-probe", "ACX_MAX_RETRIES=-3", &err);
  CHECK(WIFEXITED(st) && WEXITSTATUS(st) == 0);
  CHECK(err.find("ACX_MAX_RETRIES") != std::string::npos);

  // A well-formed value IS applied (and quietly).
  err.clear();
  st = SelfExecProbe(self, "--policy-probe-good",
                     "ACX_OP_TIMEOUT_MS=1500", &err);
  CHECK(WIFEXITED(st) && WEXITSTATUS(st) == 0);
  CHECK(err.find("ACX_OP_TIMEOUT_MS") == std::string::npos);
  std::printf("policy_env_refused: OK\n");
}

void test_deadline_api() {
  double ms = -1;
  CHECK(MPIX_Set_deadline(1234.5) == 0);
  CHECK(MPIX_Get_deadline(&ms) == 0);
  CHECK(ms > 1234.4 && ms < 1234.6);
  CHECK(MPIX_Set_deadline(-1) != 0);  // rejected, value unchanged
  CHECK(MPIX_Get_deadline(&ms) == 0);
  CHECK(ms > 1234.4 && ms < 1234.6);
  CHECK(MPIX_Get_deadline(nullptr) != 0);
  CHECK(MPIX_Set_deadline(0) == 0);  // disarm
  // Bad handles are rejected, not dereferenced.
  int st = 0, err = 0, att = 0;
  CHECK(MPIX_Op_status(nullptr, &st, &err, &att) != 0);
  RestorePolicy();
  std::printf("deadline_api: OK\n");
}

}  // namespace

int main(int argc, char** argv) {
  if (argc > 1 && strcmp(argv[1], "--fault-probe") == 0) {
    // Child mode for test_bad_env_aborts: force the env-seeded schedule
    // parse. A bad ACX_FAULT/ACX_CHAOS aborts inside Enabled().
    return fault::Enabled() || true ? 0 : 1;
  }
  if (argc > 1 && strcmp(argv[1], "--policy-probe") == 0) {
    // Child mode for test_policy_env_refused: the malformed env value must
    // be refused and the shipped default kept.
    return Policy().timeout_ns.load() == 0 && Policy().max_retries.load() == 8
               ? 0
               : 1;
  }
  if (argc > 1 && strcmp(argv[1], "--policy-probe-good") == 0) {
    return Policy().timeout_ns.load() == 1500ull * 1000000 ? 0 : 1;
  }
  test_parse_spec();
  test_on_issue_window();
  test_on_frame_window();
  test_parse_schedule();
  test_schedule_independent_windows();
  test_part_domain();
  test_expand_chaos();
  test_expand_chaos_part();
  test_kill_action();
  test_bad_env_aborts(argv[0]);
  test_policy_env_refused(argv[0]);
  test_drop_retry_success();
  test_injected_fail();
  test_injected_delay();
  test_retries_exhausted();
  test_deadline_timeout();
  test_eof_dead_peer();
  test_heartbeat_dead_peer();
  test_deadline_api();
  std::printf("test_fault: ALL OK\n");
  return 0;
}
