// Probe for the clang thread-safety annotations (acx/thread_annotations.h,
// docs/DESIGN.md §18). Two jobs:
//
//  1. As a normal ctest (no special defines): exercise acx::Mutex /
//     MutexLock / TryMutexLock at runtime — the wrappers must actually
//     lock, the try form must actually refuse a held mutex, and owns()
//     must tell the truth. This runs under gcc and clang alike.
//
//  2. Compiled with -DACX_ANNOT_PROBE_BAD under clang
//     -Werror=thread-safety (`make annotcheck`, part of `make lint`):
//     the deliberately unguarded write below MUST fail the build. That
//     proves the macros expand to real attributes and the analysis is
//     biting — guarding against a silent no-op under a future compiler
//     or flag change. Under gcc the macros compile to nothing, so the
//     annotcheck leg is clang-gated in the Makefile.

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <thread>

#include "acx/thread_annotations.h"

namespace {

struct Guarded {
  acx::Mutex mu;
  int value ACX_GUARDED_BY(mu) = 0;

  void Bump() {
    acx::MutexLock lk(mu);
    value++;
  }

  int Read() {
    acx::MutexLock lk(mu);
    return value;
  }

#ifdef ACX_ANNOT_PROBE_BAD
  // Unguarded write to a GUARDED_BY member: clang -Wthread-safety must
  // reject this translation unit. Never compiled into the shipped test.
  void BumpUnguarded() { value++; }
#endif
};

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,       \
                   #cond);                                               \
      std::exit(1);                                                      \
    }                                                                    \
  } while (0)

}  // namespace

int main() {
  Guarded g;

  // The wrappers actually serialize: hammer from two threads.
  std::thread a([&] { for (int i = 0; i < 50000; i++) g.Bump(); });
  std::thread b([&] { for (int i = 0; i < 50000; i++) g.Bump(); });
  a.join();
  b.join();
  CHECK(g.Read() == 100000);

  // TryMutexLock refuses a mutex held elsewhere and owns() reports it.
  // (The holder is a separate thread: same-thread try_lock of a held
  // std::mutex is formally undefined.)
  {
    std::atomic<int> phase{0};
    std::thread holder([&] {
      acx::MutexLock lk(g.mu);
      phase.store(1);
      while (phase.load() != 2) std::this_thread::yield();
    });
    while (phase.load() != 1) std::this_thread::yield();
    {
      acx::TryMutexLock tl(g.mu);
      CHECK(!tl.owns());
    }
    phase.store(2);
    holder.join();
  }
  // ...and acquires a free one.
  {
    acx::TryMutexLock tl(g.mu);
    CHECK(tl.owns());
  }
  // Bounded-spin form also acquires a free mutex.
  {
    acx::TryMutexLock tl(g.mu, /*spins=*/4);
    CHECK(tl.owns());
  }

  std::printf("annot_probe: OK\n");
  return 0;
}
