// Unit tests for the flight recorder (include/acx/flightrec.h): ring
// semantics, kind naming, dump format with no runtime initialized, and a
// hot-path overhead bound — the recorder is always on, so a Record that
// costs more than a couple of microseconds would tax every op issued.
// Plain asserts; exits nonzero on failure.
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "acx/fault.h"  // NowNs
#include "acx/flightrec.h"

using namespace acx;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, __LINE__, \
                   #cond);                                                 \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

static std::string slurp(const std::string& path) {
  FILE* f = std::fopen(path.c_str(), "r");
  CHECK(f != nullptr);
  std::string out;
  char buf[4096];
  size_t n;
  while ((n = std::fread(buf, 1, sizeof buf, f)) > 0) out.append(buf, n);
  std::fclose(f);
  return out;
}

int main() {
  // The event layout is part of the dump contract (40-byte packed record
  // since the causal span id landed, DESIGN.md §14).
  static_assert(sizeof(flight::Event) == 40, "flight event layout");

  CHECK(flight::Enabled());  // default ring: ACX_FLIGHT_EVENTS unset
  const flight::Stats s0 = flight::stats();
  CHECK(s0.capacity >= 1024);
  CHECK((s0.capacity & (s0.capacity - 1)) == 0);  // power of two

  // Kind names: table-driven, total, and stable at the edges.
  CHECK(std::strcmp(flight::KindName(flight::kNone), "none") == 0);
  CHECK(std::strcmp(flight::KindName(flight::kIsendEnqueue),
                    "isend_enqueue") == 0);
  CHECK(std::strcmp(flight::KindName(flight::kStallWarn), "stall_warn") == 0);
  CHECK(std::strcmp(flight::KindName(flight::kHangDump), "hang_dump") == 0);
  CHECK(std::strcmp(flight::KindName(flight::kFinalize), "finalize") == 0);
  CHECK(std::strcmp(flight::KindName(flight::kKindCount), "unknown") == 0);
  CHECK(std::strcmp(flight::KindName(9999), "unknown") == 0);

  // Recording bumps the lifetime count monotonically, past the capacity
  // (the ring wraps; the count does not).
  ACX_FLIGHT(kIsendEnqueue, 3, 1, 7, 64, 0);
  ACX_FLIGHT(kOpCompleted, 3, 1, 7, 64, 0);
  const flight::Stats s1 = flight::stats();
  CHECK(s1.recorded == s0.recorded + 2);

  // Dump with no transport/table initialized: header + config + stats +
  // empty slots/peers + our events, to an explicit prefix.
  setenv("ACX_RANK", "0", 1);
  std::string prefix = "/tmp/acx-test-flight";
  CHECK(flight::Dump(prefix.c_str(), "unit-test") == 0);
  const std::string path = prefix + ".rank0.flight.json";
  const std::string js = slurp(path);
  CHECK(js.find("\"reason\":\"unit-test\"") != std::string::npos);
  CHECK(js.find("\"slots\":[]") != std::string::npos);
  CHECK(js.find("\"peers\":[]") != std::string::npos);
  CHECK(js.find("\"kind\":\"isend_enqueue\"") != std::string::npos);
  CHECK(js.find("\"kind\":\"op_completed\"") != std::string::npos);
  CHECK(js.find("\"events_cap\"") != std::string::npos);
  CHECK(js.find("\"stall_warn_ms\"") != std::string::npos);
  CHECK(flight::stats().dumps_written == s1.dumps_written + 1);
  std::remove(path.c_str());

  // Watchdog bookkeeping counters.
  flight::NoteStallWarn();
  flight::NoteHangDump();
  CHECK(flight::stats().stall_warns == s1.stall_warns + 1);
  CHECK(flight::stats().hang_dumps == s1.hang_dumps + 1);

  // Threshold parsing: defaults are 10s / 30s (docs/DESIGN.md §10); the
  // env override path is covered end-to-end by itests/hang-doctor.c.
  CHECK(flight::StallWarnNs() == 10000ull * 1000000ull ||
        getenv("ACX_STALL_WARN_MS") != nullptr);
  CHECK(flight::HangDumpNs() == 30000ull * 1000000ull ||
        getenv("ACX_HANG_DUMP_MS") != nullptr);

  // Hot-path overhead: 1M ring writes, loose bound (avg < 2us even on a
  // loaded CI box; the real cost is ~tens of ns). Guards against someone
  // adding locking or formatting to Record().
  const int kN = 1000000;
  const uint64_t t0 = NowNs();
  for (int i = 0; i < kN; i++)
    flight::Record(flight::kTxData, i & 127, 1, 7, (uint64_t)i, 0);
  const uint64_t t1 = NowNs();
  const double avg_ns = double(t1 - t0) / kN;
  std::printf("test_flight: Record avg %.1f ns over %d events\n", avg_ns,
              kN);
  CHECK(avg_ns < 2000.0);
  CHECK(flight::stats().recorded >= s1.recorded + (uint64_t)kN);

  std::printf("test_flight: OK\n");
  return 0;
}
