// Unit tests for the core slot table + state machine + proxy engine.
// Pure host code, no devices needed (SURVEY.md §4: "add a unit layer around
// the slot table/state machine"). Plain asserts; exits nonzero on failure.
#include <cassert>
#include <map>
#include <memory>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "acx/proxy.h"
#include "acx/state.h"
#include "acx/transport.h"

using namespace acx;

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, __LINE__, \
                   #cond);                                                \
      std::exit(1);                                                       \
    }                                                                     \
  } while (0)

// A loopback transport: Isend/Irecv complete against an in-process mailbox.
// Lets us drive the full state machine without sockets.
namespace {

struct FakeTicket : Ticket {
  std::atomic<bool>* done;
  Status st;
  explicit FakeTicket(std::atomic<bool>* d, Status s) : done(d), st(s) {}
  bool Test(Status* out) override {
    if (!done->load(std::memory_order_acquire)) return false;
    *out = st;
    return true;
  }
};

// A fake wire shared by a matched Psend/Precv channel pair, so the sender's
// Pready is observed through the *receiver's* channel — real topology, per
// ADVICE r1 (the old fake pointed both slots at one channel).
struct FakeWire {
  std::vector<std::atomic<bool>> landed;
  explicit FakeWire(int parts) : landed(parts) {}
  void Reset() {
    for (auto& w : landed) w.store(false, std::memory_order_relaxed);
  }
};

struct FakeChan : PartitionedChan {
  std::shared_ptr<FakeWire> wire;
  FakeChan(std::shared_ptr<FakeWire> w, int parts, bool send)
      : wire(std::move(w)) {
    partitions = parts;
    is_send = send;
  }
  void Pready(int p) override {
    CHECK(is_send);
    wire->landed[p].store(true, std::memory_order_release);
  }
  bool Parrived(int p) override {
    CHECK(!is_send);
    return wire->landed[p].load(std::memory_order_acquire);
  }
  void StartRound() override {
    // The send side opens the round (clears the wire), mirroring how the
    // socket transport's recv side posts fresh tickets.
    if (is_send) wire->Reset();
  }
  void FinishRound(Status* st) override {
    if (!is_send)
      for (int p = 0; p < partitions; p++)
        CHECK(wire->landed[p].load(std::memory_order_acquire));
    if (st) *st = Status{0, 0, 0, part_bytes * partitions};
  }
};

struct FakeTransport : Transport {
  std::atomic<bool> sends_done{false};
  std::atomic<int> isends{0}, irecvs{0};
  int rank() const override { return 0; }
  int size() const override { return 1; }
  Ticket* Isend(const void*, size_t bytes, int dst, int tag, int,
                uint64_t) override {
    isends.fetch_add(1);
    Status st;
    st.source = 0;
    st.tag = tag;
    st.bytes = bytes;
    (void)dst;
    return new FakeTicket(&sends_done, st);
  }
  Ticket* Irecv(void*, size_t bytes, int src, int tag, int,
                uint64_t) override {
    irecvs.fetch_add(1);
    Status st;
    st.source = src;
    st.tag = tag;
    st.bytes = bytes;
    return new FakeTicket(&sends_done, st);
  }
  // Psend/Precv pairs with the same tag share one wire (loopback matching).
  std::map<int, std::shared_ptr<FakeWire>> wires;
  std::shared_ptr<FakeWire> WireFor(int tag, int parts) {
    auto it = wires.find(tag);
    if (it == wires.end())
      it = wires.emplace(tag, std::make_shared<FakeWire>(parts)).first;
    return it->second;
  }
  PartitionedChan* PsendInit(const void*, int parts, size_t pb, int, int tag,
                             int) override {
    auto* c = new FakeChan(WireFor(tag, parts), parts, /*send=*/true);
    c->part_bytes = pb;
    return c;
  }
  PartitionedChan* PrecvInit(void*, int parts, size_t pb, int, int tag,
                             int) override {
    auto* c = new FakeChan(WireFor(tag, parts), parts, /*send=*/false);
    c->part_bytes = pb;
    return c;
  }
  void Barrier(int) override {}
  void AllreduceInt(int32_t*, int, int, int) override {}
  void Abort(int code) override { std::exit(code); }
};

void SpinUntil(FlagTable& t, int idx, int32_t want) {
  while (t.Load(idx) != want) std::this_thread::yield();
}

void test_allocator_exhaustion() {
  FlagTable t(8);
  std::vector<int> got;
  for (int i = 0; i < 8; i++) {
    int s = t.Allocate();
    CHECK(s >= 0);
    got.push_back(s);
  }
  CHECK(t.Allocate() == -1);
  for (int s : got) t.Free(s);
  CHECK(t.Allocate() >= 0);
  std::printf("  allocator exhaustion: ok\n");
}

void test_watermark_decay() {
  // After a burst drains, the sweep bound must return to O(live ops) —
  // the proxy never pays for PEAK concurrency forever (BASELINE.md's
  // O(live-ops) sweep claim for non-monotone workloads).
  FlagTable t(4096);
  std::vector<int> burst;
  for (int i = 0; i < 4096; i++) burst.push_back(t.Allocate());
  CHECK(t.watermark() == 4096);
  for (int s : burst) t.Free(s);
  CHECK(t.watermark() == 0);
  // Steady state after the burst: a few live ops keep the bound tiny.
  int a = t.Allocate(), b = t.Allocate();
  CHECK(t.watermark() == 2);
  t.Free(b);
  CHECK(t.watermark() == 1);
  t.Free(a);
  CHECK(t.watermark() == 0);
  // Out-of-order drain: freeing below the top keeps the bound at the top
  // until the top frees, then it collapses past the whole freed range.
  std::vector<int> s3;
  for (int i = 0; i < 64; i++) s3.push_back(t.Allocate());
  for (int i = 0; i < 63; i++) t.Free(s3[i]);
  CHECK(t.watermark() == 64);
  t.Free(s3[63]);
  CHECK(t.watermark() == 0);
  std::printf("  watermark decay: ok\n");
}

void test_watermark_decay_race() {
  // Free's decay scan vs a concurrent Allocate: the watermark must always
  // (promptly) re-cover a just-allocated slot, or the proxy would never
  // sweep it and a wait on that op would hang (r3 code-review finding).
  FlagTable t(8);
  std::atomic<bool> stop{false};
  std::atomic<long> fails{0}, cycles{0};
  std::vector<std::thread> th;
  for (int k = 0; k < 2; k++) {
    th.emplace_back([&] {
      while (!stop.load()) {
        int s = t.Allocate();
        if (s < 0) continue;
        // Transient under-coverage while another thread's Free is mid-
        // re-verify is fine (the proxy re-sweeps); it must settle fast.
        bool covered = false;
        for (int spin = 0; spin < 200000 && !covered; spin++)
          covered = t.watermark() >= static_cast<size_t>(s) + 1;
        if (!covered) fails.fetch_add(1);
        cycles.fetch_add(1);
        t.Free(s);
      }
    });
  }
  std::this_thread::sleep_for(std::chrono::milliseconds(150));
  stop.store(true);
  for (auto& x : th) x.join();
  CHECK(cycles.load() > 0);
  CHECK(fails.load() == 0);
  std::printf("  watermark decay/allocate race (%ld cycles): ok\n",
              cycles.load());
}

void test_concurrent_allocator() {
  FlagTable t(256);
  std::atomic<bool> stop{false};
  std::atomic<int64_t> total{0};
  std::vector<std::thread> threads;
  for (int k = 0; k < 4; k++) {
    threads.emplace_back([&] {
      while (!stop.load()) {
        int s = t.Allocate();
        if (s >= 0) {
          total.fetch_add(1);
          t.Free(s);
        }
      }
    });
  }
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  stop.store(true);
  for (auto& th : threads) th.join();
  CHECK(t.active.load() == 0);
  CHECK(total.load() > 0);
  // Every slot must be AVAILABLE again — no lost or duplicated slots.
  for (int i = 0; i < 256; i++) CHECK(t.Load(i) == kAvailable);
  std::printf("  concurrent allocator (%lld cycles): ok\n",
              static_cast<long long>(total.load()));
}

void test_sendrecv_lifecycle() {
  FlagTable t(16);
  FakeTransport tr;
  Proxy proxy(&t, &tr);
  proxy.Start();

  int slot = t.Allocate();
  CHECK(slot >= 0);
  Op& op = t.op(slot);
  op.kind = OpKind::kIsend;
  op.sbuf = &op;
  op.bytes = 4;
  op.peer = 0;
  op.tag = 7;
  // "Device reaches the trigger point":
  t.Store(slot, kPending);
  proxy.Kick();

  SpinUntil(t, slot, kIssued);
  CHECK(tr.isends.load() == 1);
  // Transfer completes on the wire:
  tr.sends_done.store(true, std::memory_order_release);
  SpinUntil(t, slot, kCompleted);
  CHECK(t.op(slot).status.tag == 7);
  // Consumer (wait point) takes it to CLEANUP via CAS; proxy reclaims.
  CHECK(t.Cas(slot, kCompleted, kCleanup));
  proxy.Kick();
  SpinUntil(t, slot, kAvailable);
  CHECK(t.active.load() == 0);
  proxy.Stop();
  std::printf("  sendrecv lifecycle: ok\n");
}

void test_cleanup_never_leaks() {
  // Regression for the reference's leak: a slot entering CLEANUP while the
  // proxy is elsewhere must still be reclaimed.
  FlagTable t(16);
  FakeTransport tr;
  Proxy proxy(&t, &tr);

  int slot = t.Allocate();
  t.op(slot).kind = OpKind::kIsend;
  t.Store(slot, kCleanup);  // straight to CLEANUP before proxy even starts
  proxy.Start();
  proxy.Kick();
  SpinUntil(t, slot, kAvailable);
  proxy.Stop();
  std::printf("  cleanup reclaim: ok\n");
}

void test_partitioned_lifecycle() {
  // Real topology: sender marks through send_chan, proxy observes arrival
  // through recv_chan (shared wire underneath), and the COMPLETED->RESERVED
  // restart path runs THREE full rounds (reference runs 10 iterations,
  // ring-partitioned.cu:101-127).
  FlagTable t(64);
  FakeTransport tr;
  Proxy proxy(&t, &tr);
  proxy.Start();

  const int P = 10;
  PartitionedChan* send_chan = tr.PsendInit(nullptr, P, 4, 0, 0, 0);
  PartitionedChan* recv_chan = tr.PrecvInit(nullptr, P, 4, 0, 0, 0);
  std::vector<int> send_slots(P), recv_slots(P);
  for (int p = 0; p < P; p++) {
    int s = t.Allocate();
    t.op(s).kind = OpKind::kPready;
    t.op(s).chan = send_chan;
    t.op(s).partition = p;
    send_slots[p] = s;

    int r = t.Allocate();
    t.op(r).kind = OpKind::kParrived;
    t.op(r).chan = recv_chan;  // the receiver polls its OWN channel
    t.op(r).partition = p;
    recv_slots[p] = r;
  }

  for (int round = 0; round < 3; round++) {
    // MPIX_Start: open the round; recv partitions -> ISSUED.
    send_chan->StartRound();
    recv_chan->StartRound();
    for (int p = 0; p < P; p++) t.Store(recv_slots[p], kIssued);
    // Device marks partitions ready out of order:
    for (int p = P - 1; p >= 0; p--) t.Store(send_slots[p], kPending);
    proxy.Kick();
    for (int p = 0; p < P; p++) {
      SpinUntil(t, send_slots[p], kCompleted);
      SpinUntil(t, recv_slots[p], kCompleted);
    }
    // Host Waitall: per-partition reset to RESERVED, then close the round.
    for (int p = 0; p < P; p++) {
      t.Store(send_slots[p], kReserved);
      t.Store(recv_slots[p], kReserved);
    }
    Status st;
    recv_chan->FinishRound(&st);
    CHECK(st.bytes == 4u * P);
    send_chan->FinishRound(nullptr);
  }

  for (int p = 0; p < P; p++) {
    t.Free(send_slots[p]);
    t.Free(recv_slots[p]);
  }
  proxy.Stop();
  delete send_chan;
  delete recv_chan;
  std::printf("  partitioned lifecycle (3 rounds, two channels): ok\n");
}

void test_proxy_idle_is_cheap() {
  FlagTable t(4096);
  FakeTransport tr;
  Proxy proxy(&t, &tr);
  proxy.Start();
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  auto s = proxy.stats();
  proxy.Stop();
  // With an empty table the proxy must park, not spin (reference busy-spins
  // O(nflags) forever). 200ms parked in 50ms naps => a handful of sweeps.
  CHECK(s.sweeps < 1000);
  std::printf("  idle proxy sweeps in 200ms: %llu (parked): ok\n",
              static_cast<unsigned long long>(s.sweeps));
}

}  // namespace

int main() {
  std::printf("test_core:\n");
  test_allocator_exhaustion();
  test_watermark_decay();
  test_watermark_decay_race();
  test_concurrent_allocator();
  test_sendrecv_lifecycle();
  test_cleanup_never_leaks();
  test_partitioned_lifecycle();
  test_proxy_idle_is_cheap();
  std::printf("test_core: ALL OK\n");
  return 0;
}
