// Regression test for trace::EnvRankOr (include/acx/trace.h): the strict
// $ACX_RANK parse every crash-path artifact namer shares (trace flush,
// flight dump, tseries file). Before this existed, a process that died
// pre-SetRank with ACX_RANK="2junk" or unset would name its artifact
// ".rank0." and silently collide with the real rank 0's dump — the
// strict parse accepts ONLY a full non-negative decimal string and falls
// back otherwise, loudly preserving the caller's default.
// Also covers span::Make/Rank/Slot/Incarnation (include/acx/span.h): the
// bit layout is wire protocol (WireHeader.span), so a packing change
// must fail a test, not just reshuffle ids.
// Plain asserts; exits nonzero on failure.
#include <cstdio>
#include <cstdlib>

#include "acx/span.h"
#include "acx/trace.h"

using namespace acx;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, __LINE__, \
                   #cond);                                                 \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

static void set_rank(const char* v) {
  if (v == nullptr)
    unsetenv("ACX_RANK");
  else
    setenv("ACX_RANK", v, 1);
}

int main() {
  // Unset / empty: fallback, whatever it is.
  set_rank(nullptr);
  CHECK(trace::EnvRankOr(0) == 0);
  CHECK(trace::EnvRankOr(7) == 7);
  set_rank("");
  CHECK(trace::EnvRankOr(3) == 3);

  // Clean non-negative decimals parse, including multi-digit and zero.
  set_rank("0");
  CHECK(trace::EnvRankOr(9) == 0);
  set_rank("2");
  CHECK(trace::EnvRankOr(0) == 2);
  set_rank("1024");
  CHECK(trace::EnvRankOr(0) == 1024);

  // Garbage, trailing junk, negatives, hex, whitespace: all fall back —
  // a half-parsed rank is worse than the fallback (it picks a WRONG
  // file name instead of the predictable one).
  set_rank("garbage");
  CHECK(trace::EnvRankOr(5) == 5);
  set_rank("2junk");
  CHECK(trace::EnvRankOr(5) == 5);
  set_rank("-1");
  CHECK(trace::EnvRankOr(5) == 5);
  set_rank("0x10");
  CHECK(trace::EnvRankOr(5) == 5);
  set_rank(" 3");
  CHECK(trace::EnvRankOr(5) == 5);
  set_rank("3 ");
  CHECK(trace::EnvRankOr(5) == 5);
  set_rank("99999999999999999999");  // overflows int: fall back
  CHECK(trace::EnvRankOr(5) == 5);
  set_rank(nullptr);

  // Span id packing: rank 16 bits << 48, slot 16 bits << 32, incarnation
  // low 32 — and the decomposers invert Make exactly.
  const uint64_t s = span::Make(3, 250, 0x12345678u);
  CHECK(span::Rank(s) == 3);
  CHECK(span::Slot(s) == 250);
  CHECK(span::Incarnation(s) == 0x12345678u);
  CHECK(s == ((3ull << 48) | (250ull << 32) | 0x12345678ull));
  // Field masking at the edges: oversized inputs truncate, never bleed
  // into the neighboring field.
  const uint64_t t = span::Make(0x1ffff, 0x2ffff, 0xffffffffu);
  CHECK(span::Rank(t) == 0xffff);
  CHECK(span::Slot(t) == 0xffff);
  CHECK(span::Incarnation(t) == 0xffffffffu);
  // Span 0 is reserved for "unspanned"; any real (rank, slot, inc>0)
  // combination is nonzero.
  CHECK(span::Make(0, 0, 1) != 0);

  std::printf("test_envrank: OK\n");
  return 0;
}
