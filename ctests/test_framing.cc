// Unit tests for the bottom two layers of the net split (DESIGN.md §15):
// framing (header sealing, restamp, replay buffer) and striping policy
// (threshold/chunk-plan arithmetic), plus the CRC32C software fallback
// pinned against whatever path Crc32c actually dispatches to on this host
// (SSE4.2 where available). Everything here is plain data + arithmetic —
// no sockets, no transport, no locks.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "src/net/framing.h"
#include "src/net/stripe.h"
#include "src/net/wire.h"

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__, __LINE__, \
                   #cond);                                                \
      std::exit(1);                                                       \
    }                                                                     \
  } while (0)

namespace {

using acx::framing::ChunkHdr;
using acx::framing::FrameSeq;
using acx::framing::MakeHdr;
using acx::framing::ReplayBuffer;
using acx::framing::RestampFrame;
using acx::framing::WirePayloadLen;
using acx::wire::Crc32c;
using acx::wire::Crc32cSw;
using acx::wire::WireHeader;

// -- CRC32C: software fallback vs the dispatched path -----------------------

void test_crc32c_known_vector() {
  // The canonical Castagnoli check value: crc32c("123456789") = 0xE3069283.
  const char* v = "123456789";
  CHECK(Crc32cSw(0, v, 9) == 0xE3069283u);
  CHECK(Crc32c(0, v, 9) == 0xE3069283u);
  std::printf("  crc32c known vector 0xE3069283: ok\n");
}

void test_crc32c_sw_matches_hw() {
  // Deterministic pseudo-random buffer; compare the always-software path
  // against Crc32c (the SSE4.2 path on hosts that have it) across sizes
  // that exercise the hardware path's 8/4/2/1-byte tails and unaligned
  // starts. If this host has no SSE4.2 both sides run the table — the
  // check degrades to self-consistency, never to a false failure.
  std::vector<unsigned char> buf(8192 + 9);
  uint32_t x = 0x12345678u;
  for (auto& b : buf) {
    x = x * 1664525u + 1013904223u;
    b = static_cast<unsigned char>(x >> 24);
  }
  const size_t sizes[] = {0, 1, 2, 3, 7, 8, 9, 15, 16, 63, 64, 65,
                          255, 1024, 4096, 8191, 8192};
  for (size_t n : sizes) {
    for (size_t off = 0; off < 8; off++) {
      const uint32_t sw = Crc32cSw(0, buf.data() + off, n);
      const uint32_t hw = Crc32c(0, buf.data() + off, n);
      if (sw != hw) {
        std::fprintf(stderr, "crc mismatch n=%zu off=%zu sw=%08x hw=%08x\n",
                     n, off, sw, hw);
        std::exit(1);
      }
    }
  }
  std::printf("  crc32c sw==hw over %zu size/offset pairs: ok\n",
              sizeof(sizes) / sizeof(sizes[0]) * 8);
}

void test_crc32c_incremental() {
  // Feeding pieces must equal one shot — the deferred-chunk-CRC send path
  // relies on exactly this (ChunkHdr first, borrowed payload second).
  std::vector<char> buf(4096);
  for (size_t i = 0; i < buf.size(); i++)
    buf[i] = static_cast<char>(i * 131 + 7);
  const uint32_t one = Crc32c(0, buf.data(), buf.size());
  const size_t cuts[] = {1, 24, 56, 100, 4095};
  for (size_t cut : cuts) {
    uint32_t inc = Crc32c(0, buf.data(), cut);
    inc = Crc32c(inc, buf.data() + cut, buf.size() - cut);
    CHECK(inc == one);
    uint32_t incsw = Crc32cSw(0, buf.data(), cut);
    incsw = Crc32cSw(incsw, buf.data() + cut, buf.size() - cut);
    CHECK(incsw == one);
  }
  std::printf("  crc32c incremental == one-shot (both paths): ok\n");
}

// -- striping policy --------------------------------------------------------

void test_should_stripe_edges() {
  acx::stripe::Config cfg;
  cfg.stripes = 4;
  cfg.min_bytes = 64u << 10;

  // Threshold is INCLUSIVE: exactly min_bytes stripes.
  CHECK(acx::stripe::ShouldStripe(64u << 10, 4, cfg));
  CHECK(!acx::stripe::ShouldStripe((64u << 10) - 1, 4, cfg));

  // One live lane (all others degraded) or stripes=1 config: never.
  CHECK(!acx::stripe::ShouldStripe(1u << 20, 1, cfg));
  acx::stripe::Config off = cfg;
  off.stripes = 1;
  CHECK(!acx::stripe::ShouldStripe(1u << 20, 4, off));

  // Single-chunk refusal: a plan that cannot yield two chunks (message at
  // the kMinChunk floor) is just the eager path with extra headers.
  acx::stripe::Config tiny = cfg;
  tiny.min_bytes = acx::stripe::kMinChunk;
  CHECK(!acx::stripe::ShouldStripe(acx::stripe::kMinChunk, 2, tiny));
  CHECK(acx::stripe::ShouldStripe(2 * acx::stripe::kMinChunk, 2, tiny));
  std::printf("  ShouldStripe boundary/degenerate cases: ok\n");
}

void check_plan_covers(size_t bytes, int lanes) {
  const auto plan = acx::stripe::PlanChunks(bytes, lanes);
  CHECK(!plan.empty());
  uint64_t expect_off = 0;
  for (size_t i = 0; i < plan.size(); i++) {
    CHECK(plan[i].offset == expect_off);
    CHECK(plan[i].len > 0);
    CHECK(plan[i].len <= acx::stripe::kChunkCap);
    // Every chunk but the tail respects the floor.
    if (i + 1 < plan.size()) CHECK(plan[i].len >= acx::stripe::kMinChunk);
    expect_off += plan[i].len;
  }
  CHECK(expect_off == bytes);
}

void test_plan_chunks() {
  // Exact coverage, contiguity and bounds across shapes.
  check_plan_covers(64u << 10, 4);
  check_plan_covers((64u << 10) + 1, 4);
  check_plan_covers(1u << 20, 2);
  check_plan_covers((8u << 20) + 12345, 4);
  check_plan_covers(acx::stripe::kMinChunk - 1, 4);  // sub-floor: one chunk

  // The cap, not the lane count, bounds chunk size: 8 MiB on 4 lanes cuts
  // into 8 chunks of 1 MiB, so round-robin keeps every lane busy for the
  // whole message (chunks > lanes).
  const auto big = acx::stripe::PlanChunks(8u << 20, 4);
  CHECK(big.size() == 8);
  CHECK(static_cast<int>(big.size()) > 4);
  for (const auto& s : big) CHECK(s.len == acx::stripe::kChunkCap);

  // Even split when under the cap: 64 KiB on 4 lanes = 4 x 16 KiB.
  const auto even = acx::stripe::PlanChunks(64u << 10, 4);
  CHECK(even.size() == 4);
  for (const auto& s : even) CHECK(s.len == 16u << 10);
  std::printf("  PlanChunks coverage/cap/floor: ok\n");
}

// -- frame restamp ----------------------------------------------------------

void test_restamp_frame() {
  WireHeader h = MakeHdr(acx::wire::kMagicChunk, /*tag=*/42, /*ctx=*/0,
                         /*bytes=*/128);
  h.seq = 7;
  h.epoch = 1;
  h.crc = 0xDEADBEEFu;
  h.hcrc = acx::wire::HeaderCrc(h);
  char blob[sizeof(WireHeader) + 8] = {};
  memcpy(blob, &h, sizeof h);
  memcpy(blob + sizeof h, "payload", 8);

  // Epoch-only restamp (reconnect adoption): seq untouched, seal valid.
  RestampFrame(blob, /*epoch=*/5);
  WireHeader back;
  memcpy(&back, blob, sizeof back);
  CHECK(back.epoch == 5);
  CHECK(back.seq == 7);
  CHECK(back.crc == 0xDEADBEEFu);
  CHECK(back.hcrc == acx::wire::HeaderCrc(back));

  // Epoch + seq restamp (lane migration into a survivor's seq space).
  const uint64_t nseq = 1001;
  RestampFrame(blob, /*epoch=*/6, &nseq);
  memcpy(&back, blob, sizeof back);
  CHECK(back.epoch == 6);
  CHECK(back.seq == 1001);
  CHECK(FrameSeq(blob) == 1001);
  CHECK(back.hcrc == acx::wire::HeaderCrc(back));
  CHECK(memcmp(blob + sizeof back, "payload", 8) == 0);  // payload untouched
  std::printf("  RestampFrame epoch/seq reseal: ok\n");
}

void test_wire_payload_len() {
  CHECK(WirePayloadLen(MakeHdr(acx::wire::kMagic, 1, 0, 100)) == 100);
  CHECK(WirePayloadLen(MakeHdr(acx::wire::kMagicRts, 1, 0, 1u << 20)) ==
        sizeof(acx::framing::RvDesc));
  CHECK(WirePayloadLen(MakeHdr(acx::wire::kMagicAck, 1, 0, 0)) ==
        sizeof(acx::framing::RvAck));
  CHECK(WirePayloadLen(MakeHdr(acx::wire::kMagicStripe, 1, 0, 1u << 20)) ==
        sizeof(acx::framing::StripeDesc));
  // A chunk advertises its slice length but carries ChunkHdr + slice.
  CHECK(WirePayloadLen(MakeHdr(acx::wire::kMagicChunk, 1, 0, 512)) ==
        sizeof(ChunkHdr) + 512);
  std::printf("  WirePayloadLen per magic: ok\n");
}

// -- replay buffer ----------------------------------------------------------

WireHeader seq_hdr(uint64_t seq, uint64_t bytes) {
  WireHeader h = MakeHdr(acx::wire::kMagic, 1, 0, bytes);
  h.seq = seq;
  h.hcrc = acx::wire::HeaderCrc(h);
  return h;
}

void test_replay_two_segment_record() {
  ReplayBuffer rb;
  ChunkHdr ch{/*msg_id=*/3, /*idx=*/1, /*offset=*/4096, /*len=*/5};
  WireHeader h = seq_hdr(1, 5);
  const char* payload = "hello";
  CHECK(!rb.Record(h, reinterpret_cast<const char*>(&ch), sizeof ch,
                   payload, 5, /*budget=*/1u << 20));
  CHECK(rb.recs.size() == 1);
  const auto& f = rb.recs.front().frame;
  CHECK(f.size() == sizeof h + sizeof ch + 5);
  CHECK(memcmp(f.data(), &h, sizeof h) == 0);
  CHECK(memcmp(f.data() + sizeof h, &ch, sizeof ch) == 0);
  CHECK(memcmp(f.data() + sizeof h + sizeof ch, "hello", 5) == 0);
  CHECK(rb.bytes == f.size());

  // Single-segment form (plain eager frame): head empty.
  WireHeader h2 = seq_hdr(2, 3);
  CHECK(!rb.Record(h2, nullptr, 0, "abc", 3, 1u << 20));
  CHECK(rb.recs.back().frame.size() == sizeof h2 + 3);
  std::printf("  ReplayBuffer two-segment byte-exact record: ok\n");
}

void test_replay_ack_and_eviction() {
  ReplayBuffer rb;
  const size_t budget = 3 * (sizeof(WireHeader) + 64);
  for (uint64_t s = 1; s <= 3; s++) {
    char pay[64];
    memset(pay, static_cast<int>(s), sizeof pay);
    CHECK(!rb.Record(seq_hdr(s, 64), nullptr, 0, pay, 64, budget));
  }
  CHECK(rb.recs.size() == 3 && !rb.broken);

  // Ack trims from the front, partial then full.
  rb.AckThrough(1);
  CHECK(rb.recs.size() == 2 && rb.recs.front().seq == 2);

  // A fourth append overflows the budget (bytes > budget is strict, so
  // shave one byte): the unacked front is evicted, the broken latch
  // flips, and Record reports it.
  char pay[64] = {};
  CHECK(rb.Record(seq_hdr(4, 64), nullptr, 0, pay, 64, budget - 1));
  CHECK(rb.broken);
  CHECK(rb.recs.front().seq == 3);
  std::printf("  ReplayBuffer ack-trim + eviction->broken latch: ok\n");
}

void test_replay_queued_pins() {
  ReplayBuffer rb;
  char pay[64] = {};
  const size_t rec_sz = sizeof(WireHeader) + 64;
  CHECK(!rb.Record(seq_hdr(1, 64), nullptr, 0, pay, 64, 8 * rec_sz));
  CHECK(!rb.Record(seq_hdr(2, 64), nullptr, 0, pay, 64, 8 * rec_sz));
  rb.recs.front().queued = true;  // blob borrowed by an in-flight raw frame

  // Neither ack-trim nor budget pressure may pop a queued front — the
  // outq still points into its blob.
  rb.AckThrough(2);
  CHECK(rb.recs.size() == 2 && rb.recs.front().seq == 1);
  CHECK(!rb.Record(seq_hdr(3, 64), nullptr, 0, pay, 64, /*budget=*/1));
  CHECK(rb.recs.size() == 3 && !rb.broken);  // pinned: nothing evicted

  // Release, then the same pressures apply again.
  rb.ClearQueued(1);
  CHECK(!rb.recs.front().queued);
  rb.AckThrough(2);
  CHECK(rb.recs.size() == 1 && rb.recs.front().seq == 3);
  std::printf("  ReplayBuffer queued-record pinning: ok\n");
}

}  // namespace

int main() {
  std::printf("test_framing:\n");
  test_crc32c_known_vector();
  test_crc32c_sw_matches_hw();
  test_crc32c_incremental();
  test_should_stripe_edges();
  test_plan_chunks();
  test_restamp_frame();
  test_wire_payload_len();
  test_replay_two_segment_record();
  test_replay_ack_and_eviction();
  test_replay_queued_pins();
  std::printf("test_framing: ALL OK\n");
  return 0;
}
