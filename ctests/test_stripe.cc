// Striped-transport edge cases over a real wire (DESIGN.md §15), in the
// test_transport.cc two-ranks-in-one-process shape. Subflows rendezvous
// through the ACX_JOB_ID listener exactly as separate processes would
// (abstract unix sockets are host-scoped, not process-scoped), so the full
// dial/hello/adopt path runs, then:
//
//   - lane bring-up is observable through LinkScope.subflows_up,
//   - the striping threshold is INCLUSIVE at ACX_STRIPE_MIN_BYTES,
//   - messages cut into more chunks than lanes reassemble byte-exact,
//   - a stalled subflow reorders chunk arrival without corrupting data,
//   - ACX_STRIPES=1 puts frames on the wire bit-identical to the default
//     (unstriped) protocol, timestamp field aside.

#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "acx/fault.h"
#include "acx/net.h"
#include "src/net/framing.h"
#include "src/net/wire.h"

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

namespace {

using Clock = std::chrono::steady_clock;

void WaitDone(acx::Ticket* t, acx::Status* st) {
  while (!t->Test(st)) std::this_thread::yield();
}

// A socketpair-connected transport pair with striping armed: job id bound
// (so subflow rendezvous works), ACX_STRIPES/ACX_STRIPE_MIN_BYTES set for
// construction, env restored after (config is read at ctor time).
struct StripedPair {
  std::unique_ptr<acx::Transport> t0, t1;
  StripedPair(int stripes, size_t min_bytes) {
    static int serial = 0;
    char job[64];
    std::snprintf(job, sizeof job, "acx-ctest-stripe-%d-%d", getpid(),
                  serial++);
    setenv("ACX_JOB_ID", job, 1);
    char sbuf[16], mbuf[32];
    std::snprintf(sbuf, sizeof sbuf, "%d", stripes);
    std::snprintf(mbuf, sizeof mbuf, "%zu", min_bytes);
    setenv("ACX_STRIPES", sbuf, 1);
    setenv("ACX_STRIPE_MIN_BYTES", mbuf, 1);
    // Striping rides the eager path; pin rendezvous off so multi-MB test
    // messages stripe instead of taking the process_vm_readv pull.
    setenv("ACX_RV_THRESHOLD", "0", 1);
    int a[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, a) == 0);
    t0.reset(acx::CreateSocketTransport(0, 2, {-1, a[0]}));
    t1.reset(acx::CreateSocketTransport(1, 2, {a[1], -1}));
    unsetenv("ACX_STRIPES");
    unsetenv("ACX_STRIPE_MIN_BYTES");
    unsetenv("ACX_RV_THRESHOLD");
    unsetenv("ACX_JOB_ID");
  }

  // Pump both transports from their own threads until both directions
  // report `want` live lanes. Concurrent pumping matters: the subflow
  // handshake is a blocking hello exchange — the dialer (t0) waits inside
  // its progress engine for the reply, which only materializes when the
  // acceptor (t1) runs ITS progress engine at the same time, exactly as
  // two separate processes would.
  void AwaitSubflows(uint32_t want) {
    std::atomic<bool> stop{false};
    auto pump = [&stop](acx::Transport* mine, acx::Transport* other,
                        int peer) {
      int dummy = 0;
      std::unique_ptr<acx::Ticket> r(
          mine->Irecv(&dummy, sizeof dummy, peer, 98, 0));
      while (!stop.load(std::memory_order_relaxed)) {
        r->Test(nullptr);
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
      }
      int one = 1;  // satisfy the probe before `dummy` leaves scope
      std::unique_ptr<acx::Ticket> s(
          other->Isend(&one, sizeof one, 1 - peer, 98, 0));
      WaitDone(r.get(), nullptr);
      WaitDone(s.get(), nullptr);
    };
    std::thread p0(pump, t0.get(), t1.get(), 1);
    std::thread p1(pump, t1.get(), t0.get(), 0);
    const auto deadline = Clock::now() + std::chrono::seconds(20);
    bool up = false;
    while (!up && Clock::now() < deadline) {
      acx::LinkScope sc0{}, sc1{};
      const bool got = t0->link_scope(1, &sc0) && t1->link_scope(0, &sc1);
      up = got && sc0.subflows_up >= want && sc1.subflows_up >= want;
      if (!up) std::this_thread::sleep_for(std::chrono::milliseconds(5));
    }
    stop.store(true);
    p0.join();
    p1.join();
    CHECK(up);
  }
};

// link_scope is best-effort by contract (try-lock so samplers never block
// the progress engine) — under pump-thread contention it can miss; retry.
acx::LinkScope must_scope(acx::Transport* t, int peer) {
  acx::LinkScope sc{};
  const auto deadline = Clock::now() + std::chrono::seconds(5);
  while (!t->link_scope(peer, &sc)) {
    CHECK(Clock::now() < deadline);
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  return sc;
}

std::vector<char> pattern_buf(size_t n, unsigned seed) {
  std::vector<char> v(n);
  unsigned x = seed * 2654435761u + 12345u;
  for (size_t i = 0; i < n; i++) {
    x = x * 1664525u + 1013904223u;
    v[i] = static_cast<char>(x >> 24);
  }
  return v;
}

// Send n bytes rank0->rank1 and check byte-exact delivery; returns the
// sender's tx_frames delta for the transfer (striping visibility: one
// eager frame vs envelope + chunks).
uint64_t transfer(StripedPair& p, size_t n, unsigned seed) {
  auto src = pattern_buf(n, seed);
  std::vector<char> dst(n, 0);
  const acx::LinkScope before = must_scope(p.t0.get(), 1);
  std::thread peer([&] {
    std::unique_ptr<acx::Ticket> r(p.t1->Irecv(dst.data(), n, 0, 7, 0));
    acx::Status st;
    WaitDone(r.get(), &st);
    CHECK(st.bytes == n);
  });
  std::unique_ptr<acx::Ticket> s(p.t0->Isend(src.data(), n, 1, 7, 0));
  WaitDone(s.get(), nullptr);
  peer.join();
  CHECK(memcmp(src.data(), dst.data(), n) == 0);
  const acx::LinkScope after = must_scope(p.t0.get(), 1);
  return after.tx_frames - before.tx_frames;
}

void test_subflows_establish() {
  StripedPair p(4, 64u << 10);
  p.AwaitSubflows(4);
  acx::LinkScope sc = must_scope(p.t0.get(), 1);
  CHECK(sc.subflows == 4 && sc.subflows_up == 4);
  sc = must_scope(p.t1.get(), 0);
  CHECK(sc.subflows == 4 && sc.subflows_up == 4);
  std::printf("  4 subflows rendezvous + adopt (both sides): ok\n");
}

void test_min_bytes_boundary() {
  StripedPair p(4, 64u << 10);
  p.AwaitSubflows(4);
  // Exactly min_bytes stripes (inclusive threshold): envelope + 4 chunks
  // of 16 KiB = 5 sequenced frames, allow a stray heartbeat on top.
  const uint64_t at = transfer(p, 64u << 10, 1);
  CHECK(at >= 5);
  // One byte under: the plain eager path — a single data frame.
  const uint64_t under = transfer(p, (64u << 10) - 1, 2);
  CHECK(under <= 2);
  std::printf("  min-bytes boundary (inclusive): %llu frames at, %llu under: ok\n",
              (unsigned long long)at, (unsigned long long)under);
}

void test_chunks_exceed_lanes() {
  StripedPair p(4, 64u << 10);
  p.AwaitSubflows(4);
  // 8 MiB on 4 lanes cuts at the 1 MiB chunk cap into 8 chunks — more
  // chunks than lanes, so round-robin wraps and every lane carries two.
  const uint64_t frames = transfer(p, 8u << 20, 3);
  CHECK(frames >= 9);  // envelope + 8 chunks
  std::printf("  8MiB / 4 lanes (chunks > lanes): %llu frames: ok\n",
              (unsigned long long)frames);
}

void test_stalled_subflow_reorders_byte_exact() {
  StripedPair p(2, 16u << 10);
  p.AwaitSubflows(2);
  // Stall lane 1 on the sender for 60ms per matching frame: lane 0's
  // chunks race ahead, so chunk arrival order inverts relative to offset
  // order. Self-describing ChunkHdr offsets must reassemble regardless.
  acx::fault::Config c;
  CHECK(acx::fault::ParseSpec("stall_link_ms:rank=0:subflow=1:nth=1:count=3:ms=60",
                              &c));
  acx::fault::Configure(c);
  for (unsigned i = 0; i < 3; i++) transfer(p, 64u << 10, 10 + i);
  acx::fault::Configure(acx::fault::Config{});  // disarm
  std::printf("  stalled-subflow chunk reorder, byte-exact x3: ok\n");
}

// Capture the first DATA frame rank 0 puts on a raw wire for one 128-byte
// send under the given env, skipping control frames. No job id: recovery
// stays unarmed, so nothing but frames we can parse crosses the fd.
std::vector<char> sniff_data_frame(const char* stripes_env) {
  if (stripes_env != nullptr)
    setenv("ACX_STRIPES", stripes_env, 1);
  else
    unsetenv("ACX_STRIPES");
  int a[2];
  CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, a) == 0);
  std::unique_ptr<acx::Transport> t0(
      acx::CreateSocketTransport(0, 2, {-1, a[0]}));
  unsetenv("ACX_STRIPES");
  auto src = pattern_buf(128, 42);
  std::unique_ptr<acx::Ticket> s(t0->Isend(src.data(), 128, 1, 7, 0));
  WaitDone(s.get(), nullptr);
  for (;;) {
    acx::wire::WireHeader h;
    size_t got = 0;
    while (got < sizeof h) {
      ssize_t n = read(a[1], reinterpret_cast<char*>(&h) + got,
                       sizeof h - got);
      CHECK(n > 0);
      got += static_cast<size_t>(n);
    }
    std::vector<char> frame(reinterpret_cast<const char*>(&h),
                            reinterpret_cast<const char*>(&h) + sizeof h);
    frame.resize(sizeof h + acx::framing::WirePayloadLen(h));
    size_t off = sizeof h;
    while (off < frame.size()) {
      ssize_t n = read(a[1], frame.data() + off, frame.size() - off);
      CHECK(n > 0);
      off += static_cast<size_t>(n);
    }
    if (h.magic == acx::wire::kMagic) {
      close(a[1]);
      return frame;
    }
  }
}

void test_stripes1_frames_bit_identical() {
  // ACX_STRIPES=1 must put the SAME bytes on the wire as the default
  // config — the striped protocol is invisible until it is both enabled
  // and rendezvous-armed. tx_ns is a wall-clock stamp (and hcrc seals the
  // header over it), so those two fields are normalized before comparing;
  // every other header byte and the payload must match bit for bit.
  std::vector<char> a = sniff_data_frame(nullptr);
  std::vector<char> b = sniff_data_frame("1");
  CHECK(a.size() == b.size());
  acx::wire::WireHeader ha, hb;
  memcpy(&ha, a.data(), sizeof ha);
  memcpy(&hb, b.data(), sizeof hb);
  CHECK(ha.hcrc == acx::wire::HeaderCrc(ha));  // both seals valid as-sent
  CHECK(hb.hcrc == acx::wire::HeaderCrc(hb));
  ha.tx_ns = hb.tx_ns = 0;
  ha.hcrc = hb.hcrc = 0;
  CHECK(memcmp(&ha, &hb, sizeof ha) == 0);
  CHECK(memcmp(a.data() + sizeof ha, b.data() + sizeof hb,
               a.size() - sizeof ha) == 0);
  std::printf("  stripes=1 frames bit-identical to default wire: ok\n");
}

}  // namespace

int main() {
  std::printf("test_stripe:\n");
  test_stripes1_frames_bit_identical();
  test_subflows_establish();
  test_min_bytes_boundary();
  test_chunks_exceed_lanes();
  test_stalled_subflow_reorders_byte_exact();
  std::printf("test_stripe: ALL OK\n");
  return 0;
}
