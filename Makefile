# tpu-acx native runtime build.
# Counterpart of the reference's nvcc Makefile (reference Makefile:1-49), but
# plain g++: the device compiler on TPU is XLA/Pallas, reached from Python;
# everything here is host-side runtime.

CXX      ?= g++
CXXFLAGS ?= -O2 -g -Wall -Wextra -std=c++17 -fPIC -pthread
INCLUDES  = -Iinclude
LDFLAGS   = -pthread

BUILD := build

CORE_SRCS := src/core/flagtable.cc src/core/proxy.cc
SHIM_SRCS := src/shim/transport.cc src/shim/mpi_shim.cc
RT_SRCS   := src/runtime/stream.cc src/runtime/cuda_shim.cc
API_SRCS  := src/api/mpix.cc

LIB_SRCS := $(CORE_SRCS) $(SHIM_SRCS) $(RT_SRCS) $(API_SRCS)
LIB_OBJS := $(LIB_SRCS:%.cc=$(BUILD)/%.o)

LIB       = $(BUILD)/libtpuacx.so
STATICLIB = $(BUILD)/libtpuacx.a

CTEST_BINS = $(BUILD)/test_core

.PHONY: all lib clean check ctest

all: lib tools ctest

lib: $(LIB) $(STATICLIB)

$(BUILD)/%.o: %.cc
	@mkdir -p $(dir $@)
	$(CXX) $(CXXFLAGS) $(INCLUDES) -c $< -o $@

$(LIB): $(LIB_OBJS)
	$(CXX) -shared $(LIB_OBJS) -o $@ $(LDFLAGS)

$(STATICLIB): $(LIB_OBJS)
	ar rcs $@ $(LIB_OBJS)

# --- unit tests (no transport needed) ---
ctest: $(CTEST_BINS)

$(BUILD)/test_core: ctests/test_core.cc $(BUILD)/src/core/flagtable.o $(BUILD)/src/core/proxy.o
	$(CXX) $(CXXFLAGS) $(INCLUDES) $^ -o $@ $(LDFLAGS)

check: ctest
	$(BUILD)/test_core

# --- launcher ---
.PHONY: tools
tools: $(BUILD)/acxrun

$(BUILD)/acxrun: tools/acxrun.cc
	@mkdir -p $(BUILD)
	$(CXX) $(CXXFLAGS) $(INCLUDES) $< -o $@ $(LDFLAGS)

clean:
	rm -rf $(BUILD)
