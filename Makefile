# tpu-acx native runtime build.
# Counterpart of the reference's nvcc Makefile (reference Makefile:1-49), but
# plain g++: the device compiler on TPU is XLA/Pallas, reached from Python;
# everything here is host-side runtime (proxy, transport, stream/graph queue,
# public MPIX API, launcher).
#
# Knobs (mirroring reference Makefile:1-6):
#   CXX              host compiler (default g++)
#   ACX_DEBUG=1      compile in debug logging (reference: -DDEBUG)

CXX      ?= g++
CXXFLAGS ?= -O2 -g -Wall -Wextra -std=c++17 -fPIC -pthread -MMD -MP
INCLUDES  = -Iinclude -Iinclude/compat -I.
LDFLAGS   = -pthread

ifeq ($(ACX_DEBUG), 1)
CXXFLAGS += -DACX_DEBUG
endif

BUILD := build

# Sources are wildcarded: every directory below is part of the library the
# moment its files exist, and `make all` never references a file that does not.
LIB_SRCS := $(wildcard src/core/*.cc) \
            $(wildcard src/net/*.cc) \
            $(wildcard src/runtime/*.cc) \
            $(wildcard src/shim/*.cc) \
            $(wildcard src/api/*.cc)
LIB_OBJS := $(LIB_SRCS:%.cc=$(BUILD)/%.o)

LIB       = $(BUILD)/libtpuacx.so
STATICLIB = $(BUILD)/libtpuacx.a

.PHONY: all lib tools ctest itest check reftests clean

all: lib tools ctest itest

lib: $(LIB) $(STATICLIB)

$(BUILD)/%.o: %.cc
	@mkdir -p $(dir $@)
	$(CXX) $(CXXFLAGS) $(INCLUDES) -c $< -o $@

$(LIB): $(LIB_OBJS)
	$(CXX) -shared $(LIB_OBJS) -o $@ $(LDFLAGS)

$(STATICLIB): $(LIB_OBJS)
	ar rcs $@ $(LIB_OBJS)

# --- launcher (reference: mpiexec; ours: acxrun) ---
TOOL_SRCS := $(wildcard tools/*.cc)
TOOL_BINS := $(TOOL_SRCS:tools/%.cc=$(BUILD)/%)

tools: $(TOOL_BINS)

$(BUILD)/%: tools/%.cc $(STATICLIB)
	@mkdir -p $(BUILD)
	$(CXX) $(CXXFLAGS) $(INCLUDES) $< $(STATICLIB) -o $@ $(LDFLAGS)

# --- unit tests (single process, fake transport) ---
CTEST_SRCS := $(wildcard ctests/*.cc)
CTEST_BINS := $(CTEST_SRCS:ctests/%.cc=$(BUILD)/ctests/%)

ctest: $(CTEST_BINS)

$(BUILD)/ctests/%: ctests/%.cc $(STATICLIB)
	@mkdir -p $(BUILD)/ctests
	$(CXX) $(CXXFLAGS) $(INCLUDES) $< $(STATICLIB) -o $@ $(LDFLAGS)

# --- integration tests (multi-process, run under acxrun) ---
# Ports of the reference's six ring programs (reference test/src/*); built
# against the same compat headers (include/compat) the reference tests use.
ITEST_SRCS := $(wildcard itests/*.c) $(wildcard itests/*.cc)
ITEST_BINS := $(patsubst itests/%.c,$(BUILD)/itests/%,$(filter %.c,$(ITEST_SRCS))) \
              $(patsubst itests/%.cc,$(BUILD)/itests/%,$(filter %.cc,$(ITEST_SRCS)))

itest: $(ITEST_BINS)

$(BUILD)/itests/%: itests/%.c $(STATICLIB)
	@mkdir -p $(BUILD)/itests
	$(CXX) $(CXXFLAGS) $(INCLUDES) -x c++ $< -x none $(STATICLIB) -o $@ $(LDFLAGS)

$(BUILD)/itests/%: itests/%.cc $(STATICLIB)
	@mkdir -p $(BUILD)/itests
	$(CXX) $(CXXFLAGS) $(INCLUDES) $< $(STATICLIB) -o $@ $(LDFLAGS)

# --- reference-test source compatibility ---
# Compiles NVIDIA/mpi-acx's own C test programs UNCHANGED from
# /root/reference/test/src against our compat headers (mpi.h, cuda_runtime.h,
# mpi-acx.h) and runs them under acxrun. This is the north-star check:
# "test/ builds unchanged". (ring-partitioned.cu needs nvcc and is covered by
# our itests/ring-partitioned port instead.)
REF          ?= /root/reference
REF_TEST_DIR ?= $(REF)/test/src
REF_TESTS := ring ring-all ring-all-device ring-all-graph ring-all-graph-construction
REF_BINS  := $(REF_TESTS:%=$(BUILD)/reftests/%)

reftests: $(REF_BINS) tools
	@for t in $(REF_BINS); do echo "== acxrun -np 2 $$t"; $(BUILD)/acxrun -np 2 $$t || exit 1; done
	@echo "ALL REFERENCE TESTS PASSED"

$(BUILD)/reftests/%: $(REF_TEST_DIR)/%.c $(STATICLIB)
	@mkdir -p $(BUILD)/reftests
	$(CXX) $(CXXFLAGS) -Wno-unused-parameter $(INCLUDES) -x c++ $< -x none $(STATICLIB) -o $@ $(LDFLAGS)

# --- run everything ---
# Integration tests run on both data planes: shm (default, SPSC rings in a
# memfd) and socket (AF_UNIX, the cross-host-shaped wire).
check: ctest itest tools
	@for t in $(CTEST_BINS); do echo "== $$t"; $$t || exit 1; done
	@for t in $(ITEST_BINS); do echo "== acxrun -np 2 $$t (shm)"; $(BUILD)/acxrun -np 2 $$t || exit 1; done
	@for t in $(ITEST_BINS); do echo "== acxrun -np 2 $$t (socket)"; $(BUILD)/acxrun -np 2 -transport socket $$t || exit 1; done
	@for t in $(ITEST_BINS); do echo "== acxrun -np 2 $$t (rendezvous-all)"; ACX_RV_THRESHOLD=1 $(BUILD)/acxrun -np 2 $$t || exit 1; done
	@for t in $(ITEST_BINS); do echo "== acxrun -np 2 $$t (rendezvous-nack)"; ACX_RV_THRESHOLD=1 ACX_RV_FORCE_FALLBACK=1 $(BUILD)/acxrun -np 2 $$t || exit 1; done
	@for t in $(ITEST_BINS); do echo "== acxrun -np 2 $$t (rendezvous-socket)"; ACX_RV_THRESHOLD=1 $(BUILD)/acxrun -np 2 -transport socket $$t || exit 1; done
	@for t in $(ITEST_BINS); do echo "== acxrun -np 4 $$t (shm, 4 ranks)"; $(BUILD)/acxrun -np 4 $$t || exit 1; done
	@echo "== acxrun -np 2 fuzz (canary: corruption must be DETECTED)"
	@ACX_FUZZ_CANARY=1 $(BUILD)/acxrun -np 2 $(BUILD)/itests/fuzz || exit 1
	@echo "== acxrun -np 2 fuzz (second seed)"
	@ACX_FUZZ_SEED=98761 $(BUILD)/acxrun -np 2 $(BUILD)/itests/fuzz || exit 1
	@echo "== acxrun -np 2 ring (fault: transient send drop -> retry -> OK)"
	@$(BUILD)/acxrun -np 2 -fault drop:rank=0:kind=send:nth=1 $(BUILD)/itests/ring || exit 1
	@echo "== acxrun -np 2 ring (fault: 5ms delay on rank 1's first recv)"
	@$(BUILD)/acxrun -np 2 -fault delay:rank=1:kind=recv:nth=1:us=5000 $(BUILD)/itests/ring || exit 1
	@$(MAKE) --no-print-directory chaos-check || exit 1
	@$(MAKE) --no-print-directory membership-check || exit 1
	@$(MAKE) --no-print-directory metrics-check || exit 1
	@$(MAKE) --no-print-directory tseries-check || exit 1
	@$(MAKE) --no-print-directory doctor-check || exit 1
	@$(MAKE) --no-print-directory causality-check || exit 1
	@$(MAKE) --no-print-directory decode-check || exit 1
	@$(MAKE) --no-print-directory stripe-check || exit 1
	@$(MAKE) --no-print-directory disagg-check || exit 1
	@$(MAKE) --no-print-directory paged-check || exit 1
	@$(MAKE) --no-print-directory request-check || exit 1
	@$(MAKE) --no-print-directory lint || exit 1
	@$(MAKE) --no-print-directory asan-ctest || exit 1
	@echo "ALL NATIVE TESTS PASSED"

# --- survivable links end-to-end (DESIGN.md §9) ---
# chaos-ring under every wire-level fault on the socket plane (the only
# plane with reconnectable links), drain-on-death with a mid-flight rank
# kill, and a metrics-instrumented chaos leg validated by the merge tool.
.PHONY: chaos-check
chaos-check: itest tools
	@echo "== chaos-check: drop_frame (sequence gap -> NAK re-pull)"
	@$(BUILD)/acxrun -np 2 -transport socket \
	  -fault drop_frame:rank=0:nth=3:count=2 $(BUILD)/itests/chaos-ring || exit 1
	@echo "== chaos-check: corrupt_frame (CRC reject -> NAK -> replay)"
	@$(BUILD)/acxrun -np 2 -transport socket \
	  -fault corrupt_frame:rank=1:nth=4:count=3 $(BUILD)/itests/chaos-ring || exit 1
	@echo "== chaos-check: stall_link_ms (frozen send side, no loss)"
	@$(BUILD)/acxrun -np 2 -transport socket \
	  -fault stall_link_ms:rank=0:nth=5:ms=40 $(BUILD)/itests/chaos-ring || exit 1
	@echo "== chaos-check: close_link_once (epoch-bumped reconnect + replay)"
	@$(BUILD)/acxrun -np 2 -transport socket \
	  -fault close_link_once:rank=0:nth=6 $(BUILD)/itests/chaos-ring || exit 1
	@echo "== chaos-check: drain-on-death (survivors drain and exit 0)"
	@$(BUILD)/acxrun -np 3 $(BUILD)/itests/drain-on-death || exit 1
	@echo "== chaos-check: fault placement sweep (3 fixed seeds)"
	@$(BUILD)/acxrun -np 2 -transport socket \
	  -fault drop_frame:rank=1:nth=7:count=1 $(BUILD)/itests/chaos-ring || exit 1
	@$(BUILD)/acxrun -np 2 -transport socket \
	  -fault corrupt_frame:rank=0:nth=9:count=2 $(BUILD)/itests/chaos-ring || exit 1
	@$(BUILD)/acxrun -np 2 -transport socket \
	  -fault stall_link_ms:rank=1:nth=3:ms=60 $(BUILD)/itests/chaos-ring || exit 1
	@rm -rf $(BUILD)/chaos-metrics && mkdir -p $(BUILD)/chaos-metrics
	@echo "== chaos-check: corrupt_frame with ACX_METRICS + ACX_TRACE"
	@ACX_METRICS=$(BUILD)/chaos-metrics/run ACX_TRACE=$(BUILD)/chaos-metrics/run \
	  $(BUILD)/acxrun -np 2 -transport socket \
	  -fault corrupt_frame:rank=0:nth=2 $(BUILD)/itests/chaos-ring || exit 1
	@python3 tools/acx_trace_merge.py --validate \
	  --out $(BUILD)/chaos-metrics/merged.trace.json \
	  --metrics-out $(BUILD)/chaos-metrics/fleet.metrics.json \
	  $(BUILD)/chaos-metrics/run.rank*.trace.json \
	  $(BUILD)/chaos-metrics/run.rank*.metrics.json || exit 1
	@echo "== chaos-check: conductor kill + respawn + invariant oracle"
	@rm -rf $(BUILD)/chaos-oracle
	@python3 tools/acx_chaos.py run --np 3 --timeout 90 \
	  --acxrun $(BUILD)/acxrun --out $(BUILD)/chaos-oracle/kill \
	  --fault kill:rank=1:nth=7 \
	  -- $(BUILD)/itests/chaos-conductor || exit 1
	@echo "== chaos-check: conductor kill mid-stripe (2 lanes, big rounds)"
	@ACX_STRIPES=2 ACX_CC_INTS=16384 \
	  python3 tools/acx_chaos.py run --np 3 --timeout 90 \
	  --acxrun $(BUILD)/acxrun --out $(BUILD)/chaos-oracle/stripe-kill \
	  --fault kill:rank=1:nth=7 \
	  -- $(BUILD)/itests/chaos-conductor || exit 1
	@echo "== chaos-check: broken control (must fail, shrink, print replay)"
	@python3 tools/acx_chaos.py run --np 3 --timeout 60 --expect-fail \
	  --acxrun $(BUILD)/acxrun --out $(BUILD)/chaos-oracle/broken \
	  --fault 'stall_link_ms:rank=0:nth=3:ms=20;drop_frame:rank=0:nth=500000' \
	  -- $(BUILD)/itests/chaos-conductor || exit 1
	@$(MAKE) --no-print-directory chaos-soak SEEDS=3 || exit 1
	@echo "CHAOS CHECK PASSED"

# --- seeded multi-fault soak (tentpole PR: chaos conductor) ---
# N consecutive seeds from ACX_CHAOS_SEED_BASE (default 1000); each seed
# deterministically expands (acxrun -print-chaos) into a multi-fault
# schedule, runs the conductor under it, and is audited by the invariant
# oracle — every scheduled fault must actually fire. A nightly rotation
# just sets ACX_CHAOS_SEED_BASE=$(date +%j)000 or similar; any failure
# prints a shrunken schedule and an exact replay command.
.PHONY: chaos-soak
SEEDS ?= 3
chaos-soak: itest tools
	@python3 tools/acx_chaos.py soak --np 3 --seeds $(SEEDS) \
	  --faults 4 --mix issue,wire --timeout 90 \
	  --acxrun $(BUILD)/acxrun --out $(BUILD)/chaos-soak \
	  -- $(BUILD)/itests/chaos-conductor || exit 1

# --- elastic fleet / membership plane end-to-end (DESIGN.md §12) ---
# rolling-restart replaces every rank of the fleet one at a time under
# load (socket plane: the only one a joiner can dial into), at two fleet
# sizes, then deliberately wedges a join (ACX_RR_WEDGE=1): survivors must
# time the join out with exit 7 and flight dumps, and acx_doctor.py must
# attribute the hang to the victim even with its dump deleted — the gap
# itself is the evidence.
.PHONY: membership-check
membership-check: itest tools
	@echo "== membership-check: rolling-restart -np 2 (socket)"
	@$(BUILD)/acxrun -np 2 -timeout 120 -transport socket \
	  $(BUILD)/itests/rolling-restart || exit 1
	@echo "== membership-check: rolling-restart -np 3 (socket)"
	@$(BUILD)/acxrun -np 3 -timeout 120 -transport socket \
	  $(BUILD)/itests/rolling-restart || exit 1
	@rm -rf $(BUILD)/membership-check && mkdir -p $(BUILD)/membership-check
	@echo "== membership-check: wedged join (exit 7 + doctor attribution)"
	@ACX_RR_WEDGE=1 ACX_FLEET_JOIN_TIMEOUT_MS=8000 \
	  ACX_FLIGHT=$(BUILD)/membership-check/rr \
	  $(BUILD)/acxrun -np 3 -timeout 120 -transport socket \
	  $(BUILD)/itests/rolling-restart; \
	  st=$$?; [ $$st -eq 7 ] || { echo "wedge leg: want exit 7, got $$st"; exit 1; }
	@rm -f $(BUILD)/membership-check/rr.rank1.flight.json
	@python3 tools/acx_doctor.py \
	  --expect-anomaly dead_link --expect-culprit 1 \
	  $(BUILD)/membership-check/rr.rank*.flight.json || exit 1
	@echo "MEMBERSHIP CHECK PASSED"

# --- metrics plane end-to-end ---
# 2-rank ping-pong with metrics + tracing on, then validate every artifact
# (span balance, counter/histogram invariants) and produce the merged
# Perfetto timeline + fleet metrics with tools/acx_trace_merge.py.
.PHONY: metrics-check
metrics-check: ctest tools
	@rm -rf $(BUILD)/metrics-check && mkdir -p $(BUILD)/metrics-check
	@echo "== metrics-check: acxrun -np 2 bench_pingpong (ACX_METRICS + ACX_TRACE)"
	@ACX_METRICS=$(BUILD)/metrics-check/run ACX_TRACE=$(BUILD)/metrics-check/run \
	  ACX_TRACE_CAP=2000000 \
	  $(BUILD)/acxrun -np 2 $(BUILD)/bench_pingpong 8 > /dev/null || exit 1
	@python3 tools/acx_trace_merge.py --validate \
	  --out $(BUILD)/metrics-check/merged.trace.json \
	  --metrics-out $(BUILD)/metrics-check/fleet.metrics.json \
	  $(BUILD)/metrics-check/run.rank*.trace.json \
	  $(BUILD)/metrics-check/run.rank*.metrics.json || exit 1
	@echo "== metrics-check: flight-recorder hot-path overhead bound"
	@$(BUILD)/ctests/test_flight || exit 1
	@echo "METRICS CHECK PASSED"

# --- live telemetry plane end-to-end (DESIGN.md §13) ---
# 2-rank ping-pong with periodic sampling on, then acx_top's CI mode
# asserts series sanity (>= 2 samples/rank, monotone clocks, per-link
# wire >= payload byte accounting), the name-table ctest runs, and the
# merge tool folds the tseries stream in with barrier-anchored skew.
.PHONY: tseries-check
tseries-check: ctest tools
	@rm -rf $(BUILD)/tseries-check && mkdir -p $(BUILD)/tseries-check
	@echo "== tseries-check: acxrun -np 2 bench_pingpong (ACX_TSERIES)"
	@ACX_TSERIES=$(BUILD)/tseries-check/run ACX_TSERIES_INTERVAL_MS=50 \
	  ACX_TRACE=$(BUILD)/tseries-check/run \
	  $(BUILD)/acxrun -np 2 $(BUILD)/bench_pingpong 8 > /dev/null || exit 1
	@echo "== tseries-check: acx_top --once --json --check"
	@python3 tools/acx_top.py --once --json --check \
	  $(BUILD)/tseries-check/run > /dev/null || exit 1
	@echo "== tseries-check: skew-corrected fleet merge"
	@python3 tools/acx_trace_merge.py --validate \
	  --tseries-out $(BUILD)/tseries-check/fleet.tseries.json \
	  $(BUILD)/tseries-check/run.rank*.trace.json \
	  $(BUILD)/tseries-check/run.rank*.tseries.jsonl || exit 1
	@echo "== tseries-check: metrics name-table/enum agreement"
	@$(BUILD)/ctests/test_metrics_names || exit 1
	@echo "TSERIES CHECK PASSED"

# --- stall watchdog + hang doctor end-to-end (DESIGN.md §10) ---
# hang-doctor wedges ranks 0/1 on purpose (withheld Pready + unanswered
# recv); every stuck rank's watchdog must write a flight dump while the job
# is hung, and tools/acx_doctor.py must pair the per-rank dumps and name
# both the anomaly and the culprit rank.
.PHONY: doctor-check
doctor-check: ctest itest tools
	@rm -rf $(BUILD)/doctor-check && mkdir -p $(BUILD)/doctor-check
	@echo "== doctor-check: acxrun -np 2 hang-doctor (watchdog dumps fire)"
	@ACX_FLIGHT=$(BUILD)/doctor-check/hang \
	  $(BUILD)/acxrun -np 2 $(BUILD)/itests/hang-doctor || exit 1
	@echo "== doctor-check: acx_doctor.py names the culprit"
	@python3 tools/acx_doctor.py \
	  --expect-anomaly never_published_partition --expect-culprit 0 \
	  $(BUILD)/doctor-check/hang.rank*.flight.json || exit 1
	@echo "DOCTOR CHECK PASSED"

# --- cross-rank causal tracing end-to-end (DESIGN.md §14) ---
# causality-ping runs a strictly serialized 2-rank ping-pong on the
# socket plane with tracing on; acx_critpath.py must span-pair >= 95% of
# wire frames across the ranks (no heuristics), see non-negative one-way
# transit after the barrier-anchored skew correction, and reconstruct a
# non-empty critical path. The stall leg injects a 40 ms freeze on rank
# 0's 5th frame and the analyzer must name the 0->1 link as the longest
# edge of the step — the whole point of the plane.
.PHONY: causality-check
causality-check: itest tools
	@rm -rf $(BUILD)/causality-check && mkdir -p $(BUILD)/causality-check
	@echo "== causality-check: acxrun -np 2 causality-ping (socket, ACX_TRACE)"
	@ACX_TRACE=$(BUILD)/causality-check/ping ACX_TRACE_CAP=2000000 \
	  $(BUILD)/acxrun -np 2 -transport socket \
	  $(BUILD)/itests/causality-ping || exit 1
	@echo "== causality-check: merged trace validates"
	@python3 tools/acx_trace_merge.py --validate \
	  --out $(BUILD)/causality-check/merged.trace.json \
	  $(BUILD)/causality-check/ping.rank*.trace.json > /dev/null || exit 1
	@echo "== causality-check: span pairing + transit + critical path"
	@python3 tools/acx_critpath.py --min-pair-rate 0.95 \
	  --expect-nonneg-transit \
	  $(BUILD)/causality-check/ping.rank*.trace.json || exit 1
	@echo "== causality-check: injected stall names the 0->1 link"
	@ACX_TRACE=$(BUILD)/causality-check/stall ACX_TRACE_CAP=2000000 \
	  $(BUILD)/acxrun -np 2 -transport socket \
	  -fault stall_link_ms:rank=0:nth=5:ms=40 \
	  $(BUILD)/itests/causality-ping || exit 1
	@python3 tools/acx_critpath.py --expect-edge "0->1" \
	  $(BUILD)/causality-check/stall.rank*.trace.json || exit 1
	@echo "CAUSALITY CHECK PASSED"

# --- flash-decode kernel (ops/flash_decode.py, DESIGN.md §11) ---
# Interpret-mode parity of the Pallas decode kernel vs the dense
# reference (GQA/window/per-slot-pos/int8 grid + block-skip), then a
# CPU dryrun of the decode bench child asserting the dense-vs-flash
# A/B rows land. No chip required — the kernel runs interpreted.
.PHONY: decode-check
decode-check:
	@echo "== decode-check: flash-decode interpret parity"
	@JAX_PLATFORMS=cpu python3 -m pytest tests/test_flash_decode.py -q \
	  -p no:cacheprovider || exit 1
	@echo "== decode-check: bench.py --dryrun-decode (A/B rows emitted)"
	@JAX_PLATFORMS=cpu python3 bench.py --dryrun-decode || exit 1
	@echo "DECODE CHECK PASSED"

# --- multi-path striped transport end-to-end (DESIGN.md §15) ---
# chaos-ring with 64 KiB messages fanned across subflows: healthy striped
# traffic, a dropped chunk NAK-healed in its own lane's seq space, a
# stalled lane forcing cross-lane chunk reorder, a killed lane redialing
# (or degrading to survivors) under load — every payload byte-exact
# throughout — plus a striped causality leg whose merged trace still
# pairs every span and keeps one-way transit non-negative.
.PHONY: stripe-check
stripe-check: itest tools
	@echo "== stripe-check: striped chaos-ring (4 lanes, 64KiB msgs, fault-free)"
	@ACX_STRIPES=4 ACX_CHAOS_INTS=16384 $(BUILD)/acxrun -np 2 -transport socket \
	  $(BUILD)/itests/chaos-ring || exit 1
	@echo "== stripe-check: drop_frame on subflow 2 (per-lane NAK re-pull)"
	@ACX_STRIPES=4 ACX_CHAOS_INTS=16384 $(BUILD)/acxrun -np 2 -transport socket \
	  -fault drop_frame:rank=0:subflow=2:nth=4:count=2 $(BUILD)/itests/chaos-ring || exit 1
	@echo "== stripe-check: stall_link_ms on subflow 1 (cross-lane reorder)"
	@ACX_STRIPES=2 ACX_CHAOS_INTS=16384 $(BUILD)/acxrun -np 2 -transport socket \
	  -fault stall_link_ms:rank=0:subflow=1:nth=3:ms=40 $(BUILD)/itests/chaos-ring || exit 1
	@echo "== stripe-check: close_link_once on subflow 1 (lane redial under load)"
	@ACX_STRIPES=2 ACX_CHAOS_INTS=16384 $(BUILD)/acxrun -np 2 -transport socket \
	  -fault close_link_once:rank=0:subflow=1:nth=5 $(BUILD)/itests/chaos-ring || exit 1
	@rm -rf $(BUILD)/stripe-check && mkdir -p $(BUILD)/stripe-check
	@echo "== stripe-check: striped causality-ping (spans pair, transit >= 0)"
	@ACX_STRIPES=2 ACX_PING_INTS=16384 ACX_TRACE=$(BUILD)/stripe-check/ping \
	  ACX_TRACE_CAP=2000000 $(BUILD)/acxrun -np 2 -transport socket \
	  $(BUILD)/itests/causality-ping || exit 1
	@python3 tools/acx_critpath.py --min-pair-rate 0.95 \
	  --expect-nonneg-transit \
	  $(BUILD)/stripe-check/ping.rank*.trace.json || exit 1
	@echo "STRIPE CHECK PASSED"

# --- disaggregated prefill/decode serving (DESIGN.md §17) ---
# Loopback parity suite (the full wire handoff bit-equal to the
# monolithic server, mid-handoff failure requeue), a 3-rank role-split
# fleet (1 prefill + 2 decode) on the socket plane with both decode
# ranks byte-checking against a local monolithic serve, the same fleet
# with the prefill rank SIGKILLed mid-handoff under the chaos oracle
# (supervisor respawns it, the torn handoff requeues UNCHARGED, the
# re-ship satisfies it, acx_doctor attributes the dead link), and the
# bench disagg dryrun (TTFT-split + handoff-GB/s rows land).
.PHONY: disagg-check
disagg-check: tools
	@echo "== disagg-check: loopback parity + handoff-failure suite"
	@JAX_PLATFORMS=cpu python3 -m pytest tests/test_disagg.py -q \
	  -p no:cacheprovider || exit 1
	@echo "== disagg-check: 3-rank role-split fleet (1 prefill + 2 decode)"
	@ACX_ROLE=prefill,decode,decode $(BUILD)/acxrun -np 3 -timeout 240 \
	  -transport socket python3 tests/disagg_worker.py || exit 1
	@echo "== disagg-check: kill prefill mid-handoff (chaos oracle + doctor)"
	@rm -rf $(BUILD)/disagg-oracle
	@ACX_ROLE=prefill,decode,decode python3 tools/acx_chaos.py run --np 3 \
	  --timeout 240 --acxrun $(BUILD)/acxrun \
	  --out $(BUILD)/disagg-oracle/kill --fault kill:rank=0:nth=8 \
	  -- python3 tests/disagg_worker.py || exit 1
	@echo "== disagg-check: bench.py --dryrun-disagg (TTFT split rows)"
	@JAX_PLATFORMS=cpu python3 bench.py --dryrun-disagg || exit 1
	@echo "DISAGG CHECK PASSED"

# --- paged KV cache + radix prefix sharing + page-pressure scheduling
# (DESIGN.md §19). Four legs: the pytest suite (kernel bit-parity grid,
# allocator/trie/COW units, serve_paged_greedy vs serve_greedy
# bit-equality incl. preempt-then-resume, prefix reuse), a CPU interpret
# smoke of the paged Pallas kernel proper, the 3-rank fleet with decode
# ranks seating SHIPPED pages (byte-checked against a local monolithic
# serve) plus the same fleet with the prefill rank SIGKILLed under the
# chaos oracle, and the bench paged dryrun (HBM-scaling + prefix-TTFT +
# fixed-budget-concurrency rows land in the newest BENCH_r*.json).
.PHONY: paged-check
paged-check: tools
	@echo "== paged-check: paged KV parity + scheduler suite"
	@JAX_PLATFORMS=cpu python3 -m pytest tests/test_paged.py -q \
	  -p no:cacheprovider || exit 1
	@echo "== paged-check: paged Pallas kernel interpret smoke"
	@JAX_PLATFORMS=cpu python3 -m pytest \
	  "tests/test_paged.py::test_paged_flash_bit_equals_fixed_flash" -q \
	  -p no:cacheprovider || exit 1
	@echo "== paged-check: 3-rank fleet, decode ranks on paged pools"
	@ACX_ROLE=prefill,decode,decode $(BUILD)/acxrun -np 3 -timeout 240 \
	  -transport socket python3 tests/paged_worker.py || exit 1
	@echo "== paged-check: kill prefill mid-handoff (paged intake rollback)"
	@rm -rf $(BUILD)/paged-oracle
	@ACX_ROLE=prefill,decode,decode python3 tools/acx_chaos.py run --np 3 \
	  --timeout 240 --acxrun $(BUILD)/acxrun \
	  --out $(BUILD)/paged-oracle/kill --fault kill:rank=0:nth=8 \
	  -- python3 tests/paged_worker.py || exit 1
	@echo "== paged-check: bench.py --dryrun-paged (§19 rows land)"
	@JAX_PLATFORMS=cpu python3 bench.py --dryrun-paged || exit 1
	@echo "PAGED CHECK PASSED"

# --- request-journey tracing + SLO burn-rate plane (DESIGN.md §20) ---
# A 3-rank disaggregated fleet with ACX_REQLOG armed: every rank logs
# each request's lifecycle events, and tools/acx_request.py --check
# must reconstruct >= 95% of the journeys admit->finish ACROSS ranks
# (skew-corrected via the sibling traces) and emit the SLO burn-rate
# section. The second leg stalls the prefill rank's wire repeatedly
# (stall_link_ms on every frame from the 3rd) and the reconstructor
# must name the shipping edge as the fleet-dominant service phase —
# the whole point of the plane: "where did this request's time go"
# answered with the faulty leg, not a shrug.
.PHONY: request-check
request-check: tools
	@rm -rf $(BUILD)/request-check && mkdir -p $(BUILD)/request-check
	@echo "== request-check: 3-rank fleet with ACX_REQLOG armed"
	@ACX_ROLE=prefill,decode,decode ACX_REQLOG=$(BUILD)/request-check/run \
	  ACX_TRACE=$(BUILD)/request-check/run ACX_TRACE_CAP=2000000 \
	  $(BUILD)/acxrun -np 3 -timeout 240 \
	  -transport socket python3 tests/request_worker.py || exit 1
	@echo "== request-check: journeys reconstruct (>= 95% admit->finish)"
	@ACX_SERVE_ADMIT_TTFT_MS=60000 ACX_SERVE_ADMIT_ITL_MS=60000 \
	  python3 tools/acx_request.py --check --min-reconstructed 0.95 \
	  --waterfall 3 --json $(BUILD)/request-check/journeys.json \
	  $(BUILD)/request-check/run.rank*.reqlog.jsonl \
	  $(BUILD)/request-check/run.rank*.trace.json || exit 1
	@echo "== request-check: stalled wire -> dominant phase is the ship edge"
	@ACX_ROLE=prefill,decode,decode ACX_REQLOG=$(BUILD)/request-check/stall \
	  ACX_TRACE=$(BUILD)/request-check/stall ACX_TRACE_CAP=2000000 \
	  $(BUILD)/acxrun -np 3 -timeout 240 -transport socket \
	  -fault stall_link_ms:rank=0:nth=3:count=100000:ms=250 \
	  python3 tests/request_worker.py || exit 1
	@python3 tools/acx_request.py --check --expect-dominant ship \
	  --json $(BUILD)/request-check/stall.journeys.json \
	  $(BUILD)/request-check/stall.rank*.reqlog.jsonl \
	  $(BUILD)/request-check/stall.rank*.trace.json || exit 1
	@echo "REQUEST CHECK PASSED"

# Header dependency tracking (-MMD): a header edit rebuilds its users.
-include $(LIB_OBJS:.o=.d)

clean:
	rm -rf $(BUILD)

# --- ThreadSanitizer build + run (race detection the reference lacks,
# SURVEY.md §5.2). Rebuilds everything into build-tsan/ and runs the unit
# suite plus the multi-process integration tests under TSAN.
.PHONY: tsan
tsan:
	@$(MAKE) --no-print-directory BUILD=build-tsan \
	  CXXFLAGS="$(CXXFLAGS) -O1 -fsanitize=thread" \
	  LDFLAGS="-pthread -fsanitize=thread" \
	  ctest itest tools
	@for t in $(CTEST_BINS:$(BUILD)/%=build-tsan/%); do \
	  echo "== tsan $$t"; TSAN_OPTIONS=halt_on_error=1 $$t || exit 1; done
	@for t in $(ITEST_BINS:$(BUILD)/%=build-tsan/%); do \
	  echo "== tsan acxrun -np 2 $$t"; \
	  TSAN_OPTIONS=halt_on_error=1 build-tsan/acxrun -np 2 -timeout 600 $$t || exit 1; done
	@echo "== tsan acxrun -np 2 rolling-restart (socket, membership plane)"
	@TSAN_OPTIONS=halt_on_error=1 build-tsan/acxrun -np 2 -timeout 600 \
	  -transport socket build-tsan/itests/rolling-restart || exit 1
	@echo "TSAN CLEAN"

# --- static analysis (docs/DESIGN.md §18) ---
# `lint` is the cross-layer contract audit (tools/acx_audit.py: env knobs,
# ctypes bindings, metrics registry, flight kinds, crash-flush signal path)
# plus the clang thread-safety pass over the annotated concurrency core
# (include/acx/thread_annotations.h). The clang legs detect-and-skip: the
# annotations compile to nothing under gcc, so a gcc-only box still gets
# the full contract audit — just not the capability analysis.
ACX_CLANG ?= $(shell command -v clang++ 2>/dev/null)

.PHONY: lint annotcheck
lint:
	@echo "== acx_audit (contract linter)"
	@python3 tools/acx_audit.py
ifneq ($(ACX_CLANG),)
	@echo "== clang -Wthread-safety ($(ACX_CLANG))"
	@$(ACX_CLANG) -fsyntax-only -std=c++17 -Wall -Wthread-safety \
	  -Werror=thread-safety $(INCLUDES) $(LIB_SRCS) || exit 1
	@$(MAKE) --no-print-directory annotcheck
else
	@echo "== clang -Wthread-safety: SKIPPED (no clang++ on PATH; gcc" \
	  "compiles the annotations to nothing)"
endif
	@echo "LINT CLEAN"

# Probe that the annotation macros actually bite under clang: compiling
# ctests/annot_probe.cc with -DACX_ANNOT_PROBE_BAD (an unguarded write to
# a GUARDED_BY member) must FAIL under -Werror=thread-safety. Guards
# against the macros silently no-op'ing under a future clang/flag change.
annotcheck:
ifneq ($(ACX_CLANG),)
	@echo "== annotcheck: misannotated probe must fail under clang"
	@if $(ACX_CLANG) -fsyntax-only -std=c++17 -Wthread-safety \
	  -Werror=thread-safety -DACX_ANNOT_PROBE_BAD $(INCLUDES) \
	  ctests/annot_probe.cc 2>/dev/null; then \
	  echo "annotcheck: FAIL — ACX_ANNOT_PROBE_BAD compiled clean" \
	    "(thread-safety analysis is not biting)"; exit 1; \
	else echo "annotcheck: OK (probe rejected as expected)"; fi
else
	@echo "== annotcheck: SKIPPED (no clang++ on PATH)"
endif

# --- AddressSanitizer / UBSanitizer builds (mirror the tsan pattern).
# asan: heap/stack/use-after-free over the unit suite + the 2-rank
# integration tests on both planes. detect_leaks=0 because the runtime's
# process-lifetime singletons (metrics State, trace ring, flag table) are
# deliberately immortal — LSAN would report every one.
ASAN_ENV  = ASAN_OPTIONS=halt_on_error=1:detect_leaks=0:abort_on_error=1
UBSAN_ENV = UBSAN_OPTIONS=halt_on_error=1:print_stacktrace=1

.PHONY: asan ubsan asan-ctest
asan:
	@$(MAKE) --no-print-directory BUILD=build-asan \
	  CXXFLAGS="$(CXXFLAGS) -O1 -fsanitize=address -fno-omit-frame-pointer" \
	  LDFLAGS="-pthread -fsanitize=address" \
	  ctest itest tools
	@for t in $(CTEST_BINS:$(BUILD)/%=build-asan/%); do \
	  echo "== asan $$t"; $(ASAN_ENV) $$t || exit 1; done
	@for t in $(ITEST_BINS:$(BUILD)/%=build-asan/%); do \
	  echo "== asan acxrun -np 2 $$t"; \
	  $(ASAN_ENV) build-asan/acxrun -np 2 -timeout 600 $$t || exit 1; done
	@echo "== asan acxrun -np 2 ring (socket)"
	@$(ASAN_ENV) build-asan/acxrun -np 2 -timeout 600 \
	  -transport socket build-asan/itests/ring || exit 1
	@echo "ASAN CLEAN"

ubsan:
	@$(MAKE) --no-print-directory BUILD=build-ubsan \
	  CXXFLAGS="$(CXXFLAGS) -O1 -fsanitize=undefined -fno-sanitize-recover=all" \
	  LDFLAGS="-pthread -fsanitize=undefined" \
	  ctest itest tools
	@for t in $(CTEST_BINS:$(BUILD)/%=build-ubsan/%); do \
	  echo "== ubsan $$t"; $(UBSAN_ENV) $$t || exit 1; done
	@for t in $(ITEST_BINS:$(BUILD)/%=build-ubsan/%); do \
	  echo "== ubsan acxrun -np 2 $$t"; \
	  $(UBSAN_ENV) build-ubsan/acxrun -np 2 -timeout 600 $$t || exit 1; done
	@echo "UBSAN CLEAN"

# The fast asan leg `make check` runs: unit suite + one 2-rank itest per
# plane (the full matrix stays in `make asan`).
asan-ctest:
	@$(MAKE) --no-print-directory BUILD=build-asan \
	  CXXFLAGS="$(CXXFLAGS) -O1 -fsanitize=address -fno-omit-frame-pointer" \
	  LDFLAGS="-pthread -fsanitize=address" \
	  ctest itest tools
	@for t in $(CTEST_BINS:$(BUILD)/%=build-asan/%); do \
	  echo "== asan $$t"; $(ASAN_ENV) $$t || exit 1; done
	@echo "== asan acxrun -np 2 ring (shm)"
	@$(ASAN_ENV) build-asan/acxrun -np 2 -timeout 600 build-asan/itests/ring || exit 1
	@echo "== asan acxrun -np 2 ring (socket)"
	@$(ASAN_ENV) build-asan/acxrun -np 2 -timeout 600 \
	  -transport socket build-asan/itests/ring || exit 1
	@echo "ASAN CTEST LEG CLEAN"
