#!/usr/bin/env python3
"""Cross-rank hang doctor: merge per-rank flight-recorder dumps and say
who waits on whom — and which rank is the culprit.

Each rank writes ``<prefix>.rank<r>.flight.json`` (src/core/flightrec.cc)
when its stall watchdog trips (ACX_HANG_DUMP_MS), on a fatal signal, or on
an explicit ``MPIX_Dump_state`` / ``Runtime.hang_report()`` call. One dump
shows a rank stuck; only the merged view shows *why*: rank 1's parrived
poll on partition 3 is hopeless because rank 0 reserved that partition and
never published it, rank 2's irecv of tag 7 waits on a send rank 3 never
made, rank 0 sits in a barrier rank 2 never entered.

This tool pairs the stuck operations across ranks — sends with recvs by
(src, dst, tag), partitioned channels by partition index, barriers by
entry count — and prints a diagnosis naming one of:

    dead_link                  a peer was declared dead (heartbeat loss)
    missing_dump               a stuck op waits on a rank that produced
                               no flight dump at all — the gap itself is
                               the evidence (the rank died, or was killed,
                               before its recorder could flush)
    never_published_partition  recv side polls a partition the send side
                               reserved but never MPIX_Pready'd
    tag_mismatch               both sides stuck on each other with
                               different tags
    span_pair_conflict         the (peer, tag) heuristic and the wire
                               span ids disagree: the peer posted what
                               LOOKS like a matching recv, but the frame
                               carrying the send's span id never arrived
                               — the bytes were lost in flight, and the
                               heuristic alone would have mis-paired
    unmatched_send             a send in flight toward a rank that never
                               posted a matching recv
    unmatched_recv             a recv posted for a message the source
                               never sent
    barrier_skew               some ranks entered a barrier another rank
                               never reached
    peer_died                  nothing is stuck now, but a dump's event
                               log recorded a peer's death — the fleet
                               declared it dead and has since moved on
                               (the chaos-conductor heal/rejoin shape)
    none                       no anomaly detected

The culprit is the rank whose *missing* action would unblock the job: the
sender that never published the partition, the rank that never posted the
recv / never sent, the rank missing from the barrier. When several
anomalies coexist the most causal one wins (a dead link explains stuck
ops; a never-published partition explains a stuck parrived poll), in the
priority order listed above.

Pairing is span-exact when the dumps allow it: every op minted by a v2
build carries a causal span id (docs/DESIGN.md §14) that rides the wire
in the frame header, and the receiver records each arriving frame as an
``rx_frame`` event tagged with the SENDER's span. So a stuck send with
span S is matched against the peer's rx_frame spans — an exact identity
check, no guessing. The (peer, tag, bytes) heuristic remains only as
the fallback for dumps from pre-span builds (or spanless control ops),
and when the two methods disagree the disagreement itself is reported
(``span_pair_conflict``) instead of silently trusting either.

Usage:
    python3 tools/acx_doctor.py [--json] [--expect-culprit N]
        [--expect-anomaly NAME] hang.rank0.flight.json hang.rank1...

``--expect-*`` flags make the tool a test oracle: exit 0 iff the
diagnosis matches (itests/hang-doctor.c + `make doctor-check`).
"""

import argparse
import json
import sys

# Slot states that mean "still waiting on the wire / the peer".
STUCK_STATES = ("PENDING", "ISSUED", "RECOVERING")

# Every event kind a flight dump can carry (src/core/flightrec.cc
# kKindNames — dumps carry the NAME, never the raw enum value). The
# contract is bidirectional and enforced by tools/acx_audit.py
# (flight_kinds rule, docs/DESIGN.md §18): a kind added to the recorder
# without a row here fails `make lint`, as does a stale row the
# recorder no longer emits. An unknown kind in a dump is reported as
# evidence, not crashed on — it usually means the dump and this tool
# come from different builds.
KNOWN_KINDS = {
    "none",
    # op lifecycle
    "isend_enqueue", "irecv_enqueue", "trigger_fired", "isend_issued",
    "irecv_issued", "op_completed", "wait_observed", "op_timeout",
    "op_retry", "op_parked", "op_resumed", "op_drained", "slot_reclaimed",
    "op_fault",
    # partitioned
    "psend_slot", "precv_slot", "pready_mark", "pready_wire", "parrived",
    # wire
    "tx_data", "tx_rts", "tx_ack", "tx_seqack", "tx_nak",
    "rx_data", "rx_frame", "rx_seqack", "rx_nak",
    "link_recovering", "link_up", "peer_dead",
    # process scope
    "barrier_enter", "barrier_exit", "stall_warn", "hang_dump",
    "init", "finalize",
}


def unknown_kinds(dumps):
    """Event kinds present in the merged dumps that this tool cannot
    decode: {kind: [ranks]}. Nonempty means a recorder/doctor version
    skew — the diagnosis still runs, but these events carried no
    weight in it."""
    out = {}
    for rank, d in sorted(dumps.items()):
        for e in d.get("events", []):
            k = e.get("kind")
            if k and k not in KNOWN_KINDS:
                out.setdefault(k, []).append(rank)
    return {k: sorted(set(rs)) for k, rs in out.items()}


def load_dumps(paths, skipped=None):
    """Parse flight dumps into {rank: dump} (later files win on dup).

    A path that is missing, unreadable, or truncated mid-write — exactly
    what a rank that died before flushing leaves behind — does NOT fail
    the merge: it is recorded in ``skipped`` (a list of (path, reason)
    tuples, when the caller passes one) and the diagnosis runs on the
    dumps that DID land. The gap shows up as evidence in the report."""
    dumps = {}
    for p in paths:
        try:
            with open(p) as f:
                d = json.load(f)
            rank = int(d["rank"])
        except (OSError, ValueError, KeyError, TypeError) as exc:
            if skipped is None:
                raise
            skipped.append((p, "%s: %s" % (type(exc).__name__, exc)))
            continue
        d["_path"] = p
        dumps[rank] = d
    return dumps


def _stuck_slots(dump):
    return [s for s in dump.get("slots", [])
            if s.get("state") in STUCK_STATES]


def _events(dump, kind=None):
    evs = dump.get("events", [])
    if kind is None:
        return evs
    return [e for e in evs if e.get("kind") == kind]


def _carries_spans(dump):
    """True iff this dump comes from a span-aware (v2) build: any event
    or slot row with a nonzero span id. Dumps from older builds (all
    spans absent or zero) keep the pure-heuristic diagnosis path."""
    for e in dump.get("events", []):
        if e.get("span"):
            return True
    for s in dump.get("slots", []):
        if s.get("span"):
            return True
    return False


def _rx_spans(dump):
    """Span ids of every frame this rank RECEIVED (rx_frame is recorded
    for each arriving sequenced frame with the sender's span off the
    wire; rx_data covers the shm plane's direct deliveries)."""
    return {e["span"] for e in dump.get("events", [])
            if e.get("kind") in ("rx_frame", "rx_data") and e.get("span")}


def _has_recv_for(dump, src, tag):
    """Did `dump`'s rank ever post a recv matching (src, tag)? Stuck slots
    and completed history (irecv_enqueue / irecv_issued events) count —
    a recv that exists but hasn't matched yet is not the anomaly."""
    for s in dump.get("slots", []):
        if s.get("kind") == "irecv" and s.get("peer") == src \
                and s.get("tag") == tag:
            return True
    for e in dump.get("events", []):
        if e.get("kind") in ("irecv_enqueue", "irecv_issued") \
                and e.get("peer") == src and e.get("tag") == tag:
            return True
    return False


def _has_send_for(dump, dst, tag):
    """Did `dump`'s rank ever produce a send matching (dst, tag)?"""
    for s in dump.get("slots", []):
        if s.get("kind") in ("isend", "pready") and s.get("peer") == dst \
                and s.get("tag") == tag:
            return True
    for e in dump.get("events", []):
        if e.get("kind") in ("isend_enqueue", "isend_issued", "psend_slot",
                             "pready_mark") \
                and e.get("peer") == dst and e.get("tag") == tag:
            return True
    return False


def _published_partition(dump, peer, tag, partition):
    """True iff `dump`'s rank published (MPIX_Pready) this partition."""
    for e in dump.get("events", []):
        if e.get("kind") in ("pready_mark", "pready_wire") \
                and e.get("aux") == partition and e.get("peer") == peer \
                and (tag is None or e.get("tag") == tag):
            return True
    return False


def _reserved_send_partition(dump, peer, tag, partition):
    """True iff `dump`'s rank holds the matching send-side partition slot
    still RESERVED (allocated by MPIX_Psend_init, never Pready'd)."""
    for s in dump.get("slots", []):
        if s.get("kind") == "pready" and s.get("state") == "RESERVED" \
                and s.get("peer") == peer and s.get("partition") == partition \
                and (tag is None or s.get("tag") == tag):
            return True
    return False


def _dump_gaps(dumps):
    """Ranks other dumps point at (stuck-op peer, dead/recovering link, or
    just `size` says the fleet is wider) for which no dump was loaded.
    Each gap is evidence: every healthy rank's recorder flushes on the
    watchdog / signal / dump-state paths, so a referenced-but-dumpless
    rank most likely died before it could write."""
    expected = set()
    for rank, d in dumps.items():
        for s in _stuck_slots(d):
            peer = s.get("peer")
            if isinstance(peer, int) and peer >= 0:
                expected.add(peer)
        for p in d.get("peers", []):
            if p.get("health") in ("dead", "recovering"):
                expected.add(int(p["rank"]))
    return sorted(r for r in expected if r not in dumps)


def diagnose(dumps):
    """Diagnose a set of per-rank flight dumps ({rank: dump}).

    Returns {"anomaly": str, "culprit": int|None, "detail": str,
    "waits": [str, ...], "missing_ranks": [int, ...]} — `waits` is the
    who-waits-on-whom evidence, one line per stuck operation;
    `missing_ranks` are ranks the dumps reference but that produced no
    dump of their own (died before flushing)."""
    waits = []
    for rank in sorted(dumps):
        d = dumps[rank]
        for s in _stuck_slots(d):
            part = s.get("partition", -1)
            waits.append(
                "rank %d waits on rank %s: %s slot %s tag=%s%s "
                "state=%s age=%.0fms" % (
                    rank, s.get("peer"), s.get("kind"), s.get("slot"),
                    s.get("tag"),
                    (" partition=%d" % part) if part >= 0 else "",
                    s.get("state"), s.get("age_ms", 0.0)))
    gaps = _dump_gaps(dumps)
    for g in gaps:
        waits.append("rank %d produced no flight dump (died before "
                     "flushing?) — the gap itself is evidence" % g)

    def _result(anomaly, culprit, detail):
        if anomaly != "missing_dump" and culprit is not None \
                and culprit in gaps:
            detail += ("; rank %d also produced no flight dump, which "
                       "corroborates it died" % culprit)
        return {"anomaly": anomaly, "culprit": culprit, "detail": detail,
                "waits": waits, "missing_ranks": gaps,
                "unknown_kinds": unknown_kinds(dumps)}

    # 1. dead link: a declared-dead peer explains every stuck op on it.
    for rank in sorted(dumps):
        for p in dumps[rank].get("peers", []):
            if p.get("health") == "dead":
                return _result(
                    "dead_link", int(p["rank"]),
                    "rank %d declared rank %d dead (heartbeat "
                    "loss); ops toward it cannot complete"
                    % (rank, p["rank"]))

    # 2. missing dump: a stuck op waits on a rank for which no dump was
    # loaded. Nothing can be paired against it — and that IS the finding:
    # the rank died (or was killed, or never got far enough to install a
    # recorder) before it could flush, so its absence names the culprit.
    for rank in sorted(dumps):
        for s in _stuck_slots(dumps[rank]):
            peer = s.get("peer")
            if isinstance(peer, int) and peer in gaps:
                return _result(
                    "missing_dump", int(peer),
                    "rank %d waits on rank %d, which produced no flight "
                    "dump — it likely died before flushing; the missing "
                    "dump is the evidence" % (rank, peer))

    # 3. never-published partition: recv side polls partition p from S;
    # S holds the matching send partition RESERVED and never Pready'd it.
    for rank in sorted(dumps):
        for s in _stuck_slots(dumps[rank]):
            if s.get("kind") != "parrived":
                continue
            src, tag, part = s.get("peer"), s.get("tag"), s.get("partition")
            peer_dump = dumps.get(src)
            if peer_dump is None:
                continue
            if _published_partition(peer_dump, rank, tag, part):
                continue  # published; the data is merely late
            if _reserved_send_partition(peer_dump, rank, tag, part) or \
                    not _has_send_for(peer_dump, rank, tag):
                return _result(
                    "never_published_partition", int(src),
                    "rank %d polls partition %s of tag=%s from "
                    "rank %s, but rank %s reserved that "
                    "partition and never called MPIX_Pready"
                    % (rank, part, tag, src, src))

    # 4. tag mismatch: both sides stuck on each other, tags disagree.
    for rank in sorted(dumps):
        for s in _stuck_slots(dumps[rank]):
            if s.get("kind") != "isend":
                continue
            dst = s.get("peer")
            peer_dump = dumps.get(dst)
            if peer_dump is None:
                continue
            for r in _stuck_slots(peer_dump):
                if r.get("kind") == "irecv" and r.get("peer") == rank \
                        and r.get("tag") != s.get("tag"):
                    return _result(
                        "tag_mismatch", int(rank),
                        "rank %d sends tag=%s to rank %s, which "
                        "only has a recv posted for tag=%s"
                        % (rank, s.get("tag"), dst, r.get("tag")))

    # 5. span-exact send pairing (docs/DESIGN.md §14): a stuck send's
    # span id either appears among the peer's received-frame spans (the
    # bytes arrived — any hang is peer-side matching) or it does not
    # (the bytes never landed). When the exact answer and the (peer,
    # tag) heuristic disagree, that disagreement IS the finding: the
    # heuristic would have called the op matched while the frame was in
    # fact lost in flight — report it rather than silently trusting
    # either method.
    for rank in sorted(dumps):
        for s in _stuck_slots(dumps[rank]):
            if s.get("kind") != "isend" or not s.get("span"):
                continue
            dst, tag = s.get("peer"), s.get("tag")
            peer_dump = dumps.get(dst)
            if peer_dump is None or not _carries_spans(peer_dump):
                continue
            arrived = s["span"] in _rx_spans(peer_dump)
            heur_matched = _has_recv_for(peer_dump, rank, tag)
            if arrived and not heur_matched:
                return _result(
                    "unmatched_send", int(dst),
                    "rank %d's send tag=%s reached rank %s (frame span "
                    "%#x was received) but rank %s never posted a "
                    "matching recv — span-exact evidence, no heuristic"
                    % (rank, tag, dst, s["span"], dst))
            if not arrived and heur_matched:
                return _result(
                    "span_pair_conflict", int(rank),
                    "rank %d's send tag=%s to rank %s looks matched by "
                    "the (peer, tag) heuristic, but no frame carrying "
                    "its span %#x ever arrived at rank %s — the bytes "
                    "were lost in flight, and the heuristic alone "
                    "would have mis-paired this op"
                    % (rank, tag, dst, s["span"], dst))

    # 6. unmatched send (heuristic fallback, pre-span dumps): the
    # destination never posted a matching recv.
    for rank in sorted(dumps):
        for s in _stuck_slots(dumps[rank]):
            if s.get("kind") != "isend":
                continue
            dst, tag = s.get("peer"), s.get("tag")
            peer_dump = dumps.get(dst)
            if peer_dump is not None and not _has_recv_for(peer_dump, rank,
                                                           tag):
                return _result(
                    "unmatched_send", int(dst),
                    "rank %d's send tag=%s to rank %s has no "
                    "matching recv — rank %s never posted one"
                    % (rank, tag, dst, dst))

    # 7. unmatched recv: the source never produced a matching send.
    for rank in sorted(dumps):
        for s in _stuck_slots(dumps[rank]):
            if s.get("kind") != "irecv":
                continue
            src, tag = s.get("peer"), s.get("tag")
            peer_dump = dumps.get(src)
            if peer_dump is not None and not _has_send_for(peer_dump, rank,
                                                           tag):
                return _result(
                    "unmatched_recv", int(src),
                    "rank %d's recv tag=%s from rank %s has no "
                    "matching send — rank %s never sent it"
                    % (rank, tag, src, src))

    # 8. barrier skew: some ranks sit inside barrier k (enter without
    # exit) while another rank never reached it. The rank with the fewest
    # barrier entries is the one the others wait for.
    entered = {r: len(_events(d, "barrier_enter")) for r, d in dumps.items()}
    exited = {r: len(_events(d, "barrier_exit")) for r, d in dumps.items()}
    in_barrier = [r for r in dumps if entered[r] > exited[r]]
    if in_barrier and entered:
        straggler = min(dumps, key=lambda r: entered[r])
        if straggler not in in_barrier \
                and entered[straggler] < max(entered.values()):
            return _result(
                "barrier_skew", int(straggler),
                "rank(s) %s wait inside barrier %d; rank %d has "
                "only entered %d barrier(s)"
                % (sorted(in_barrier), max(entered.values()),
                   straggler, entered[straggler]))

    # 9. historical death: nothing is stuck NOW, but one or more dumps
    # recorded a peer_dead event. This is the chaos-conductor shape
    # (DESIGN.md §16): survivors of a SIGKILLed rank dump at heal time,
    # after which the victim's respawned incarnation rejoins and clears
    # the dead latch — so no live anomaly remains, yet the event log
    # still names who died. Lowest priority: any live wait/latch evidence
    # above explains the dumps better than a death the fleet already
    # survived.
    died = {}
    for rank in sorted(dumps):
        for e in _events(dumps[rank], "peer_dead"):
            p = e.get("peer")
            if isinstance(p, int) and p >= 0:
                died[p] = died.get(p, 0) + 1
    if died:
        victim = max(sorted(died), key=lambda p: died[p])
        return _result(
            "peer_died", int(victim),
            "no live anomaly, but %d dump(s) recorded rank %d's death "
            "(peer_dead event) — the fleet declared it dead and has "
            "since moved on (healed or rejoined)"
            % (died[victim], victim))

    return _result("none", None, "no anomaly detected")


def format_report(dumps, diag, skipped=()):
    lines = []
    lines.append("acx doctor: %d rank dump(s): %s" % (
        len(dumps),
        ", ".join("rank %d (%s, %d events)" % (
            r, dumps[r].get("reason", "?"), len(dumps[r].get("events", [])))
            for r in sorted(dumps))))
    for path, reason in skipped:
        lines.append("  skipped unreadable dump %s (%s)" % (path, reason))
    for w in diag["waits"]:
        lines.append("  " + w)
    for kind, ranks in sorted(diag.get("unknown_kinds", {}).items()):
        lines.append("  warning: undecodable event kind %r from rank(s) %s "
                     "(recorder/doctor build skew?)" % (kind, ranks))
    lines.append("diagnosis: %s" % diag["detail"])
    lines.append("anomaly: %s" % diag["anomaly"])
    if diag["culprit"] is not None:
        lines.append("culprit: rank %d" % diag["culprit"])
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Merge per-rank flight dumps and diagnose a hang.")
    ap.add_argument("files", nargs="+",
                    help="per-rank <prefix>.rank<r>.flight.json dumps")
    ap.add_argument("--json", action="store_true",
                    help="emit the diagnosis as one JSON object")
    ap.add_argument("--expect-anomaly", default=None, metavar="NAME",
                    help="exit nonzero unless the diagnosis matches")
    ap.add_argument("--expect-culprit", type=int, default=None, metavar="N",
                    help="exit nonzero unless the culprit is rank N")
    args = ap.parse_args(argv)

    skipped = []
    dumps = load_dumps(args.files, skipped=skipped)
    if not dumps:
        print("acx doctor: no readable flight dumps among %d input(s)"
              % len(args.files), file=sys.stderr)
        for path, reason in skipped:
            print("  %s: %s" % (path, reason), file=sys.stderr)
        return 2
    diag = diagnose(dumps)
    if args.json:
        out = dict(diag)
        out["skipped_files"] = ["%s (%s)" % (p, r) for p, r in skipped]
        print(json.dumps(out, indent=1))
    else:
        print(format_report(dumps, diag, skipped))

    if args.expect_anomaly is not None and \
            diag["anomaly"] != args.expect_anomaly:
        print("doctor: FAIL expected anomaly %s, got %s"
              % (args.expect_anomaly, diag["anomaly"]), file=sys.stderr)
        return 1
    if args.expect_culprit is not None and \
            diag["culprit"] != args.expect_culprit:
        print("doctor: FAIL expected culprit rank %d, got %s"
              % (args.expect_culprit, diag["culprit"]), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
