#!/usr/bin/env bash
# tools/bank_chip.sh — incremental TPU evidence banker.
#
# Rounds 2-4 lost every healthy-tunnel window to all-or-nothing capture
# and a session-local /tmp banker that died with the session. This
# script is the checked-in replacement: probe the axon tunnel cheaply;
# on success run the bench suite + the on-chip trigger/bridge proof
# tests, committing every green artifact IMMEDIATELY so even a
# 3-minute window banks at least one TPU row.
#
# Usage:
#   tools/bank_chip.sh            one probe+bank pass (rc 0 = done)
#   tools/bank_chip.sh --loop [s] retry every s seconds (default 420)
#                                 until every gated row + the segment
#                                 rows + the on-chip proof have banked
#                                 (or the gate is RED / the proof
#                                 failed 3x on a healthy tunnel — both
#                                 mean code bugs retries can't fix)
#
# Safe to run from cron or any session: commits touch ONLY the bench
# artifacts (explicit pathspecs), never the working tree's other files.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG="${ACX_BANK_LOG:-$REPO/chip_bank.log}"
ARTIFACTS="BENCH_BANK.json BENCH_FULL.json"
cd "$REPO"

log() { echo "[$(date -u +%FT%TZ)] $*" | tee -a "$LOG"; }

probe() {
  # jax.devices() HANGS (not errors) when the tunnel is down — always
  # wrap in timeout. A matmul proves the chip executes, not just lists.
  timeout 180 python -c \
    "import jax, jax.numpy as jnp; \
     print(jax.devices()); \
     print(float(jax.jit(lambda a: (a@a).sum())(jnp.ones((64,64)))))" \
    >>"$LOG" 2>&1
}

commit_artifacts() {
  # Pathspec-limited commit: only the bench artifacts, regardless of
  # whatever else is dirty or staged in the tree. add -f first: a
  # freshly created BENCH_BANK.json is untracked, and `git commit --
  # <pathspec>` errors on paths git doesn't know (verified).
  if ! git status --porcelain -- $ARTIFACTS | grep -q .; then
    return 0
  fi
  git add -f -- $ARTIFACTS >>"$LOG" 2>&1
  git commit -m "$1" -- $ARTIFACTS >>"$LOG" 2>&1 \
    && log "committed: $1" || log "commit FAILED: $1"
}

bank_fingerprint() { md5sum BENCH_BANK.json 2>/dev/null || echo none; }

bank_once() {
  log "probing tunnel..."
  if ! probe; then
    log "probe FAILED (tunnel down)"
    return 1
  fi
  log "tunnel UP — banking evidence"
  before="$(bank_fingerprint)"
  # ONE --full pass: it supersets the plain run (same fwd group, plus
  # flash/decode/train/spec) and banks BENCH_BANK/BENCH_FULL after
  # every child, so a second plain pass would only burn healthy-tunnel
  # minutes re-measuring the probe + fwd group.
  # Reuse same-day banked TPU groups so a retry pass skips straight to
  # the groups the last window didn't reach (bench.py _bank_reuse).
  ACX_BANK_REUSE_H="${ACX_BANK_REUSE_H:-18}" \
  timeout 3600 python bench.py --full >>"$LOG" 2>&1 \
    && log "bench.py --full done (gate green)" \
    || log "bench.py --full nonzero (gate red or outage)"
  commit_artifacts "Bank TPU bench rows (bench.py --full)"
  onchip_ok=0
  if ACX_TPU_TESTS=1 timeout 1800 \
      python -m pytest tests/test_tpu_onchip.py -q >>"$LOG" 2>&1; then
    log "on-chip trigger/bridge proof PASSED"
    python -c "import bench; bench._bank({'onchip_proof_passed': 1,
                                          'device': 'tpu'})"
    rm -f .bank_proof_fails
    commit_artifacts "Bank on-chip trigger/bridge proof result"
    onchip_ok=1
  else
    # Count failures only when the tunnel is still up afterwards — a
    # mid-proof outage is an outage, not a proof bug.
    if probe; then
      n=$(( $(cat .bank_proof_fails 2>/dev/null || echo 0) + 1 ))
      echo "$n" > .bank_proof_fails
      log "on-chip proof FAILED on a healthy tunnel ($n/3; see $LOG)"
    else
      log "on-chip proof FAILED or timed out (tunnel down; see $LOG)"
    fi
  fi
  # Success = evidence actually landed, not merely a green probe: the
  # tunnel can drop between the probe and the first bench child, and
  # --loop must keep watching in that case.
  if [ "$(bank_fingerprint)" = "$before" ] && [ "$onchip_ok" = 0 ]; then
    log "bank pass banked NOTHING (tunnel dropped mid-run?) — will retry"
    return 1
  fi
  # A pass that banked SOMETHING still isn't done while gated rows
  # remain unmeasured, the segment rows are missing, or the on-chip
  # proof hasn't passed (r05: the first healthy window banked
  # fwd/flash/decode, then the tunnel died before train/spec/proof —
  # the loop must keep hunting windows). A RED gate (real regression)
  # stops the loop: retrying can't fix code, and looping would re-burn
  # healthy windows forever. Repeated proof failures on a HEALTHY
  # tunnel likewise stop after 3 tries (counter in .bank_proof_fails,
  # untracked) — that's a bug to debug, not an outage to outwait.
  rc="$(python - <<'EOF'
import json, os, sys
try:
    full = json.load(open("BENCH_FULL.json"))
    bank = json.load(open("BENCH_BANK.json"))
except Exception:
    print("retry"); sys.exit(0)
if full["result"].get("regressions"):
    print("red"); sys.exit(0)
done = (not full["result"].get("unmeasured")
        and "train_seg_fwd_ms" in bank)
if done and "onchip_proof_passed" not in bank:
    fails = 0
    try:
        fails = int(open(".bank_proof_fails").read())
    except Exception:
        pass
    print("gaveup" if fails >= 3 else "retry")
    sys.exit(0)
print("done" if done else "retry")
EOF
)"
  if [ "$rc" = "red" ]; then
    log "gate RED (real regression) — stopping loop; fix the code"
    return 0
  fi
  if [ "$rc" = "gaveup" ]; then
    log "STOPPING: on-chip proof failed 3x on a healthy tunnel — the" \
        "proof did NOT bank; debug tests/test_tpu_onchip.py"
    return 0
  fi
  if [ "$rc" = "done" ]; then
    log "bank pass complete (all gated rows measured + segments + proof)"
    return 0
  fi
  log "partial bank (gated rows, segments, or proof still missing) — will retry"
  return 1
}

if [ "${1:-}" = "--loop" ]; then
  interval="${2:-420}"
  while true; do
    bank_once && exit 0
    sleep "$interval"
  done
else
  bank_once
fi
