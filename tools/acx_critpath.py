#!/usr/bin/env python3
"""acx_critpath — cross-rank critical-path analyzer for spanned ACX traces.

Every op the runtime mints carries a 64-bit causal span id (origin rank,
op slot, op incarnation — include/acx/span.h) that rides the wire in the
frame header and is stamped into the trace ring at every lifecycle
transition on BOTH sides (docs/DESIGN.md §14). That turns per-rank
``<prefix>.rank<r>.trace.json`` files (src/core/trace.cc) into one causal
graph:

  * within a span, the lifecycle instants chain on the origin rank:
    isend_enqueue → trigger_fired → isend_issued → wire_tx →
    op_completed → wait_observed (recv flavor analogous);
  * across ranks, each ``wire_tx`` pairs with the ``wire_rx`` carrying
    the SAME span id on the peer (n-th with n-th in corrected-time order
    — a rendezvous span has an RTS, ACK and DATA frame, causally
    ordered); the edge weight is the one-way transit;
  * on the receiver, the back-to-back ``rx_from``/``rx_match`` instant
    pair (emitted under the transport lock, so each rx_from pairs with
    the NEXT rx_match in that rank's stream) bridges the sender's span
    chain into the local recv op's chain;
  * ``req_op`` instants tie an application request id (the span the
    serving layer brackets with acx_span_app_begin) to each native op
    minted inside the bracket, so a request's latency decomposes into
    queue vs compute vs wire.

Clock alignment starts from the barrier-anchored skew that
tools/acx_trace_merge.py owns (compute_skew — the LAST common
barrier_exit is the anchor); this tool never re-derives that base. The
barrier anchor is only as tight as the barrier's own exit asymmetry
(the release reaches the root one op-latency before everyone else —
several hundred µs through the proxy/wait machinery, dwarfing a
localhost one-way transit), so a second, fine correction is fit from
the span-paired frames themselves: per link, the median transit must be
symmetric in the two directions (the NTP offset assumption), and the
residual per-rank offset that symmetrizes each link is propagated over
a BFS tree from the lowest rank. Both components are reported
separately (``barrier_skew_us`` + ``link_offset_us`` = ``skew_us``).
The median is robust to injected stalls — one 40 ms frame among a
hundred does not move it.

The critical path is reconstructed backward from the globally last event
by last-arrival: at each cross-rank receive the predecessor is whichever
of (previous local event, paired remote transmit) happened LATER on the
corrected timeline — the classic message-passing critical-path walk. The
not-chosen arrival's headroom is the edge's slack. The result is the
longest causal chain of the step, each edge labeled with its stage
(trigger / proxy_pickup / tx_queue / transit / match / deliver /
wait_pickup / app) and, for wire edges, its link ("0->1").

Usage:
    python3 tools/acx_critpath.py [--top K] [--json]
        [--min-pair-rate F] [--expect-nonneg-transit]
        [--expect-edge A->B]
        run.rank0.trace.json run.rank1.trace.json ...

``--expect-*`` / ``--min-pair-rate`` make the tool a CI oracle (`make
causality-check`): exit 0 iff the assertions hold. Exits 2 when no
spanned events exist at all (tracing was off, or a pre-span build).
"""

import argparse
import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from acx_trace_merge import compute_skew, load, parse_rank  # noqa: E402

# Lifecycle instants that participate in the causal graph. Everything
# else (barrier_exit, heartbeats, fleet events) anchors clocks or is
# noise for path purposes.
CHAIN_EVENTS = frozenset([
    "isend_enqueue", "irecv_enqueue", "req_op", "trigger_fired",
    "isend_issued", "irecv_issued", "wire_tx", "wire_rx", "rx_from",
    "rx_match", "op_completed", "wait_observed", "pready_marked",
    "pready_wire", "parrived",
])

# Stage label for a same-rank edge, by (predecessor name, successor name).
# Pairs not listed degrade to "local" — still on the path, just untyped.
EDGE_KIND = {
    ("isend_enqueue", "trigger_fired"): "trigger",
    ("irecv_enqueue", "trigger_fired"): "trigger",
    ("trigger_fired", "isend_issued"): "proxy_pickup",
    ("trigger_fired", "irecv_issued"): "proxy_pickup",
    ("isend_issued", "wire_tx"): "tx_queue",
    ("irecv_issued", "wire_tx"): "tx_queue",
    ("wire_rx", "rx_from"): "demux",
    ("rx_from", "rx_match"): "match",
    ("rx_match", "op_completed"): "deliver",
    ("wire_rx", "op_completed"): "deliver",
    ("op_completed", "wait_observed"): "wait_pickup",
    ("wait_observed", "isend_enqueue"): "app",
    ("wait_observed", "irecv_enqueue"): "app",
}


class Ev:
    __slots__ = ("rank", "name", "ts", "slot", "span", "idx", "pair",
                 "pair_rx")

    def __init__(self, rank, name, ts, slot, span):
        self.rank = rank
        self.name = name
        self.ts = ts          # corrected µs
        self.slot = slot
        self.span = span
        self.idx = -1         # position in the per-rank chain
        self.pair = None      # wire_rx -> its paired wire_tx Ev
        self.pair_rx = None   # wire_tx -> its paired wire_rx Ev


def span_rank(span):
    """Origin-rank field of a span id (include/acx/span.h layout)."""
    return (span >> 48) & 0xFFFF


def extract_events(rank, trace, shift):
    """Spanned + chain instants of one rank, time-shifted onto the
    common timeline. Synthesized "b"/"e" lifecycle bars are skipped —
    the instants they were derived from are already here."""
    out = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "i" or e.get("name") not in CHAIN_EVENTS:
            continue
        span = int(e.get("args", {}).get("span", 0))
        out.append(Ev(rank, e["name"], float(e["ts"]) + shift,
                      int(e.get("tid", -1)), span))
    out.sort(key=lambda ev: ev.ts)
    for i, ev in enumerate(out):
        ev.idx = i
    return out


def pair_wire(chains):
    """Pair wire_tx with wire_rx per span, n-th with n-th in corrected
    time order (the frames of one span — RTS, ACK, DATA — are causally
    ordered, so index order IS causal order on each side). A pair must
    straddle ranks; same-rank pseudo-pairs (shouldn't happen) are
    rejected and counted as unpaired. Returns pairing stats."""
    txs = collections.defaultdict(list)
    rxs = collections.defaultdict(list)
    for chain in chains.values():
        for ev in chain:
            if ev.span == 0:
                continue
            if ev.name == "wire_tx":
                txs[ev.span].append(ev)
            elif ev.name == "wire_rx":
                rxs[ev.span].append(ev)
    paired = unpaired_tx = unpaired_rx = cross_rank_reject = 0
    transits = []  # (tx_ev, rx_ev, dt_us)
    for span in set(txs) | set(rxs):
        t, r = txs.get(span, []), rxs.get(span, [])
        t.sort(key=lambda ev: ev.ts)
        r.sort(key=lambda ev: ev.ts)
        for i in range(min(len(t), len(r))):
            if t[i].rank == r[i].rank:
                cross_rank_reject += 1
                continue
            r[i].pair = t[i]
            t[i].pair_rx = r[i]
            paired += 1
            transits.append((t[i], r[i], r[i].ts - t[i].ts))
        unpaired_tx += max(0, len(t) - len(r))
        unpaired_rx += max(0, len(r) - len(t))
    return {"paired": paired, "unpaired_tx": unpaired_tx,
            "unpaired_rx": unpaired_rx,
            "cross_rank_reject": cross_rank_reject,
            "transits": transits}


def link_offsets(transits, ranks):
    """Fine per-rank clock offsets (µs) on top of the barrier skew.

    The barrier anchor leaves a residual equal to the barrier's exit
    asymmetry; the wire pairs expose it: with symmetric true transit,
    measured median(a->b) = true + bias and median(b->a) = true - bias,
    so shifting b by (med(b->a) - med(a->b)) / 2 symmetrizes the link.
    Offsets propagate from the lowest rank over a BFS tree of links that
    saw traffic BOTH ways; a rank reachable by no such link keeps 0."""
    by = collections.defaultdict(list)
    for tx, rx, dt in transits:
        by[(tx.rank, rx.rank)].append(dt)
    med = {}
    for k, v in by.items():
        v.sort()
        med[k] = v[len(v) // 2]
    delta = {}
    if ranks:
        root = min(ranks)
        delta[root] = 0.0
        frontier = [root]
        while frontier:
            a = frontier.pop(0)
            for b in ranks:
                if b in delta or (a, b) not in med or (b, a) not in med:
                    continue
                delta[b] = delta[a] + (med[(b, a)] - med[(a, b)]) / 2.0
                frontier.append(b)
    for r in ranks:
        delta.setdefault(r, 0.0)
    return delta


def link_stats(transits):
    """Per-link one-way transit summary: {"0->1": {n, min/median/max µs,
    negative-after-correction count}}. Negatives are skew-correction
    residue — reported, and clamped to 0 only by consumers that need a
    duration, never here."""
    by_link = collections.defaultdict(list)
    for tx, rx, dt in transits:
        by_link[f"{tx.rank}->{rx.rank}"].append(dt)
    out = {}
    for link, dts in sorted(by_link.items()):
        dts.sort()
        out[link] = {
            "frames": len(dts),
            "min_us": dts[0],
            "median_us": dts[len(dts) // 2],
            "max_us": dts[-1],
            "negative": sum(1 for d in dts if d < 0),
        }
    return out


def critical_path(chains):
    """Backward last-arrival walk from the globally latest event.

    At a paired wire_rx the predecessor is whichever of (previous event
    on this rank, the paired wire_tx on the sender) is LATER — the
    later arrival is what the receive actually waited for; the earlier
    one's headroom is recorded as the edge's slack. Everywhere else the
    predecessor is simply the previous chain event on the same rank.
    Returns the path as a list of edge dicts, earliest first."""
    last = None
    for chain in chains.values():
        if chain and (last is None or chain[-1].ts > last.ts):
            last = chain[-1]
    if last is None:
        return []
    edges = []
    cur = last
    # Visited guard: with pathological skew residue a paired tx can sort
    # AFTER its rx, which could otherwise cycle the walk. Real runs never
    # trip this; a synthetic adversarial trace must still terminate.
    seen = set()
    while True:
        if (cur.rank, cur.idx) in seen:
            break
        seen.add((cur.rank, cur.idx))
        local = (chains[cur.rank][cur.idx - 1] if cur.idx > 0 else None)
        remote = cur.pair
        cand = [c for c in (local, remote) if c is not None]
        if not cand:
            break
        pred = max(cand, key=lambda ev: ev.ts)
        cross = pred.rank != cur.rank
        if cross:
            kind = "transit"
            link = f"{pred.rank}->{cur.rank}"
        else:
            kind = EDGE_KIND.get((pred.name, cur.name), "local")
            link = None
            # Whatever preceded a wire_tx locally, the gap before it is
            # send-side queueing of THAT frame (the instant fires when
            # the frame is fully on the wire, so an injected stall or a
            # backed-up socket lands here, on its link).
            if cur.name == "wire_tx":
                kind = "tx_queue"
        edge = {
            "from": {"rank": pred.rank, "name": pred.name, "ts_us": pred.ts,
                     "span": pred.span},
            "to": {"rank": cur.rank, "name": cur.name, "ts_us": cur.ts,
                   "span": cur.span},
            "dt_us": cur.ts - pred.ts,
            "kind": kind,
            "link": link,
        }
        # Any edge that ENDS at a paired receive was, one way or the
        # other, time spent waiting for that link's frame — record the
        # link even when the local predecessor won the last-arrival race
        # (--expect-edge matches either attribution).
        if cur.pair is not None:
            edge["rx_link"] = f"{cur.pair.rank}->{cur.rank}"
        if cur.pair_rx is not None:
            edge["tx_link"] = f"{cur.rank}->{cur.pair_rx.rank}"
        # Slack at the merge point: how much later the NOT-chosen
        # arrival could have been without delaying this event.
        if local is not None and remote is not None:
            loser = remote if pred is local else local
            edge["slack_us"] = pred.ts - loser.ts
            if pred is local:
                edge["slack_of"] = f"transit {remote.rank}->{cur.rank}"
            else:
                edge["slack_of"] = f"local {cur.rank}"
        edges.append(edge)
        cur = pred
    edges.reverse()
    return edges


def dominant_edges(path, top):
    """Aggregate on-path time by stage (wire edges keyed by link, local
    edges by kind@rank); return the top-k plus the single longest edge."""
    agg = collections.Counter()
    for e in path:
        if e["link"]:
            key = e["link"]
        elif e["kind"] == "tx_queue" and e.get("tx_link"):
            key = "txq " + e["tx_link"]
        else:
            key = f"{e['kind']}@{e['to']['rank']}"
        agg[key] += e["dt_us"]
    ranked = [{"edge": k, "total_us": v} for k, v in agg.most_common(top)]
    longest = max(path, key=lambda e: e["dt_us"], default=None)
    return ranked, longest


def request_split(chains):
    """Per-application-request latency decomposition. A req_op instant
    (span = the request id the serving layer bracketed) precedes the op
    enqueue it annotates on the SAME slot; the op's span then owns the
    stage timings. Returns {req_id: {ops, queue_us, wire_us}}."""
    # req_op -> the next enqueue on the same (rank, slot).
    op_to_req = {}
    for chain in chains.values():
        pending = {}  # slot -> req id
        for ev in chain:
            if ev.name == "req_op":
                pending[ev.slot] = ev.span
            elif ev.name in ("isend_enqueue", "irecv_enqueue") \
                    and ev.slot in pending:
                op_to_req[ev.span] = pending.pop(ev.slot)
    if not op_to_req:
        return {}
    # Stage sums per op span: queue = enqueue->issued, wire = issued->
    # completed (covers tx queue + transit + peer match).
    stamps = collections.defaultdict(dict)
    for chain in chains.values():
        for ev in chain:
            if ev.span in op_to_req and ev.name != "req_op":
                stamps[ev.span].setdefault(ev.name, ev.ts)
    out = collections.defaultdict(
        lambda: {"ops": 0, "queue_us": 0.0, "wire_us": 0.0})
    for span, st in stamps.items():
        req = out[str(op_to_req[span])]
        req["ops"] += 1
        enq = st.get("isend_enqueue", st.get("irecv_enqueue"))
        iss = st.get("isend_issued", st.get("irecv_issued"))
        done = st.get("op_completed")
        if enq is not None and iss is not None:
            req["queue_us"] += max(0.0, iss - enq)
        if iss is not None and done is not None:
            req["wire_us"] += max(0.0, done - iss)
    return dict(out)


def format_report(result, top_edges, longest):
    lines = ["acx critpath: %d rank(s), %d spanned events, "
             "%d/%d frames paired (%.1f%%)" % (
                 len(result["ranks"]), result["events"],
                 result["paired_frames"], result["total_frames"],
                 100.0 * result["pair_rate"])]
    for link, st in result["links"].items():
        lines.append(
            "  link %s: %d frame(s), transit min/median/max "
            "%.1f/%.1f/%.1f µs, %d negative after skew correction"
            % (link, st["frames"], st["min_us"], st["median_us"],
               st["max_us"], st["negative"]))
    path = result["path"]
    lines.append("critical path: %d edge(s), %.1f µs end to end"
                 % (len(path), result["path_us"]))
    for e in path[-min(len(path), 40):]:
        where = e["link"] if e["link"] else "rank %d" % e["to"]["rank"]
        slack = (", slack %.1f µs (%s)" % (e["slack_us"], e["slack_of"])
                 if "slack_us" in e else "")
        lines.append("  %-12s %-7s %10.1f µs  %s -> %s%s"
                     % (e["kind"], where, e["dt_us"], e["from"]["name"],
                        e["to"]["name"], slack))
    lines.append("dominant edges:")
    for d in top_edges:
        lines.append("  %-16s %10.1f µs" % (d["edge"], d["total_us"]))
    if longest is not None:
        where = longest["link"] or "rank %d" % longest["to"]["rank"]
        lines.append("longest single edge: %s (%s) %.1f µs"
                     % (longest["kind"], where, longest["dt_us"]))
    for req, split in sorted(result.get("requests", {}).items()):
        lines.append("  request %s: %d op(s), queue %.1f µs, wire %.1f µs"
                     % (req, split["ops"], split["queue_us"],
                        split["wire_us"]))
    return "\n".join(lines)


def analyze(traces, top=5):
    """traces: list of (rank, trace_dict). Returns the full result dict
    (the --json output) — separated from main() so tests drive it
    directly on synthetic traces."""
    skew = compute_skew(traces)
    # Pass 1 on the barrier-anchored timeline: pair the wire frames so
    # the fine per-link offsets can be fit from them.
    chains = {}
    for r, d in traces:
        chains[r] = extract_events(r, d, skew[r] or 0.0)
    offsets = link_offsets(pair_wire(chains)["transits"],
                           sorted(chains))
    # Pass 2 on the refined timeline: everything reported below —
    # transits, the path, the dominant edges — uses the combined shift.
    chains = {}
    for r, d in traces:
        chains[r] = extract_events(r, d, (skew[r] or 0.0) + offsets[r])
    n_events = sum(len(c) for c in chains.values())
    wire = pair_wire(chains)
    total = wire["paired"] + wire["unpaired_tx"] + wire["unpaired_rx"] \
        + wire["cross_rank_reject"]
    path = critical_path(chains)
    top_edges, longest = dominant_edges(path, top)
    result = {
        "ranks": sorted(chains),
        "barrier_skew_us": {str(r): skew[r] for r in skew},
        "link_offset_us": {str(r): offsets[r] for r in offsets},
        "skew_us": {str(r): (skew[r] or 0.0) + offsets[r] for r in skew},
        "aligned": all(s is not None for s in skew.values())
        if len(traces) > 1 else False,
        "events": n_events,
        "paired_frames": wire["paired"],
        "total_frames": total,
        "pair_rate": (wire["paired"] / total) if total else 0.0,
        "unpaired_tx": wire["unpaired_tx"],
        "unpaired_rx": wire["unpaired_rx"],
        "links": link_stats(wire["transits"]),
        "path": path,
        "path_us": sum(e["dt_us"] for e in path),
        "dominant": top_edges,
        "longest_edge": longest,
        "requests": request_split(chains),
    }
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Reconstruct the cross-rank critical path from "
                    "spanned ACX traces.")
    ap.add_argument("inputs", nargs="+",
                    help="per-rank *.trace.json files")
    ap.add_argument("--top", type=int, default=5,
                    help="how many dominant edges to report (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full analysis as one JSON object")
    ap.add_argument("--min-pair-rate", type=float, default=None,
                    metavar="F",
                    help="exit nonzero unless >= F of wire frames are "
                         "span-paired across ranks (e.g. 0.95)")
    ap.add_argument("--expect-nonneg-transit", action="store_true",
                    help="exit nonzero if any link's MEDIAN one-way "
                         "transit is negative after skew correction")
    ap.add_argument("--expect-edge", default=None, metavar="A->B",
                    help="exit nonzero unless the longest single "
                         "critical-path edge is on link A->B")
    args = ap.parse_args(argv)

    traces = []
    for i, p in enumerate(args.inputs):
        try:
            traces.append((parse_rank(p, i), load(p)))
        except (OSError, json.JSONDecodeError) as exc:
            # Same contract as the merge tool: a dead rank's missing
            # trace is evidence, not an error in the survivors.
            print("acx_critpath: skipping %s (%s)" % (p, exc),
                  file=sys.stderr)
    if not traces:
        print("acx_critpath: no readable traces", file=sys.stderr)
        return 2

    result = analyze(traces, top=args.top)
    if result["events"] == 0:
        print("acx_critpath: no spanned lifecycle events in %d trace(s) "
              "— was ACX_TRACE set, and is this a spanned (v2) build?"
              % len(traces), file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(result, indent=1))
    else:
        print(format_report(result, result["dominant"],
                            result["longest_edge"]))

    fail = []
    if args.min_pair_rate is not None \
            and result["pair_rate"] < args.min_pair_rate:
        fail.append("pair rate %.3f < required %.3f (%d unpaired tx, "
                    "%d unpaired rx)"
                    % (result["pair_rate"], args.min_pair_rate,
                       result["unpaired_tx"], result["unpaired_rx"]))
    if args.expect_nonneg_transit:
        for link, st in result["links"].items():
            if st["median_us"] < 0:
                fail.append("link %s median transit %.1f µs < 0 after "
                            "skew correction" % (link, st["median_us"]))
        if not result["links"]:
            fail.append("no cross-rank frame pairs to measure transit on")
    if args.expect_edge is not None:
        le = result["longest_edge"]
        got = (le.get("link") or le.get("rx_link")
               or le.get("tx_link")) if le else None
        if got != args.expect_edge:
            fail.append("longest edge is %s (%s), expected link %s"
                        % (le["kind"] if le else "none", got,
                           args.expect_edge))
    if not result["path"]:
        fail.append("critical path is empty")
    for f in fail:
        print("acx_critpath: FAIL " + f, file=sys.stderr)
    return 1 if fail else 0


if __name__ == "__main__":
    sys.exit(main())
