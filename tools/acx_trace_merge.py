#!/usr/bin/env python3
"""Merge per-rank ACX traces into one Perfetto timeline, aggregate
per-rank metrics into a fleet view, and validate both artifact kinds.

Each rank writes its own ``<path>.rank<r>.trace.json`` (src/core/trace.cc)
on its own steady clock with its own zero — loading two of them side by
side in Perfetto puts rank 1's first event at t=0 even if it really fired
mid-way through rank 0's run. This tool:

  * merges the traces into one Chrome trace-event file with one process
    (pid = rank, named "rank <r>") per input file;
  * aligns the per-rank clocks on a common barrier: every rank leaves the
    same MPI_Barrier at (nearly) the same wall instant, so the k-th
    ``barrier_exit`` instant (slot -1, emitted by the MPI shim) is a shared
    anchor. Each rank is shifted so its LAST common barrier_exit lands at
    the max across ranks (the barrier releases when the last rank arrives);
    the applied shift is reported as that rank's clock skew. Traces without
    common anchors merge unaligned (skew reported as null);
  * aggregates sibling ``*.metrics.json`` registries (src/core/metrics.cc)
    into one fleet file: counters sum (``slot_hwm`` maxes — a watermark
    across ranks is a max, not a sum), histogram counts/sums/buckets
    vector-add;
  * merges sibling ``*.tseries.jsonl`` time-series files (src/core/tseries.cc,
    docs/DESIGN.md §13) into one rank-tagged, time-sorted sample stream
    (``--tseries-out``). Samples are stamped with the same
    ns-since-trace-start monotonic clock the trace events use, so the
    barrier-anchored skew computed for the traces applies verbatim:
    ``corrected_us = t_mono_ns / 1000 + skew_us[rank]``. Without sibling
    traces (or without common anchors) samples merge unaligned
    (``corrected_us`` null). Application SLO fragments (the ``"app"``
    section each serving loop publishes via acx_tseries_annotate) ride
    through rank-tagged, and the newest one per rank is summarized in
    the output's ``app_by_rank``;
  * validates (``--validate``): traces parse, timestamps are sorted, every
    span begin has a matching end (name+cat+id+pid, the Perfetto async-span
    contract) and span/instant counts match ``otherData``; metrics files
    parse, expose >= 8 counters and >= 3 histograms, and every histogram's
    count equals the sum of its buckets.

Usage:
    python3 tools/acx_trace_merge.py [--out merged.json]
        [--metrics-out fleet.json] [--tseries-out fleet.tseries.json]
        [--validate]
        run.rank0.trace.json run.rank1.trace.json
        run.rank0.metrics.json run.rank1.metrics.json
        run.rank0.tseries.jsonl run.rank1.tseries.jsonl

Inputs are classified by filename (``.trace.json`` / ``.metrics.json`` /
``.tseries.jsonl``); the rank is parsed from the ``.rank<r>.`` filename
component (falling back to input order). Prints one JSON summary line; exits non-zero if any
``--validate`` check fails.

A missing or truncated input — what a rank that died before flushing
leaves behind — does NOT fail the merge: the gap is reported in the
summary (``missing``) and in the merged trace's
``otherData.missing_ranks``, and the surviving ranks merge normally.
The absent artifact is evidence of which rank went down, not an error
in the ones that landed.
"""

import argparse
import json
import re
import sys


def parse_rank(path, fallback):
    m = re.search(r"\.rank(\d+)\.", path)
    return int(m.group(1)) if m else fallback


def load(path):
    with open(path) as f:
        return json.load(f)


def load_tseries(path):
    """Line-by-line JSONL loader for *.tseries.jsonl.

    A rank killed mid-write leaves a torn final line; that line is
    skipped and counted, never fatal — same contract as tools/acx_top.py.
    Returns (samples, torn_line_count).
    """
    samples, torn = [], 0
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                samples.append(json.loads(line))
            except (json.JSONDecodeError, ValueError):
                torn += 1
    return samples, torn


# ---- validation -----------------------------------------------------------

def validate_trace(path, d, errors):
    evs = d.get("traceEvents")
    if not isinstance(evs, list):
        errors.append(f"{path}: no traceEvents list")
        return
    ts = [float(e["ts"]) for e in evs if "ts" in e]
    if ts != sorted(ts):
        errors.append(f"{path}: timestamps not sorted")
    open_spans = {}
    n_inst = n_span = 0
    for e in evs:
        ph = e.get("ph")
        if ph == "i":
            n_inst += 1
        elif ph in ("b", "e"):
            key = (e.get("name"), e.get("cat"), e.get("id"), e.get("pid"))
            if ph == "b":
                open_spans[key] = open_spans.get(key, 0) + 1
                n_span += 1
            else:
                if open_spans.get(key, 0) <= 0:
                    errors.append(f"{path}: span end without begin: {key}")
                else:
                    open_spans[key] -= 1
    for key, n in open_spans.items():
        if n != 0:
            errors.append(f"{path}: unbalanced span: {key}")
    other = d.get("otherData", {})
    if "dropped" not in other:
        errors.append(f"{path}: otherData.dropped missing")
    if other.get("events", n_inst) != n_inst:
        errors.append(f"{path}: otherData.events={other.get('events')} "
                      f"but {n_inst} instants")
    if other.get("spans", n_span) != n_span:
        errors.append(f"{path}: otherData.spans={other.get('spans')} "
                      f"but {n_span} span begins")


def validate_tseries(path, samples, torn, errors):
    if len(samples) < 2:
        errors.append(f"{path}: wants >= 2 samples, got {len(samples)}")
        return
    if torn:
        # Informational only via the summary; a torn tail is expected
        # from a crashed rank and must not fail validation.
        pass
    prev = -1
    for i, s in enumerate(samples):
        t = s.get("t_mono_ns")
        if t is None:
            errors.append(f"{path}: sample {i} missing t_mono_ns")
            continue
        if t <= prev:
            errors.append(f"{path}: t_mono_ns not monotone at sample {i} "
                          f"({t} <= {prev})")
        prev = t


def validate_metrics(path, d, errors):
    counters = d.get("counters")
    hists = d.get("histograms")
    if not isinstance(counters, dict) or len(counters) < 8:
        errors.append(f"{path}: wants >= 8 counters, got "
                      f"{len(counters) if isinstance(counters, dict) else 0}")
    if not isinstance(hists, dict) or len(hists) < 3:
        errors.append(f"{path}: wants >= 3 histograms, got "
                      f"{len(hists) if isinstance(hists, dict) else 0}")
        return
    for name, h in hists.items():
        if h.get("count", -1) != sum(h.get("buckets", [])):
            errors.append(f"{path}: histogram {name}: count {h.get('count')}"
                          f" != sum(buckets) {sum(h.get('buckets', []))}")


# ---- trace merge ----------------------------------------------------------

def barrier_anchors(d):
    """Timestamps (µs) of this rank's barrier_exit instants, in order."""
    return [float(e["ts"]) for e in d.get("traceEvents", [])
            if e.get("ph") == "i" and e.get("name") == "barrier_exit"]


def compute_skew(traces):
    """Barrier-anchored per-rank clock skew (µs) for a list of
    (rank, trace_dict) pairs. This is THE skew definition for every
    offline consumer (the trace merge, the tseries merge, and
    tools/acx_critpath.py import it rather than re-deriving): anchor on
    the LAST common barrier_exit (k = n_common-1) — late in the run the
    clocks have drifted as far as they will, and a barrier releases only
    when the last rank arrives, so its exit is the tightest shared
    instant available. skew[r] = target - anchor[r]; adding skew[r] to
    rank r's raw timestamps puts every rank on one timeline. Traces
    without common anchors (or a single trace) get skew None."""
    anchors = {r: barrier_anchors(d) for r, d in traces}
    n_common = min((len(a) for a in anchors.values()), default=0)
    if n_common > 0 and len(traces) > 1:
        k = n_common - 1
        target = max(a[k] for a in anchors.values())
        return {r: target - anchors[r][k] for r, _ in traces}
    return {r: None for r, _ in traces}


def merge_traces(traces):
    """traces: list of (rank, dict). Returns (merged_dict, skew_us)."""
    skew = compute_skew(traces)

    events = []
    for r, d in traces:
        shift = skew[r] or 0.0
        events.append({"name": "process_name", "ph": "M", "pid": r,
                       "args": {"name": f"rank {r}"}})
        for e in d.get("traceEvents", []):
            e = dict(e)
            e["pid"] = r
            if "ts" in e:
                e["ts"] = float(e["ts"]) + shift
            events.append(e)
    # Metadata events carry no ts; sort them first, then by time.
    events.sort(key=lambda e: (0, 0) if "ts" not in e else (1, e["ts"]))
    return ({"traceEvents": events, "displayTimeUnit": "ms",
             "otherData": {"ranks": sorted(r for r, _ in traces),
                           "skew_us": {str(r): skew[r] for r in skew}}},
            skew)


# ---- time-series merge ----------------------------------------------------

def merge_tseries(tseries, skew):
    """tseries: list of (rank, samples, torn). skew: the per-rank trace
    skew (µs) from merge_traces, or {} when no sibling traces were given.

    Samples stamp t_mono_ns on the SAME ns-since-trace-start clock the
    trace events use (src/core/tseries.cc uses trace::NowSinceStartNs),
    so the barrier-anchored per-rank shift applies verbatim:
    corrected_us = t_mono_ns/1000 + skew. Ranks without a skew (no common
    barrier anchors, or no traces at all) merge unaligned with
    corrected_us null — their samples sort on the raw per-rank clock.
    """
    merged = []
    # Rank-tagged carry-through of the application SLO fragment: each
    # sample keeps its own "app" section verbatim (the dict copy below),
    # and the newest fragment per rank is ALSO surfaced as a fleet-level
    # summary — so "which rank's serving loop reports the worst p99 TTFT"
    # is one lookup, not a scan of the merged stream.
    app_by_rank = {}
    for r, samples, _torn in tseries:
        sk = skew.get(r)
        for s in samples:
            e = dict(s)
            e["rank"] = r
            t = s.get("t_mono_ns")
            e["corrected_us"] = (t / 1000.0 + sk
                                 if t is not None and sk is not None else None)
            merged.append(e)
            if isinstance(s.get("app"), dict):
                app_by_rank[str(r)] = s["app"]
    merged.sort(key=lambda e: (
        e["corrected_us"] if e["corrected_us"] is not None
        else e.get("t_mono_ns", 0) / 1000.0,
        e["rank"]))
    return {"ranks": sorted(r for r, _, _ in tseries),
            "skew_us": {str(r): skew.get(r) for r, _, _ in tseries},
            "aligned": all(skew.get(r) is not None for r, _, _ in tseries),
            "torn_lines": {str(r): t for r, _, t in tseries},
            "app_by_rank": app_by_rank,
            "samples": merged}


# ---- metrics aggregation --------------------------------------------------

# Watermarks: a per-rank max aggregates across ranks as a max.
MAX_COUNTERS = {"slot_hwm"}


def merge_metrics(metrics):
    """metrics: list of (rank, dict). Sums counters (maxing watermarks)
    and vector-adds histograms into one fleet registry."""
    counters = {}
    hists = {}
    for _, d in metrics:
        for k, v in d.get("counters", {}).items():
            if k in MAX_COUNTERS:
                counters[k] = max(counters.get(k, 0), v)
            else:
                counters[k] = counters.get(k, 0) + v
        for name, h in d.get("histograms", {}).items():
            agg = hists.setdefault(name, {"unit": h.get("unit", "ns"),
                                          "count": 0, "sum": 0,
                                          "buckets": [0] * len(h["buckets"])})
            agg["count"] += h["count"]
            agg["sum"] += h["sum"]
            for i, b in enumerate(h["buckets"]):
                agg["buckets"][i] += b
    return {"ranks": sorted(r for r, _ in metrics),
            "counters": counters, "histograms": hists}


def main():
    ap = argparse.ArgumentParser(
        description="merge/aggregate/validate per-rank ACX observability "
                    "artifacts")
    ap.add_argument("inputs", nargs="+",
                    help="*.trace.json, *.metrics.json and/or "
                         "*.tseries.jsonl files")
    ap.add_argument("--out", help="write the merged Perfetto trace here")
    ap.add_argument("--metrics-out", help="write the fleet metrics here")
    ap.add_argument("--tseries-out",
                    help="write the merged, skew-corrected time-series here")
    ap.add_argument("--validate", action="store_true",
                    help="check artifact invariants; exit 1 on failure")
    args = ap.parse_args()

    traces, metrics, tseries, errors, missing = [], [], [], [], []
    reqlogs = 0
    for i, path in enumerate(args.inputs):
        # Request-journey logs (mpi_acx_tpu/reqlog.py) are JSONL too, and
        # their consumer is tools/acx_request.py — count them so a mixed
        # glob over a run directory passes through without choking the
        # whole-file json.load below.
        if path.endswith(".reqlog.jsonl"):
            reqlogs += 1
            continue
        # Time-series files are JSONL — one JSON object per line — so the
        # whole-file json.load below would choke on line two. Classify
        # them by suffix BEFORE loading.
        if path.endswith(".tseries.jsonl"):
            try:
                samples, torn = load_tseries(path)
            except OSError as exc:
                missing.append({"path": path, "rank": parse_rank(path, i),
                                "reason": str(exc)})
                continue
            tseries.append((parse_rank(path, i), samples, torn))
            if args.validate:
                validate_tseries(path, samples, torn, errors)
            continue
        try:
            d = load(path)
        except (OSError, json.JSONDecodeError) as exc:
            # A rank that died before flushing leaves a missing or
            # truncated artifact. That must not fail the merge of the
            # ranks that DID flush — record the gap (it is evidence of
            # which rank went down) and keep going.
            missing.append({"path": path, "rank": parse_rank(path, i),
                            "reason": str(exc)})
            continue
        if path.endswith(".metrics.json") or "histograms" in d:
            metrics.append((parse_rank(path, i), d))
            if args.validate:
                validate_metrics(path, d, errors)
        else:
            traces.append((parse_rank(path, i), d))
            if args.validate:
                validate_trace(path, d, errors)

    summary = {"traces": len(traces), "metrics": len(metrics),
               "tseries": len(tseries)}
    if reqlogs:
        summary["reqlogs_skipped"] = reqlogs
    if missing:
        summary["missing"] = missing
    # The tseries merge reuses the traces' barrier-anchored skew, so run
    # the trace merge whenever either output wants it.
    skew = {}
    if traces and (args.out or (tseries and args.tseries_out)):
        merged, skew = merge_traces(traces)
        if args.out:
            if missing:
                merged["otherData"]["missing_ranks"] = sorted(
                    {m["rank"] for m in missing})
            with open(args.out, "w") as f:
                json.dump(merged, f)
            summary["out"] = args.out
            summary["events"] = len(merged["traceEvents"])
        summary["skew_us"] = {str(r): skew[r] for r in skew}
    if tseries and args.tseries_out:
        fleet_ts = merge_tseries(tseries, skew)
        with open(args.tseries_out, "w") as f:
            json.dump(fleet_ts, f)
        summary["tseries_out"] = args.tseries_out
        summary["tseries_samples"] = len(fleet_ts["samples"])
        summary["tseries_aligned"] = fleet_ts["aligned"]
        summary["tseries_app_ranks"] = sorted(
            int(k) for k in fleet_ts["app_by_rank"])
    if metrics and args.metrics_out:
        fleet = merge_metrics(metrics)
        with open(args.metrics_out, "w") as f:
            json.dump(fleet, f, indent=1)
        summary["metrics_out"] = args.metrics_out
    if args.validate:
        summary["errors"] = errors
        summary["valid"] = not errors
    print(json.dumps(summary))
    for m in missing:
        print(f"acx_trace_merge: missing artifact for rank {m['rank']}: "
              f"{m['path']} ({m['reason']}) — merged without it",
              file=sys.stderr)
    if errors:
        for e in errors:
            print(f"acx_trace_merge: {e}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
