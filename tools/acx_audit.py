#!/usr/bin/env python3
"""acx_audit: the cross-layer contract linter (docs/DESIGN.md §18).

The runtime spans five contract surfaces that every PR tends to grow at
once: env knobs, the C API <-> ctypes bindings, the metrics registry,
the flight-recorder event kinds, and the crash-flush signal path. Each
surface has two or more files that must agree (code <-> README, capi.cc
<-> runtime.py, metrics.cc <-> DESIGN.md tables, flightrec.cc <->
acx_doctor.py) and nothing but convention kept them in sync. This tool
turns each convention into an enforced rule:

  knobs        every getenv("ACX_*") site is documented in README.md,
               and every README knob still exists in code
  bindings     every acx_* export in src/api/capi.cc has a ctypes
               declaration (name + arity) in mpi_acx_tpu/runtime.py,
               and vice versa
  registry     every counter/hist/gauge name in the metrics registry
               has a row in DESIGN.md's observability tables, the
               tables name only live entries, and the generic
               consumers (tseries.cc, acx_top.py) still consume them
  flight_kinds every event kind name in flightrec.cc is decodable by
               acx_doctor.py's KNOWN_KINDS table, and vice versa
  journey_kinds every request-journey kind emitted by the serving
               loops (serving.py/disagg.py/kvpage.py via reqlog.emit)
               is declared in mpi_acx_tpu/reqlog.py KINDS and
               decodable by tools/acx_request.py's KINDS table, and
               neither table carries stale rows
  signal_path  functions reachable from the crash-flusher registry
               (trace.cc RegisterCrashFlusher roots) never call a
               denylist of non-async-signal-safe / blocking
               primitives (malloc, fprintf on shared streams,
               blocking lock(), condvar waits, ...)

stdlib-only, like acx_doctor.py / acx_chaos.py. Exit 0 = clean,
1 = violations (one `rule: file:line: message` line each), 2 = the
audit itself could not run (missing surface file, bad allowlist).

Intentional-exception policy lives in tools/audit_allowlist.json; every
entry requires a human-readable reason string (empty reasons are an
error — the allowlist documents debt, it does not hide it).

The signal-path rule is a conservative regex call graph: function
bodies are found by brace matching, callees by bare name (so virtual
dispatch and same-named methods conflate — deliberately: a flusher
must be safe against every plausible resolution). `static x = []{...}()`
initializer lambdas are excluded from the scan: they run exactly once,
at first call on a normal (non-signal) path, and every crash flusher is
registered *from* such a latch — by the time a flusher can run, the
latch has already run. Indirect calls the graph cannot see (function
pointers) are declared as `extra_edges` in the allowlist.
"""

import argparse
import json
import os
import re
import sys

# --------------------------------------------------------------------------
# shared helpers


class AuditError(Exception):
    """The audit itself cannot run (missing file, malformed allowlist)."""


class Violation:
    def __init__(self, rule, path, line, msg):
        self.rule = rule
        self.path = path
        self.line = line
        self.msg = msg

    def __str__(self):
        return "%s: %s:%d: %s" % (self.rule, self.path, self.line, self.msg)

    def as_json(self):
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "msg": self.msg}


def read_file(root, rel):
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        raise AuditError("required file missing: %s" % rel)
    with open(path, "r", errors="replace") as f:
        return f.read()


def line_of(text, offset):
    return text.count("\n", 0, offset) + 1


def strip_c(text, strip_strings=True):
    """Blank out C/C++ comments (and optionally string/char literals),
    preserving newlines so offsets still map to the right line."""
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(re.sub(r"[^\n]", " ", text[i:j]))
            i = j
        elif strip_strings and c in "\"'":
            q = c
            j = i + 1
            while j < n:
                if text[j] == "\\":
                    j += 2
                    continue
                if text[j] == q:
                    j += 1
                    break
                j += 1
            out.append(q + " " * (j - i - 2) + q if j - i >= 2 else text[i:j])
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


def match_brace(text, open_pos, open_ch="{", close_ch="}"):
    """Index one past the brace matching text[open_pos]; -1 if unbalanced."""
    depth = 0
    for i in range(open_pos, len(text)):
        if text[i] == open_ch:
            depth += 1
        elif text[i] == close_ch:
            depth -= 1
            if depth == 0:
                return i + 1
    return -1


def extract_array_strings(text, array_name):
    """Quoted strings inside `array_name[] = { ... }` (comment-stripped
    text must NOT have strings stripped). Returns (names, line)."""
    m = re.search(re.escape(array_name) + r"\s*\[\s*\]\s*=\s*\{", text)
    if not m:
        raise AuditError("array %s not found" % array_name)
    start = text.index("{", m.start())
    end = match_brace(text, start)
    if end < 0:
        raise AuditError("array %s: unbalanced braces" % array_name)
    names = re.findall(r'"([^"]*)"', text[start:end])
    return names, line_of(text, m.start())


# --------------------------------------------------------------------------
# allowlist

ALLOWLIST_REL = os.path.join("tools", "audit_allowlist.json")


def load_allowlist(root, explicit_path=None):
    path = explicit_path or os.path.join(root, ALLOWLIST_REL)
    if not os.path.isfile(path):
        raise AuditError("allowlist missing: %s" % path)
    try:
        with open(path, "r") as f:
            allow = json.load(f)
    except ValueError as e:
        raise AuditError("allowlist %s: invalid JSON: %s" % (path, e))
    # Every exception must carry a nonempty reason. extra_edges values are
    # lists of callees; acx_top_deps is a plain list — everything else maps
    # name -> reason.
    for section, table in sorted(allow.items()):
        if section.startswith("_"):
            continue
        if not isinstance(table, dict):
            raise AuditError("allowlist: section %r must be an object"
                            % section)
        for key, val in sorted(table.items()):
            if key in ("extra_edges", "acx_top_deps") or key.startswith("_"):
                continue
            if isinstance(val, dict):
                for name, reason in sorted(val.items()):
                    if not (isinstance(reason, str) and reason.strip()):
                        raise AuditError(
                            "allowlist: %s.%s.%s needs a nonempty reason"
                            % (section, key, name))
            elif not (isinstance(val, str) and val.strip()):
                raise AuditError("allowlist: %s.%s needs a nonempty reason"
                                % (section, key))
    return allow


# --------------------------------------------------------------------------
# rule 1: knob audit

KNOB_DIRS = ("src", "include", "tools", "mpi_acx_tpu")
KNOB_RE = r"(?:ACX|MPIACX)_[A-Z0-9_]+"
# Read/write sites that prove a knob is live in code. Subscripts cover both
# os.environ["X"] reads and the env-dict writes acxrun uses to arm children.
# The C form also matches the repo's env-reading helpers (fault.cc Env(),
# flightrec.cc EnvMsToNs(), ...): any *getenv/Env* function taking the
# knob name as its first string literal argument.
C_KNOB_REF = re.compile(r'\b(?:\w*getenv|Env\w*)\(\s*"(%s)"' % KNOB_RE)
PY_KNOB_REF = re.compile(
    r'(?:getenv|environ\.get)\(\s*"(%s)"|\[\s*"(%s)"\s*\]'
    % (KNOB_RE, KNOB_RE))


def iter_source_files(root, dirs, exts):
    for d in dirs:
        top = os.path.join(root, d)
        if not os.path.isdir(top):
            continue
        for dirpath, _dirnames, filenames in os.walk(top):
            for fn in sorted(filenames):
                if os.path.splitext(fn)[1] in exts:
                    full = os.path.join(dirpath, fn)
                    yield os.path.relpath(full, root), full


def audit_knobs(root, allow):
    violations = []
    knob_allow = allow.get("knobs", {})
    test_only = knob_allow.get("test_only", {})
    not_knobs = knob_allow.get("not_knobs", {})
    # Documented knobs whose only read sites are outside the audited dirs
    # (e.g. bench.py at the repo root). Still real knobs — just consumed
    # beyond the surface this rule scans.
    external = knob_allow.get("external_readers", {})

    refs = {}  # name -> (relpath, line) of first reference
    for rel, full in iter_source_files(root, KNOB_DIRS,
                                       {".c", ".cc", ".h", ".py"}):
        if rel == ALLOWLIST_REL:
            continue
        with open(full, "r", errors="replace") as f:
            text = f.read()
        pat = PY_KNOB_REF if rel.endswith(".py") else C_KNOB_REF
        scan = text if rel.endswith(".py") else strip_c(text,
                                                        strip_strings=False)
        for m in pat.finditer(scan):
            name = m.group(1) or (m.group(2) if pat is PY_KNOB_REF else None)
            if name and name not in refs:
                refs[name] = (rel, line_of(scan, m.start()))

    readme = read_file(root, "README.md")
    documented = {}  # name -> first README line
    for m in re.finditer(r"\b(%s)\b" % KNOB_RE, readme):
        documented.setdefault(m.group(1), line_of(readme, m.start()))

    for name in sorted(set(refs) - set(documented) - set(test_only)):
        rel, line = refs[name]
        violations.append(Violation(
            "knobs", rel, line,
            "env knob %s is read in code but has no row/mention in "
            "README.md (document it, or allowlist it under "
            "knobs.test_only with a reason)" % name))
    for name in sorted(set(documented) - set(refs) - set(not_knobs)
                       - set(external)):
        violations.append(Violation(
            "knobs", "README.md", documented[name],
            "README documents %s but no code under %s references it "
            "(delete the row; allowlist under knobs.not_knobs if it is "
            "not an env knob, or knobs.external_readers if it is read "
            "outside the audited dirs)" % (name, "/".join(KNOB_DIRS))))
    return violations


# --------------------------------------------------------------------------
# rule 2: binding audit

CAPI_REL = os.path.join("src", "api", "capi.cc")
RUNTIME_REL = os.path.join("mpi_acx_tpu", "runtime.py")
CAPI_DEF = re.compile(
    r"^[A-Za-z_][\w \t\*]*?\b(acx_\w+)\s*\(([^)]*)\)\s*\{",
    re.MULTILINE | re.DOTALL)


def c_arity(params):
    params = params.strip()
    if params in ("", "void"):
        return 0
    return params.count(",") + 1


def split_top_level(text):
    """Split on commas not nested in (), [], {}. Empty text -> []."""
    parts, depth, cur = [], 0, []
    for c in text:
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
        if c == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return [p for p in (q.strip() for q in parts) if p]


def audit_bindings(root, allow):
    violations = []
    bind_allow = allow.get("bindings", {})
    unbound_ok = bind_allow.get("unbound_exports", {})

    capi = strip_c(read_file(root, CAPI_REL))
    exports = {}  # name -> (line, arity)
    for m in CAPI_DEF.finditer(capi):
        exports[m.group(1)] = (line_of(capi, m.start(1)),
                               c_arity(m.group(2)))

    runtime = read_file(root, RUNTIME_REL)
    # Strip full-line comments only; ctypes decls never share a line with
    # meaningful '#' usage here.
    runtime = re.sub(r"(?m)^\s*#.*$", "", runtime)
    declared = {}  # name -> line of first decl
    argtypes = {}  # name -> (line, arity)
    for m in re.finditer(r"_lib\.(acx_\w+)\.restype", runtime):
        declared.setdefault(m.group(1), line_of(runtime, m.start()))
    for m in re.finditer(r"_lib\.(acx_\w+)\.argtypes\s*=\s*\[", runtime):
        name = m.group(1)
        declared.setdefault(name, line_of(runtime, m.start()))
        start = runtime.index("[", m.end() - 1)
        end = match_brace(runtime, start, "[", "]")
        if end < 0:
            violations.append(Violation(
                "bindings", RUNTIME_REL, line_of(runtime, m.start()),
                "%s.argtypes: unbalanced bracket" % name))
            continue
        argtypes[name] = (line_of(runtime, m.start()),
                          len(split_top_level(runtime[start + 1:end - 1])))

    for name in sorted(set(exports) - set(declared) - set(unbound_ok)):
        line, arity = exports[name]
        violations.append(Violation(
            "bindings", CAPI_REL, line,
            "C export %s (arity %d) has no ctypes declaration in %s "
            "(add restype/argtypes, or allowlist under "
            "bindings.unbound_exports with a reason)"
            % (name, arity, RUNTIME_REL)))
    for name in sorted(set(declared) - set(exports)):
        violations.append(Violation(
            "bindings", RUNTIME_REL, declared[name],
            "ctypes declaration for %s has no matching export in %s "
            "(stale binding?)" % (name, CAPI_REL)))
    for name in sorted(set(exports) & set(declared)):
        _line, arity = exports[name]
        if name in argtypes:
            pline, parity = argtypes[name]
            if parity != arity:
                violations.append(Violation(
                    "bindings", RUNTIME_REL, pline,
                    "%s: argtypes lists %d parameter(s) but the C export "
                    "takes %d" % (name, parity, arity)))
        elif arity != 0:
            violations.append(Violation(
                "bindings", RUNTIME_REL, declared[name],
                "%s: C export takes %d parameter(s) but runtime.py sets "
                "no argtypes (ctypes would guess)" % (name, arity)))
    return violations


# --------------------------------------------------------------------------
# rule 3: registry audit

METRICS_CC_REL = os.path.join("src", "core", "metrics.cc")
TSERIES_REL = os.path.join("src", "core", "tseries.cc")
TOP_REL = os.path.join("tools", "acx_top.py")
DESIGN_REL = os.path.join("docs", "DESIGN.md")
TABLE_BEGIN = "<!-- acx-audit:registry-table:begin -->"
TABLE_END = "<!-- acx-audit:registry-table:end -->"
# Generic-consumption tokens: tseries.cc iterates the whole registry by
# construction. If a refactor replaces the generic loop with a
# hand-maintained list, the per-name guarantee is gone and this rule must
# be extended — so their disappearance is itself a violation.
TSERIES_TOKENS = ("kNumCounters", "CounterName", "IsGauge", "kNumHists",
                  "HistName")


def parse_design_tables(design):
    """Backticked names in table rows between the audit markers.
    Returns (dict name -> line, marker_line)."""
    begin = design.find(TABLE_BEGIN)
    end = design.find(TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        raise AuditError(
            "%s: registry table markers (%s ... %s) missing"
            % (DESIGN_REL, TABLE_BEGIN, TABLE_END))
    rows = {}
    offset = begin
    for rawline in design[begin:end].split("\n"):
        stripped = rawline.strip()
        if stripped.startswith("|"):
            m = re.match(r"\|\s*`([a-z0-9_]+)`", stripped)
            if m:
                rows.setdefault(m.group(1), line_of(design, offset))
        offset += len(rawline) + 1
    return rows, line_of(design, begin)


def audit_registry(root, allow):
    violations = []
    reg_allow = allow.get("registry", {})
    top_deps = reg_allow.get("acx_top_deps", [])

    metrics = strip_c(read_file(root, METRICS_CC_REL), strip_strings=False)
    counters, counters_line = extract_array_strings(metrics, "kCounterName")
    hists, _ = extract_array_strings(metrics, "kHistName")
    gm = re.search(r'\\"gauges\\":\[([^\]]*)\]', metrics)
    gauges = re.findall(r'\\"([a-z0-9_]+)\\"', gm.group(1)) if gm else []
    registry = set(counters) | set(hists)

    for g in gauges:
        if g not in counters:
            violations.append(Violation(
                "registry", METRICS_CC_REL, counters_line,
                'gauge "%s" (SnapshotString "gauges" list) is not a '
                "registered counter name" % g))

    design = read_file(root, DESIGN_REL)
    rows, table_line = parse_design_tables(design)
    for name in sorted(registry - set(rows)):
        kind = "histogram" if name in hists else \
               ("gauge" if name in gauges else "counter")
        violations.append(Violation(
            "registry", DESIGN_REL, table_line,
            "registry %s \"%s\" (%s) has no row in the observability "
            "table" % (kind, name, METRICS_CC_REL)))
    for name in sorted(set(rows) - registry):
        violations.append(Violation(
            "registry", DESIGN_REL, rows[name],
            "observability table row `%s` names no registry entry in %s "
            "(stale doc row?)" % (name, METRICS_CC_REL)))

    tseries = read_file(root, TSERIES_REL)
    for tok in TSERIES_TOKENS:
        if tok not in tseries:
            violations.append(Violation(
                "registry", TSERIES_REL, 1,
                "generic registry consumption token %s missing from "
                "tseries.cc — if the sampler no longer iterates the whole "
                "registry, extend the registry rule (DESIGN.md §18)"
                % tok))

    top = read_file(root, TOP_REL)
    for name in top_deps:
        if name not in registry:
            violations.append(Violation(
                "registry", ALLOWLIST_REL, 1,
                "registry.acx_top_deps names \"%s\" which is not a "
                "registry entry (renamed counter?)" % name))
        elif '"%s"' % name not in top:
            violations.append(Violation(
                "registry", TOP_REL, 1,
                "acx_top.py no longer references registry counter \"%s\" "
                "its columns depend on (update the column or "
                "registry.acx_top_deps)" % name))
    return violations


# --------------------------------------------------------------------------
# rule 4: flight-kind audit

FLIGHTREC_REL = os.path.join("src", "core", "flightrec.cc")
DOCTOR_REL = os.path.join("tools", "acx_doctor.py")


def audit_flight_kinds(root, allow):
    del allow  # no exceptions: every kind must be decodable
    violations = []
    flight = strip_c(read_file(root, FLIGHTREC_REL), strip_strings=False)
    kinds, kinds_line = extract_array_strings(flight, "kKindNames")

    doctor = read_file(root, DOCTOR_REL)
    m = re.search(r"KNOWN_KINDS\s*=\s*\{", doctor)
    if not m:
        raise AuditError("%s: KNOWN_KINDS table not found" % DOCTOR_REL)
    start = doctor.index("{", m.start())
    end = match_brace(doctor, start)
    if end < 0:
        raise AuditError("%s: KNOWN_KINDS: unbalanced braces" % DOCTOR_REL)
    table_line = line_of(doctor, m.start())
    known = {}
    offset = start
    for km in re.finditer(r'"([a-z0-9_]+)"', doctor[start:end]):
        known.setdefault(km.group(1), line_of(doctor, start + km.start()))

    for name in sorted(set(kinds) - set(known)):
        violations.append(Violation(
            "flight_kinds", FLIGHTREC_REL, kinds_line,
            'event kind "%s" is not decodable by acx_doctor.py '
            "(add it to KNOWN_KINDS at %s:%d)"
            % (name, DOCTOR_REL, table_line)))
    for name in sorted(set(known) - set(kinds)):
        violations.append(Violation(
            "flight_kinds", DOCTOR_REL, known[name],
            'KNOWN_KINDS entry "%s" matches no kind in %s kKindNames '
            "(stale table row?)" % (name, FLIGHTREC_REL)))
    return violations


# --------------------------------------------------------------------------
# rule 4b: journey-kind audit (the flight_kinds rule, one layer up: the
# request-journey plane of DESIGN.md §20 instead of the flight recorder)

REQLOG_REL = os.path.join("mpi_acx_tpu", "reqlog.py")
REQUEST_TOOL_REL = os.path.join("tools", "acx_request.py")
JOURNEY_EMITTERS = (
    os.path.join("mpi_acx_tpu", "models", "serving.py"),
    os.path.join("mpi_acx_tpu", "models", "disagg.py"),
    os.path.join("mpi_acx_tpu", "models", "kvpage.py"),
)


def _brace_table(text, head_re, rel, what, key_re=r'"([a-z0-9_]+)"'):
    """Quoted names inside the first brace block after head_re.
    Returns (dict name -> line, header_line)."""
    m = re.search(head_re, text)
    if not m:
        raise AuditError("%s: %s not found" % (rel, what))
    start = text.index("{", m.start())
    end = match_brace(text, start)
    if end < 0:
        raise AuditError("%s: %s: unbalanced braces" % (rel, what))
    names = {}
    for km in re.finditer(key_re, text[start:end]):
        names.setdefault(km.group(1), line_of(text, start + km.start()))
    return names, line_of(text, m.start())


def audit_journey_kinds(root, allow):
    del allow  # no exceptions: every emitted kind must be decodable
    violations = []

    # The literal kinds the serving loops emit (first site per kind).
    emitted = {}
    for rel in JOURNEY_EMITTERS:
        text = read_file(root, rel)
        for m in re.finditer(r'reqlog\.emit\(\s*"([a-z0-9_]+)"', text):
            emitted.setdefault(m.group(1), (rel, line_of(text, m.start())))

    # The declared vocabulary (reqlog.KINDS frozenset).
    vocab, vocab_line = _brace_table(
        read_file(root, REQLOG_REL),
        r"KINDS\s*=\s*frozenset\(\s*\{", REQLOG_REL, "KINDS frozenset")
    # The offline decode table (acx_request.KINDS dict — keys only; the
    # values are free-text descriptions).
    decode, decode_line = _brace_table(
        read_file(root, REQUEST_TOOL_REL),
        r"(?m)^KINDS\s*=\s*\{", REQUEST_TOOL_REL, "KINDS decode table",
        key_re=r'(?m)^\s*"([a-z0-9_]+)"\s*:')

    for name in sorted(set(emitted) - set(vocab)):
        rel, line = emitted[name]
        violations.append(Violation(
            "journey_kinds", rel, line,
            'journey kind "%s" is emitted but not declared in %s KINDS '
            "(line %d)" % (name, REQLOG_REL, vocab_line)))
    for name in sorted(set(emitted) - set(decode)):
        rel, line = emitted[name]
        violations.append(Violation(
            "journey_kinds", rel, line,
            'journey kind "%s" is emitted but not decodable by %s KINDS '
            "(line %d) — acx_request.py would warn it unknown at merge "
            "time" % (name, REQUEST_TOOL_REL, decode_line)))
    for name in sorted(set(vocab) - set(emitted)):
        violations.append(Violation(
            "journey_kinds", REQLOG_REL, vocab[name],
            'KINDS declares "%s" but no serving loop (%s) emits it '
            "(stale vocabulary entry?)"
            % (name, ", ".join(JOURNEY_EMITTERS))))
    for name in sorted(set(vocab) - set(decode)):
        violations.append(Violation(
            "journey_kinds", REQLOG_REL, vocab[name],
            'KINDS declares "%s" but %s cannot decode it (add a decode '
            "table row)" % (name, REQUEST_TOOL_REL)))
    for name in sorted(set(decode) - set(vocab)):
        violations.append(Violation(
            "journey_kinds", REQUEST_TOOL_REL, decode[name],
            'decode table row "%s" matches no kind in %s KINDS (stale '
            "row?)" % (name, REQLOG_REL)))
    return violations


# --------------------------------------------------------------------------
# rule 5: signal-path audit

SIGNAL_DIRS = (os.path.join("src", "core"), os.path.join("src", "net"),
               os.path.join("src", "api"), os.path.join("include", "acx"))
CXX_KEYWORDS = frozenset((
    "if", "for", "while", "switch", "catch", "return", "sizeof", "alignof",
    "decltype", "static_assert", "noexcept", "defined", "assert", "new",
    "delete", "throw", "else", "do", "case", "not"))
# A function definition: name(params) [const] [noexcept] [ACX_*(...)]...
# [: init-list] { — params may span lines but contain no top-level ')'.
FUNC_DEF = re.compile(
    r"\b([A-Za-z_]\w*)\s*\(([^(){};]*(?:\([^()]*\)[^(){};]*)*)\)\s*"
    r"(?:const\b\s*)?(?:noexcept\b\s*)?"
    r"(?:ACX_[A-Z_]+\s*\([^()]*\)\s*)*"
    r"(?::\s*[^;{]*?)?\{")
CALLEE = re.compile(r"\b([A-Za-z_]\w*)\s*\(")
STATIC_IIFE = re.compile(
    r"static\s+[^;{}=]*=\s*\[[^\]]*\]\s*(?:\([^)]*\)\s*)?"
    r"(?:->\s*[\w:<>\*&\s]+?)?\s*\{")

# (regex, label). Applied to comment/string-stripped bodies of every
# crash-flush-reachable function. `new` is deliberately absent (flagging
# it would force assume_safe noise for container growth the flush paths
# avoid by construction); std::string member ops are a documented
# limitation (DESIGN.md §18).
DENYLIST = (
    (re.compile(r"\b(?:malloc|calloc|realloc|free)\s*\("),
     "heap allocator call (not async-signal-safe)"),
    (re.compile(r"\bfprintf\s*\(\s*(?:stderr|stdout)\b"),
     "fprintf on a shared stdio stream (takes the stream lock; "
     "use trace::WriteErrNote)"),
    (re.compile(r"(?<!\w)printf\s*\("),
     "printf (shared stdio stream)"),
    (re.compile(r"\bstd::lock_guard\s*<"),
     "blocking std::lock_guard (use acx::TryMutexLock on flush paths)"),
    (re.compile(r"(?<!Try)\bMutexLock\s*\("),
     "blocking acx::MutexLock (use TryMutexLock on flush paths)"),
    (re.compile(r"\.\s*lock\s*\("),
     "blocking .lock() (use try_lock on flush paths)"),
    (re.compile(r"\bstd::call_once\b"),
     "std::call_once (blocks on a concurrent in-flight initializer)"),
    (re.compile(r"\bsleep_(?:for|until)\s*\("),
     "thread sleep on a flush path"),
    (re.compile(r"\bstd::to_string\s*\("),
     "std::to_string allocates (use snprintf into a stack buffer)"),
    (re.compile(r"\.\s*wait(?:_for|_until)?\s*\("),
     "condition-variable wait on a flush path"),
)

ROOT_RE = re.compile(r"RegisterCrashFlusher\s*\(\s*&?(?:\w+::)*(\w+)")


def strip_static_iifes(body):
    """Blank out `static x = []{...}()` latch bodies (run once, on a
    normal path, before any flusher can fire)."""
    out = body
    pos = 0
    while True:
        m = STATIC_IIFE.search(out, pos)
        if not m:
            return out
        # the regex anchors on the lambda's opening body brace (last char)
        start = m.end() - 1
        end = match_brace(out, start)
        if end < 0:
            return out
        out = out[:start + 1] + re.sub(r"[^\n]", " ",
                                       out[start + 1:end - 1]) + out[end - 1:]
        pos = end


def extract_functions(text):
    """[(name, body_start_offset, body_text)] from comment/string-stripped
    C++ source. Bare names: overloads and same-named methods conflate."""
    funcs = []
    for m in FUNC_DEF.finditer(text):
        name = m.group(1)
        if name in CXX_KEYWORDS:
            continue
        open_pos = m.end() - 1
        close = match_brace(text, open_pos)
        if close < 0:
            continue
        funcs.append((name, open_pos, text[open_pos:close]))
    return funcs


def audit_signal_path(root, allow):
    violations = []
    sig_allow = allow.get("signal_path", {})
    assume_safe = sig_allow.get("assume_safe", {})
    extra_edges = sig_allow.get("extra_edges", {})

    defs = {}   # bare name -> [(relpath, body_offset, stripped_body)]
    roots = set()
    texts = {}  # relpath -> stripped text (for line numbers)
    for rel, full in iter_source_files(root, SIGNAL_DIRS, {".cc", ".h"}):
        with open(full, "r", errors="replace") as f:
            raw = f.read()
        text = strip_c(raw)
        texts[rel] = text
        for m in ROOT_RE.finditer(text):
            # Skip the registrar's own prototype/definition, which matches
            # the pattern with its parameter type ("void (*fn)()").
            if m.group(1) not in ("void",) and m.group(1) not in CXX_KEYWORDS:
                roots.add(m.group(1))
        for name, off, body in extract_functions(text):
            defs.setdefault(name, []).append(
                (rel, off, strip_static_iifes(body)))

    if not roots:
        # No crash-flusher registry in the scanned tree (fixture trees may
        # stub it): nothing is reachable, nothing to check.
        return violations

    # BFS over bare-name call edges from the flusher roots.
    parent = {r: None for r in roots}
    queue = sorted(roots)
    reachable = set()
    while queue:
        name = queue.pop(0)
        if name in reachable or name in assume_safe:
            continue
        reachable.add(name)
        for callee in extra_edges.get(name, []):
            if callee not in parent:
                parent[callee] = name
                queue.append(callee)
        for _rel, _off, body in defs.get(name, []):
            for cm in CALLEE.finditer(body):
                callee = cm.group(1)
                if callee in CXX_KEYWORDS or callee == name:
                    continue
                if callee in defs and callee not in parent:
                    parent[callee] = name
                    queue.append(callee)

    def chain(name):
        links = []
        while name is not None:
            links.append(name)
            name = parent.get(name)
        return " <- ".join(links)

    for name in sorted(reachable):
        for rel, off, body in defs.get(name, []):
            for pat, label in DENYLIST:
                for dm in pat.finditer(body):
                    violations.append(Violation(
                        "signal_path", rel,
                        line_of(texts[rel], off + dm.start()),
                        "%s in %s(), reachable from a crash flusher "
                        "(%s)" % (label, name, chain(name))))
    return violations


# --------------------------------------------------------------------------
# driver

RULES = (
    ("knobs", audit_knobs),
    ("bindings", audit_bindings),
    ("registry", audit_registry),
    ("flight_kinds", audit_flight_kinds),
    ("journey_kinds", audit_journey_kinds),
    ("signal_path", audit_signal_path),
)


def find_root(start):
    d = os.path.abspath(start)
    while True:
        if os.path.isfile(os.path.join(d, "README.md")) and \
           os.path.isdir(os.path.join(d, "src")):
            return d
        up = os.path.dirname(d)
        if up == d:
            return None
        d = up


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="cross-layer contract linter (DESIGN.md §18)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: walk up from this script)")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist JSON (default: <root>/%s)"
                    % ALLOWLIST_REL)
    ap.add_argument("--rule", action="append", default=None,
                    choices=[name for name, _ in RULES],
                    help="run only this rule (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, fn in RULES:
            print("%-14s %s" % (name, (fn.__doc__ or "").strip()))
        return 0

    root = args.root or find_root(os.path.dirname(os.path.abspath(__file__)))
    if root is None or not os.path.isdir(root):
        print("acx_audit: cannot locate repo root (pass --root)",
              file=sys.stderr)
        return 2

    try:
        allow = load_allowlist(root, args.allowlist)
        selected = args.rule or [name for name, _ in RULES]
        violations = []
        counts = {}
        for name, fn in RULES:
            if name not in selected:
                continue
            found = fn(root, allow)
            counts[name] = len(found)
            violations.extend(found)
    except AuditError as e:
        if args.json:
            print(json.dumps({"ok": False, "error": str(e)}))
        else:
            print("acx_audit: error: %s" % e, file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "ok": not violations,
            "rules": counts,
            "violations": [v.as_json() for v in violations],
        }, indent=2, sort_keys=True))
    else:
        for v in violations:
            print(v)
        if violations:
            bad = sorted(r for r, n in counts.items() if n)
            print("acx_audit: %d violation(s) in rule(s): %s"
                  % (len(violations), ", ".join(bad)), file=sys.stderr)
        else:
            print("acx_audit: clean (%s)"
                  % ", ".join("%s=0" % r for r, _n in sorted(counts.items())),
                  file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
