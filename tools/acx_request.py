#!/usr/bin/env python3
"""Reconstruct per-request journeys from ACX request logs and report
fleet phase breakdowns + SLO burn rate (docs/DESIGN.md §20).

Each rank with ``ACX_REQLOG=<prefix>`` set appends one JSON line per
request-lifecycle event to ``<prefix>.rank<r>.reqlog.jsonl``
(mpi_acx_tpu/reqlog.py). A request's journey usually spans ranks — in
the disaggregated fleet a prefill rank emits admit/queue/prefill/ship
while a decode rank emits seat/stream/finish — so this tool:

  * merges the per-rank logs onto one timeline. When sibling
    ``*.trace.json`` files are given, the barrier-anchored skew from
    tools/acx_trace_merge.compute_skew (THE skew definition — shared,
    not re-derived) applies verbatim because reqlog stamps the same
    trace::NowSinceStartNs clock. Without traces, the init line's
    paired (t_mono_ns, t_wall_ms) reading anchors each rank on the
    wall clock — coarser (ms-granular, NTP-subject) but always there;
  * reconstructs each rid's journey and attributes wall time to
    phases: queue (admit→prefill_start), prefill
    (prefill_start→prefill_end), ship (prefill_end→seat — the
    cross-rank KV handoff leg), decode (seat→finish minus preempted),
    preempted (Σ preempt→resume);
  * prints fleet phase-breakdown percentiles (p50/p95/p99 per phase)
    and names the dominant phase — where the fleet's wall time went;
  * computes a rolling SLO burn rate: with TTFT/ITL targets (the
    ``ACX_SERVE_ADMIT_TTFT_MS`` / ``ACX_SERVE_ADMIT_ITL_MS`` knobs, or
    --ttft-ms/--itl-ms), requests finishing in each window are checked
    against the targets and burn = violation_rate / error_budget
    (--budget, default 1%). burn > 1 means the fleet is eating budget
    faster than the SLO allows;
  * renders a per-request waterfall (--waterfall N: the N slowest);
  * ``--check`` gates CI: >= --min-reconstructed of the rids seen must
    have a complete journey (an entry event AND a finish), the
    burn-rate section must be emitted, and with --expect-dominant
    PHASE the fleet-dominant phase must match — the bar the Makefile's
    request-check holds a fault-injected fleet to.

Unknown event kinds warn at merge time: the KINDS table below is the
decode vocabulary, and tools/acx_audit.py's ``journey_kinds`` rule
pins it to the literal kinds the serving loops emit.

Usage:
    python3 tools/acx_request.py [--json out.json] [--waterfall 5]
        [--check] [--min-reconstructed 0.95] [--expect-dominant ship]
        [--ttft-ms 500] [--itl-ms 100] [--budget 0.01] [--window-s 5]
        run.rank*.reqlog.jsonl [run.rank*.trace.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from acx_trace_merge import compute_skew, load, parse_rank  # noqa: E402

# Decode table: journey kind -> meaning. tools/acx_audit.py's
# journey_kinds rule brace-matches this dict and asserts it equals the
# set of kinds literally emitted by serving.py/disagg.py/kvpage.py and
# the KINDS frozenset in mpi_acx_tpu/reqlog.py.
KINDS = {
    "admit": "request accepted by typed admission",
    "reject": "typed admission rejection (reason)",
    "queue": "request enqueued on the scheduler queue",
    "prefill_start": "prompt pass begins",
    "prefill_layer": "one layerwise-prefill layer done",
    "prefill_end": "prompt pass done, first token known",
    "ship_hdr": "KV handoff header sent/received",
    "ship_pready": "one KV partition published",
    "ship_fin": "KV handoff FIN sent/received",
    "seat": "request seated in a cache slot",
    "prefix_hit": "radix prefix-cache prompt match",
    "decode_step": "one batched decode step (rid-less)",
    "stream": "tokens streamed to the request",
    "preempt": "request evicted by page pressure",
    "resume": "preempted request re-seated",
    "requeue": "failure-path restart",
    "finish": "request retired",
}

PHASES = ("queue", "prefill", "ship", "decode", "preempted")
# Dominance is judged over SERVICE phases only: queue time is backlog —
# the consequence of whichever service leg is slow (every later request
# queues behind it), so including it would let the symptom outvote the
# cause on any serially-scheduled fleet.
SERVICE_PHASES = ("prefill", "ship", "decode", "preempted")


def load_reqlog(path):
    """Returns (init_line_or_None, events, torn). Torn-tolerant like
    every other ACX JSONL reader: a rank killed mid-write leaves one
    torn final line, which is skipped and counted, never fatal."""
    init, events, torn = None, [], 0
    with open(path, errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                torn += 1
                continue
            if d.get("init"):
                init = d
            else:
                events.append(d)
    return init, events, torn


def rank_skews(reqlogs, traces):
    """Per-rank shift (µs) onto one fleet timeline.

    Preferred: the traces' barrier-anchored skew (reqlog stamps the
    same clock). Fallback: align each rank's init line so that its
    paired wall reading lands where the wall clock says — skew_us[r] =
    (t_wall_us - t_mono_us) normalized to the minimum across ranks.
    A rank whose init line recorded clock="mono" (no native runtime)
    can only use the wall fallback even when traces exist, because its
    zero is process-local, not trace-start.
    """
    skew = {}
    if traces:
        skew = dict(compute_skew(traces))
    wall = {}
    for r, init, _evs, _torn in reqlogs:
        if init and "t_wall_ms" in init and "t_mono_ns" in init:
            wall[r] = (float(init["t_wall_ms"]) * 1e3
                       - float(init["t_mono_ns"]) / 1e3)
    base = min(wall.values()) if wall else 0.0
    out, source = {}, {}
    for r, init, _evs, _torn in reqlogs:
        native = bool(init) and init.get("clock") == "native"
        if native and skew.get(r) is not None:
            out[r], source[r] = skew[r], "barrier"
        elif r in wall:
            out[r], source[r] = wall[r] - base, "wall"
        else:
            out[r], source[r] = 0.0, "none"
    return out, source


def build_journeys(reqlogs, skew):
    """rid -> time-sorted [(corrected_us, rank, event)] plus fleet-wide
    rid-less event tallies and the unknown-kind set."""
    journeys, unknown = {}, {}
    fleet = {"decode_steps": 0, "decode_time_s": 0.0, "events": 0}
    for r, _init, events, _torn in reqlogs:
        sh = skew.get(r, 0.0)
        for e in events:
            fleet["events"] += 1
            k = e.get("k")
            if k not in KINDS:
                unknown[k] = unknown.get(k, 0) + 1
                continue
            if k == "decode_step":
                fleet["decode_steps"] += 1
                fleet["decode_time_s"] += float(e.get("dt_s", 0.0))
                continue
            rid = e.get("rid")
            if rid is None:
                continue
            t = float(e.get("t_mono_ns", 0)) / 1e3 + sh
            journeys.setdefault(int(rid), []).append((t, r, e))
    for evs in journeys.values():
        evs.sort(key=lambda x: x[0])
    return journeys, fleet, unknown


def first_t(evs, *kinds):
    for t, _r, e in evs:
        if e["k"] in kinds:
            return t
    return None


def last_t(evs, *kinds):
    out = None
    for t, _r, e in evs:
        if e["k"] in kinds:
            out = t
    return out


def attribute(evs):
    """Phase attribution (seconds) for one rid's merged journey.
    Negative legs — possible under the coarse wall-clock fallback —
    clamp to 0 rather than poisoning the fleet sums."""
    admit = first_t(evs, "admit", "queue")
    pstart = first_t(evs, "prefill_start")
    pend = first_t(evs, "prefill_end")
    seat = first_t(evs, "seat", "resume")
    fin = last_t(evs, "finish")
    preempted = 0.0
    t_pre = None
    for t, _r, e in evs:
        if e["k"] == "preempt":
            t_pre = t
        elif e["k"] == "resume" and t_pre is not None:
            preempted += max(0.0, t - t_pre) / 1e6
            t_pre = None

    def leg(a, b):
        return max(0.0, (b - a) / 1e6) if a is not None and b is not None \
            else None

    # Wire backpressure INSIDE the overlapped layerwise-prefill window
    # is ship time, not prefill: the gap between a layer's compute end
    # (prefill_layer) and its publish returning (ship_pready), and the
    # descriptor-header send wait (prefill_start -> ship_hdr). A
    # monolithic journey has neither event and loses nothing.
    publish_block = 0.0
    t_layer = None
    for t, _r, e in evs:
        if e["k"] == "prefill_layer":
            t_layer = t
        elif e["k"] == "ship_pready" and t_layer is not None:
            publish_block += max(0.0, t - t_layer) / 1e6
            t_layer = None
    hdr = first_t(evs, "ship_hdr")
    hdr_block = (leg(pstart, hdr) or 0.0) if hdr is not None else 0.0
    wire_in_prefill = publish_block + hdr_block

    ship = leg(pend, seat)
    if ship is not None:
        ship += wire_in_prefill
    prefill = leg(pstart, pend)
    if prefill is not None:
        prefill = max(0.0, prefill - wire_in_prefill)

    ph = {"queue": leg(admit, pstart),
          "prefill": prefill,
          "ship": ship,
          "preempted": preempted if preempted > 0 else
          (0.0 if seat is not None else None)}

    streams = [e for _t, _r, e in evs if e["k"] == "stream"]
    ttft = next((float(e["ttft_s"]) for e in streams if "ttft_s" in e), None)
    itls = [float(e["itl_s"]) for e in streams if "itl_s" in e]
    # Decode SERVICE is this rid's share of the batched steps (tokens x
    # per-token step time from the stream events), not the seat->finish
    # wall window: the window also holds head-of-line interference —
    # the loop blocking on a NEIGHBOR's inbound handoff or an in-loop
    # refill — which would let a wire fault masquerade as slow decode.
    # The full window still shows in total_s. Journeys that died before
    # any inter-token stream fall back to the window.
    dec = leg(seat, fin)
    if itls:
        ph["decode"] = sum(float(e["itl_s"]) * int(e.get("n", 1))
                           for e in streams if "itl_s" in e)
    else:
        ph["decode"] = max(0.0, dec - preempted) if dec is not None else None
    entry = admit if admit is not None else pstart
    return {
        "phases": ph,
        "start_us": entry,
        "finish_us": fin,
        "total_s": leg(entry, fin),
        "ttft_s": ttft,
        "itl_p50_s": percentile(itls, 50),
        "rejected": any(e["k"] == "reject" for _t, _r, e in evs),
        "reconstructed": (entry is not None and fin is not None),
        "ranks": sorted({r for _t, r, _e in evs}),
    }


def percentile(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[i]


def fleet_breakdown(journeys_attr):
    """Per-phase percentiles + totals over reconstructed journeys, and
    the dominant phase (largest share of summed wall time)."""
    per_phase = {p: [] for p in PHASES}
    for a in journeys_attr.values():
        if not a["reconstructed"]:
            continue
        for p in PHASES:
            v = a["phases"].get(p)
            if v is not None:
                per_phase[p].append(v)
    out, totals = {}, {}
    for p in PHASES:
        xs = per_phase[p]
        if p in SERVICE_PHASES:
            totals[p] = sum(xs)
        out[p] = {"n": len(xs), "total_s": round(sum(xs), 6),
                  "p50_s": percentile(xs, 50), "p95_s": percentile(xs, 95),
                  "p99_s": percentile(xs, 99)}
    dominant = max(totals, key=totals.get) if any(totals.values()) else None
    return out, dominant


def burn_rate(journeys_attr, ttft_s, itl_s, budget, window_s):
    """Rolling SLO burn: bucket finished requests by corrected finish
    time into window_s windows; per window, violation fraction vs the
    TTFT/ITL targets; burn = fraction / budget. Emitted even without
    targets (targets null, burn null) so --check can assert presence.
    """
    rep = {"ttft_target_s": ttft_s, "itl_target_s": itl_s,
           "budget": budget, "window_s": window_s, "windows": []}
    done = [a for a in journeys_attr.values()
            if a["reconstructed"] and a["finish_us"] is not None]
    if not done or (ttft_s is None and itl_s is None):
        rep["max_burn"] = None
        rep["last_burn"] = None
        return rep
    t0 = min(a["finish_us"] for a in done)
    buckets = {}
    for a in done:
        buckets.setdefault(int((a["finish_us"] - t0) / (window_s * 1e6)),
                           []).append(a)
    for w in sorted(buckets):
        group = buckets[w]
        bad = 0
        for a in group:
            v = (ttft_s is not None and a["ttft_s"] is not None
                 and a["ttft_s"] > ttft_s)
            v = v or (itl_s is not None and a["itl_p50_s"] is not None
                      and a["itl_p50_s"] > itl_s)
            bad += bool(v)
        frac = bad / len(group)
        rep["windows"].append({"window": w, "n": len(group),
                               "violations": bad,
                               "burn": round(frac / budget, 3)})
    burns = [w["burn"] for w in rep["windows"]]
    rep["max_burn"] = max(burns)
    rep["last_burn"] = burns[-1]
    return rep


def render_waterfall(journeys_attr, n, out=sys.stdout):
    """ASCII per-request waterfall: the n slowest reconstructed
    journeys, one bar per request, one glyph per phase."""
    glyph = {"queue": "q", "prefill": "P", "ship": "S", "decode": "d",
             "preempted": "x"}
    done = sorted(
        ((rid, a) for rid, a in journeys_attr.items()
         if a["reconstructed"] and a["total_s"]),
        key=lambda kv: -kv[1]["total_s"])[:n]
    if not done:
        return
    width = 60
    tmax = max(a["total_s"] for _rid, a in done)
    print(f"-- waterfall: {len(done)} slowest requests "
          f"(q=queue P=prefill S=ship d=decode x=preempted) --", file=out)
    for rid, a in done:
        bar = ""
        for p in PHASES:
            v = a["phases"].get(p) or 0.0
            bar += glyph[p] * max(1 if v > 0 else 0,
                                  int(round(v / tmax * width)))
        ranks = ",".join(str(r) for r in a["ranks"])
        print(f"rid {rid:>4} [{bar:<{width + 8}}] "
              f"{a['total_s'] * 1e3:8.1f} ms  ranks {ranks}", file=out)


def main():
    ap = argparse.ArgumentParser(
        description="reconstruct ACX request journeys; fleet phase "
                    "breakdown + SLO burn rate")
    ap.add_argument("inputs", nargs="+",
                    help="*.reqlog.jsonl (and optional sibling "
                         "*.trace.json for barrier-anchored skew)")
    ap.add_argument("--json", help="write the full report here")
    ap.add_argument("--waterfall", type=int, default=0, metavar="N",
                    help="render the N slowest request waterfalls")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit 1 unless enough journeys "
                         "reconstruct and the burn section is emitted")
    ap.add_argument("--min-reconstructed", type=float, default=0.95)
    ap.add_argument("--expect-dominant", choices=SERVICE_PHASES,
                    help="with --check: require this fleet-dominant "
                         "service phase (queue is backlog, not service)")
    ap.add_argument("--ttft-ms", type=float, default=float(
        os.environ.get("ACX_SERVE_ADMIT_TTFT_MS", "0") or 0))
    ap.add_argument("--itl-ms", type=float, default=float(
        os.environ.get("ACX_SERVE_ADMIT_ITL_MS", "0") or 0))
    ap.add_argument("--budget", type=float, default=0.01,
                    help="SLO error budget (violation fraction allowed)")
    ap.add_argument("--window-s", type=float, default=5.0)
    args = ap.parse_args()

    reqlogs, traces, missing = [], [], []
    for i, path in enumerate(args.inputs):
        r = parse_rank(path, i)
        if path.endswith(".reqlog.jsonl"):
            try:
                init, events, torn = load_reqlog(path)
            except OSError as exc:
                missing.append({"path": path, "rank": r, "reason": str(exc)})
                continue
            reqlogs.append((r, init, events, torn))
        elif path.endswith(".trace.json"):
            try:
                traces.append((r, load(path)))
            except (OSError, json.JSONDecodeError) as exc:
                missing.append({"path": path, "rank": r, "reason": str(exc)})
        else:
            print(f"acx_request: ignoring unrecognized input {path}",
                  file=sys.stderr)
    if not reqlogs:
        print("acx_request: no .reqlog.jsonl inputs", file=sys.stderr)
        sys.exit(2)

    skew, skew_source = rank_skews(reqlogs, traces)
    journeys, fleet, unknown = build_journeys(reqlogs, skew)
    for k, n in sorted(unknown.items()):
        print(f"acx_request: WARNING: unknown journey kind {k!r} "
              f"x{n} — decode table out of date?", file=sys.stderr)

    attr = {rid: attribute(evs) for rid, evs in journeys.items()}
    rejected = sum(a["rejected"] for a in attr.values())
    candidates = {rid: a for rid, a in attr.items() if not a["rejected"]}
    recon = sum(a["reconstructed"] for a in candidates.values())
    rate = recon / len(candidates) if candidates else 0.0
    breakdown, dominant = fleet_breakdown(candidates)
    burn = burn_rate(candidates, args.ttft_ms / 1e3 or None,
                     args.itl_ms / 1e3 or None, args.budget, args.window_s)

    report = {
        "ranks": sorted(r for r, _i, _e, _t in reqlogs),
        "skew_source": {str(r): skew_source[r] for r in skew_source},
        "torn_lines": {str(r): t for r, _i, _e, t in reqlogs},
        "events": fleet["events"],
        "decode_steps": fleet["decode_steps"],
        "rids": len(attr),
        "rejected": rejected,
        "reconstructed": recon,
        "reconstructed_rate": round(rate, 4),
        "unknown_kinds": unknown,
        "phase_breakdown": breakdown,
        "dominant_phase": dominant,
        "burn": burn,
    }
    if missing:
        report["missing"] = missing
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    print(json.dumps({k: v for k, v in report.items()
                      if k not in ("phase_breakdown",)}))
    if args.waterfall:
        render_waterfall(candidates, args.waterfall)

    if args.check:
        errors = []
        if rate < args.min_reconstructed:
            errors.append(f"reconstructed {recon}/{len(candidates)} "
                          f"({rate:.1%}) < {args.min_reconstructed:.0%}")
        if "max_burn" not in burn:
            errors.append("burn-rate section missing")
        if args.expect_dominant and dominant != args.expect_dominant:
            errors.append(f"dominant phase {dominant!r}, expected "
                          f"{args.expect_dominant!r}")
        if unknown:
            errors.append(f"unknown kinds: {sorted(unknown)}")
        for e in errors:
            print(f"acx_request: CHECK FAIL: {e}", file=sys.stderr)
        sys.exit(1 if errors else 0)


if __name__ == "__main__":
    main()
