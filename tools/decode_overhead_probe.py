"""Decompose the decode step's time on the live chip.

BENCH_BANK r05: greedy decode runs at 21.7% of its weight+KV-stream
roofline (1.82 ms/step vs 0.40 ms of HBM traffic at B=8). This probe
fits t(step) = c0 + c_layer*L + c_bytes*streamed_bytes by sweeping the
layer count and cache length on the real chip, separating fixed
per-step overhead (dispatch, sampling, unembed) from per-layer
overhead (scan iteration, small-matmul latency) from true bandwidth.

Usage: python tools/decode_overhead_probe.py
Prints one JSON line per configuration plus a least-squares fit.
"""

import dataclasses
import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from mpi_acx_tpu.models import transformer as tfm

    base = tfm.gpt2_small()
    rows = []
    for L, max_len in [(12, 256), (12, 512), (12, 1024), (6, 256),
                       (6, 1024), (3, 256), (3, 1024), (12, 2048)]:
        cfg = dataclasses.replace(base, n_layers=L)
        params = tfm.cast_params(
            tfm.init_params(jax.random.key(0), cfg), jnp.bfloat16)
        B, S, n_new = 8, 32, 16
        prompt = jax.random.randint(jax.random.key(1), (B, S), 0,
                                    cfg.vocab)

        # Prefill OUTSIDE the timed region (it streams the weights once
        # and scales with L — folding it in biases every coefficient of
        # the fit); the timed program is the pure decode scan.
        from jax import lax

        logits, cache0 = jax.jit(
            lambda p, t, c=cfg, ml=max_len: tfm.prefill(
                p, c, t, ml, last_only=True))(params, prompt)
        first = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)

        @jax.jit
        def decode_loop(p, cache, tok, c=cfg):
            def step(carry, _):
                cache, tok = carry
                lg, cache = tfm.decode_step(p, c, cache, tok)
                return (cache, jnp.argmax(lg, axis=-1).astype(tok.dtype)), None
            (cache, tok), _ = lax.scan(step, (cache, tok), None,
                                       length=n_new)
            return tok

        jax.block_until_ready(decode_loop(params, cache0, first))
        t0 = time.perf_counter()
        jax.block_until_ready(decode_loop(params, cache0, first))
        dt = (time.perf_counter() - t0) / n_new

        wbytes = sum(x.size * x.dtype.itemsize
                     for x in jax.tree.leaves(params))
        kvbytes = 2 * L * B * max_len * cfg.d_model * 2  # H*Dh = d_model
        rows.append({"L": L, "max_len": max_len,
                     "ms_per_tok": round(dt * 1e3, 3),
                     "weight_mb": round(wbytes / 1e6, 1),
                     "kv_mb": round(kvbytes / 1e6, 1)})
        print(json.dumps(rows[-1]), flush=True)

    # Least squares: t = c0 + cL * L + cB * bytes
    A = np.array([[1.0, r["L"], r["weight_mb"] + r["kv_mb"]]
                  for r in rows])
    y = np.array([r["ms_per_tok"] for r in rows])
    c, *_ = np.linalg.lstsq(A, y, rcond=None)
    print(json.dumps({
        "fit_fixed_ms": round(float(c[0]), 4),
        "fit_per_layer_ms": round(float(c[1]), 4),
        "fit_per_mb_ms": round(float(c[2]), 5),
        "implied_bw_gbps": round(1.0 / float(c[2]), 1) if c[2] > 0 else None,
    }))


if __name__ == "__main__":
    main()
