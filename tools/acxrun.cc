// acxrun — tpu-acx process launcher.
//
// Plays the role `mpiexec -np N` plays for the reference (reference
// README.md:99-103): spawns N ranks of a program on this host with a fully
// connected mesh of AF_UNIX socketpairs, which SocketTransport
// (src/net/socket_transport.cc) picks up via ACX_RANK / ACX_SIZE / ACX_FDS.
//
// Usage: acxrun -np N [-timeout SECONDS] prog [args...]
//
// Exit status: 0 iff every rank exited 0. If any rank exits nonzero or a
// timeout fires, the remaining ranks are killed (matching mpiexec behavior
// on MPI_Abort).

#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <string>
#include <vector>

static void usage() {
  fprintf(stderr, "usage: acxrun -np N [-timeout SEC] prog [args...]\n");
  exit(2);
}

int main(int argc, char** argv) {
  int np = -1;
  int timeout_s = 120;
  int argi = 1;
  while (argi < argc && argv[argi][0] == '-') {
    if (!strcmp(argv[argi], "-np") && argi + 1 < argc) {
      np = atoi(argv[argi + 1]);
      argi += 2;
    } else if (!strcmp(argv[argi], "-timeout") && argi + 1 < argc) {
      timeout_s = atoi(argv[argi + 1]);
      argi += 2;
    } else {
      usage();
    }
  }
  if (np < 1 || argi >= argc) usage();

  // fd_of[i][j] = fd rank i uses to talk to rank j.
  std::vector<std::vector<int>> fd_of(np, std::vector<int>(np, -1));
  for (int i = 0; i < np; i++) {
    for (int j = i + 1; j < np; j++) {
      int sv[2];
      if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
        perror("acxrun: socketpair");
        return 2;
      }
      fd_of[i][j] = sv[0];
      fd_of[j][i] = sv[1];
    }
  }

  std::vector<pid_t> pids(np);
  for (int r = 0; r < np; r++) {
    pid_t pid = fork();
    if (pid < 0) {
      perror("acxrun: fork");
      return 2;
    }
    if (pid == 0) {
      // Child, rank r: keep only this rank's fds, close the rest.
      std::string fds;
      for (int j = 0; j < np; j++) {
        if (j) fds += ',';
        fds += std::to_string(fd_of[r][j]);
      }
      for (int i = 0; i < np; i++) {
        if (i == r) continue;
        for (int j = 0; j < np; j++) {
          if (fd_of[i][j] >= 0 && i != r && j != r) close(fd_of[i][j]);
        }
      }
      setenv("ACX_RANK", std::to_string(r).c_str(), 1);
      setenv("ACX_SIZE", std::to_string(np).c_str(), 1);
      setenv("ACX_FDS", fds.c_str(), 1);
      execvp(argv[argi], &argv[argi]);
      fprintf(stderr, "acxrun: exec %s failed: %s\n", argv[argi],
              strerror(errno));
      _exit(127);
    }
    pids[r] = pid;
  }

  // Parent: close every fd, then reap with a timeout.
  for (int i = 0; i < np; i++)
    for (int j = 0; j < np; j++)
      if (fd_of[i][j] >= 0) close(fd_of[i][j]);

  // SIGALRM must interrupt wait() (no SA_RESTART) rather than kill us.
  struct sigaction sa {};
  sa.sa_handler = [](int) {};
  sigaction(SIGALRM, &sa, nullptr);
  alarm(timeout_s);
  int worst = 0;
  int live = np;
  while (live > 0) {
    int st = 0;
    pid_t pid = wait(&st);
    if (pid < 0) {
      if (errno == EINTR) {
        fprintf(stderr, "acxrun: timeout after %ds, killing ranks\n",
                timeout_s);
        for (int r = 0; r < np; r++) kill(pids[r], SIGKILL);
        worst = worst ? worst : 124;
        timeout_s = 5;
        alarm(5);
        continue;
      }
      break;
    }
    live--;
    int code = WIFEXITED(st) ? WEXITSTATUS(st)
                             : 128 + (WIFSIGNALED(st) ? WTERMSIG(st) : 0);
    if (code != 0) {
      if (!worst) worst = code;
      // One rank failed: take the job down like mpiexec does on MPI_Abort.
      for (int r = 0; r < np; r++)
        if (pids[r] != pid) kill(pids[r], SIGTERM);
    }
  }
  return worst;
}
