// acxrun — tpu-acx process launcher.
//
// Plays the role `mpiexec -np N` plays for the reference (reference
// README.md:99-103): spawns N ranks of a program on this host with two
// pre-wired data planes the transport picks from at init:
//   * a shared-memory segment (memfd) of SPSC rings, the same-host fast
//     path (ACX_SHM_FD / ACX_SHM_RING_BYTES), and
//   * a fully connected mesh of AF_UNIX socketpairs (ACX_FDS).
// Ranks default to shm; `-transport socket` (or env ACX_TRANSPORT=socket)
// selects the socket plane.
//
// Usage: acxrun -np N [-timeout SECONDS] [-transport shm|socket] prog [args...]
//
// Exit status: 0 iff every rank exited 0. If any rank exits nonzero or a
// timeout fires, the remaining ranks are killed (matching mpiexec behavior
// on MPI_Abort).
//
// Chaos mode (-chaos, DESIGN.md §16): a rank that dies by SIGKILL — the
// `kill` fault action, or an external chaos agent — is respawned with
// ACX_JOIN=1 so it rejoins the fleet through the membership plane (§12)
// instead of failing the job. Respawns are bounded (-max-respawns, default
// 2 per rank); the respawned incarnation gets fault injection stripped
// (one scheduled kill must not re-fire forever) and its artifact prefixes
// (ACX_FLIGHT/ACX_METRICS/ACX_TSERIES/ACX_TRACE/ACX_FAULT_REPORT)
// suffixed ".i<k>" so it cannot clobber its predecessor's dumps. The
// supervisor prints a machine-readable ledger:
//   acxrun: chaos schedule <full ;-joined spec list>   (launch, if armed)
//   acxrun: chaos respawn rank=R incarnation=K         (per respawn)
//   acxrun: chaos ledger rank=R respawns=K             (at exit)
// `-print-chaos SPEC` expands an ACX_CHAOS seed spec (with -np if given,
// else np=2) to its concrete schedule on stdout and exits — the same
// expansion every rank performs, exposed for harnesses and replay.
//
// Failure detection (exceeds the reference, whose only story is
// MPI_ERRORS_ARE_FATAL abort — SURVEY.md §5.3): the supervisor attributes
// every failure to a rank. The FIRST failing rank is named with its exit
// code or signal before peers are torn down, every abnormal exit is
// reported per rank, and on timeout the set of still-running (stuck)
// ranks is listed — turning "the job hung" into "rank 2 never exited".
// A machine-readable `acxrun: status rank=R ...` line per abnormal rank
// goes to stderr for harnesses to parse.

#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <string>
#include <vector>

#include "acx/fault.h"
#include "src/net/link.h"

static void usage() {
  fprintf(stderr,
          "usage: acxrun -np N [-timeout SEC] [-transport shm|socket] "
          "[-fault SCHEDULE] [-chaos] [-max-respawns K] prog [args...]\n"
          "       acxrun -print-chaos SPEC [-np N]\n"
          "  -fault SCHEDULE  arm deterministic fault injection in every rank\n"
          "               (sets ACX_FAULT; ';'-separated list of specs, each\n"
          "               action[:key=val]..., e.g.\n"
          "               drop:rank=0:kind=send:nth=1;kill:rank=1:nth=7 —\n"
          "               see include/acx/fault.h)\n"
          "               op-level actions:   drop | delay | fail | kill\n"
          "               wire-level actions: drop_frame | corrupt_frame |\n"
          "               stall_link_ms (ms=M) | close_link_once — exercise\n"
          "               the CRC/NAK/replay/reconnect machinery on the\n"
          "               socket plane (-transport socket)\n"
          "  -chaos       respawn SIGKILLed ranks with ACX_JOIN=1 (requires\n"
          "               -transport socket); print respawn ledger\n"
          "  -max-respawns K  per-rank respawn budget in -chaos mode "
          "(default 2)\n"
          "  -print-chaos SPEC  expand an ACX_CHAOS seed spec (seed=N:\n"
          "               faults=K:mix=...) to its concrete schedule and "
          "exit\n");
  exit(2);
}

int main(int argc, char** argv) {
  int np = -1;
  int timeout_s = 120;
  const char* transport = nullptr;  // nullptr = leave env as-is (default shm)
  const char* fault = nullptr;
  const char* print_chaos = nullptr;
  bool chaos = false;
  int max_respawns = 2;
  int argi = 1;
  while (argi < argc && argv[argi][0] == '-') {
    if (!strcmp(argv[argi], "-np") && argi + 1 < argc) {
      np = atoi(argv[argi + 1]);
      argi += 2;
    } else if (!strcmp(argv[argi], "-timeout") && argi + 1 < argc) {
      timeout_s = atoi(argv[argi + 1]);
      argi += 2;
    } else if (!strcmp(argv[argi], "-transport") && argi + 1 < argc) {
      transport = argv[argi + 1];
      argi += 2;
    } else if (!strcmp(argv[argi], "-fault") && argi + 1 < argc) {
      fault = argv[argi + 1];
      argi += 2;
    } else if (!strcmp(argv[argi], "-chaos")) {
      chaos = true;
      argi += 1;
    } else if (!strcmp(argv[argi], "-max-respawns") && argi + 1 < argc) {
      max_respawns = atoi(argv[argi + 1]);
      argi += 2;
    } else if (!strcmp(argv[argi], "-print-chaos") && argi + 1 < argc) {
      print_chaos = argv[argi + 1];
      argi += 2;
    } else {
      usage();
    }
  }
  if (print_chaos != nullptr) {
    // Expansion oracle: same splitmix64 expansion every rank performs on
    // ACX_CHAOS, exposed so harnesses can know the concrete schedule (and
    // replay it verbatim via -fault) without running a rank.
    char buf[2048];
    if (!acx::fault::ExpandChaos(print_chaos, np > 0 ? np : 2, buf,
                                 sizeof buf)) {
      fprintf(stderr, "acxrun: bad -print-chaos spec '%s'\n", print_chaos);
      return 2;
    }
    printf("%s\n", buf);
    return 0;
  }
  if (np < 1 || argi >= argc) usage();
  if (max_respawns < 0) max_respawns = 0;
  if (fault != nullptr) {
    // Validate up front with the same parser the ranks use: a typo'd
    // schedule must fail the launch, not silently run the job fault-free.
    acx::fault::Config fc[acx::fault::kMaxSpecs];
    int nspec = 0;
    if (!acx::fault::ParseSchedule(fault, fc, acx::fault::kMaxSpecs,
                                   &nspec)) {
      fprintf(stderr, "acxrun: bad -fault schedule '%s'\n", fault);
      return 2;
    }
  }
  if (transport != nullptr && strcmp(transport, "shm") != 0 &&
      strcmp(transport, "socket") != 0) {
    fprintf(stderr, "acxrun: unknown -transport '%s' (want shm or socket)\n",
            transport);
    return 2;
  }
  const char* env_transport = getenv("ACX_TRANSPORT");
  const bool socket_plane =
      (transport != nullptr && strcmp(transport, "socket") == 0) ||
      (transport == nullptr && env_transport != nullptr &&
       strcmp(env_transport, "socket") == 0);
  if (chaos && !socket_plane) {
    // Rejoin runs over the reconnect listeners (§9) — a shm-plane rank has
    // no path back into the fleet, so respawning it would just wedge.
    fprintf(stderr, "acxrun: -chaos requires -transport socket\n");
    return 2;
  }

  // Echo the full concrete schedule when any injection is armed: the one
  // line a harness needs to audit "every scheduled fault fired" and to
  // replay a seeded run without re-deriving the expansion.
  {
    const char* env_fault = getenv("ACX_FAULT");
    const char* env_chaos = getenv("ACX_CHAOS");
    std::string sched;
    if (fault != nullptr)
      sched = fault;
    else if (env_fault != nullptr && env_fault[0] != '\0')
      sched = env_fault;
    if (env_chaos != nullptr && env_chaos[0] != '\0') {
      char buf[2048];
      if (!acx::fault::ExpandChaos(env_chaos, np, buf, sizeof buf)) {
        fprintf(stderr, "acxrun: bad ACX_CHAOS spec '%s'\n", env_chaos);
        return 2;
      }
      if (!sched.empty()) sched += ';';
      sched += buf;
    }
    if (!sched.empty())
      fprintf(stderr, "acxrun: chaos schedule %s\n", sched.c_str());
  }

  // Shared-memory plane: one memfd of np*(np-1) directed rings. The fd is
  // inherited across fork+exec (no MFD_CLOEXEC); each rank mmaps it.
  const char* ring_env = getenv("ACX_SHM_RING_BYTES");
  const size_t ring_bytes = acx::ShmSanitizeRingBytes(
      ring_env ? strtoull(ring_env, nullptr, 10) : acx::kShmDefaultRingBytes);
  int shm_fd = -1;
  if (np > 1) {
    shm_fd = memfd_create("acx-shm", 0);
    if (shm_fd < 0) {
      perror("acxrun: memfd_create (shm plane disabled)");
    } else if (ftruncate(shm_fd,
                         (off_t)acx::ShmSegmentBytes(np, ring_bytes)) != 0) {
      perror("acxrun: ftruncate (shm plane disabled)");
      close(shm_fd);
      shm_fd = -1;
    }
    if (shm_fd < 0 && transport != nullptr && strcmp(transport, "shm") == 0) {
      // shm was requested by name: fail loudly rather than silently
      // benchmarking the socket plane.
      fprintf(stderr, "acxrun: -transport shm requested but unavailable\n");
      return 2;
    }
  }

  // fd_of[i][j] = fd rank i uses to talk to rank j.
  std::vector<std::vector<int>> fd_of(np, std::vector<int>(np, -1));
  for (int i = 0; i < np; i++) {
    for (int j = i + 1; j < np; j++) {
      int sv[2];
      if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
        perror("acxrun: socketpair");
        return 2;
      }
      fd_of[i][j] = sv[0];
      fd_of[j][i] = sv[1];
    }
  }

  const std::string job_id = std::to_string(getpid());  // captured pre-fork
  std::vector<pid_t> pids(np);
  for (int r = 0; r < np; r++) {
    pid_t pid = fork();
    if (pid < 0) {
      perror("acxrun: fork");
      return 2;
    }
    if (pid == 0) {
      // Child, rank r: keep only this rank's fds, close the rest.
      std::string fds;
      for (int j = 0; j < np; j++) {
        if (j) fds += ',';
        fds += std::to_string(fd_of[r][j]);
      }
      // Close every fd that is not this rank's own end (row r). Keeping
      // a peer's end of a pair involving r would hold that socket open
      // from inside r itself: when the peer later dies, r's stray dup
      // suppresses the EOF and the death is never detected.
      for (int i = 0; i < np; i++) {
        if (i == r) continue;
        for (int j = 0; j < np; j++) {
          if (fd_of[i][j] >= 0) close(fd_of[i][j]);
        }
      }
      setenv("ACX_RANK", std::to_string(r).c_str(), 1);
      setenv("ACX_SIZE", std::to_string(np).c_str(), 1);
      setenv("ACX_FDS", fds.c_str(), 1);
      // Job id namespaces the per-rank reconnect listeners (abstract AF_UNIX
      // "\0acx-<job>-<rank>", DESIGN.md §9). The launcher pid is unique per
      // concurrent job on a host; overwrite=0 lets a test pin its own id.
      setenv("ACX_JOB_ID", job_id.c_str(), 0);
      if (shm_fd >= 0) {
        setenv("ACX_SHM_FD", std::to_string(shm_fd).c_str(), 1);
        setenv("ACX_SHM_RING_BYTES", std::to_string(ring_bytes).c_str(), 1);
      }
      if (transport != nullptr) setenv("ACX_TRANSPORT", transport, 1);
      if (fault != nullptr) setenv("ACX_FAULT", fault, 1);
      execvp(argv[argi], &argv[argi]);
      fprintf(stderr, "acxrun: exec %s failed: %s\n", argv[argi],
              strerror(errno));
      _exit(127);
    }
    pids[r] = pid;
  }

  // Parent: close every fd, then reap with a timeout.
  for (int i = 0; i < np; i++)
    for (int j = 0; j < np; j++)
      if (fd_of[i][j] >= 0) close(fd_of[i][j]);
  if (shm_fd >= 0) close(shm_fd);

  // SIGALRM must interrupt wait() (no SA_RESTART) rather than kill us.
  struct sigaction sa {};
  sa.sa_handler = [](int) {};
  sigaction(SIGALRM, &sa, nullptr);
  alarm(timeout_s);
  int worst = 0;
  int live = np;
  // Per-rank terminal status for attribution: -1 = still running,
  // otherwise the rank's effective exit code (128+sig for signals).
  std::vector<int> status_of(np, -1);
  // Ranks the SUPERVISOR signaled (teardown/timeout): their deaths are
  // induced, not failures, and are tagged killed=1 so a harness counting
  // `status rank=R exit=`/`signal=` lines counts only genuine failures.
  std::vector<bool> killed_by_us(np, false);
  // Chaos mode: per-rank respawn ledger.
  std::vector<int> respawns(np, 0);
  // Respawn a SIGKILLed rank as a late joiner. The original socketpair
  // mesh is gone (every fd is closed on both sides by now); the new
  // incarnation comes back through the reconnect listeners, which is
  // exactly the ACX_JOIN=1 path the membership plane already speaks.
  auto respawn_rank = [&](int r) -> bool {
    pid_t pid = fork();
    if (pid < 0) {
      perror("acxrun: fork (respawn)");
      return false;
    }
    if (pid == 0) {
      // Stale launch plumbing from the supervisor's env must not leak in:
      // the fds in ACX_FDS don't exist in this process.
      unsetenv("ACX_FDS");
      unsetenv("ACX_SHM_FD");
      // Strip injection — a scheduled kill must not re-fire in every
      // incarnation, turning one fault into an infinite crash loop.
      unsetenv("ACX_FAULT");
      unsetenv("ACX_CHAOS");
      setenv("ACX_RANK", std::to_string(r).c_str(), 1);
      setenv("ACX_SIZE", std::to_string(np).c_str(), 1);
      setenv("ACX_JOB_ID", job_id.c_str(), 0);
      setenv("ACX_JOIN", "1", 1);
      if (transport != nullptr) setenv("ACX_TRANSPORT", transport, 1);
      // Artifact prefixes get ".i<k>" so incarnation k's flight dump /
      // metrics / tseries / traces land NEXT TO the dead incarnation's
      // files instead of overwriting them (the oracle audits both).
      static const char* const kPrefixEnvs[] = {
          "ACX_FLIGHT", "ACX_METRICS", "ACX_TSERIES", "ACX_TRACE",
          "ACX_FAULT_REPORT"};
      for (const char* name : kPrefixEnvs) {
        const char* v = getenv(name);
        if (v == nullptr || v[0] == '\0' || !strcmp(v, "0") ||
            !strcmp(v, "1"))
          continue;  // boolean/off gating, not a path prefix
        std::string nv = std::string(v) + ".i" + std::to_string(respawns[r]);
        setenv(name, nv.c_str(), 1);
      }
      execvp(argv[argi], &argv[argi]);
      fprintf(stderr, "acxrun: exec %s failed (respawn): %s\n", argv[argi],
              strerror(errno));
      _exit(127);
    }
    pids[r] = pid;
    return true;
  };
  auto rank_of = [&](pid_t pid) {
    for (int r = 0; r < np; r++)
      if (pids[r] == pid) return r;
    return -1;
  };
  // Record one reaped child: status bookkeeping + the attribution line.
  // Returns true iff this was a GENUINE failure (nonzero, not a death
  // the supervisor itself induced).
  auto reap_one = [&](pid_t pid, int st) {
    live--;
    int rank = rank_of(pid);
    int code = WIFEXITED(st) ? WEXITSTATUS(st)
                             : 128 + (WIFSIGNALED(st) ? WTERMSIG(st) : 0);
    if (rank >= 0) status_of[rank] = code;
    if (code == 0) return false;
    // Induced deaths are SIGNAL deaths of ranks we signaled — the
    // supervisor only ever sends signals, so a WIFEXITED nonzero code is
    // always the rank's own (genuine) failure, even if our SIGTERM was
    // in flight when it exited. This closes the last mistag window.
    bool induced = rank >= 0 && killed_by_us[rank] && WIFSIGNALED(st);
    if (WIFSIGNALED(st)) {
      fprintf(stderr, "acxrun: status rank=%d signal=%d%s\n", rank,
              WTERMSIG(st), induced ? " killed=1" : "");
    } else {
      fprintf(stderr, "acxrun: status rank=%d exit=%d%s\n", rank, code,
              induced ? " killed=1" : "");
    }
    if (induced) return false;
    if (!worst) {
      worst = code;
      fprintf(stderr,
              "acxrun: rank %d failed first; terminating %d peer(s)\n",
              rank, live);
    }
    return true;
  };
  while (live > 0) {
    int st = 0;
    pid_t pid = wait(&st);
    if (pid < 0) {
      if (errno == EINTR) {
        // Timeout: name the stuck ranks before killing them — the
        // difference between "the job hung" and "rank 2 never exited".
        std::string stuck;
        for (int r = 0; r < np; r++) {
          if (status_of[r] < 0) {
            if (!stuck.empty()) stuck += ',';
            stuck += std::to_string(r);
          }
        }
        fprintf(stderr,
                "acxrun: timeout after %ds; stuck ranks: %s (killing)\n",
                timeout_s, stuck.empty() ? "none" : stuck.c_str());
        for (int r = 0; r < np; r++)
          if (status_of[r] < 0) {
            fprintf(stderr, "acxrun: status rank=%d stuck=1\n", r);
            killed_by_us[r] = true;
            kill(pids[r], SIGKILL);
          }
        worst = worst ? worst : 124;
        timeout_s = 5;
        alarm(5);
        continue;
      }
      break;
    }
    if (chaos) {
      // SIGKILL deaths we did not induce are chaos casualties: respawn
      // within budget instead of failing the job. (Only SIGKILL — a rank
      // that aborts or segfaults is a genuine bug, not injected chaos.)
      const int rank = rank_of(pid);
      if (rank >= 0 && WIFSIGNALED(st) && WTERMSIG(st) == SIGKILL &&
          !killed_by_us[rank] && respawns[rank] < max_respawns) {
        respawns[rank]++;
        fprintf(stderr, "acxrun: chaos respawn rank=%d incarnation=%d\n",
                rank, respawns[rank]);
        if (respawn_rank(rank)) continue;  // live count unchanged
        live--;  // fork failed: fall through to plain accounting
        status_of[rank] = 128 + SIGKILL;
        worst = worst ? worst : status_of[rank];
        continue;
      }
    }
    if (reap_one(pid, st)) {
      // Genuine failure: before attributing teardown to the peers,
      // DRAIN ranks that already died on their own (kill() on an
      // unreaped zombie "succeeds", which would mistag a simultaneous
      // second genuine failure as supervisor-induced).
      int st2 = 0;
      pid_t p2;
      while (live > 0 && (p2 = waitpid(-1, &st2, WNOHANG)) > 0)
        reap_one(p2, st2);
      // Take the job down like mpiexec does on MPI_Abort.
      for (int r = 0; r < np; r++)
        if (status_of[r] < 0) {
          killed_by_us[r] = true;
          kill(pids[r], SIGTERM);
        }
    }
  }
  if (chaos) {
    for (int r = 0; r < np; r++)
      if (respawns[r] > 0)
        fprintf(stderr, "acxrun: chaos ledger rank=%d respawns=%d\n", r,
                respawns[r]);
  }
  return worst;
}
