// tpu-acx host-plane benchmark: enqueued ping-pong latency + partitioned
// bandwidth (the two BASELINE.md metrics the reference never published).
//
// Run under `acxrun -np 2 build/bench_pingpong [msg_bytes]`.
// Rank 0 prints one parseable line:
//   BENCH pingpong_p50_us=<v> pingpong_p99_us=<v> part_bw_gbps=<v> iters=<n>
//
// Ping-pong: rank 0 enqueues isend+irecv on the host queue and host-waits
// (the reference ring.c flow, full proxy + wire round trip); one-way
// latency = rtt/2. Partitioned: 64MiB in 16 partitions, Pready-marked
// out of order, timed over full rounds.

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include <mpi.h>
#include <mpi-acx.h>

using Clock = std::chrono::steady_clock;

static double us_since(Clock::time_point t0) {
  return std::chrono::duration<double, std::micro>(Clock::now() - t0).count();
}

int main(int argc, char** argv) {
  int provided, rank, size;
  MPI_Init_thread(&argc, &argv, MPI_THREAD_MULTIPLE, &provided);
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  if (size != 2) {
    if (rank == 0) std::fprintf(stderr, "bench_pingpong needs -np 2\n");
    MPI_Abort(MPI_COMM_WORLD, 2);
  }
  if (MPIX_Init()) MPI_Abort(MPI_COMM_WORLD, 2);

  const int peer = 1 - rank;
  const size_t msg = argc > 1 ? std::atol(argv[1]) : 8;
  const int warmup = 200, iters = 2000;
  std::vector<char> sbuf(msg, 1), rbuf(msg, 0);
  std::vector<double> lat;
  lat.reserve(iters);

  for (int it = -warmup; it < iters; it++) {
    auto t0 = Clock::now();
    MPIX_Request req[2];
    cudaStream_t s0 = 0;
    if (rank == 0) {
      MPIX_Isend_enqueue(sbuf.data(), (int)msg, MPI_BYTE, peer, 1,
                         MPI_COMM_WORLD, &req[0], MPIX_QUEUE_XLA_STREAM,
                         &s0);
      MPIX_Irecv_enqueue(rbuf.data(), (int)msg, MPI_BYTE, peer, 1,
                         MPI_COMM_WORLD, &req[1], MPIX_QUEUE_XLA_STREAM,
                         &s0);
    } else {
      MPIX_Irecv_enqueue(rbuf.data(), (int)msg, MPI_BYTE, peer, 1,
                         MPI_COMM_WORLD, &req[1], MPIX_QUEUE_XLA_STREAM,
                         &s0);
      MPIX_Isend_enqueue(sbuf.data(), (int)msg, MPI_BYTE, peer, 1,
                         MPI_COMM_WORLD, &req[0], MPIX_QUEUE_XLA_STREAM,
                         &s0);
    }
    MPIX_Wait(&req[1], MPI_STATUS_IGNORE);
    MPIX_Wait(&req[0], MPI_STATUS_IGNORE);
    if (it >= 0 && rank == 0) lat.push_back(us_since(t0) / 2.0);
  }

  // Partitioned bandwidth: 64 MiB, 16 partitions, 20 rounds.
  const int parts = 16;
  const size_t total = 64u << 20;
  std::vector<char> pbuf(total, 3);
  MPIX_Request preq;
  double gbps = 0;
  {
    if (rank == 0)
      MPIX_Psend_init(pbuf.data(), parts, (MPI_Count)(total / parts),
                      MPI_BYTE, peer, 7, MPI_COMM_WORLD, MPI_INFO_NULL,
                      &preq);
    else
      MPIX_Precv_init(pbuf.data(), parts, (MPI_Count)(total / parts),
                      MPI_BYTE, peer, 7, MPI_COMM_WORLD, MPI_INFO_NULL,
                      &preq);
    // Best of 3 sets x 20 rounds: the first set absorbs cold page faults
    // on the shm rings and destination buffer; report steady-state BW.
    const int rounds = 20, sets = 3;
    for (int set = 0; set < sets; set++) {
      MPI_Barrier(MPI_COMM_WORLD);
      auto t0 = Clock::now();
      for (int r = 0; r < rounds; r++) {
        MPIX_Start(&preq);
        if (rank == 0) {
          for (int p = parts - 1; p >= 0; p--) MPIX_Pready(p, &preq);
        }
        MPIX_Wait(&preq, MPI_STATUS_IGNORE);
      }
      MPI_Barrier(MPI_COMM_WORLD);
      double secs = us_since(t0) / 1e6;
      gbps = std::max(gbps, (double)total * rounds / secs / 1e9);
    }
    MPIX_Request_free(&preq);
  }

  if (rank == 0) {
    std::sort(lat.begin(), lat.end());
    std::printf("BENCH pingpong_p50_us=%.3f pingpong_p99_us=%.3f "
                "part_bw_gbps=%.3f iters=%d msg_bytes=%zu\n",
                lat[lat.size() / 2], lat[(size_t)(lat.size() * 0.99)], gbps,
                iters, msg);
  }

  // Striped-bandwidth sweep (ACX_BENCH_STRIPE_SWEEP=1, DESIGN.md §15):
  // one-way windowed stream per message size, receiver preposted so every
  // striped message takes the direct zero-copy delivery path. ACX_STRIPES
  // is fixed at transport construction, so one process measures ONE lane
  // count; the harness (tools/bench.py) sweeps lane counts across runs and
  // pairs the rows. Run with ACX_RV_THRESHOLD=0 so large messages take the
  // eager (striping) path rather than rendezvous.
  if (getenv("ACX_BENCH_STRIPE_SWEEP") != nullptr) {
    const char* stripes_s = getenv("ACX_STRIPES");
    const size_t sizes[] = {256u << 10, 1u << 20, 4u << 20};
    for (size_t mb : sizes) {
      const int win = 16;                       // messages in flight
      const int rounds = (int)((96u << 20) / (mb * win)) + 1;
      std::vector<char> sb(mb, 5), rb(mb, 0);
      double best = 0;
      for (int set = 0; set < 3; set++) {       // best-of-3, cold set absorbed
        MPI_Barrier(MPI_COMM_WORLD);
        auto t0 = Clock::now();
        for (int r = 0; r < rounds; r++) {
          MPIX_Request req[16];
          cudaStream_t s0 = 0;
          for (int w = 0; w < win; w++) {
            if (rank == 0)
              MPIX_Isend_enqueue(sb.data(), (int)mb, MPI_BYTE, peer, 20 + w,
                                 MPI_COMM_WORLD, &req[w],
                                 MPIX_QUEUE_XLA_STREAM, &s0);
            else
              MPIX_Irecv_enqueue(rb.data(), (int)mb, MPI_BYTE, peer, 20 + w,
                                 MPI_COMM_WORLD, &req[w],
                                 MPIX_QUEUE_XLA_STREAM, &s0);
          }
          for (int w = 0; w < win; w++)
            MPIX_Wait(&req[w], MPI_STATUS_IGNORE);
        }
        MPI_Barrier(MPI_COMM_WORLD);
        const double secs = us_since(t0) / 1e6;
        const double bw = (double)mb * win * rounds / secs / 1e9;
        best = std::max(best, bw);
      }
      if (rank == 0)
        std::printf("BENCH_STRIPE stripes=%s msg_bytes=%zu bw_gbps=%.3f\n",
                    stripes_s != nullptr ? stripes_s : "1", mb, best);
    }
  }

  MPIX_Finalize();
  MPI_Finalize();
  return 0;
}
