#!/usr/bin/env python3
"""acx_top — live fleet console for the tpu-acx telemetry plane.

Tails the per-rank time-series files a run writes under
``ACX_TSERIES=<prefix>`` (``<prefix>.rank<r>.tseries.jsonl``, one
delta-encoded JSON sample per line — docs/DESIGN.md §13) and renders a
fleet table: per rank, the fleet epoch, op and byte rates over the most
recent sample interval, goodput vs on-wire MB/s from the per-link wire
scope, proxy utilization, per-frame wire latency (txq = send-side
queueing enqueue->on-wire, rxt = raw one-way transit off the sender's
in-header tx stamp — uncorrected for cross-process clock offset; the
skew-corrected figure is tools/acx_critpath.py's job), live serving
SLOs (rolling p99 TTFT, queue depth — published by the serving loop via
acx_tseries_annotate), and link health.

Modes:
  acx_top.py <prefix>                 live console, refreshed every
                                      --interval seconds (default 1.0)
  acx_top.py --once <prefix>          render one table and exit
  acx_top.py --once --json <prefix>   machine-readable snapshot for CI
  acx_top.py --once --json --check <prefix>
                                      additionally assert series sanity
                                      (>= 2 samples/rank, monotone
                                      clocks, wire >= payload per link)
                                      and exit nonzero on violation
  acx_top.py --prom <prefix>          one-shot Prometheus text
                                      exposition of the newest reading
  acx_top.py --prom-port 9100 <prefix>
                                      serve it at :9100/metrics,
                                      re-read per scrape

The reader tolerates a torn final line (a rank mid-write or killed
mid-sample): any line that fails to parse is skipped. Everything here is
stdlib-only — the tool must run on a bare operator box.
"""

import argparse
import glob
import json
import os
import re
import sys
import time

LINK_STATE = {0: "ok", 1: "rec", 2: "dead"}


def load_series(path):
    """Parse one .tseries.jsonl file into a reconstructed series.

    Returns a dict with the rank, the raw samples, and per-sample
    reconstructed cumulative counters (init line carries absolutes, later
    lines carry deltas for counters and absolutes for gauges/links).
    Undecodable lines — the torn tail of a crashed or mid-write rank —
    are counted, not fatal.
    """
    samples = []
    torn = 0
    with open(path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                samples.append(json.loads(line))
            except (json.JSONDecodeError, ValueError):
                torn += 1
    rank = None
    interval_ms = None
    running = {}
    recon = []
    for s in samples:
        if s.get("init"):
            rank = s.get("rank", rank)
            interval_ms = s.get("interval_ms", interval_ms)
            running = dict(s.get("counters", {}))
        else:
            for k, v in s.get("d", {}).items():
                running[k] = running.get(k, 0) + v
            for k, v in s.get("g", {}).items():
                running[k] = v
        recon.append(dict(running))
    if rank is None:
        m = re.search(r"\.rank(\d+)\.tseries\.jsonl$", path)
        rank = int(m.group(1)) if m else -1
    return {
        "path": path,
        "rank": rank,
        "interval_ms": interval_ms,
        "samples": samples,
        "counters": recon,
        "torn_lines": torn,
    }


def _latest(series, key, default=None):
    for s in reversed(series["samples"]):
        if key in s:
            return s[key]
    return default


def _link_totals(sample):
    """Sum cumulative link counters across peers for one sample."""
    tot = {"tx_pb": 0, "tx_wb": 0, "rx_pb": 0, "rx_wb": 0,
           "txq_ns": 0, "txq_fr": 0, "rxt_ns": 0, "rxt_fr": 0}
    for ln in sample.get("links", []):
        for k in tot:
            tot[k] += ln.get(k, 0)
    return tot


def summarize(series):
    """Per-rank summary row: rates over the last sample interval, live
    SLOs from the newest "app" fragment, link health from the newest
    links section."""
    samples = series["samples"]
    counters = series["counters"]
    row = {
        "rank": series["rank"],
        "samples": len(samples),
        "torn_lines": series["torn_lines"],
        "fleet_epoch": _latest(series, "epoch", 0),
        "ops_per_s": 0.0,
        "goodput_mbps": 0.0,
        "wire_mbps": 0.0,
        "proxy_util_pct": _latest(series, "proxy_util_pct", 0.0),
        "txq_us": None,
        "rxt_us": None,
        "queue_depth": None,
        "ttft_p99_s": None,
        "itl_p99_s": None,
        "rejections": None,
        "rejects": None,
        "preemptions": None,
        "resumes": None,
        "link_health": "-",
        "subflows": "-",
        "part_inflight": None,
        "pages_free": None,
        "pages_shared": None,
    }
    # Paged-KV pool occupancy (serving layer, DESIGN.md §19): gauges, so
    # the newest reconstructed absolutes are the live reading. A pure
    # transport rank reports 0/0 — its registry entries never move.
    if counters:
        row["pages_free"] = counters[-1].get("pages_free")
        row["pages_shared"] = counters[-1].get("pages_shared")
    if len(samples) >= 2:
        a, b = samples[-2], samples[-1]
        dt = (b.get("t_mono_ns", 0) - a.get("t_mono_ns", 0)) / 1e9
        if dt > 0:
            ca, cb = counters[-2], counters[-1]
            d_ops = cb.get("ops_completed", 0) - ca.get("ops_completed", 0)
            row["ops_per_s"] = d_ops / dt
        # Link sections are cumulative absolutes: rates come from
        # differencing the two newest samples that CARRY a links section
        # (the post-finalize tail sample has none — the transport is
        # detached by then — and must not zero the rate).
        with_links = [s for s in samples if s.get("links")]
        if len(with_links) >= 2:
            a, b = with_links[-2], with_links[-1]
            ldt = (b.get("t_mono_ns", 0) - a.get("t_mono_ns", 0)) / 1e9
            if ldt > 0:
                la, lb = _link_totals(a), _link_totals(b)
                good = (lb["tx_pb"] - la["tx_pb"]) + (lb["rx_pb"] - la["rx_pb"])
                wire = (lb["tx_wb"] - la["tx_wb"]) + (lb["rx_wb"] - la["rx_wb"])
                row["goodput_mbps"] = good / ldt / 1e6
                row["wire_mbps"] = wire / ldt / 1e6
                # Per-frame wire latency over the same window: send-side
                # queueing (enqueue -> fully on the wire) and raw one-way
                # transit off the sender's tx stamp (cross-process clock
                # delta included — see docs/DESIGN.md §14; the offline
                # skew-corrected figure lives in acx_critpath.py).
                dq_fr = lb["txq_fr"] - la["txq_fr"]
                dt_fr = lb["rxt_fr"] - la["rxt_fr"]
                if dq_fr > 0:
                    row["txq_us"] = (lb["txq_ns"] - la["txq_ns"]) \
                        / dq_fr / 1e3
                if dt_fr > 0:
                    row["rxt_us"] = (lb["rxt_ns"] - la["rxt_ns"]) \
                        / dt_fr / 1e3
    app = _latest(series, "app")
    if isinstance(app, dict):
        row["queue_depth"] = app.get("queue_depth")
        row["ttft_p99_s"] = app.get("ttft_p99_s")
        row["itl_p99_s"] = app.get("itl_p99_s")
        # Admission-health breakdown (DESIGN.md §20): cumulative typed
        # rejection counts plus page-pressure preempt/resume churn, so
        # "why is goodput down" is answerable from the console — shed
        # load and thrashing seats both live here, not in the op plane.
        row["rejections"] = app.get("rejections")
        row["rejects"] = app.get("rejects")
        row["preemptions"] = app.get("preemptions")
        row["resumes"] = app.get("resumes")
    # Newest non-empty links section (the tail sample's is empty).
    links = next((s["links"] for s in reversed(samples) if s.get("links")),
                 None)
    if links:
        worst = max(ln.get("state", 0) for ln in links)
        row["link_health"] = LINK_STATE.get(worst, "?")
        # Striping lanes (DESIGN.md §15): show the worst-off link's
        # up/configured subflow counts — "3/4" flags a degraded lane at a
        # glance. Absolutes, not rates, so the newest section suffices.
        ratios = [(ln.get("sf_up", 1), ln.get("sf", 1)) for ln in links]
        up, total = min(ratios, key=lambda r: (r[0] / max(r[1], 1), r[0]))
        row["subflows"] = f"{up}/{total}"
        # Partitions in flight across this rank's links — a GAUGE (absolute
        # per sample, from the newest links section), so a handoff that
        # stalls mid-round shows as a pinned nonzero value here while the
        # cumulative preadys/parriveds counters stop moving.
        row["part_inflight"] = sum(ln.get("pif", 0) for ln in links)
    elif _latest(series, "links") == []:
        row["link_health"] = "none"
    return row


def check_series(series):
    """CI assertions over one rank's series. Returns a list of violation
    strings (empty = healthy)."""
    errs = []
    samples = series["samples"]
    r = series["rank"]
    if len(samples) < 2:
        errs.append(f"rank {r}: only {len(samples)} sample(s), need >= 2")
        return errs
    prev = -1
    for i, s in enumerate(samples):
        t = s.get("t_mono_ns")
        if t is None:
            errs.append(f"rank {r}: sample {i} missing t_mono_ns")
            continue
        if t <= prev:
            errs.append(
                f"rank {r}: t_mono_ns not monotone at sample {i} "
                f"({t} <= {prev})")
        prev = t
    # Per-link byte accounting: wire >= payload in every direction, and
    # cumulative counters never go backwards for a (peer, epoch) pair
    # (an epoch bump means a reconnect, counters still persist).
    last = {}
    for i, s in enumerate(samples):
        for ln in s.get("links", []):
            peer = ln.get("peer")
            if ln.get("tx_wb", 0) < ln.get("tx_pb", 0):
                errs.append(
                    f"rank {r}: sample {i} peer {peer}: tx wire bytes "
                    f"{ln.get('tx_wb')} < payload {ln.get('tx_pb')}")
            if ln.get("rx_wb", 0) < ln.get("rx_pb", 0):
                errs.append(
                    f"rank {r}: sample {i} peer {peer}: rx wire bytes "
                    f"{ln.get('rx_wb')} < payload {ln.get('rx_pb')}")
            for k in ("tx_pb", "tx_wb", "rx_pb", "rx_wb", "tx_fr",
                      "rx_fr", "naks", "crc", "replayed",
                      "txq_ns", "txq_fr", "rxt_ns", "rxt_fr"):
                v = ln.get(k, 0)
                if v < last.get((peer, k), 0):
                    errs.append(
                        f"rank {r}: sample {i} peer {peer}: {k} went "
                        f"backwards ({v} < {last[(peer, k)]})")
                last[(peer, k)] = v
    return errs


def collect(prefix):
    paths = sorted(glob.glob(glob.escape(prefix) + ".rank*.tseries.jsonl"))
    return [load_series(p) for p in paths]


def _fmt(v, spec, dash="-"):
    return dash if v is None else format(v, spec)


def render_table(all_series):
    rows = [summarize(s) for s in all_series]
    rows.sort(key=lambda r: r["rank"])
    hdr = (f"{'rank':>4} {'epoch':>5} {'smpls':>5} {'ops/s':>9} "
           f"{'good MB/s':>9} {'wire MB/s':>9} {'proxy%':>6} "
           f"{'txq µs':>7} {'rxt µs':>7} "
           f"{'qdepth':>6} {'p99 TTFT':>9} {'rej':>4} {'pre':>4} "
           f"{'pif':>4} {'pages':>9} "
           f"{'link':>5} {'sf':>5}")
    lines = [hdr, "-" * len(hdr)]
    rej_detail = []
    for r in rows:
        ttft = (_fmt(r["ttft_p99_s"], ".3f") + "s"
                if r["ttft_p99_s"] is not None else "-")
        # free/shared page counts from the paged-KV pool gauges.
        pages = ("-" if r["pages_free"] is None
                 else f"{r['pages_free']}/{r['pages_shared'] or 0}")
        lines.append(
            f"{r['rank']:>4} {r['fleet_epoch']:>5} {r['samples']:>5} "
            f"{r['ops_per_s']:>9.1f} {r['goodput_mbps']:>9.2f} "
            f"{r['wire_mbps']:>9.2f} {r['proxy_util_pct']:>6.1f} "
            f"{_fmt(r['txq_us'], '.1f'):>7} {_fmt(r['rxt_us'], '.1f'):>7} "
            f"{_fmt(r['queue_depth'], 'd'):>6} {ttft:>9} "
            f"{_fmt(r['rejections'], 'd'):>4} "
            f"{_fmt(r['preemptions'], 'd'):>4} "
            f"{_fmt(r['part_inflight'], 'd'):>4} {pages:>9} "
            f"{r['link_health']:>5} {r['subflows']:>5}")
        if r["rejects"]:
            detail = " ".join(f"{k}={v}"
                              for k, v in sorted(r["rejects"].items()))
            rej_detail.append(f"  rank {r['rank']} rejects: {detail}"
                              + (f"  resumes={r['resumes']}"
                                 if r["resumes"] else ""))
    # Per-reason rejection breakdown under the table — the serving
    # loop's typed admission reasons, not a bare count.
    lines.extend(rej_detail)
    if not rows:
        lines.append("  (no .tseries.jsonl files yet)")
    return "\n".join(lines)


# Registry names that are level readings, not cumulative counts — must
# match metrics::IsGauge in src/core/metrics.cc so both Prometheus
# surfaces (this file-plane bridge and the native acx_metrics_prom
# export) agree on instrument types.
PROM_GAUGES = {"fleet_epoch", "slot_hwm", "pages_free", "pages_shared"}


def render_prom(all_series):
    """Prometheus text exposition (0.0.4) of the newest per-rank
    reading, rank-labelled. This is the file-plane bridge for fleets
    scraped from an operator box: the authoritative in-process export
    is ``acx_metrics_prom`` / ``Runtime.metrics_prom()`` — same names,
    same types, so dashboards work against either."""
    by_name = {}
    for s in all_series:
        if s["counters"]:
            for k, v in s["counters"][-1].items():
                by_name.setdefault(k, {})[s["rank"]] = v
    lines = []
    for k in sorted(by_name):
        kind = "gauge" if k in PROM_GAUGES else "counter"
        lines.append(f"# TYPE acx_{k} {kind}")
        for r in sorted(by_name[k]):
            lines.append(f'acx_{k}{{rank="{r}"}} {by_name[k][r]}')
    # Serving-layer SLO fragment as derived gauges/counters.
    app_num = [("queue_depth", "gauge"), ("ttft_p99_s", "gauge"),
               ("itl_p99_s", "gauge"), ("rejections", "counter"),
               ("preemptions", "counter"), ("resumes", "counter")]
    rows = [(s["rank"], _latest(s, "app")) for s in all_series]
    rows = [(r, a) for r, a in rows if isinstance(a, dict)]
    for key, kind in app_num:
        vals = [(r, a.get(key)) for r, a in rows if a.get(key) is not None]
        if not vals:
            continue
        lines.append(f"# TYPE acx_app_{key} {kind}")
        for r, v in vals:
            lines.append(f'acx_app_{key}{{rank="{r}"}} {v}')
    rej = [(r, a["rejects"]) for r, a in rows
           if isinstance(a.get("rejects"), dict) and a["rejects"]]
    if rej:
        lines.append("# TYPE acx_app_rejects counter")
        for r, d in rej:
            for reason, v in sorted(d.items()):
                lines.append(f'acx_app_rejects{{rank="{r}",'
                             f'reason="{reason}"}} {v}')
    return "\n".join(lines) + "\n"


def serve_prom(prefix, port):
    """Tiny stdlib scrape endpoint: GET /metrics re-reads the tseries
    files per scrape, so a Prometheus server pointed here follows a
    live fleet with no sidecar beyond this script."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            body = render_prom(collect(prefix)).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet: this is a console tool
            pass

    srv = http.server.HTTPServer(("", port), Handler)
    print(f"acx_top: serving Prometheus metrics on :{port}/metrics",
          file=sys.stderr)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Live fleet console over ACX_TSERIES telemetry files.")
    ap.add_argument("prefix",
                    help="the ACX_TSERIES prefix the run was started with")
    ap.add_argument("--once", action="store_true",
                    help="render a single snapshot and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the snapshot as JSON (implies --once)")
    ap.add_argument("--check", action="store_true",
                    help="run CI series assertions; nonzero exit on failure")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="live-mode refresh period in seconds (default 1.0)")
    ap.add_argument("--prom", action="store_true",
                    help="emit a Prometheus text exposition of the "
                         "newest reading and exit (one-shot)")
    ap.add_argument("--prom-port", type=int, metavar="PORT",
                    help="serve the exposition at :PORT/metrics, "
                         "re-reading the files per scrape")
    args = ap.parse_args(argv)

    if args.prom_port:
        return serve_prom(args.prefix, args.prom_port)
    if args.prom:
        sys.stdout.write(render_prom(collect(args.prefix)))
        return 0

    if args.as_json or args.check:
        args.once = True

    if not args.once:
        try:
            while True:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                print(f"acx_top — {args.prefix}  "
                      f"({time.strftime('%H:%M:%S')})")
                print(render_table(collect(args.prefix)))
                sys.stdout.flush()
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    all_series = collect(args.prefix)
    violations = []
    if args.check:
        if not all_series:
            violations.append(
                f"no {args.prefix}.rank*.tseries.jsonl files found")
        for s in all_series:
            violations.extend(check_series(s))

    if args.as_json:
        out = {
            "prefix": args.prefix,
            "ranks": sorted((summarize(s) for s in all_series),
                            key=lambda r: r["rank"]),
        }
        if args.check:
            out["check"] = {"ok": not violations, "violations": violations}
        json.dump(out, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        print(render_table(all_series))
        for v in violations:
            print(f"CHECK FAIL: {v}", file=sys.stderr)

    if args.check and violations:
        if args.as_json:
            for v in violations:
                print(f"CHECK FAIL: {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
