#!/usr/bin/env python3
"""Trace-backed enqueue-latency budget (VERDICT r03 item 9).

Runs the enqueued ping-pong under ACX_TRACE (nanosecond event clock)
and attributes rank 0's op latency segment by segment, separately for
the send op and the recv op (anchored at trigger_fired = the flag going
PENDING, the reference's device-write instant):

    trigger_fired -> i{send,recv}_issued   proxy pickup of PENDING
    issued        -> op_completed          wire + peer + completion poll
    op_completed  -> wait_observed         waiter pickup of COMPLETED

The SEND op's completed->wait segment absorbs the whole round trip
(the app waits on its recv first); the RECV op's completed->wait is the
true waiter-pickup cost. A future p50 move can thus be pinned to a
segment (code) or seen as uniform inflation (host weather). Tracing
itself costs ~0.1-0.2 us per event (mutexed ns clock), so the traced
totals read above the untraced bench_pingpong p50 — compare SHAPES,
not absolutes, across runs.

When the metrics plane is available the budget is read straight from the
native histogram registry (ACX_METRICS, src/core/metrics.cc): per-segment
p50/p90 derived from the power-of-two latency buckets, with no tracing
mutex on the hot path ("source": "metrics"). The trace-stitched
send/recv breakdown below rides along either way; if the metrics file is
missing the stitching is the only source ("source": "trace").

Usage: python tools/latency_budget.py [--msg-bytes N]  (builds if needed)
Prints one JSON line with per-segment p50/p90 in microseconds.
"""

import argparse
import json
import math
import os
import statistics
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def hist_pct_us(hist, p):
    """Nearest-rank percentile from a power-of-two bucket histogram
    (bucket 0 = exactly 0 ns, bucket i = [2^(i-1), 2^i) ns), reported at
    the bucket midpoint in µs."""
    target = max(1, math.ceil(p * hist["count"]))
    cum = 0
    for i, n in enumerate(hist["buckets"]):
        cum += n
        if cum >= target:
            ns = 0 if i == 0 else (2 ** (i - 1) + 2 ** i) / 2
            return round(ns / 1000.0, 3)
    return 0.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--msg-bytes", type=int, default=8)
    args = ap.parse_args()

    subprocess.run(["make", "-C", REPO, "itest", "tools"], check=True,
                   capture_output=True)
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ)
        env["ACX_TRACE"] = os.path.join(td, "lb")
        env["ACX_TRACE_CAP"] = "2000000"
        env["ACX_METRICS"] = os.path.join(td, "lb")
        r = subprocess.run(
            [os.path.join(REPO, "build", "acxrun"), "-np", "2",
             "-timeout", "300",
             os.path.join(REPO, "build", "bench_pingpong"),
             str(args.msg_bytes)],
            env=env, capture_output=True, text=True, timeout=400)
        if r.returncode != 0:
            sys.exit(f"bench_pingpong failed: {r.stdout} {r.stderr}")
        bench_line = next((l for l in r.stdout.splitlines()
                           if l.startswith("BENCH")), "")
        d = json.loads(
            open(os.path.join(td, "lb.rank0.trace.json")).read())
        hists = None
        mpath = os.path.join(td, "lb.rank0.metrics.json")
        if os.path.exists(mpath):
            hists = json.loads(open(mpath).read()).get("histograms")

    # Stitch per-op lifecycles: events for one op share a slot (tid) and
    # the slot is reused only after slot_reclaimed, so one pass with a
    # per-slot open dict reconstructs each lifecycle. The API-exit
    # "isend_enqueue" log point lands AFTER the inline host-queue
    # trigger, so the budget anchors on trigger_fired (= the moment the
    # flag goes PENDING — the reference's device-write instant).
    # Two budgets. The SEND op's completed->wait segment absorbs the
    # whole round trip (rank 0 waits on its recv first), so its useful
    # segments are proxy pickup and wire issue. The RECV op is the one
    # the app actively spins on, so its completed->wait is the true
    # waiter-pickup cost, and its issued->completed is peer + wire.
    KINDS = {"send": ["trigger_fired", "isend_issued", "op_completed",
                      "wait_observed"],
             "recv": ["trigger_fired", "irecv_issued", "op_completed",
                      "wait_observed"]}
    names = {n for seg in KINDS.values() for n in seg}
    open_ops = {}
    ops = {"send": [], "recv": []}
    for e in d["traceEvents"]:
        name, slot, ts = e["name"], e["tid"], float(e["ts"])
        if name == "slot_reclaimed":
            op = open_ops.pop(slot, None)
            if op is None:
                continue
            for kind, seg in KINDS.items():
                if all(s in op for s in seg):
                    ops[kind].append(op)
        elif name in names:
            open_ops.setdefault(slot, {})[name] = ts

    if not ops["send"] or not ops["recv"]:
        sys.exit("no complete lifecycles found in trace")

    def stats(v):
        v = sorted(v)
        return {"p50_us": round(statistics.median(v), 3),
                "p90_us": round(v[int(0.9 * len(v))], 3)}

    out = {"bench_line": bench_line}
    # Histogram-derived budget (no trace mutex in these numbers): the
    # registry's segments pool send+recv ops, so this is the fleet-wide
    # shape; the stitched send/recv breakdown below separates the kinds.
    if hists:
        out["source"] = "metrics"
        for seg in ("trigger_to_issue_ns", "issue_to_complete_ns",
                    "complete_to_wait_ns"):
            h = hists.get(seg)
            if h and h["count"] > 0:
                out[f"hist:{seg[:-3]}"] = {
                    "n": h["count"],
                    "p50_us": hist_pct_us(h, 0.50),
                    "p90_us": hist_pct_us(h, 0.90),
                }
    else:
        out["source"] = "trace"
    for kind, seg in KINDS.items():
        kops = ops[kind][20:] or ops[kind]   # drop cold-start
        out[f"n_{kind}"] = len(kops)
        for a, b in zip(seg, seg[1:]):
            out[f"{kind}:{a}->{b}"] = stats([op[b] - op[a] for op in kops])
        out[f"{kind}:total"] = stats(
            [op["wait_observed"] - op["trigger_fired"] for op in kops])
    print(json.dumps(out))


if __name__ == "__main__":
    main()
