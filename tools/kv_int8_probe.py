"""Rank int8-KV decode-attention variants on the live chip.

BENCH_BANK r05 showed the naive int8-KV path at 0.73x the bf16
baseline (decode_longctx_int8kv_speedup): the kv_dequant of the full
[B, S, H, D] cache slice materializes a bf16 tensor in HBM before the
attention einsums, so the step pays int8-read + bf16-write + bf16-read
— MORE traffic than the bf16 cache it was meant to halve.

This probe times one decode-attention step (single layer, full-cache
attend, the bandwidth-bound regime) for four variants:

  bf16      — plain bf16 cache (the baseline the int8 path must beat)
  dequant   — current ops/kvquant.py path: dequantize, then einsum
  scaleskv  — int8 codes are the einsum operands; the per-(pos, head)
              scales are applied to the SMALL tensors (scores and
              probabilities), so no [B, S, H, D] dequant tensor ever
              exists: scores = (q @ Kq^T) * sK ; out = (p * sV) @ Vq
  int8mxu   — additionally quantize q per (B, H) vector and use a
              native int8 x int8 -> int32 dot for the score matmul

Usage: python tools/kv_int8_probe.py [S] (default 4096)
Prints one JSON line per variant: {"variant", "ms", "x_vs_bf16"}.
"""

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

B, H, D = 8, 12, 64


def attend_bf16(q, kc, vc, sk, sv, mask):
    # q [B,H,D]; kc/vc [B,S,H,D]; mask [S]
    scores = jnp.einsum("bhd,bshd->bhs", q, kc) / (D ** 0.5)
    scores = jnp.where(mask[None, None, :], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p, vc)


def attend_dequant(q, kc, vc, sk, sv, mask):
    k = (kc.astype(jnp.float32) * sk).astype(q.dtype)
    v = (vc.astype(jnp.float32) * sv).astype(q.dtype)
    return attend_bf16(q, k, v, None, None, mask)


def attend_scaleskv(q, kc, vc, sk, sv, mask):
    # scores_ij = sum_d q_d * Kq_sd * sK_s  ->  (q @ Kq) * sK
    scores = jnp.einsum("bhd,bshd->bhs", q, kc.astype(q.dtype))
    scores = scores * sk[..., 0].transpose(0, 2, 1) / (D ** 0.5)
    scores = jnp.where(mask[None, None, :], scores, -1e30)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    # out_d = sum_s p_s * sV_s * Vq_sd  ->  (p * sV) @ Vq
    pv = (p * sv[..., 0].transpose(0, 2, 1)).astype(q.dtype)
    return jnp.einsum("bhs,bshd->bhd", pv, vc.astype(q.dtype))


def attend_int8mxu(q, kc, vc, sk, sv, mask):
    aq = jnp.max(jnp.abs(q.astype(jnp.float32)), axis=-1, keepdims=True)
    sq = jnp.maximum(aq, 1e-12) / 127.0
    qq = jnp.clip(jnp.round(q.astype(jnp.float32) / sq),
                  -127, 127).astype(jnp.int8)
    scores = lax.dot_general(
        qq, kc, (((2,), (3,)), ((0, 1), (0, 2))),
        preferred_element_type=jnp.int32)  # [B,H,S] int32
    scores = (scores.astype(jnp.float32) * sq
              * sk[..., 0].transpose(0, 2, 1)) / (D ** 0.5)
    scores = jnp.where(mask[None, None, :], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    pv = (p * sv[..., 0].transpose(0, 2, 1)).astype(jnp.bfloat16)
    return jnp.einsum("bhs,bshd->bhd", pv, vc.astype(jnp.bfloat16))


def main():
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    key = jax.random.key(0)
    kf = jax.random.normal(key, (B, S, H, D), jnp.float32)
    vf = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.float32)
    q = jax.random.normal(jax.random.key(2), (B, H, D)).astype(jnp.bfloat16)
    mask = jnp.ones((S,), bool)

    a = jnp.max(jnp.abs(kf), axis=-1, keepdims=True)
    sk = jnp.maximum(a, 1e-12) / 127.0
    kq = jnp.clip(jnp.round(kf / sk), -127, 127).astype(jnp.int8)
    a = jnp.max(jnp.abs(vf), axis=-1, keepdims=True)
    sv = jnp.maximum(a, 1e-12) / 127.0
    vq = jnp.clip(jnp.round(vf / sv), -127, 127).astype(jnp.int8)
    kb, vb = kf.astype(jnp.bfloat16), vf.astype(jnp.bfloat16)

    reps = 50
    variants = {
        "bf16": (attend_bf16, kb, vb, None, None),
        "dequant": (attend_dequant, kq, vq, sk, sv),
        "scaleskv": (attend_scaleskv, kq, vq, sk, sv),
        "int8mxu": (attend_int8mxu, kq, vq, sk, sv),
    }
    ref = None
    base = None
    for name, (fn, kc, vc, s1, s2) in variants.items():
        @jax.jit
        def loop(q, kc, vc, s1, s2, fn=fn):
            def body(c, _):
                o = fn(c, kc, vc, s1, s2, mask)
                return (q + 0.001 * o.astype(q.dtype)), o
            c, os_ = lax.scan(body, q, None, length=reps)
            return c, os_[-1]

        c, out = jax.block_until_ready(loop(q, kc, vc, s1, s2))
        t0 = time.perf_counter()
        c, out = jax.block_until_ready(loop(q, kc, vc, s1, s2))
        ms = (time.perf_counter() - t0) / reps * 1e3
        if ref is None:
            ref, base = out.astype(jnp.float32), ms
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
        print(json.dumps({"variant": name, "ms": round(ms, 3),
                          "x_vs_bf16": round(base / ms, 2),
                          "max_err_vs_bf16": round(err, 4)}))


if __name__ == "__main__":
    main()
