#!/usr/bin/env python3
"""Chaos conductor: run fault schedules under ``acxrun -chaos`` and audit
the artifact trail against cross-rank invariants (docs/DESIGN.md §16).

A chaos run is only as good as its verdict. The workload (typically
``itests/chaos-conductor``) already byte-checks every payload; this tool
holds the run to the invariants the payload check alone cannot see:

  workload_exit     the job exited 0 — byte-exactness is the workload's
                    own closed-form check, so nonzero means data loss,
                    duplication, corruption, or a wedged heal
  fault_accounting  every scheduled fault spec FIRED at least once. A
                    schedule that never triggers is a broken experiment,
                    not a passing one — silence is failure. Verified from
                    the per-rank fault reports (<ACX_FAULT_REPORT>.rank<r>
                    .fault.json, per-spec matched/fired counters); `kill`
                    specs are verified from the supervisor's respawn
                    ledger instead (a SIGKILLed rank writes no report —
                    the ledger line IS the evidence it died)
  epoch_monotone    the fleet epoch never moves backwards in any rank's
                    tseries stream, and a run that killed a rank shows
                    the epoch climbing (death + rejoin = at least two
                    bumps over the seed value of 1)
  seq_spaces        per-(peer, lane) rx_frame sequence numbers in the
                    flight dumps are strictly increasing between recovery
                    boundaries — a duplicate or regressed seq outside a
                    NAK/reconnect/rejoin window means duplicate delivery
  doctor_verdict    tools/acx_doctor.py, fed the survivors' flight dumps,
                    names the killed rank as the culprit (dead_link /
                    missing_dump / peer_died)

On failure the schedule is shrunk with ddmin — subsets are re-run until a
minimal failing spec list remains — and the tool prints the exact replay
command (``ACX_FAULT='...' acxrun ... -chaos ...``) and writes it to
<out>/replay.txt, so "seed 1007 is broken" becomes a one-line repro.

Usage:
    python3 tools/acx_chaos.py run  --np 3 --fault 'kill:rank=1:nth=7' \
        [--chaos seed=7:faults=3:mix=issue,wire,kill] [--expect-fail] \
        [--no-shrink] [--out DIR] -- ./build/itests/chaos-conductor
    python3 tools/acx_chaos.py soak --np 3 --seeds 3 [--seed-base 1000] \
        [--faults 3] [--mix issue,wire] -- ./build/itests/chaos-conductor

Seed rotation: --seed-base defaults to $ACX_CHAOS_SEED_BASE (then 1000),
so a nightly job can sweep fresh schedules (e.g. base = day number) while
CI pins a fixed base for reproducibility. Every schedule a seed expands
to is printed, so any nightly failure is replayable by spec, not seed.

The audit functions are importable and pure (tests/test_chaos.py drives
them on synthetic artifacts without a build).
"""

import argparse
import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCTOR = os.path.join(REPO, "tools", "acx_doctor.py")

# Event kinds that legitimately reset a peer's rx seq floor: NAK-driven
# replay re-sends old seqs, and a reconnect / rejoin restarts the lane's
# id space from scratch (src/net/socket_transport.cc).
SEQ_BOUNDARIES = ("link_recovering", "link_up", "tx_nak", "rx_nak",
                  "peer_dead")

# Doctor anomalies that correctly attribute a killed rank.
KILL_ANOMALIES = ("dead_link", "missing_dump", "peer_died")


# ---- schedule parsing (mirror of fault.cc's grammar, audit subset) ----

def parse_spec(spec):
    """One spec string -> {action, rank, nth, count, raw}. Filters the
    audit does not route on are kept in `raw` only."""
    parts = spec.split(":")
    if not parts or not parts[0]:
        raise ValueError("empty spec in %r" % spec)
    out = {"action": parts[0], "rank": -1, "nth": 1, "count": 1,
           "raw": spec}
    for kv in parts[1:]:
        if "=" not in kv:
            raise ValueError("bad key=value %r in %r" % (kv, spec))
        k, v = kv.split("=", 1)
        if k in ("rank", "nth", "count"):
            out[k] = int(v)
    return out


def parse_schedule(sched):
    """';'-separated schedule -> list of spec dicts (order preserved)."""
    return [parse_spec(s) for s in sched.split(";") if s != ""]


# ---- artifact loaders -------------------------------------------------

def load_fault_reports(prefix):
    """All <prefix>[.i<k>].rank<r>.fault.json -> [{rank, incarnation,
    specs: [...]}, ...]."""
    reports = []
    for path in sorted(glob.glob(prefix + "*.fault.json")):
        m = re.search(r"(?:\.i(\d+))?\.rank(\d+)\.fault\.json$", path)
        if not m:
            continue
        with open(path) as f:
            d = json.load(f)
        d["incarnation"] = int(m.group(1)) if m.group(1) else 0
        d["rank"] = int(m.group(2))
        reports.append(d)
    return reports


def load_flight_dumps(prefix):
    dumps = []
    for path in sorted(glob.glob(prefix + "*.flight.json")):
        with open(path) as f:
            dumps.append((path, json.load(f)))
    return dumps


def load_tseries(prefix):
    """All <prefix>[.i<k>].rank<r>.tseries.jsonl -> {stream_name:
    [sample, ...]} (malformed trailing lines from a killed sampler are
    skipped)."""
    streams = {}
    for path in sorted(glob.glob(prefix + "*.tseries.jsonl")):
        samples = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    samples.append(json.loads(line))
                except ValueError:
                    continue  # torn final line of a SIGKILLed rank
        streams[os.path.basename(path)] = samples
    return streams


# ---- invariant audits (pure: artifacts in, failure strings out) -------

def audit_fault_accounting(schedule, reports, respawned_ranks):
    """Every scheduled spec fired >= once. Returns (failures, notes)."""
    failures, notes = [], []
    fired_by_rank = {}  # (rank, spec_index) -> fired total across incs
    for rep in reports:
        for i, s in enumerate(rep.get("specs", [])):
            key = (rep["rank"], i)
            fired_by_rank[key] = fired_by_rank.get(key, 0) + \
                int(s.get("fired", 0))
    for i, spec in enumerate(schedule):
        if spec["action"] == "kill":
            victims = respawned_ranks if spec["rank"] < 0 \
                else ([spec["rank"]] if spec["rank"] in respawned_ranks
                      else [])
            if not victims:
                failures.append(
                    "fault_accounting: spec %d %r scheduled a kill but "
                    "no respawn was recorded — the kill never fired"
                    % (i, spec["raw"]))
            continue
        if spec["rank"] >= 0 and spec["rank"] in respawned_ranks:
            # The victim's pre-kill incarnation writes no report (SIGKILL)
            # and its respawn runs fault-free; this spec is unverifiable.
            notes.append(
                "fault_accounting: spec %d %r targets killed rank %d; "
                "its report died with it (unverifiable, skipped)"
                % (i, spec["raw"], spec["rank"]))
            continue
        ranks = [spec["rank"]] if spec["rank"] >= 0 else \
            sorted({r["rank"] for r in reports})
        fired = sum(fired_by_rank.get((r, i), 0) for r in ranks)
        if fired == 0:
            failures.append(
                "fault_accounting: spec %d %r never fired (matched "
                "window never reached on rank %s) — a scheduled fault "
                "that does not happen is a failed experiment"
                % (i, spec["raw"],
                   spec["rank"] if spec["rank"] >= 0 else "any"))
    return failures, notes


def audit_epoch_monotone(streams, expect_kill):
    """Fleet epoch never regresses per stream; climbs past 2 on a kill
    run (1 seed + death + join)."""
    failures = []
    peak = 0
    for name, samples in streams.items():
        last = 0
        for s in samples:
            e = int(s.get("epoch", 0))
            if e < last:
                failures.append(
                    "epoch_monotone: %s: fleet epoch regressed %d -> %d"
                    % (name, last, e))
                break
            last = e
            peak = max(peak, e)
    if expect_kill and streams and peak < 3:
        failures.append(
            "epoch_monotone: a rank was killed and respawned but no "
            "stream's fleet epoch climbed past %d (want >= 3: death + "
            "rejoin over the seed epoch of 1)" % peak)
    return failures


def audit_seq_spaces(dumps):
    """rx_frame seqs strictly increase per (peer, lane) between recovery
    boundaries: a repeat or regression elsewhere is duplicate delivery."""
    failures = []
    for path, d in dumps:
        floor = {}  # (peer, lane) -> last seq seen since boundary
        for e in d.get("events", []):
            kind = e.get("kind")
            peer = e.get("peer")
            if kind in SEQ_BOUNDARIES:
                for key in [k for k in floor if k[0] == peer]:
                    del floor[key]
                continue
            if kind != "rx_frame":
                continue
            key = (peer, e.get("aux", 0))
            seq = int(e.get("seq", 0))
            if key in floor and seq <= floor[key]:
                failures.append(
                    "seq_spaces: %s: rx_frame from peer %s lane %s seq "
                    "%d after %d with no recovery boundary — duplicate "
                    "or regressed delivery"
                    % (os.path.basename(path), key[0], key[1], seq,
                       floor[key]))
                break
            floor[key] = seq
    return failures


def audit_doctor(flight_prefix, victims):
    """acx_doctor must attribute the kill to the victim rank."""
    paths = sorted(glob.glob(flight_prefix + "*.flight.json"))
    if not paths:
        return ["doctor_verdict: a rank was killed but no survivor "
                "wrote a flight dump — no evidence trail to audit"]
    proc = subprocess.run(
        [sys.executable, DOCTOR, "--json"] + paths,
        capture_output=True, text=True)
    try:
        diag = json.loads(proc.stdout)
    except ValueError:
        return ["doctor_verdict: acx_doctor produced no JSON "
                "(rc=%d): %s" % (proc.returncode, proc.stderr.strip())]
    if diag.get("anomaly") not in KILL_ANOMALIES:
        return ["doctor_verdict: anomaly %r, want one of %s"
                % (diag.get("anomaly"), list(KILL_ANOMALIES))]
    if diag.get("culprit") not in victims:
        return ["doctor_verdict: culprit %r, want the killed rank %s"
                % (diag.get("culprit"), sorted(victims))]
    return []


def audit_run(run):
    """All invariants over one run's result dict. Returns (failures,
    notes)."""
    failures, notes = [], []
    if run["exit"] != 0:
        failures.append("workload_exit: job exited %d (byte check or "
                        "heal failed)" % run["exit"])
    f, n = audit_fault_accounting(run["schedule"], run["reports"],
                                  set(run["respawns"]))
    failures += f
    notes += n
    expect_kill = any(s["action"] == "kill" for s in run["schedule"])
    failures += audit_epoch_monotone(run["tseries"], expect_kill
                                     and bool(run["respawns"]))
    failures += audit_seq_spaces(run["dumps"])
    if expect_kill and run["respawns"]:
        failures += audit_doctor(run["flight_prefix"],
                                 set(run["respawns"]))
    return failures, notes


# ---- runner -----------------------------------------------------------

def run_schedule(acxrun, np, schedule_str, workload, outdir, timeout):
    """One supervised chaos run; artifacts land under outdir."""
    os.makedirs(outdir, exist_ok=True)
    env = dict(os.environ)
    env.pop("ACX_CHAOS", None)  # the concrete schedule is authoritative
    env["ACX_FLIGHT"] = os.path.join(outdir, "fl")
    env["ACX_METRICS"] = os.path.join(outdir, "m")
    env["ACX_FAULT_REPORT"] = os.path.join(outdir, "fr")
    env["ACX_TSERIES"] = os.path.join(outdir, "ts")
    env.setdefault("ACX_TSERIES_INTERVAL_MS", "50")
    cmd = [acxrun, "-np", str(np), "-timeout", str(timeout),
           "-transport", "socket", "-chaos"]
    if schedule_str:
        cmd += ["-fault", schedule_str]
    cmd += workload
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout + 60)
    with open(os.path.join(outdir, "run.log"), "w") as f:
        f.write(proc.stdout)
        f.write(proc.stderr)
    respawns = {}
    for m in re.finditer(r"acxrun: chaos ledger rank=(\d+) respawns=(\d+)",
                         proc.stderr):
        respawns[int(m.group(1))] = int(m.group(2))
    return {
        "exit": proc.returncode,
        "schedule_str": schedule_str,
        "schedule": parse_schedule(schedule_str) if schedule_str else [],
        "respawns": respawns,
        "reports": load_fault_reports(os.path.join(outdir, "fr")),
        "dumps": load_flight_dumps(os.path.join(outdir, "fl")),
        "tseries": load_tseries(os.path.join(outdir, "ts")),
        "flight_prefix": os.path.join(outdir, "fl"),
        "stdout": proc.stdout,
        "stderr": proc.stderr,
    }


def expand_chaos(acxrun, spec, np):
    """Seed spec -> concrete schedule via `acxrun -print-chaos` (the same
    splitmix64 expansion every rank performs)."""
    proc = subprocess.run([acxrun, "-print-chaos", spec, "-np", str(np)],
                         capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError("acxrun -print-chaos failed for %r: %s"
                           % (spec, proc.stderr.strip()))
    return proc.stdout.strip()


# ---- ddmin shrinker ---------------------------------------------------

def ddmin(items, still_fails):
    """Classic ddmin: smallest sublist of `items` for which
    still_fails(sublist) holds. still_fails(items) must be true."""
    n = 2
    while len(items) >= 2:
        chunk = max(1, len(items) // n)
        subsets = [items[i:i + chunk] for i in range(0, len(items), chunk)]
        reduced = False
        for i, sub in enumerate(subsets):
            complement = [x for j, s in enumerate(subsets) if j != i
                          for x in s]
            if still_fails(sub):
                items, n, reduced = sub, 2, True
                break
            if len(subsets) > 2 and complement and still_fails(complement):
                items, n, reduced = complement, max(n - 1, 2), True
                break
        if not reduced:
            if n >= len(items):
                break
            n = min(len(items), n * 2)
    return items


def shrink(run, acxrun, np, workload, outdir, timeout):
    """Shrink a failing schedule to a minimal failing spec subset by
    re-running candidates. Returns (minimal_specs, replay_cmd)."""
    specs = [s["raw"] for s in run["schedule"]]
    counter = [0]

    def still_fails(subset):
        if not subset:
            return False
        counter[0] += 1
        sub_out = os.path.join(outdir, "shrink-%d" % counter[0])
        r = run_schedule(acxrun, np, ";".join(subset), workload, sub_out,
                         timeout)
        failures, _ = audit_run(r)
        return bool(failures)

    minimal = ddmin(specs, still_fails) if len(specs) > 1 else specs
    sched = ";".join(minimal)
    replay = "ACX_FAULT='%s' %s -np %d -transport socket -chaos " \
             "-timeout %d %s" % (sched, acxrun, np, timeout,
                                 " ".join(workload))
    return minimal, replay


# ---- CLI --------------------------------------------------------------

def report(run, failures, notes, label):
    for n in notes:
        print("acx_chaos: note: %s" % n)
    for f in failures:
        print("acx_chaos: FAIL %s: %s" % (label, f))
    if not failures:
        fired = sum(int(s.get("fired", 0)) for rep in run["reports"]
                    for s in rep.get("specs", []))
        print("acx_chaos: PASS %s (%d spec(s), %d fault(s) fired, "
              "%d respawn(s))" % (label, len(run["schedule"]), fired,
                                  sum(run["respawns"].values())))


def do_run(args):
    schedule = args.fault or ""
    if args.chaos:
        expanded = expand_chaos(args.acxrun, args.chaos, args.np)
        schedule = (schedule + ";" + expanded) if schedule else expanded
    if not schedule:
        print("acx_chaos: nothing to run (need --fault and/or --chaos)",
              file=sys.stderr)
        return 2
    print("acx_chaos: schedule %s" % schedule)
    run = run_schedule(args.acxrun, args.np, schedule, args.workload,
                       args.out, args.timeout)
    failures, notes = audit_run(run)
    report(run, failures, notes, "run")
    if failures and not args.no_shrink:
        minimal, replay = shrink(run, args.acxrun, args.np, args.workload,
                                 args.out, args.timeout)
        print("acx_chaos: minimal failing schedule: %s" % ";".join(minimal))
        print("acx_chaos: replay: %s" % replay)
        with open(os.path.join(args.out, "replay.txt"), "w") as f:
            f.write(replay + "\n")
    if args.expect_fail:
        # Control leg: the oracle itself is under test — it must both
        # flag the run AND hand back a replay line.
        ok = bool(failures) and (args.no_shrink or
                                 os.path.exists(os.path.join(args.out,
                                                             "replay.txt")))
        print("acx_chaos: expect-fail %s" % ("satisfied" if ok else
                                             "NOT satisfied"))
        return 0 if ok else 1
    return 1 if failures else 0


def do_soak(args):
    base = args.seed_base
    if base is None:
        base = int(os.environ.get("ACX_CHAOS_SEED_BASE", "1000"))
    bad = 0
    for i in range(args.seeds):
        seed = base + i
        spec = "seed=%d:faults=%d:mix=%s" % (seed, args.faults, args.mix)
        schedule = expand_chaos(args.acxrun, spec, args.np)
        print("acx_chaos: seed %d -> %s" % (seed, schedule))
        outdir = os.path.join(args.out, "seed-%d" % seed)
        run = run_schedule(args.acxrun, args.np, schedule, args.workload,
                           outdir, args.timeout)
        failures, notes = audit_run(run)
        report(run, failures, notes, "seed %d" % seed)
        if failures:
            bad += 1
            minimal, replay = shrink(run, args.acxrun, args.np,
                                     args.workload, outdir, args.timeout)
            print("acx_chaos: minimal failing schedule: %s"
                  % ";".join(minimal))
            print("acx_chaos: replay: %s" % replay)
            with open(os.path.join(outdir, "replay.txt"), "w") as f:
                f.write(replay + "\n")
    print("acx_chaos: soak %d/%d seed(s) passed"
          % (args.seeds - bad, args.seeds))
    return 1 if bad else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Run and audit chaos schedules (DESIGN.md §16).")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--np", type=int, default=3)
        p.add_argument("--timeout", type=int, default=90)
        p.add_argument("--out", default="chaos-out")
        p.add_argument("--acxrun",
                       default=os.path.join(REPO, "build", "acxrun"))
        p.add_argument("workload", nargs="+",
                       help="workload command (prefix with -- )")

    rp = sub.add_parser("run", help="one schedule, audited")
    common(rp)
    rp.add_argument("--fault", default=None,
                    help="explicit ';'-separated schedule")
    rp.add_argument("--chaos", default=None,
                    help="seed spec (seed=N:faults=K:mix=...) to expand")
    rp.add_argument("--expect-fail", action="store_true",
                    help="exit 0 iff the audit fails and a replay line "
                         "is produced (oracle self-test)")
    rp.add_argument("--no-shrink", action="store_true")

    sp = sub.add_parser("soak", help="sweep seeds seed_base..+N")
    common(sp)
    sp.add_argument("--seeds", type=int, default=3)
    sp.add_argument("--seed-base", type=int, default=None,
                    help="default $ACX_CHAOS_SEED_BASE, then 1000")
    sp.add_argument("--faults", type=int, default=3)
    sp.add_argument("--mix", default="issue,wire")

    args = ap.parse_args(argv)
    return do_run(args) if args.cmd == "run" else do_soak(args)


if __name__ == "__main__":
    sys.exit(main())
