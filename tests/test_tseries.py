"""Live telemetry plane (include/acx/tseries.h, src/core/tseries.cc,
tools/acx_top.py, docs/DESIGN.md §13): periodic delta-encoded sampling of
the metrics registry, per-link wire scope, crash-flushed tails, the
acx_top fleet console, and the skew-corrected tseries merge.

Everything drives real 2-rank runs through acxrun and reads back the
JSONL artifacts the way an operator's tools would.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOP = os.path.join(REPO, "tools", "acx_top.py")
MERGE = os.path.join(REPO, "tools", "acx_trace_merge.py")


@pytest.fixture(scope="module", autouse=True)
def _built():
    r = subprocess.run(["make", "-C", REPO, "itest", "tools"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


def _acxrun(env_extra, *argv, np_ranks=2, timeout=300):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", str(np_ranks),
         "-timeout", "120", *argv],
        env=env, capture_output=True, text=True, timeout=timeout)


def _run_bench(env_extra):
    r = _acxrun(env_extra, os.path.join(REPO, "build", "bench_pingpong"),
                "8")
    assert r.returncode == 0, r.stdout + r.stderr
    return r


def _load_samples(path):
    samples = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                samples.append(json.loads(line))
    return samples


# -- sampling artifacts -----------------------------------------------------


def test_tseries_jsonl_written_per_rank(tmp_path):
    """ACX_TSERIES=<prefix> produces one delta-encoded JSONL per rank:
    an init line carrying the full absolute registry, then delta lines,
    every sample stamped with both clocks and the fleet epoch, and the
    per-link wire scope obeying wire >= payload in both directions."""
    _run_bench({"ACX_TSERIES": str(tmp_path / "run"),
                "ACX_TSERIES_INTERVAL_MS": "50"})
    for rank in (0, 1):
        path = tmp_path / f"run.rank{rank}.tseries.jsonl"
        assert path.exists(), f"rank {rank} wrote no tseries file"
        samples = _load_samples(path)
        assert len(samples) >= 2, f"rank {rank}: {len(samples)} samples"

        init = samples[0]
        assert init.get("init") is True
        assert init["rank"] == rank
        assert init["interval_ms"] == 50
        assert len(init["counters"]) >= 8  # full absolute registry

        prev_mono = -1
        saw_links = False
        for s in samples:
            assert s["t_mono_ns"] > prev_mono  # strictly monotone
            prev_mono = s["t_mono_ns"]
            assert s["t_wall_ms"] > 0
            assert "epoch" in s
            for ln in s.get("links", []):
                saw_links = True
                assert ln["tx_wb"] >= ln["tx_pb"], ln
                assert ln["rx_wb"] >= ln["rx_pb"], ln
                assert ln["peer"] != rank
        assert saw_links, f"rank {rank}: no sample carried a links section"
        # The ping-pong moved real bytes: the newest links section shows
        # payload flowing both ways, and header overhead makes wire
        # STRICTLY larger.
        last = next(s["links"] for s in reversed(samples) if s.get("links"))
        tot_pb = sum(l["tx_pb"] + l["rx_pb"] for l in last)
        tot_wb = sum(l["tx_wb"] + l["rx_wb"] for l in last)
        assert tot_pb > 0 and tot_wb > tot_pb


def test_tseries_disabled_by_default(tmp_path):
    """Without ACX_TSERIES no artifact appears."""
    env = {k: v for k, v in os.environ.items() if k != "ACX_TSERIES"}
    r = subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
         "120", os.path.join(REPO, "build", "itests", "ring")],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert not list(tmp_path.glob("*.tseries.jsonl"))


@pytest.mark.parametrize("bad", ["0", "garbage", "-5"])
def test_interval_env_parsing_rejects(tmp_path, bad):
    """ACX_TSERIES_INTERVAL_MS that is zero or unparseable disables
    sampling entirely (no files) and says so once on stderr, rather than
    spinning the proxy at interval 0 or silently guessing."""
    r = _acxrun({"ACX_TSERIES": str(tmp_path / "run"),
                 "ACX_TSERIES_INTERVAL_MS": bad},
                os.path.join(REPO, "build", "itests", "ring"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert not list(tmp_path.glob("*.tseries.jsonl"))
    assert "ACX_TSERIES_INTERVAL_MS" in r.stderr
    assert "sampling disabled" in r.stderr


# -- acx_top ----------------------------------------------------------------


def _top(*argv):
    return subprocess.run([sys.executable, TOP, *argv],
                          capture_output=True, text=True, timeout=120)


def test_acx_top_once_json_check(tmp_path):
    """acx_top --once --json --check over a real run: per-rank rows carry
    rates and link health, and the CI assertions (>= 2 samples, monotone
    clocks, byte accounting) pass."""
    _run_bench({"ACX_TSERIES": str(tmp_path / "run"),
                "ACX_TSERIES_INTERVAL_MS": "50"})
    r = _top("--once", "--json", "--check", str(tmp_path / "run"))
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert out["check"]["ok"], out["check"]["violations"]
    assert [row["rank"] for row in out["ranks"]] == [0, 1]
    for row in out["ranks"]:
        assert row["samples"] >= 2
        assert row["torn_lines"] == 0
        assert row["goodput_mbps"] >= 0.0
        assert row["wire_mbps"] >= row["goodput_mbps"]
        assert row["link_health"] == "ok"
        assert 0.0 <= row["proxy_util_pct"] <= 100.0


def test_acx_top_tolerates_torn_last_line(tmp_path):
    """A rank killed mid-write leaves a torn final line; the reader skips
    it (counted in torn_lines) and the series still checks out."""
    _run_bench({"ACX_TSERIES": str(tmp_path / "run"),
                "ACX_TSERIES_INTERVAL_MS": "50"})
    path = tmp_path / "run.rank0.tseries.jsonl"
    with open(path, "a") as f:
        f.write('{"seq":99999,"t_mono_ns":12345,"d":{"ops_comp')  # torn
    r = _top("--once", "--json", "--check", str(tmp_path / "run"))
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    row0 = next(x for x in out["ranks"] if x["rank"] == 0)
    assert row0["torn_lines"] == 1
    assert out["check"]["ok"]


def test_acx_top_check_fails_on_empty_series(tmp_path):
    """--check is a real gate: a one-line series fails it."""
    path = tmp_path / "x.rank0.tseries.jsonl"
    path.write_text('{"init":true,"rank":0,"t_mono_ns":1,"t_wall_ms":1,'
                    '"epoch":0,"counters":{}}\n')
    r = _top("--once", "--json", "--check", str(tmp_path / "x"))
    assert r.returncode == 1
    assert "need >= 2" in r.stderr


# -- crash flush ------------------------------------------------------------


_CRASH_PROG = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %r)
    from mpi_acx_tpu import runtime
    import numpy as np
    rt = runtime.Runtime()
    src = np.arange(4, dtype=np.int32)
    dst = np.zeros(4, dtype=np.int32)
    s = rt.isend_enqueue(src, dest=0)
    r = rt.irecv_enqueue(dst, source=0)
    rt.wait(r); rt.wait(s)
    os.abort()   # no finalize: only the fatal-signal hook can flush
""") % REPO


def test_crash_flush_writes_final_sample(tmp_path):
    """A rank that dies on SIGABRT still leaves its series: the
    crash-flusher registered with the trace plane writes one last sample
    on the way down, so the tail of the run is never lost."""
    env = dict(os.environ)
    env["ACX_TSERIES"] = str(tmp_path / "t")
    env["ACX_TSERIES_INTERVAL_MS"] = "50"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", _CRASH_PROG], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == -signal.SIGABRT, (r.returncode, r.stderr)
    f = tmp_path / "t.rank0.tseries.jsonl"
    assert f.exists(), "crash flush did not write the series"
    samples = _load_samples(f)
    assert samples, "series is empty"
    assert samples[0].get("init") is True
    # Reconstruct the cumulative count: the init line may predate the
    # isend (the very first proxy sweep samples immediately), in which
    # case a later delta carries it — possibly only the crash-flushed
    # tail sample itself.
    isend = samples[0]["counters"].get("ops_isend", 0)
    isend += sum(s.get("d", {}).get("ops_isend", 0) for s in samples[1:])
    assert isend >= 1


# -- live metrics through the Python runtime --------------------------------


def test_python_runtime_live_metrics():
    """Runtime.live_metrics() forces a sample mid-run through the
    acx_tseries_* C API and returns the newest one, including the "app"
    fragment published via tseries_annotate."""
    prog = textwrap.dedent("""
        import json, sys
        import numpy as np
        from mpi_acx_tpu import runtime
        rt = runtime.Runtime()
        assert rt.tseries_enabled()
        src = np.arange(16, dtype=np.float32)
        dst = np.zeros(16, dtype=np.float32)
        s = rt.isend_enqueue(src, dest=0, tag=7)
        r = rt.irecv_enqueue(dst, source=0, tag=7)
        rt.wait(r); rt.wait(s)
        rt.tseries_annotate({"queue_depth": 3, "ttft_p99_s": 0.25})
        m = rt.live_metrics()
        assert m, "no live sample"
        assert m["epoch"] >= 0
        assert m["t_mono_ns"] > 0
        assert m["app"]["queue_depth"] == 3
        rt.finalize()
        print("LIVE_OK")
    """)
    env = dict(os.environ)
    env["ACX_TSERIES"] = "/tmp/acx_live_metrics_test"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "LIVE_OK" in r.stdout


# -- merge tool -------------------------------------------------------------


def test_merge_tool_tseries_alignment(tmp_path):
    """Sibling traces give the tseries merge its barrier-anchored skew:
    the merged stream is rank-tagged, time-sorted on corrected_us, and
    reported aligned."""
    _run_bench({"ACX_TSERIES": str(tmp_path / "run"),
                "ACX_TSERIES_INTERVAL_MS": "50",
                "ACX_TRACE": str(tmp_path / "run")})
    fleet = tmp_path / "fleet.tseries.json"
    r = subprocess.run(
        [sys.executable, MERGE, "--validate", "--tseries-out", str(fleet)]
        + [str(tmp_path / f"run.rank{k}.trace.json") for k in (0, 1)]
        + [str(tmp_path / f"run.rank{k}.tseries.jsonl") for k in (0, 1)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout)
    assert summary["valid"] and summary["tseries"] == 2
    assert summary["tseries_aligned"] is True

    d = json.loads(fleet.read_text())
    assert d["ranks"] == [0, 1]
    assert d["aligned"] is True
    assert {s["rank"] for s in d["samples"]} == {0, 1}
    cs = [s["corrected_us"] for s in d["samples"]]
    assert all(c is not None for c in cs)
    assert cs == sorted(cs)


def test_merge_tool_tseries_unaligned_without_traces(tmp_path):
    """Without traces there is no skew anchor: samples merge with
    corrected_us null and the stream is reported unaligned — never a
    silently wrong alignment."""
    _run_bench({"ACX_TSERIES": str(tmp_path / "run"),
                "ACX_TSERIES_INTERVAL_MS": "50"})
    fleet = tmp_path / "fleet.tseries.json"
    r = subprocess.run(
        [sys.executable, MERGE, "--tseries-out", str(fleet)]
        + [str(tmp_path / f"run.rank{k}.tseries.jsonl") for k in (0, 1)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    d = json.loads(fleet.read_text())
    assert d["aligned"] is False
    assert all(s["corrected_us"] is None for s in d["samples"])


def test_merge_tool_tseries_app_by_rank(tmp_path):
    """The application SLO fragment (tseries_annotate) survives the
    fleet merge rank-tagged: each merged sample keeps its own "app"
    section, and the NEWEST fragment per rank is surfaced as a
    fleet-level app_by_rank summary — so "which rank's serving loop
    reports the worst p99" is one lookup, not a scan."""
    def _line(rank, seq, t_ns, app=None, init=False):
        s = {"seq": seq, "t_mono_ns": t_ns, "t_wall_ms": t_ns // 10**6 + 1,
             "epoch": 0}
        if init:
            s.update({"init": True, "rank": rank, "interval_ms": 50,
                      "counters": {}})
        else:
            s["d"] = {}
        if app is not None:
            s["app"] = app
        return json.dumps(s)

    f0 = tmp_path / "run.rank0.tseries.jsonl"
    f0.write_text("\n".join([
        _line(0, 0, 1000, init=True),
        _line(0, 1, 2000, app={"queue_depth": 9, "ttft_p99_s": 0.5}),
        _line(0, 2, 3000, app={"queue_depth": 2, "ttft_p99_s": 0.1}),
    ]) + "\n")
    f1 = tmp_path / "run.rank1.tseries.jsonl"
    f1.write_text("\n".join([
        _line(1, 0, 1000, init=True),
        _line(1, 1, 2500, app={"queue_depth": 7}),
    ]) + "\n")

    fleet = tmp_path / "fleet.tseries.json"
    r = subprocess.run(
        [sys.executable, MERGE, "--tseries-out", str(fleet),
         str(f0), str(f1)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout)
    assert summary["tseries_app_ranks"] == [0, 1]

    d = json.loads(fleet.read_text())
    # Newest fragment per rank wins the summary...
    assert d["app_by_rank"]["0"] == {"queue_depth": 2, "ttft_p99_s": 0.1}
    assert d["app_by_rank"]["1"] == {"queue_depth": 7}
    # ...and every sample still carries its own fragment verbatim.
    r0_apps = [s.get("app") for s in d["samples"] if s["rank"] == 0]
    assert {"queue_depth": 9, "ttft_p99_s": 0.5} in r0_apps


# -- make target ------------------------------------------------------------


def test_makefile_tseries_check_target():
    """`make tseries-check` (wired into `make check`) goes green."""
    r = subprocess.run(["make", "-C", REPO, "tseries-check"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "TSERIES CHECK PASSED" in r.stdout
