"""Flagship 1F1B schedule: exact parity with the GPipe train step.

schedule="1f1b" must be pure schedule — the same loss scalar and the
same gradients (hence updated parameters) as the autodiff GPipe path,
for all three model families, on the full dp x pp x tp mesh with the
stage collectives (ring attention psum/all_gather/ppermute) running
inside the manual vjp.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import moe_transformer as mtf
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.parallel.mesh import mesh_from_devices
from mpi_acx_tpu.train import make_train_step


def _mesh():
    return mesh_from_devices({"dp": 2, "pp": 2, "tp": 2})


def _compare(cfg, params, tokens, targets, n_micro, atol=2e-5,
             rtol=2e-4, **kw):
    lr = 0.1
    gp_step, n_st = make_train_step(cfg, _mesh(), n_micro=n_micro,
                                    lr=lr, **kw)
    ob_step, _ = make_train_step(cfg, _mesh(), n_micro=n_micro, lr=lr,
                                 schedule="1f1b", **kw)
    staged = tfm.stage_slice(params, n_st)
    gl, gnew = gp_step(staged, tokens, targets)
    ol, onew = ob_step(staged, tokens, targets)
    np.testing.assert_allclose(float(ol), float(gl), rtol=1e-6)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_flatten_with_path(onew)[0],
            jax.tree_util.tree_flatten_with_path(gnew)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=atol, rtol=rtol,
            err_msg=jax.tree_util.keystr(ka))


def test_1f1b_matches_gpipe_gpt2():
    cfg = tfm.TransformerConfig(**{**tfm.tiny_config(
        vocab=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq=16).__dict__, "dtype": jnp.float32})
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 4, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=-1)
    _compare(cfg, params, tokens, targets, n_micro=4)


def test_1f1b_matches_gpipe_llama():
    c = lm.tiny_llama(vocab=64, d_model=32, n_heads=4, n_kv_heads=2,
                      n_layers=4, d_ff=64, max_seq=16)
    cfg = lm.LlamaConfig(**{**c.__dict__, "dtype": jnp.float32})
    params = lm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=-1)
    _compare(cfg, params, tokens, targets, n_micro=2)


def test_1f1b_matches_gpipe_moe_with_aux():
    """MoE under 1F1B: the router aux losses (values AND gradients,
    seeded per-stage inside the manual vjp) must match the GPipe path's
    scan-carried accumulator exactly."""
    cfg = mtf.tiny_moe_config(vocab=32, d_model=32, n_heads=2,
                              n_layers=4, d_ff=64, n_experts=8, top_k=1,
                              capacity_factor=4.0, max_seq=16)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 32)
    targets = jnp.roll(tokens, -1, axis=-1)
    _compare(cfg, params, tokens, targets, n_micro=2,
             aux_weight=1e-2, z_weight=1e-3)


def test_1f1b_with_remat_matches():
    """Per-layer jax.checkpoint composes with the manual-vjp backward
    (the recompute nests)."""
    cfg = tfm.TransformerConfig(**{**tfm.tiny_config(
        vocab=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq=16).__dict__, "dtype": jnp.float32})
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=-1)
    _compare(cfg, params, tokens, targets, n_micro=2, remat=True)


def _compare_interleaved(cfg, params, tokens, targets, n_micro,
                         n_virtual, atol=2e-5, rtol=2e-4, **kw):
    """schedule='1f1b' x n_virtual>1 (interleaved 1F1B) vs the
    interleaved GPipe autodiff path: same staged params layout
    ([pp, v, ...]), must produce the same loss and updated params."""
    lr = 0.1
    gp_step, n_st = make_train_step(cfg, _mesh(), n_micro=n_micro,
                                    lr=lr, n_virtual=n_virtual, **kw)
    ob_step, _ = make_train_step(cfg, _mesh(), n_micro=n_micro, lr=lr,
                                 n_virtual=n_virtual, schedule="1f1b",
                                 **kw)
    staged = tfm.stage_slice_interleaved(params, n_st, n_virtual)
    gl, gnew = gp_step(staged, tokens, targets)
    ol, onew = ob_step(staged, tokens, targets)
    np.testing.assert_allclose(float(ol), float(gl), rtol=1e-6)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_flatten_with_path(onew)[0],
            jax.tree_util.tree_flatten_with_path(gnew)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=atol, rtol=rtol,
            err_msg=jax.tree_util.keystr(ka))


def test_interleaved_1f1b_matches_gpipe_gpt2():
    """The round-4 verdict composition: 1F1B's O(pp) memory AND
    interleaving's bubble/v, in one schedule, exact to autodiff."""
    cfg = tfm.TransformerConfig(**{**tfm.tiny_config(
        vocab=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq=16).__dict__, "dtype": jnp.float32})
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 4, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=-1)
    _compare_interleaved(cfg, params, tokens, targets, n_micro=4,
                         n_virtual=2)


def test_interleaved_1f1b_matches_gpipe_llama():
    c = lm.tiny_llama(vocab=64, d_model=32, n_heads=4, n_kv_heads=2,
                      n_layers=4, d_ff=64, max_seq=16)
    cfg = lm.LlamaConfig(**{**c.__dict__, "dtype": jnp.float32})
    params = lm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 2, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=-1)
    _compare_interleaved(cfg, params, tokens, targets, n_micro=2,
                         n_virtual=2)


def test_interleaved_1f1b_matches_gpipe_moe_with_aux():
    """MoE + interleaved 1F1B: router aux values and gradients seeded
    per-chunk inside the manual vjp must still match GPipe exactly."""
    cfg = mtf.tiny_moe_config(vocab=32, d_model=32, n_heads=2,
                              n_layers=4, d_ff=64, n_experts=8, top_k=1,
                              capacity_factor=4.0, max_seq=16)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 2, 16), 0, 32)
    targets = jnp.roll(tokens, -1, axis=-1)
    _compare_interleaved(cfg, params, tokens, targets, n_micro=2,
                         n_virtual=2, aux_weight=1e-2, z_weight=1e-3)


def test_interleaved_1f1b_needs_divisible_micro():
    cfg = tfm.TransformerConfig(**{**tfm.tiny_config(
        vocab=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        max_seq=16).__dict__, "dtype": jnp.float32})
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (3, 4, 16), 0, 64)
    step, n_st = make_train_step(cfg, _mesh(), n_micro=3, n_virtual=2,
                                 schedule="1f1b")
    staged = tfm.stage_slice_interleaved(params, n_st, 2)
    with pytest.raises(ValueError, match="interleaved 1F1B"):
        step(staged, tokens, jnp.roll(tokens, -1, axis=-1))
