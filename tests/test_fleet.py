"""Elastic-fleet membership through the Python stack (docs/DESIGN.md §12):
epoch/view/stats surface on Runtime, the multihost join budget and fleet
snapshot helpers, join-warm checkpoint restore, the serving loop's
slot-revive telemetry, and the rolling-restart itest end-to-end — including
a deliberately wedged join whose hang the doctor must attribute to the
victim even when the victim's flight dump is missing.

Fleet state seeds at first native-library use and stays armed for the
life of the process, so every test that instantiates ``Runtime`` runs in
a SUBPROCESS (worker modes of this file, the test_recovery.py pattern).
The pure-Python helpers run in-process.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _acxrun():
    from mpi_acx_tpu import runtime
    return runtime.acxrun_path()


def _rolling_restart():
    p = os.path.join(REPO, "build", "itests", "rolling-restart")
    if not os.path.exists(p):
        subprocess.run(["make", "-C", REPO, "itest"], check=True,
                       capture_output=True)
    return p


def _run(cmd, env_extra=None, timeout=120):
    env = dict(os.environ)
    env.pop("ACX_FAULT", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


def _load_multihost():
    """Load parallel/multihost.py directly: going through the package
    __init__ drags in collective.py, whose jax.shard_map import is absent
    on some CPU-only jax builds — the fleet helpers don't need it."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "acx_test_multihost",
        os.path.join(REPO, "mpi_acx_tpu", "parallel", "multihost.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- pure-Python surface ----------------------------------------------------


def test_fleet_state_names_cover_lifecycle():
    """The state-name table matches the lifecycle the native enum walks:
    JOIN -> ACTIVE -> DRAINING -> LEFT/DEAD, with index 0 reserved for
    unknown so a garbage value never renders as a real state."""
    from mpi_acx_tpu.runtime import FLEET_STATE_NAMES
    assert FLEET_STATE_NAMES[0] == "unknown"
    for name in ("joining", "active", "draining", "left", "dead"):
        assert name in FLEET_STATE_NAMES


def test_fleet_join_budget_defaults_and_env(monkeypatch):
    """Join budget = ACX_FLEET_JOIN_TIMEOUT_MS (default 10 s) plus the
    handshake margin; an explicit timeout wins over the env."""
    multihost = _load_multihost()
    monkeypatch.delenv("ACX_FLEET_JOIN_TIMEOUT_MS", raising=False)
    assert multihost.fleet_join_budget_s() == pytest.approx(11.0)
    assert multihost.fleet_join_budget_s(timeout_ms=4000.0) == \
        pytest.approx(5.0)
    monkeypatch.setenv("ACX_FLEET_JOIN_TIMEOUT_MS", "2500")
    assert multihost.fleet_join_budget_s() == pytest.approx(3.5)
    assert multihost.fleet_join_budget_s(margin_s=0.0,
                                         timeout_ms=1000.0) == \
        pytest.approx(1.0)


def test_serving_metrics_revive_field_defaults_zero():
    """slots_revived rides next to slots_shed so a serving run with no
    membership churn reports 0/0, not missing keys."""
    from mpi_acx_tpu.models.serving import ServingMetrics
    m = ServingMetrics()
    assert m.slots_shed == 0
    assert m.slots_revived == 0


def test_warm_start_empty_dir_returns_none(tmp_path):
    """A fleet that never checkpointed gives the joiner nothing to warm
    from: (None, None), keep the freshly built state."""
    from mpi_acx_tpu import checkpoint
    state, step = checkpoint.warm_start(str(tmp_path / "empty"),
                                        like={"w": np.zeros(4)})
    assert state is None and step is None


def test_warm_start_restores_latest_step(tmp_path):
    """Join-warm restore hands back the latest saved step bit-identical:
    the joiner serves the same weights the fleet is serving."""
    from mpi_acx_tpu import checkpoint
    d = str(tmp_path / "ckpt")
    with checkpoint.Checkpointer(d) as ckpt:
        ckpt.save(3, {"w": np.arange(4, dtype=np.float32)})
        ckpt.save(7, {"w": np.arange(4, dtype=np.float32) * 2})
    state, step = checkpoint.warm_start(
        d, like={"w": np.zeros(4, dtype=np.float32)})
    assert step == 7
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.arange(4, dtype=np.float32) * 2)


# -- Runtime fleet surface (subprocess: armed native state) -----------------


def test_fleet_view_loopback():
    """A 1-rank fleet boots at epoch >= 1 with its own slot ACTIVE and
    zeroed churn counters; fleet_snapshot agrees with the parts."""
    r = _run([sys.executable, __file__, "--fleet-loopback-worker"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "FLEET LOOPBACK OK" in r.stdout


def test_fleet_leave_loopback_is_clean():
    """A graceful leave with nothing in flight cancels 0 ops and moves
    this rank's own slot out of ACTIVE."""
    r = _run([sys.executable, __file__, "--fleet-leave-worker"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "FLEET LEAVE OK" in r.stdout


def _fleet_loopback_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    multihost = _load_multihost()
    rt = runtime.Runtime()
    assert rt.fleet_epoch() >= 1
    assert rt.fleet_view() == ["active"]
    stats = rt.fleet_stats()
    assert set(stats) == {"epoch", "joins", "leaves", "deaths", "active"}
    assert stats["active"] == 1
    assert stats["joins"] == stats["leaves"] == stats["deaths"] == 0
    snap = multihost.fleet_snapshot(rt)
    assert snap["epoch"] == rt.fleet_epoch()
    assert snap["view"] == ["active"]
    assert snap["stats"]["active"] == 1
    print("FLEET LOOPBACK OK", flush=True)
    return 0


def _fleet_leave_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    assert rt.fleet_leave(500.0) == 0  # nothing in flight: clean departure
    assert rt.fleet_stats()["active"] == 0
    assert rt.fleet_view() != ["active"]
    print("FLEET LEAVE OK", flush=True)
    return 0


# -- rolling restart end-to-end ---------------------------------------------


def test_rolling_restart_replaces_every_rank():
    """The capstone itest under acxrun: every rank of a 2-rank socket
    fleet is replaced one at a time under load, the fleet epoch climbs
    monotonically, and the run exits 0."""
    r = _run([_acxrun(), "-np", "2", "-timeout", "100",
              "-transport", "socket", _rolling_restart()],
             timeout=150)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rolling-restart: OK" in r.stdout


def test_wedged_join_doctor_attribution(tmp_path):
    """A deliberately wedged join (the replacement never dials in) must
    not hang the survivors: they time the join out on the
    ACX_FLEET_JOIN_TIMEOUT_MS budget, dump flight state, and exit 7.
    acx_doctor.py then attributes the stall to the victim rank even with
    the victim's own dump deleted — the gap corroborates the verdict
    (satellite: tolerate a missing per-rank dump)."""
    flight = str(tmp_path / "rr")
    r = _run([_acxrun(), "-np", "3", "-timeout", "100",
              "-transport", "socket", _rolling_restart()],
             env_extra={"ACX_RR_WEDGE": "1",
                        "ACX_FLEET_JOIN_TIMEOUT_MS": "6000",
                        "ACX_FLIGHT": flight},
             timeout=150)
    assert r.returncode == 7, r.stdout + r.stderr
    dumps = sorted(str(p) for p in tmp_path.glob("rr.rank*.flight.json"))
    assert len(dumps) >= 2, r.stdout + r.stderr
    victim = str(tmp_path / "rr.rank1.flight.json")
    if victim in dumps:
        os.unlink(victim)
        dumps.remove(victim)
    d = _run([sys.executable, os.path.join(REPO, "tools", "acx_doctor.py"),
              "--json"] + dumps)
    assert d.returncode == 0, d.stdout + d.stderr
    verdict = json.loads(d.stdout)
    assert verdict["culprit"] == 1, verdict
    assert verdict["anomaly"] in ("dead_link", "missing_dump"), verdict
    assert 1 in verdict.get("missing_ranks", []), verdict


if __name__ == "__main__":
    if "--fleet-loopback-worker" in sys.argv:
        raise SystemExit(_fleet_loopback_worker())
    if "--fleet-leave-worker" in sys.argv:
        raise SystemExit(_fleet_leave_worker())
    raise SystemExit(2)
