"""Unified metrics plane (include/acx/metrics.h, src/core/metrics.cc,
tools/acx_trace_merge.py): native counter/histogram registry, lifecycle
spans in the trace, crash-safe flushes, and the cross-rank merge tool.

Everything here drives real 2-rank runs through acxrun — the registry's
numbers are checked against what the workload actually did, not against
the implementation's own bookkeeping.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MERGE = os.path.join(REPO, "tools", "acx_trace_merge.py")


@pytest.fixture(scope="module", autouse=True)
def _built():
    r = subprocess.run(["make", "-C", REPO, "itest", "tools"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


def _acxrun(env_extra, *argv, np_ranks=2, timeout=300):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", str(np_ranks),
         "-timeout", "120", *argv],
        env=env, capture_output=True, text=True, timeout=timeout)


def _run_ring(tmp_path, env_extra):
    r = _acxrun(env_extra, os.path.join(REPO, "build", "itests", "ring"))
    assert r.returncode == 0, r.stdout + r.stderr
    return r


# -- registry artifacts -----------------------------------------------------


def test_metrics_json_written_per_rank(tmp_path):
    """ACX_METRICS=<path> dumps one <path>.rank<r>.metrics.json per rank
    at finalize, with the counters the ring run must have produced: the
    itest sends/recvs one int per rank per phase (2 phases)."""
    _run_ring(tmp_path, {"ACX_METRICS": str(tmp_path / "m")})
    for rank in (0, 1):
        d = json.loads((tmp_path / f"m.rank{rank}.metrics.json").read_text())
        assert d["enabled"] is True
        c = d["counters"]
        assert len(c) >= 8
        assert c["ops_isend"] == 2 and c["ops_irecv"] == 2
        assert c["bytes_sent"] == 8 and c["bytes_recv"] == 8  # 2 x int32
        assert c["triggers"] == 4 and c["waits"] == 4
        assert c["ops_issued"] == 4 and c["ops_completed"] == 4
        assert c["slot_hwm"] >= 1
        h = d["histograms"]
        assert len(h) >= 3
        for name in ("trigger_to_issue_ns", "issue_to_complete_ns",
                     "complete_to_wait_ns"):
            assert h[name]["count"] == 4, name
            assert h[name]["sum"] > 0
            assert sum(h[name]["buckets"]) == h[name]["count"]


def test_metrics_disabled_by_default(tmp_path):
    """Without ACX_METRICS no artifact appears (and the hot path took
    the one-branch disabled route the whole run)."""
    env = {k: v for k, v in os.environ.items() if k != "ACX_METRICS"}
    r = subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
         "120", os.path.join(REPO, "build", "itests", "ring")],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert not list(tmp_path.glob("*.metrics.json"))


def test_python_runtime_metrics_snapshot():
    """Runtime.metrics() reads the registry through the C API. Run in a
    subprocess so ACX_METRICS=1 (snapshot-only mode: no file) is set
    before the native library loads."""
    prog = textwrap.dedent("""
        import json, sys
        import numpy as np
        from mpi_acx_tpu import runtime
        rt = runtime.Runtime()
        assert rt.metrics_enabled()
        src = np.arange(16, dtype=np.float32)
        dst = np.zeros(16, dtype=np.float32)
        s = rt.isend_enqueue(src, dest=0, tag=7)
        r = rt.irecv_enqueue(dst, source=0, tag=7)
        rt.wait(r); rt.wait(s)
        m = rt.metrics()
        assert m["enabled"] is True
        assert m["counters"]["ops_isend"] == 1
        assert m["counters"]["bytes_sent"] == 64
        assert m["histograms"]["issue_to_complete_ns"]["count"] >= 1
        rt.finalize()
        print("METRICS_OK", json.dumps(len(m["counters"])))
    """)
    env = dict(os.environ)
    env["ACX_METRICS"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "METRICS_OK" in r.stdout


# -- trace spans ------------------------------------------------------------


def test_trace_spans_balanced_and_sorted(tmp_path):
    """The upgraded trace carries paired duration spans (ph b/e) next to
    the instants, stays time-sorted, and balances every begin with an
    end of the same name+id."""
    _run_ring(tmp_path, {"ACX_TRACE": str(tmp_path / "t")})
    for rank in (0, 1):
        d = json.loads((tmp_path / f"t.rank{rank}.trace.json").read_text())
        evs = d["traceEvents"]
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)
        assert {e["name"] for e in evs if e["ph"] == "i"} >= {
            "trigger_fired", "op_completed"}
        begins = [(e["name"], e["id"]) for e in evs if e["ph"] == "b"]
        ends = [(e["name"], e["id"]) for e in evs if e["ph"] == "e"]
        assert begins and sorted(begins) == sorted(ends)
        assert {n for n, _ in begins} >= {"proxy_pickup", "wire",
                                          "wait_pickup"}
        assert d["otherData"]["spans"] == len(begins)


def test_trace_ring_overflow_drops_new_keeps_old(tmp_path):
    """Satellite: with a tiny ACX_TRACE_CAP the ring drops NEW events,
    keeps the oldest, and reports the count in otherData.dropped."""
    _run_ring(tmp_path, {"ACX_TRACE": str(tmp_path / "t"),
                         "ACX_TRACE_CAP": "16"})
    for rank in (0, 1):
        d = json.loads((tmp_path / f"t.rank{rank}.trace.json").read_text())
        other = d["otherData"]
        assert other["events"] == 16          # capped, not truncated lower
        assert other["dropped"] > 0
        names = [e["name"] for e in d["traceEvents"] if e["ph"] == "i"]
        # The FIRST events of the run survive — the enqueue of op one
        # happens before event 17 on every rank of the 2-int ring.
        assert "isend_enqueue" in names or "irecv_enqueue" in names


# -- crash-safe flush -------------------------------------------------------


_CRASH_PROG = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %r)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    import numpy as np
    src = np.arange(4, dtype=np.int32)
    dst = np.zeros(4, dtype=np.int32)
    s = rt.isend_enqueue(src, dest=0)
    r = rt.irecv_enqueue(dst, source=0)
    rt.wait(r); rt.wait(s)
    mode = sys.argv[1]
    if mode == "exit":
        sys.exit(0)          # NO finalize: only the atexit hook can flush
    os.kill(os.getpid(), int(mode))
""") % REPO


@pytest.mark.parametrize("mode,rc", [("exit", 0),
                                     (str(int(signal.SIGTERM)),
                                      -signal.SIGTERM)],
                         ids=["atexit", "sigterm"])
def test_crash_flush_writes_trace(tmp_path, mode, rc):
    """A rank that never reaches MPIX_Finalize still leaves its trace:
    the atexit hook covers plain exits, the signal hook covers a
    SIGTERM'd process (handlers installed only over SIG_DFL)."""
    env = dict(os.environ)
    env["ACX_TRACE"] = str(tmp_path / "t")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", _CRASH_PROG, mode], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == rc, (r.returncode, r.stdout, r.stderr)
    f = tmp_path / "t.rank0.trace.json"
    assert f.exists(), "crash flush did not write the trace"
    d = json.loads(f.read_text())
    assert {e["name"] for e in d["traceEvents"]} >= {"trigger_fired",
                                                     "op_completed"}


# -- merge tool -------------------------------------------------------------


def test_merge_tool_end_to_end(tmp_path):
    """2-rank run -> one Perfetto-loadable file with one named process
    per rank and every span intact, plus the fleet metrics aggregate,
    all under --validate."""
    _run_ring(tmp_path, {"ACX_TRACE": str(tmp_path / "t"),
                         "ACX_METRICS": str(tmp_path / "m")})
    merged = tmp_path / "merged.trace.json"
    fleet = tmp_path / "fleet.metrics.json"
    r = subprocess.run(
        [sys.executable, MERGE, "--validate", "--out", str(merged),
         "--metrics-out", str(fleet)]
        + [str(tmp_path / f"t.rank{k}.trace.json") for k in (0, 1)]
        + [str(tmp_path / f"m.rank{k}.metrics.json") for k in (0, 1)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout)
    assert summary["valid"] and summary["traces"] == 2

    d = json.loads(merged.read_text())
    assert {e["pid"] for e in d["traceEvents"]} == {0, 1}
    proc_names = {e["args"]["name"] for e in d["traceEvents"]
                  if e.get("ph") == "M"}
    assert proc_names == {"rank 0", "rank 1"}
    spans = [e for e in d["traceEvents"] if e.get("ph") == "b"]
    assert spans and {e["pid"] for e in spans} == {0, 1}

    f = json.loads(fleet.read_text())
    assert f["ranks"] == [0, 1]
    assert f["counters"]["ops_isend"] == 4          # 2 per rank, summed
    assert f["counters"]["slot_hwm"] >= 1           # maxed, not summed
    assert f["histograms"]["issue_to_complete_ns"]["count"] == 8


def test_merge_tool_validate_catches_corruption(tmp_path):
    """--validate is a real check: an unbalanced span fails it."""
    bad = tmp_path / "bad.rank0.trace.json"
    bad.write_text(json.dumps({
        "traceEvents": [
            {"name": "wire", "cat": "acx", "ph": "b", "id": 0, "pid": 0,
             "tid": 1, "ts": 1.0},
        ],
        "otherData": {"dropped": 0, "events": 0, "spans": 1}}))
    r = subprocess.run([sys.executable, MERGE, "--validate", str(bad)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "unbalanced span" in r.stderr


def test_makefile_metrics_check_target():
    """`make metrics-check` (wired into `make check`) goes green."""
    r = subprocess.run(["make", "-C", REPO, "metrics-check"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "METRICS CHECK PASSED" in r.stdout
