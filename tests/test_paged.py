"""Paged KV cache + radix prefix sharing + page-pressure scheduling
(models/kvpage.py, serve_paged_greedy, the paged flash-decode arm —
docs/DESIGN.md §19).

The load-bearing claim is BIT-equality: a slot whose pages hold the
fixed cache's rows must attend identically (paged_gather_attend
reshapes into the exact dense layout; the paged Pallas kernel at
``block_k == page_tokens`` runs the fixed kernel's FLOP sequence), and
``serve_paged_greedy`` must reproduce fixed-slot ``serve_greedy``
token for token — including across a page-pressure preemption, whose
replay re-lands on the same deterministic page placement. Prefix-hit
prefills use different tensor shapes than cold ones, so the sharing
tests assert determinism and page *reuse* (the HBM claim), not
bitwise identity with the cold path.

Everything runs on CPU: the gather path is plain jnp, the Pallas
kernel runs in interpret mode (the same discipline as
tests/test_flash_decode.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_acx_tpu.models import kvpage
from mpi_acx_tpu.models import serving
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.decoding import dense_decode_attend
from mpi_acx_tpu.ops.flash_decode import (flash_decode_attend,
                                          paged_flash_decode_attend,
                                          paged_gather_attend,
                                          select_paged_decode_attend)
from mpi_acx_tpu.ops.kvquant import kv_quant

B, Hkv, D, MAX_LEN, PT = 3, 2, 16, 96, 32       # max_pages = 3


# --------------------------------------------------------------------------
# kernel-level parity: paged attend vs the fixed-cache references


def _fixed_case(n_rep, W, kind, seed=0):
    """(q, kc, vc): the fixed-slot [B, MAX_LEN, Hkv, D] caches of
    tests/test_flash_decode.py, bf16 or (int8 codes, f32 scales)."""
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, W, Hkv * n_rep, D))
    kc = rng.standard_normal((B, MAX_LEN, Hkv, D))
    vc = rng.standard_normal((B, MAX_LEN, Hkv, D))
    if kind == "int8":
        q = jnp.asarray(q, jnp.float32)
        kc = kv_quant(jnp.asarray(kc, jnp.float32))
        vc = kv_quant(jnp.asarray(vc, jnp.float32))
        return q, kc, vc
    return (jnp.asarray(q, jnp.bfloat16), jnp.asarray(kc, jnp.bfloat16),
            jnp.asarray(vc, jnp.bfloat16))


def _paginate(kc, vc, shared_prefix=False):
    """Slice fixed caches into a page pool + block table holding the
    SAME rows. ``shared_prefix=True`` makes every slot's first page one
    aliased pool page (their row contents are first made identical) —
    the layout a radix-cache hit produces."""
    def split(c):
        # [B, MAX_LEN, Hkv, *] -> [B*max_pages, PT, Hkv, *]
        return c.reshape(B, MAX_LEN // PT, PT, *c.shape[2:]).reshape(
            B * (MAX_LEN // PT), PT, *c.shape[2:])

    max_pages = MAX_LEN // PT
    table = np.arange(B * max_pages, dtype=np.int32).reshape(B, max_pages)
    if shared_prefix:
        if isinstance(kc, tuple):
            kc = (kc[0].at[:, :PT].set(kc[0][0, :PT]),
                  kc[1].at[:, :PT].set(kc[1][0, :PT]))
            vc = (vc[0].at[:, :PT].set(vc[0][0, :PT]),
                  vc[1].at[:, :PT].set(vc[1][0, :PT]))
        else:
            kc = kc.at[:, :PT].set(kc[0, :PT])
            vc = vc.at[:, :PT].set(vc[0, :PT])
        table[:, 0] = 0                           # alias slot 0's page
    pk = ((split(kc[0]), split(kc[1])) if isinstance(kc, tuple)
          else split(kc))
    pv = ((split(vc[0]), split(vc[1])) if isinstance(vc, tuple)
          else split(vc))
    return kc, vc, pk, pv, jnp.asarray(table)


@pytest.mark.parametrize("kind", ["bf16", "int8"])
@pytest.mark.parametrize("posmode", ["scalar", "vector"])
@pytest.mark.parametrize("shared", [False, True],
                         ids=["prefix-miss", "prefix-hit"])
def test_paged_gather_bit_equals_dense(kind, posmode, shared):
    """paged_gather_attend over pages holding the fixed cache's rows is
    BIT-equal to dense_decode_attend on the fixed cache — private pages
    (cold/miss) and an aliased shared first page (hit) alike. This is
    the anchor the whole §19 equality chain hangs from."""
    q, kc, vc = _fixed_case(n_rep=2, W=1, kind=kind)
    kc, vc, pk, pv, table = _paginate(kc, vc, shared_prefix=shared)
    pos = 41 if posmode == "scalar" else jnp.array([33, 63, MAX_LEN - 1],
                                                   jnp.int32)
    ref = dense_decode_attend(q, kc, vc, pos, MAX_LEN, 2)
    out = paged_gather_attend(q, pk, pv, table, pos, PT, 2)
    np.testing.assert_array_equal(np.asarray(out, np.float32),
                                  np.asarray(ref, np.float32))


@pytest.mark.parametrize("kind", ["bf16", "int8"])
@pytest.mark.parametrize("posmode", ["scalar", "vector"])
# The prefix-hit variants differ only in page aliasing, which the cheap
# gather grid above already pins; keep the kernel leg of the tier-1
# sweep to the miss grid and run the full cross in `make paged-check`.
@pytest.mark.parametrize("shared", [
    False,
    pytest.param(True, marks=pytest.mark.slow),
], ids=["prefix-miss", "prefix-hit"])
def test_paged_flash_bit_equals_fixed_flash(kind, posmode, shared):
    """The paged Pallas kernel at block size == page size runs the
    fixed kernel's exact FLOP sequence — outputs are BIT-equal to
    flash_decode_attend(block_k=PT) on the same rows (interpret mode
    on CPU, same discipline as test_flash_decode.py)."""
    q, kc, vc = _fixed_case(n_rep=2, W=1, kind=kind, seed=7)
    kc, vc, pk, pv, table = _paginate(kc, vc, shared_prefix=shared)
    pos = 50 if posmode == "scalar" else jnp.array([0, 41, 77], jnp.int32)
    ref = flash_decode_attend(q, kc, vc, pos, MAX_LEN, 2, block_k=PT)
    out = paged_flash_decode_attend(q, pk, pv, table, pos, PT, 2)
    np.testing.assert_array_equal(np.asarray(out, np.float32),
                                  np.asarray(ref, np.float32))


def test_select_paged_decode_attend_dispatch():
    """Same contract as select_decode_attend: None -> auto, True ->
    kernel, False -> gather reference."""
    assert select_paged_decode_attend(True) is paged_flash_decode_attend
    assert select_paged_decode_attend(False) is paged_gather_attend
    auto = select_paged_decode_attend(None)
    q, kc, vc = _fixed_case(n_rep=1, W=1, kind="bf16")
    _, _, pk, pv, table = _paginate(kc, vc)
    out = auto(q, pk, pv, table, 10, PT, 1)
    assert out.shape == (B, 1, Hkv * D)


# --------------------------------------------------------------------------
# allocator / trie / PagedKV units


def test_allocator_deterministic_and_refcounted():
    a = kvpage.PageAllocator(6)
    assert a.alloc(3) == [0, 1, 2]                # lowest ids first
    assert a.alloc(4) is None                     # all-or-nothing
    assert a.free_count == 3
    a.incref(1)
    assert a.shared_count() == 1
    assert not a.decref(1)                        # still referenced
    assert a.decref(1)                            # refcount 0 -> reclaimed
    assert a.decref(0) and a.decref(2)
    assert a.free_count == 6
    # Reclaim re-sorts: the next alloc hands back the lowest ids again.
    assert a.alloc(2) == [0, 1]


def _pool_cfg(kv_int8=False):
    cfg = tfm.tiny_config(vocab=61, d_model=48, n_heads=4, n_layers=2,
                          d_ff=96, max_seq=96)
    return cfg, tfm.init_params(jax.random.key(0), cfg)


def test_cow_on_divergence():
    """ensure_writable on a shared page copies it: the slot gets a
    private page with identical bytes, the shared original keeps its
    other reference, refcounts land right. (Unreachable under the
    full-page-adoption policy — this pins the defensive guard.)"""
    cfg, _ = _pool_cfg()
    pkv = kvpage.PagedKV(cfg, tfm, n_slots=2, max_len=32, page_tokens=8,
                         n_pages=6)
    pages = pkv.alloc_evicting(2)
    pkv.pool["k"] = pkv.pool["k"].at[:, pages[0]].set(1.5)
    pkv.alloc.incref(pages[0])                    # simulate a trie share
    pkv.seat(0, [pages[0]], [pages[1]], new_pos=10)
    assert pkv.alloc.refcount(pages[0]) == 2
    assert pkv.ensure_writable(0, 0)              # shared -> copies
    new_page = pkv.pages[0][0]
    assert new_page != pages[0]
    assert pkv.alloc.refcount(pages[0]) == 1
    assert pkv.alloc.refcount(new_page) == 1
    np.testing.assert_array_equal(
        np.asarray(pkv.pool["k"][:, new_page]),
        np.asarray(pkv.pool["k"][:, pages[0]]))
    assert pkv.table[0, 0] == new_page
    assert not pkv.ensure_writable(0, 0)          # now private: no-op


def test_release_reclaims_to_zero_and_parks():
    cfg, _ = _pool_cfg()
    pkv = kvpage.PagedKV(cfg, tfm, n_slots=2, max_len=32, page_tokens=8,
                         n_pages=8)
    pages = pkv.alloc_evicting(3)
    pkv.seat(1, [], pages, new_pos=20)
    assert pkv.alloc.used_count == 3
    pkv.release(1)
    assert pkv.alloc.used_count == 0
    assert pkv.pos[1] == 0
    # Parked: every table entry points at the slot's own parking page.
    assert (pkv.table[1] == pkv.n_pages + 1).all()


def test_radix_trie_match_caps_and_full_page_adoption():
    """A match never swallows the whole prompt (the suffix keeps >= 1
    token) and insert adopts only FULL pages."""
    alloc = kvpage.PageAllocator(8)
    trie = kvpage.RadixPrefixCache(alloc, page_tokens=4)
    prompt = np.arange(10, dtype=np.int32)        # 2 full pages + 2 tail
    pages = alloc.alloc(3)
    assert trie.insert(prompt, pages) == 2        # 10 // 4 full pages
    assert alloc.refcount(pages[0]) == 2          # trie holds a ref
    assert alloc.refcount(pages[2]) == 1          # tail page not adopted
    # Exact same prompt: depth cap (len-1)//4 = 2 -> both full pages hit.
    hit = trie.match(prompt)
    assert hit == pages[:2]
    assert trie.hits == 1
    for p in hit:
        alloc.decref(p)
    # An 8-token prompt may only match 1 page ((8-1)//4) even though
    # its first 8 tokens are 2 cached pages: the seated request must
    # own the page its write cursor starts in.
    hit = trie.match(prompt[:8])
    assert hit == pages[:1]
    for p in hit:
        alloc.decref(p)


# --------------------------------------------------------------------------
# serving parity: serve_paged_greedy vs serve_greedy


def _serve_setup():
    cfg = tfm.tiny_config(vocab=61, d_model=48, n_heads=4, n_layers=2,
                          d_ff=96, max_seq=96)
    params = tfm.init_params(jax.random.key(0), cfg)
    ks = jax.random.split(jax.random.key(3), 7)
    prompts = [np.asarray(jax.random.randint(ks[i], (l,), 0, cfg.vocab),
                          np.int32)
               for i, l in enumerate([5, 9, 3, 12, 7, 6, 10])]
    return cfg, params, prompts


# Tier-1 (`-m 'not slow'`) keeps ONE end-to-end serve parity case
# ([1-int8kv], the disagg-relevant configuration); the other three
# variants and the serving-heavy tests below run in `make paged-check`,
# which invokes this file unfiltered. Each full serve jit-compiles its
# own step functions (~4-7s on this box), and the tier-1 sweep runs
# against a hard wall-clock budget.
@pytest.mark.parametrize("kv_int8", [
    pytest.param(False, marks=pytest.mark.slow),
    True,
], ids=["bf16", "int8kv"])
@pytest.mark.parametrize("chunk", [
    1,
    pytest.param(4, marks=pytest.mark.slow),
])
def test_serve_paged_bit_equals_fixed(kv_int8, chunk):
    """The §19 acceptance bar: on identical schedules the paged server
    reproduces fixed-slot serve_greedy BIT for BIT — bf16 and int8
    caches, chunked dispatch included."""
    cfg, params, prompts = _serve_setup()
    fixed = serving.serve_greedy(params, cfg, prompts, 6, n_slots=3,
                                 max_len=32, family=tfm, chunk=chunk,
                                 kv_int8=kv_int8)
    paged = serving.serve_paged_greedy(params, cfg, prompts, 6, n_slots=3,
                                       max_len=32, family=tfm, chunk=chunk,
                                       kv_int8=kv_int8, page_tokens=8)
    for i, (f, p) in enumerate(zip(fixed, paged)):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(p),
                                      err_msg=f"request {i}")
    assert paged.metrics.preemptions == 0
    # The HBM claim in miniature: 7 staggered requests through 3 slots
    # peak well under the fixed-equivalent 12 pages (3 slots * 4 pages).
    assert 0 < paged.metrics.pages_hwm < 12


@pytest.mark.slow
def test_preempt_then_resume_byte_exact():
    """A pool too small for three live requests forces a page-pressure
    preemption; the victim requeues UNCHARGED and replays onto the same
    deterministic page placement — outputs stay bit-equal to the
    unpressured fixed-slot run."""
    cfg, params, prompts = _serve_setup()
    fixed = serving.serve_greedy(params, cfg, prompts, 6, n_slots=3,
                                 max_len=32, family=tfm)
    paged = serving.serve_paged_greedy(params, cfg, prompts, 6, n_slots=3,
                                       max_len=32, family=tfm,
                                       page_tokens=8, n_pages=6)
    for i, (f, p) in enumerate(zip(fixed, paged)):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(p),
                                      err_msg=f"request {i}")
    assert paged.metrics.preemptions >= 1
    assert paged.metrics.requeues == 0            # preemption != failure


@pytest.mark.slow
def test_pool_drains_to_zero_after_serving():
    cfg, params, prompts = _serve_setup()
    out = serving.serve_paged_greedy(params, cfg, prompts, 4, n_slots=2,
                                     max_len=32, family=tfm, page_tokens=8,
                                     return_paged_state=True)
    assert out.paged_state.alloc.used_count == 0
    assert out.paged_state.alloc.free_count == out.paged_state.n_pages


@pytest.mark.parametrize("which", ["fixed", "paged"])
@pytest.mark.slow
def test_typed_rejection_replaces_assert(which):
    """Satellite: an over-long request degrades to RequestRejected at
    its output index (reason exceeds_max_len) in BOTH servers; the
    other requests are served normally and stay path-equal."""
    cfg, params, prompts = _serve_setup()
    prompts = [prompts[0],
               np.zeros((30,), np.int32),         # 30 + 6 + 1 > 32
               prompts[1]]
    serve = (serving.serve_greedy if which == "fixed"
             else serving.serve_paged_greedy)
    out = serve(params, cfg, prompts, 6, n_slots=2, max_len=32, family=tfm)
    assert isinstance(out[1], serving.RequestRejected)
    assert out[1].reason == "exceeds_max_len"
    assert out.metrics.rejections == 1
    assert out.metrics.rejection_reasons == {"exceeds_max_len": 1}
    want = serving.serve_greedy(params, cfg, [prompts[0], prompts[2]], 6,
                                n_slots=2, max_len=32, family=tfm)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(out[2]), np.asarray(want[1]))


def test_page_budget_rejection():
    """The paged-only admission bound: a request whose page need
    exceeds the whole pool is rejected up front (it could never be
    seated even alone), not preempt-looped."""
    cfg, params, prompts = _serve_setup()
    out = serving.serve_paged_greedy(params, cfg, [prompts[3]], 6,
                                     n_slots=1, max_len=32, family=tfm,
                                     page_tokens=8, n_pages=2)
    assert isinstance(out[0], serving.RequestRejected)
    assert out[0].reason == "exceeds_page_budget"


@pytest.mark.slow
def test_streaming_on_token_matches_outputs():
    """on_token fires per consumed token, prefill token included; the
    concatenated stream equals the returned output's generated tail."""
    cfg, params, prompts = _serve_setup()
    streams = {}
    out = serving.serve_paged_greedy(
        params, cfg, prompts[:4], 5, n_slots=2, max_len=32, family=tfm,
        page_tokens=8,
        on_token=lambda rid, tok: streams.setdefault(rid, []).append(tok))
    for rid in range(4):
        got = np.asarray(out[rid])[len(prompts[rid]):]
        np.testing.assert_array_equal(np.asarray(streams[rid], np.int32),
                                      got)


# --------------------------------------------------------------------------
# radix prefix sharing end to end


@pytest.mark.parametrize("kv_int8", [False, True], ids=["bf16", "int8kv"])
@pytest.mark.slow
def test_prefix_hit_reuses_shared_pages(kv_int8):
    """The acceptance assertion: requests sharing a long system prompt
    re-use >= the shared prefix's full-page count from the radix cache,
    and the hit-path outputs are deterministic (two identical serves
    agree bit for bit)."""
    cfg, params, _ = _serve_setup()
    rng = np.random.default_rng(11)
    system = rng.integers(0, cfg.vocab, 20).astype(np.int32)  # 2 full pages
    prompts = [np.concatenate([system,
                               rng.integers(0, cfg.vocab, 4 + i)
                               .astype(np.int32)])
               for i in range(3)]

    def serve():
        return serving.serve_paged_greedy(
            params, cfg, prompts, 4, n_slots=1, max_len=40, family=tfm,
            page_tokens=8, kv_int8=kv_int8, prefix_cache=True)

    out = serve()
    # 1 slot -> strictly sequential: requests 1 and 2 both hit the
    # system prefix request 0 inserted. 20 tokens / 8 = 2 full pages.
    assert out.metrics.prefix_hits >= 2
    assert out.metrics.prefix_pages_reused >= 2 * 2
    again = serve()
    for a, b in zip(out, again):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_prefix_cold_path_unchanged():
    """prefix_cache=True with no shareable history (distinct prompts,
    first pass) must not change cold outputs: still bit-equal to the
    fixed-slot server."""
    cfg, params, prompts = _serve_setup()
    fixed = serving.serve_greedy(params, cfg, prompts[:4], 5, n_slots=2,
                                 max_len=32, family=tfm)
    paged = serving.serve_paged_greedy(params, cfg, prompts[:4], 5,
                                       n_slots=2, max_len=32, family=tfm,
                                       page_tokens=8, prefix_cache=True)
    assert paged.metrics.prefix_hits == 0
    for f, p in zip(fixed, paged):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(p))


@pytest.mark.slow
def test_slo_gate_off_by_default_and_defers_under_target(monkeypatch):
    """Unset knobs = no gate (bit-equal schedules, asserted throughout
    this file); an impossible TTFT target defers refills but never
    starves an empty server, so the batch still completes."""
    cfg, params, prompts = _serve_setup()
    assert serving._slo_admit_targets(None) == (None, None)
    monkeypatch.setenv("ACX_SERVE_ADMIT_TTFT_MS", "0.000001")
    out = serving.serve_paged_greedy(params, cfg, prompts[:4], 4,
                                     n_slots=2, max_len=32, family=tfm,
                                     page_tokens=8)
    want = serving.serve_greedy(params, cfg, prompts[:4], 4, n_slots=2,
                                max_len=32, family=tfm)
    for f, p in zip(want, out):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(p))
    assert out.metrics.slo_deferrals >= 1
