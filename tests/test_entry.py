"""Driver entry points: entry() compiles, dryrun_multichip(8) runs a full
distributed step on the virtual mesh."""

import importlib.util
import os

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(REPO, "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_jits():
    mod = _load()
    fn, (params, tokens) = mod.entry()
    # Compile-check on a small shape variant to keep the test fast: the
    # driver itself compiles the full flagship shapes.
    logits = jax.jit(fn)(params, tokens[:, :32])
    assert logits.shape == (tokens.shape[0], 32, 50257)


def test_dryrun_multichip_8():
    mod = _load()
    mod.dryrun_multichip(8)


def test_dryrun_multichip_2():
    mod = _load()
    mod.dryrun_multichip(2)
