"""Interpret-mode parity for the Pallas flash-decode kernel.

ops/flash_decode.py runs the SAME code path interpreted on CPU that it
compiles on TPU (pallas_call interpret mode), so these tests pin the
kernel's math — GQA rows, window masking, per-slot positions, in-register
int8 dequant — against :func:`dense_decode_attend`, the dense reference
every decode path used before the kernel existed. ``block_k=32`` on a
96-long cache forces multiple K/V blocks so the unmasked/straddle loop
split and the block-skip bounds are actually exercised (the default
block_k would cover the toy cache with one block).

The block-skip test is the length-aware claim itself: tail blocks past
``pos + W`` are filled with NaN — if the kernel read them, the online
softmax would poison every output lane.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_acx_tpu.models.decoding import (dense_decode_attend,
                                         grouped_decode_attend)
from mpi_acx_tpu.ops import attention
from mpi_acx_tpu.ops.flash_decode import (_fit_block_k, auto_decode_attend,
                                          flash_decode_attend,
                                          select_decode_attend)
from mpi_acx_tpu.ops.kvquant import kv_quant

B, Hkv, D, MAX_LEN, BLOCK_K = 3, 2, 16, 96, 32


def _case(n_rep, W, kind, seed=0):
    """(q, kc, vc, tol): bf16 arrays or f32 q + (codes, scales) caches."""
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, W, Hkv * n_rep, D))
    kc = rng.standard_normal((B, MAX_LEN, Hkv, D))
    vc = rng.standard_normal((B, MAX_LEN, Hkv, D))
    if kind == "int8":
        # f32 q against (int8 codes, f32 scales) tuple caches; both
        # paths dequantize exactly, tolerance is accumulation order.
        q = jnp.asarray(q, jnp.float32)
        kc = kv_quant(jnp.asarray(kc, jnp.float32))
        vc = kv_quant(jnp.asarray(vc, jnp.float32))
        return q, kc, vc, 2e-4
    q = jnp.asarray(q, jnp.bfloat16)
    kc = jnp.asarray(kc, jnp.bfloat16)
    vc = jnp.asarray(vc, jnp.bfloat16)
    return q, kc, vc, 4e-2


@pytest.mark.parametrize("kind", ["bf16", "int8"])
@pytest.mark.parametrize("posmode", ["scalar", "vector"])
@pytest.mark.parametrize("n_rep", [1, 4])
@pytest.mark.parametrize("W", [1, 4])
def test_flash_matches_dense(W, n_rep, posmode, kind):
    q, kc, vc, tol = _case(n_rep, W, kind)
    if posmode == "scalar":
        pos = 41                                  # mid-straddle-block
    else:
        # Slot 0 empty-but-self, slot at a block edge, slot at the end.
        pos = jnp.array([0, 63, MAX_LEN - W], jnp.int32)
    ref = dense_decode_attend(q, kc, vc, pos, MAX_LEN, n_rep)
    out = flash_decode_attend(q, kc, vc, pos, MAX_LEN, n_rep,
                              block_k=BLOCK_K)
    assert out.shape == ref.shape == (B, W, Hkv * n_rep * D)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("kind", ["bf16", "int8"])
def test_block_skip_ignores_dead_tail(kind):
    """Cache rows past pos+W never cross the DMA: NaN-poison them and
    the output must be bit-identical to the clean cache's."""
    W, n_rep, pos = 2, 2, 40                      # live rows: 0..41
    q, kc, vc, tol = _case(n_rep, W, kind)
    live = 64                                     # first dead BLOCK col

    def poison(c):
        if isinstance(c, tuple):
            codes, scales = c
            codes = codes.at[:, live:].set(127)
            scales = scales.at[:, live:].set(jnp.nan)
            return codes, scales
        return c.at[:, live:].set(jnp.nan)

    clean = flash_decode_attend(q, kc, vc, pos, MAX_LEN, n_rep,
                                block_k=BLOCK_K)
    dirty = flash_decode_attend(q, poison(kc), poison(vc), pos, MAX_LEN,
                                n_rep, block_k=BLOCK_K)
    assert not np.isnan(np.asarray(dirty, np.float32)).any()
    np.testing.assert_array_equal(np.asarray(clean, np.float32),
                                  np.asarray(dirty, np.float32))


def test_per_slot_positions_match_solo_runs():
    """Vector-pos output for slot b equals a scalar-pos run at pos[b] —
    the continuous-batching contract (serving.py's bit-equality claim
    rides on it)."""
    q, kc, vc, tol = _case(2, 1, "bf16")
    pos = jnp.array([5, 50, 90], jnp.int32)
    batched = flash_decode_attend(q, kc, vc, pos, MAX_LEN, 2,
                                  block_k=BLOCK_K)
    for b in range(B):
        solo = flash_decode_attend(q[b:b + 1], kc[b:b + 1], vc[b:b + 1],
                                   int(pos[b]), MAX_LEN, 2,
                                   block_k=BLOCK_K)
        np.testing.assert_array_equal(np.asarray(batched[b:b + 1]),
                                      np.asarray(solo))


def test_select_decode_attend_dispatch():
    """The select_attention idiom: False -> dense, True -> kernel,
    None -> auto (dense on CPU — interpret overhead loses there)."""
    assert select_decode_attend(False) is dense_decode_attend
    assert select_decode_attend(True) is flash_decode_attend
    assert select_decode_attend(None) is auto_decode_attend
    q, kc, vc, _ = _case(1, 1, "bf16")
    np.testing.assert_array_equal(
        np.asarray(grouped_decode_attend(q, kc, vc, 7, MAX_LEN, 1),
                   np.float32),
        np.asarray(dense_decode_attend(q, kc, vc, 7, MAX_LEN, 1),
                   np.float32))


def test_fit_block_k_prefers_mosaic_tiles():
    assert _fit_block_k(4096, 256) == 256
    assert _fit_block_k(384, 256) == 128          # 128-multiple beats 192
    assert _fit_block_k(96, 256) == 96
    assert _fit_block_k(96, 32) == 32


def test_fit_blocks_fallback_warns_once_and_matches_reference():
    """S=648 has no 128-multiple divisor: flash_attention must fall back
    to the dense reference with ONE warning, not crash (the old
    AssertionError path)."""
    attention._fallback_warned.clear()
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 648, 2, 16)),
                           jnp.float32) for _ in range(3))
    with pytest.warns(RuntimeWarning, match="dense reference"):
        out = attention.flash_attention(q, k, v)
    ref = attention.attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    attention._fallback_warned.clear()            # shared one-time set
    with pytest.warns(RuntimeWarning, match="dense reference"):
        o_lse, lse = attention.flash_attention_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(o_lse), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert lse.shape == (1, 2, 648)

    # One-time: the same shape does not warn again.
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        attention.flash_attention(q, k, v)
