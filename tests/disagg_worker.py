"""Worker for the disagg-check legs: one role-split serving rank.

Launched by acxrun (``acxrun -np 3 -transport socket python3
tests/disagg_worker.py`` with ``ACX_ROLE=prefill,decode,decode``):
every rank runs the same deterministic workload through
``serve_disagg_greedy``, which dispatches on this rank's role — the
prefill rank ships per-layer KV handoffs, the decode ranks splice,
generate, and then each VERIFIES its outputs bit-for-bit against a
local monolithic ``serve_greedy(..., kv_int8=True)`` of the same
requests. Prints ``DISAGG_OK`` / ``DISAGG_SHIPPED`` plus one
``DISAGG_ROW {json}`` line per rank (the bench child parses these).

Under the chaos leg the prefill rank is killed mid-handoff and
respawned by the acx_chaos supervisor; the respawn re-runs this script
from rid 0 — re-shipping is idempotent (decode discards duplicates by
rid) — and the decode ranks requeue the torn handoff UNCHARGED.

Knobs: ACX_DISAGG_OVERLAP=0 ships only after the full prompt pass (the
bench baseline), ACX_DISAGG_PREFILL_INT8=1 uses the quantize-at-compute
prefill cache variant, ACX_DISAGG_REQS scales the request count, and
ACX_DISAGG_BIG=1 switches to a wider model + longer prompts so the
exposed-ship time (the wire cost per-layer overlap hides) is well above
clock noise for the bench A/B.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The axon sitecustomize pins the tunnel platform via jax.config, which
# wins over the env var; pin back (the bench.py r05 lesson).
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from mpi_acx_tpu import runtime  # noqa: E402
from mpi_acx_tpu.models import transformer as tfm  # noqa: E402
from mpi_acx_tpu.models.disagg import fleet_roles, serve_disagg_greedy  # noqa: E402
from mpi_acx_tpu.models.serving import make_server_fns, serve_greedy  # noqa: E402


def main():
    overlap = os.environ.get("ACX_DISAGG_OVERLAP", "1") != "0"
    prefill_int8 = os.environ.get("ACX_DISAGG_PREFILL_INT8", "0") == "1"
    n_reqs = int(os.environ.get("ACX_DISAGG_REQS", "6"))
    big = os.environ.get("ACX_DISAGG_BIG", "0") == "1"

    if big:
        # Wider heads + near-bucket prompts: ~1 MiB of codes+scales per
        # handoff, so the exposed-ship time is milliseconds, not noise.
        cfg = tfm.tiny_config(d_model=256, n_heads=8, max_seq=1024)
        lens = [450, 380, 500, 410, 470, 360]
        max_len, n_slots, chunk = 576, 2, 1
    else:
        cfg = tfm.tiny_config()
        lens = [5, 11, 3, 17, 8, 13, 7, 21, 4, 9]
        max_len, n_slots, chunk = 64, 2, 1
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, cfg.vocab, size=lens[i % len(lens)])
               .astype(np.int32) for i in range(n_reqs)]
    n_new = [3 + (i % 5) for i in range(n_reqs)]

    rt = runtime.Runtime()
    # A torn fleet (killed peer before heartbeat detection) must surface
    # as a typed error, not an infinite block on a posted descriptor.
    rt.set_deadline(60_000)
    roles = fleet_roles(rt.size)
    role = roles[rt.rank]

    fns = None
    if role == "decode":
        fns = make_server_fns(params, cfg, tfm, chunk=chunk, kv_int8=True)

    t0 = time.perf_counter()
    batch = serve_disagg_greedy(
        params, cfg, prompts, n_new, n_slots=n_slots, max_len=max_len,
        chunk=chunk, server_fns=fns, rt=rt, overlap=overlap,
        prefill_kv_int8=prefill_int8)
    wall = time.perf_counter() - t0

    if role == "prefill":
        print(f"DISAGG_SHIPPED rank={rt.rank} n={len(prompts)}",
              flush=True)
        print("DISAGG_ROW " + json.dumps({
            "rank": rt.rank, "role": "prefill", "wall_s": round(wall, 4),
            "overlap": overlap}), flush=True)
    else:
        mono = serve_greedy(params, cfg, prompts, n_new, n_slots=n_slots,
                            max_len=max_len, chunk=chunk, kv_int8=True,
                            server_fns=fns)
        m = batch.metrics
        mine = [r.rid for r in m.per_request]
        assert mine, "decode rank owns no requests"
        for rid in mine:
            assert batch[rid] is not None, f"request {rid} unserved"
            np.testing.assert_array_equal(
                batch[rid], mono[rid],
                err_msg=f"rank {rt.rank} request {rid} disagg != mono")
        wire = sum(h.wire_bytes for h in m.handoffs)
        ship_wall = sum(h.pickup_s for h in m.handoffs) or 1e-9
        exposes = sorted(h.expose_s for h in m.handoffs)
        print(f"DISAGG_OK rank={rt.rank} rids={mine} "
              f"requeues={m.requeues} peer_requeues={m.peer_requeues}",
              flush=True)
        print("DISAGG_ROW " + json.dumps({
            "rank": rt.rank, "role": "decode", "wall_s": round(wall, 4),
            "overlap": overlap, "prefill_int8": prefill_int8,
            "requests": len(mine),
            "ttft_p50_s": round(m.ttft_p50_s, 6),
            "pickup_p50_s": round(m.handoff_pickup_p50_s, 6),
            "expose_p50_s": round(exposes[len(exposes) // 2], 6),
            "handoff_wire_bytes": wire,
            "handoff_gbps": round(wire / ship_wall / 1e9, 4),
            "requeues": m.requeues,
            "peer_requeues": m.peer_requeues}), flush=True)
    rt.barrier()
    rt.finalize()


if __name__ == "__main__":
    main()
