"""Request-journey plane (mpi_acx_tpu/reqlog.py, tools/acx_request.py,
the Prometheus metrics export — docs/DESIGN.md §20).

Three layers, bottom up: the per-rank JSONL writer (armed/disabled
latch, init-line schema, span offset, never-raise discipline), the
offline journey tool (wall-clock fallback merge, phase attribution,
burn rate, the --check CI gate), and the Prometheus text exposition
round-trip through the native registry.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REQUEST = os.path.join(REPO, "tools", "acx_request.py")


# -- reqlog writer ----------------------------------------------------------


@pytest.fixture
def rl(monkeypatch):
    """A clean reqlog latch before AND after: the armed/disabled state is
    process-global, so tests must never leak it into the rest of the
    suite (serving tests would otherwise start journaling)."""
    monkeypatch.delenv("ACX_REQLOG", raising=False)
    from mpi_acx_tpu import reqlog
    reqlog._reset_for_tests()
    yield reqlog
    reqlog._reset_for_tests()


def test_reqlog_disabled_without_env(rl, tmp_path):
    """With ACX_REQLOG unset, emit is a cheap no-op: no file, falsy
    return, and the disabled verdict is latched."""
    assert not rl.enabled()
    assert rl.emit("admit", 0, reason="x") is False
    assert not list(tmp_path.glob("*.reqlog.jsonl"))


def test_reqlog_init_line_and_span_offset(rl, tmp_path, monkeypatch):
    """The armed writer opens <prefix>.rank<r>.reqlog.jsonl with a
    schema-stamped init line (paired clock readings for the offline
    wall fallback), then one line per event with span = rid + 1 — the
    PR-8 app span offset — and no rid/span on rid-less events."""
    monkeypatch.setenv("ACX_REQLOG", str(tmp_path / "run"))
    monkeypatch.setenv("ACX_RANK", "3")
    monkeypatch.setenv("ACX_ROLE", "decode")
    assert rl.emit("admit", 7, queued=2) is True
    assert rl.emit("decode_step", step=1, dt_s=0.5) is True

    path = tmp_path / "run.rank3.reqlog.jsonl"
    assert path.exists()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    init, admit, step = lines

    assert init["init"] is True and init["schema"] == 1
    assert init["rank"] == 3 and init["role"] == "decode"
    assert init["pid"] == os.getpid()
    assert init["clock"] in ("native", "mono")
    assert init["t_mono_ns"] >= 0 and init["t_wall_ms"] > 0

    assert admit["k"] == "admit" and admit["rid"] == 7
    assert admit["span"] == 8  # rid + 1
    assert admit["queued"] == 2
    assert step["k"] == "decode_step" and "rid" not in step
    assert "span" not in step
    assert step["t_mono_ns"] >= admit["t_mono_ns"]


def test_reqlog_kinds_are_vocabulary(rl):
    """Every kind the emitters may use is in the frozen vocabulary the
    audit rule pins (a free-form kind would silently fail offline
    decode)."""
    assert "admit" in rl.KINDS and "finish" in rl.KINDS
    assert len(rl.KINDS) == 17


def test_reqlog_emit_never_raises(rl, tmp_path, monkeypatch):
    """The never-raise discipline: a dead file handle (rank torn down
    mid-serve) turns emit into a falsy drop, not an exception in the
    serving loop."""
    monkeypatch.setenv("ACX_REQLOG", str(tmp_path / "run"))
    assert rl.emit("queue", 0) is True
    rl._state.close()  # yank the file out from under the writer
    assert rl.emit("finish", 0) is False  # dropped, no raise


# -- acx_request.py: merge, attribution, burn, --check ----------------------


def _tool(*argv):
    return subprocess.run([sys.executable, REQUEST, *argv],
                          capture_output=True, text=True, timeout=120)


def _write_reqlog(path, rank, wall0_ms, events, clock="mono"):
    lines = [json.dumps({"init": True, "schema": 1, "rank": rank,
                         "pid": 1, "role": "", "clock": clock,
                         "t_mono_ns": 0, "t_wall_ms": wall0_ms})]
    lines += [json.dumps(e) for e in events]
    path.write_text("\n".join(lines) + "\n")


def _ev(k, t_ms, rid=None, **fields):
    e = {"k": k, "t_mono_ns": int(t_ms * 1e6)}
    if rid is not None:
        e["rid"] = rid
        e["span"] = rid + 1
    e.update(fields)
    return e


def _two_rank_journey(tmp_path):
    """One rid whose journey spans two mono-clock ranks. Rank 1's
    process started 2 ms after rank 0 (wall readings 1000 vs 1002), so
    the wall fallback must shift rank 1 by +2 ms; the legs below are
    chosen so each phase is distinct: queue 1 ms, prefill 20 ms, ship
    1 ms (cross-rank: prefill_end at rank-0 22 ms = fleet 22 ms, seat
    at rank-1 local 21 ms = fleet 23 ms), decode 10 ms (2 stream
    events x 1 token x 5 ms)."""
    _write_reqlog(tmp_path / "run.rank0.reqlog.jsonl", 0, 1000, [
        _ev("admit", 1, rid=0),
        _ev("queue", 1, rid=0, depth=0),
        _ev("prefill_start", 2, rid=0, bucket=8),
        _ev("prefill_end", 22, rid=0),
    ])
    _write_reqlog(tmp_path / "run.rank1.reqlog.jsonl", 1, 1002, [
        _ev("seat", 21, rid=0, slot=0),
        _ev("stream", 23, rid=0, n=1, ttft_s=0.024),
        _ev("stream", 28, rid=0, n=1, itl_s=0.005),
        _ev("stream", 33, rid=0, n=1, itl_s=0.005),
        _ev("finish", 33, rid=0, new_tokens=3),
        _ev("reject", 40, rid=1, reason="queue_full"),
    ])
    return [str(tmp_path / f"run.rank{r}.reqlog.jsonl") for r in (0, 1)]


def test_request_wall_fallback_attribution(tmp_path):
    """Without traces the init lines' paired (t_mono_ns, t_wall_ms)
    anchor each rank; the cross-rank journey reconstructs and each
    phase lands where the synthetic timeline put it."""
    inputs = _two_rank_journey(tmp_path)
    out = tmp_path / "report.json"
    r = _tool("--json", str(out), *inputs)
    assert r.returncode == 0, r.stdout + r.stderr

    summary = json.loads(r.stdout)
    assert summary["ranks"] == [0, 1]
    assert summary["skew_source"] == {"0": "wall", "1": "wall"}
    assert summary["rids"] == 2 and summary["rejected"] == 1
    assert summary["reconstructed"] == 1  # rejected rid 1 not a candidate
    assert summary["reconstructed_rate"] == 1.0
    assert summary["unknown_kinds"] == {}
    assert summary["dominant_phase"] == "prefill"

    rep = json.loads(out.read_text())
    ph = rep["phase_breakdown"]
    assert abs(ph["queue"]["total_s"] - 0.001) < 1e-6
    assert abs(ph["prefill"]["total_s"] - 0.020) < 1e-6
    # ship = prefill_end (fleet 22 ms) -> seat (local 21 + 2 ms skew)
    assert abs(ph["ship"]["total_s"] - 0.001) < 1e-6
    # decode from the stream events (2 itl x 1 token x 5 ms), NOT the
    # seat->finish window (12 ms) that holds interference.
    assert abs(ph["decode"]["total_s"] - 0.010) < 1e-6


def test_request_dominance_ignores_queue_backlog(tmp_path):
    """A request that queued 500 ms behind a busy fleet but was served
    in 20 ms must NOT report queue as dominant: queue is the symptom of
    a slow service leg, so dominance is judged over service phases
    only."""
    _write_reqlog(tmp_path / "run.rank0.reqlog.jsonl", 0, 1000, [
        _ev("admit", 0, rid=0),
        _ev("prefill_start", 500, rid=0),
        _ev("prefill_end", 515, rid=0),
        _ev("seat", 516, rid=0, slot=0),
        _ev("finish", 520, rid=0),
    ])
    r = _tool(str(tmp_path / "run.rank0.reqlog.jsonl"))
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout)
    assert summary["dominant_phase"] == "prefill"


def test_request_burn_rate_and_waterfall(tmp_path):
    """With a TTFT target below the observed TTFT every finished
    request violates: burn = violation_fraction / budget. The waterfall
    renders the slowest journey with the phase glyph legend."""
    inputs = _two_rank_journey(tmp_path)
    r = _tool("--ttft-ms", "5", "--budget", "0.01", "--waterfall", "1",
              *inputs)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.splitlines()[0])
    burn = summary["burn"]
    assert burn["ttft_target_s"] == 0.005
    assert burn["windows"] and burn["windows"][0]["violations"] == 1
    assert burn["max_burn"] == 100.0  # 1.0 violation fraction / 1% budget
    assert "waterfall" in r.stdout and "rid    0" in r.stdout

    # ...and with a generous target the same journeys burn nothing.
    r2 = _tool("--ttft-ms", "60000", *inputs)
    s2 = json.loads(r2.stdout)
    assert s2["burn"]["max_burn"] == 0.0


def test_request_burn_section_present_without_targets(tmp_path):
    """No targets -> the burn section still exists with null burn (so
    --check can assert its presence instead of silently skipping)."""
    inputs = _two_rank_journey(tmp_path)
    r = _tool(*inputs)
    assert r.returncode == 0, r.stdout + r.stderr
    burn = json.loads(r.stdout)["burn"]
    assert burn["ttft_target_s"] is None and burn["max_burn"] is None


def test_request_check_gate(tmp_path):
    """--check passes on the healthy fleet, fails (exit 1) when the
    expected dominant phase disagrees, and fails on an unknown journey
    kind with a decode-table warning."""
    inputs = _two_rank_journey(tmp_path)
    assert _tool("--check", "--min-reconstructed", "0.95",
                 *inputs).returncode == 0

    r = _tool("--check", "--expect-dominant", "ship", *inputs)
    assert r.returncode == 1
    assert "dominant phase 'prefill', expected 'ship'" in r.stderr

    # An event kind the decode table does not know: warned, and fatal
    # under --check (schema drift must not pass CI).
    extra = tmp_path / "run.rank2.reqlog.jsonl"
    _write_reqlog(extra, 2, 1000, [_ev("warp", 1, rid=5)])
    r = _tool("--check", *inputs, str(extra))
    assert r.returncode == 1
    assert "unknown journey kind 'warp'" in r.stderr


def test_request_check_fails_on_torn_journeys(tmp_path):
    """Journeys missing their finish (rank died mid-serve) drop the
    reconstruction rate below the bar -> --check exits 1."""
    _write_reqlog(tmp_path / "run.rank0.reqlog.jsonl", 0, 1000, [
        _ev("admit", 1, rid=0),
        _ev("prefill_start", 2, rid=0),
    ])
    r = _tool("--check", str(tmp_path / "run.rank0.reqlog.jsonl"))
    assert r.returncode == 1
    assert "reconstructed 0/1" in r.stderr


def test_request_torn_tail_tolerated(tmp_path):
    """A torn final line (rank killed mid-write) is skipped and counted,
    never fatal — the tseries reader discipline."""
    path = tmp_path / "run.rank0.reqlog.jsonl"
    _write_reqlog(path, 0, 1000, [
        _ev("admit", 1, rid=0),
        _ev("prefill_start", 2, rid=0),
        _ev("prefill_end", 3, rid=0),
        _ev("seat", 4, rid=0, slot=0),
        _ev("finish", 9, rid=0),
    ])
    with open(path, "a") as f:
        f.write('{"k":"fini')  # torn mid-write
    r = _tool(str(path))
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout)
    assert summary["torn_lines"] == {"0": 1}
    assert summary["reconstructed_rate"] == 1.0


def test_request_no_reqlog_inputs_exits_2(tmp_path):
    """Only traces (or nothing decodable) -> exit 2 with a clear
    message, distinct from a failed --check."""
    r = _tool(str(tmp_path / "run.rank0.trace.json"))
    assert r.returncode == 2
    assert "no .reqlog.jsonl inputs" in r.stderr


# -- Prometheus text exposition round-trip ----------------------------------


@pytest.fixture(scope="module")
def _built_lib():
    r = subprocess.run(["make", "-C", REPO, "lib"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


def test_metrics_prom_round_trip(_built_lib):
    """Runtime.metrics_prom() is valid Prometheus 0.0.4 text and
    round-trips the whole registry: every counter/gauge from
    rt.metrics() appears as acx_<name> under a # TYPE line, every
    histogram becomes a cumulative _bucket{le=...} series ending at
    +Inf with matching _sum/_count."""
    prog = textwrap.dedent("""
        import re
        import numpy as np
        from mpi_acx_tpu import runtime
        rt = runtime.Runtime()
        src = np.arange(32, dtype=np.float32)
        dst = np.zeros(32, dtype=np.float32)
        s = rt.isend_enqueue(src, dest=0, tag=9)
        r = rt.irecv_enqueue(dst, source=0, tag=9)
        rt.wait(r); rt.wait(s)
        m = rt.metrics()
        text = rt.metrics_prom()
        rt.finalize()

        types, values = {}, {}
        sample_re = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(\\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+="[^"]*")*\\})?'
            r' (-?[0-9.eE+]+|\\+Inf|NaN)$')
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                _h, _t, name, kind = line.split()
                assert kind in ("counter", "gauge", "histogram"), line
                assert name not in types, f"duplicate TYPE for {name}"
                types[name] = kind
            elif line.startswith("#"):
                continue
            else:
                mo = sample_re.match(line)
                assert mo, f"malformed sample line: {line!r}"
                values.setdefault(mo.group(1), []).append(
                    (mo.group(2) or "", float(mo.group(4))))

        # Every sample belongs to a declared family (histogram series
        # hang off their family name).
        for name in values:
            fam = re.sub(r'_(bucket|sum|count)$', '', name)
            assert name in types or fam in types, f"undeclared {name}"

        # Round trip: every registry counter/gauge name...
        for cname in m["counters"]:
            pname = "acx_" + cname
            assert types.get(pname) in ("counter", "gauge"), pname
            assert pname in values, pname
        # ...and every histogram, as a well-formed cumulative series.
        for hname in m["histograms"]:
            pname = "acx_" + hname
            assert types.get(pname) == "histogram", pname
            buckets = values[pname + "_bucket"]
            les = [lbl for lbl, _v in buckets]
            assert les[-1] == '{le="+Inf"}', les
            counts = [v for _lbl, v in buckets]
            assert counts == sorted(counts), f"{pname} not cumulative"
            # count is loaded after the buckets, so a concurrent proxy
            # sample can only make it >=, never <.
            assert values[pname + "_count"][0][1] >= counts[-1]
            assert values[pname + "_sum"][0][1] >= 0
        # The derived utilization gauge rides along for scrapers.
        assert types.get("acx_proxy_util_pct") == "gauge"
        print("PROM_OK counters=%d hists=%d" %
              (len(m["counters"]), len(m["histograms"])))
    """)
    env = dict(os.environ)
    env["ACX_METRICS"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PROM_OK" in r.stdout


# -- make target ------------------------------------------------------------


@pytest.mark.slow
def test_makefile_request_check_target():
    """`make request-check` (wired into `make check`) goes green: the
    3-rank journaled fleet, the offline reconstruction gate, and the
    stalled-wire leg naming ship as the dominant phase."""
    r = subprocess.run(["make", "-C", REPO, "request-check"],
                       capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REQUEST CHECK PASSED" in r.stdout
