"""Survivable links through the Python stack (docs/DESIGN.md §9):
graceful drain (Runtime.drain / MPIX_Drain), recovery counters in
resilience/metrics snapshots, the serving loop's uncharged
requeue-on-peer-loss, and the chaos-ring itest's CRC/NAK/replay
counters landing in the metrics plane.

Native recovery state (ACX_RECONNECT_*, ACX_METRICS) seeds at first
library use and stays armed for the life of the process, so every armed
path runs in a SUBPROCESS (worker modes of this file, the test_fault.py
pattern). The serving-loop tests are pure JAX/CPU and run in-process.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _acxrun():
    from mpi_acx_tpu import runtime
    return runtime.acxrun_path()


def _chaos_ring():
    p = os.path.join(REPO, "build", "itests", "chaos-ring")
    if not os.path.exists(p):
        subprocess.run(["make", "-C", REPO, "itest"], check=True,
                       capture_output=True)
    return p


def _run(cmd, env_extra=None, timeout=120):
    env = dict(os.environ)
    env.pop("ACX_FAULT", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


# -- drain: bounded cancellation of in-flight ops ---------------------------


def test_drain_cancels_unmatched_loopback_recv():
    """An irecv nobody will ever match is cancelled by drain() within its
    timeout: drain returns 1, the waiter raises the typed error the
    cancel stamped, and a second drain of the now-empty table returns
    0."""
    r = _run([sys.executable, __file__, "--drain-loopback-worker"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DRAIN LOOPBACK OK" in r.stdout


def test_drain_unblocks_survivor_of_dead_peer():
    """acceptance: a rank dies mid-flight on the socket plane with the
    reconnect ladder pinned long (the op parks in RECOVERING, no failure
    detector will save the waiter) — the survivor's drain() cancels the
    op with a typed error and the process exits 0."""
    r = _run([_acxrun(), "-np", "2", "-transport", "socket",
              sys.executable, __file__, "--drain-socket-worker"],
             env_extra={"ACX_RECONNECT_MAX": "8",
                        "ACX_RECONNECT_BACKOFF_MS": "500"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DRAIN SOCKET OK" in r.stdout


# -- recovery counters reach every stats surface ----------------------------


def test_drain_while_recovering_no_double_count():
    """Drain during the RECOVERING window (peer lost, reconnect ladder
    pinned long so the link sits mid-recovery for seconds): the parked op
    cancels in bounded time with a typed error, a second drain returns 0,
    and drained_slots moves by exactly the first drain's count — no
    double-charge across repeated drains."""
    r = _run([_acxrun(), "-np", "2", "-transport", "socket",
              sys.executable, __file__, "--drain-recovering-worker"],
             env_extra={"ACX_RECONNECT_MAX": "8",
                        "ACX_RECONNECT_BACKOFF_MS": "500"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DRAIN RECOVERING OK" in r.stdout


def test_recovery_counters_in_metrics_registry():
    """Runtime.metrics() (the ACX_METRICS registry) and
    Runtime.recovery_stats() both expose the survivable-link counters by
    name, and a drained op ticks drained_slots in both."""
    r = _run([sys.executable, __file__, "--metrics-keys-worker"],
             env_extra={"ACX_METRICS": "1"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RECOVERY METRICS OK" in r.stdout


def test_chaos_ring_counters_reach_metrics_json(tmp_path):
    """chaos-ring under corrupt_frame heals (exit 0, byte-exact payloads)
    AND the healing is visible: the per-rank metrics dumps carry
    crc_rejects / naks_sent on the receiver and frames_replayed on the
    sender."""
    m = str(tmp_path / "m")
    r = _run([_acxrun(), "-np", "2", "-transport", "socket",
              "-fault", "corrupt_frame:rank=0:nth=2",
              _chaos_ring()],
             env_extra={"ACX_METRICS": m, "ACX_CHAOS_ROUNDS": "10"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "chaos-ring: OK" in r.stdout
    totals = {}
    for rank in (0, 1):
        d = json.loads((tmp_path / f"m.rank{rank}.metrics.json").read_text())
        for k, v in d["counters"].items():
            totals[k] = totals.get(k, 0) + v
    assert totals["crc_rejects"] >= 1, totals
    assert totals["naks_sent"] >= 1, totals
    assert totals["frames_replayed"] >= 1, totals


# -- replay_broken: budget overrun latches, next loss is terminal -----------


def test_replay_broken_latch_end_to_end():
    """Overrunning ACX_REPLAY_BUF_BYTES latches the link replay_broken:
    the gauge is live in Runtime.recovery_stats(), and when the peer then
    dies the parked op resolves to a typed error in bounded time (the
    broken link cannot heal, so it dead-latches instead of recovering)
    and the gauge settles back to 0."""
    r = _run([_acxrun(), "-np", "2", "-transport", "socket",
              sys.executable, __file__, "--replay-broken-worker"],
             env_extra={"ACX_REPLAY_BUF_BYTES": "64",
                        "ACX_RECONNECT_MAX": "2",
                        "ACX_RECONNECT_BACKOFF_MS": "50"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REPLAY BROKEN OK" in r.stdout
    # The runtime said so out loud, once, at latch time.
    assert "overran ACX_REPLAY_BUF_BYTES" in r.stderr, r.stderr


# -- serving: peer loss requeues without charging the retry budget ----------


def _tiny():
    import jax
    from mpi_acx_tpu.models import transformer as tfm
    cfg = tfm.tiny_config(vocab=61, d_model=48, n_heads=4, n_layers=2,
                          d_ff=96, max_seq=96)
    return cfg, tfm.init_params(jax.random.key(0), cfg), tfm


def _tiny_prompts(cfg, n=5):
    import jax
    ks = jax.random.split(jax.random.key(3), n)
    lens = [5, 9, 3, 7, 4]
    return [np.asarray(jax.random.randint(ks[i], (lens[i % len(lens)],),
                                          0, cfg.vocab), np.int32)
            for i in range(n)]


def test_serving_requeues_on_peer_loss_without_charge():
    """A step failure shaped like a lost rank (AcxPeerDeadError) requeues
    the in-flight requests WITHOUT spending their retry budget — proven
    by serving with max_request_retries=0, where a charged requeue would
    raise — sheds one slot to match the lost capacity, keeps serving,
    and still produces outputs bit-equal to the failure-free run."""
    from mpi_acx_tpu import runtime
    from mpi_acx_tpu.models import serving
    cfg, params, tfm = _tiny()
    prompts = _tiny_prompts(cfg)
    want = serving.serve_greedy(params, cfg, prompts, n_new=6, n_slots=3,
                                max_len=32, family=tfm)

    fns = serving.make_server_fns(params, cfg, tfm)
    prefill_fn, step_fn, scatter_fn, chunk, kv8, smp = fns
    calls = {"n": 0}

    def lossy_step(cache, tok, keys):
        calls["n"] += 1
        if calls["n"] == 2:
            raise runtime.AcxPeerDeadError(
                "tpu-acx: peer dead (error=20, source=1, tag=0)",
                runtime.ERR_PEER_DEAD, 1, 0)
        return step_fn(cache, tok, keys)

    got = serving.serve_greedy(
        params, cfg, prompts, n_new=6, n_slots=3, max_len=32, family=tfm,
        max_request_retries=0,
        server_fns=(prefill_fn, lossy_step, scatter_fn, chunk, kv8, smp))
    assert calls["n"] > 2, "peer loss fired before the loop finished"
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    assert got.metrics.peer_requeues >= 1, got.metrics
    assert got.metrics.slots_shed == 1, got.metrics
    # Uncharged: no victim's retry counter moved.
    assert all(r.retries == 0 for r in got.metrics.per_request), \
        got.metrics.per_request


def test_serving_charged_failure_still_bounded():
    """A non-peer-loss failure keeps the old contract: it charges the
    budget and a persistent one propagates past max_request_retries —
    the uncharged path must not have unbounded every failure."""
    from mpi_acx_tpu.models import serving
    cfg, params, tfm = _tiny()
    fns = serving.make_server_fns(params, cfg, tfm)

    def dead_step(cache, tok, keys):
        raise RuntimeError("wedged device")

    with pytest.raises(RuntimeError, match="max_request_retries"):
        serving.serve_greedy(
            params, cfg, _tiny_prompts(cfg, n=2), n_new=4, n_slots=2,
            max_len=32, family=tfm, max_request_retries=1,
            server_fns=(fns[0], dead_step, fns[2], fns[3], fns[4],
                        fns[5]))


# -- multihost: recovery-aware patience -------------------------------------


def test_recovery_budget_tracks_reconnect_ladder(monkeypatch):
    """recovery_budget_s mirrors the native dial ladder: explicit args
    are summed exponentially with the cap, and the env-seeded form reads
    the same knobs the transport does."""
    try:
        from mpi_acx_tpu.parallel import multihost
    except ImportError as e:  # package needs a newer jax here
        pytest.skip(f"parallel package unimportable here: {e}")
    # 5 attempts, 50ms base: waits 50+100+200+400 = 750ms + 1s margin.
    assert abs(multihost.recovery_budget_s(5, 50.0) - 1.75) < 1e-9
    # The per-wait cap bounds the tail: 4 waits of 100,200,400,500.
    assert abs(multihost.recovery_budget_s(5, 100.0, cap_ms=500.0)
               - 2.2) < 1e-9
    monkeypatch.setenv("ACX_RECONNECT_MAX", "3")
    monkeypatch.setenv("ACX_RECONNECT_BACKOFF_MS", "100")
    assert abs(multihost.recovery_budget_s() - 1.3) < 1e-9


# -- subprocess workers ----------------------------------------------------


def _drain_loopback_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    dst = np.zeros(8, dtype=np.int32)
    rv = rt.irecv_enqueue(dst, source=0, tag=11)  # never matched
    t0 = time.monotonic()
    n = rt.drain(200.0)
    assert time.monotonic() - t0 < 30
    assert n == 1, n
    try:
        rt.wait(rv)
        return 1  # a cancelled op must not look completed-clean
    except runtime.AcxTimeoutError:
        pass  # loopback peer is healthy, so the cancel stamps TIMEOUT
    assert rt.recovery_stats()["drained_slots"] >= 1
    assert rt.proxy_stats()["drained_slots"] >= 1  # merged view, same data
    assert rt.drain(50.0) == 0  # nothing left in flight
    print("DRAIN LOOPBACK OK")
    rt.finalize()
    return 0


def _drain_socket_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    if rt.rank == 1:
        time.sleep(0.1)  # let rank 0 post against us first
        sys.stdout.flush()
        os._exit(0)      # die mid-flight: no finalize, no goodbye
    dst = np.zeros(8, dtype=np.int32)
    rv = rt.irecv_enqueue(dst, source=1, tag=12)
    time.sleep(0.2)
    n = rt.drain(400.0)
    assert n >= 1, n
    try:
        rt.wait(rv)
        return 1
    except (runtime.AcxPeerDeadError, runtime.AcxTimeoutError):
        pass  # PEER_DEAD while the link recovers; TIMEOUT otherwise
    assert rt.recovery_stats()["drained_slots"] >= 1
    print("DRAIN SOCKET OK", flush=True)
    os._exit(0)  # peer is gone; skip the finalize barrier entirely


def _drain_recovering_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    if rt.rank == 1:
        # Exit only after rank 0's recv is provably posted (its token
        # send follows the irecv): an EOF with nothing in flight would
        # dead-latch immediately instead of opening a RECOVERING window.
        tok = np.zeros(1, dtype=np.int32)
        rt.wait(rt.irecv_enqueue(tok, source=0, tag=22))
        os._exit(0)      # die mid-flight: no finalize, no goodbye
    dst = np.zeros(8, dtype=np.int32)
    rv = rt.irecv_enqueue(dst, source=1, tag=21)
    tok = np.ones(1, dtype=np.int32)
    rt.wait(rt.isend_enqueue(tok, dest=1, tag=22))
    # Wait for the cut wire to be noticed and the link to enter RECOVERING
    # (the pinned 8 x 500ms ladder keeps the window open for ~10s).
    deadline = time.monotonic() + 10
    while rt.recovery_stats()["links_recovering"] < 1:
        assert time.monotonic() < deadline, rt.recovery_stats()
        time.sleep(0.01)
    base = rt.recovery_stats()["drained_slots"]
    t0 = time.monotonic()
    n1 = rt.drain(300.0)
    assert time.monotonic() - t0 < 30  # bounded, not a hang
    assert n1 == 1, n1
    try:
        rt.wait(rv)
        return 1  # a drained op must not look completed-clean
    except (runtime.AcxPeerDeadError, runtime.AcxTimeoutError):
        pass  # PEER_DEAD while the link recovers; TIMEOUT otherwise
    assert rt.drain(100.0) == 0  # nothing left: the cancel latched
    stats = rt.recovery_stats()
    assert stats["drained_slots"] == base + 1, stats
    print("DRAIN RECOVERING OK", flush=True)
    os._exit(0)  # peer is gone; skip the finalize barrier entirely


def _replay_broken_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    if rt.rank == 1:
        # Receive the sends that overrun rank 0's replay budget, tell
        # rank 0 we're done, then die without finalize — the broken
        # link's next loss must be terminal, not a heal.
        buf = np.zeros(256, dtype=np.int32)
        for i in range(3):
            rt.wait(rt.irecv_enqueue(buf, source=0, tag=31))
            assert buf[0] == i, (i, buf[0])
        tok = np.ones(1, dtype=np.int32)
        rt.wait(rt.isend_enqueue(tok, dest=0, tag=32))
        time.sleep(0.1)  # let the token frame drain off the socket
        os._exit(0)
    # Each 1 KiB eager frame dwarfs the 64-byte budget, so recording it
    # evicts unacked bytes and latches replay_broken on first full write.
    src = np.zeros(256, dtype=np.int32)
    for i in range(3):
        src[0] = i
        rt.wait(rt.isend_enqueue(src, dest=1, tag=31))
    deadline = time.monotonic() + 10
    while rt.recovery_stats()["replay_broken_links"] < 1:
        assert time.monotonic() < deadline, rt.recovery_stats()
        time.sleep(0.01)
    tok = np.zeros(1, dtype=np.int32)
    rt.wait(rt.irecv_enqueue(tok, source=1, tag=32))
    assert tok[0] == 1
    # Park an op against the (about to be dead) peer. The short pinned
    # ladder means the EOF dead-latches within ~1s; the posted recv must
    # resolve to a typed error, never hang.
    dst = np.zeros(8, dtype=np.int32)
    rv = rt.irecv_enqueue(dst, source=1, tag=33)
    t0 = time.monotonic()
    try:
        rt.wait(rv)
        return 1  # completing clean against a dead peer is the bug
    except (runtime.AcxPeerDeadError, runtime.AcxTimeoutError):
        pass
    assert time.monotonic() - t0 < 30
    # Dead-latch settles the gauge: a gone link is no longer "moving but
    # fragile".
    assert rt.recovery_stats()["replay_broken_links"] == 0, \
        rt.recovery_stats()
    print("REPLAY BROKEN OK", flush=True)
    os._exit(0)  # peer is gone; skip the finalize barrier entirely


def _metrics_keys_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    keys = ("reconnects", "replayed_frames", "crc_rejects", "naks_sent",
            "drained_slots", "links_recovering")
    rs = rt.recovery_stats()
    assert all(k in rs for k in keys), rs
    # Drain an unmatched recv so drained_slots is provably live, then
    # check the metrics registry mirrors the recovery counters by name.
    dst = np.zeros(4, dtype=np.int32)
    rv = rt.irecv_enqueue(dst, source=0, tag=13)
    assert rt.drain(100.0) == 1
    try:
        rt.wait(rv)
        return 1
    except runtime.AcxTimeoutError:
        pass
    c = rt.metrics()["counters"]
    for k in ("reconnects", "frames_replayed", "crc_rejects", "naks_sent",
              "drained_slots"):
        assert k in c, sorted(c)
    assert c["drained_slots"] >= 1, c
    print("RECOVERY METRICS OK")
    rt.finalize()
    return 0


if __name__ == "__main__":
    if "--drain-loopback-worker" in sys.argv:
        raise SystemExit(_drain_loopback_worker())
    if "--drain-socket-worker" in sys.argv:
        raise SystemExit(_drain_socket_worker())
    if "--drain-recovering-worker" in sys.argv:
        raise SystemExit(_drain_recovering_worker())
    if "--replay-broken-worker" in sys.argv:
        raise SystemExit(_replay_broken_worker())
    if "--metrics-keys-worker" in sys.argv:
        raise SystemExit(_metrics_keys_worker())
    raise SystemExit("unknown worker mode")
