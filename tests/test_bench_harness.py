"""Tests for bench.py's incremental TPU-evidence capture (round-4
verdict item #1: rounds 2-4 lost entire healthy-tunnel windows to
all-or-nothing 600 s children; the harness itself must be tested).

The TPU children are mocked — these tests verify the ORCHESTRATION:
probe-first fast-fail, per-child banking to BENCH_BANK.json, the
rewrite of BENCH_FULL.json after every child (so a mid-run kill keeps
everything measured so far), and the unmeasured-vs-regression gate
split. Reference: the reference repo has no benchmark harness at all
(SURVEY.md §6) — this is our own obligation.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    # Native rows are not under test here: pin them.
    monkeypatch.setattr(mod, "native_bench",
                        lambda msg_bytes=None: (25.0, 40.0, 1.5))
    monkeypatch.setattr(
        mod, "_run_cpu_child",
        lambda mode, timeout=300: (
            {"quant_allreduce_traffic_reduction": 3.88}, None))
    return mod


def _run_main(bench, full=True):
    code = 0
    try:
        bench.main(full=full)
    except SystemExit as e:
        code = e.code or 0
    return code


def test_probe_down_fast_fails_and_skips(bench, capsys):
    """Dead tunnel: ONE probe failure gates every TPU child; all TPU rows
    are unmeasured (skipped loudly), not regressions; exit 0."""
    calls = []

    def fake_child(mode, attempts=3, timeout=420, **kw):
        calls.append(mode)
        return None, f"timeout after {timeout}s (attempt {attempts})"

    bench._run_tpu_child = fake_child
    assert _run_main(bench) == 0
    assert calls == ["probe"], "expensive children must not run"
    doc = json.load(open(os.path.join(bench.REPO, "BENCH_FULL.json")))
    assert "partial" not in doc
    skipped = {c["metric"] for c in doc["checks"] if c.get("skipped")}
    assert "gpt2_fwd_tokens_per_s" in skipped
    assert "train_step_tokens_per_s" in skipped
    assert not doc["result"]["regressions"]
    assert "probe failed" in doc["result"]["tpu_error"]
    # Native + chip-independent rows still gated green.
    ok = {c["metric"] for c in doc["checks"] if c.get("ok")}
    assert {"pingpong_p50_us", "partitioned_bw_gbps",
            "quant_allreduce_traffic_reduction"} <= ok


def test_partial_failure_keeps_earlier_rows(bench):
    """Tunnel dies mid-run (after flash): fwd+flash rows are banked and
    in BENCH_FULL.json; later rows are outage-skips, exit 0."""
    rows = {
        "probe": {"tpu_probe_ok": True, "device": "tpu"},
        "fwd": {"gpt2_fwd_tokens_per_s": 250000.0,
                "gpt2_fwd_b16s512_tokens_per_s": 380000.0,
                "device": "tpu"},
        "flash": {"flash_speedup_s4096": 30.0, "device": "tpu"},
    }

    def fake_child(mode, attempts=3, timeout=420, **kw):
        if mode in rows:
            return rows[mode], None
        return None, f"timeout after {timeout}s (attempt 1)"

    bench._run_tpu_child = fake_child
    assert _run_main(bench) == 0
    doc = json.load(open(os.path.join(bench.REPO, "BENCH_FULL.json")))
    by = {c["metric"]: c for c in doc["checks"]}
    assert by["gpt2_fwd_tokens_per_s"]["ok"] is True
    assert by["flash_speedup_s4096"]["ok"] is True
    assert by["decode_tokens_per_s"]["skipped"]
    assert "TPU outage" in by["decode_tokens_per_s"]["reason"]
    assert not doc["result"]["regressions"]
    # The measured rows were banked the moment they landed.
    bank = json.load(open(os.path.join(bench.REPO, "BENCH_BANK.json")))
    assert bank["gpt2_fwd_tokens_per_s"]["value"] == 250000.0
    assert bank["flash_speedup_s4096"]["value"] == 30.0
    assert "decode_tokens_per_s" not in bank


def test_tunnel_death_mid_run_skips_remaining_groups(bench):
    """Once a group exhausts retries AND the re-probe fails, later
    groups must fail fast (no attempts x timeout burn) with a loud
    mid-run error."""
    calls = []
    alive = {"probe": True}

    def fake_child(mode, attempts=3, timeout=420, **kw):
        calls.append(mode)
        if mode == "probe":
            if alive["probe"]:
                alive["probe"] = False     # first probe green, re-probe dead
                return {"tpu_probe_ok": True, "device": "tpu"}, None
            return None, "timeout after 150s (attempt 1)"
        if mode == "fwd":
            return {"gpt2_fwd_tokens_per_s": 250000.0,
                    "gpt2_fwd_b16s512_tokens_per_s": 380000.0,
                    "device": "tpu"}, None
        return None, f"timeout after {timeout}s"

    bench._run_tpu_child = fake_child
    assert _run_main(bench) == 0
    # flash fails -> re-probe fails -> decode/train/spec never spawn.
    assert calls.count("flash") == 1
    assert "decode" not in calls and "train" not in calls \
        and "spec" not in calls
    doc = json.load(open(os.path.join(bench.REPO, "BENCH_FULL.json")))
    by = {c["metric"]: c for c in doc["checks"]}
    assert by["gpt2_fwd_tokens_per_s"]["ok"] is True
    assert by["decode_tokens_per_s"]["skipped"]
    assert "mid-run" in by["decode_tokens_per_s"]["reason"]


def test_true_regression_still_fails_gate(bench):
    """A measured row below 0.9x baseline exits nonzero — the
    unmeasured split must not soften real regressions."""
    def fake_child(mode, attempts=3, timeout=420, **kw):
        if mode == "probe":
            return {"tpu_probe_ok": True, "device": "tpu"}, None
        if mode == "fwd":
            return {"gpt2_fwd_tokens_per_s": 1000.0,   # way below baseline
                    "gpt2_fwd_b16s512_tokens_per_s": 380000.0,
                    "device": "tpu"}, None
        return None, "timeout"

    bench._run_tpu_child = fake_child
    assert _run_main(bench) == 1
    doc = json.load(open(os.path.join(bench.REPO, "BENCH_FULL.json")))
    assert "gpt2_fwd_tokens_per_s" in doc["result"]["regressions"]


def test_bank_merges_not_overwrites(bench):
    """_bank appends/updates rows without dropping earlier evidence."""
    bench._bank({"a": 1, "device": "tpu"})
    bench._bank({"b": 2.5, "device": "tpu"})
    bench._bank({"a": 3, "device": "tpu"})
    bank = json.load(open(os.path.join(bench.REPO, "BENCH_BANK.json")))
    assert bank["a"]["value"] == 3 and bank["b"]["value"] == 2.5
    assert "device" not in bank
    assert bank["a"]["device"] == "tpu"


def test_key_drift_is_a_failure_not_a_skip(bench):
    """A successful child whose expected metric key vanished must FAIL
    the gate (key drift), never silently skip."""
    def fake_child(mode, attempts=3, timeout=420, **kw):
        if mode == "probe":
            return {"tpu_probe_ok": True, "device": "tpu"}, None
        if mode == "fwd":
            return {"renamed_key": 1.0, "device": "tpu"}, None
        return None, "timeout"

    bench._run_tpu_child = fake_child
    assert _run_main(bench) == 1
    doc = json.load(open(os.path.join(bench.REPO, "BENCH_FULL.json")))
    by = {c["metric"]: c for c in doc["checks"]}
    assert by["gpt2_fwd_tokens_per_s"]["ok"] is False
    assert "key drift" in by["gpt2_fwd_tokens_per_s"]["reason"]


def test_bank_reuse_requires_same_code_rev(bench, monkeypatch):
    """Reuse may stand in for a fresh measurement ONLY when the banked
    rows carry the CURRENT code fingerprint — rows from older code
    (or rows with none, e.g. pre-r05 banks) must re-measure."""
    monkeypatch.setattr(bench, "_code_rev", lambda: "rev-live")
    bench._bank({"decode_tokens_per_s": 5000.0, "device": "tpu"},
                group="decode")
    monkeypatch.setenv("ACX_BANK_REUSE_H", "18")
    assert bench._bank_reuse("decode") == {"decode_tokens_per_s": 5000.0}

    # Code changed since the row was banked -> refuse.
    monkeypatch.setattr(bench, "_code_rev", lambda: "rev-changed")
    assert bench._bank_reuse("decode") is None

    # No fingerprint at all (legacy row) -> refuse.
    bank_path = os.path.join(bench.REPO, "BENCH_BANK.json")
    bank = json.load(open(bank_path))
    del bank["decode_tokens_per_s"]["rev"]
    json.dump(bank, open(bank_path, "w"))
    monkeypatch.setattr(bench, "_code_rev", lambda: "rev-live")
    assert bench._bank_reuse("decode") is None

    # Reuse is opt-in: without the env the fresh row is never reused.
    monkeypatch.delenv("ACX_BANK_REUSE_H")
    bench._bank({"decode_tokens_per_s": 5000.0, "device": "tpu"},
                group="decode")
    assert bench._bank_reuse("decode") is None


def test_outage_attaches_banked_rows(bench, capsys):
    """A dead-tunnel run must still surface committed chip evidence:
    the final JSON line carries every banked TPU row with provenance
    instead of a tpu_error-only artifact (rounds 2-4 failure mode)."""
    bench._bank({"gpt2_fwd_tokens_per_s": 250000.0, "device": "tpu"},
                group="fwd")
    bench._run_tpu_child = lambda mode, **kw: (None, "timeout (probe)")
    assert _run_main(bench, full=False) == 0
    last = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(last)
    assert "tpu_error" in out
    row = out["banked_tpu_rows"]["gpt2_fwd_tokens_per_s"]
    assert row["value"] == 250000.0
    assert row["ts"] and row["rev"]


def test_outage_refuses_cross_rev_speedups(bench, capsys, monkeypatch):
    """A `*_speedup` ratio only attaches when it AND both component rows
    carry the same recorded rev; mixed (or missing) revs land under
    banked_speedups_dropped instead — the stale pre-factoring 0.73x
    int8-KV row survived exactly because both sides defaulted to
    "unrecorded" and compared equal."""
    monkeypatch.setattr(bench, "_code_rev", lambda: "rev-a")
    bench._bank({"decode_tokens_per_s": 5000.0,
                 "decode_flash_tokens_per_s": 9000.0,
                 "decode_flash_speedup": 1.8, "device": "tpu"},
                group="decode")
    bench._run_tpu_child = lambda mode, **kw: (None, "timeout (probe)")

    def last_out():
        return json.loads(
            [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")][-1])

    assert _run_main(bench, full=False) == 0
    out = last_out()
    assert out["banked_tpu_rows"]["decode_flash_speedup"]["value"] == 1.8
    assert "decode_flash_speedup" not in out.get(
        "banked_speedups_dropped", {})

    # The variant row re-measured on different code: refuse the ratio
    # (the plain component rows still attach).
    monkeypatch.setattr(bench, "_code_rev", lambda: "rev-b")
    bench._bank({"decode_flash_tokens_per_s": 9500.0, "device": "tpu"},
                group="decode")
    assert _run_main(bench, full=False) == 0
    out = last_out()
    assert "decode_flash_speedup" not in out["banked_tpu_rows"]
    assert "decode_flash_tokens_per_s" in out["banked_tpu_rows"]
    assert "different revs" in \
        out["banked_speedups_dropped"]["decode_flash_speedup"]

    # Rows predating rev stamping never count as matching.
    bank_path = os.path.join(bench.REPO, "BENCH_BANK.json")
    bank = json.load(open(bank_path))
    for k in ("decode_tokens_per_s", "decode_flash_tokens_per_s",
              "decode_flash_speedup"):
        del bank[k]["rev"]
    json.dump(bank, open(bank_path, "w"))
    assert _run_main(bench, full=False) == 0
    out = last_out()
    assert "decode_flash_speedup" not in out.get("banked_tpu_rows", {})
    assert "unrecorded" in \
        out["banked_speedups_dropped"]["decode_flash_speedup"]


def test_midrun_outage_artifact_carries_banked_rows(bench):
    """Tunnel dies mid --full run: BENCH_FULL.json itself (not just the
    stdout line) must carry the banked evidence."""
    bench._bank({"decode_tokens_per_s": 6000.0, "device": "tpu"},
                group="decode")
    rows = {
        "probe": {"tpu_probe_ok": True, "device": "tpu"},
        "fwd": {"gpt2_fwd_tokens_per_s": 250000.0,
                "gpt2_fwd_b16s512_tokens_per_s": 380000.0,
                "device": "tpu"},
    }

    def fake_child(mode, attempts=3, timeout=420, **kw):
        if mode in rows:
            return rows[mode], None
        return None, f"timeout after {timeout}s (attempt 1)"

    bench._run_tpu_child = fake_child
    assert _run_main(bench) == 0
    doc = json.load(open(os.path.join(bench.REPO, "BENCH_FULL.json")))
    banked = doc["result"]["banked_tpu_rows"]
    assert banked["decode_tokens_per_s"]["value"] == 6000.0
