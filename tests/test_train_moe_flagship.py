"""MoE router auxiliaries through the FLAGSHIP dp x pp x tp train step.

Round-3 verdict item: the scaled path was CE-only, risking expert
collapse at pp x tp scale. These tests pin the fix from both ends:
(1) the flagship scalar equals the dp+ep trainer's aux-regularized loss
on a pp=tp=1 mesh (same token groups => bit-equal routing, same
normalization), and (2) at pp=2 the aux actually does its job — training
with it keeps routing measurably more balanced than training without.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from mpi_acx_tpu.models import moe_transformer as mtf
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.parallel.mesh import mesh_from_devices
from mpi_acx_tpu.train import make_loss_and_grads, make_train_step


def _unstage(staged):
    """Invert tfm.stage_slice: [pp, per, ...] layer leaves -> [L, ...]."""
    out = dict(staged)
    out["layers"] = jax.tree.map(
        lambda p: p.reshape((-1,) + p.shape[2:]), staged["layers"])
    return out


def test_flagship_loss_matches_dp_ep_trainer_at_pp1():
    """On a dp=2, pp=1, tp=1 mesh with n_micro=1 the flagship loss must
    equal make_moe_transformer_train_step's loss on the same data: the
    per-rank token groups coincide (B/dp x S tokens per router call), so
    routing is bit-equal, and both normalize aux per (layer, group)."""
    aw, zw = 1e-2, 1e-3
    dp = 2
    mesh = mesh_from_devices({"dp": dp, "pp": 1, "tp": 1},
                             jax.devices()[:dp])
    cfg = mtf.tiny_moe_config(vocab=67, d_model=32, n_heads=2, n_layers=2,
                              d_ff=64, n_experts=8, top_k=2,
                              capacity_factor=2.0, max_seq=16)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)   # exactness test
    params = mtf.init_params(jax.random.key(0), cfg)
    B, S = 4, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)

    # dp+ep trainer (lr=0 would still update; just read the loss).
    ep_mesh = mesh_from_devices({"dp": dp}, jax.devices()[:dp])
    ep_step = mtf.make_moe_transformer_train_step(
        cfg, ep_mesh, axis="dp", lr=0.0, aux_weight=aw, z_weight=zw)
    ep_loss, _ = ep_step(params, tokens, targets)

    grad_fn, n_st = make_loss_and_grads(cfg, mesh, n_micro=1,
                                        aux_weight=aw, z_weight=zw)
    staged = tfm.stage_slice(params, n_st)
    flag_loss, _ = grad_fn(staged, tokens[None], targets[None])
    np.testing.assert_allclose(float(flag_loss), float(ep_loss),
                               rtol=1e-6)


def test_flagship_aux_keeps_routing_balanced_at_pp2():
    """Train the flagship composition at dp=2, pp=2, tp=2 twice from the
    same init — with the router auxiliaries on (default weights, scaled
    up to bite at this tiny scale) and with them off — and measure the
    load-balance statistic of the trained model: the regularized run
    must end strictly more balanced. This is the 'trains with balanced
    routing at pp=2' guarantee the CE-only path could not make."""
    mesh = mesh_from_devices({"dp": 2, "pp": 2, "tp": 2})
    cfg = mtf.tiny_moe_config(vocab=32, d_model=32, n_heads=2, n_layers=4,
                              d_ff=64, n_experts=8, top_k=1,
                              capacity_factor=4.0, max_seq=16)
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 32)

    def train(aux_weight):
        step, n_st = make_train_step(cfg, mesh, n_micro=2, lr=0.5,
                                     aux_weight=aux_weight, z_weight=0.0)
        p = tfm.stage_slice(params, n_st)
        for _ in range(8):
            loss, p = step(p, tokens, tokens)
        return _unstage(p)

    def balance(p):
        # Layer-mean Switch balance statistic of the trained router on
        # the training tokens; 1.0 = perfectly uniform.
        _, aux = mtf.forward(p, cfg, tokens.reshape(-1, 16))
        return float(aux["load_balance"])

    bal_on = balance(train(aux_weight=0.5))
    bal_off = balance(train(aux_weight=0.0))
    assert bal_on < bal_off, (bal_on, bal_off)
    # And the regularized run is genuinely near-uniform, not just less
    # collapsed: the statistic's minimum is 1.0.
    assert bal_on < 1.5, bal_on


def test_flagship_aux_interleaved_matches_gpipe_schedule():
    """The aux accumulator is schedule-invariant: the interleaved
    pipeline (n_virtual=2) must produce the same loss as the plain GPipe
    schedule — both sum each (layer, microbatch) router call exactly
    once, fill/drain slots masked out."""
    mesh = mesh_from_devices({"dp": 2, "pp": 2, "tp": 2})
    cfg = mtf.tiny_moe_config(vocab=32, d_model=32, n_heads=2, n_layers=4,
                              d_ff=64, n_experts=8, top_k=1,
                              capacity_factor=2.0, max_seq=16)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)   # exactness test
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 32)
    targets = jnp.roll(tokens, -1, axis=-1)

    g1, n_st = make_loss_and_grads(cfg, mesh, n_micro=2)
    l1, _ = g1(tfm.stage_slice(params, n_st), tokens, targets)
    g2, _ = make_loss_and_grads(cfg, mesh, n_micro=2, n_virtual=2)
    l2, _ = g2(tfm.stage_slice_interleaved(params, n_st, 2), tokens,
               targets)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
