"""Quantized ring all-reduce (parallel/quantized.py, after EQuARX):
accuracy vs the exact collective, rank agreement, and end-to-end training
with quantized dp-gradient sync.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from mpi_acx_tpu.parallel.mesh import mesh_from_devices
from mpi_acx_tpu.parallel.quantized import quantized_pmean, quantized_psum


def _run(mesh, fn, x, axis="x"):
    """Per-rank inputs x [n, ...] -> stacked per-rank outputs [n, ...]."""
    f = shard_map(fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
                  check_vma=False)
    return jax.jit(f)(x)


@pytest.mark.parametrize("shape", [(1024,), (4096,), (64, 33)])
def test_quantized_psum_close_to_exact(shape):
    """Error envelope of the int8 ring vs the exact psum on an 8-ring:
    each of the 7 reduce-scatter hops re-quantizes the partial sum at
    ~1/254 of its max-abs, so worst-case elementwise error accumulates
    linearly in ring length (measured ~1.5% of the result's max-norm)
    while the MEAN error stays an order of magnitude tighter — the
    regime gradient descent cares about."""
    n = 8
    mesh = mesh_from_devices({"x": n}, jax.devices()[:n])
    x = jax.random.normal(jax.random.key(0), (n,) + shape, jnp.float32)

    got = _run(mesh, lambda v: quantized_psum(v[0], "x")[None], x)
    want = np.asarray(x.sum(0))
    scale = np.abs(want).max() + 1e-6
    for r in range(n):
        diff = np.abs(np.asarray(got[r]) - want)
        assert diff.max() / scale < 0.025, (r, diff.max() / scale)
        assert diff.mean() / scale < 0.004, (r, diff.mean() / scale)


@pytest.mark.parametrize("shape", [(33,), (16, 7), (3, 5, 11)])
def test_quantized_psum_small_leaf_is_exact(shape):
    """Leaves below n*_BLOCK elements take the exact-psum fallback (the
    quantized ring would cost more bytes AND more hops there)."""
    n = 8
    mesh = mesh_from_devices({"x": n}, jax.devices()[:n])
    x = jax.random.normal(jax.random.key(0), (n,) + shape, jnp.float32)
    got = _run(mesh, lambda v: quantized_psum(v[0], "x")[None], x)
    want = np.asarray(x.sum(0))
    for r in range(n):
        np.testing.assert_allclose(np.asarray(got[r]), want, rtol=1e-5,
                                   atol=1e-5)


def test_quantized_psum_identical_on_all_ranks():
    """The all-gather phase distributes ONE quantized value, so every
    rank holds bit-identical results (no rank-dependent rounding)."""
    n = 8
    mesh = mesh_from_devices({"x": n}, jax.devices()[:n])
    x = jax.random.normal(jax.random.key(1), (n, 1037), jnp.float32)
    got = np.asarray(_run(mesh, lambda v: quantized_psum(v[0], "x")[None], x))
    for r in range(1, n):
        np.testing.assert_array_equal(got[0], got[r])


def test_quantized_psum_zero_and_axis1():
    n = 8
    mesh = mesh_from_devices({"x": n}, jax.devices()[:n])
    z = jnp.zeros((n, 2048), jnp.float32)
    got = np.asarray(_run(mesh, lambda v: quantized_psum(v[0], "x")[None], z))
    np.testing.assert_array_equal(got, np.zeros((n, 2048)))
    # Axis of size 1: exact passthrough.
    mesh1 = mesh_from_devices({"x": 1}, jax.devices()[:1])
    y = jax.random.normal(jax.random.key(2), (1, 33), jnp.float32)
    got1 = _run(mesh1, lambda v: quantized_psum(v[0], "x")[None], y)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(y), rtol=1e-6)


def test_quantized_pmean_matches_scaled_psum():
    n = 4
    mesh = mesh_from_devices({"x": n}, jax.devices()[:n])
    x = jax.random.normal(jax.random.key(3), (n, 1100), jnp.float32)
    got = _run(mesh, lambda v: quantized_pmean(v[0], "x")[None], x)
    want = _run(mesh, lambda v: (quantized_psum(v[0], "x") / n)[None], x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_train_step_with_quantized_dp_sync_converges():
    """dp_quant_bits=8 through the full dp x pp x tp step: the first-step
    loss equals the exact step's (loss is computed before grad sync), the
    updated parameters stay within quantization tolerance of the exact
    step's, and training still converges on a fixed batch."""
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.train import make_train_step

    cfg = tfm.tiny_config(vocab=61, d_model=32, n_heads=2, n_layers=2,
                          d_ff=64, max_seq=16)
    mesh = mesh_from_devices({"dp": 2, "pp": 2, "tp": 2})
    params = tfm.init_params(jax.random.key(0), cfg)
    M, mb, S = 2, 4, 16
    tok = jax.random.randint(jax.random.key(1), (M, mb, S), 0, cfg.vocab)
    tgt = jnp.roll(tok, -1, -1)

    exact_step, n_st = make_train_step(cfg, mesh, n_micro=M, lr=0.1)
    quant_step, _ = make_train_step(cfg, mesh, n_micro=M, lr=0.1,
                                    dp_quant_bits=8)
    staged = tfm.stage_slice(params, n_st)
    le, pe = exact_step(staged, tok, tgt)
    lq, pq = quant_step(staged, tok, tgt)
    np.testing.assert_allclose(float(le), float(lq), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(pe), jax.tree.leaves(pq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=0.05)

    p = staged
    l0 = None
    for _ in range(8):
        loss, p = quant_step(p, tok, tgt)
        if l0 is None:
            l0 = float(loss)
    assert float(loss) < l0, (float(loss), l0)
