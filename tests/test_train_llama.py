"""Llama family through the dp x pp x tp/sp distributed train step
(BASELINE.json configs[4]): the parallel composition must compute EXACTLY
the same step as the single-device Llama implementation — RoPE with
global positions on sequence shards, GQA broadcast before ring attention,
SwiGLU tensor-parallel reduction, and the family's untied unembed head
all have to be right for parameters to match."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.parallel.mesh import mesh_from_devices
from mpi_acx_tpu.train import make_train_step


@pytest.fixture(scope="module")
def setup():
    cfg = lm.tiny_llama(vocab=89, d_model=64, n_heads=4, n_kv_heads=2,
                        n_layers=4, d_ff=96, max_seq=32)
    mesh = mesh_from_devices({"dp": 2, "pp": 2, "tp": 2})
    params = lm.init_params(jax.random.key(0), cfg)
    M, mb, S = 3, 4, 16
    tokens = jax.random.randint(jax.random.key(1), (M, mb, S), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)
    return cfg, mesh, params, tokens, targets


def _sequential_step(cfg, params, tokens, targets, lr):
    M, mb, S = tokens.shape
    flat_t = tokens.reshape(M * mb, S)
    flat_y = targets.reshape(M * mb, S)
    loss, grads = jax.value_and_grad(lm.loss_fn)(params, cfg, flat_t, flat_y)
    return loss, jax.tree.map(lambda p, g: p - lr * g, params, grads)


def test_llama_distributed_step_matches_sequential(setup):
    cfg, mesh, params, tokens, targets = setup
    lr = 0.1
    step, n_stages = make_train_step(cfg, mesh, n_micro=tokens.shape[0],
                                     lr=lr)
    staged = tfm.stage_slice(params, n_stages)

    dist_loss, dist_new = step(staged, tokens, targets)
    seq_loss, seq_new = _sequential_step(cfg, params, tokens, targets, lr)

    np.testing.assert_allclose(float(dist_loss), float(seq_loss), rtol=2e-4)

    seq_staged = tfm.stage_slice(seq_new, n_stages)
    flat_d = jax.tree.leaves_with_path(jax.tree.map(np.asarray, dist_new))
    flat_s = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree.leaves_with_path(
            jax.tree.map(np.asarray, seq_staged)))
    for key, got in flat_d:
        want = flat_s[jax.tree_util.keystr(key)]
        np.testing.assert_allclose(
            got, want, atol=5e-4, rtol=5e-3,
            err_msg=f"param {jax.tree_util.keystr(key)} diverged")


def test_llama_distributed_training_converges(setup):
    cfg, mesh, params, tokens, targets = setup
    step, n_stages = make_train_step(cfg, mesh, n_micro=tokens.shape[0],
                                     lr=0.3)
    staged = tfm.stage_slice(params, n_stages)
    l0, staged = step(staged, tokens, targets)
    for _ in range(6):
        l1, staged = step(staged, tokens, targets)
    assert float(l1) < float(l0)
