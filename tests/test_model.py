"""Transformer model family: shapes, gradient sanity, training progress,
and the MoE/expert-parallel layer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from mpi_acx_tpu.models import (
    MoeConfig, init_moe_params, moe_layer,
    gpt2_small, init_params, forward, loss_fn, tiny_config,
)
from mpi_acx_tpu.parallel import make_mesh


def test_forward_shapes_and_dtype():
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab)
    logits = jax.jit(lambda p, t: forward(p, cfg, t))(params, tokens)
    assert logits.shape == (2, 32, cfg.vocab)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_gpt2_small_is_125m():
    cfg = gpt2_small()
    params = init_params(jax.random.key(0), cfg)
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert 115e6 < n < 135e6, n  # 124M + pos embeddings


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = tiny_config(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    t1 = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab)
    t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab)
    l1 = forward(params, cfg, t1)
    l2 = forward(params, cfg, t2)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=2e-3)


def test_loss_decreases_with_sgd():
    cfg = tiny_config(n_layers=2, d_model=64, d_ff=128, vocab=64)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    @jax.jit
    def step(p):
        l, g = jax.value_and_grad(loss_fn)(p, cfg, tokens, targets)
        return l, jax.tree.map(lambda a, b: a - 0.5 * b, p, g)

    l0, params = step(params)
    for _ in range(10):
        l1, params = step(params)
    assert float(l1) < float(l0)


def test_moe_layer_single_device():
    cfg = MoeConfig(d_model=32, d_ff=64, n_experts=4)
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    y = jax.jit(lambda p, x: moe_layer(p, x, cfg))(params, x)
    assert y.shape == x.shape
    assert bool(jnp.isfinite(y).all())
    assert float(jnp.abs(y).max()) > 0


def test_moe_expert_parallel_matches_single_device():
    """EP over 8 devices == the same routing computed on one device."""
    mesh = make_mesh(8)
    cfg = MoeConfig(d_model=16, d_ff=32, n_experts=8, capacity_factor=8.0)
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (32, 16), jnp.float32)

    want = moe_layer(params, x, cfg)

    f = shard_map(
        lambda p, xx: moe_layer(p, xx, cfg, ep_axis="x"),
        mesh=mesh,
        in_specs=({"gate": P(), "w1": P("x"), "w2": P("x")}, P()),
        out_specs=P(),
        check_vma=False)
    got = f(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5,
                               rtol=1e-5)


# -- KV-cache decode -------------------------------------------------------


class TestDecode:
    def _setup(self, dtype=jnp.float32):
        import dataclasses
        from mpi_acx_tpu.models.transformer import TransformerConfig
        cfg = dataclasses.replace(tiny_config(n_layers=2), dtype=dtype)
        params = init_params(jax.random.key(0), cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 12), 0, cfg.vocab)
        return cfg, params, tokens

    def test_prefill_matches_forward(self):
        from mpi_acx_tpu.models.transformer import prefill
        cfg, params, tokens = self._setup()
        full = forward(params, cfg, tokens)
        pre, cache = prefill(params, cfg, tokens, max_len=32)
        np.testing.assert_allclose(np.asarray(full), np.asarray(pre),
                                   rtol=1e-4, atol=1e-4)
        assert int(cache["pos"]) == tokens.shape[1]
        assert cache["k"].shape == (cfg.n_layers, 2, 32, cfg.n_heads,
                                    cfg.head_dim)

    def test_decode_step_matches_forward(self):
        """Logits from cached single-token decode == logits from running
        the whole prefix densely (the KV cache is exact, not approximate)."""
        from mpi_acx_tpu.models.transformer import prefill, decode_step
        cfg, params, tokens = self._setup()
        _, cache = prefill(params, cfg, tokens, max_len=32)
        step = jax.jit(lambda c, t: decode_step(params, cfg, c, t))
        seq = tokens
        for i in range(4):
            nxt = jax.random.randint(jax.random.key(10 + i), (2,), 0,
                                     cfg.vocab)
            logits, cache = step(cache, nxt)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
            dense = forward(params, cfg, seq)[:, -1]
            np.testing.assert_allclose(np.asarray(logits), np.asarray(dense),
                                       rtol=2e-3, atol=2e-3)
        assert int(cache["pos"]) == tokens.shape[1] + 4

    def test_generate_greedy_matches_dense_rollout(self):
        from mpi_acx_tpu.models.transformer import generate
        cfg, params, tokens = self._setup()
        out = jax.jit(
            lambda p, t: generate(p, cfg, t, n_new=5))(params, tokens)
        assert out.shape == (2, tokens.shape[1] + 5)
        # naive rollout: full forward each step, greedy argmax
        seq = tokens
        for _ in range(5):
            nxt = jnp.argmax(forward(params, cfg, seq)[:, -1], axis=-1)
            seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)],
                                  axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))

    def test_decode_bf16(self):
        """The bf16 path stays finite and shape-correct."""
        from mpi_acx_tpu.models.transformer import generate
        cfg, params, tokens = self._setup(dtype=jnp.bfloat16)
        out = generate(params, cfg, tokens, n_new=3)
        assert out.shape == (2, 15)
        assert bool((out >= 0).all()) and bool((out < cfg.vocab).all())

    def test_cast_params_decode(self):
        """bf16-cast weights (the inference configuration) generate the
        same shapes and valid tokens."""
        from mpi_acx_tpu.models.transformer import cast_params, generate
        cfg, params, tokens = self._setup(dtype=jnp.bfloat16)
        p16 = cast_params(params)
        assert all(p.dtype == jnp.bfloat16 for p in jax.tree.leaves(p16))
        out = generate(p16, cfg, tokens, n_new=3)
        assert out.shape == (2, 15)
        assert bool((out >= 0).all()) and bool((out < cfg.vocab).all())

    def test_decode_from_empty_cache(self):
        """Decoding token-by-token from an init_kv_cache (no prefill)
        matches the dense forward at every step."""
        from mpi_acx_tpu.models.transformer import init_kv_cache, decode_step
        cfg, params, tokens = self._setup()
        cache = init_kv_cache(cfg, batch=2, max_len=16)
        step = jax.jit(lambda c, t: decode_step(params, cfg, c, t))
        for i in range(5):
            logits, cache = step(cache, tokens[:, i])
            dense = forward(params, cfg, tokens[:, :i + 1])[:, -1]
            np.testing.assert_allclose(np.asarray(logits), np.asarray(dense),
                                       rtol=2e-3, atol=2e-3)

    def test_generate_rejects_past_max_seq(self):
        cfg, params, tokens = self._setup()
        from mpi_acx_tpu.models.transformer import generate
        with pytest.raises(AssertionError):
            generate(params, cfg, tokens, n_new=cfg.max_seq)
