"""Transformer model family: shapes, gradient sanity, training progress,
and the MoE/expert-parallel layer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from mpi_acx_tpu.models import (
    MoeConfig, init_moe_params, moe_layer,
    gpt2_small, init_params, forward, loss_fn, tiny_config,
)
from mpi_acx_tpu.parallel import make_mesh


def test_forward_shapes_and_dtype():
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab)
    logits = jax.jit(lambda p, t: forward(p, cfg, t))(params, tokens)
    assert logits.shape == (2, 32, cfg.vocab)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_gpt2_small_is_125m():
    cfg = gpt2_small()
    params = init_params(jax.random.key(0), cfg)
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert 115e6 < n < 135e6, n  # 124M + pos embeddings


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = tiny_config(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    t1 = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab)
    t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab)
    l1 = forward(params, cfg, t1)
    l2 = forward(params, cfg, t2)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=2e-3)


def test_loss_decreases_with_sgd():
    cfg = tiny_config(n_layers=2, d_model=64, d_ff=128, vocab=64)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=1)

    @jax.jit
    def step(p):
        l, g = jax.value_and_grad(loss_fn)(p, cfg, tokens, targets)
        return l, jax.tree.map(lambda a, b: a - 0.5 * b, p, g)

    l0, params = step(params)
    for _ in range(10):
        l1, params = step(params)
    assert float(l1) < float(l0)


def test_moe_layer_single_device():
    cfg = MoeConfig(d_model=32, d_ff=64, n_experts=4)
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    y = jax.jit(lambda p, x: moe_layer(p, x, cfg))(params, x)
    assert y.shape == x.shape
    assert bool(jnp.isfinite(y).all())
    assert float(jnp.abs(y).max()) > 0


def test_moe_expert_parallel_matches_single_device():
    """EP over 8 devices == the same routing computed on one device."""
    mesh = make_mesh(8)
    cfg = MoeConfig(d_model=16, d_ff=32, n_experts=8, capacity_factor=8.0)
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (32, 16), jnp.float32)

    want = moe_layer(params, x, cfg)

    f = shard_map(
        lambda p, xx: moe_layer(p, xx, cfg, ep_axis="x"),
        mesh=mesh,
        in_specs=({"gate": P(), "w1": P("x"), "w2": P("x")}, P()),
        out_specs=P(),
        check_vma=False)
    got = f(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5,
                               rtol=1e-5)
