"""Resilience plane through the Python stack: env-driven fault
injection, retry/backoff, op deadlines, heartbeat dead-peer detection
(src/core/fault.cc, src/core/proxy.cc, src/net/socket_transport.cc),
plus the serving loop's request re-queue (models/serving.py).

ACX_FAULT / ACX_HEARTBEAT_MS seed process-global native state at first
use and stay armed for the life of the process, so every fault-armed
path runs in a SUBPROCESS (worker modes of this file, the
test_runtime.py pattern) — the shared pytest process never arms one.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _acxrun():
    from mpi_acx_tpu import runtime
    return runtime.acxrun_path()


def _run(cmd, env_extra=None, timeout=120):
    env = dict(os.environ)
    env.pop("ACX_FAULT", None)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


# -- launcher-level spec validation ----------------------------------------


def test_acxrun_rejects_bad_fault_spec():
    """A typo'd -fault spec must die at launch (exit 2), not silently
    run the job fault-free."""
    r = _run([_acxrun(), "-np", "1", "-fault", "bogus:nth=1",
              "/bin/true"])
    assert r.returncode == 2, r.stdout + r.stderr
    assert "bad -fault schedule" in r.stderr


def test_acxrun_rejects_truncated_schedule():
    """A trailing ';' means a spec went missing (shell quoting): refuse
    the half-schedule rather than run a different experiment."""
    r = _run([_acxrun(), "-np", "1", "-fault", "drop:nth=1;",
              "/bin/true"])
    assert r.returncode == 2, r.stdout + r.stderr
    assert "bad -fault schedule" in r.stderr


# -- transient drop -> retry -> success ------------------------------------


def test_transient_drop_retried_to_completion(tmp_path):
    """acceptance (a): rank 0's first send is swallowed at issue; the
    proxy's backoff retry re-posts it and the ring completes. Counters
    land in resilience_stats AND the ACX_TRACE event stream."""
    trace = str(tmp_path / "t")
    r = _run([_acxrun(), "-np", "2", "-fault",
              "drop:rank=0:kind=send:nth=1",
              sys.executable, __file__, "--drop-worker"],
             env_extra={"ACX_TRACE": trace})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DROP RETRY OK" in r.stdout
    events = [e["name"] for e in
              json.load(open(f"{trace}.rank0.trace.json"))["traceEvents"]]
    assert "fault_drop" in events, events
    assert "op_retry" in events, events


def test_injected_fail_raises_typed_error():
    """fail:... completes the op with MPIX_ERR_INJECTED and wait()
    surfaces it as AcxError (not a hang, not a bare status)."""
    r = _run([sys.executable, __file__, "--fail-worker"],
             env_extra={"ACX_FAULT": "fail:rank=0:kind=send:nth=1"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "FAIL RAISED OK" in r.stdout


def test_deadline_bounds_unmatched_recv():
    """A recv nobody ever sends to completes with AcxTimeoutError
    within the configured deadline instead of blocking forever."""
    t0 = time.monotonic()
    r = _run([sys.executable, __file__, "--deadline-worker"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DEADLINE OK" in r.stdout
    assert time.monotonic() - t0 < 60


def test_dead_peer_raises_within_deadline():
    """acceptance (b): a peer that exits mid-job is declared dead by
    the heartbeat sweep and the blocked Python wait() raises a typed
    exception within the configured bound."""
    r = _run([_acxrun(), "-np", "2",
              sys.executable, __file__, "--deadpeer-worker"],
             env_extra={"ACX_HEARTBEAT_MS": "25",
                        "ACX_PEER_TIMEOUT_MS": "200",
                        "ACX_PEER_GRACE_MS": "500"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DEADPEER OK" in r.stdout


# -- multihost bootstrap degrades cleanly ----------------------------------


def test_multihost_initialize_bounded():
    """A worker pointed at a coordinator that isn't there raises a
    RuntimeError naming the rendezvous triple within ACX_INIT_TIMEOUT_S
    (where the JAX build supports a bounded init; SKIP otherwise)."""
    code = (
        "import inspect, os, jax\n"
        "import sys\n"
        "sys.path.insert(0, " + repr(REPO) + ")\n"
        "if 'initialization_timeout' not in inspect.signature("
        "jax.distributed.initialize).parameters:\n"
        "    print('SKIP: no initialization_timeout'); raise SystemExit(0)\n"
        "try:\n"
        "    from mpi_acx_tpu.parallel import multihost\n"
        "except ImportError as e:\n"
        "    print(f'SKIP: parallel package unimportable here: {e}')\n"
        "    raise SystemExit(0)\n"
        "try:\n"
        "    multihost.initialize()\n"
        "except RuntimeError as e:\n"
        "    assert 'multihost initialize failed' in str(e), e\n"
        "    print('INIT BOUNDED OK'); raise SystemExit(0)\n"
        "raise SystemExit('initialize() against a dead coordinator "
        "returned')\n")
    r = _run([sys.executable, "-c", code],
             env_extra={"JAX_PLATFORMS": "cpu",
                        "ACX_COORDINATOR": "127.0.0.1:1",
                        "ACX_NPROCS": "2", "ACX_PROC_ID": "1",
                        "ACX_INIT_TIMEOUT_S": "5"},
             timeout=180)
    if "SKIP" in r.stdout:
        pytest.skip("jax.distributed.initialize has no bounded init")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "INIT BOUNDED OK" in r.stdout


# -- serving: failed step costs a replay, not the server -------------------


def _tiny():
    import jax
    from mpi_acx_tpu.models import transformer as tfm
    cfg = tfm.tiny_config(vocab=61, d_model=48, n_heads=4, n_layers=2,
                          d_ff=96, max_seq=96)
    return cfg, tfm.init_params(jax.random.key(0), cfg), tfm


def _tiny_prompts(cfg, n=5):
    import jax
    ks = jax.random.split(jax.random.key(3), n)
    lens = [5, 9, 3, 7, 4]
    return [np.asarray(jax.random.randint(ks[i], (lens[i % len(lens)],),
                                          0, cfg.vocab), np.int32)
            for i in range(n)]


def test_serving_requeues_after_step_failure():
    """A step_fn that raises once mid-stream: active requests restart
    from scratch and the final outputs equal the failure-free serve bit
    for bit (greedy determinism + emitted-token reset)."""
    from mpi_acx_tpu.models import serving
    cfg, params, tfm = _tiny()
    prompts = _tiny_prompts(cfg)
    want = serving.serve_greedy(params, cfg, prompts, n_new=6, n_slots=2,
                                max_len=32, family=tfm)

    fns = serving.make_server_fns(params, cfg, tfm)
    prefill_fn, step_fn, scatter_fn, chunk, kv8, smp = fns
    calls = {"n": 0}

    def flaky_step(cache, tok, keys):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected device step failure")
        return step_fn(cache, tok, keys)

    got = serving.serve_greedy(
        params, cfg, prompts, n_new=6, n_slots=2, max_len=32, family=tfm,
        server_fns=(prefill_fn, flaky_step, scatter_fn, chunk, kv8, smp))
    assert calls["n"] > 2, "failure fired before the loop finished"
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)


def test_serving_persistent_failure_raises_with_rid():
    """Past max_request_retries the failure propagates, naming the
    request — a permanently broken step can't spin the server."""
    from mpi_acx_tpu.models import serving
    cfg, params, tfm = _tiny()
    prompts = _tiny_prompts(cfg, n=2)
    fns = serving.make_server_fns(params, cfg, tfm)

    def dead_step(cache, tok, keys):
        raise RuntimeError("wedged device")

    with pytest.raises(RuntimeError, match="max_request_retries"):
        serving.serve_greedy(
            params, cfg, prompts, n_new=4, n_slots=2, max_len=32,
            family=tfm, max_request_retries=1,
            server_fns=(fns[0], dead_step, fns[2], fns[3], fns[4],
                        fns[5]))


def test_serving_rejects_zero_length_prompt():
    from mpi_acx_tpu.models import serving
    cfg, params, tfm = _tiny()
    with pytest.raises(AssertionError, match="zero-length"):
        serving.serve_greedy(params, cfg,
                             [np.asarray([1, 2], np.int32),
                              np.asarray([], np.int32)],
                             n_new=2, n_slots=2, max_len=32, family=tfm)


def test_serving_rejects_chunk_mismatched_fns():
    """The tuple carries its baked-in chunk; reusing it under another
    chunk must fail at the door, not mis-slice token blocks."""
    from mpi_acx_tpu.models import serving
    cfg, params, tfm = _tiny()
    fns = serving.make_server_fns(params, cfg, tfm, chunk=2)
    with pytest.raises(AssertionError, match="chunk"):
        serving.serve_greedy(params, cfg, _tiny_prompts(cfg, n=2),
                             n_new=4, n_slots=2, max_len=32, family=tfm,
                             chunk=1, server_fns=fns)


# -- subprocess workers ----------------------------------------------------


def _drop_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    right = (rt.rank + 1) % rt.size
    left = (rt.rank - 1) % rt.size
    src = np.full(16, rt.rank * 10, dtype=np.int32)
    dst = np.full(16, -1, dtype=np.int32)
    s = rt.isend_enqueue(src, dest=right, tag=1)
    rv = rt.irecv_enqueue(dst, source=left, tag=1)
    rt.wait(rv)
    rt.wait(s)
    errs = int(not (dst == left * 10).all())
    if rt.rank == 0:
        st = rt.resilience_stats()
        errs |= int(st["fault_drops"] < 1 or st["retries"] < 1)
        # Merged view reaches the same counters (proxy_stats satellite).
        errs |= int(rt.proxy_stats()["retries"] != st["retries"])
    errs = rt.allreduce_max(errs)
    if rt.rank == 0 and errs == 0:
        print("DROP RETRY OK")
    rt.finalize()
    return errs


def _fail_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    src = np.arange(8, dtype=np.int32)
    s = rt.isend_enqueue(src, dest=0, tag=2)
    try:
        rt.wait(s)
    except runtime.AcxError as e:
        assert e.error == runtime.ERR_INJECTED, e
        assert rt.resilience_stats()["fault_fails"] >= 1
        print("FAIL RAISED OK")
        rt.finalize()
        return 0
    return 1


def _deadline_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    rt.set_deadline(200.0)
    assert abs(rt.get_deadline() - 200.0) < 1e-6
    dst = np.zeros(8, dtype=np.int32)
    rv = rt.irecv_enqueue(dst, source=0, tag=3)  # never matched
    t0 = time.monotonic()
    try:
        rt.wait(rv)
    except runtime.AcxTimeoutError:
        elapsed = time.monotonic() - t0
        assert elapsed < 30, elapsed
        assert rt.resilience_stats()["timeouts"] >= 1
        rt.set_deadline(0.0)
        print("DEADLINE OK")
        rt.finalize()
        return 0
    return 1


def _deadpeer_worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    if rt.rank != 0:
        # Crash without farewell: the heartbeat sweep must notice.
        sys.stdout.flush()
        os._exit(0)
    rt.set_deadline(10000.0)  # failsafe so a missed detection still ends
    dst = np.zeros(8, dtype=np.int32)
    rv = rt.irecv_enqueue(dst, source=1, tag=4)
    try:
        rt.wait(rv)
    except runtime.AcxPeerDeadError:
        assert rt.resilience_stats()["peers_dead"] >= 1
    except runtime.AcxTimeoutError:
        pass  # deadline failsafe: still bounded, still typed
    else:
        return 1
    print("DEADPEER OK", flush=True)
    os._exit(0)  # peer is gone; skip the finalize barrier entirely


if __name__ == "__main__":
    if "--drop-worker" in sys.argv:
        raise SystemExit(_drop_worker())
    if "--fail-worker" in sys.argv:
        raise SystemExit(_fail_worker())
    if "--deadline-worker" in sys.argv:
        raise SystemExit(_deadline_worker())
    if "--deadpeer-worker" in sys.argv:
        raise SystemExit(_deadpeer_worker())
    raise SystemExit("unknown worker mode")
