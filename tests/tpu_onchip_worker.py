"""Worker for the ON-CHIP trigger/bridge proof: one acxrun rank.

VERDICT r03 item 2: the trigger plane and the device->proxy bridge had
only ever executed with ``JAX_PLATFORMS=cpu`` (interpret-mode Pallas,
CPU io_callback). The reference's entire reason to exist is the REAL
device firing communication (reference src/sendrecv.cu:152-208,
partitioned.cu:200-212); this worker is the single-chip TPU variant:

rank 0 runs on the REAL chip (platform from ACX_RANK0_PLATFORM, the
test passes the tunnel's platform): a COMPILED jitted program computes
a matmul on the MXU and fires an in-program ``io_callback`` send with
the result; then a COMPILED (not interpret-mode — asserted) Pallas
produce_and_pready kernel publishes partition readiness through the
flag bridge, driving a real 2-rank wire transfer. rank 1 stays on CPU
and verifies both payloads.

Prints ONCHIP_OK <backend> per rank on success.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RANK = int(os.environ.get("ACX_RANK", "0"))
if RANK == 0:
    plat = os.environ.get("ACX_RANK0_PLATFORM", "cpu")
    if plat != "default":
        os.environ["JAX_PLATFORMS"] = plat
else:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if RANK != 0 or os.environ.get("ACX_RANK0_PLATFORM", "cpu") == "cpu":
    # In tpu mode the test must keep PYTHONPATH so rank 0 reaches the
    # tunnel — but then the axon sitecustomize runs in THIS process too
    # and its register() does jax.config.update("jax_platforms",
    # "axon,cpu"), which OVERRIDES the env var above. Left alone, rank
    # 1's first jax.devices() would try to build a second axon client
    # against the single-session tunnel and deadlock both ranks (r05:
    # both ranks stuck in make_c_api_client until acxrun's kill).
    # Forcing the config back AFTER import wins over the sitecustomize.
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.experimental import io_callback  # noqa: E402

from mpi_acx_tpu import xla_triggers as xt  # noqa: E402
from mpi_acx_tpu.ops import flags as fl  # noqa: E402
from mpi_acx_tpu.runtime import Runtime  # noqa: E402

PARTS = 2
ROWS, LANES = 8, 128


def main():
    rt = Runtime()
    assert rt.size == 2, rt.size
    peer = 1 - rt.rank
    backend = jax.default_backend()

    if rt.rank == 0:
        want_tpu = os.environ.get("ACX_RANK0_PLATFORM", "cpu") != "cpu"
        if want_tpu:
            # The whole point: the CHIP, not a CPU stand-in.
            assert backend == "tpu", backend
            assert not fl._interpret(), "Pallas must compile, not interpret"

        # -- 1) in-program trigger from a compiled program ------------
        w = jnp.eye(LANES, dtype=jnp.float32) * 3.0

        @jax.jit
        def program(x):
            y = x @ w                      # MXU work before the trigger
            y = xt.send_in_program(rt, y, dest=peer, tag=5)
            return y.sum()

        x = jnp.ones((ROWS, LANES), jnp.float32)
        s = float(jax.block_until_ready(program(x)))
        assert s == 3.0 * ROWS * LANES, s
        assert xt.drain_sends(rt) == 1

        # -- 2) compiled Pallas flag kernel drives the bridge ---------
        buf = np.zeros((PARTS, ROWS, LANES), dtype=np.float32)
        req = rt.psend_init(buf, PARTS, dest=peer)
        rt.start(req)

        def publish(p, payload, dev_flags):
            buf[int(p)] = np.asarray(payload)
            rt.publish_partition_flags(req, np.asarray(dev_flags))

        @jax.jit
        def sender(dev_flags):
            def step(dev_flags, p):
                xp = jnp.full((ROWS, LANES), 0.0, jnp.float32) + (
                    p + 2).astype(jnp.float32)
                payload, dev_flags = fl.produce_and_pready(
                    lambda t: t * t, xp, dev_flags, p)
                io_callback(publish, None, p, payload, dev_flags,
                            ordered=True)
                return dev_flags, None
            return lax.scan(step, dev_flags, jnp.arange(PARTS))[0]

        flags_out = jax.block_until_ready(
            sender(jnp.full((PARTS,), fl.RESERVED, jnp.int32)))
        assert [int(v) for v in flags_out] == [fl.PENDING] * PARTS
        rt.wait(req)
        rt.request_free(req)
        rt.barrier()
        print(f"ONCHIP_OK {backend}")
    else:
        # Plain host-side receive of the triggered send.
        got = np.zeros((ROWS, LANES), np.float32)
        r = rt.irecv_enqueue(got, source=peer, tag=5)
        rt.wait(r)
        np.testing.assert_array_equal(got, 3.0)

        # Bridge receive: poll the mirror, kernel decides arrival.
        buf = np.zeros((PARTS, ROWS, LANES), dtype=np.float32)
        req = rt.precv_init(buf, PARTS, source=peer)
        rt.start(req)
        idxs = jnp.arange(PARTS)
        deadline = time.time() + 120
        while int(fl.parrived_all(
                jnp.asarray(rt.fetch_partition_flags(req)), idxs)) != 1:
            if time.time() > deadline:
                raise TimeoutError("partitions never arrived")
            time.sleep(0.002)
        rt.wait(req)
        for p in range(PARTS):
            np.testing.assert_array_equal(buf[p], float((p + 2) ** 2))
        rt.request_free(req)
        rt.barrier()
        print(f"ONCHIP_OK {backend}")

    rt.finalize()


if __name__ == "__main__":
    main()
