"""Worker for tests/test_xla_triggers.py: one acxrun rank.

ONE jitted XLA program per rank that (a) computes, (b) triggers a native
enqueued send of the intermediate value when execution reaches that
program point, (c) receives the peer's intermediate mid-program, and
(d) consumes the reply in further computation — the TPU-native analogue
of the reference's stream-triggered ring (test/src/ring.c semantics with
the trigger INSIDE the compiled program, reference sendrecv.cu:152-208).

Prints TRIG_OK <value> on success.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mpi_acx_tpu.runtime import Runtime  # noqa: E402
from mpi_acx_tpu import xla_triggers as xt  # noqa: E402


def main():
    rt = Runtime()
    assert rt.size == 2, rt.size
    rank, peer = rt.rank, 1 - rt.rank
    n = 64

    @jax.jit
    def program(x):
        y = x * 2.0 + rank                 # compute
        y = xt.send_in_program(rt, y, peer, tag=7)   # trigger mid-program
        z = xt.recv_in_program(rt, (n,), np.float32, peer, tag=7)
        return jnp.sum(y + z), z           # consume the reply in-program

    x = jnp.arange(n, dtype=jnp.float32)
    total, z = program(x)
    jax.block_until_ready((total, z))
    assert xt.drain_sends(rt) == 1

    # Closed-form: y_r = 2*arange + r; total = sum(y_rank + y_peer).
    ys = [2.0 * np.arange(n) + r for r in (0, 1)]
    np.testing.assert_allclose(np.asarray(z), ys[peer])
    expect = float((ys[rank] + ys[peer]).sum())
    got = float(total)
    assert got == expect, (got, expect)

    # Re-running the same compiled program re-fires the triggers (the
    # graph re-fire semantics of the reference, internal.h:183-188).
    total2, _ = program(x)
    jax.block_until_ready(total2)
    assert xt.drain_sends(rt) == 1
    assert float(total2) == expect

    rt.barrier()
    print(f"TRIG_OK {got}")
    rt.finalize()


if __name__ == "__main__":
    main()
