"""Chaos invariant oracle (tools/acx_chaos.py): schedule parsing, the
cross-rank invariant audits, and the ddmin schedule shrinker.

These tests feed the oracle *synthetic* artifacts — fault reports,
tseries streams, and flight dumps of the shapes the runtime writes —
so each invariant is exercised in isolation, and drive ddmin with a
scripted failure predicate instead of real runs. The end-to-end path
(real kills under `acxrun -chaos`, real artifact audits, real shrink
runs) is covered by `make chaos-check`.
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chaos():
    spec = importlib.util.spec_from_file_location(
        "acx_chaos", os.path.join(REPO, "tools", "acx_chaos.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


chaos = _chaos()


def _report(rank, fired, incarnation=0):
    """A fault report: fired[i] = times spec i fired on this rank."""
    return {"rank": rank, "incarnation": incarnation,
            "specs": [{"spec": "s%d" % i, "fired": f, "matched": f}
                      for i, f in enumerate(fired)]}


# ---- schedule parsing -------------------------------------------------

def test_parse_schedule_routes_audit_fields():
    sched = chaos.parse_schedule(
        "drop:rank=1:nth=3:count=2;kill:rank=2:nth=7;delay:us=100")
    assert [s["action"] for s in sched] == ["drop", "kill", "delay"]
    assert sched[0]["rank"] == 1 and sched[0]["nth"] == 3
    assert sched[0]["count"] == 2
    assert sched[1]["rank"] == 2
    assert sched[2]["rank"] == -1  # unfiltered spec matches any rank
    assert sched[2]["raw"] == "delay:us=100"


# ---- fault accounting -------------------------------------------------

def test_fault_accounting_all_fired():
    sched = chaos.parse_schedule("drop:rank=0:nth=2;drop_frame:rank=1:nth=3")
    reports = [_report(0, [1, 0]), _report(1, [0, 2])]
    failures, notes = chaos.audit_fault_accounting(sched, reports, set())
    assert failures == [] and notes == []


def test_fault_accounting_never_fired_is_failure():
    sched = chaos.parse_schedule("drop:rank=0:nth=2;drop_frame:rank=1:nth=999")
    reports = [_report(0, [1, 0]), _report(1, [0, 0])]
    failures, _ = chaos.audit_fault_accounting(sched, reports, set())
    assert len(failures) == 1
    assert "spec 1" in failures[0] and "never fired" in failures[0]


def test_fault_accounting_unfiltered_spec_sums_ranks():
    # rank=-1 specs may fire on any rank; firing on ONE rank suffices.
    sched = chaos.parse_schedule("drop:nth=5")
    reports = [_report(0, [0]), _report(1, [3])]
    failures, _ = chaos.audit_fault_accounting(sched, reports, set())
    assert failures == []


def test_fault_accounting_kill_verified_from_respawn_ledger():
    # The SIGKILLed incarnation writes no report: the supervisor's respawn
    # ledger is the evidence that the kill fired.
    sched = chaos.parse_schedule("kill:rank=1:nth=7")
    failures, _ = chaos.audit_fault_accounting(sched, [], {1})
    assert failures == []
    failures, _ = chaos.audit_fault_accounting(sched, [], set())
    assert len(failures) == 1 and "no respawn" in failures[0]


def test_fault_accounting_spec_on_killed_rank_is_skipped():
    # A non-kill spec targeting the killed rank died with its report;
    # unverifiable is a note, not a failure.
    sched = chaos.parse_schedule("drop:rank=1:nth=3;kill:rank=1:nth=7")
    failures, notes = chaos.audit_fault_accounting(sched, [], {1})
    assert failures == []
    assert len(notes) == 1 and "unverifiable" in notes[0]


# ---- epoch monotonicity ----------------------------------------------

def test_epoch_monotone_pass():
    streams = {"ts.rank0": [{"epoch": 1}, {"epoch": 1}, {"epoch": 3}],
               "ts.rank1": [{"epoch": 1}, {"epoch": 5}]}
    assert chaos.audit_epoch_monotone(streams, expect_kill=True) == []


def test_epoch_regression_is_failure():
    streams = {"ts.rank0": [{"epoch": 3}, {"epoch": 2}]}
    failures = chaos.audit_epoch_monotone(streams, expect_kill=False)
    assert len(failures) == 1 and "regressed" in failures[0]


def test_epoch_must_climb_on_kill_run():
    # Death + rejoin bumps the fleet epoch twice past the seed of 1; a
    # kill run whose peak epoch stays at 1 healed nothing.
    streams = {"ts.rank0": [{"epoch": 1}, {"epoch": 1}]}
    failures = chaos.audit_epoch_monotone(streams, expect_kill=True)
    assert len(failures) == 1 and "climbed" in failures[0]
    assert chaos.audit_epoch_monotone(streams, expect_kill=False) == []


# ---- per-lane sequence spaces ----------------------------------------

def _dump_events(events):
    return [("fl.rank0.flight.json", {"events": events})]


def test_seq_spaces_monotone_pass():
    evs = [{"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 1},
           {"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 2},
           {"kind": "rx_frame", "peer": 2, "aux": 0, "seq": 1}]
    assert chaos.audit_seq_spaces(_dump_events(evs)) == []


def test_seq_regression_without_boundary_is_failure():
    evs = [{"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 2},
           {"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 1}]
    failures = chaos.audit_seq_spaces(_dump_events(evs))
    assert len(failures) == 1 and "duplicate or regressed" in failures[0]


def test_seq_restart_after_boundary_is_legal():
    # A recovery boundary (reconnect, NAK, death) legally resets the
    # peer's seq space — the joiner's new incarnation starts from 1.
    evs = [{"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 5},
           {"kind": "peer_dead", "peer": 1},
           {"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 1}]
    assert chaos.audit_seq_spaces(_dump_events(evs)) == []


def test_seq_spaces_are_per_lane():
    # Striped links interleave lanes with independent wire clocks: lane 1
    # starting at 1 after lane 0 reached 2 is NOT a regression.
    evs = [{"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 1},
           {"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 2},
           {"kind": "rx_frame", "peer": 1, "aux": 1, "seq": 1},
           {"kind": "rx_frame", "peer": 1, "aux": 1, "seq": 2}]
    assert chaos.audit_seq_spaces(_dump_events(evs)) == []


def test_boundary_resets_only_that_peer():
    evs = [{"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 4},
           {"kind": "rx_frame", "peer": 2, "aux": 0, "seq": 4},
           {"kind": "link_recovering", "peer": 1},
           {"kind": "rx_frame", "peer": 1, "aux": 0, "seq": 1},  # legal
           {"kind": "rx_frame", "peer": 2, "aux": 0, "seq": 1}]  # not
    failures = chaos.audit_seq_spaces(_dump_events(evs))
    assert len(failures) == 1 and "peer 2" in failures[0]


# ---- ddmin shrinker ---------------------------------------------------

def test_ddmin_finds_single_culprit():
    items = ["a", "b", "c", "d", "e", "f", "g", "h"]
    assert chaos.ddmin(items, lambda s: "f" in s) == ["f"]


def test_ddmin_finds_interacting_pair():
    # The failure needs BOTH specs: ddmin must keep exactly the pair.
    items = ["a", "b", "c", "d", "e", "f"]
    out = chaos.ddmin(items, lambda s: "b" in s and "e" in s)
    assert sorted(out) == ["b", "e"]


def test_ddmin_preserves_schedule_order():
    # Schedule order is semantic (first in-window spec wins): the minimal
    # subset must come back in original order, not sorted or shuffled.
    items = ["z", "m", "a"]
    out = chaos.ddmin(items, lambda s: "z" in s and "a" in s)
    assert out == ["z", "a"]


def test_ddmin_counts_runs_frugally():
    # 8 specs, single culprit: ddmin needs O(k log n) probes, not 2^n.
    calls = [0]

    def still_fails(s):
        calls[0] += 1
        return "d" in s

    assert chaos.ddmin(list("abcdefgh"), still_fails) == ["d"]
    assert calls[0] <= 20


# ---- full-run audit plumbing -----------------------------------------

def _ok_run(schedule_str, **over):
    run = {
        "exit": 0,
        "schedule_str": schedule_str,
        "schedule": chaos.parse_schedule(schedule_str),
        "respawns": {},
        "reports": [],
        "dumps": [],
        "tseries": {},
        "flight_prefix": "/nonexistent/fl",
        "stdout": "",
        "stderr": "",
    }
    run.update(over)
    return run


def test_audit_run_clean():
    run = _ok_run("drop:rank=0:nth=2", reports=[_report(0, [1])])
    failures, notes = chaos.audit_run(run)
    assert failures == [] and notes == []


def test_audit_run_nonzero_exit_fails():
    run = _ok_run("drop:rank=0:nth=2", exit=7, reports=[_report(0, [1])])
    failures, _ = chaos.audit_run(run)
    assert any("workload_exit" in f for f in failures)


def test_audit_run_kill_without_respawn_fails():
    run = _ok_run("kill:rank=1:nth=7")
    failures, _ = chaos.audit_run(run)
    assert any("no respawn" in f for f in failures)
