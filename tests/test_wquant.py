"""Int8 weight-only quantization (ops/wquant.py): the decode-roofline
optimization — weight bytes halve, so the bandwidth-bound decode floor
drops ~2x (BASELINE.md decode row; measured on-chip via bench.py's
decode child). These tests pin the quality and mechanics on CPU:

* quantized logits stay close to bf16 logits (per-channel int8 bound),
* greedy decode on a TRAINED model emits the same tokens (quantization
  noise must not flip well-separated argmaxes),
* the pytree keeps its structure (+_scale companions) so every decode
  scaffold — prefill, decode_step, generate — runs unchanged,
* weight_bytes reflects the ~2x storage cut (the roofline numerator).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.ops.wquant import (GPT2_WEIGHTS, LLAMA_WEIGHTS,
                                    quantize_weights_int8, weight_bytes,
                                    wread)


def test_wread_dequant_roundtrip_error_bound():
    """Per-channel symmetric int8: reconstruction error per element is
    bounded by scale/2 = amax/254 of its output channel."""
    w = jax.random.normal(jax.random.key(0), (4, 64, 32)) * 0.3
    lay = {"w": w}
    q = quantize_weights_int8({"layers": lay}, ["w"])["layers"]
    back = wread(q, "w", jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
    assert float(jnp.max(jnp.abs(back - w) / (amax / 127.0))) <= 0.5 + 1e-3


def _train(mod, cfg, steps=60):
    """Shared Adam scaffold: train `mod`'s model on the repetition task
    so greedy argmaxes are well-separated before quantizing."""
    params = mod.init_params(jax.random.key(0), cfg)
    tok = jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab)
    opt = optax.adam(3e-3)
    st = opt.init(params)

    @jax.jit
    def step(p, st):
        loss, g = jax.value_and_grad(mod.loss_fn)(p, cfg, tok, tok)
        up, st = opt.update(g, st)
        return optax.apply_updates(p, up), st, loss

    for _ in range(steps):
        params, st, _ = step(params, st)
    return params, tok


def _trained_gpt2():
    cfg = tfm.TransformerConfig(**{**tfm.tiny_config(
        vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq=32).__dict__, "dtype": jnp.float32})
    params, tok = _train(tfm, cfg)
    return cfg, params, tok


def _trained_llama():
    c = lm.tiny_llama(vocab=64, d_model=32, n_heads=4, n_kv_heads=2,
                      n_layers=2, d_ff=64, max_seq=32)
    cfg = lm.LlamaConfig(**{**c.__dict__, "dtype": jnp.float32})
    params, tok = _train(lm, cfg)
    return cfg, params, tok


def test_int8_weights_logits_close_and_greedy_tokens_equal():
    cfg, params, tok = _trained_gpt2()
    qparams = quantize_weights_int8(params, GPT2_WEIGHTS)

    logits = tfm.forward(params, cfg, tok[:2])
    qlogits = tfm.forward(qparams, cfg, tok[:2])
    # Quality bound: relative error of the logit vector, f32 reference.
    rel = float(jnp.linalg.norm(qlogits - logits)
                / jnp.linalg.norm(logits))
    assert rel < 0.05, rel

    # Greedy decode: same scaffold, same tokens on the trained task.
    prompt = tok[:2, :8]
    want = tfm.generate(params, cfg, prompt, 8, max_len=24)
    got = tfm.generate(qparams, cfg, prompt, 8, max_len=24)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_int8_weights_llama_generate_runs_and_matches():
    cfg, params, tok = _trained_llama()
    qparams = quantize_weights_int8(params, LLAMA_WEIGHTS)
    prompt = tok[:2, :8]
    want = lm.generate(params, cfg, prompt, 8, max_len=24)
    got = lm.generate(qparams, cfg, prompt, 8, max_len=24)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_weight_bytes_roughly_halve():
    """The roofline numerator: GPT-2's layer matmuls dominate its
    parameter bytes, so int8 storage lands well under 60% of bf16."""
    cfg = tfm.tiny_config(vocab=64, d_model=64, n_heads=4, n_layers=4,
                          d_ff=256, max_seq=32)
    params = tfm.cast_params(tfm.init_params(jax.random.key(0), cfg),
                             jnp.bfloat16)
    q = quantize_weights_int8(params, GPT2_WEIGHTS)
    assert weight_bytes(q) < 0.6 * weight_bytes(params), (
        weight_bytes(q), weight_bytes(params))


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_int8_weights_speculative_matches(family):
    """Speculative decoding over quantized draft AND target (every
    weight read goes through wread, including the W-wide window's wo)
    must emit the same tokens as quantized target-only greedy — both
    families, as the docs claim."""
    import dataclasses
    from mpi_acx_tpu.models.speculative import speculative_generate

    if family == "gpt2":
        cfg, params, tok = _trained_gpt2()
        mod, names = tfm, GPT2_WEIGHTS
    else:
        cfg, params, tok = _trained_llama()
        mod, names = lm, LLAMA_WEIGHTS
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dparams = mod.init_params(jax.random.key(9), dcfg)
    qp = quantize_weights_int8(params, names)
    qd = quantize_weights_int8(dparams, names)
    prompt = tok[:1, :8]
    want = mod.generate(qp, cfg, prompt, 8, max_len=24)
    got, _ = speculative_generate(qd, dcfg, qp, cfg, prompt, 8, k=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_moe_forward_rejects_quantized_experts():
    """block()/_hidden (the training+forward path) must refuse int8
    expert weights loudly — not only the serving _moe_ffn scaffold."""
    from mpi_acx_tpu.models import moe_transformer as mtf
    cfg = mtf.tiny_moe_config(vocab=64, d_model=32, n_heads=2,
                              n_layers=2, d_ff=64, n_experts=4, top_k=1,
                              capacity_factor=4.0, max_seq=32)
    params = mtf.init_params(jax.random.key(0), cfg)
    q = quantize_weights_int8(params, ("w1", "w2"))
    tok = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    with pytest.raises(ValueError, match="quantization"):
        mtf.forward(q, cfg, tok)


def test_tp_serving_int8_matches_single_device_gpt2():
    """TP serving over an int8 checkpoint (scale companions sharded
    alongside their weights, wread in the TP layer ops) must emit the
    same tokens as the single-device quantized generate."""
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_generate
    cfg, params, tok = _trained_gpt2()
    q = quantize_weights_int8(params, GPT2_WEIGHTS)
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    prompt = tok[:2, :8]
    want = tfm.generate(q, cfg, prompt, 8, max_len=24)
    gen = make_tp_generate(cfg, mesh, 8)
    got = gen(q, prompt, jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # The same builder still serves the PLAIN checkpoint (separate
    # compiled program, same per-shard code).
    want_p = tfm.generate(params, cfg, prompt, 8, max_len=24)
    got_p = gen(params, prompt, jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(got_p), np.asarray(want_p))


def test_tp_serving_int8_matches_single_device_llama():
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_generate_llama
    cfg, params, tok = _trained_llama()
    q = quantize_weights_int8(params, LLAMA_WEIGHTS)
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    prompt = tok[:2, :8]
    want = lm.generate(q, cfg, prompt, 8, max_len=24)
    gen = make_tp_generate_llama(cfg, mesh, 8)
    got = gen(q, prompt, jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_speculative_int8_matches_single_device():
    """TP speculative decoding over a quantized draft AND target must
    emit the same tokens/stats as the single-device quantized run —
    the (draft, target) scale-key cache and both families' scale
    re-layouts compose with the speculative loop."""
    import dataclasses
    from mpi_acx_tpu.models.speculative import speculative_generate
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import \
        make_tp_speculative_generate
    cfg, params, tok = _trained_gpt2()
    dcfg = dataclasses.replace(cfg, n_layers=1)
    dparams = tfm.init_params(jax.random.key(9), dcfg)
    qp = quantize_weights_int8(params, GPT2_WEIGHTS)
    qd = quantize_weights_int8(dparams, GPT2_WEIGHTS)
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    prompt = tok[:1, :8]
    want, wstats = speculative_generate(qd, dcfg, qp, cfg, prompt, 8,
                                        k=3)
    gen = make_tp_speculative_generate(dcfg, cfg, mesh, 8, k=3)
    got, stats = gen(qd, qp, prompt, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert int(stats["rounds"]) == int(wstats["rounds"])


def test_tp_moe_quantized_attention_matches_single_device():
    """MoE TP serving with int8 ATTENTION weights (the supported
    subset) matches the single-device quantized generate; experts stay
    bf16."""
    from mpi_acx_tpu.models import moe_transformer as mtf
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_generate_moe
    cfg = mtf.tiny_moe_config(vocab=64, d_model=32, n_heads=2,
                              n_layers=2, d_ff=64, n_experts=4, top_k=1,
                              capacity_factor=4.0, max_seq=32)
    params = mtf.init_params(jax.random.key(0), cfg)
    q = quantize_weights_int8(params, ("wqkv", "wo"))
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    want = mtf.generate(q, cfg, prompt, 6, max_len=16)
    gen = make_tp_generate_moe(cfg, mesh, 6)
    got = gen(q, prompt, jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_serving_rejects_quantized_moe_experts():
    """Quantized MoE EXPERT weights stay unsupported in TP serving:
    the restricted scale-spec map must raise loudly."""
    from mpi_acx_tpu.models import moe_transformer as mtf
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_generate_moe
    cfg = mtf.tiny_moe_config(vocab=64, d_model=32, n_heads=2,
                              n_layers=2, d_ff=64, n_experts=4, top_k=1,
                              capacity_factor=4.0, max_seq=32)
    params = mtf.init_params(jax.random.key(0), cfg)
    q = quantize_weights_int8(params, ("w1", "w2"))
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    gen = make_tp_generate_moe(cfg, mesh, 4)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    with pytest.raises(ValueError, match="w1_scale"):
        gen(q, prompt, jax.random.key(2))


def test_weight_quantization_loss_delta_bounded():
    """Quality metric beyond greedy parity: teacher-forced mean NLL of
    a trained model moves by < 2% relative under int8 weights
    (per-channel scales keep logits close, so the measured loss barely
    moves)."""
    cfg, params, tok = _trained_gpt2()
    probe = jax.random.randint(jax.random.key(11), (8, 16), 0,
                               cfg.vocab)
    base = float(tfm.loss_fn(params, cfg, probe, probe))
    qw = float(tfm.loss_fn(quantize_weights_int8(params, GPT2_WEIGHTS),
                           cfg, probe, probe))
    assert abs(qw - base) / base < 0.02, (base, qw)


def test_unquantized_path_untouched():
    """wread without a _scale companion is exactly astype — the shared
    read path must not perturb normal checkpoints."""
    w = jax.random.normal(jax.random.key(0), (8, 8), jnp.float32)
    out = wread({"w": w}, "w", jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(w.astype(jnp.bfloat16)))
