"""Checkpoint/resume: exact-resume semantics, retention, sharded arrays."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_acx_tpu.checkpoint import Checkpointer
from mpi_acx_tpu.models import init_params, loss_fn, tiny_config
from mpi_acx_tpu.parallel import make_mesh


@pytest.fixture
def cfg_params():
    cfg = tiny_config(n_layers=2)
    return cfg, init_params(jax.random.key(0), cfg)


def _sgd_steps(cfg, params, n, seed=7, lr=0.1):
    """n deterministic SGD steps; returns (params, losses)."""
    tokens = jax.random.randint(jax.random.key(seed), (2, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)
    step = jax.jit(lambda p: jax.value_and_grad(
        lambda p: loss_fn(p, cfg, tokens, targets))(p))
    losses = []
    for _ in range(n):
        loss, g = step(params)
        params = jax.tree.map(lambda p, g: p - lr * g, params, g)
        losses.append(float(loss))
    return params, losses


def test_save_restore_resume_identical(tmp_path, cfg_params):
    """Train 3 steps, checkpoint, train 2 more; a resume from the
    checkpoint replays the exact same trajectory (bit-identical params)."""
    cfg, p0 = cfg_params
    p3, _ = _sgd_steps(cfg, p0, 3)
    with Checkpointer(str(tmp_path / "run")) as ckpt:
        ckpt.save(3, {"params": p3, "step": 3})
        p5, tail = _sgd_steps(cfg, p3, 2)

        state = ckpt.restore(like={"params": p0, "step": 0})
    assert state["step"] == 3
    for a, b in zip(jax.tree.leaves(state["params"]), jax.tree.leaves(p3)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    p5r, tail_r = _sgd_steps(cfg, state["params"], 2)
    assert tail == tail_r  # float-exact replay
    for a, b in zip(jax.tree.leaves(p5), jax.tree.leaves(p5r)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_and_retention(tmp_path, cfg_params):
    cfg, p0 = cfg_params
    with Checkpointer(str(tmp_path / "run"), max_to_keep=2) as ckpt:
        for s in (1, 2, 3):
            ckpt.save(s, {"w": jnp.full((4,), float(s))})
        assert ckpt.latest_step() == 3
        assert ckpt.all_steps() == [2, 3]  # step 1 evicted
        got = ckpt.restore(like={"w": jnp.zeros((4,))})
        np.testing.assert_array_equal(np.asarray(got["w"]), np.full((4,), 3.0))


def test_sharded_roundtrip(tmp_path):
    """Mesh-sharded arrays save and restore with shardings preserved."""
    mesh = make_mesh(8)
    sh = NamedSharding(mesh, P("x"))
    x = jax.device_put(jnp.arange(32.0).reshape(8, 4), sh)
    with Checkpointer(str(tmp_path / "run")) as ckpt:
        ckpt.save(0, {"x": x})
        got = ckpt.restore(like={"x": x})
    assert got["x"].sharding == sh
    np.testing.assert_array_equal(np.asarray(got["x"]), np.asarray(x))


def test_restore_empty_raises(tmp_path):
    with Checkpointer(str(tmp_path / "none")) as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore()


def test_restore_without_like(tmp_path):
    """No-`like` restore returns device arrays with saved values/dtypes."""
    with Checkpointer(str(tmp_path / "run")) as ckpt:
        ckpt.save(1, {"w": jnp.arange(4, dtype=jnp.int32), "step": 1})
        got = ckpt.restore()
    assert got["step"] == 1
    np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(4))
    assert got["w"].dtype == jnp.int32


def test_initialize_env_validation():
    """ACX_COORDINATOR without a process count must raise, not default."""
    import subprocess, sys, os
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["ACX_COORDINATOR"] = "127.0.0.1:1"
    env.pop("ACX_NPROCS", None); env.pop("ACX_SIZE", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r); "
         "from mpi_acx_tpu.parallel import multihost as mh\n"
         "try:\n    mh.initialize()\nexcept ValueError as e:\n"
         "    print('RAISED', e)" % repo],
        env=env, capture_output=True, text=True, timeout=120)
    assert "RAISED" in r.stdout, (r.stdout, r.stderr)
