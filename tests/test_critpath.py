"""Cross-rank causal tracing (include/acx/span.h, tools/acx_critpath.py,
tools/acx_trace_merge.py, docs/DESIGN.md §14): span-exact wire pairing,
barrier-anchored + link-refined clock alignment, critical-path
reconstruction, and the dominant-edge report.

The analyzer tests drive analyze() directly on hand-built traces with
KNOWN clock offsets and transits, so every assertion has an exact
expected value; the end-to-end behavior over real runs is covered by
`make causality-check` (smoke-tested at the bottom) and the np=3 tests,
which use real acxrun traces.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CRITPATH = os.path.join(REPO, "tools", "acx_critpath.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import acx_critpath  # noqa: E402
import acx_trace_merge  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def _built():
    r = subprocess.run(["make", "-C", REPO, "itest", "tools"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


# -- synthetic trace construction -------------------------------------------

def _span(rank, slot, inc):
    """include/acx/span.h layout."""
    return (rank & 0xFFFF) << 48 | (slot & 0xFFFF) << 32 | inc


def _ev(name, ts, slot=0, span=0):
    e = {"name": name, "ph": "i", "s": "t", "pid": 0, "tid": slot,
         "ts": float(ts)}
    if span:
        e["args"] = {"span": span}
    return e


S0 = _span(0, 0, 1)   # rank 0's send op
S1 = _span(1, 0, 2)   # rank 1's reply op
R1 = _span(1, 1, 1)   # rank 1's recv op (local span; wire carries S0)


def _ping_traces(r1_clock_off=0.0, r0_barrier_early=0.0, stall_us=0.0,
                 req_id=None):
    """One serialized 0->1 ping and 1->0 reply with true one-way transit
    10 µs, plus the barrier anchors the merge aligns on.

    r1_clock_off:     added to every RAW rank-1 timestamp (clock skew the
                      barrier anchor must recover).
    r0_barrier_early: rank 0's barrier_exit instants fire this much
                      BEFORE the true barrier release (the root-exits-
                      first asymmetry the per-link refinement corrects).
    stall_us:         extra send-side queueing before rank 0's wire_tx
                      (and everything after it), like a stall fault.
    req_id:           when set, a req_op instant brackets rank 0's send
                      the way the serving layer's span_app_begin does.
    """
    st = stall_us
    r0 = [_ev("barrier_exit", 0.0 - r0_barrier_early)]
    if req_id is not None:
        r0.append(_ev("req_op", 9, slot=0, span=req_id))
    r0 += [
        _ev("isend_enqueue", 10, slot=0, span=S0),
        _ev("trigger_fired", 12, slot=0, span=S0),
        _ev("isend_issued", 14, slot=0, span=S0),
        _ev("wire_tx", 20 + st, slot=-1, span=S0),
        _ev("wire_rx", 120 + st, slot=-1, span=S1),
        _ev("op_completed", 122 + st, slot=0, span=S0),
        _ev("wait_observed", 124 + st, slot=0, span=S0),
        _ev("barrier_exit", 200 + st - r0_barrier_early),
    ]
    off = r1_clock_off
    r1 = [
        _ev("barrier_exit", 0 + off),
        _ev("irecv_enqueue", 5 + off, slot=1, span=R1),
        _ev("wire_rx", 30 + st + off, slot=-1, span=S0),
        _ev("rx_from", 31 + st + off, slot=-1, span=S0),
        _ev("rx_match", 31.5 + st + off, slot=1, span=R1),
        _ev("op_completed", 32 + st + off, slot=1, span=R1),
        _ev("wait_observed", 35 + st + off, slot=1, span=R1),
        _ev("isend_enqueue", 40 + st + off, slot=0, span=S1),
        _ev("isend_issued", 45 + st + off, slot=0, span=S1),
        _ev("wire_tx", 110 + st + off, slot=-1, span=S1),
        _ev("op_completed", 112 + st + off, slot=0, span=S1),
        _ev("barrier_exit", 200 + st + off),
    ]
    return [(0, {"traceEvents": r0}), (1, {"traceEvents": r1})]


# -- span pairing + transit -------------------------------------------------

def test_pairing_and_transit_synced_clocks():
    """With synced clocks both frames pair exactly (rate 1.0) and the
    per-link medians are the true 10 µs transit in each direction."""
    res = acx_critpath.analyze(_ping_traces())
    assert res["paired_frames"] == 2
    assert res["pair_rate"] == 1.0
    assert res["unpaired_tx"] == 0 and res["unpaired_rx"] == 0
    assert set(res["links"]) == {"0->1", "1->0"}
    assert res["links"]["0->1"]["median_us"] == pytest.approx(10.0)
    assert res["links"]["1->0"]["median_us"] == pytest.approx(10.0)
    assert res["links"]["0->1"]["negative"] == 0
    assert res["aligned"] is True


def test_barrier_skew_recovers_clock_offset():
    """A 5 ms raw clock offset on rank 1 disappears behind the barrier
    anchor: transits still come out at the true 10 µs, not 5010."""
    res = acx_critpath.analyze(_ping_traces(r1_clock_off=5000.0))
    assert res["aligned"] is True
    assert res["links"]["0->1"]["median_us"] == pytest.approx(10.0)
    assert res["links"]["1->0"]["median_us"] == pytest.approx(10.0)


def test_link_refinement_corrects_barrier_exit_asymmetry():
    """When rank 0 exits the barrier 100 µs before the true release (the
    root-exits-first bias), the anchor alone would make 0->1 transit
    -90 µs. The per-link symmetric-median refinement must absorb the
    bias: transits return to 10 µs and the fitted offset names it."""
    res = acx_critpath.analyze(_ping_traces(r0_barrier_early=100.0))
    assert res["links"]["0->1"]["median_us"] == pytest.approx(10.0)
    assert res["links"]["1->0"]["median_us"] == pytest.approx(10.0)
    assert res["links"]["0->1"]["negative"] == 0
    assert res["link_offset_us"]["1"] == pytest.approx(100.0)


def test_unpaired_frames_counted():
    """A tx whose frame never showed up on the peer (dropped trace tail)
    is reported as unpaired, not silently matched to something else."""
    traces = _ping_traces()
    r1 = traces[1][1]["traceEvents"]
    traces[1] = (1, {"traceEvents":
                     [e for e in r1 if e["name"] != "wire_rx"]})
    res = acx_critpath.analyze(traces)
    assert res["paired_frames"] == 1          # the 1->0 reply still pairs
    assert res["unpaired_tx"] == 1            # S0's rx is gone
    assert res["pair_rate"] == pytest.approx(0.5)


# -- critical path ----------------------------------------------------------

def test_critical_path_crosses_ranks():
    """The serialized ping's path must cross 0->1 and back 1->0, and the
    µs on the path equal the wall span it covers."""
    res = acx_critpath.analyze(_ping_traces())
    path = res["path"]
    assert path, "empty path"
    crossings = {e["link"] for e in path if e["kind"] == "transit"}
    assert crossings == {"0->1", "1->0"}
    # Contiguous walk: each edge starts where the previous ended.
    for a, b in zip(path, path[1:]):
        assert a["to"] == b["from"]
    assert res["path_us"] == pytest.approx(
        path[-1]["to"]["ts_us"] - path[0]["from"]["ts_us"])


def test_stall_lands_on_tx_queue_edge_with_link():
    """An injected 40 ms send-side stall surfaces as the longest single
    edge: kind tx_queue, attributed to the 0->1 link via the paired rx
    (the wire_tx instant fires at full write, AFTER the stall)."""
    res = acx_critpath.analyze(_ping_traces(stall_us=40000.0))
    le = res["longest_edge"]
    assert le["kind"] == "tx_queue"
    assert le["tx_link"] == "0->1"
    assert le["dt_us"] == pytest.approx(40006.0)
    assert res["dominant"][0]["edge"] == "txq 0->1"


def test_request_split_brackets_ops():
    """A req_op instant (the serving layer's request id) claims the next
    enqueue on its slot; the report splits that op's latency into queue
    (enqueue->issued) and wire (issued->completed) stages."""
    res = acx_critpath.analyze(_ping_traces(req_id=77))
    assert "77" in res["requests"]
    req = res["requests"]["77"]
    assert req["ops"] == 1
    assert req["queue_us"] == pytest.approx(4.0)    # 14 - 10
    assert req["wire_us"] == pytest.approx(108.0)   # 122 - 14


# -- CLI contract -----------------------------------------------------------

def _critpath(*argv):
    return subprocess.run([sys.executable, CRITPATH, *argv],
                          capture_output=True, text=True, timeout=120)


def _write_traces(tmp_path, traces):
    paths = []
    for r, d in traces:
        p = tmp_path / f"ping.rank{r}.trace.json"
        p.write_text(json.dumps(d))
        paths.append(str(p))
    return paths


def test_cli_missing_trace_is_skipped_not_fatal(tmp_path):
    """A dead rank's missing trace is evidence, not an error: the
    analyzer notes the skip on stderr and reports on the survivors."""
    paths = _write_traces(tmp_path, _ping_traces())
    r = _critpath("--json", paths[0], str(tmp_path / "ping.rank9.trace.json"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "skipping" in r.stderr
    out = json.loads(r.stdout)
    assert out["ranks"] == [0]
    assert out["paired_frames"] == 0   # nothing to pair against


def test_cli_expectation_flags_gate(tmp_path):
    """--min-pair-rate / --expect-edge are real gates: they pass on the
    good synthetic run and fail with a named reason when violated."""
    paths = _write_traces(tmp_path, _ping_traces(stall_us=40000.0))
    ok = _critpath("--min-pair-rate", "0.95", "--expect-nonneg-transit",
                   "--expect-edge", "0->1", *paths)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = _critpath("--expect-edge", "1->0", *paths)
    assert bad.returncode == 1
    assert "expected link 1->0" in bad.stderr


def test_cli_unspanned_traces_exit_2(tmp_path):
    """Pre-span (v1) traces have no lifecycle spans at all: the analyzer
    says so and exits 2 rather than printing an empty report."""
    p = tmp_path / "old.rank0.trace.json"
    p.write_text(json.dumps(
        {"traceEvents": [_ev("barrier_exit", 1.0)]}))
    r = _critpath(str(p))
    assert r.returncode == 2
    assert "no spanned lifecycle events" in r.stderr


# -- np=3 barrier-skew alignment (acx_trace_merge) --------------------------

def _np3_trace_files(tmp_path):
    """Three synthetic rank traces whose clocks disagree by KNOWN
    offsets (rank 1 +300 µs, rank 2 -40 µs), each with two barrier
    anchors and one spanned instant between them."""
    paths = []
    for r, off in ((0, 0.0), (1, 300.0), (2, -40.0)):
        d = {"traceEvents": [
            _ev("barrier_exit", 10 + off),
            _ev("isend_enqueue", 100 + off, slot=0, span=_span(r, 0, 1)),
            _ev("barrier_exit", 500 + off),
        ], "otherData": {"dropped": 0}}
        p = tmp_path / f"run.rank{r}.trace.json"
        p.write_text(json.dumps(d))
        paths.append(p)
    return paths


def test_np3_merge_aligns_all_ranks(tmp_path):
    """Three ranks with known clock offsets merge onto one timeline:
    every rank gets the exact recovering skew and the merged stream is
    time-sorted with the spanned instants landing at the same corrected
    instant."""
    paths = _np3_trace_files(tmp_path)
    out = tmp_path / "merged.trace.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "acx_trace_merge.py"),
         "--validate", "--out", str(out)] + [str(p) for p in paths],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout)
    assert summary["valid"], summary
    # target = slowest last anchor (rank 1's 800): exact recovery.
    assert summary["skew_us"] == {"0": pytest.approx(300.0),
                                  "1": pytest.approx(0.0),
                                  "2": pytest.approx(340.0)}
    d = json.loads(out.read_text())
    assert d["otherData"]["ranks"] == [0, 1, 2]
    ts = [e["ts"] for e in d["traceEvents"] if "ts" in e]
    assert ts == sorted(ts)
    enq = [e["ts"] for e in d["traceEvents"]
           if e.get("name") == "isend_enqueue"]
    assert enq == [pytest.approx(400.0)] * 3


def test_np3_merge_survives_missing_rank(tmp_path):
    """Delete rank 2's trace (it 'died before flushing'): the survivors
    still merge aligned, and the gap is recorded as evidence."""
    paths = _np3_trace_files(tmp_path)
    paths[2].unlink()
    out = tmp_path / "merged.trace.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "acx_trace_merge.py"),
         "--validate", "--out", str(out)] + [str(p) for p in paths],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout)
    assert summary["valid"], summary
    assert summary["skew_us"] == {"0": pytest.approx(300.0),
                                  "1": pytest.approx(0.0)}
    assert [m["rank"] for m in summary["missing"]] == [2]
    d = json.loads(out.read_text())
    assert d["otherData"]["missing_ranks"] == [2]


def test_compute_skew_exact_on_synthetic_np3():
    """compute_skew anchors on the LAST common barrier_exit: known
    per-rank offsets come back exactly, against the slowest rank."""
    traces = []
    for r, off in ((0, 0.0), (1, 300.0), (2, -40.0)):
        traces.append((r, {"traceEvents": [
            _ev("barrier_exit", 10 + off),
            _ev("barrier_exit", 500 + off),
        ]}))
    skew = acx_trace_merge.compute_skew(traces)
    # target = max anchor = rank 1's 800; skew[r] = target - anchor[r].
    assert skew[1] == pytest.approx(0.0)
    assert skew[0] == pytest.approx(300.0)
    assert skew[2] == pytest.approx(340.0)


def test_compute_skew_none_without_common_anchor():
    """A rank that never reached a barrier (no common anchors) cannot be
    aligned — skew is None for everyone, never silently wrong."""
    traces = [(0, {"traceEvents": [_ev("barrier_exit", 10)]}),
              (1, {"traceEvents": [_ev("isend_enqueue", 5, span=S0)]})]
    skew = acx_trace_merge.compute_skew(traces)
    assert skew == {0: None, 1: None}


# -- make target ------------------------------------------------------------

def test_makefile_causality_check_target():
    """`make causality-check` (wired into `make check`) goes green: the
    clean leg pairs >= 95% of frames with non-negative median transit,
    and the stalled leg names the 0->1 link as dominant."""
    r = subprocess.run(["make", "-C", REPO, "causality-check"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CAUSALITY CHECK PASSED" in r.stdout
