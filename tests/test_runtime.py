"""ctypes bindings: single-process loopback runtime, plus a 2-process
exchange driven through acxrun running this file as a worker."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def rt():
    from mpi_acx_tpu import runtime
    r = runtime.Runtime()
    yield r
    r.finalize()


def test_loopback_enqueued_sendrecv(rt):
    assert rt.rank == 0 and rt.size == 1
    src = np.arange(64, dtype=np.float32)
    dst = np.zeros(64, dtype=np.float32)
    s = rt.isend_enqueue(src, dest=0, tag=5)
    r = rt.irecv_enqueue(dst, source=0, tag=5)
    st = rt.wait(r)
    rt.wait(s)
    np.testing.assert_array_equal(src, dst)
    assert st.MPI_SOURCE == 0 and st.MPI_TAG == 5
    assert st.acx_bytes == 64 * 4


def test_loopback_partitioned_rounds(rt):
    parts = 8
    send = np.arange(32, dtype=np.int32)
    recv = np.zeros(32, dtype=np.int32)
    sreq = rt.psend_init(send, parts, dest=0, tag=9)
    rreq = rt.precv_init(recv, parts, source=0, tag=9)
    for rnd in range(3):
        send[:] = np.arange(32) * (rnd + 1)
        recv[:] = -1
        rt.start(sreq)
        rt.start(rreq)
        for p in reversed(range(parts)):  # out-of-order readiness
            rt.pready(p, sreq)
        while not rt.parrived(rreq, parts - 1):
            pass
        rt.wait_partitioned(sreq)
        rt.wait_partitioned(rreq)
        np.testing.assert_array_equal(recv, np.arange(32) * (rnd + 1))
    rt.request_free(sreq)
    rt.request_free(rreq)


def test_proxy_stats_populated(rt):
    st = rt.proxy_stats()
    assert st["ops_issued"] > 0
    assert st["ops_completed"] > 0


def test_two_process_python_ring():
    """acxrun -np 2 python <this file as worker>: full Python stack across
    real process boundaries."""
    from mpi_acx_tpu import runtime
    r = subprocess.run(
        [runtime.acxrun_path(), "-np", "2", sys.executable, __file__,
         "--worker"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PYRING OK" in r.stdout


def _worker() -> int:
    sys.path.insert(0, REPO)
    from mpi_acx_tpu import runtime
    rt = runtime.Runtime()
    right = (rt.rank + 1) % rt.size
    left = (rt.rank - 1) % rt.size
    src = np.full(16, rt.rank * 10, dtype=np.int32)
    dst = np.full(16, -1, dtype=np.int32)
    s = rt.isend_enqueue(src, dest=right, tag=1)
    rv = rt.irecv_enqueue(dst, source=left, tag=1)
    st = rt.wait(rv)
    rt.wait(s)
    errs = int(not (dst == left * 10).all() or st.MPI_SOURCE != left)
    errs = rt.allreduce_max(errs)
    if rt.rank == 0 and errs == 0:
        print("PYRING OK")
    rt.finalize()
    return errs


if __name__ == "__main__" and "--worker" in sys.argv:
    raise SystemExit(_worker())
