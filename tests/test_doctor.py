"""Cross-rank hang doctor (tools/acx_doctor.py): pair-matching of stuck
operations across per-rank flight dumps and the culprit diagnosis.

These tests feed the doctor *synthetic* two-rank dumps — the shape
src/core/flightrec.cc writes, boiled down to the fields the matcher keys
on — so each anomaly is exercised in isolation without spinning up real
ranks. The end-to-end path (real watchdog trip under acxrun, real dump
files) is covered by `make doctor-check` / itests/hang-doctor.c.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _doctor():
    spec = importlib.util.spec_from_file_location(
        "acx_doctor", os.path.join(REPO, "tools", "acx_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


doctor = _doctor()


def _dump(rank, size=2, slots=(), peers=(), events=(), reason="watchdog"):
    """A minimal flight dump of the documented shape."""
    return {
        "rank": rank,
        "size": size,
        "reason": reason,
        "now_ns": 5_000_000_000,
        "config": {"events_cap": 8192, "stall_warn_ms": 150,
                   "hang_dump_ms": 400},
        "stats": {"recorded": len(events), "stall_warns": 1,
                  "hang_dumps": 1, "dumps_written": 1},
        "slots": list(slots),
        "peers": list(peers),
        "events": list(events),
    }


def _slot(slot, state, kind, peer, tag, partition=-1, age_ms=500.0):
    return {"slot": slot, "state": state, "kind": kind, "peer": peer,
            "tag": tag, "bytes": 16, "partition": partition,
            "attempts": 1, "error": 0, "age_ms": age_ms}


def _event(kind, slot=-1, peer=-1, tag=-1, seq=0, aux=0):
    return {"t_ns": 1_000_000, "kind": kind, "slot": slot, "peer": peer,
            "tag": tag, "seq": seq, "aux": aux}


def test_unmatched_send_blames_missing_receiver():
    # Rank 0 sends tag 5 to rank 1; rank 1 never posted any recv for it.
    dumps = {
        0: _dump(0, slots=[_slot(3, "ISSUED", "isend", peer=1, tag=5)],
                 events=[_event("isend_enqueue", 3, 1, 5)]),
        1: _dump(1, events=[_event("init", -1, 1)]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "unmatched_send"
    assert diag["culprit"] == 1
    assert any("rank 0 waits on rank 1" in w for w in diag["waits"])


def test_posted_recv_is_not_unmatched():
    # Same stuck send, but rank 1 DID post the matching recv (it's just
    # late-matching) — that is a slow run, not an anomaly.
    dumps = {
        0: _dump(0, slots=[_slot(3, "ISSUED", "isend", peer=1, tag=5)]),
        1: _dump(1, slots=[_slot(0, "ISSUED", "irecv", peer=0, tag=5)]),
    }
    assert doctor.diagnose(dumps)["anomaly"] == "none"


SP = (3 << 32) | 1  # span of rank 0, slot 3, incarnation 1


def test_span_exact_unmatched_send():
    # v2 dumps: rank 1 RECEIVED the frame carrying rank 0's send span
    # (rx_frame row) yet never posted a recv — the diagnosis is
    # span-exact, no heuristic involved.
    dumps = {
        0: _dump(0, slots=[
            dict(_slot(3, "ISSUED", "isend", peer=1, tag=5), span=SP)]),
        1: _dump(1, events=[
            _event("init", -1, 1),
            dict(_event("rx_frame", -1, 0, 5), span=SP)]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "unmatched_send"
    assert diag["culprit"] == 1
    assert "span-exact" in diag["detail"]
    assert "no heuristic" in diag["detail"]


def test_span_pair_conflict_when_heuristic_disagrees():
    # Rank 1 posted a recv that matches (peer, tag) — the heuristic
    # calls the op paired — but NO frame carrying the send's span ever
    # arrived: the bytes are lost in flight, and the disagreement itself
    # is the anomaly (a heuristic-only doctor would have mis-paired).
    dumps = {
        0: _dump(0, slots=[
            dict(_slot(3, "ISSUED", "isend", peer=1, tag=5), span=SP)]),
        1: _dump(1, slots=[
            dict(_slot(0, "ISSUED", "irecv", peer=0, tag=5),
                 span=(1 << 48) | 7)]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "span_pair_conflict"
    assert diag["culprit"] == 0
    assert "lost in flight" in diag["detail"]


def test_span_arrived_and_matched_is_not_an_anomaly():
    # Frame arrived AND the recv is posted: a slow run, nothing to report
    # — the span evidence and the heuristic agree.
    dumps = {
        0: _dump(0, slots=[
            dict(_slot(3, "ISSUED", "isend", peer=1, tag=5), span=SP)]),
        1: _dump(1, slots=[
            dict(_slot(0, "ISSUED", "irecv", peer=0, tag=5),
                 span=(1 << 48) | 7)],
                 events=[dict(_event("rx_frame", -1, 0, 5), span=SP)]),
    }
    assert doctor.diagnose(dumps)["anomaly"] == "none"


def test_pre_span_dumps_keep_heuristic_fallback():
    # The peer's dump is from a pre-span build (no span anywhere): the
    # span-exact step must stand aside and the (peer, tag) heuristic
    # still names the missing receiver, with its own wording.
    dumps = {
        0: _dump(0, slots=[
            dict(_slot(3, "ISSUED", "isend", peer=1, tag=5), span=SP)]),
        1: _dump(1, events=[_event("init", -1, 1)]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "unmatched_send"
    assert diag["culprit"] == 1
    assert "span-exact" not in diag["detail"]


def test_never_published_partition_blames_sender():
    # Rank 1 polls partition 1 from rank 0; rank 0 holds the matching
    # send partition RESERVED with no pready_mark in its history.
    dumps = {
        0: _dump(0, slots=[
            _slot(0, "RESERVED", "pready", peer=1, tag=0, partition=0),
            _slot(1, "RESERVED", "pready", peer=1, tag=0, partition=1),
        ], events=[
            _event("psend_slot", 0, 1, 0, aux=0),
            _event("psend_slot", 1, 1, 0, aux=1),
            _event("pready_mark", 0, 1, 0, aux=0),
        ]),
        1: _dump(1, slots=[
            _slot(1, "ISSUED", "parrived", peer=0, tag=0, partition=1),
        ]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "never_published_partition"
    assert diag["culprit"] == 0
    assert "partition 1" in diag["detail"]


def test_published_partition_is_not_an_anomaly():
    # The sender DID publish partition 1 — data is merely in flight.
    dumps = {
        0: _dump(0, events=[_event("pready_mark", 1, 1, 0, aux=1)]),
        1: _dump(1, slots=[
            _slot(1, "ISSUED", "parrived", peer=0, tag=0, partition=1),
        ]),
    }
    assert doctor.diagnose(dumps)["anomaly"] == "none"


def test_unmatched_recv_blames_silent_sender():
    dumps = {
        0: _dump(0, slots=[_slot(2, "ISSUED", "irecv", peer=1, tag=9)]),
        1: _dump(1, events=[_event("init", -1, 1)]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "unmatched_recv"
    assert diag["culprit"] == 1


def test_tag_mismatch_beats_unmatched():
    # Both sides stuck on each other with different tags: diagnose the
    # tag mismatch, not two separate unmatched ops.
    dumps = {
        0: _dump(0, slots=[_slot(3, "ISSUED", "isend", peer=1, tag=5)]),
        1: _dump(1, slots=[_slot(0, "ISSUED", "irecv", peer=0, tag=6)]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "tag_mismatch"
    assert "tag=5" in diag["detail"] and "tag=6" in diag["detail"]


def test_dead_link_outranks_everything():
    dumps = {
        0: _dump(0,
                 slots=[_slot(3, "ISSUED", "isend", peer=1, tag=5)],
                 peers=[{"rank": 1, "health": "dead", "have_clock": True,
                         "epoch": 2, "tx_seq": 10, "rx_seq": 4,
                         "acked_rx": 4, "replay_bytes": 0}]),
        1: _dump(1, events=[_event("init", -1, 1)]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "dead_link"
    assert diag["culprit"] == 1


def test_barrier_skew_blames_straggler():
    # Ranks 0 and 1 sit inside barrier 2; rank 2 only ever entered one.
    in_barrier = [_event("barrier_enter"), _event("barrier_exit"),
                  _event("barrier_enter")]
    dumps = {
        0: _dump(0, size=3, events=in_barrier),
        1: _dump(1, size=3, events=in_barrier),
        2: _dump(2, size=3,
                 events=[_event("barrier_enter"), _event("barrier_exit")]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "barrier_skew"
    assert diag["culprit"] == 2


def test_clean_run_reports_no_anomaly():
    dumps = {
        0: _dump(0, reason="explicit", events=[
            _event("init", -1, 0), _event("isend_enqueue", 0, 1, 0),
            _event("op_completed", 0, 1, 0), _event("finalize", -1, 0),
        ]),
        1: _dump(1, reason="explicit", events=[
            _event("init", -1, 1), _event("irecv_enqueue", 0, 0, 0),
            _event("op_completed", 0, 0, 0), _event("finalize", -1, 1),
        ]),
    }
    diag = doctor.diagnose(dumps)
    assert diag["anomaly"] == "none"
    assert diag["culprit"] is None
    assert diag["waits"] == []


def test_cli_expectation_oracle(tmp_path, capsys):
    # The CLI is the `make doctor-check` oracle: exit 0 iff the diagnosis
    # matches the --expect-* flags.
    files = []
    d0 = _dump(0, slots=[_slot(3, "ISSUED", "isend", peer=1, tag=5)],
               events=[_event("isend_enqueue", 3, 1, 5)])
    d1 = _dump(1, events=[_event("init", -1, 1)])
    for d in (d0, d1):
        p = tmp_path / f"hang.rank{d['rank']}.flight.json"
        p.write_text(json.dumps(d))
        files.append(str(p))
    assert doctor.main(["--expect-anomaly", "unmatched_send",
                        "--expect-culprit", "1"] + files) == 0
    out = capsys.readouterr().out
    assert "culprit: rank 1" in out
    assert doctor.main(["--expect-anomaly", "dead_link"] + files) == 1
    assert doctor.main(["--expect-culprit", "0"] + files) == 1


def test_cli_json_mode(tmp_path, capsys):
    p = tmp_path / "hang.rank0.flight.json"
    p.write_text(json.dumps(_dump(0, reason="explicit")))
    assert doctor.main(["--json", str(p)]) == 0
    diag = json.loads(capsys.readouterr().out)
    assert diag["anomaly"] == "none"
