"""Ulysses all-to-all sequence parallelism vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.parallel import make_mesh
from mpi_acx_tpu.parallel.ring_attention import (
    blockwise_attention_reference,
    ring_attention_sharded,
)
from mpi_acx_tpu.parallel.ulysses import ulysses_attention_sharded


@pytest.fixture
def qkv():
    S, H, D = 64, 8, 16
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (S, H, D), jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_dense_reference(qkv, causal):
    q, k, v = qkv
    mesh = make_mesh(8)
    got = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    want = blockwise_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_matches_ring_attention(qkv):
    """The two sequence-parallel strategies agree with each other."""
    q, k, v = qkv
    mesh = make_mesh(8)
    a = ulysses_attention_sharded(q, k, v, mesh, causal=True)
    b = ring_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


def test_jit_sharded_end_to_end(qkv):
    """Jitted with sharded inputs: the compiled program keeps the output
    sequence-sharded and numerics intact."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    q, k, v = qkv
    mesh = make_mesh(8)
    sh = NamedSharding(mesh, P("x"))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    f = jax.jit(lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh))
    got = f(qs, ks, vs)
    want = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_head_divisibility_assert(qkv):
    q, k, v = qkv
    mesh = make_mesh(8)
    with pytest.raises(AssertionError):
        ulysses_attention_sharded(q[:, :6], k[:, :6], v[:, :6], mesh)
