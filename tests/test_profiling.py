"""Device-side profiling helpers (mpi_acx_tpu/profiling.py)."""

import glob
import json
import os

import jax
import jax.numpy as jnp

from mpi_acx_tpu import profiling


def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "prof")
    with profiling.trace(logdir):
        with profiling.annotate("matmul"):
            x = jnp.ones((128, 128))
            jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    files = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), files


def test_step_timer_stats_and_dump(tmp_path):
    t = profiling.StepTimer()
    f = jax.jit(lambda a: a * 2 + 1)
    x = jnp.arange(1024.0)
    for _ in range(5):
        with t.step() as region:
            region.sync(f(x))
    s = t.summary()
    assert s["steps"] == 5
    assert 0 < s["min_s"] <= s["p50_s"] <= s["p90_s"] <= s["p99_s"] \
        <= s["max_s"]
    assert abs(s["mean_s"] - sum(t.samples) / 5) < 1e-12
    out = t.dump(str(tmp_path / "steps.json"), extra={"tag": "test"})
    loaded = json.load(open(tmp_path / "steps.json"))
    assert loaded["tag"] == "test" and len(loaded["samples"]) == 5
    assert out["steps"] == 5


def test_step_timer_empty():
    assert profiling.StepTimer().summary() == {"steps": 0}


def test_step_timer_requires_sync():
    t = profiling.StepTimer()
    try:
        with t.step():
            pass
    except RuntimeError as e:
        assert "sync" in str(e)
    else:
        raise AssertionError("unsynced region must raise")
    assert t.samples == []


def test_percentiles_nearest_rank():
    t = profiling.StepTimer()
    t.samples = [float(i) for i in range(1, 11)]   # 1..10
    s = t.summary()
    assert s["min_s"] == 1.0
    assert s["p50_s"] == 5.0    # ceil(0.5*10)=5th smallest
    assert s["p90_s"] == 9.0    # ceil(0.9*10)=9th smallest, not the max
    assert s["p99_s"] == 10.0   # ceil(0.99*10)=10th smallest
    assert s["max_s"] == 10.0
