"""MoE training: top-k routing, auxiliary losses, expert-parallel step.

The reference provides the communication substrate, not MoE (SURVEY.md §0);
these tests validate the framework's EP training composition the same way
test_train.py validates dp x pp x tp — exactly against a single-device
computation of the identical math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from mpi_acx_tpu.models.moe import (
    MoeConfig, init_moe_params, load_balance_loss, make_moe_train_step,
    moe_layer, moe_layer_and_aux, router_z_loss,
)
from mpi_acx_tpu.parallel.mesh import mesh_from_devices


def make_mesh(n, axis="ep"):
    return mesh_from_devices({axis: n}, jax.devices()[:n])


def naive_topk_reference(params, x, gates, k):
    """Per-token loop reference for top-k routing with ample capacity:
    y[t] = sum over the token's k best experts of p_e * expert_e(x[t])."""
    probs = np.asarray(jax.nn.softmax(gates, axis=-1))
    idx = np.argsort(-np.asarray(gates), axis=-1)[:, :k]
    w1 = np.asarray(params["w1"], np.float64)
    w2 = np.asarray(params["w2"], np.float64)
    xs = np.asarray(x, np.float64)
    out = np.zeros_like(xs)
    for t in range(xs.shape[0]):
        for c in range(k):
            e = idx[t, c]
            h = np.asarray(jax.nn.gelu(jnp.asarray(xs[t] @ w1[e])))
            out[t] += probs[t, e] * (h @ w2[e])
    return out


@pytest.mark.parametrize("k", [1, 2])
def test_topk_routing_matches_naive(k):
    """Ample capacity: the einsum dispatch == a per-token loop."""
    cfg = MoeConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=16.0,
                    top_k=k)
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (16, 16), jnp.float32)
    gates = x @ params["gate"]
    y = moe_layer(params, x, cfg)
    want = naive_topk_reference(params, x, gates, k)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4, rtol=1e-4)


def test_top2_capacity_priority():
    """First choices claim expert queues before second choices: with
    capacity 1 per expert, every surviving (expert, slot) belongs to a
    rank-0 choice whenever one wanted it."""
    cfg = MoeConfig(d_model=8, d_ff=16, n_experts=2, capacity_factor=0.5,
                    top_k=2)   # cap = int(0.5 * 4 / 2 + 1) = 2
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (4, 8), jnp.float32)
    gates = x @ params["gate"]
    from mpi_acx_tpu.models.moe import _dispatch_tensors
    dispatch, combine = _dispatch_tensors(gates, 2, k=2)
    # Per-expert load never exceeds capacity.
    load = np.asarray(dispatch.sum(axis=(0, 2)))
    assert (load <= 2 + 1e-6).all()
    # Each surviving (token, expert) weight is that token's router prob.
    probs = np.asarray(jax.nn.softmax(gates, -1))
    sel = np.asarray(dispatch.sum(-1))                   # [T, E] 0/1
    got = np.asarray(combine.sum(-1))
    np.testing.assert_allclose(got, sel * probs, atol=1e-6)
    # Rank-0 choices all survived (T=4 first choices spread over 2
    # experts can exceed cap only if 3+ tokens share a first choice —
    # then the overflow must be the LAST tokens, not rank promotion).
    idx0 = np.argsort(-np.asarray(gates), -1)[:, 0]
    for e in range(2):
        wanted = np.flatnonzero(idx0 == e)
        kept = np.flatnonzero(sel[:, e] > 0)
        # the first min(cap, len) rank-0 claimants are kept
        assert set(wanted[:2]).issubset(set(kept))


def test_load_balance_loss_uniform_vs_collapsed():
    T, E = 256, 8
    # Uniform-ish logits -> loss near its 1.0 minimum.
    g_uni = jax.random.normal(jax.random.key(0), (T, E)) * 1e-3
    lb_uni = float(load_balance_loss(g_uni))
    # All tokens routed to expert 0 -> loss near E.
    g_col = jnp.zeros((T, E)).at[:, 0].set(10.0)
    lb_col = float(load_balance_loss(g_col))
    assert abs(lb_uni - 1.0) < 0.1, lb_uni
    assert lb_col > E * 0.9, lb_col
    # z-loss is positive and finite.
    assert 0 < float(router_z_loss(g_uni)) < 100


@pytest.mark.parametrize("k", [1, 2])
def test_moe_train_step_matches_single_device(k):
    """EP train step over 8 devices: loss AND updated params match the
    identical math computed shard-by-shard on one device (capacity is per
    dispatch group, so the per-shard single-device layer reproduces the
    EP routing exactly — including drops)."""
    ep = 8
    mesh = make_mesh(ep)
    cfg = MoeConfig(d_model=16, d_ff=32, n_experts=8, capacity_factor=2.0,
                    top_k=k)
    params = init_moe_params(jax.random.key(0), cfg)
    T, d = 64, 16
    x = jax.random.normal(jax.random.key(1), (T, d), jnp.float32)
    tgt = jax.random.normal(jax.random.key(2), (T, d), jnp.float32)
    lr, aw, zw = 0.05, 1e-2, 1e-3

    step = make_moe_train_step(cfg, mesh, lr=lr, aux_weight=aw, z_weight=zw)
    loss, new_params = step(params, x, tgt)

    def single_loss(p):
        tl = T // ep
        tot = 0.0
        for s in range(ep):
            xs = jax.lax.dynamic_slice_in_dim(x, s * tl, tl, 0)
            ts = jax.lax.dynamic_slice_in_dim(tgt, s * tl, tl, 0)
            y, aux = moe_layer_and_aux(p, xs, cfg)
            tot = tot + (jnp.sum((y - ts) ** 2) / (T * d)
                         + (aw * aux["load_balance"]
                            + zw * aux["router_z"]) / ep)
        return tot

    want_loss, g = jax.value_and_grad(single_loss)(params)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    want_new = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    for name in ("gate", "w1", "w2"):
        np.testing.assert_allclose(
            np.asarray(new_params[name]), np.asarray(want_new[name]),
            atol=2e-5, rtol=2e-4, err_msg=name)


def test_moe_train_step_learns():
    """A few EP steps reduce the loss on a fixed batch."""
    mesh = make_mesh(8)
    cfg = MoeConfig(d_model=16, d_ff=32, n_experts=8, capacity_factor=4.0,
                    top_k=2)
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (64, 16), jnp.float32)
    tgt = jnp.tanh(x) * 0.5
    step = make_moe_train_step(cfg, mesh, lr=0.5)
    l0, params = step(params, x, tgt)
    for _ in range(5):
        l1, params = step(params, x, tgt)
    assert float(l1) < float(l0)


# -- MoE transformer family ------------------------------------------------

from mpi_acx_tpu.models import moe_transformer as mtf


def test_moe_transformer_forward_and_loss():
    cfg = mtf.tiny_moe_config()
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab)
    logits, aux = jax.jit(
        lambda p, t: mtf.forward(p, cfg, t))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab)
    assert bool(jnp.isfinite(logits).all())
    assert float(aux["load_balance"]) > 0
    loss = mtf.loss_fn(params, cfg, tokens, jnp.roll(tokens, -1, -1))
    # Near-uniform logits at init: CE ~ log(vocab) + small aux terms.
    assert abs(float(loss) - np.log(cfg.vocab)) < 1.0


@pytest.mark.parametrize("k", [1, 2])
def test_moe_transformer_train_step_matches_single_device(k):
    """DP+EP over 8 devices: loss and every updated parameter equal the
    per-shard single-device computation (capacity is per dispatch group,
    so shard-by-shard single-device forward reproduces EP routing
    exactly, drops included)."""
    n = 8
    mesh = make_mesh(n, axis="dp")
    cfg = mtf.tiny_moe_config(n_experts=8, top_k=k)
    params = mtf.init_params(jax.random.key(0), cfg)
    B, S = 16, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, -1)
    lr, aw, zw = 0.05, 1e-2, 1e-3

    step = mtf.make_moe_transformer_train_step(
        cfg, mesh, axis="dp", lr=lr, aux_weight=aw, z_weight=zw)
    loss, new_params = step(params, tokens, targets)

    def single_loss(p):
        bl = B // n
        tot = 0.0
        for s in range(n):
            tk = jax.lax.dynamic_slice_in_dim(tokens, s * bl, bl, 0)
            tg = jax.lax.dynamic_slice_in_dim(targets, s * bl, bl, 0)
            tot = tot + mtf.loss_fn(p, cfg, tk, tg, aw, zw) / n
        return tot

    want_loss, g = jax.value_and_grad(single_loss)(params)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    want_new = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    flat_got = jax.tree_util.tree_flatten_with_path(new_params)[0]
    flat_want = jax.tree_util.tree_flatten_with_path(want_new)[0]
    for (path, got), (_, want) in zip(flat_got, flat_want):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=3e-5, rtol=3e-4,
            err_msg=jax.tree_util.keystr(path))


def test_moe_transformer_train_learns():
    mesh = make_mesh(8, axis="dp")
    cfg = mtf.tiny_moe_config(n_experts=8, top_k=2, capacity_factor=4.0)
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (16, 16), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, -1)
    step = mtf.make_moe_transformer_train_step(cfg, mesh, lr=0.5)
    l0, params = step(params, tokens, targets)
    for _ in range(5):
        l1, params = step(params, tokens, targets)
    assert float(l1) < float(l0)


# -- MoE transformer KV-cache decode ---------------------------------------


def test_moe_decode_matches_forward():
    """Cached single-token decode == dense forward on the growing
    sequence. Capacity is ample (cf = E) so routing is drop-free in both:
    with drops, dense-forward queue priority depends on the whole token
    stream, which per-step decode cannot see — the standard capacity-MoE
    caveat, so serving configs should keep cf >= n_experts."""
    cfg = mtf.tiny_moe_config(n_experts=4, top_k=2, capacity_factor=4.0)
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 12), 0, cfg.vocab)
    _, cache = mtf.prefill(params, cfg, tokens, max_len=32)
    step = jax.jit(lambda c, t: mtf.decode_step(params, cfg, c, t))
    seq = tokens
    for i in range(3):
        nxt = jax.random.randint(jax.random.key(10 + i), (2,), 0, cfg.vocab)
        logits, cache = step(cache, nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        dense, _ = mtf.forward(params, cfg, seq)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(dense[:, -1]),
                                   rtol=2e-3, atol=2e-3)
    assert int(cache["pos"]) == tokens.shape[1] + 3


def test_moe_generate_and_sample():
    cfg = mtf.tiny_moe_config(n_experts=4, top_k=1, capacity_factor=4.0)
    params = mtf.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    out = mtf.generate(params, cfg, prompt, n_new=5)
    assert out.shape == (2, 13)
    assert ((0 <= np.asarray(out)) & (np.asarray(out) < cfg.vocab)).all()
    a = mtf.generate_sample(params, cfg, prompt, 6, jax.random.key(2),
                            temperature=0.8, top_k=16)
    b = mtf.generate_sample(params, cfg, prompt, 6, jax.random.key(2),
                            temperature=0.8, top_k=16)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- MoE family through the flagship dp x pp x tp composition --------------


def test_moe_through_distributed_train_step():
    """The MoE transformer runs the same dp x pp x tp train step as the
    other two families (experts sharded over tp, tokens routed by
    all_to_all inside each pipeline stage), and the step EXACTLY matches
    the single-device math computed per (microbatch, dp-shard) group —
    routing capacity is per dispatch group, so the groups reproduce the
    distributed routing bit-for-bit, drops included. The loss INCLUDES
    the router auxiliaries (threaded through the pipeline scan's aux
    accumulator), matching CE + aux_weight*balance + z_weight*z averaged
    per group exactly as the reference math below computes it."""
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.train import make_train_step

    dp = pp = tp = 2
    mesh = mesh_from_devices({"dp": dp, "pp": pp, "tp": tp})
    cfg = mtf.tiny_moe_config(vocab=67, d_model=32, n_heads=2,
                              n_layers=2 * pp, d_ff=64, n_experts=8,
                              top_k=2, capacity_factor=2.0, max_seq=16)
    params = mtf.init_params(jax.random.key(0), cfg)
    M, mbg, S = 2, 4, 16            # mb_local = mbg/dp = 2
    tokens = jax.random.randint(jax.random.key(1), (M, mbg, S), 0,
                                cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)
    lr = 0.1

    step, n_stages = make_train_step(cfg, mesh, n_micro=M, lr=lr)
    staged = tfm.stage_slice(params, n_stages)
    dist_loss, dist_new = step(staged, tokens, targets)

    mbl = mbg // dp

    def single_loss(p):
        tot = 0.0
        for m in range(M):
            for s_ in range(dp):
                tk = jax.lax.dynamic_slice(tokens, (m, s_ * mbl, 0),
                                           (1, mbl, S))[0]
                tg = jax.lax.dynamic_slice(targets, (m, s_ * mbl, 0),
                                           (1, mbl, S))[0]
                logits, aux = mtf.forward(p, cfg, tk)
                logp = jax.nn.log_softmax(logits, axis=-1)
                ll = jnp.take_along_axis(logp, tg[..., None], -1)[..., 0]
                # CE plus the per-group router auxiliaries (forward
                # returns the layer-mean), averaged over groups — the
                # flagship's per-(layer, microbatch) normalization.
                tot = tot + (-jnp.mean(ll)
                             + 1e-2 * aux["load_balance"]
                             + 1e-3 * aux["router_z"]) / (M * dp)
        return tot

    seq_loss, g = jax.value_and_grad(single_loss)(params)
    np.testing.assert_allclose(float(dist_loss), float(seq_loss),
                               rtol=2e-4)
    seq_new = jax.tree.map(lambda a, b: a - lr * b, params, g)
    seq_staged = tfm.stage_slice(seq_new, n_stages)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_flatten_with_path(dist_new)[0],
            jax.tree_util.tree_flatten_with_path(seq_staged)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3,
            err_msg=jax.tree_util.keystr(ka))


def test_moe_distributed_converges():
    from mpi_acx_tpu.models import transformer as tfm
    from mpi_acx_tpu.train import make_train_step

    mesh = mesh_from_devices({"dp": 2, "pp": 2, "tp": 2})
    cfg = mtf.tiny_moe_config(vocab=32, d_model=32, n_heads=2, n_layers=4,
                              d_ff=64, n_experts=8, capacity_factor=4.0,
                              max_seq=16)
    params = mtf.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 4, 16), 0, 32)
    step, n_st = make_train_step(cfg, mesh, n_micro=2, lr=0.5)
    p = tfm.stage_slice(params, n_st)
    l0, p = step(p, tokens, tokens)
    for _ in range(5):
        l1, p = step(p, tokens, tokens)
    assert float(l1) < float(l0)
