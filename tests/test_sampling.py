"""Stochastic decoding: sample_logits filters and the sample_generate
scaffold across both model families."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.decoding import sample_logits


class TestSampleLogits:
    def _logits(self, key, b=4, v=64):
        return jax.random.normal(key, (b, v), jnp.float32) * 3.0

    def test_temperature_zero_is_argmax(self):
        lg = self._logits(jax.random.key(0))
        got = sample_logits(lg, jax.random.key(1), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.argmax(np.asarray(lg), -1))

    def test_top_k_one_is_argmax(self):
        lg = self._logits(jax.random.key(2))
        got = sample_logits(lg, jax.random.key(3), top_k=1)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.argmax(np.asarray(lg), -1))

    def test_top_k_never_escapes_the_set(self):
        lg = self._logits(jax.random.key(4))
        topk = np.argsort(np.asarray(lg), -1)[:, -8:]
        for i in range(50):
            got = np.asarray(sample_logits(lg, jax.random.key(i), top_k=8))
            for b in range(lg.shape[0]):
                assert got[b] in topk[b]

    def test_top_p_keeps_nucleus_only(self):
        # One token holds 99% of the mass: top_p=0.5 must always pick it.
        lg = jnp.full((2, 16), -10.0).at[:, 3].set(10.0)
        for i in range(20):
            got = np.asarray(sample_logits(lg, jax.random.key(i), top_p=0.5))
            assert (got == 3).all()

    def test_temperature_spreads_mass(self):
        lg = jnp.zeros((1, 8))  # uniform: samples must not all collide
        draws = {int(sample_logits(lg, jax.random.key(i))[0])
                 for i in range(40)}
        assert len(draws) > 3

    def test_jits(self):
        lg = self._logits(jax.random.key(5))
        f = jax.jit(lambda lg, k: sample_logits(lg, k, temperature=0.8,
                                                top_k=8, top_p=0.9))
        assert f(lg, jax.random.key(6)).shape == (4,)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_sample_generate_matches_greedy_at_t0(family):
    if family == "gpt2":
        cfg = tfm.tiny_config(n_layers=2)
        params = tfm.init_params(jax.random.key(0), cfg)
        gen, gen_s = tfm.generate, tfm.generate_sample
    else:
        cfg = lm.tiny_llama(n_layers=2)
        params = lm.init_params(jax.random.key(0), cfg)
        gen, gen_s = lm.generate, lm.generate_sample
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    a = gen(params, cfg, prompt, n_new=6)
    b = gen_s(params, cfg, prompt, n_new=6, key=jax.random.key(2),
              temperature=0.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sample_generate_is_stochastic_and_jittable():
    cfg = tfm.tiny_config(n_layers=2)
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    f = jax.jit(lambda p, t, k: tfm.generate_sample(
        p, cfg, t, n_new=8, key=k, temperature=1.0, top_k=16, top_p=0.95))
    a = f(params, prompt, jax.random.key(2))
    b = f(params, prompt, jax.random.key(3))
    assert a.shape == (2, 16)
    # Prompt preserved; different keys give different continuations.
    np.testing.assert_array_equal(np.asarray(a[:, :8]), np.asarray(prompt))
    assert not np.array_equal(np.asarray(a[:, 8:]), np.asarray(b[:, 8:]))


def test_top_p_zero_still_returns_top1():
    # Degenerate nucleus: top_p=0 must keep the single most likely token
    # (r3 code-review regression: all-masked logits argmax'd to id 0).
    lg = jnp.full((2, 16), -1.0).at[:, 5].set(4.0)
    for i in range(10):
        got = np.asarray(sample_logits(lg, jax.random.key(i), top_p=0.0))
        assert (got == 5).all(), got
