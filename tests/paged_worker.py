"""Worker for the paged-check fleet legs: one role-split serving rank
whose DECODE side seats shipped KV into the PAGED pool
(models/kvpage.py, docs/DESIGN.md §19) instead of fixed slot rows.

Launched by acxrun (``acxrun -np 3 -transport socket python3
tests/paged_worker.py`` with ``ACX_ROLE=prefill,decode,decode``): the
prefill rank runs the unchanged per-layer KV shipper — the wire format
(int8 codes + f32 scales, partition index == layer) is already the
page-resident form, so §17 needs no update to feed a paged decode —
and each decode rank runs ``run_decode_worker(page_tokens=...)``, then
VERIFIES its outputs bit-for-bit against a local monolithic
``serve_greedy(..., kv_int8=True)`` of the same requests. Prints
``DISAGG_OK`` / ``DISAGG_SHIPPED`` plus one ``PAGED_ROW {json}`` line
per rank (bench.py's paged dryrun child parses these).

Under the chaos leg the prefill rank is killed mid-handoff and
respawned by the acx_chaos supervisor; re-shipping is idempotent
(decode discards duplicates by rid) and a torn handoff requeues
UNCHARGED — same rules as tests/disagg_worker.py, now with the paged
intake's allocate/rollback path in the loop.

Knobs: ACX_DISAGG_REQS scales the request count; ACX_PAGED_PT
overrides the page size (default 8 — several pages per request on the
tiny config, so the allocator actually cycles).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The axon sitecustomize pins the tunnel platform via jax.config, which
# wins over the env var; pin back (the bench.py r05 lesson).
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from mpi_acx_tpu import runtime  # noqa: E402
from mpi_acx_tpu.models import transformer as tfm  # noqa: E402
from mpi_acx_tpu.models.disagg import (fleet_roles, run_decode_worker,  # noqa: E402
                                       run_prefill_worker)
from mpi_acx_tpu.models.serving import serve_greedy  # noqa: E402


def main():
    n_reqs = int(os.environ.get("ACX_DISAGG_REQS", "6"))
    pt = int(os.environ.get("ACX_PAGED_PT", "8"))

    cfg = tfm.tiny_config()
    lens = [5, 11, 3, 17, 8, 13, 7, 21, 4, 9]
    max_len, n_slots, chunk = 64, 2, 1
    params = tfm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, cfg.vocab, size=lens[i % len(lens)])
               .astype(np.int32) for i in range(n_reqs)]
    n_new = [3 + (i % 5) for i in range(n_reqs)]

    rt = runtime.Runtime()
    rt.set_deadline(60_000)
    roles = fleet_roles(rt.size)
    role = roles[rt.rank]

    t0 = time.perf_counter()
    if role == "prefill":
        shipped = run_prefill_worker(rt, params, cfg, prompts, max_len,
                                     family=tfm)
        wall = time.perf_counter() - t0
        print(f"DISAGG_SHIPPED rank={rt.rank} n={shipped}", flush=True)
        print("PAGED_ROW " + json.dumps({
            "rank": rt.rank, "role": "prefill",
            "wall_s": round(wall, 4)}), flush=True)
    else:
        batch = run_decode_worker(
            rt, params, cfg, prompts, n_new, n_slots=n_slots,
            max_len=max_len, family=tfm, chunk=chunk,
            page_tokens=pt)
        wall = time.perf_counter() - t0
        mono = serve_greedy(params, cfg, prompts, n_new, n_slots=n_slots,
                            max_len=max_len, chunk=chunk, kv_int8=True)
        m = batch.metrics
        mine = [r.rid for r in m.per_request]
        assert mine, "decode rank owns no requests"
        for rid in mine:
            assert batch[rid] is not None, f"request {rid} unserved"
            np.testing.assert_array_equal(
                batch[rid], mono[rid],
                err_msg=f"rank {rt.rank} request {rid} paged != mono")
        print(f"DISAGG_OK rank={rt.rank} rids={mine} "
              f"requeues={m.requeues} peer_requeues={m.peer_requeues}",
              flush=True)
        print("PAGED_ROW " + json.dumps({
            "rank": rt.rank, "role": "decode",
            "wall_s": round(wall, 4), "page_tokens": pt,
            "requests": len(mine),
            "ttft_p50_s": round(m.ttft_p50_s, 6),
            "requeues": m.requeues,
            "peer_requeues": m.peer_requeues}), flush=True)
    rt.barrier()
    rt.finalize()


if __name__ == "__main__":
    main()
