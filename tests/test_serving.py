"""Continuous-batching serving (models/serving.py): per-slot positions
must make every slot's math identical to its solo run, so the whole
server is pinned by bit-equality against per-request generate().

The reference has no serving stack (SURVEY.md §0); this is
framework-goal surface. The throughput claim (no drain bubble at mixed
output lengths) is structural — covered here by the refill bookkeeping
test; wall-clock lands via bench on the chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.models import llama as lm
from mpi_acx_tpu.models import moe_transformer as moe
from mpi_acx_tpu.models import serving
from mpi_acx_tpu.models import transformer as tfm


def _gpt2():
    cfg = tfm.tiny_config(vocab=61, d_model=48, n_heads=4, n_layers=2,
                          d_ff=96, max_seq=96)
    return cfg, tfm.init_params(jax.random.key(0), cfg), tfm


def _llama():
    cfg = lm.tiny_llama(vocab=61, d_model=48, n_heads=4, n_kv_heads=2,
                        n_layers=2, d_ff=96, max_seq=96)
    return cfg, lm.init_params(jax.random.key(1), cfg), lm


def _moe():
    cfg = moe.tiny_moe_config(vocab=61, d_model=48, n_heads=4, n_layers=2,
                              d_ff=96, max_seq=96, n_experts=4)
    return cfg, moe.init_params(jax.random.key(2), cfg), moe


def _prompts(key, n, vocab, lens):
    ks = jax.random.split(key, n)
    return [np.asarray(jax.random.randint(ks[i], (lens[i % len(lens)],),
                                          0, vocab), np.int32)
            for i in range(n)]


@pytest.mark.parametrize("fam", [_gpt2, _llama, _moe],
                         ids=["gpt2", "llama", "moe"])
def test_continuous_batching_equals_solo_runs(fam):
    """7 requests with staggered lengths through 3 slots: every output
    equals that request's solo greedy generate, bit for bit — including
    the requests that entered mid-stream through a refill."""
    cfg, params, mod = fam()
    n_new, max_len = 6, 32
    prompts = _prompts(jax.random.key(3), 7, cfg.vocab,
                       lens=[5, 9, 3, 12, 7])
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=3,
                               max_len=max_len, family=mod)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n_new,
                            max_len=max_len)
        np.testing.assert_array_equal(np.asarray(g),
                                      np.asarray(want)[0], err_msg=str(p))


def test_more_requests_than_slots_and_single_slot():
    """Queue pressure: 5 requests through ONE slot — pure sequential
    refills — still bit-equal to solo runs."""
    cfg, params, mod = _gpt2()
    prompts = _prompts(jax.random.key(4), 5, cfg.vocab, lens=[4, 6])
    got = serving.serve_greedy(params, cfg, prompts, 4, n_slots=1,
                               max_len=24, family=mod)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], 4,
                            max_len=24)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_eos_retires_early_and_refills():
    """An ``eos`` hit retires the request at the eos token; outputs are
    the solo output truncated at the first eos in the generated part,
    and later requests still complete correctly after the early
    refill."""
    cfg, params, mod = _gpt2()
    n_new, max_len = 8, 32
    prompts = _prompts(jax.random.key(5), 6, cfg.vocab, lens=[5, 8, 11])
    solo = [np.asarray(mod.generate(params, cfg, jnp.asarray(p)[None],
                                    n_new, max_len=max_len))[0]
            for p in prompts]
    # Pick an eos that actually occurs mid-generation somewhere so the
    # early-retire path runs (fall back to an unused id otherwise).
    eos = None
    for s, p in zip(solo, prompts):
        gen = s[len(p):]
        if len(np.unique(gen)) > 1:
            eos = int(gen[0])
            break
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, eos=eos)
    for p, g, s in zip(prompts, got, solo):
        gen = s[len(p):]
        if eos is not None and eos in gen.tolist():
            stop = gen.tolist().index(eos) + 1
            want = np.concatenate([p, gen[:stop]])
        else:
            want = s
        np.testing.assert_array_equal(np.asarray(g), want)


def test_vector_pos_matches_scalar_pos_decode():
    """decode_step with pos [B] (all equal) must equal scalar pos
    exactly — the serving mode is the generate path's math."""
    cfg, params, mod = _gpt2()
    B, S, max_len = 3, 6, 16
    tok = jax.random.randint(jax.random.key(6), (B, S), 0, cfg.vocab)
    _, cache_s = mod.prefill(params, cfg, tok, max_len, last_only=True)
    cache_v = dict(cache_s)
    cache_v["pos"] = jnp.full((B,), S, jnp.int32)
    nxt = jax.random.randint(jax.random.key(7), (B,), 0, cfg.vocab)
    ls, cs = mod.decode_step(params, cfg, cache_s, nxt)
    lv, cv = mod.decode_step(params, cfg, cache_v, nxt)
    np.testing.assert_array_equal(np.asarray(ls), np.asarray(lv))
    np.testing.assert_array_equal(np.asarray(cs["k"]), np.asarray(cv["k"]))
    assert cv["pos"].shape == (B,) and int(cv["pos"][0]) == S + 1


@pytest.mark.parametrize("chunk", [4, 5])
def test_chunked_serving_equals_solo_runs(chunk):
    """chunk>1 amortizes host dispatch without changing a single
    output token (including n_new not divisible by chunk, mid-chunk
    finishes, and refills at chunk boundaries)."""
    cfg, params, mod = _gpt2()
    n_new, max_len = 6, 40
    prompts = _prompts(jax.random.key(8), 6, cfg.vocab, lens=[5, 9, 3])
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, chunk=chunk)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n_new,
                            max_len=max_len)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_per_request_n_new():
    """Mixed output lengths — the workload continuous batching exists
    for: each request stops at ITS OWN n_new, refills backfill the
    freed slots, outputs equal per-request solo runs."""
    cfg, params, mod = _gpt2()
    max_len = 48
    prompts = _prompts(jax.random.key(9), 6, cfg.vocab, lens=[5, 8])
    n_new = [2, 9, 4, 7, 1, 6]
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, chunk=3)
    for p, g, n in zip(prompts, got, n_new):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n,
                            max_len=max_len)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


@pytest.mark.parametrize("fam", [_gpt2, _llama, _moe],
                         ids=["gpt2", "llama", "moe"])
def test_int8_slots_equal_int8_solo(fam):
    """Continuous batching over int8 slot caches: same codes, same
    scales, same scale-on-scores read as the solo kv_int8 run — so
    outputs must be bit-equal to generate(..., kv_int8=True)."""
    cfg, params, mod = fam()
    n_new, max_len = 5, 32
    prompts = _prompts(jax.random.key(10), 5, cfg.vocab, lens=[4, 9, 6])
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, chunk=2,
                               kv_int8=True)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n_new,
                            max_len=max_len, kv_int8=True)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_int8_weight_checkpoint_serves():
    """Weight-only int8 checkpoints (ops/wquant.py) flow through the
    serving tier transparently — every slot op reads weights via
    wread — and outputs equal the solo quantized runs."""
    from mpi_acx_tpu.ops.wquant import GPT2_WEIGHTS, quantize_weights_int8
    cfg, params, mod = _gpt2()
    qparams = quantize_weights_int8(params, GPT2_WEIGHTS)
    prompts = _prompts(jax.random.key(11), 4, cfg.vocab, lens=[5, 8])
    got = serving.serve_greedy(qparams, cfg, prompts, 4, n_slots=2,
                               max_len=24, family=mod, chunk=2)
    for p, g in zip(prompts, got):
        want = mod.generate(qparams, cfg, jnp.asarray(p)[None], 4,
                            max_len=24)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_serve_sample_equals_solo_sampled_runs():
    """Stochastic serving: request rid's key stream is
    fold_in(key, rid) with sample_generate's split discipline, so each
    output must equal the solo generate_sample run under that key —
    regardless of slot assignment, refill order, or chunking."""
    cfg, params, mod = _gpt2()
    n_new, max_len = 5, 40
    base = jax.random.key(42)
    prompts = _prompts(jax.random.key(12), 6, cfg.vocab, lens=[4, 7, 10])
    got = serving.serve_sample(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, key=base, family=mod,
                               temperature=0.9, top_k=17, chunk=3)
    for rid, (p, g) in enumerate(zip(prompts, got)):
        want = mod.generate_sample(params, cfg, jnp.asarray(p)[None],
                                   n_new, jax.random.fold_in(base, rid),
                                   temperature=0.9, top_k=17,
                                   max_len=max_len)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0],
                                      err_msg=f"request {rid}")


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_continuous_batching_equals_solo(tp):
    """Continuous batching composed with tensor parallelism: the same
    host scheduler drives shard_map programs (make_tp_server_fns) whose
    KV slots shard by attention head — outputs must equal the solo
    single-device generate runs bit for bit at any tp (f32, the
    test_tp_inference convention: the matmul split reorders summation,
    and bf16 near-ties on a random-init model would flip argmaxes)."""
    import dataclasses
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_server_fns

    cfg, params, mod = _gpt2()
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    mesh = mesh_from_devices({"tp": tp}, jax.devices()[:tp])
    n_new, max_len, chunk = 5, 32, 3
    prompts = _prompts(jax.random.key(13), 5, cfg.vocab, lens=[4, 9, 6])
    fns = make_tp_server_fns(params, cfg, mesh, chunk=chunk)
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, chunk=chunk,
                               server_fns=fns)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n_new,
                            max_len=max_len)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_tp_serving_int8_weight_checkpoint():
    """The full composition: continuous batching x tensor parallelism x
    int8 weight-only checkpoint (scale-keyed TP program cache + wread)
    — outputs equal the solo single-device quantized runs (f32 per the
    test_tp_inference convention)."""
    import dataclasses
    from mpi_acx_tpu.ops.wquant import GPT2_WEIGHTS, quantize_weights_int8
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_server_fns

    cfg, params, mod = _gpt2()
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    qparams = quantize_weights_int8(params, GPT2_WEIGHTS)
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    prompts = _prompts(jax.random.key(14), 4, cfg.vocab, lens=[5, 8])
    fns = make_tp_server_fns(qparams, cfg, mesh, chunk=2)
    got = serving.serve_greedy(qparams, cfg, prompts, 4, n_slots=2,
                               max_len=24, family=mod, chunk=2,
                               server_fns=fns)
    for p, g in zip(prompts, got):
        want = mod.generate(qparams, cfg, jnp.asarray(p)[None], 4,
                            max_len=24)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_tp_llama_continuous_batching_equals_solo():
    """Llama TP serving: GQA slot caches shard by KV-head group,
    per-slot RoPE positions — outputs equal the solo runs at tp=2
    (f32 per the test_tp_inference convention)."""
    import dataclasses
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_server_fns

    cfg, params, mod = _llama()
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    n_new, max_len, chunk = 5, 32, 3
    prompts = _prompts(jax.random.key(15), 5, cfg.vocab, lens=[4, 9, 6])
    fns = make_tp_server_fns(params, cfg, mesh, chunk=chunk,
                             family="llama")
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, chunk=chunk,
                               server_fns=fns)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n_new,
                            max_len=max_len)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_tp_moe_continuous_batching_equals_solo():
    """MoE TP serving: routed expert FFN through the ffn hook, experts
    sharded n_experts/tp per rank, auto EP dispatch — outputs equal
    the solo runs at tp=2 (f32 per the test_tp_inference convention;
    drop-free capacity so routing is batch-invariant)."""
    import dataclasses
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_server_fns

    cfg, params, mod = _moe()
    cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                              capacity_factor=float(cfg.n_experts))
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    n_new, max_len, chunk = 5, 32, 3
    prompts = _prompts(jax.random.key(16), 5, cfg.vocab, lens=[4, 9, 6])
    fns = make_tp_server_fns(params, cfg, mesh, chunk=chunk,
                             family="moe")
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, chunk=chunk,
                               server_fns=fns)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n_new,
                            max_len=max_len)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


@pytest.mark.parametrize("fam,name", [(_gpt2, "gpt2"), (_llama, "llama")])
def test_tp_int8_kv_slots_equal_solo_int8(fam, name):
    """The last serving composition: continuous batching x tensor
    parallelism x int8 KV slot caches. Each rank quantizes its own
    head slice; outputs equal the solo single-device kv_int8 runs
    (f32 compute per the TP convention — the int8 codes/scales are
    identical per head regardless of the split, so quantization adds
    no TP-specific divergence)."""
    import dataclasses
    from mpi_acx_tpu.parallel.mesh import mesh_from_devices
    from mpi_acx_tpu.parallel.tp_inference import make_tp_server_fns

    cfg, params, mod = fam()
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    mesh = mesh_from_devices({"tp": 2}, jax.devices()[:2])
    n_new, max_len, chunk = 5, 32, 3
    prompts = _prompts(jax.random.key(17), 5, cfg.vocab, lens=[4, 9, 6])
    fns = make_tp_server_fns(params, cfg, mesh, chunk=chunk,
                             family=name, kv_int8=True)
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=max_len, family=mod, chunk=chunk,
                               server_fns=fns, kv_int8=True)
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], n_new,
                            max_len=max_len, kv_int8=True)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_serve_sample_int8_kv_equals_solo():
    """Sampling and the int8 KV cache are orthogonal serving axes —
    together they must still equal the solo sampled int8 runs."""
    cfg, params, mod = _gpt2()
    base = jax.random.key(21)
    prompts = _prompts(jax.random.key(20), 4, cfg.vocab, lens=[5, 8])
    got = serving.serve_sample(params, cfg, prompts, 4, n_slots=2,
                               max_len=24, key=base, family=mod,
                               temperature=0.8, top_k=13, chunk=2,
                               kv_int8=True)
    for rid, (p, g) in enumerate(zip(prompts, got)):
        want = mod.generate_sample(params, cfg, jnp.asarray(p)[None], 4,
                                   jax.random.fold_in(base, rid),
                                   temperature=0.8, top_k=13,
                                   max_len=24, kv_int8=True)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


def test_serving_telemetry():
    """serve_greedy returns a ServedBatch: the outputs behave as the
    plain list they always were, and .metrics carries the batch
    telemetry — per-request TTFT/latency/tokens-per-sec, queue depth,
    slot occupancy, requeue counts."""
    cfg, params, mod = _gpt2()
    n_new = 4
    prompts = _prompts(jax.random.key(22), 5, cfg.vocab, lens=[4, 7, 5])
    got = serving.serve_greedy(params, cfg, prompts, n_new, n_slots=2,
                               max_len=24, family=mod)
    assert isinstance(got, list) and len(got) == 5   # list face intact
    m = got.metrics
    assert isinstance(m, serving.ServingMetrics)
    assert m.requests == 5
    assert m.new_tokens == sum(len(g) - len(p)
                               for p, g in zip(prompts, got)) == 5 * n_new
    assert m.wall_s > 0 and m.tokens_per_s > 0
    assert m.steps > 0 and m.prefills == 5 and m.requeues == 0
    # 5 requests into 2 slots: 3 must have queued behind the seed.
    assert m.queue_depth_max >= 3
    assert 0 < m.slot_occupancy_mean <= 1.0
    assert 0 < m.ttft_p50_s <= m.ttft_p99_s
    assert 0 < m.itl_p50_s <= m.itl_p99_s
    assert len(m.per_request) == 5
    for r in m.per_request:
        assert r.new_tokens == n_new and r.retries == 0
        assert 0 < r.ttft_s <= r.latency_s <= m.wall_s
        assert r.tokens_per_s > 0


def test_serving_telemetry_counts_requeues():
    """A request whose step failed and was re-queued shows up in the
    telemetry (requeues, per-request retries) — and the batch still
    completes bit-equal."""
    cfg, params, mod = _gpt2()
    fns = serving.make_server_fns(params, cfg, mod)
    prefill_fn, step_fn, scatter_fn = fns[0], fns[1], fns[2]
    boom = {"n": 0}

    def flaky_step(slots, tok, keys):
        boom["n"] += 1
        if boom["n"] == 2:
            raise RuntimeError("injected step failure")
        return step_fn(slots, tok, keys)

    prompts = _prompts(jax.random.key(23), 3, cfg.vocab, lens=[4, 6])
    got = serving.serve_greedy(
        params, cfg, prompts, 4, n_slots=2, max_len=24, family=mod,
        server_fns=(prefill_fn, flaky_step, scatter_fn) + fns[3:])
    m = got.metrics
    assert m.requeues >= 1
    assert sum(r.retries for r in m.per_request) >= 1
    for p, g in zip(prompts, got):
        want = mod.generate(params, cfg, jnp.asarray(p)[None], 4,
                            max_len=24)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(want)[0])


# -- RollingSLO window semantics (docs/DESIGN.md §13/§20) -------------------


def test_rolling_slo_empty_window():
    """A fresh (or fully-expired) window reports zeroed percentiles and
    empty lifecycle counters — never a crash on the empty deque."""
    s = serving.RollingSLO(window_s=30.0)
    d = s.live_slos()
    assert d["ttft_n"] == 0 and d["itl_n"] == 0
    assert d["ttft_p50_s"] == 0.0 and d["ttft_p99_s"] == 0.0
    assert d["itl_p50_s"] == 0.0 and d["itl_p99_s"] == 0.0
    assert d["rejections"] == 0 and d["rejects"] == {}
    assert d["preemptions"] == 0 and d["resumes"] == 0


def test_rolling_slo_single_sample():
    """With one sample every percentile IS that sample (nearest-rank,
    no interpolation against phantom neighbors)."""
    s = serving.RollingSLO()
    s.note_ttft(0.25)
    s.note_itl(0.01)
    d = s.live_slos()
    assert d["ttft_n"] == 1
    assert d["ttft_p50_s"] == d["ttft_p99_s"] == 0.25
    assert d["itl_p50_s"] == d["itl_p99_s"] == 0.01


def test_rolling_slo_window_expiry(monkeypatch):
    """Samples older than window_s fall out of the percentiles — the
    30 s default window forgets a slow start once it is 30 s in the
    past, unlike ServingMetrics' whole-batch aggregates."""
    now = {"t": 100.0}
    monkeypatch.setattr(serving.time, "monotonic", lambda: now["t"])
    s = serving.RollingSLO(window_s=30.0)
    s.note_ttft(1.0)
    now["t"] = 110.0
    s.note_ttft(2.0)
    now["t"] = 131.0  # first sample now 31 s old, second only 21 s
    d = s.live_slos()
    assert d["ttft_n"] == 1 and d["ttft_p50_s"] == 2.0
    now["t"] = 200.0  # everything expired
    d = s.live_slos()
    assert d["ttft_n"] == 0 and d["ttft_p50_s"] == 0.0


def test_rolling_slo_lifecycle_counters_cumulative(monkeypatch):
    """Rejections/preemptions/resumes are cumulative, NOT windowed: a
    rejection burst 40 s ago still matters to an operator triaging
    goodput, so expiry must not erase it."""
    now = {"t": 0.0}
    monkeypatch.setattr(serving.time, "monotonic", lambda: now["t"])
    s = serving.RollingSLO(window_s=30.0)
    s.note_reject("queue_full")
    s.note_reject("queue_full")
    s.note_reject("ttft_budget")
    s.note_preempt()
    s.note_resume()
    now["t"] = 1000.0  # far past any window
    d = s.live_slos()
    assert d["rejections"] == 3
    assert d["rejects"] == {"queue_full": 2, "ttft_budget": 1}
    assert d["preemptions"] == 1 and d["resumes"] == 1
