"""Pallas device-side ops: flag signaling kernels + flash attention.

On the CPU test mesh these run through Pallas interpret mode — the exact
same kernel bodies that compile via Mosaic on a real TPU chip (bench.py /
entry() exercise the compiled path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.ops import (
    AVAILABLE, RESERVED, PENDING, COMPLETED,
    pready, pready_many, parrived, parrived_all, produce_and_pready,
    flash_attention, attention_reference,
)


def _table(n=16, state=RESERVED):
    return jnp.full((n,), state, jnp.int32)


class TestFlagKernels:
    def test_pready_sets_one_slot(self):
        flags = pready(_table(), 5)
        assert flags[5] == PENDING
        np.testing.assert_array_equal(
            np.delete(np.asarray(flags), 5), RESERVED)

    def test_pready_traced_index(self):
        # idx may be a traced value (e.g. scan counter) — jit the whole op.
        f = jax.jit(lambda t, i: pready(t, i))
        flags = f(_table(), jnp.int32(3))
        assert flags[3] == PENDING

    def test_pready_many(self):
        flags = pready_many(_table(32), jnp.array([1, 7, 31]))
        assert flags[1] == flags[7] == flags[31] == PENDING
        assert flags[0] == flags[30] == RESERVED

    def test_parrived_polls_without_blocking(self):
        flags = _table()
        assert int(parrived(flags, 4)) == 0          # RESERVED: not arrived
        flags = flags.at[4].set(COMPLETED)
        assert int(parrived(flags, 4)) == 1

    def test_parrived_all(self):
        flags = _table(8, COMPLETED).at[6].set(PENDING)
        assert int(parrived_all(flags, jnp.array([0, 1, 2]))) == 1
        assert int(parrived_all(flags, jnp.array([0, 6]))) == 0

    def test_produce_and_pready_fuses_payload_and_signal(self):
        x = jnp.ones((8, 128), jnp.float32)
        payload, flags = produce_and_pready(
            lambda b: b * 3.0, x, _table(), idx=2)
        np.testing.assert_allclose(np.asarray(payload), 3.0)
        assert flags[2] == PENDING
        assert flags[0] == RESERVED

    def test_state_machine_roundtrip_matches_native_protocol(self):
        # AVAILABLE->RESERVED->PENDING->...->COMPLETED, reference
        # mpi-acx-internal.h:196-203 / include/acx/state.h.
        flags = _table(8, AVAILABLE)
        flags = flags.at[0].set(RESERVED)            # host: slot allocate
        flags = pready(flags, 0)                     # device kernel
        assert flags[0] == PENDING
        flags = flags.at[0].set(COMPLETED)           # proxy: op completed
        assert int(parrived(flags, 0)) == 1


class TestFlashAttention:
    @pytest.mark.parametrize("s,d,causal", [
        (128, 64, True), (256, 64, True), (128, 128, True), (128, 64, False),
    ])
    def test_matches_reference(self, s, d, causal):
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(k1, (2, s, 4, d), jnp.float32)
        k = jax.random.normal(k2, (2, s, 4, d), jnp.float32)
        v = jax.random.normal(k3, (2, s, 4, d), jnp.float32)
        out = flash_attention(q, k, v, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16_inputs(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        q = jax.random.normal(k1, (1, 128, 2, 64), jnp.bfloat16)
        kv = jax.random.normal(k2, (1, 128, 2, 64), jnp.bfloat16)
        out = flash_attention(q, kv, kv)
        ref = attention_reference(q, kv, kv)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=3e-2, rtol=3e-2)

    @pytest.mark.parametrize("streaming", [False, True])
    def test_cross_length_kv_attends_all_keys(self, streaming):
        # Non-causal with Sk != Sq: BOTH kernel paths must attend every
        # key (r3 code-review regression: the resident specs were built
        # from q's S and silently dropped keys past it).
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(11), 3)
        q = jax.random.normal(k1, (1, 128, 2, 32), jnp.float32)
        k = jax.random.normal(k2, (1, 256, 2, 32), jnp.float32)
        v = jax.random.normal(k3, (1, 256, 2, 32), jnp.float32)
        out = flash_attention(q, k, v, causal=False, streaming=streaming,
                              block_q=64, block_k=64)
        ref = attention_reference(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_multiple_q_blocks_causality(self):
        # S spans several q/k blocks; late queries must not see the future.
        q = jnp.ones((1, 512, 1, 64), jnp.float32)
        k = jnp.ones((1, 512, 1, 64), jnp.float32)
        v = jnp.broadcast_to(
            jnp.arange(512, dtype=jnp.float32)[None, :, None, None],
            (1, 512, 1, 64))
        out = flash_attention(q, k, v, block_q=128, block_k=128)
        # With uniform scores, out[t] = mean(v[0..t]) = t/2.
        expect = jnp.arange(512, dtype=jnp.float32) / 2.0
        np.testing.assert_allclose(np.asarray(out[0, :, 0, 0]),
                                   np.asarray(expect), atol=1e-3, rtol=1e-4)


class TestFlashAttentionStreaming:
    """The k-grid streaming kernel (one K/V tile in VMEM, scratch-carried
    online softmax) must match the resident kernel and the dense
    reference, values and grads — it is the long-context path past the
    resident kernel's VMEM ceiling."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(k1, (2, 256, 4, 64), jnp.float32)
        k = jax.random.normal(k2, (2, 256, 4, 64), jnp.float32)
        v = jax.random.normal(k3, (2, 256, 4, 64), jnp.float32)
        out = flash_attention(q, k, v, causal=causal, streaming=True,
                              block_q=64, block_k=64)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grad_matches_dense(self):
        q = jax.random.normal(jax.random.key(8), (1, 128, 2, 32),
                              jnp.float32)
        k = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)
        v = jax.random.normal(jax.random.key(10), q.shape, jnp.float32)
        gs = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, streaming=True, block_q=64, block_k=64) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(lambda q, k, v: (attention_reference(
            q, k, v) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gs, gd):
            err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert err < 1e-5, err

    def test_auto_policy_kicks_in_at_16k(self):
        # streaming=None must select the streaming kernel exactly where
        # the resident kernel's VMEM ceiling is (S >= 16384).
        from mpi_acx_tpu.ops import attention as A
        calls = []
        orig = A._flash

        def spy(qt, kt, vt, causal, bq, bk, streaming=False):
            calls.append(streaming)
            return orig(qt, kt, vt, causal, bq, bk, streaming)

        A._flash = spy
        try:
            x = jnp.zeros((1, 128, 1, 32), jnp.float32)
            A.flash_attention.__wrapped__(x, x, x)          # small: resident
            big = jnp.zeros((1, 16384, 1, 32), jnp.float32)
            A.flash_attention.__wrapped__(big, big, big)    # big: streaming
        finally:
            A._flash = orig
        assert calls == [False, True], calls


class TestFlashAttentionLse:
    """flash_attention_lse: values, the logsumexp output, the two-block
    merge identity (what ring attention builds on), and gradients through
    BOTH outputs."""

    def _qkv(self, key, s, h=2, d=32):
        k1, k2, k3 = jax.random.split(key, 3)
        return (jax.random.normal(k1, (1, s, h, d), jnp.float32),
                jax.random.normal(k2, (1, s, h, d), jnp.float32),
                jax.random.normal(k3, (1, s, h, d), jnp.float32))

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_and_lse(self, causal):
        from mpi_acx_tpu.ops.attention import flash_attention_lse
        q, k, v = self._qkv(jax.random.key(0), 128)
        o, lse = flash_attention_lse(q, k, v, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        # lse against a dense computation.
        d = q.shape[-1]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d)
        if causal:
            mask = jnp.tril(jnp.ones((128, 128), bool))
            logits = jnp.where(mask[None, None], logits, -jnp.inf)
        want = jax.scipy.special.logsumexp(logits, axis=-1)   # [B,H,S]
        np.testing.assert_allclose(np.asarray(lse), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_two_block_merge_identity(self):
        # Attending to K/V halves separately and merging by logaddexp must
        # equal attending to the whole sequence — the ring-attention merge.
        from mpi_acx_tpu.ops.attention import flash_attention_lse
        q, k, v = self._qkv(jax.random.key(1), 128)
        o_full, _ = flash_attention_lse(q, k, v, causal=False)
        o1, l1 = flash_attention_lse(q, k[:, :64], v[:, :64], causal=False)
        o2, l2 = flash_attention_lse(q, k[:, 64:], v[:, 64:], causal=False)
        lse = jnp.logaddexp(l1, l2)
        w1 = jnp.moveaxis(jnp.exp(l1 - lse), 1, 2)[..., None]
        w2 = jnp.moveaxis(jnp.exp(l2 - lse), 1, 2)[..., None]
        merged = o1 * w1 + o2 * w2
        np.testing.assert_allclose(np.asarray(merged), np.asarray(o_full),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_through_both_outputs(self, causal):
        # The lse cotangent feeds dS = P*(dP - D + dLSE): check against
        # jax.grad of the dense formula for a loss that uses o AND lse.
        from mpi_acx_tpu.ops.attention import flash_attention_lse
        q, k, v = self._qkv(jax.random.key(2), 64)
        wl = jax.random.normal(jax.random.key(3), (1, 2, 64), jnp.float32)

        def loss_flash(q, k, v):
            o, lse = flash_attention_lse(q, k, v, causal=causal)
            return (o ** 2).sum() + (wl * lse).sum()

        def loss_dense(q, k, v):
            d = q.shape[-1]
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d)
            if causal:
                mask = jnp.tril(jnp.ones((64, 64), bool))
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            p = jnp.exp(logits - lse[..., None])
            o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
            return (o ** 2).sum() + (wl * lse).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert err < 1e-5, (causal, err)


class TestFlashAttentionGrad:
    """The custom VJP (blockwise lse-recompute backward) must match
    gradients of the dense reference to machine precision."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_grad_matches_dense(self, causal):
        from mpi_acx_tpu.ops.attention import (attention_reference,
                                               flash_attention)
        S = 256
        q = jax.random.normal(jax.random.key(1), (1, S, 2, 64), jnp.float32)
        k = jax.random.normal(jax.random.key(2), (1, S, 2, 64), jnp.float32)
        v = jax.random.normal(jax.random.key(3), (1, S, 2, 64), jnp.float32)
        w = jax.random.normal(jax.random.key(4), q.shape, jnp.float32)
        gf = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, causal=causal) * w).sum(), argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(lambda q, k, v: (attention_reference(
            q, k, v, causal=causal) * w).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert err < 1e-5, (causal, err)

    def test_grad_through_model_loss(self):
        """value_and_grad through a model whose attention is the Pallas
        kernel (the configuration that crashes without the custom VJP)."""
        import dataclasses
        from mpi_acx_tpu.models import init_params, tiny_config
        from mpi_acx_tpu.models.transformer import loss_fn
        cfg = dataclasses.replace(tiny_config(n_layers=2), use_flash=True)
        params = init_params(jax.random.key(0), cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 128), 0,
                                    cfg.vocab)
        targets = jnp.roll(tokens, -1, axis=-1)
        loss, g = jax.value_and_grad(
            lambda p: loss_fn(p, cfg, tokens, targets))(params)
        assert bool(jnp.isfinite(loss))
        assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(g))
