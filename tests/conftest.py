"""Test configuration: virtual 8-device CPU mesh.

Multi-chip behavior (sharding, collectives, pipeline) is validated on a
virtual CPU mesh (XLA host devices); the same code paths run unmodified on
a real TPU slice. The environment pins JAX_PLATFORMS=axon for the real
chip, so we must force cpu via jax.config (which wins over env)."""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


# The suite compiles thousands of XLA executables in ONE process; past
# ~250 tests the accumulated jit cache segfaults jaxlib's CPU compiler
# (r05: three suite runs died at three different late-suite points, all
# inside backend_compile, after the serving tests pushed the count up).
# Dropping the caches at module boundaries bounds the accumulation; the
# next module recompiles what it needs.
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` wall-clock budget; "
        "still run by the packaged make targets (e.g. paged-check), which "
        "invoke their test files unfiltered.")


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    jax.clear_caches()
