"""Test configuration: virtual 8-device CPU mesh.

Multi-chip behavior (sharding, collectives, pipeline) is validated on a
virtual CPU mesh (XLA host devices); the same code paths run unmodified on
a real TPU slice. The environment pins JAX_PLATFORMS=axon for the real
chip, so we must force cpu via jax.config (which wins over env)."""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
