"""Cross-layer contract linter (tools/acx_audit.py, DESIGN.md §18).

Each rule module gets a seeded-violation fixture proving it fires — with
the rule name and a file:line in the message — plus suppression tests for
the allowlist escape hatches, and a clean run over the REAL repo proving
zero false positives (the property `make lint` gates on).

Fixtures are minimal synthetic trees: just the surface files a rule
parses, boiled down to the shapes the real files use.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _audit():
    spec = importlib.util.spec_from_file_location(
        "acx_audit", os.path.join(REPO, "tools", "acx_audit.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


audit = _audit()


# --------------------------------------------------------------------------
# fixture tree

CLEAN_ALLOWLIST = {
    "knobs": {"test_only": {}, "not_knobs": {}, "external_readers": {}},
    "bindings": {"unbound_exports": {}},
    "registry": {"acx_top_deps": []},
    "signal_path": {"extra_edges": {}, "assume_safe": {}},
}

README = """# fixture
Env vars: `ACX_FOO` (the only knob).
"""

DUMMY_CC = """#include <cstdlib>
void Configure() {
  const char* v = getenv("ACX_FOO");
  (void)v;
}
"""

CAPI_CC = """extern "C" {
int acx_ping(int x) { return x; }
void acx_stats(unsigned long long* out, int n) { (void)out; (void)n; }
}
"""

RUNTIME_PY = """import ctypes
def _bind(_lib):
    _lib.acx_ping.restype = ctypes.c_int
    _lib.acx_ping.argtypes = [ctypes.c_int]
    _lib.acx_stats.restype = None
    _lib.acx_stats.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int,
    ]
"""

METRICS_CC = """namespace {
const char* const kCounterName[] = {
    "triggers", "waits", "epoch_g",
};
const char* const kHistName[] = {
    "sweep_ns",
};
void Tail(S& out) {
  out += "},\\"gauges\\":[\\"epoch_g\\"],\\"derived\\":{";
}
}  // namespace
"""

DESIGN_MD = """# fixture design
<!-- acx-audit:registry-table:begin -->
| name | kind | meaning |
|---|---|---|
| `triggers` | counter | t |
| `waits` | counter | w |
| `epoch_g` | gauge | e |
| `sweep_ns` | histogram | s |
<!-- acx-audit:registry-table:end -->
"""

TSERIES_CC = """// generic consumption: kNumCounters CounterName IsGauge
// kNumHists HistName
"""

TOP_PY = '"""fixture console""" \nCOLS = ["triggers"]\n'

FLIGHTREC_CC = """const char* kKindNames[] = {
    "none", "init", "finalize",
};
"""

DOCTOR_PY = '''KNOWN_KINDS = {
    "none", "init", "finalize",
}
'''

REQLOG_PY = """KINDS = frozenset({
    "admit",
    "finish",
})
"""

SERVING_PY = """from mpi_acx_tpu import reqlog
def serve():
    reqlog.emit("admit", 0)
    reqlog.emit("finish", 0)
"""

REQUEST_PY = '''"""fixture journey tool"""
KINDS = {
    "admit": "accepted",
    "finish": "retired",
}
'''

TRACE_CC = """#include <cstdio>
namespace acx { namespace trace {
void Safe() { }
void FlushBestEffort() { Safe(); }
void Enabled() {
  RegisterCrashFlusher(FlushBestEffort, true);
}
} }
"""


def write_tree(tmp_path, **overrides):
    """Materialize the minimal clean fixture tree; overrides replace file
    contents by relative path (None deletes)."""
    files = {
        "README.md": README,
        "src/core/dummy.cc": DUMMY_CC,
        "src/api/capi.cc": CAPI_CC,
        "mpi_acx_tpu/runtime.py": RUNTIME_PY,
        "src/core/metrics.cc": METRICS_CC,
        "docs/DESIGN.md": DESIGN_MD,
        "src/core/tseries.cc": TSERIES_CC,
        "tools/acx_top.py": TOP_PY,
        "src/core/flightrec.cc": FLIGHTREC_CC,
        "tools/acx_doctor.py": DOCTOR_PY,
        "mpi_acx_tpu/reqlog.py": REQLOG_PY,
        "mpi_acx_tpu/models/serving.py": SERVING_PY,
        "mpi_acx_tpu/models/disagg.py": "# fixture: no journey emits\n",
        "mpi_acx_tpu/models/kvpage.py": "# fixture: no journey emits\n",
        "tools/acx_request.py": REQUEST_PY,
        "src/core/trace.cc": TRACE_CC,
        "tools/audit_allowlist.json": json.dumps(CLEAN_ALLOWLIST),
        "include/acx/.keep": "",
    }
    files.update(overrides)
    for rel, content in files.items():
        if content is None:
            continue
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(content)
    return tmp_path


def run_audit(tree, *extra):
    return audit.main(["--root", str(tree)] + list(extra))


def violations(tree, rule=None):
    allow = audit.load_allowlist(str(tree))
    out = []
    for name, fn in audit.RULES:
        if rule is None or name == rule:
            out.extend(fn(str(tree), allow))
    return out


# --------------------------------------------------------------------------
# the clean fixture tree and the real repo: zero false positives

def test_clean_fixture_tree_passes(tmp_path):
    assert run_audit(write_tree(tmp_path)) == 0


def test_real_repo_is_clean():
    # The property `make lint` gates on: the shipped tree audits clean.
    assert audit.main(["--root", REPO]) == 0


def test_json_report_shape(tmp_path, capsys):
    assert run_audit(write_tree(tmp_path), "--json") == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] is True
    assert sorted(report["rules"]) == ["bindings", "flight_kinds",
                                       "journey_kinds", "knobs",
                                       "registry", "signal_path"]
    assert report["violations"] == []


# --------------------------------------------------------------------------
# rule 1: knobs

def test_undocumented_knob_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "src/core/dummy.cc": DUMMY_CC +
        'void Extra() { (void)getenv("ACX_UNDOCUMENTED"); }\n'})
    vs = violations(tree, "knobs")
    assert len(vs) == 1
    v = vs[0]
    assert v.rule == "knobs"
    assert "ACX_UNDOCUMENTED" in v.msg
    assert v.path == os.path.join("src", "core", "dummy.cc")
    assert v.line == 6  # the getenv line in the appended function


def test_stale_readme_knob_fires(tmp_path):
    tree = write_tree(tmp_path,
                      **{"README.md": README + "\nAlso `ACX_GHOST`.\n"})
    vs = violations(tree, "knobs")
    assert [v for v in vs if "ACX_GHOST" in v.msg and v.path == "README.md"
            and v.line == 4]


def test_helper_mediated_read_counts(tmp_path):
    # flightrec-style EnvMsToNs("ACX_X") reads must count as references.
    tree = write_tree(tmp_path, **{
        "src/core/dummy.cc": DUMMY_CC +
        'void H() { EnvMsToNs("ACX_HELPER_KNOB", 5); }\n',
        "README.md": README + "`ACX_HELPER_KNOB` too.\n"})
    assert violations(tree, "knobs") == []


def test_test_only_allowlist_suppresses(tmp_path):
    allow = json.loads(json.dumps(CLEAN_ALLOWLIST))
    allow["knobs"]["test_only"]["ACX_UNDOCUMENTED"] = "fixture test hook"
    tree = write_tree(tmp_path, **{
        "src/core/dummy.cc": DUMMY_CC +
        'void Extra() { (void)getenv("ACX_UNDOCUMENTED"); }\n',
        "tools/audit_allowlist.json": json.dumps(allow)})
    assert violations(tree, "knobs") == []


def test_allowlist_empty_reason_rejected(tmp_path):
    allow = json.loads(json.dumps(CLEAN_ALLOWLIST))
    allow["knobs"]["test_only"]["ACX_X"] = "  "
    tree = write_tree(tmp_path,
                      **{"tools/audit_allowlist.json": json.dumps(allow)})
    assert run_audit(tree) == 2  # the audit refuses to run


# --------------------------------------------------------------------------
# rule 2: bindings

def test_unbound_export_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "src/api/capi.cc": CAPI_CC.replace(
            'extern "C" {\n',
            'extern "C" {\nint acx_orphan(void) { return 0; }\n')})
    vs = violations(tree, "bindings")
    assert len(vs) == 1
    assert "acx_orphan" in vs[0].msg
    assert vs[0].path == os.path.join("src", "api", "capi.cc")
    assert vs[0].line > 0


def test_stale_ctypes_binding_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "mpi_acx_tpu/runtime.py": RUNTIME_PY +
        "    _lib.acx_gone.restype = ctypes.c_int\n"})
    vs = violations(tree, "bindings")
    assert len(vs) == 1
    assert "acx_gone" in vs[0].msg
    assert vs[0].path == os.path.join("mpi_acx_tpu", "runtime.py")


def test_arity_mismatch_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "mpi_acx_tpu/runtime.py": RUNTIME_PY.replace(
            "_lib.acx_ping.argtypes = [ctypes.c_int]",
            "_lib.acx_ping.argtypes = [ctypes.c_int, ctypes.c_int]")})
    vs = violations(tree, "bindings")
    assert len(vs) == 1
    assert "acx_ping" in vs[0].msg and "2" in vs[0].msg and "1" in vs[0].msg


def test_multiline_argtypes_counted(tmp_path):
    # acx_stats's argtypes span lines in the fixture; clean tree already
    # proves arity 2 is read correctly — here shrink the C side to force
    # a mismatch and prove the count is 2, not 1 or 0.
    tree = write_tree(tmp_path, **{
        "src/api/capi.cc": CAPI_CC.replace(
            "void acx_stats(unsigned long long* out, int n)",
            "void acx_stats(unsigned long long* out)")})
    vs = violations(tree, "bindings")
    assert len(vs) == 1
    assert "acx_stats" in vs[0].msg


# --------------------------------------------------------------------------
# rule 3: registry

def test_counter_without_doc_row_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "src/core/metrics.cc": METRICS_CC.replace(
            '"triggers", "waits",', '"triggers", "waits", "brand_new",')})
    vs = violations(tree, "registry")
    assert len(vs) == 1
    assert "brand_new" in vs[0].msg
    assert vs[0].path == os.path.join("docs", "DESIGN.md")


def test_stale_doc_row_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "docs/DESIGN.md": DESIGN_MD.replace(
            "| `sweep_ns` | histogram | s |",
            "| `sweep_ns` | histogram | s |\n| `removed_c` | counter | r |")})
    vs = violations(tree, "registry")
    assert len(vs) == 1
    assert "removed_c" in vs[0].msg and vs[0].line == 9


def test_generic_consumption_token_loss_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "src/core/tseries.cc": TSERIES_CC.replace("kNumCounters ", "")})
    vs = violations(tree, "registry")
    assert len(vs) == 1
    assert "kNumCounters" in vs[0].msg


def test_acx_top_dep_drift_fires(tmp_path):
    allow = json.loads(json.dumps(CLEAN_ALLOWLIST))
    allow["registry"]["acx_top_deps"] = ["triggers", "not_a_counter"]
    tree = write_tree(tmp_path,
                      **{"tools/audit_allowlist.json": json.dumps(allow)})
    msgs = [v.msg for v in violations(tree, "registry")]
    # "triggers" is in the registry AND quoted in the fixture acx_top.py;
    # "not_a_counter" is not a registry entry.
    assert len(msgs) == 1 and "not_a_counter" in msgs[0]


def test_missing_table_markers_is_audit_error(tmp_path):
    tree = write_tree(tmp_path, **{"docs/DESIGN.md": "# no markers\n"})
    assert run_audit(tree) == 2


# --------------------------------------------------------------------------
# rule 4: flight kinds

def test_undecodable_kind_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "src/core/flightrec.cc": FLIGHTREC_CC.replace(
            '"finalize",', '"finalize", "op_zap",')})
    vs = violations(tree, "flight_kinds")
    assert len(vs) == 1
    assert "op_zap" in vs[0].msg
    assert vs[0].path == os.path.join("src", "core", "flightrec.cc")


def test_stale_doctor_kind_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "tools/acx_doctor.py": DOCTOR_PY.replace(
            '"finalize",', '"finalize", "never_emitted",')})
    vs = violations(tree, "flight_kinds")
    assert len(vs) == 1
    assert "never_emitted" in vs[0].msg
    assert vs[0].path == os.path.join("tools", "acx_doctor.py")


# --------------------------------------------------------------------------
# rule 4b: journey kinds

def test_journey_emitted_but_undeclared_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "mpi_acx_tpu/models/disagg.py":
            'from mpi_acx_tpu import reqlog\nreqlog.emit("warp", 0)\n'})
    vs = violations(tree, "journey_kinds")
    # Undeclared in reqlog.KINDS AND undecodable by acx_request.py.
    assert len(vs) == 2
    assert all("warp" in v.msg for v in vs)
    assert vs[0].path == os.path.join("mpi_acx_tpu", "models", "disagg.py")


def test_journey_stale_vocab_and_decode_row_fire(tmp_path):
    tree = write_tree(tmp_path, **{
        "mpi_acx_tpu/reqlog.py": REQLOG_PY.replace(
            '"finish",', '"finish",\n    "never_emitted",')})
    vs = violations(tree, "journey_kinds")
    # Declared-never-emitted and declared-not-decodable both fire.
    assert len(vs) == 2
    assert all("never_emitted" in v.msg for v in vs)
    assert all(v.path == os.path.join("mpi_acx_tpu", "reqlog.py")
               for v in vs)


def test_journey_stale_decode_table_row_fires(tmp_path):
    tree = write_tree(tmp_path, **{
        "tools/acx_request.py": REQUEST_PY.replace(
            '"finish": "retired",',
            '"finish": "retired",\n    "ghost": "stale row",')})
    vs = violations(tree, "journey_kinds")
    assert len(vs) == 1
    assert "ghost" in vs[0].msg
    assert vs[0].path == os.path.join("tools", "acx_request.py")


# --------------------------------------------------------------------------
# rule 5: signal path

BAD_TRACE = """#include <cstdio>
#include <mutex>
namespace acx { namespace trace {
std::mutex g_mu;
void Helper() {
  std::lock_guard<std::mutex> lk(g_mu);
}
void FlushBestEffort() {
  Helper();
  std::fprintf(stderr, "flushing\\n");
}
void Enabled() {
  RegisterCrashFlusher(FlushBestEffort, true);
}
} }
"""


def test_denylisted_calls_in_flusher_fire(tmp_path):
    tree = write_tree(tmp_path, **{"src/core/trace.cc": BAD_TRACE})
    vs = violations(tree, "signal_path")
    labels = "\n".join(v.msg for v in vs)
    # Both the direct fprintf(stderr) in the root and the blocking
    # lock_guard one call away must be flagged, each with the chain.
    assert any("lock_guard" in v.msg and "Helper" in v.msg for v in vs)
    assert any("fprintf" in v.msg and "FlushBestEffort" in v.msg
               for v in vs)
    assert "FlushBestEffort" in labels  # chain names the root
    for v in vs:
        assert v.path == os.path.join("src", "core", "trace.cc")
        assert v.line > 0


def test_to_string_allocation_fires(tmp_path):
    tree = write_tree(tmp_path, **{"src/core/trace.cc": TRACE_CC.replace(
        "void Safe() { }",
        "void Safe() { auto s = std::to_string(7); (void)s; }")})
    vs = violations(tree, "signal_path")
    assert len(vs) == 1 and "to_string" in vs[0].msg


def test_unreachable_function_not_flagged(tmp_path):
    # The same denylisted call OUTSIDE the flusher call graph is fine.
    tree = write_tree(tmp_path, **{"src/core/trace.cc": TRACE_CC.replace(
        "} }",
        "void NotAFlusher() { std::fprintf(stderr, \"x\\n\"); }\n} }")})
    assert violations(tree, "signal_path") == []


def test_static_iife_latch_excluded(tmp_path):
    # A `static x = []{...}()` one-time latch inside a reachable function
    # may allocate/print: it ran before any flusher could fire.
    tree = write_tree(tmp_path, **{"src/core/trace.cc": TRACE_CC.replace(
        "void Safe() { }",
        "int Safe() {\n"
        "  static int v = [] {\n"
        "    std::fprintf(stderr, \"init\\n\");\n"
        "    return 1;\n"
        "  }();\n"
        "  return v;\n"
        "}")})
    assert violations(tree, "signal_path") == []


def test_assume_safe_suppresses_with_reason(tmp_path):
    allow = json.loads(json.dumps(CLEAN_ALLOWLIST))
    allow["signal_path"]["assume_safe"]["Helper"] = \
        "fixture: pretend this latch is safe"
    tree = write_tree(tmp_path, **{
        "src/core/trace.cc": BAD_TRACE.replace(
            'std::fprintf(stderr, "flushing\\n");', ""),
        "tools/audit_allowlist.json": json.dumps(allow)})
    assert violations(tree, "signal_path") == []


def test_extra_edges_extend_the_graph(tmp_path):
    # An indirect call (function pointer) the regex graph cannot see is
    # declared via extra_edges and then traversed.
    allow = json.loads(json.dumps(CLEAN_ALLOWLIST))
    allow["signal_path"]["extra_edges"] = {"FlushBestEffort": ["Hidden"]}
    tree = write_tree(tmp_path, **{
        "src/core/trace.cc": TRACE_CC.replace(
            "} }",
            "void Hidden() { std::printf(\"x\\n\"); }\n} }"),
        "tools/audit_allowlist.json": json.dumps(allow)})
    vs = violations(tree, "signal_path")
    assert len(vs) == 1 and "Hidden" in vs[0].msg


def test_try_forms_not_flagged(tmp_path):
    # TryMutexLock and .try_lock() are the sanctioned flush-path forms.
    tree = write_tree(tmp_path, **{"src/core/trace.cc": TRACE_CC.replace(
        "void Safe() { }",
        "void Safe() {\n"
        "  TryMutexLock lk(g_mu);\n"
        "  if (g_mu.try_lock()) g_mu.unlock();\n"
        "}")})
    assert violations(tree, "signal_path") == []


# --------------------------------------------------------------------------
# CLI / exit-code contract

def test_exit_one_and_rule_named_on_violation(tmp_path, capsys):
    tree = write_tree(tmp_path, **{
        "src/core/dummy.cc": DUMMY_CC +
        'void Extra() { (void)getenv("ACX_UNDOCUMENTED"); }\n'})
    assert run_audit(tree) == 1
    out = capsys.readouterr()
    # `rule: file:line: message` on stdout, rule summary on stderr.
    assert "knobs: " in out.out and "dummy.cc:6:" in out.out
    assert "knobs" in out.err


def test_rule_selection(tmp_path):
    tree = write_tree(tmp_path, **{
        "src/core/dummy.cc": DUMMY_CC +
        'void Extra() { (void)getenv("ACX_UNDOCUMENTED"); }\n'})
    assert run_audit(tree, "--rule", "bindings") == 0
    assert run_audit(tree, "--rule", "knobs") == 1


def test_missing_surface_file_is_audit_error(tmp_path, capsys):
    tree = write_tree(tmp_path)
    os.unlink(str(tree / "src" / "api" / "capi.cc"))
    assert run_audit(tree) == 2
    assert "capi.cc" in capsys.readouterr().err
