"""ON-CHIP proof of the trigger + bridge plane (VERDICT r03 item 2).

Opt-in: these run only in a real TPU session (``ACX_TPU_TESTS=1`` with
the axon tunnel healthy) — the CI/CPU suite covers the same code paths
under the interpreter via test_xla_triggers / test_device_bridge; THIS
file is the evidence that a compiled jitted program on the actual chip
fires io_callback triggers and that a compiled (not interpret-mode)
Pallas flag kernel publishes through the device->proxy bridge, driving
a real 2-rank wire transfer (rank 0 on TPU, rank 1 on CPU).

The same worker also runs in cpu/cpu mode unconditionally, so the
launch plumbing itself stays continuously tested.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "tpu_onchip_worker.py")


def _run(rank0_platform):
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True, timeout=600)
    env = dict(os.environ)
    if rank0_platform == "cpu":
        # CPU/CPU mode must not touch the tunnel.
        env.pop("PYTHONPATH", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
    else:
        # Let rank 0 load the session's real platform (axon): drop the
        # conftest's cpu pin and the virtual-device flags; keep
        # PYTHONPATH (the axon sitecustomize wires the tunnel).
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
    env["ACX_RANK0_PLATFORM"] = rank0_platform
    return subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
         "420", sys.executable, WORKER],
        env=env, capture_output=True, text=True, timeout=480)


def test_onchip_worker_cpu_mode():
    """The worker's program shapes and plumbing, chip-free."""
    r = _run("cpu")
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("ONCHIP_OK") == 2, r.stdout + r.stderr


@pytest.mark.skipif(os.environ.get("ACX_TPU_TESTS") != "1",
                    reason="needs a live TPU session (ACX_TPU_TESTS=1)")
def test_onchip_trigger_and_bridge_real_tpu():
    """Rank 0 on the REAL chip: compiled program fires the trigger,
    compiled Pallas kernel publishes through the bridge."""
    r = _run("default")   # rank 0 keeps the session platform (axon)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ONCHIP_OK tpu" in r.stdout, r.stdout + r.stderr
    assert r.stdout.count("ONCHIP_OK") == 2, r.stdout + r.stderr
