"""ICI-plane collectives on the 8-device virtual mesh, checked against
closed-form numpy expectations (the reference's self-checking-ring test
style, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

from mpi_acx_tpu.parallel import (
    all_to_all_seq,
    halo_exchange_1d,
    halo_exchange_2d,
    make_mesh,
    mesh_from_devices,
    ring_shift,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_ring_shift_moves_shards(mesh):
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    f = shard_map(lambda a: ring_shift(a, "x"), mesh=mesh,
                  in_specs=(P("x"),), out_specs=P("x"))
    y = np.asarray(f(x))
    # Shard i lands on device i+1: row i of output == row i-1 of input.
    np.testing.assert_array_equal(y, np.roll(np.asarray(x), 1, axis=0))


def test_ring_shift_is_enqueued_in_one_program(mesh):
    """The exchange plus surrounding compute is ONE compiled program —
    the 'enqueued' property (no host between compute and comm)."""
    x = jnp.ones((8, 4), jnp.float32)

    @jax.jit
    def fused(a):
        f = shard_map(lambda s: ring_shift(s * 2.0, "x") + 1.0, mesh=mesh,
                      in_specs=(P("x"),), out_specs=P("x"))
        return f(a)

    np.testing.assert_allclose(np.asarray(fused(x)), 3.0)


def test_halo_exchange_1d(mesh):
    n, rows = 8, 6
    x = jnp.arange(n * rows * 3, dtype=jnp.float32).reshape(n * rows, 3)

    def body(shard):
        return halo_exchange_1d(shard, "x", halo=2)[None]

    f = shard_map(body, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))
    out = np.asarray(f(x))  # [8, rows+4, 3]
    xs = np.asarray(x).reshape(n, rows, 3)
    for i in range(n):
        np.testing.assert_array_equal(out[i, 2:-2], xs[i])
        np.testing.assert_array_equal(out[i, :2], xs[(i - 1) % n][-2:])
        np.testing.assert_array_equal(out[i, -2:], xs[(i + 1) % n][:2])


def test_halo_exchange_2d_5point(mesh2=None):
    mesh2 = mesh_from_devices({"r": 2, "c": 4})
    h, w = 4, 6
    x = jnp.arange(2 * h * 4 * w, dtype=jnp.float32).reshape(2 * h, 4 * w)

    def body(shard):
        return halo_exchange_2d(shard, "r", "c", halo=1)[None, None]

    f = shard_map(body, mesh=mesh2, in_specs=(P("r", "c"),),
                  out_specs=P("r", "c"))
    out = np.asarray(f(x))  # [2, 4, h+2, w+2]
    xs = np.asarray(x).reshape(2, h, 4, w).transpose(0, 2, 1, 3)  # [2,4,h,w]
    for r in range(2):
        for c in range(4):
            np.testing.assert_array_equal(out[r, c, 1:-1, 1:-1], xs[r, c])
            # north halo row comes from the row-neighbor above (periodic)
            np.testing.assert_array_equal(out[r, c, 0, 1:-1],
                                          xs[(r - 1) % 2, c][-1])
            # west halo col comes from the col-neighbor left (periodic)
            np.testing.assert_array_equal(out[r, c, 1:-1, 0],
                                          xs[r, (c - 1) % 4][:, -1])


def test_all_to_all_seq_round_trip(mesh):
    # seq-sharded [S/n, H, D] -> head-sharded [S, H/n, D] and back.
    S, H, D = 16, 8, 4
    x = jnp.arange(S * H * D, dtype=jnp.float32).reshape(S, H, D)

    def body(shard):  # shard [S/8, H, D]
        heads = all_to_all_seq(shard, "x", split_axis=1, concat_axis=0)
        back = all_to_all_seq(heads, "x", split_axis=0, concat_axis=1)
        return back

    f = shard_map(body, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))
    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))
