"""Device->proxy flag bridge (SURVEY.md §2 C6, the reference's defining
coupling): a Pallas kernel's flag write must drive a real wire transfer.

Two acxrun ranks; the sender's partition payloads are computed by Pallas
kernels that mark readiness in the same kernel, the readiness crosses the
Python/native boundary into the proxy-polled table, the proxy pushes the
partitions onto the wire, and the receiver's arrival decision is made by
the Pallas parrived kernel over a mirror of the native table. See
tests/device_bridge_worker.py for the per-rank script.
"""

import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "device_bridge_worker.py")


def test_kernel_pready_drives_wire_transfer():
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True, timeout=600)
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # axon sitecustomize pins the tunnel chip
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    import sys
    r = subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
         "240", sys.executable, WORKER],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("BRIDGE_OK 4") == 2, r.stdout + r.stderr
