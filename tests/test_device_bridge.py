"""Device->proxy flag bridge (SURVEY.md §2 C6, the reference's defining
coupling): a Pallas kernel's flag write must drive a real wire transfer.

Two acxrun ranks; the sender's partition payloads are computed by Pallas
kernels that mark readiness in the same kernel, the readiness crosses the
Python/native boundary into the proxy-polled table, the proxy pushes the
partitions onto the wire, and the receiver's arrival decision is made by
the Pallas parrived kernel over a mirror of the native table. See
tests/device_bridge_worker.py for the per-rank script.
"""

import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "device_bridge_worker.py")


def _run_worker(worker, extra_env=None):
    subprocess.run(["make", "-C", REPO, "lib", "tools"], check=True,
                   capture_output=True, timeout=600)
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # axon sitecustomize pins the tunnel chip
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    import sys
    return subprocess.run(
        [os.path.join(REPO, "build", "acxrun"), "-np", "2", "-timeout",
         "480", sys.executable, worker],
        env=env, capture_output=True, text=True, timeout=540)


def test_kernel_pready_drives_wire_transfer():
    r = _run_worker(WORKER)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("BRIDGE_OK 4") == 2, r.stdout + r.stderr


def test_in_program_partitioned_publish(tmp_path):
    """VERDICT r03 item 3: ONE jitted program per rank — the sender's
    ordered io_callback publish nodes fire between Pallas produce
    kernels inside the running program, the receiver's while_loop polls
    the table in-program, and the receiver PROVES overlap by witnessing
    a partially-completed flag table. The ACX_TRACE timeline must show
    the per-partition wire pushes staggered across the program (not a
    tail batch after it)."""
    import json
    tr = str(tmp_path / "ip")
    stagger_s = 0.04
    r = _run_worker(
        os.path.join(REPO, "tests", "device_bridge_inprogram_worker.py"),
        extra_env={"ACX_TRACE": tr, "ACX_IP_STAGGER_S": str(stagger_s)})
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("INPROGRAM_OK 4") == 2, r.stdout + r.stderr

    # Sender-side trace: one pready_wire per partition, spread over at
    # least two stagger intervals — the proxy pushed partitions while
    # the program was still running, not after it returned.
    d = json.loads((tmp_path / "ip.rank0.trace.json").read_text())
    wires = sorted(float(e["ts"]) for e in d["traceEvents"]
                   if e["name"] == "pready_wire")
    assert len(wires) == 4, d["traceEvents"]
    assert wires[-1] - wires[0] > 2 * stagger_s * 1e6, wires
