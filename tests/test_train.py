"""The dp x pp x tp/sp distributed train step must compute EXACTLY the same
step as a single-device implementation of the same math (the strongest
correctness statement available for the parallel composition: every
collective transpose, mask, and reduction must be right for parameters to
match)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.parallel.mesh import mesh_from_devices
from mpi_acx_tpu.train import make_train_step


@pytest.fixture(scope="module")
def setup():
    cfg = tfm.tiny_config(vocab=97, d_model=64, n_heads=4, n_layers=4,
                          d_ff=128, max_seq=32)
    mesh = mesh_from_devices({"dp": 2, "pp": 2, "tp": 2})
    params = tfm.init_params(jax.random.key(0), cfg)
    M, mb, S = 3, 4, 16
    tokens = jax.random.randint(jax.random.key(1), (M, mb, S), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)
    return cfg, mesh, params, tokens, targets


def _sequential_step(cfg, params, tokens, targets, lr):
    """Reference: same math, one device — mean xent over all microbatches,
    one SGD step."""
    M, mb, S = tokens.shape
    flat_t = tokens.reshape(M * mb, S)
    flat_y = targets.reshape(M * mb, S)
    loss, grads = jax.value_and_grad(tfm.loss_fn)(params, cfg, flat_t, flat_y)
    return loss, jax.tree.map(lambda p, g: p - lr * g, params, grads)


def _assert_step_matches_sequential(cfg, mesh, params, tokens, targets,
                                    n_virtual=1, remat=False):
    lr = 0.1
    step, n_stages = make_train_step(cfg, mesh, n_micro=tokens.shape[0],
                                     lr=lr, n_virtual=n_virtual, remat=remat)

    def stage(p):
        if n_virtual > 1:
            return tfm.stage_slice_interleaved(p, n_stages, n_virtual)
        return tfm.stage_slice(p, n_stages)

    staged = stage(params)

    dist_loss, dist_new = step(staged, tokens, targets)
    seq_loss, seq_new = _sequential_step(cfg, params, tokens, targets, lr)

    np.testing.assert_allclose(float(dist_loss), float(seq_loss), rtol=2e-4)

    seq_staged = stage(seq_new)
    flat_d = jax.tree.leaves_with_path(jax.tree.map(np.asarray, dist_new))
    flat_s = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree.leaves_with_path(
            jax.tree.map(np.asarray, seq_staged)))
    for key, got in flat_d:
        want = flat_s[jax.tree_util.keystr(key)]
        np.testing.assert_allclose(
            got, want, atol=5e-4, rtol=5e-3,
            err_msg=f"param {jax.tree_util.keystr(key)} diverged")


def test_distributed_step_matches_sequential(setup):
    cfg, mesh, params, tokens, targets = setup
    _assert_step_matches_sequential(cfg, mesh, params, tokens, targets)


@pytest.mark.parametrize("dp,pp,tp", [(1, 4, 2), (4, 2, 1), (1, 2, 4),
                                      (2, 1, 4), (8, 1, 1)])
def test_step_matches_sequential_across_mesh_shapes(dp, pp, tp):
    """The gradient-reduction construction (exclusive loss paths + the
    pp*tp cotangent rescale under check_vma=False) must hold on EVERY
    mesh factorization, not just the 2x2x2 it was derived on (VERDICT r2
    weak#4: 'validated only on tiny configs')."""
    cfg = tfm.tiny_config(vocab=83, d_model=64, n_heads=4, n_layers=4,
                          d_ff=96, max_seq=32)
    mesh = mesh_from_devices({"dp": dp, "pp": pp, "tp": tp})
    params = tfm.init_params(jax.random.key(5), cfg)
    M, mb, S = 2, 2 * dp, 16
    tokens = jax.random.randint(jax.random.key(6), (M, mb, S), 0, cfg.vocab)
    targets = jnp.roll(tokens, -1, axis=-1)
    _assert_step_matches_sequential(cfg, mesh, params, tokens, targets)


def test_interleaved_schedule_matches_sequential(setup):
    """The interleaved pipeline schedule (n_virtual=2: 4 layers snake
    over pp=2 twice) must produce the SAME step as GPipe and the
    single-device math — same loss, same updated parameters."""
    cfg, mesh, params, tokens, targets = setup
    # n_micro must divide by pp for the interleaved schedule.
    M = tokens.shape[0] - tokens.shape[0] % mesh.shape["pp"]
    _assert_step_matches_sequential(cfg, mesh, params, tokens[:M],
                                    targets[:M], n_virtual=2)


def test_remat_step_matches_sequential(setup):
    """jax.checkpoint per layer must not change the math: the remat step
    produces the same loss and parameters as the plain step and the
    single-device reference (it only trades activation memory for
    recompute FLOPs)."""
    cfg, mesh, params, tokens, targets = setup
    _assert_step_matches_sequential(cfg, mesh, params, tokens, targets,
                                    remat=True)


def test_distributed_training_converges(setup):
    cfg, mesh, params, tokens, targets = setup
    step, n_stages = make_train_step(cfg, mesh, n_micro=tokens.shape[0],
                                     lr=0.3)
    staged = tfm.stage_slice(params, n_stages)
    l0, staged = step(staged, tokens, targets)
    for _ in range(8):
        l1, staged = step(staged, tokens, targets)
    assert float(l1) < float(l0)


def test_optax_adamw_matches_sequential(setup):
    """Distributed AdamW (grads from the shard_map core, update applied by
    optax outside) == single-device AdamW on the same math. One step:
    Adam's g/sqrt(v) normalization turns the first update into ~lr*sign(g),
    so tiny f32 reduction-order differences bound the tolerance at
    O(2*lr) on near-zero-gradient params — any sharding/transpose bug is
    orders of magnitude larger."""
    import optax
    from mpi_acx_tpu.train import make_train_step_optax

    cfg, mesh, params, tokens, targets = setup
    lr = 1e-3
    opt = optax.adamw(lr, weight_decay=0.01)

    step, n_stages = make_train_step_optax(cfg, mesh, n_micro=3,
                                           optimizer=opt)
    staged = tfm.stage_slice(params, n_stages)
    dloss, dp, _ = step(staged, opt.init(staged), tokens, targets)

    # sequential reference on the same staged tree
    M, mb, S = tokens.shape
    flat_t, flat_y = tokens.reshape(M * mb, S), targets.reshape(M * mb, S)

    def seq_loss(p):
        flat = dict(p)
        flat["layers"] = jax.tree.map(
            lambda x: x.reshape((-1,) + x.shape[2:]), p["layers"])
        return tfm.loss_fn(flat, cfg, flat_t, flat_y)

    sloss, g = jax.value_and_grad(seq_loss)(staged)
    upd, _ = opt.update(g, opt.init(staged), staged)
    sp = optax.apply_updates(staged, upd)

    np.testing.assert_allclose(float(dloss), float(sloss), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(dp), jax.tree.leaves(sp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3 * lr, rtol=1e-2)


def test_optax_adamw_converges(setup):
    import optax
    from mpi_acx_tpu.train import make_train_step_optax

    cfg, mesh, params, tokens, targets = setup
    opt = optax.adamw(3e-3)
    step, n_stages = make_train_step_optax(cfg, mesh, n_micro=3,
                                           optimizer=opt)
    p = tfm.stage_slice(params, n_stages)
    s = opt.init(p)
    l0, p, s = step(p, s, tokens, targets)
    for _ in range(6):
        l1, p, s = step(p, s, tokens, targets)
    assert float(l1) < float(l0)


def test_optax_state_checkpoints(setup, tmp_path):
    """Optimizer moments checkpoint and restore for an exact resume."""
    import optax
    from mpi_acx_tpu.checkpoint import Checkpointer
    from mpi_acx_tpu.train import make_train_step_optax

    cfg, mesh, params, tokens, targets = setup
    opt = optax.adamw(1e-3)
    step, n_stages = make_train_step_optax(cfg, mesh, n_micro=3,
                                           optimizer=opt)
    p = tfm.stage_slice(params, n_stages)
    s = opt.init(p)
    for _ in range(2):
        _, p, s = step(p, s, tokens, targets)
    with Checkpointer(str(tmp_path / "run")) as ck:
        ck.save(2, {"params": p, "opt": s})
        la, pa, _ = step(p, s, tokens, targets)
        st = ck.restore(like={"params": p, "opt": s})
    lb, pb, _ = step(st["params"], st["opt"], tokens, targets)
    assert float(la) == float(lb)
    for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
