"""Worker for tests/test_device_bridge.py: one acxrun rank.

Demonstrates the full device->proxy->wire->device coupling the reference
prototypes with CUDA kernels writing host-mapped flags
(partitioned.cu:200-212 -> init.cpp:82-115), TPU-native:

rank 0 (sender): per partition, ONE Pallas kernel (ops.flags.
produce_and_pready) computes the partition payload AND marks its flag
word PENDING in the device flag buffer; the buffer is mirrored into the
native table (Runtime.publish_partition_flags), where the proxy observes
PENDING and pushes the partition onto the wire.

rank 1 (receiver): polls the native table into a device mirror
(Runtime.fetch_partition_flags) and asks the Pallas parrived_all kernel —
never the host — whether every partition has COMPLETED, then verifies the
payloads the sender's kernels computed.

Prints BRIDGE_OK <published> on success.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mpi_acx_tpu.ops import flags as fl  # noqa: E402
from mpi_acx_tpu.runtime import Runtime  # noqa: E402

PARTS = 4
ROWS, LANES = 8, 128  # one partition's payload tile


def main():
    rt = Runtime()
    assert rt.size == 2, rt.size
    peer = 1 - rt.rank
    buf = np.zeros((PARTS, ROWS, LANES), dtype=np.float32)

    if rt.rank == 0:
        req = rt.psend_init(buf, PARTS, dest=peer)
        rt.start(req)
        # Device flag buffer, one word per partition, protocol constants
        # shared with the native table (ops/flags.py == acx/state.h).
        dev_flags = jnp.full((PARTS,), fl.RESERVED, jnp.int32)
        published = 0
        for p in range(PARTS):
            x = jnp.full((ROWS, LANES), float(p + 1), jnp.float32)
            # ONE kernel: compute payload + publish readiness (the pattern
            # the reference's partitioned API exists for).
            payload, dev_flags = fl.produce_and_pready(
                lambda t: t * 2.0 + 1.0, x, dev_flags, p)
            assert int(dev_flags[p]) == fl.PENDING
            buf[p] = np.asarray(payload)  # payload lands in the wire buffer
            n = rt.publish_partition_flags(req, np.asarray(dev_flags))
            published += n
        assert published == PARTS, published
        # Re-publishing the same buffer is idempotent (CAS in native land).
        assert rt.publish_partition_flags(req, np.asarray(dev_flags)) == 0
        rt.wait(req)
        rt.request_free(req)
        rt.barrier()
        print(f"BRIDGE_OK {published}")
    else:
        req = rt.precv_init(buf, PARTS, source=peer)
        rt.start(req)
        idxs = jnp.arange(PARTS)
        deadline = time.time() + 60
        while True:
            # Native words -> device mirror -> Pallas poll (the kernel, not
            # the host, decides arrival — reference ring-partitioned.cu's
            # wait_until_arrived, as a poll per the no-device-spin rule).
            mirror = jnp.asarray(rt.fetch_partition_flags(req))
            if int(fl.parrived_all(mirror, idxs)) == 1:
                break
            if time.time() > deadline:
                raise TimeoutError("partitions never arrived")
            time.sleep(0.001)
        rt.wait(req)
        for p in range(PARTS):
            np.testing.assert_array_equal(buf[p], (p + 1) * 2.0 + 1.0)
        rt.request_free(req)
        rt.barrier()
        print(f"BRIDGE_OK {PARTS}")

    rt.finalize()


if __name__ == "__main__":
    main()
