"""Speculative decoding: exact greedy equality and acceptance accounting.

The defining property: for ANY draft model, the output tokens equal the
target-only greedy decode — the draft changes only the round count.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.speculative import speculative_generate


def _cfg(n_layers, max_seq=128, vocab=64):
    c = tfm.tiny_config(vocab=vocab, d_model=32, n_heads=2,
                        n_layers=n_layers, d_ff=64, max_seq=max_seq)
    return tfm.TransformerConfig(**{**c.__dict__, "dtype": jnp.float32})


@pytest.mark.parametrize("k", [2, 4])
def test_exact_match_random_draft(k):
    """A random (unrelated) draft: almost nothing gets accepted, output
    still EXACTLY equals the target-only greedy decode."""
    cfg = _cfg(2)
    dcfg = _cfg(1)
    params = tfm.init_params(jax.random.key(0), cfg)
    dparams = tfm.init_params(jax.random.key(7), dcfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new = 24

    want = tfm.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # Every round emits at least one token.
    assert int(stats["rounds"]) <= n_new


def test_perfect_draft_amortizes_rounds():
    """Draft == target: every proposal is accepted, so each round emits k
    tokens and the target runs ~n_new/k window passes instead of n_new
    steps — the speedup mechanism, observable in the round count."""
    cfg = _cfg(2)
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 24, 4

    want = tfm.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(params, cfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds = int(stats["rounds"])
    # ceil((n_new - 1) / k) + 1 rounds would be perfect; allow slack for
    # the prefill bonus accounting but require real amortization.
    assert rounds <= -(-n_new // k) + 1, rounds
    assert int(stats["drafted_accepted"]) >= (k - 1) * (rounds - 1)


def test_trained_draft_accepts_most():
    """A draft trained on the same copy task as the target accepts most
    proposals — the realistic deployment regime (distilled draft)."""
    cfg = _cfg(2, vocab=32)
    dcfg = _cfg(1, vocab=32)
    tok = jax.random.randint(jax.random.key(1), (8, 16), 0, 32)
    tgt = tok   # predict-current: rollout repeats the final token

    def train(c, key, steps=60):
        p = tfm.init_params(key, c)
        import optax
        opt = optax.adam(3e-2)
        st = opt.init(p)
        loss_g = jax.jit(jax.value_and_grad(
            lambda p: tfm.loss_fn(p, c, tok, tgt)))
        for _ in range(steps):
            _, g = loss_g(p)
            up, st = opt.update(g, st)
            p = optax.apply_updates(p, up)
        return p

    params = train(cfg, jax.random.key(0))
    dparams = train(dcfg, jax.random.key(9))
    prompt = tok[:1, :8]
    n_new, k = 16, 4

    want = tfm.generate(params, cfg, prompt, n_new, max_len=8 + n_new + k)
    got, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds = int(stats["rounds"])
    acc = int(stats["drafted_accepted"])
    # Both models learned the task, so acceptance is high and rounds are
    # far below n_new (each round emits ~k tokens).
    assert rounds <= n_new // 2, (rounds, acc)
    assert acc >= rounds, (rounds, acc)


def test_batched_rows_match_single_row_runs():
    """B=8: every row of the batched greedy decode equals BOTH the
    target-only greedy decode of that row and the B=1 speculative run of
    that row, and each row's round count matches its own B=1 run — rows
    advance independently through the vmap-lifted loop; neighbors cannot
    change a row's output or its pace.

    The batch is GENUINELY mixed-pace (asserted): with these seeds the
    per-row round counts span 4..9, so a regression that couples rows —
    e.g. stats not select-guarded, every row reporting the slowest row's
    rounds — cannot hide behind uniform acceptance."""
    cfg = _cfg(2)
    dcfg = _cfg(1)
    params = tfm.init_params(jax.random.key(0), cfg)
    dparams = tfm.init_params(jax.random.key(7), dcfg)
    B, n_new, k = 8, 16, 4
    prompts = jax.random.randint(jax.random.key(1), (B, 8), 0, cfg.vocab)

    got, stats = speculative_generate(dparams, dcfg, params, cfg,
                                      prompts, n_new, k=k)
    assert got.shape == (B, 8 + n_new)
    assert stats["rounds"].shape == (B,)
    rounds = [int(r) for r in stats["rounds"]]
    assert len(set(rounds)) > 1, rounds      # really mixed pace
    for b in range(B):
        row = prompts[b:b + 1]
        want = tfm.generate(params, cfg, row, n_new,
                            max_len=8 + n_new + k)
        np.testing.assert_array_equal(np.asarray(got[b:b + 1]),
                                      np.asarray(want))
        solo, sstats = speculative_generate(dparams, dcfg, params, cfg,
                                            row, n_new, k=k)
        np.testing.assert_array_equal(np.asarray(got[b:b + 1]),
                                      np.asarray(solo))
        assert int(stats["rounds"][b]) == int(sstats["rounds"])
        assert (int(stats["drafted_accepted"][b])
                == int(sstats["drafted_accepted"]))


def test_batched_sample_rows_match_single_row_subkey_runs():
    """Batched STOCHASTIC decode: row b of the B>1 call must equal the
    B=1 ``speculative_sample`` run with ``jax.random.split(key, B)[b]``
    — the documented per-row key fold — proving the vmapped while_loop
    select-guards the stochastic carry (keys, residual resampling, buf)
    exactly as the greedy one."""
    from mpi_acx_tpu.models.speculative import speculative_sample
    cfg = _cfg(2)
    dcfg = _cfg(1)
    params = tfm.init_params(jax.random.key(0), cfg)
    dparams = tfm.init_params(jax.random.key(7), dcfg)
    B, n_new, k = 4, 12, 4
    prompts = jax.random.randint(jax.random.key(2), (B, 8), 0, cfg.vocab)
    key = jax.random.key(11)

    got, stats = speculative_sample(dparams, dcfg, params, cfg, prompts,
                                    n_new, key, k=k, temperature=0.9)
    assert got.shape == (B, 8 + n_new)
    subkeys = jax.random.split(key, B)
    for b in range(B):
        solo, sstats = speculative_sample(dparams, dcfg, params, cfg,
                                          prompts[b:b + 1], n_new,
                                          subkeys[b], k=k, temperature=0.9)
        np.testing.assert_array_equal(np.asarray(got[b:b + 1]),
                                      np.asarray(solo))
        assert int(stats["rounds"][b]) == int(sstats["rounds"])


def test_no_draft_cache_hole_at_full_acceptance():
    """Regression: at full acceptance the rollback jumps past the last
    proposal's seat; the draft must still have written that cache entry
    (an unwritten zero K/V row would perturb every later draft step and
    silently erode acceptance). With draft == target, acceptance must
    stay PERFECT across many rounds — any hole shows up as a rejected
    proposal."""
    cfg = _cfg(2, max_seq=256)
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 61, 4
    want = tfm.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(params, cfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds, acc = int(stats["rounds"]), int(stats["drafted_accepted"])
    assert acc == rounds * (k - 1), (acc, rounds)


# -- Llama family ----------------------------------------------------------

from mpi_acx_tpu.models import llama as lm


def _lcfg(n_layers, n_kv=2, max_seq=128, vocab=64):
    c = lm.tiny_llama(vocab=vocab, d_model=32, n_heads=4, n_kv_heads=n_kv,
                      n_layers=n_layers, d_ff=64, max_seq=max_seq)
    return lm.LlamaConfig(**{**c.__dict__, "dtype": jnp.float32})


def test_llama_exact_match_random_draft():
    """GQA window verification: output equals llama.generate exactly
    for an unrelated random draft."""
    cfg, dcfg = _lcfg(2), _lcfg(1)
    params = lm.init_params(jax.random.key(0), cfg)
    dparams = lm.init_params(jax.random.key(7), dcfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 20, 4
    want = lm.generate(params, cfg, prompt, n_new,
                       max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_llama_perfect_draft_full_acceptance():
    cfg = _lcfg(2, max_seq=256)
    params = lm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 41, 4
    want = lm.generate(params, cfg, prompt, n_new,
                       max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(params, cfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds, acc = int(stats["rounds"]), int(stats["drafted_accepted"])
    assert acc == rounds * (k - 1), (acc, rounds)
    assert rounds <= -(-n_new // k) + 1, rounds


# -- Stochastic speculative sampling (accept/resample) ---------------------

from mpi_acx_tpu.models.speculative import speculative_sample


def test_speculative_sample_distribution_matches_target():
    """The algorithm's defining guarantee: emitted tokens follow the
    TARGET's sampling distribution exactly, regardless of the draft.
    Checked on the joint distribution of the first TWO generated tokens
    (the second flows through the accept/resample round) against exact
    teacher-forced target probabilities, with a differentiated draft so
    both the accept and the resample branches fire."""
    V = 8
    cfg = _cfg(1, vocab=V, max_seq=32)
    dcfg = _cfg(1, vocab=V, max_seq=32)
    # Scale up the random weights so the distributions are far from
    # uniform (near-zero logits would give the test no power).
    sharpen = lambda p: jax.tree.map(lambda a: a * 8.0, p)  # noqa: E731
    params = sharpen(tfm.init_params(jax.random.key(0), cfg))
    dparams = sharpen(tfm.init_params(jax.random.key(9), dcfg))
    prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
    S, n_new, k, temp = prompt.shape[1], 2, 3, 1.0

    # Exact target joint: p(a | prompt) * p(b | prompt + a).
    p1 = jax.nn.softmax(tfm.forward(params, cfg, prompt)[0, -1] / temp)
    exts = jnp.concatenate(
        [jnp.repeat(prompt, V, 0),
         jnp.arange(V, dtype=jnp.int32)[:, None]], axis=1)     # [V, S+1]
    p2 = jax.nn.softmax(
        tfm.forward(params, cfg, exts)[:, -1] / temp, axis=-1)  # [V, V]
    joint_t = np.asarray(p1[:, None] * p2)
    # Draft joint (negative control — must differ, or the test is blind).
    q1 = jax.nn.softmax(tfm.forward(dparams, dcfg, prompt)[0, -1] / temp)
    q2 = jax.nn.softmax(
        tfm.forward(dparams, dcfg, exts)[:, -1] / temp, axis=-1)
    joint_d = np.asarray(q1[:, None] * q2)
    power = 0.5 * np.abs(joint_t - joint_d).sum()
    assert power > 0.2, f"draft too similar to target; no power: {power}"

    # Empirical joint over many keys (vmapped compiled runs).
    from mpi_acx_tpu.models.speculative import _build_sample
    run = _build_sample(dcfg, cfg, S, n_new, k, temp)
    N = 6000
    keys = jax.random.split(jax.random.key(123), N)
    toks = jax.vmap(lambda kk: run(dparams, params, prompt, kk)[0])(keys)
    pairs = np.asarray(toks[:, 0, S:S + 2])
    emp = np.zeros((V, V))
    for a, b in pairs:
        emp[a, b] += 1.0 / N
    tv_target = 0.5 * np.abs(emp - joint_t).sum()
    tv_draft = 0.5 * np.abs(emp - joint_d).sum()
    # Sampling noise floor at N=6000 over 64 cells is ~0.05-0.08.
    assert tv_target < 0.12, (tv_target, tv_draft)
    assert tv_draft > tv_target + 0.05, (tv_target, tv_draft)


def test_speculative_sample_reproducible_and_valid():
    cfg = _cfg(2, max_seq=128)
    dcfg = _cfg(1, max_seq=128)
    params = tfm.init_params(jax.random.key(0), cfg)
    dparams = tfm.init_params(jax.random.key(7), dcfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    a, sa = speculative_sample(dparams, dcfg, params, cfg, prompt, 20,
                               jax.random.key(3), k=4, temperature=0.8)
    b, _ = speculative_sample(dparams, dcfg, params, cfg, prompt, 20,
                              jax.random.key(3), k=4, temperature=0.8)
    c, _ = speculative_sample(dparams, dcfg, params, cfg, prompt, 20,
                              jax.random.key(4), k=4, temperature=0.8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) != np.asarray(c)).any()
    body = np.asarray(a)
    assert ((0 <= body) & (body < cfg.vocab)).all()
    assert int(sa["rounds"]) <= 20


# -- MoE family ------------------------------------------------------------

from mpi_acx_tpu.models import moe_transformer as mtf
import dataclasses


def _mcfg(n_layers, max_seq=128, vocab=64):
    c = mtf.tiny_moe_config(vocab=vocab, d_model=32, n_heads=2,
                            n_layers=n_layers, d_ff=64, n_experts=4,
                            top_k=2, capacity_factor=4.0, max_seq=max_seq)
    return dataclasses.replace(c, dtype=jnp.float32)


def test_moe_exact_match_random_draft():
    """MoE target with a dense GPT-2 draft: output equals mtf.generate
    exactly (drop-free capacity, so the window's k-token routing group
    equals the stepwise per-token routing)."""
    cfg = _mcfg(2)
    dcfg = _cfg(1)
    params = mtf.init_params(jax.random.key(0), cfg)
    dparams = tfm.init_params(jax.random.key(7), dcfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 18, 4
    want = mtf.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, _ = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                  n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_moe_perfect_draft_full_acceptance():
    cfg = _mcfg(2, max_seq=256)
    params = mtf.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 41, 4
    want = mtf.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(params, cfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds, acc = int(stats["rounds"]), int(stats["drafted_accepted"])
    assert acc == rounds * (k - 1), (acc, rounds)


def test_moe_target_tight_capacity_rejected():
    """An MoE target outside the drop-free regime is rejected with a
    clear message (window-vs-stepwise routing groups could diverge)."""
    cfg = dataclasses.replace(_mcfg(2), capacity_factor=2.0)  # < E=4
    params = mtf.init_params(jax.random.key(0), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(AssertionError, match="drop-free"):
        speculative_generate(params, cfg, params, cfg, prompt, 4)
