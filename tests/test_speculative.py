"""Speculative decoding: exact greedy equality and acceptance accounting.

The defining property: for ANY draft model, the output tokens equal the
target-only greedy decode — the draft changes only the round count.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_acx_tpu.models import transformer as tfm
from mpi_acx_tpu.models.speculative import speculative_generate


def _cfg(n_layers, max_seq=128, vocab=64):
    c = tfm.tiny_config(vocab=vocab, d_model=32, n_heads=2,
                        n_layers=n_layers, d_ff=64, max_seq=max_seq)
    return tfm.TransformerConfig(**{**c.__dict__, "dtype": jnp.float32})


@pytest.mark.parametrize("k", [2, 4])
def test_exact_match_random_draft(k):
    """A random (unrelated) draft: almost nothing gets accepted, output
    still EXACTLY equals the target-only greedy decode."""
    cfg = _cfg(2)
    dcfg = _cfg(1)
    params = tfm.init_params(jax.random.key(0), cfg)
    dparams = tfm.init_params(jax.random.key(7), dcfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new = 24

    want = tfm.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # Every round emits at least one token.
    assert int(stats["rounds"]) <= n_new


def test_perfect_draft_amortizes_rounds():
    """Draft == target: every proposal is accepted, so each round emits k
    tokens and the target runs ~n_new/k window passes instead of n_new
    steps — the speedup mechanism, observable in the round count."""
    cfg = _cfg(2)
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 24, 4

    want = tfm.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(params, cfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds = int(stats["rounds"])
    # ceil((n_new - 1) / k) + 1 rounds would be perfect; allow slack for
    # the prefill bonus accounting but require real amortization.
    assert rounds <= -(-n_new // k) + 1, rounds
    assert int(stats["drafted_accepted"]) >= (k - 1) * (rounds - 1)


def test_trained_draft_accepts_most():
    """A draft trained on the same copy task as the target accepts most
    proposals — the realistic deployment regime (distilled draft)."""
    cfg = _cfg(2, vocab=32)
    dcfg = _cfg(1, vocab=32)
    tok = jax.random.randint(jax.random.key(1), (8, 16), 0, 32)
    tgt = tok   # predict-current: rollout repeats the final token

    def train(c, key, steps=60):
        p = tfm.init_params(key, c)
        import optax
        opt = optax.adam(3e-2)
        st = opt.init(p)
        loss_g = jax.jit(jax.value_and_grad(
            lambda p: tfm.loss_fn(p, c, tok, tgt)))
        for _ in range(steps):
            _, g = loss_g(p)
            up, st = opt.update(g, st)
            p = optax.apply_updates(p, up)
        return p

    params = train(cfg, jax.random.key(0))
    dparams = train(dcfg, jax.random.key(9))
    prompt = tok[:1, :8]
    n_new, k = 16, 4

    want = tfm.generate(params, cfg, prompt, n_new, max_len=8 + n_new + k)
    got, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds = int(stats["rounds"])
    acc = int(stats["drafted_accepted"])
    # Both models learned the task, so acceptance is high and rounds are
    # far below n_new (each round emits ~k tokens).
    assert rounds <= n_new // 2, (rounds, acc)
    assert acc >= rounds, (rounds, acc)


def test_batch_rejected():
    cfg = _cfg(2)
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    with pytest.raises(AssertionError):
        speculative_generate(params, cfg, params, cfg, prompt, 4)


def test_no_draft_cache_hole_at_full_acceptance():
    """Regression: at full acceptance the rollback jumps past the last
    proposal's seat; the draft must still have written that cache entry
    (an unwritten zero K/V row would perturb every later draft step and
    silently erode acceptance). With draft == target, acceptance must
    stay PERFECT across many rounds — any hole shows up as a rejected
    proposal."""
    cfg = _cfg(2, max_seq=256)
    params = tfm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 61, 4
    want = tfm.generate(params, cfg, prompt, n_new,
                        max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(params, cfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds, acc = int(stats["rounds"]), int(stats["drafted_accepted"])
    assert acc == rounds * (k - 1), (acc, rounds)


# -- Llama family ----------------------------------------------------------

from mpi_acx_tpu.models import llama as lm


def _lcfg(n_layers, n_kv=2, max_seq=128, vocab=64):
    c = lm.tiny_llama(vocab=vocab, d_model=32, n_heads=4, n_kv_heads=n_kv,
                      n_layers=n_layers, d_ff=64, max_seq=max_seq)
    return lm.LlamaConfig(**{**c.__dict__, "dtype": jnp.float32})


def test_llama_exact_match_random_draft():
    """GQA window verification: output equals llama.generate exactly
    for an unrelated random draft."""
    cfg, dcfg = _lcfg(2), _lcfg(1)
    params = lm.init_params(jax.random.key(0), cfg)
    dparams = lm.init_params(jax.random.key(7), dcfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 20, 4
    want = lm.generate(params, cfg, prompt, n_new,
                       max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(dparams, dcfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_llama_perfect_draft_full_acceptance():
    cfg = _lcfg(2, max_seq=256)
    params = lm.init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab)
    n_new, k = 41, 4
    want = lm.generate(params, cfg, prompt, n_new,
                       max_len=prompt.shape[1] + n_new + k)
    got, stats = speculative_generate(params, cfg, params, cfg, prompt,
                                      n_new, k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds, acc = int(stats["rounds"]), int(stats["drafted_accepted"])
    assert acc == rounds * (k - 1), (acc, rounds)
    assert rounds <= -(-n_new // k) + 1, rounds
